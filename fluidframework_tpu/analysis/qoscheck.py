"""qoscheck — overload-safety rules for the service plane.

``service-unbounded-queue``: an ``asyncio.Queue()`` without a
``maxsize`` (or a ``collections.deque()`` without a ``maxlen``)
reachable in the service layer is a standing invitation for one slow
consumer or one hot tenant to buffer the server into the ground —
exactly the failure the qos subsystem exists to rule out (the
per-session outbound queue was this bug until it grew a bound and a
slow-consumer policy; docs/QOS.md). The rule flags every unbounded
construction in a ``service``/``qos`` path component; the few
intentional ones (queues drained synchronously before the
constructor's caller returns) carry a justified inline
``# fluidlint: disable=service-unbounded-queue``.

Scope is by PATH COMPONENT (any ``service`` or ``qos`` directory in
the file's repo-relative path), so the rule covers the real tree and
still fires on test fixtures placed under a ``service/`` tmp dir.
``queue.Queue()`` (the threading one) counts too — the driver layer
uses it legitimately, but in the service plane it has the same
unbounded-buffer failure mode.

``retry-without-jitter``: a ``time.sleep(<constant>)`` inside a
retry/reconnect loop in a ``drivers``/``service``/``qos`` path
component synchronizes every client the service just shed — after a
mass disconnect (exactly what a chaos storm injects) they all come
back at t+delay, t+2*delay, ... in lockstep, re-creating the spike
that caused the shedding (the thundering herd). Backoff delays must
route through ``drivers/driver_utils.full_jitter_delay`` (which also
honors a throttle's ``retry_after_seconds`` as the floor).
Flagged: a constant argument (directly, via constant arithmetic, or
via a local name bound to one) slept inside a ``for``/``while`` body.
Clean: the slept value flows from a ``full_jitter_delay(...)`` call
(directly or via a local name). Unknown provenance (parameters,
attributes, other calls) is trusted — the arithmetic-with-names
backoff (``base * 2 ** attempt``: exponential but unjittered) is a
documented false negative; route it through the helper anyway.

``fence-before-fanout``: inside the replicated sequencer, the calls
that release a sequenced op toward fan-out (the reviewed
``FANOUT_GATES`` registry — ``replicate_before_fanout`` and its
underscore twin, on both the document plane and the partitioned
queue) MUST be textually preceded, in the same function, by an epoch
fence check (``<...>.fence.check(...)`` or a ``check_epoch(...)``
call). A deposed leader that fans out before checking the fence is
the split-brain failure the whole replication design exists to rule
out (docs/ROBUSTNESS.md "Replication & failover"); the runtime half
is ``EpochFence.check`` raising ``FencedWriteError`` + the
follower-side stale-epoch refusal, and this rule pins the ordering
statically so a refactor cannot silently move the fan-out above the
fence. Scope: ``service`` path components (where the replicated
sequencer lives).

``unbounded-blocking-wait``: a polling/blocking wait loop in the
service plane — a ``while`` loop whose body sleeps (``time.sleep``,
an injectable ``self._sleep``/``wait`` primitive) while it waits for
external progress — must carry a DEADLINE: a comparison against a
clock reading or a ``deadline``/``timeout``-named bound somewhere in
the loop. The replicated sequencer's quorum barrier was this bug
(the ``while acked < quorum`` wait): a minority-side leader hung
every submitter forever instead of answering with the retriable
unavailable nack (docs/ROBUSTNESS.md "Partition tolerance &
degraded mode"). Scope: ``service`` path components. A wait that is
legitimately unbounded (none known today — the allowlist stays
empty) would carry a justified inline
``# fluidlint: disable=unbounded-blocking-wait``.
"""
from __future__ import annotations

import ast
from typing import Optional

from .core import (
    Finding,
    SourceFile,
    dotted_path as _dotted,
    import_aliases,
)

# dotted-path suffixes that construct a queue-like container, and the
# keyword (or positional index) that bounds it
_QUEUE_SUFFIXES = {
    "asyncio.Queue": ("maxsize", 0),
    "asyncio.LifoQueue": ("maxsize", 0),
    "asyncio.PriorityQueue": ("maxsize", 0),
    "queue.Queue": ("maxsize", 0),
    "queue.LifoQueue": ("maxsize", 0),
    "queue.PriorityQueue": ("maxsize", 0),
    "collections.deque": ("maxlen", 1),
    "deque": ("maxlen", 1),
}


def _in_scope(relpath: str) -> bool:
    parts = relpath.split("/")
    return "service" in parts[:-1] or "qos" in parts[:-1]


def _import_aliases(tree: ast.AST) -> dict[str, str]:
    # same resolution style as obscheck: relative-import tails stay,
    # suffix matching keys on them
    return import_aliases(tree, relative="tail")


def _bound_spec(dotted: str) -> Optional[tuple[str, int]]:
    for suffix, spec in _QUEUE_SUFFIXES.items():
        if dotted == suffix or dotted.endswith("." + suffix):
            # the bare-name form ("deque") only counts when it came
            # through an import (collections.deque resolves dotted);
            # a module's own class named deque would resolve bare and
            # must not fire — mirrored from obscheck's reasoning
            if suffix == "deque" and dotted == "deque":
                return None
            return spec
    return None


def _has_bound(node: ast.Call, spec: tuple[str, int]) -> bool:
    kw_name, pos_index = spec

    def bounds(value: ast.AST) -> bool:
        if not isinstance(value, ast.Constant):
            return True  # a computed bound: trust it
        if value.value is None:
            return False  # explicit None = unbounded
        # asyncio/queue semantics: maxsize <= 0 means INFINITE;
        # deque(maxlen=0) genuinely bounds (to empty)
        if kw_name == "maxsize" and isinstance(
                value.value, (int, float)) and value.value <= 0:
            return False
        return True

    for kw in node.keywords:
        if kw.arg == kw_name:
            return bounds(kw.value)
        if kw.arg is None:
            return True  # **kwargs: cannot prove unbounded
    if len(node.args) > pos_index:
        return bounds(node.args[pos_index])
    return False


def _qualname_of(stack: list[str], node: ast.Call,
                 parents: dict) -> str:
    """Stable, line-free finding key: enclosing scope + assignment
    target (e.g. ``_ClientSession.__init__.outbound``)."""
    target = ""
    parent = parents.get(node)
    # walk up through subscripts/annotations to the binding statement
    hops = 0
    while parent is not None and hops < 4:
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            t = parent.targets[0] if isinstance(parent, ast.Assign) \
                else parent.target
            if isinstance(t, ast.Attribute):
                target = t.attr
            elif isinstance(t, ast.Name):
                target = t.id
            break
        parent = parents.get(parent)
        hops += 1
    scope = ".".join(stack) or "<module>"
    return f"{scope}.{target}" if target else scope


JITTER_HELPER = "full_jitter_delay"


def _in_retry_scope(relpath: str) -> bool:
    parts = relpath.split("/")
    return any(p in ("drivers", "service", "qos") for p in parts[:-1])


def _is_sleep_call(node: ast.Call, aliases: dict) -> bool:
    dotted = _dotted(node.func, aliases)
    if dotted is None:
        return False
    return dotted == "time.sleep" or dotted.endswith(".time.sleep") \
        or dotted == "sleep" and aliases.get("sleep", "") == "time.sleep"


def _derives_from_jitter(value: ast.AST, env: dict) -> bool:
    """Does the expression (or a local name it reads) flow from a
    full_jitter_delay(...) call?"""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            callee = node.func
            name = callee.attr if isinstance(callee, ast.Attribute) \
                else getattr(callee, "id", None)
            if name == JITTER_HELPER:
                return True
        if isinstance(node, ast.Name) and node.id in env:
            if env[node.id] == "jitter":
                return True
    return False


def _const_only(value: ast.AST, env: dict) -> bool:
    """True when every leaf is a literal constant or a local name
    bound to one — the deterministic-schedule shape the rule exists
    to flag."""
    for node in ast.walk(value):
        if isinstance(node, (ast.BinOp, ast.UnaryOp, ast.Constant,
                             ast.operator, ast.unaryop, ast.expr_context)):
            continue
        if isinstance(node, ast.Name):
            if env.get(node.id) != "const":
                return False
            continue
        return False
    return True


def _check_retry_jitter(src: SourceFile, aliases: dict,
                        module: str, findings: list) -> None:
    # Class.method qualnames so same-named methods of two classes
    # never share a finding key (the shapecheck-review lesson)
    quals: dict[ast.AST, str] = {}
    for cls in ast.walk(src.tree):
        if isinstance(cls, ast.ClassDef):
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    quals[item] = f"{cls.name}.{item.name}"
    for scope in ast.walk(src.tree):
        if not isinstance(scope, (ast.FunctionDef,
                                  ast.AsyncFunctionDef,
                                  ast.Module)):
            continue
        # textual-order local provenance: name -> "const" | "jitter"
        # (later bindings supersede; anything else drops the name)
        env: dict[str, str] = {}
        hits = 0
        own_body = list(ast.iter_child_nodes(scope))

        def walk(node, in_loop: bool, owner) -> None:
            nonlocal hits
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and node is not owner:
                return  # nested scopes analyzed on their own walk
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if _derives_from_jitter(node.value, env):
                    env[name] = "jitter"
                elif _const_only(node.value, env):
                    env[name] = "const"
                else:
                    env.pop(name, None)
            if isinstance(node, ast.Call) and in_loop \
                    and _is_sleep_call(node, aliases) and node.args:
                arg = node.args[0]
                if not _derives_from_jitter(arg, env) \
                        and _const_only(arg, env):
                    hits += 1
                    qual = quals.get(
                        owner, getattr(owner, "name", "<module>"))
                    suffix = "" if hits == 1 else str(hits)
                    findings.append(Finding(
                        rule="retry-without-jitter",
                        path=src.relpath, line=node.lineno,
                        message=(
                            "constant sleep in a retry/reconnect "
                            "loop: a fixed delay synchronizes every "
                            "shed client's comeback (thundering "
                            "herd) — route the delay through "
                            "driver_utils.full_jitter_delay "
                            "(docs/ROBUSTNESS.md)"
                        ),
                        key=f"{module}:{qual}.sleep{suffix}",
                    ))
            loops_here = in_loop or isinstance(node,
                                               (ast.For, ast.While))
            for child in ast.iter_child_nodes(node):
                walk(child, loops_here, owner)

        for child in own_body:
            walk(child, False, scope)


#: reviewed registry: the replication gates — calls that release a
#: sequenced op toward fan-out in the replicated sequencer. Adding a
#: new gate spelling here is a REVIEWED change (the rule's coverage
#: is only as good as this list).
FANOUT_GATES = ("replicate_before_fanout", "_replicate_before_fanout")

#: bare-call fence spellings; ``<...>.fence.check(...)`` is always
#: recognized structurally
FENCE_CALLS = ("check_epoch",)


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_fence_check(node: ast.Call) -> bool:
    name = _callee_name(node.func)
    if name in FENCE_CALLS:
        return True
    if name != "check" or not isinstance(node.func, ast.Attribute):
        return False
    value = node.func.value
    # <anything>.fence.check(...) / fence.check(...)
    if isinstance(value, ast.Attribute) and value.attr == "fence":
        return True
    return isinstance(value, ast.Name) and value.id == "fence"


def _check_fence_before_fanout(src: SourceFile, module: str,
                               findings: list) -> None:
    quals: dict[ast.AST, str] = {}
    for cls in ast.walk(src.tree):
        if isinstance(cls, ast.ClassDef):
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    quals[item] = f"{cls.name}.{item.name}"
    def own_calls(scope) -> list[ast.Call]:
        """Calls in the scope's OWN body — nested defs are their own
        scopes (a fence check inside a nested helper does not guard
        the outer function's gate, and a nested gate must not be
        double-reported against the outer scope)."""
        out: list[ast.Call] = []

        def walk(node) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.Call):
                    out.append(child)
                walk(child)

        walk(scope)
        return out

    for scope in ast.walk(src.tree):
        if not isinstance(scope, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
            continue
        calls = own_calls(scope)
        fences = sorted((n.lineno, n.col_offset) for n in calls
                        if _is_fence_check(n))
        hits = 0
        for call in sorted(calls, key=lambda n: (n.lineno,
                                                 n.col_offset)):
            if _callee_name(call.func) not in FANOUT_GATES:
                continue
            pos = (call.lineno, call.col_offset)
            if any(f < pos for f in fences):
                continue
            hits += 1
            qual = quals.get(scope, scope.name)
            suffix = "" if hits == 1 else str(hits)
            findings.append(Finding(
                rule="fence-before-fanout",
                path=src.relpath, line=call.lineno,
                message=(
                    f"{_callee_name(call.func)}() releases a "
                    "sequenced op toward fan-out without an epoch "
                    "fence check earlier in this function: a "
                    "deposed leader (split-brain candidate) could "
                    "fan out an op the quorum will refuse — call "
                    "<...>.fence.check(epoch) (or check_epoch) "
                    "first (docs/ROBUSTNESS.md)"
                ),
                key=f"{module}:{qual}.fanout{suffix}",
            ))


#: callee-name fragments that mark a call as a blocking/polling wait
#: primitive (the loop body "waits" through them): time.sleep and the
#: injectable sleep/wait seams the service plane uses
_WAIT_NAME_FRAGMENTS = ("sleep", "wait")

#: name fragments that mark a Name/Attribute as a deadline bound
_DEADLINE_FRAGMENTS = ("deadline", "timeout", "expires")

#: callee-name fragments whose call result reads a clock
_CLOCK_FRAGMENTS = ("clock", "monotonic", "time")


def _is_wait_call(node: ast.Call) -> bool:
    name = _callee_name(node.func)
    if name is None:
        return False
    ident = name.strip("_").lower()
    return ("sleep" in ident or ident == "wait"
            or ident.startswith("wait_") or ident.endswith("_wait"))


def _names_deadline(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        ident = node.id.lower()
    elif isinstance(node, ast.Attribute):
        ident = node.attr.lower()
    else:
        return False
    return any(f in ident for f in _DEADLINE_FRAGMENTS)


def _reads_clock(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _callee_name(node.func)
    if name is None:
        return False
    ident = name.lower()
    return any(f in ident for f in _CLOCK_FRAGMENTS)


def _has_deadline_bound(loop: ast.While) -> bool:
    """A comparison anywhere in the loop (test or body) where either
    side names a deadline/timeout or reads a clock — the shape
    ``if self.clock() >= deadline: ...`` the fixed barrier carries."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left, *node.comparators]
        if any(_names_deadline(s) or _reads_clock(s) for s in sides):
            return True
    return False


def _check_blocking_wait(src: SourceFile, module: str,
                         findings: list) -> None:
    quals: dict[ast.AST, str] = {}
    for cls in ast.walk(src.tree):
        if isinstance(cls, ast.ClassDef):
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    quals[item] = f"{cls.name}.{item.name}"
    parents: dict = {}
    for parent in ast.walk(src.tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent

    def enclosing_scope(node) -> str:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                return quals.get(cur, cur.name)
            cur = parents.get(cur)
        return "<module>"

    hits: dict[str, int] = {}
    for loop in ast.walk(src.tree):
        if not isinstance(loop, ast.While):
            continue
        waits = any(isinstance(n, ast.Call) and _is_wait_call(n)
                    for stmt in loop.body for n in ast.walk(stmt))
        if not waits:
            continue
        if _has_deadline_bound(loop):
            continue
        qual = enclosing_scope(loop)
        n = hits.get(qual, 0) + 1
        hits[qual] = n
        suffix = "" if n == 1 else str(n)
        findings.append(Finding(
            rule="unbounded-blocking-wait",
            path=src.relpath, line=loop.lineno,
            message=(
                "blocking wait loop with no deadline in the service "
                "plane: a vanished peer set (netsplit, dead "
                "followers) hangs every caller forever — bound the "
                "wait on an injectable clock (`if clock() >= "
                "deadline: refuse`) and answer with a retriable "
                "unavailable nack (docs/ROBUSTNESS.md)"
            ),
            key=f"{module}:{qual}.blockwait{suffix}",
        ))


def check(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        if _in_retry_scope(src.relpath):
            _check_retry_jitter(
                src, _import_aliases(src.tree),
                src.relpath.rsplit("/", 1)[-1], findings)
        if not _in_scope(src.relpath):
            continue
        aliases = _import_aliases(src.tree)
        module = src.relpath.rsplit("/", 1)[-1]
        _check_fence_before_fanout(src, module, findings)
        _check_blocking_wait(src, module, findings)
        parents: dict = {}
        for parent in ast.walk(src.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent

        def scope_stack(node) -> list[str]:
            out: list[str] = []
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    out.append(cur.name)
                cur = parents.get(cur)
            return list(reversed(out))

        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func, aliases)
            if dotted is None:
                continue
            spec = _bound_spec(dotted)
            if spec is None or _has_bound(node, spec):
                continue
            qual = _qualname_of(scope_stack(node), node, parents)
            findings.append(Finding(
                rule="service-unbounded-queue",
                path=src.relpath, line=node.lineno,
                message=(
                    f"unbounded {dotted}() in the service layer: one "
                    "slow consumer / hot tenant buffers the server "
                    "into the ground — pass a bound "
                    f"({spec[0]}=...) and an explicit overflow "
                    "policy, or justify with '# fluidlint: "
                    "disable=service-unbounded-queue' (docs/QOS.md)"
                ),
                key=f"{module}:{qual}",
            ))
    return findings
