"""detcheck — determinism-provenance analysis over the service plane.

Every proof this repo ships — the 20-seed chaos convergence
differentials, bit-equal storm reruns, config9's five-run equality,
the failover oracle — depends on one unstated invariant: no wall-clock
read and no unseeded RNG draw on a deterministic-contract path. The
qos/slo layers already model the discipline (``clock=`` injection,
``FaultSchedule.rng_for`` seed streams); this family makes the
invariant machine-checked everywhere, by a clock/RNG-provenance pass
over the shared callgraph:

- **wall-clock-unrouted** — a direct ``time.time()`` /
  ``time.monotonic()`` / ``time.perf_counter()`` /
  ``datetime.now()``-family call in a function reachable from the
  deterministic-contract roots (sequencer ticketing, qos/slo grading,
  replication/lease, the chaos harness, serve_bench, partitioning)
  that does not flow from an injectable ``clock=`` parameter.
  Telemetry/obs timestamps are legitimately wall-clock — they live in
  the reviewed :data:`WALL_CLOCK_SINKS` registry (per function, with
  justification), NOT in the allowlist.
- **unseeded-rng** — ``random.Random()`` with no seed, module-level
  ``random.*`` draws (the process-global unseeded stream), or
  ``np.random.*`` without seed provenance, anywhere in a
  deterministic-plane component.
- **iteration-order-leak** — a ``set`` (or a value derived from set
  ops) iterated into an order-sensitive sink: a fan-out/append/send
  loop, ``list()``/``tuple()`` materialization, a ``join`` or an
  ordered comprehension. Set iteration order varies per process
  (PYTHONHASHSEED); ``sorted(...)`` is the one-word fix and kills the
  taint.
- **hash-order-dependence** — builtin ``hash()`` of str/bytes feeding
  ordering or partition selection (``hash(x) % n``). str/bytes hashes
  are salted per process since PEP 456; use ``zlib.crc32`` / hashlib
  (the ``partitioning.partition_for`` idiom). ``__hash__``
  implementations are exempt — in-process dict identity is fine, the
  hazard is cross-run ordering.

The runtime cross-check is ``testing/detsan.py`` (the
concheck<->fluidsan / shapecheck<->jitsan pattern): patched
``time``/``random`` entry points observe the reads that actually
happen, and the differential test (tests/test_detsan.py) pins every
runtime-observed un-routed site to a static finding or a registry
entry while driving the real chaos sweep and a serve_bench slice — a
gap fails BY NAME as an analyzer-resolution gap.

Like every fluidlint pass, this module imports NOTHING it lints:
resolution is pure AST over the shared callgraph.
"""
from __future__ import annotations

import ast
from typing import Optional

from .callgraph import CallGraph, build_callgraph
from .core import (
    Finding,
    SourceFile,
    dotted_path as _dotted,
    import_aliases,
)

# ---------------------------------------------------------------------------
# reviewed registries

# Direct wall-clock reads the pass recognizes (absolute stdlib dotted
# paths after alias substitution, matching import_aliases
# relative="skip" exactly like jaxhazards).
WALL_CLOCK_CALLS = frozenset((
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
))

# Deterministic-contract roots (relpath suffix -> qualnames, "*" =
# every function in the module): the entry points whose transitive
# callees must never read the wall clock un-routed. These are the
# planes the convergence proofs pin: sequencer ticketing (python and
# native), the ordering/replication/partitioning stack, qos/slo
# grading, the chaos harness, and the serving benchmark.
DETERMINISTIC_ROOTS = {
    "service/sequencer.py": ("*",),
    "native/sequencer_core.py": ("*",),
    "service/local_orderer.py": ("*",),
    "service/local_server.py": ("*",),
    "service/replication.py": ("*",),
    "service/partitioning.py": ("*",),
    # the client half of the replay contract: crash-recovery
    # differentials replay THROUGH Containers (batch integrity, msn
    # heartbeats, slice deadlines), and the callgraph cannot see the
    # harness's attribute-held dispatch into them — roots, not edges
    "loader/container.py": ("*",),
    "loader/collab_window.py": ("*",),
    "loader/scheduler.py": ("*",),
    "obs/slo.py": ("*",),
    "qos/admission.py": ("*",),
    "qos/breaker.py": ("*",),
    "qos/pressure.py": ("*",),
    "qos/rate_limiter.py": ("*",),
    "qos/policy.py": ("*",),
    "testing/chaos.py": ("*",),
    "tools/serve_bench.py": ("*",),
}

# Call edges the shared graph cannot resolve syntactically
# (attribute-held objects), declared like concurrency.INDIRECT_CALLS /
# shapecheck.PREWARM_INDIRECT:
#   (relpath suffix, caller qualname) -> ((relpath suffix, qualname), ...)
DETERMINISTIC_INDIRECT = {
    # the chaos harness replays the durable log into the sidecar it
    # holds by attribute; serve_bench drives its sidecar rounds the
    # same way
    ("testing/chaos.py", "ChaosHarness.crash"): (
        ("service/tpu_sidecar.py", "TpuMergeSidecar.ingest"),
    ),
    ("testing/chaos.py", "ChaosHarness._build_sidecar"): (
        ("service/tpu_sidecar.py", "TpuMergeSidecar.subscribe"),
    ),
    ("tools/serve_bench.py", "run_serve_bench"): (
        ("service/tpu_sidecar.py", "TpuMergeSidecar.ingest"),
        ("service/tpu_sidecar.py", "TpuMergeSidecar.apply"),
        ("service/tpu_sidecar.py", "TpuMergeSidecar.prewarm"),
    ),
}

# Reviewed wall-clock sinks: (relpath suffix, qualname or "*") ->
# justification. Telemetry and observability TIMESTAMP/duration reads
# are legitimately wall-clock — the contract is that nothing
# deterministic derives from them (deterministic_fields excludes
# them, trace timestamps never feed ordering). This is a REGISTRY,
# not an allowlist: every entry is a reviewed design decision, the
# gate test fails if an entry goes stale (no wall-clock call left at
# the site), and a new un-routed read anywhere else still fails the
# gate.
WALL_CLOCK_SINKS: dict[tuple[str, str], str] = {
    ("obs/trace.py", "stamp"):
        "wire-hop trace timestamps are observability metadata; "
        "deterministic callers (sequencer, sidecar) pass timestamp= "
        "from their injected clock",
    ("obs/profiler.py", "*"):
        "the sampling profiler measures wall time by definition",
    ("utils/telemetry.py", "*"):
        "duration telemetry (PerformanceEvent timers) measures wall "
        "time by definition",
    ("service/telemetry.py", "*"):
        "Lumberjack event timestamps/durations are log metadata",
    ("service/tenancy.py", "sign_token"):
        "token iat/exp are wall-clock validity by protocol design",
    ("service/tenancy.py", "TenantManager.validate_token"):
        "token expiry check is wall-clock validity by design",
    ("drivers/caching_driver.py", "SnapshotCache.put"):
        "cache entry freshness (cached_at) is wall-clock by design",
    ("drivers/caching_driver.py",
     "CachingDocumentService.get_latest_summary"):
        "cache age check against max_age_s is wall-clock by design",
    ("service/tpu_sidecar.py", "TpuMergeSidecar.prewarm"):
        "prewarm returns measured warmup wall seconds (obs only)",
    ("service/tpu_sidecar.py", "TpuMergeSidecar._dispatch"):
        "pack_ms histogram + sidecar:pack trace timestamp (obs only; "
        "never feeds ordering)",
    ("service/tpu_sidecar.py", "TpuMergeSidecar._settle"):
        "settle_ms histogram + sidecar:settle trace timestamp (obs "
        "only; never feeds ordering)",
    ("service/tree_sidecar.py", "TreeSidecar.prewarm"):
        "prewarm returns measured warmup wall seconds (obs only)",
    ("service/tree_sidecar.py", "TreeSidecar._dispatch"):
        "tree pack_ms histogram (obs only; never feeds ordering)",
    ("service/tree_sidecar.py", "TreeSidecar._settle"):
        "tree settle_ms histogram (obs only; never feeds ordering)",
    ("service/ingress.py", "AlfredServer._dispatch"):
        "dispatch_ms histogram measures wall latency (obs only)",
    ("service/ingress.py", "AlfredServer._handle_upload_chunk"):
        "abandoned-upload reclaim TTL is transport hygiene on real "
        "wall time, outside the ordering contract",
    ("loader/container.py", "Container._process"):
        "submit->ack roundtrip_ms telemetry (obs only; convergence "
        "state never derives from it)",
    ("loader/container.py", "Container._submit_runtime_op"):
        "records send time for the roundtrip_ms telemetry pair",
    ("tools/serve_bench.py", "run_serve_bench"):
        "wall_s / sidecar round timing ride the report's NON-"
        "deterministic fields (deterministic_fields excludes them)",
    ("tools/benchmark.py", "*"):
        "a benchmark measures wall time by definition",
    ("tools/net_stress.py", "*"):
        "real-socket stress deadlines wait on actual network "
        "progress",
    ("native/replay_baseline.py", "*"):
        "the native replay baseline measures wall time by definition",
}

# Path components where the unseeded-rng / iteration-order-leak /
# hash-order-dependence rules apply: the deterministic planes. obs/
# and utils/ are the telemetry layers (wall-clock by design, no RNG);
# tests/ and examples/ are out of scope — a test's wall-clock
# deadline loop or demo RNG is not the contract's business.
DET_SCOPE_COMPONENTS = (
    "drivers", "loader", "service", "qos", "runtime", "parallel",
    "ops", "native", "protocol", "framework", "models", "testing",
    "tools",
)

# module-level random.* draws that ride the process-global unseeded
# stream (random.seed included: seeding the GLOBAL stream is itself
# cross-component order dependence — whoever seeds last wins)
_GLOBAL_RNG_FNS = frozenset((
    "random", "uniform", "randint", "randrange", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "lognormvariate",
    "expovariate", "betavariate", "gammavariate", "triangular",
    "vonmisesvariate", "paretovariate", "weibullvariate",
    "getrandbits", "randbytes", "seed",
))

# np.random constructors that ARE seedable — fine when an explicit
# non-None seed argument is present
_NP_SEEDABLE = frozenset((
    "default_rng", "RandomState", "Generator", "SeedSequence",
))

# calls inside a set-iterating fan-out loop that make the iteration
# order observable (wire writes, queue/log appends, fan-out sends)
_ORDER_SINK_CALLS = frozenset((
    "append", "appendleft", "extend", "send", "sendall", "write",
    "writelines", "emit", "publish", "put", "put_nowait", "submit",
    "dispatch", "broadcast", "produce",
))


def _in_det_scope(relpath: str) -> bool:
    parts = relpath.split("/")
    return any(p in DET_SCOPE_COMPONENTS for p in parts[:-1])


class _OrdinalKeys:
    """Stable line-free finding keys: ``module:qual:leaf`` with an
    ordinal suffix for repeats in one scope (the retry-without-jitter
    precedent — two raw reads in one function get distinct keys that
    both survive line insertions above them)."""

    def __init__(self) -> None:
        self._seen: dict[tuple, int] = {}

    def key(self, module: str, qual: str, leaf: str) -> str:
        slot = (module, qual, leaf)
        n = self._seen.get(slot, 0) + 1
        self._seen[slot] = n
        return f"{module}:{qual}:{leaf}" + ("" if n == 1 else str(n))


# ===========================================================================
# rule: wall-clock-unrouted


def wall_clock_calls_in(tree: ast.AST, aliases: dict) -> list[ast.Call]:
    """Direct wall-clock Call nodes in ``tree`` (shared with detsan's
    routed/un-routed site classifier: a read whose call site is NOT
    one of these lines arrived through an injected ``clock()`` — the
    routing the static rule credits)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _dotted(node.func, aliases) in WALL_CLOCK_CALLS:
            out.append(node)
    return out


def sink_registered(relpath: str, qualname: str,
                    by_code_name: bool = False) -> bool:
    """Whether a (file, function) pair is a reviewed wall-clock sink.

    The static pass has full dotted qualnames and matches them
    EXACTLY (or ``"*"``): a leaf fallback there would silently exempt
    an unrelated same-named method in the same file. detsan only has
    the code object's bare name, so it passes ``by_code_name=True``
    and matches an entry's tail — the runtime backstop trades that
    precision for coverage, the static half never does."""
    leaf = qualname.rsplit(".", 1)[-1]
    for (suffix, qual), _just in WALL_CLOCK_SINKS.items():
        if not relpath.endswith(suffix):
            continue
        if qual == "*" or qual == qualname:
            return True
        if by_code_name and qual.rsplit(".", 1)[-1] == leaf:
            return True
    return False


def _det_root_infos(graph: CallGraph) -> list:
    roots = []
    for info in graph.functions():
        for suffix, quals in DETERMINISTIC_ROOTS.items():
            if not info.relpath.endswith(suffix):
                continue
            if "*" in quals or info.qualname in quals:
                roots.append(info)
    return roots


def _det_reachable(files: list[SourceFile], graph: CallGraph) -> list:
    """FunctionInfos reachable from the deterministic roots through
    resolved edges plus the declared DETERMINISTIC_INDIRECT edges."""
    fn_index: dict[tuple, object] = {}
    for info in graph.functions():
        fn_index.setdefault((info.relpath, info.qualname), info)

    def lookup(suffix: str, qual: str):
        for (rel, q), info in fn_index.items():
            if q == qual and rel.endswith(suffix):
                yield info

    seen: dict[int, object] = {}
    queue = _det_root_infos(graph)
    while queue:
        info = queue.pop()
        if info is None or id(info.node) in seen:
            continue
        seen[id(info.node)] = info
        queue.extend(graph.callees(info))
        for (suffix, qual), targets in DETERMINISTIC_INDIRECT.items():
            if info.relpath.endswith(suffix) and \
                    info.qualname == qual:
                for tsuffix, tqual in targets:
                    queue.extend(lookup(tsuffix, tqual))
    return list(seen.values())


def _check_wall_clock(files: list[SourceFile],
                      graph: CallGraph) -> list[Finding]:
    by_rel = {src.relpath: src for src in files}
    aliases_cache: dict[str, dict] = {}
    findings: list[Finding] = []
    # per-FILE ordinal counters: keys carry the module basename only,
    # so a shared counter would couple same-named modules' ordinals
    # (service/telemetry.py vs utils/telemetry.py) across files —
    # exactly the key churn the line-free contract forbids
    keys_by_file: dict[str, _OrdinalKeys] = {}
    reachable = sorted(
        _det_reachable(files, graph),
        key=lambda info: (info.relpath,
                          info.node.lineno, info.qualname),
    )
    for info in reachable:
        src = by_rel.get(info.relpath)
        if src is None or src.tree is None:
            continue
        aliases = aliases_cache.get(info.relpath)
        if aliases is None:
            aliases = import_aliases(src.tree, relative="skip")
            aliases_cache[info.relpath] = aliases
        if sink_registered(info.relpath, info.qualname):
            continue
        module = info.relpath.rsplit("/", 1)[-1]
        keys = keys_by_file.setdefault(info.relpath, _OrdinalKeys())
        # source order, not ast.walk's BFS order: a nested read must
        # not swap ordinals with a later top-level one when a
        # refactor wraps/unwraps a call (key churn the line-free
        # contract forbids) — the other three rules sort the same way
        for call in sorted(wall_clock_calls_in(info.node, aliases),
                           key=lambda c: (c.lineno, c.col_offset)):
            leaf = _dotted(call.func, aliases)
            findings.append(Finding(
                rule="wall-clock-unrouted",
                path=info.relpath, line=call.lineno,
                message=(
                    f"{leaf}() inside {info.qualname}(), which is "
                    "reachable from a deterministic-contract root "
                    "(sequencer/qos/replication/chaos/serve_bench): "
                    "every convergence differential assumes this "
                    "path is replayable — inject the clock "
                    "(``clock=`` defaulting to the wall, the "
                    "qos/slo idiom) or, for telemetry timestamps, "
                    "register the function in "
                    "determinism.WALL_CLOCK_SINKS with a "
                    "justification"
                ),
                key=keys.key(module, info.qualname, leaf),
            ))
    return findings


def stale_wall_clock_sinks(files: list[SourceFile]
                           ) -> list[tuple[str, str]]:
    """Registry entries that no longer resolve to a real wall-clock
    call site (the FANOUT_GATES non-vacuity contract: a stale entry
    fails the gate test — the registry only describes live code)."""
    stale = []
    for (suffix, qual) in WALL_CLOCK_SINKS:
        live = False
        for src in files:
            if src.tree is None or not src.relpath.endswith(suffix):
                continue
            aliases = import_aliases(src.tree, relative="skip")
            if qual == "*":
                live = bool(wall_clock_calls_in(src.tree, aliases))
            else:
                for fn_qual, fn in _functions(src.tree):
                    if fn_qual == qual and \
                            wall_clock_calls_in(fn, aliases):
                        live = True
                        break
            if live:
                break
        if not live:
            stale.append((suffix, qual))
    return stale


# ===========================================================================
# shared per-module scope map (module-level code attributes to
# "<module>"; nested defs to their qualified name)


def _functions(tree: ast.AST) -> list:
    """(qualname, node) for EVERY def at any nesting depth — class
    methods, functions nested inside methods, classes inside
    functions. shapecheck's enumerator stops one level down inside
    classes; the per-function rules here must see a def nested in a
    method as its own scope (one finding, its own key) rather than
    missing it entirely."""
    out: list = []

    def rec(node, prefix: str) -> None:
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + sub.name
                out.append((qual, sub))
                rec(sub, qual + ".")
            elif isinstance(sub, ast.ClassDef):
                rec(sub, prefix + sub.name + ".")
            else:
                rec(sub, prefix)

    rec(tree, "")
    return out


def _scope_map(tree: ast.AST) -> dict[int, str]:
    scope: dict[int, str] = {}
    # outermost first so nested defs override their enclosing scope
    for qual, fn in _functions(tree):
        for sub in ast.walk(fn):
            scope[id(sub)] = qual
    return scope


def _walk_own(fn):
    """``ast.walk`` over one function EXCLUDING nested def subtrees:
    ``_functions`` yields nested defs as their own entries, so a rule
    walking both would report one defect twice under two keys
    (lambdas stay in — they have no ``_functions`` entry)."""
    stack = [fn]
    while stack:
        node = stack.pop()
        if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _scoped_calls(src: SourceFile):
    """(qualname, Call) for every call in the module, module-level
    statements attributed to "<module>"."""
    scope = _scope_map(src.tree)
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            yield scope.get(id(node), "<module>"), node


# ===========================================================================
# rule: unseeded-rng


def _is_none(node: Optional[ast.expr]) -> bool:
    return node is None or (
        isinstance(node, ast.Constant) and node.value is None)


def _check_unseeded_rng(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in files:
        if src.tree is None or not _in_det_scope(src.relpath):
            continue
        aliases = import_aliases(src.tree, relative="skip")
        module = src.relpath.rsplit("/", 1)[-1]
        keys = _OrdinalKeys()
        hits: list[tuple] = []
        for qual, call in _scoped_calls(src):
            dotted = _dotted(call.func, aliases)
            if dotted is None:
                continue
            if dotted == "random.Random":
                if (not call.args and not call.keywords) or \
                        (call.args and _is_none(call.args[0])):
                    hits.append((qual, call, "Random", (
                        "random.Random() without a seed draws its "
                        "state from OS entropy: a failing run cannot "
                        "be replayed. Thread a seed through (the "
                        "FFTPU_SEED / FaultSchedule.rng_for idiom) "
                        "or accept an injected rng parameter"
                    )))
            elif dotted == "random.SystemRandom":
                hits.append((qual, call, "SystemRandom", (
                    "random.SystemRandom draws from the OS entropy "
                    "pool on every call — unreplayable by "
                    "construction; use a seeded random.Random"
                )))
            elif dotted.startswith("random.") and \
                    dotted.split(".", 1)[1] in _GLOBAL_RNG_FNS:
                hits.append((qual, call, dotted, (
                    f"{dotted}() rides the process-global unseeded "
                    "stream shared by every module in the process: "
                    "draws interleave across components, so even a "
                    "global random.seed() cannot make one "
                    "component's schedule reproducible — use an "
                    "injected/seeded random.Random instance"
                )))
            elif dotted.startswith("numpy.random."):
                leaf = dotted.rsplit(".", 1)[-1]
                seeded = (
                    leaf in _NP_SEEDABLE
                    and call.args and not _is_none(call.args[0])
                ) or any(
                    kw.arg == "seed" and not _is_none(kw.value)
                    for kw in call.keywords
                )
                if not seeded:
                    hits.append((qual, call, dotted, (
                        f"{dotted}() without seed provenance: "
                        "np.random's global state (or a fresh "
                        "unseeded generator) is unreplayable — pass "
                        "an explicit seed or a seeded Generator"
                    )))
        for qual, call, leaf, msg in sorted(
                hits, key=lambda h: (h[1].lineno, h[1].col_offset)):
            short = leaf.rsplit(".", 1)[-1] if leaf.startswith(
                "numpy.") else leaf
            findings.append(Finding(
                rule="unseeded-rng",
                path=src.relpath, line=call.lineno,
                message=msg,
                key=keys.key(module, qual, short),
            ))
    return findings


# ===========================================================================
# rule: iteration-order-leak


_SET_METHODS = frozenset((
    "union", "intersection", "difference", "symmetric_difference",
    "copy",
))


class _SetTaint:
    """Per-module set-provenance: which class attributes and local
    names provably hold sets. Straight-line, last-assignment-wins —
    the same approximation shapecheck's local env uses."""

    def __init__(self, src: SourceFile):
        self.src = src
        # class name -> attr names assigned set-valued expressions
        self.class_attrs: dict[str, set] = {}
        for node in src.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            attrs: set = set()
            for sub in ast.walk(node):
                target = None
                value = None
                if isinstance(sub, ast.Assign) and len(
                        sub.targets) == 1:
                    target, value = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign):
                    target, value = sub.target, sub.value
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                ann = getattr(sub, "annotation", None)
                # the annotation's NAMES must say set ("Dataset" or
                # an "offset" field name must not)
                ann_names = {
                    n.id for n in ast.walk(ann)
                    if isinstance(n, ast.Name)
                } | {
                    n.attr for n in ast.walk(ann)
                    if isinstance(n, ast.Attribute)
                } if ann is not None else set()
                ann_set = bool(ann_names & {
                    "set", "Set", "frozenset", "FrozenSet",
                    "MutableSet", "AbstractSet",
                })
                if ann_set or (value is not None
                               and self._is_set(value, {}, attrs)):
                    attrs.add(target.attr)
            if attrs:
                self.class_attrs[node.name] = attrs

    def _is_set(self, expr: ast.expr, env: dict,
                self_attrs: set) -> bool:
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return True
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name) and \
                    expr.func.id in ("set", "frozenset"):
                return True
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr in _SET_METHODS and \
                    self._is_set(expr.func.value, env, self_attrs):
                return True
            return False
        if isinstance(expr, ast.BinOp) and isinstance(
                expr.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
            return (self._is_set(expr.left, env, self_attrs)
                    or self._is_set(expr.right, env, self_attrs))
        if isinstance(expr, ast.Name):
            return env.get(expr.id, False)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return expr.attr in self_attrs
        return False

    def env_for(self, fn, class_name: Optional[str]) -> tuple:
        self_attrs = self.class_attrs.get(class_name or "", set())
        env: dict = {}
        assigns = sorted(
            (n for n in _walk_own(fn) if isinstance(n, ast.Assign)),
            key=lambda n: (n.lineno, n.col_offset),
        )
        for node in assigns:
            verdict = self._is_set(node.value, env, self_attrs)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    env[tgt.id] = verdict
        return env, self_attrs


def _display_of(expr: ast.expr) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return "<set>"


def _check_iteration_order(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in files:
        if src.tree is None or not _in_det_scope(src.relpath):
            continue
        module = src.relpath.rsplit("/", 1)[-1]
        taint = _SetTaint(src)
        keys = _OrdinalKeys()
        class_of: dict[int, Optional[str]] = {}
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        class_of[id(sub)] = node.name
        for qual, fn in _functions(src.tree):
            env, self_attrs = taint.env_for(
                fn, class_of.get(id(fn)))

            def is_set(expr) -> bool:
                return taint._is_set(expr, env, self_attrs)

            hits: list[tuple] = []
            for node in _walk_own(fn):
                if isinstance(node, (ast.For, ast.AsyncFor)) and \
                        is_set(node.iter):
                    sink = None
                    for sub in ast.walk(node):
                        if isinstance(sub, (ast.Yield, ast.YieldFrom)):
                            sink = "yield"
                            break
                        if isinstance(sub, ast.Call) and isinstance(
                                sub.func, ast.Attribute) and \
                                sub.func.attr in _ORDER_SINK_CALLS:
                            sink = sub.func.attr
                            break
                    if sink is not None:
                        hits.append((node, node.iter, (
                            f"set iterated into an order-sensitive "
                            f"sink ({sink}): set order varies per "
                            "process (PYTHONHASHSEED) — iterate "
                            "sorted(...) or keep an insertion-"
                            "ordered dict"
                        )))
                elif isinstance(node, ast.Call):
                    func = node.func
                    if isinstance(func, ast.Name) and \
                            func.id in ("list", "tuple") and \
                            len(node.args) == 1 and \
                            is_set(node.args[0]):
                        hits.append((node, node.args[0], (
                            f"{func.id}() materializes a set in "
                            "arbitrary per-process order — use "
                            "sorted(...) (or an insertion-ordered "
                            "dict) so downstream consumers see a "
                            "stable order"
                        )))
                    elif isinstance(func, ast.Attribute) and \
                            func.attr == "join" and node.args:
                        arg = node.args[0]
                        leaky = is_set(arg) or (
                            isinstance(arg, ast.GeneratorExp)
                            and arg.generators
                            and is_set(arg.generators[0].iter)
                        )
                        if leaky:
                            hits.append((node, arg, (
                                "join() over a set serializes it in "
                                "arbitrary per-process order — "
                                "join over sorted(...)"
                            )))
                elif isinstance(node, ast.ListComp) and \
                        node.generators and \
                        is_set(node.generators[0].iter):
                    hits.append((node, node.generators[0].iter, (
                        "list comprehension over a set builds an "
                        "arbitrarily-ordered list — comprehend over "
                        "sorted(...)"
                    )))
            for node, src_expr, msg in sorted(
                    hits, key=lambda h: (h[0].lineno,
                                         h[0].col_offset)):
                findings.append(Finding(
                    rule="iteration-order-leak",
                    path=src.relpath, line=node.lineno,
                    message=msg,
                    key=keys.key(module, qual, _display_of(src_expr)),
                ))
    return findings


# ===========================================================================
# rule: hash-order-dependence


def _provably_strlike(expr: ast.expr, env: dict) -> bool:
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, (str, bytes))
    if isinstance(expr, ast.JoinedStr):
        return True
    if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.Add, ast.Mod)):
        return (_provably_strlike(expr.left, env)
                or _provably_strlike(expr.right, env))
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Name) and \
                expr.func.id in ("str", "repr", "format"):
            return True
        if isinstance(expr.func, ast.Attribute) and expr.func.attr in (
                "encode", "decode", "format", "join", "lower",
                "upper", "strip"):
            return True
        return False
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(_provably_strlike(e, env) for e in expr.elts)
    if isinstance(expr, ast.Name):
        return env.get(expr.id, False)
    return False


def _check_hash_order(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in files:
        if src.tree is None or not _in_det_scope(src.relpath):
            continue
        module = src.relpath.rsplit("/", 1)[-1]
        keys = _OrdinalKeys()
        for qual, fn in _functions(src.tree):
            if qual.rsplit(".", 1)[-1] == "__hash__":
                # dict/set identity inside one process is fine; the
                # hazard is cross-run ordering, which __hash__ alone
                # does not create
                continue
            env: dict = {}
            for node in sorted(
                    (n for n in _walk_own(fn)
                     if isinstance(n, ast.Assign)),
                    key=lambda n: (n.lineno, n.col_offset)):
                verdict = _provably_strlike(node.value, env)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        env[tgt.id] = verdict
            flagged: set[int] = set()
            hits: list[tuple] = []

            def is_hash(call) -> bool:
                return (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Name)
                        and call.func.id == "hash" and call.args)

            for node in _walk_own(fn):
                if isinstance(node, ast.BinOp) and isinstance(
                        node.op, ast.Mod) and is_hash(node.left):
                    flagged.add(id(node.left))
                    hits.append((node.left, (
                        "hash(x) % n selects a partition/slot from "
                        "the builtin hash: for str/bytes keys the "
                        "hash is salted per process "
                        "(PYTHONHASHSEED), so placement diverges "
                        "across runs and hosts — use zlib.crc32 "
                        "(the partitioning.partition_for idiom) or "
                        "hashlib"
                    )))
            for node in _walk_own(fn):
                if is_hash(node) and id(node) not in flagged and \
                        _provably_strlike(node.args[0], env):
                    hits.append((node, (
                        "builtin hash() of str/bytes is salted per "
                        "process (PYTHONHASHSEED): any ordering or "
                        "selection derived from it diverges across "
                        "runs — use zlib.crc32/hashlib for stable "
                        "keys (dict membership inside one process "
                        "does not need this rule; __hash__ methods "
                        "are exempt)"
                    )))
            for node, msg in sorted(
                    hits, key=lambda h: (h[0].lineno,
                                         h[0].col_offset)):
                findings.append(Finding(
                    rule="hash-order-dependence",
                    path=src.relpath, line=node.lineno,
                    message=msg,
                    key=keys.key(module, qual, "hash"),
                ))
    return findings


# ===========================================================================
# entry point


def check(files: list[SourceFile],
          graph: Optional[CallGraph] = None) -> list[Finding]:
    graph = graph or build_callgraph(files)
    findings: list[Finding] = []
    findings += _check_wall_clock(files, graph)
    findings += _check_unseeded_rng(files)
    findings += _check_iteration_order(files)
    findings += _check_hash_order(files)
    return findings
