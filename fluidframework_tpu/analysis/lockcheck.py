"""lockcheck — lock discipline around cross-thread mutable state.

For every class (or module) that creates a ``threading.Lock`` /
``RLock``, infer which attributes (or globals) are written while the
lock is held, then report writes that bypass it — the exact shape of
the ``debug_driver.break_at`` race the round-5 advisor found: an
attribute read under the state lock but mutated raw from outside.

Inference rules, deliberately conservative:

- Lock regions are ``with self.<lock>:`` blocks (``acquire()`` /
  ``release()`` pairs are not tracked — none exist in this tree; use
  ``with``).
- A private helper method (``_name``) counts as lock-held when EVERY
  in-class call site holds the lock (transitively) — that covers the
  ``_drain_locked`` pattern without annotations. Public methods are
  externally callable and never inherit a caller's lock.
- An attribute's guard is the INTERSECTION of locks held across its
  locked writes; only writes holding none of the guard are reported
  (an attr consistently written under lock A inside a nested lock-B
  region is not a lock-B attr).
- ``__init__`` is construction-time and exempt.

Two rules:

- ``lock-unlocked-write`` — a method of the owning scope writes a
  guarded attribute (or module global) without holding its lock.
- ``lock-external-write`` — code OUTSIDE the owning class assigns,
  through an instance, a public attribute the class only ever writes
  under its lock: external callers cannot hold a private lock
  correctly, so mutation must go through the class's locked setter.
  (Matching is by bare attribute name across the tree; restricting
  the registry to locked-WRITTEN attrs keeps config names like
  ``host``/``timeout`` — merely read under locks — out of it.)
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .core import Finding, SourceFile

# method calls that mutate their receiver (list/dict/set/deque)
MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft",
    "appendleft", "clear", "add", "discard", "update", "setdefault",
    "sort", "reverse",
}

LOCK_FACTORIES = {"threading.Lock", "threading.RLock", "Lock", "RLock"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    parts = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts)) in LOCK_FACTORIES


@dataclasses.dataclass
class _Access:
    attr: str
    write: bool
    held: frozenset
    method: str
    line: int


@dataclasses.dataclass
class _CallSite:
    callee: str
    held: frozenset


class _ScopeWalker(ast.NodeVisitor):
    """Walk one function/method body tracking which of the scope's
    locks are held, recording attribute/global accesses and intra-scope
    calls. ``base`` is "self" for methods, None for module functions
    (then plain Names declared ``global`` are the tracked attrs)."""

    def __init__(self, locks: set, method: str, base: Optional[str],
                 tracked_globals: Optional[set] = None):
        self.locks = locks
        self.method = method
        self.base = base
        self.tracked_globals = tracked_globals or set()
        self.declared_global: set = set()
        self.held: frozenset = frozenset()
        self.accesses: list[_Access] = []
        self.calls: list[_CallSite] = []

    # -- helpers -------------------------------------------------------

    def _own_attr(self, node: ast.AST) -> Optional[str]:
        if self.base is not None:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == self.base:
                return node.attr
            return None
        if isinstance(node, ast.Name) and \
                node.id in self.declared_global and \
                node.id in self.tracked_globals:
            return node.id
        return None

    def _lock_name(self, node: ast.AST) -> Optional[str]:
        """The scope lock a with-item context names, if any. Unlike
        attribute tracking this needs no ``global`` declaration —
        ``with _lock:`` only READS the module global."""
        if self.base is not None:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == self.base and \
                    node.attr in self.locks:
                return node.attr
            return None
        if isinstance(node, ast.Name) and node.id in self.locks:
            return node.id
        return None

    def _record(self, attr: str, write: bool, line: int) -> None:
        self.accesses.append(_Access(
            attr, write, self.held, self.method, line,
        ))

    def _record_target(self, target: ast.AST, line: int) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_target(elt, line)
            return
        attr = self._own_attr(target)
        if attr is not None:
            self._record(attr, True, line)
            return
        if isinstance(target, ast.Subscript):
            # self.attr[k] = v / self.attr[:0] = ... mutate the attr
            attr = self._own_attr(target.value)
            if attr is not None:
                self._record(attr, True, line)
        if isinstance(target, ast.Starred):
            self._record_target(target.value, line)

    # -- visitors ------------------------------------------------------

    def visit_Global(self, node: ast.Global) -> None:
        self.declared_global.update(node.names)

    def visit_With(self, node: ast.With) -> None:
        acquired = set()
        for item in node.items:
            name = self._lock_name(item.context_expr)
            if name is not None:
                acquired.add(name)
        prev = self.held
        self.held = self.held | frozenset(acquired)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._record_target(target, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node.target, node.lineno)
            self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        # self.attr.append(...) — receiver mutation counts as a write
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
            attr = self._own_attr(f.value)
            if attr is not None:
                self._record(attr, True, node.lineno)
        # self.method(...) / local function call
        if self.base is not None:
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == self.base:
                self.calls.append(_CallSite(f.attr, self.held))
        elif isinstance(f, ast.Name):
            self.calls.append(_CallSite(f.id, self.held))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._own_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._record(attr, False, node.lineno)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if self.base is None and isinstance(node.ctx, ast.Load):
            attr = self._own_attr(node)
            if attr is not None:
                self._record(attr, False, node.lineno)

    def visit_FunctionDef(self, node):  # nested defs: same scope rules
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


@dataclasses.dataclass
class _Scope:
    """One lock-owning scope (a class, or the module itself)."""

    name: str            # class name, or "<module>"
    locks: set
    accesses: list[_Access]
    callsites: dict      # method -> list[_CallSite]
    methods: set


def _propagate(scope: _Scope) -> dict[str, frozenset]:
    """locks guaranteed held on entry to each PRIVATE helper (every
    in-scope call site holds them, transitively). Greatest fixpoint."""
    private = {
        m for m in scope.methods
        if m.startswith("_") and not m.startswith("__")
    }
    inherited = {m: frozenset(scope.locks) for m in private}
    sites: dict[str, list[tuple[str, frozenset]]] = {m: [] for m in private}
    for caller, calls in scope.callsites.items():
        for c in calls:
            if c.callee in private:
                sites[c.callee].append((caller, c.held))
    changed = True
    while changed:
        changed = False
        for m in private:
            if not sites[m]:
                new = frozenset()
            else:
                new = frozenset(scope.locks)
                for caller, held in sites[m]:
                    new &= held | inherited.get(caller, frozenset())
            if new != inherited[m]:
                inherited[m] = new
                changed = True
    return inherited


def _analyze_scope(scope: _Scope, relpath: str,
                   ) -> tuple[list[Finding], dict[str, str]]:
    """Findings for one scope, plus the scope's PUBLIC guarded attrs
    (attr -> owning scope name) for the external-write rule."""
    inherited = _propagate(scope)

    def effective(acc: _Access) -> frozenset:
        return acc.held | inherited.get(acc.method, frozenset())

    events = [a for a in scope.accesses if a.method != "__init__"]
    writes: dict[str, list[_Access]] = {}
    for a in events:
        if a.write:
            writes.setdefault(a.attr, []).append(a)

    findings = []
    # module-scope keys carry the module filename: "<module>.attr"
    # alone would collide across files (one allowlist entry silently
    # grandfathering every module's same-named global). Class keys
    # stay bare — class names are already tree-unique identities.
    module = relpath.rsplit("/", 1)[-1]
    key_scope = f"{module}:{scope.name}" \
        if scope.name == "<module>" else scope.name
    for attr, evs in sorted(writes.items()):
        locked = [e for e in evs if effective(e)]
        if not locked:
            continue
        guard = frozenset(scope.locks)
        for e in locked:
            guard &= effective(e)
        if not guard:
            continue  # inconsistent guards; no single lock to enforce
        lock_desc = "/".join(sorted(guard))
        for e in evs:
            if effective(e) & guard:
                continue
            findings.append(Finding(
                rule="lock-unlocked-write",
                path=relpath, line=e.line,
                message=(
                    f"{scope.name}.{e.method}() writes {attr!r} "
                    f"without {lock_desc!r} (other writes hold it); "
                    "a concurrent locked reader can observe a torn "
                    "update"
                ),
                key=f"{key_scope}.{attr}",
            ))
    # public attrs the class WRITES under its lock: the class chose to
    # serialize mutation, so a raw external write bypasses an existing
    # discipline. Attrs merely READ under the lock (host/port/timeout
    # config) are deliberately excluded — name-based cross-file
    # matching would flag every unrelated object sharing the name.
    public_guarded = {
        attr: scope.name
        for attr, evs in writes.items()
        if not attr.startswith("_") and scope.name != "<module>"
        and any(effective(e) for e in evs)
    }
    return findings, public_guarded


def _collect_scopes(src: SourceFile) -> list[_Scope]:
    scopes = []
    tree = src.tree

    def _assign_targets(stmt):
        """Targets of a lock-creating statement — plain and annotated
        (``_lock: threading.Lock = threading.Lock()``) assignments."""
        if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
            return stmt.targets
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and _is_lock_ctor(stmt.value):
            return [stmt.target]
        return []

    # module-level locks guard module globals
    mod_locks = set()
    for stmt in tree.body:
        for t in _assign_targets(stmt):
            if isinstance(t, ast.Name):
                mod_locks.add(t.id)
    if mod_locks:
        tracked = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                tracked.update(
                    t.id for t in stmt.targets
                    if isinstance(t, ast.Name)
                )
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                tracked.add(stmt.target.id)
        accesses, callsites, methods = [], {}, set()
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                w = _ScopeWalker(mod_locks, stmt.name, None, tracked)
                for s in stmt.body:
                    w.visit(s)
                accesses.extend(w.accesses)
                callsites[stmt.name] = w.calls
                methods.add(stmt.name)
        scopes.append(_Scope("<module>", mod_locks, accesses,
                             callsites, methods))

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        locks = set()
        for sub in ast.walk(node):
            for t in _assign_targets(sub):
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    locks.add(t.attr)
        if not locks:
            continue
        accesses, callsites, methods = [], {}, set()
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                w = _ScopeWalker(locks, stmt.name, "self")
                for s in stmt.body:
                    w.visit(s)
                accesses.extend(w.accesses)
                callsites[stmt.name] = w.calls
                methods.add(stmt.name)
        scopes.append(_Scope(node.name, locks, accesses, callsites,
                             methods))
    return scopes


class _ExternalWriteFinder(ast.NodeVisitor):
    """Assignments ``<expr>.attr = ...`` through a non-self base, for
    attrs registered as public lock-guarded somewhere in the tree."""

    def __init__(self, registry: dict[str, set], relpath: str):
        self.registry = registry
        self.relpath = relpath
        self.findings: list[Finding] = []

    def _check_target(self, target: ast.AST, line: int) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt, line)
            return
        if not isinstance(target, ast.Attribute):
            return
        base = target.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            return
        owners = self.registry.get(target.attr)
        if not owners:
            return
        owner = "/".join(sorted(owners))
        self.findings.append(Finding(
            rule="lock-external-write",
            path=self.relpath, line=line,
            message=(
                f"raw write to {target.attr!r}, which "
                f"{owner} writes only under a lock: external callers "
                "cannot hold a private lock — use/add a locked "
                "setter on the owning class"
            ),
            key=f"{owner}.{target.attr}",
        ))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)


def check(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    # public guarded attr -> owning class(es); matching is by bare
    # attribute name (no cross-file type inference), so colliding
    # owners are all reported rather than last-writer-wins
    registry: dict[str, set] = {}
    for src in files:
        if src.tree is None:
            continue
        for scope in _collect_scopes(src):
            scope_findings, public_guarded = _analyze_scope(
                scope, src.relpath
            )
            findings.extend(scope_findings)
            for attr, owner in public_guarded.items():
                registry.setdefault(attr, set()).add(owner)
    if registry:
        for src in files:
            if src.tree is None:
                continue
            finder = _ExternalWriteFinder(registry, src.relpath)
            finder.visit(src.tree)
            # writes inside the owning class's own file through a
            # non-self alias are rare and legitimate there; still
            # report — the allowlist can grandfather if needed
            findings.extend(finder.findings)
    return findings
