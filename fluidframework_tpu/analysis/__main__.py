"""fluidlint CLI.

    python -m fluidframework_tpu.analysis [paths...] [options]

Exit status 0 when every finding is suppressed or allowlisted, 1
otherwise (2 for usage errors). ``--json`` emits a machine-readable
report for BENCH/ADVICE tooling.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .core import (
    ALLOWLIST_PATH,
    DEFAULT_ROOTS,
    FAMILIES,
    REPO_ROOT,
    apply_allowlist,
    load_allowlist,
    run_analysis,
)


def changed_files(ref: str, repo_root: str = REPO_ROOT) -> list[str]:
    """Python files touched vs ``ref`` (committed diff + staged +
    working tree + untracked), repo-root-relative, existing only —
    the fast-local-iteration scan set for ``--changed``."""
    out: set[str] = set()
    for args in (
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                args, capture_output=True, text=True, cwd=repo_root,
                timeout=30,
            )
        except subprocess.TimeoutExpired as e:
            # the documented CLI failure contract is `error: ...` +
            # exit 2, not a raw traceback
            raise ValueError(
                f"git timed out for {' '.join(args)!r}"
            ) from e
        if proc.returncode != 0:
            raise ValueError(
                f"git failed for {' '.join(args)!r}: "
                f"{proc.stderr.strip()}"
            )
        out.update(
            line.strip() for line in proc.stdout.splitlines()
            if line.strip()
        )
    return sorted(
        p for p in out
        if p.endswith(".py")
        and os.path.exists(os.path.join(repo_root, p))
    )


def to_sarif(findings, stale) -> dict:
    """SARIF 2.1.0 (the interchange format CI diff annotators read).
    Stale allowlist entries report as tool-level notifications: they
    have no code location but must not exit 0 silently."""
    rules = sorted({f.rule for f in findings})
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "fluidlint",
                    "informationUri":
                        "docs/ANALYSIS.md",
                    "rules": [{"id": r} for r in rules],
                },
            },
            "results": [
                {
                    "ruleId": f.rule,
                    "level": "error",
                    "message": {"text": f.message},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {"startLine": max(1, f.line)},
                        },
                    }],
                    # the allowlist identity, so annotation tooling
                    # can dedupe across rebases exactly as the
                    # ratchet does
                    "partialFingerprints": {"fluidlintKey": f.key},
                }
                for f in findings
            ],
            "invocations": [{
                # SARIF semantics: whether the TOOL ran to completion
                # — findings do NOT make the run unsuccessful (CI
                # consumers would discard the results exactly when
                # there is something to annotate); only a tool-level
                # fault (stale allowlist) flips it
                "executionSuccessful": not stale,
                "toolExecutionNotifications": [
                    {
                        "level": "error",
                        "message": {"text": (
                            f"stale allowlist entry '{rule} {key}' "
                            "matches no live finding — delete it"
                        )},
                    }
                    for rule, key in stale
                ],
            }],
        }],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fluidframework_tpu.analysis",
        description="fluidlint: " + " + ".join(FAMILIES),
    )
    parser.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to scan (default: the repo tree)",
    )
    parser.add_argument(
        "--changed", nargs="?", const="HEAD", default=None,
        metavar="REF",
        help="scan only python files touched vs a git ref (default "
             "HEAD when the flag is bare) — fast local iteration "
             "before the full tier-1 gate run; allowlist staleness "
             "is skipped like any partial-path scan",
    )
    parser.add_argument(
        "--rules", default=",".join(FAMILIES),
        help="comma-separated pass families to run "
             f"(default: {','.join(FAMILIES)})",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON "
             "{findings, allowlisted, stale_allowlist}",
    )
    parser.add_argument(
        "--sarif", action="store_true", dest="as_sarif",
        help="emit findings as SARIF 2.1.0 (CI diff annotation)",
    )
    parser.add_argument(
        "--allowlist", default=ALLOWLIST_PATH,
        help="allowlist file (default: analysis/allowlist.txt)",
    )
    parser.add_argument(
        "--no-allowlist", action="store_true",
        help="report grandfathered findings too",
    )
    args = parser.parse_args(argv)

    families = [f for f in args.rules.split(",") if f]
    partial_scan = bool(args.paths)
    try:
        if args.changed is not None:
            if args.paths:
                raise ValueError(
                    "--changed and explicit paths are mutually "
                    "exclusive"
                )
            roots = changed_files(args.changed, REPO_ROOT)
            partial_scan = True
            if not roots:
                # still fall through to the output stage: a docs-only
                # diff under --sarif/--json must emit a valid empty
                # report, not zero bytes of stdout
                print(
                    f"fluidlint: no python files changed vs "
                    f"{args.changed}", file=sys.stderr,
                )
        else:
            roots = args.paths or DEFAULT_ROOTS
        findings = run_analysis(
            roots=roots,
            families=families,
            repo_root=REPO_ROOT,
        ) if roots else []
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    allowlist = [] if args.no_allowlist else load_allowlist(
        args.allowlist
    )
    kept, stale = apply_allowlist(findings, allowlist)
    n_allowed = len(findings) - len(kept)
    if partial_scan:
        # a partial-path scan (explicit paths or --changed)
        # legitimately misses allowlisted findings elsewhere in the
        # tree; staleness is only meaningful (and only enforced, here
        # and in the gate test) on a full default-roots run
        stale = []

    if args.as_sarif:
        print(json.dumps(to_sarif(kept, stale), indent=2))
    elif args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in kept],
            "allowlisted": n_allowed,
            "stale_allowlist": [
                {"rule": r, "key": k} for r, k in stale
            ],
            "families": families,
        }, indent=2))
    else:
        for f in kept:
            print(f.format())
        for rule, key in stale:
            print(
                f"allowlist entry '{rule} {key}' matches no finding "
                "anymore — delete it (the ratchet only goes down)"
            )
        summary = (
            f"fluidlint: {len(kept)} finding(s), "
            f"{n_allowed} allowlisted, {len(stale)} stale allowlist "
            f"entr{'y' if len(stale) == 1 else 'ies'}"
        )
        print(summary, file=sys.stderr)
    return 1 if (kept or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
