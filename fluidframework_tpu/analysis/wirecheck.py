"""wirecheck — static wire-schema extraction and encoder/decoder
symmetry over the frame codecs.

The wire contract — length-prefixed JSON frames between the socket
drivers and the alfred ingress — is the one interface every peer,
recorded corpus and cross-version deployment depends on, and until
this family it was guarded only by hand-written interop cases. The
pass extracts a per-frame-type field schema from the encoder and
decoder ASTs (dict displays carrying a ``"type"`` key, ``out["k"]``
augmentations, ``**helper()`` expansions resolved through the shared
callgraph, and the matching reads on the other side) and checks it
against the reviewed :data:`WIRE_SCHEMA` registry in
``protocol/constants.py`` (frame type -> field -> since-version spec):

- **encoder-decoder-drift** — every field a serializer can emit must
  be consumed somewhere by the matching deserializer side (or be
  explicitly tolerated, the ``~`` flag), and an UNGUARDED decoder read
  of a field no encoder in scope ever emits is the same drift seen
  from the other end.
- **optional-field-unconditional-emit** — a field the registry marks
  optional-presence (``?`` — the post-1.0 byte-stability discipline:
  qos shed attribution, traces, boxcar members) must be emitted only
  under a guard (an ``if`` around the emit, or a non-None constant
  value), never unconditionally with a maybe-None value: a 1.0 peer
  and a recorded corpus must not see keys that carry nothing.
- **ungated-wire-read** — a decoder reading a post-1.0 (or
  optional-presence) field with a bare subscript must ``.get()`` with
  a default, sit behind a presence check on the same field, or be
  version-gated by ``wire_version_lt`` (directly, through a
  gate-providing helper, or inherited from a gate-covered call site —
  the ``upload_summary`` -> ``_doc_upload_summary`` shape), so a 1.0
  peer's frame can never KeyError a newer endpoint.
- **unversioned-frame-field** — an emitted field (or whole frame
  type) absent from the registry fails the gate: schema growth is a
  reviewed registry diff, never an accident.

Scope is the reviewed :data:`WIRE_MODULES` list — the protocol codecs
and the production driver/ingress endpoints. The chaos harness,
serve_bench, stress tools and the broker/moira sidecar planes speak
the same frames but are HARNESSES, not the contract's endpoints; the
runtime half (``testing/wiresan.py``) covers what they actually put
on the wire, and its differential (tests/test_wiresan.py) pins every
observed (frame type, field) back to this registry BY NAME.

Known approximation shapes (docs/ANALYSIS.md has the full list): a
frame dict built under ANY ``if`` counts as guarded for rule 2 (the
guard's condition is not checked), and every callee of a
gate-covered call site inherits the gate for rule 3 — both trade
false positives for false negatives the runtime differential
backstops.

Like every fluidlint pass, this module imports NOTHING it lints: the
registry itself is read from the SCANNED tree's
``protocol/constants.py`` via ``ast.literal_eval``, so linting a
fixture tree uses the fixture's registry, never the live one.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .callgraph import CallGraph, build_callgraph
from .core import Finding, SourceFile

# ---------------------------------------------------------------------------
# reviewed registries

# The wire contract's endpoints (relpath suffixes). Everything else
# that speaks frames (testing/chaos.py, tools/serve_bench.py,
# tools/stress.py, service/broker.py, service/moira.py, tests/) is a
# harness or a separate protocol plane: runtime wiresan observes their
# traffic instead.
WIRE_MODULES = (
    "protocol/serialization.py",
    "protocol/columnar.py",
    "protocol/tree_payload.py",
    "drivers/socket_driver.py",
    "drivers/caching_driver.py",
    "service/ingress.py",
    "service/__main__.py",
)

# where the WIRE_SCHEMA registry literal lives in the scanned tree
SCHEMA_MODULE = "protocol/constants.py"

# Payload codecs: op payloads ride inside frames ("msg", "msgs",
# "op"/"ops", "operation") with their own field vocabulary; the
# registry models them as ``msg:*`` pseudo-types and these function
# pairs are their single encode/decode definitions. Unlike frame
# dicts, a payload schema KEEPS its "type" field (it is a payload
# field, not the frame discriminator).
PAYLOAD_CODECS = {
    ("protocol/serialization.py", "message_to_json"):
        ("emit", "msg:sequenced"),
    ("protocol/serialization.py", "message_from_json"):
        ("read", "msg:sequenced"),
    ("service/ingress.py", "document_message_to_json"):
        ("emit", "msg:document"),
    ("service/ingress.py", "document_message_from_json"):
        ("read", "msg:document"),
    # the wire-1.3 columnar submitOp payload ("cols"): the payload IS
    # the column layout, so its codec pair registers the column names
    # the same way the row codecs register message fields
    ("protocol/columnar.py", "encode_columns"):
        ("emit", "cols:columnar"),
    ("protocol/columnar.py", "decode_columns"):
        ("read", "cols:columnar"),
    # the wire-1.5 sharedtree channel-op payload: one codec pair for
    # the dict the runtime envelope carries two levels down a msg:*
    # payload (the tree serving plane's ingest feed)
    ("protocol/tree_payload.py", "tree_change_to_json"):
        ("emit", "msg:tree"),
    ("protocol/tree_payload.py", "tree_change_from_json"):
        ("read", "msg:tree"),
}

# request frame type -> the response frame type a ``_request()`` call
# returns (the rid-paired request/response plane)
RESPONSE_OF = {
    "read_ops": "ops",
    "fetch_summary": "summary",
    "upload_summary_chunk": "summary_uploaded",
    "metrics": "metrics",
    "fleet-metrics": "fleet-metrics",
    "slo": "slo",
    "heat": "heat",
}

# leaf method names whose return value is the rid-paired response of
# the request dict they were passed
REQUEST_HELPERS = frozenset(("_request",))

# the one version-gate helper (protocol/constants.py); calling it —
# or a function that transitively calls it — before a read counts as
# version-gating for rule 3
GATE_FN = "wire_version_lt"


def parse_spec(spec: str) -> tuple[str, bool, bool]:
    """``"1.1?"`` -> (since, optional_presence, tolerated). Mirrors
    ``protocol.constants.wire_schema_fields`` — duplicated because a
    fluidlint pass imports nothing it lints."""
    s = str(spec)
    optional = "?" in s
    tolerated = "~" in s
    since = s.replace("?", "").replace("~", "")
    return since, optional, tolerated


def _ver(v: str) -> tuple:
    try:
        return tuple(int(x) for x in v.split("."))
    except ValueError:
        return (9, 9)


def load_registry(files: list[SourceFile]) -> Optional[dict]:
    """The WIRE_SCHEMA dict literal from the scanned tree's
    ``protocol/constants.py`` (None when the scan scope carries no
    registry — the pass then has no contract to check against)."""
    for src in files:
        if src.tree is None or not src.relpath.endswith(SCHEMA_MODULE):
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "WIRE_SCHEMA":
                try:
                    reg = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(reg, dict):
                    return reg
    return None


class _OrdinalKeys:
    """Stable line-free finding keys (the detcheck discipline):
    ``module:qual:leaf`` with an ordinal suffix for repeats."""

    def __init__(self) -> None:
        self._seen: dict[tuple, int] = {}

    def key(self, module: str, qual: str, leaf: str) -> str:
        slot = (module, qual, leaf)
        n = self._seen.get(slot, 0) + 1
        self._seen[slot] = n
        return f"{module}:{qual}:{leaf}" + ("" if n == 1 else str(n))


# ---------------------------------------------------------------------------
# per-function AST facts


@dataclasses.dataclass
class _Site:
    """One emit or read site."""

    relpath: str
    module: str
    qual: str
    line: int
    col: int
    guarded: bool
    gated: bool = False


def _functions(tree: ast.AST) -> list:
    """(qualname, node) for every def at any nesting depth."""
    out: list = []

    def rec(node, prefix: str) -> None:
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + sub.name
                out.append((qual, sub))
                rec(sub, qual + ".")
            elif isinstance(sub, ast.ClassDef):
                rec(sub, prefix + sub.name + ".")
            else:
                rec(sub, prefix)

    rec(tree, "")
    return out


def _walk_own(fn):
    """Walk one function excluding nested def subtrees (lambdas stay
    in: a fanout closure's frame dict belongs to its enclosing
    handler)."""
    stack = [fn]
    while stack:
        node = stack.pop()
        if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_terminal(stmts: list) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _type_names(node) -> Optional[tuple]:
    """The frame-type string constants a compare tests against:
    Constant or a Tuple/List of Constants."""
    s = _const_str(node)
    if s is not None:
        return (s,)
    if isinstance(node, (ast.Tuple, ast.List)):
        names = tuple(_const_str(e) for e in node.elts)
        if names and all(n is not None for n in names):
            return names
    return None


def _get_call_field(node, varnames) -> Optional[tuple]:
    """``v.get("f" [, default])`` on a name in ``varnames`` ->
    (varname, field)."""
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "get" and \
            isinstance(node.func.value, ast.Name) and \
            (varnames is None or node.func.value.id in varnames) and \
            node.args:
        field = _const_str(node.args[0])
        if field is not None:
            return node.func.value.id, field
    return None


def _subscript_field(node, varnames) -> Optional[tuple]:
    """``v["f"]`` (Load) on a name in ``varnames`` -> (varname,
    field)."""
    if isinstance(node, ast.Subscript) and \
            isinstance(node.ctx, ast.Load) and \
            isinstance(node.value, ast.Name) and \
            (varnames is None or node.value.id in varnames):
        field = _const_str(node.slice)
        if field is not None:
            return node.value.id, field
    return None


def _type_expr_var(node, kind_of: dict) -> Optional[str]:
    """The frame var whose TYPE this expression denotes:
    ``frame.get("type")``, ``frame["type"]``, or a kind-var name."""
    hit = _get_call_field(node, None) or _subscript_field(node, None)
    if hit is not None and hit[1] == "type":
        return hit[0]
    if isinstance(node, ast.Name) and node.id in kind_of:
        return kind_of[node.id]
    return None


@dataclasses.dataclass
class _Region:
    var: str
    types: tuple            # frame types (typed region)
    field: Optional[str]    # presence-guard region when set
    ids: frozenset          # contained node ids

    @property
    def size(self) -> int:
        return len(self.ids)


def _ids_of(stmts: list) -> frozenset:
    out: set = set()
    for stmt in stmts:
        for sub in ast.walk(stmt):
            out.add(id(sub))
    return frozenset(out)


class _FnFacts:
    """Everything the rules need from one function, computed once."""

    def __init__(self, src: SourceFile, qual: str, fn,
                 info, class_name: Optional[str]) -> None:
        self.src = src
        self.relpath = src.relpath
        self.module = src.relpath.rsplit("/", 1)[-1]
        self.qual = qual
        self.fn = fn
        self.info = info
        self.class_name = class_name
        self.params = [a.arg for a in fn.args.args]
        if class_name is not None and self.params and \
                self.params[0] in ("self", "cls"):
            self.params = self.params[1:]
        # filled by the scan below
        self.kind_of: dict[str, str] = {}
        self.dict_types: dict[str, str] = {}
        self.regions: list[_Region] = []
        self.dispatch: dict[str, set] = {}
        self.var_types: dict[str, set] = {}
        self.reads: list[tuple] = []       # (var, field, node, guarded)
        self.frame_dicts: list[tuple] = [] # (type, fields, expands, node)
        self.calls: list[ast.Call] = []
        self.gate_lines: list[int] = []
        self.ret_schema: Optional[dict] = None
        # propagated state
        self.param_types: dict[str, set] = {}
        self.gate_inherited = False
        self._under_if: set = set()
        self._scan()

    # -- scan ----------------------------------------------------------

    def _scan(self) -> None:
        self._mark_conditional(self.fn, False)
        self._scan_kind_vars()
        self._scan_dict_literals()
        self._scan_regions()
        self._scan_calls_and_gates()
        self._scan_response_vars()
        self._scan_reads()
        self.ret_schema = self._return_schema()

    def _mark_conditional(self, node, under: bool) -> None:
        """ids of nodes nested under an If/IfExp within this
        function (nested defs excluded like _walk_own)."""
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            sub_under = under or isinstance(node, (ast.If, ast.IfExp))
            if sub_under:
                self._under_if.add(id(sub))
            self._mark_conditional(sub, sub_under)

    def _scan_kind_vars(self) -> None:
        for node in _walk_own(self.fn):
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                hit = _get_call_field(node.value, None) or \
                    _subscript_field(node.value, None)
                if hit is not None and hit[1] == "type":
                    self.kind_of[node.targets[0].id] = hit[0]

    def _scan_dict_literals(self) -> None:
        """Frame-typed dict displays + the vars they're assigned to
        (augmentation targets), and the generic literal-var map used
        by the return-schema extractor."""
        assigned: dict[int, str] = {}
        for node in _walk_own(self.fn):
            target = None
            if isinstance(node, ast.Assign) and node.targets and \
                    isinstance(node.targets[0], ast.Name):
                target = node.targets[0].id
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                target = node.target.id
            if target is not None and isinstance(
                    getattr(node, "value", None), ast.Dict):
                assigned[id(node.value)] = target
        self._literal_vars: dict[str, tuple] = {}
        for node in _walk_own(self.fn):
            if not isinstance(node, ast.Dict):
                continue
            fields: list[tuple] = []
            expands: list[ast.Call] = []
            ftype = None
            cond = id(node) in self._under_if
            for key, value in zip(node.keys, node.values):
                if key is None:
                    if isinstance(value, ast.Call):
                        expands.append(value)
                    continue
                name = _const_str(key)
                if name is None:
                    continue
                if name == "type":
                    ftype = _const_str(value)
                guarded = cond or (
                    isinstance(value, ast.Constant)
                    and value.value is not None
                )
                fields.append((name, value.lineno, value.col_offset,
                               guarded))
            var = assigned.get(id(node))
            if var is not None:
                self._literal_vars[var] = (list(fields), node)
                if ftype is not None:
                    self.dict_types[var] = ftype
            if ftype is not None:
                self.frame_dicts.append((ftype, fields, expands, node))
        # subscript augmentations on literal-held vars:
        #   out["k"] = v        and        d["a"], d["b"] = pair
        for node in _walk_own(self.fn):
            if not isinstance(node, ast.Assign):
                continue
            targets = []
            for tgt in node.targets:
                if isinstance(tgt, ast.Tuple):
                    targets.extend(tgt.elts)
                else:
                    targets.append(tgt)
            for tgt in targets:
                if not (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)):
                    continue
                field = _const_str(tgt.slice)
                var = tgt.value.id
                if field is None or var not in self._literal_vars:
                    continue
                guarded = id(node) in self._under_if or (
                    isinstance(node.value, ast.Constant)
                    and node.value.value is not None
                    and len(targets) == 1
                )
                entry = (field, tgt.lineno, tgt.col_offset, guarded)
                self._literal_vars[var][0].append(entry)
                ftype = self.dict_types.get(var)
                if ftype is not None:
                    for i, (t, fs, ex, dn) in enumerate(
                            self.frame_dicts):
                        if dn is self._literal_vars[var][1]:
                            fs.append(entry)
                            break

    def _scan_regions(self) -> None:
        """Typed regions from type compares and presence-guard
        regions from ``.get`` tests, including the negative-compare
        (``!= "X"`` + early return) and ``.get(...) is None`` + early
        return shapes used by the dump clients."""
        for node in _walk_own(self.fn):
            for attr in ("body", "orelse", "finalbody"):
                stmts = getattr(node, attr, None)
                if not isinstance(stmts, list) or not stmts:
                    continue
                for i, stmt in enumerate(stmts):
                    if not isinstance(stmt, ast.If):
                        continue
                    self._regions_from_if(stmt, stmts[i + 1:])

    def _regions_from_if(self, stmt: ast.If, siblings: list) -> None:
        for comp in ast.walk(stmt.test):
            if isinstance(comp, ast.Compare) and len(comp.ops) == 1:
                left, op, right = comp.left, comp.ops[0], \
                    comp.comparators[0]
                var = _type_expr_var(left, self.kind_of)
                names = _type_names(right)
                if var is None or names is None:
                    var = _type_expr_var(right, self.kind_of)
                    names = _type_names(left)
                if var is None or names is None:
                    continue
                self.dispatch.setdefault(var, set()).update(names)
                if isinstance(op, (ast.Eq, ast.In)):
                    self._add_region(var, names, None, stmt.body)
                elif isinstance(op, (ast.NotEq, ast.NotIn)):
                    if stmt.orelse:
                        self._add_region(var, names, None, stmt.orelse)
                    if _is_terminal(stmt.body):
                        self._add_region(var, names, None, siblings)
        # presence guards: the If test touches v.get("f")
        for sub in ast.walk(stmt.test):
            hit = _get_call_field(sub, None)
            if hit is None or hit[1] == "type":
                continue
            var, field = hit
            self._add_region(var, (), field, stmt.body)
            if _is_terminal(stmt.body):
                self._add_region(var, (), field, siblings)

    def _add_region(self, var, types, field, stmts) -> None:
        ids = _ids_of(stmts)
        if ids:
            self.regions.append(_Region(var, tuple(types), field, ids))

    def _scan_calls_and_gates(self) -> None:
        for node in _walk_own(self.fn):
            if not isinstance(node, ast.Call):
                continue
            self.calls.append(node)
            func = node.func
            leaf = func.id if isinstance(func, ast.Name) else (
                func.attr if isinstance(func, ast.Attribute) else None)
            if leaf == GATE_FN:
                self.gate_lines.append(node.lineno)

    def _scan_response_vars(self) -> None:
        """``frame = self._request(data)`` types ``frame`` as the
        request dict's response frame type."""
        for node in _walk_own(self.fn):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            func = node.value.func
            leaf = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None)
            if leaf not in REQUEST_HELPERS or not node.value.args:
                continue
            arg = node.value.args[0]
            rtype = None
            if isinstance(arg, ast.Name):
                rtype = self.dict_types.get(arg.id)
            elif isinstance(arg, ast.Dict):
                for k, v in zip(arg.keys, arg.values):
                    if _const_str(k) == "type":
                        rtype = _const_str(v)
            if rtype in RESPONSE_OF:
                self.var_types.setdefault(
                    node.targets[0].id, set()).add(RESPONSE_OF[rtype])

    def _scan_reads(self) -> None:
        for node in _walk_own(self.fn):
            hit = _subscript_field(node, None)
            if hit is not None:
                self.reads.append((hit[0], hit[1], node, False))
                continue
            hit = _get_call_field(node, None)
            if hit is not None:
                self.reads.append((hit[0], hit[1], node, True))

    def _return_schema(self) -> Optional[dict]:
        """field -> (guarded, line, col) for a function returning a
        dict literal (directly or via an augmented local)."""
        schema: dict = {}
        found = False
        for node in _walk_own(self.fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            fields = None
            if isinstance(node.value, ast.Dict):
                fields = []
                cond = id(node.value) in self._under_if
                for key, value in zip(node.value.keys,
                                      node.value.values):
                    name = _const_str(key)
                    if name is None:
                        continue
                    guarded = cond or (
                        isinstance(value, ast.Constant)
                        and value.value is not None
                    )
                    fields.append((name, value.lineno,
                                   value.col_offset, guarded))
            elif isinstance(node.value, ast.Name) and \
                    node.value.id in self._literal_vars:
                fields = self._literal_vars[node.value.id][0]
            if fields is None:
                continue
            found = True
            for name, line, col, guarded in fields:
                prev = schema.get(name)
                if prev is None:
                    schema[name] = (guarded, line, col)
                else:
                    schema[name] = (prev[0] and guarded, prev[1],
                                    prev[2])
        return schema if found else None

    # -- attribution ---------------------------------------------------

    def types_at(self, var: str, node) -> tuple[tuple, bool]:
        """(frame types attributed to ``var`` at ``node``,
        known-frame-var?). Innermost typed region wins; otherwise the
        function-wide var/param typing; otherwise the function's
        dispatch set for that var (reads hoisted above the frame
        switch, like ``doc = frame.get("document_id")``)."""
        best = None
        for region in self.regions:
            if region.field is not None or region.var != var:
                continue
            if id(node) in region.ids and (
                    best is None or region.size < best.size):
                best = region
        if best is not None:
            return best.types, True
        merged: set = set()
        merged.update(self.var_types.get(var, ()))
        merged.update(self.param_types.get(var, ()))
        if merged:
            return tuple(sorted(merged)), True
        disp = self.dispatch.get(var)
        if disp:
            return tuple(sorted(disp)), True
        return (), False

    def presence_guarded(self, var: str, field: str, node) -> bool:
        for region in self.regions:
            if region.var == var and region.field == field and \
                    id(node) in region.ids:
                return True
        return False

    def gate_covered(self, line: int) -> bool:
        return self.gate_inherited or any(
            g <= line for g in self.gate_lines)


# ---------------------------------------------------------------------------
# whole-scope extraction


class Extraction:
    """Merged emit/read tables over the wire modules."""

    def __init__(self) -> None:
        # (frame_type, field) -> [_Site]
        self.emits: dict[tuple, list] = {}
        self.reads: dict[tuple, list] = {}
        # frame types emitted with no registry entry: type -> [_Site]
        self.unknown_types: dict[str, list] = {}

    def add_emit(self, ftype: str, field: str, site: _Site) -> None:
        self.emits.setdefault((ftype, field), []).append(site)

    def add_read(self, ftype: str, field: str, site: _Site) -> None:
        self.reads.setdefault((ftype, field), []).append(site)

    def emitted_fields(self) -> dict:
        """frame type -> {field} actually extracted as emitted —
        what wiresan's differential pins runtime traffic against."""
        out: dict = {}
        for (ftype, field) in self.emits:
            out.setdefault(ftype, set()).add(field)
        return out


def _wire_files(files: list[SourceFile]) -> list[SourceFile]:
    return [
        src for src in files
        if src.tree is not None and any(
            src.relpath.endswith(sfx) for sfx in WIRE_MODULES)
    ]


def _class_hierarchy(files: list[SourceFile]) -> dict:
    """class name -> set of descendant class names (transitive, by
    leaf name) across the wire modules — ``self._on_connected(frame)``
    in the base driver must propagate to the multiplexed override."""
    bases: dict[str, set] = {}
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for b in node.bases:
                leaf = b.id if isinstance(b, ast.Name) else (
                    b.attr if isinstance(b, ast.Attribute) else None)
                if leaf is not None:
                    bases.setdefault(leaf, set()).add(node.name)
    desc: dict[str, set] = {}

    def collect(name: str, seen: set) -> set:
        out: set = set()
        for child in bases.get(name, ()):
            if child in seen:
                continue
            seen.add(child)
            out.add(child)
            out |= collect(child, seen)
        return out

    for name in bases:
        desc[name] = collect(name, {name})
    return desc


def extract(files: list[SourceFile],
            graph: Optional[CallGraph] = None
            ) -> tuple[Extraction, dict]:
    """Run the full emit/read extraction; returns (tables, facts by
    (relpath, qualname)). Shared with wiresan's differential, which
    compares runtime-observed fields against ``emitted_fields()``."""
    graph = graph or build_callgraph(files)
    wire = _wire_files(files)
    hierarchy = _class_hierarchy(wire)

    facts: dict[tuple, _FnFacts] = {}
    by_class: dict[tuple, list] = {}    # (class, leaf) -> [facts]
    for src in wire:
        for qual, fn in _functions(src.tree):
            info = graph.info_for_node(fn)
            class_name = getattr(info, "class_name", None)
            f = _FnFacts(src, qual, fn, info, class_name)
            facts[(src.relpath, qual)] = f
            if class_name is not None:
                leaf = qual.rsplit(".", 1)[-1]
                by_class.setdefault((class_name, leaf), []).append(f)

    # -- gate-providing fixpoint: a call to a function that calls
    # wire_version_lt (transitively) is itself a gate site
    gate_keys = {
        k for k, f in facts.items() if f.gate_lines
    }
    changed = True
    while changed:
        changed = False
        for key, f in facts.items():
            for call in f.calls:
                if f.info is None:
                    continue
                for target in graph.resolve_call(call, f.info, f.src):
                    if tuple(target.key) in gate_keys and \
                            call.lineno not in f.gate_lines:
                        f.gate_lines.append(call.lineno)
                        if key not in gate_keys:
                            gate_keys.add(key)
                        changed = True

    # -- frame-type propagation through calls (+ gate inheritance)
    def callee_facts(call: ast.Call, f: _FnFacts) -> list:
        out = []
        if f.info is not None:
            for target in graph.resolve_call(call, f.info, f.src):
                t = facts.get(tuple(target.key))
                if t is not None:
                    out.append(t)
                # subclass overrides: the callgraph resolves
                # self-methods UP the base chain only
                cls = getattr(target, "class_name", None)
                leaf = target.qualname.rsplit(".", 1)[-1]
                if cls is not None:
                    for sub in hierarchy.get(cls, ()):
                        out.extend(by_class.get((sub, leaf), ()))
        return out

    changed = True
    while changed:
        changed = False
        for f in facts.values():
            for call in f.calls:
                arg_types = []
                for pos, arg in enumerate(call.args):
                    if not isinstance(arg, ast.Name):
                        continue
                    types, known = f.types_at(arg.id, arg)
                    if known and types:
                        arg_types.append((pos, set(types)))
                covered = f.gate_covered(call.lineno)
                if not arg_types and not covered:
                    continue
                for target in callee_facts(call, f):
                    if covered and not target.gate_inherited:
                        target.gate_inherited = True
                        changed = True
                    for pos, types in arg_types:
                        if pos >= len(target.params):
                            continue
                        slot = target.param_types.setdefault(
                            target.params[pos], set())
                        if not types <= slot:
                            slot |= types
                            changed = True

    # -- final tables
    ext = Extraction()
    for f in facts.values():
        codec = None
        for (sfx, qual), spec in PAYLOAD_CODECS.items():
            if f.relpath.endswith(sfx) and f.qual == qual:
                codec = spec
        site = lambda line, col, guarded, gated=False: _Site(  # noqa: E731
            f.relpath, f.module, f.qual, line, col, guarded, gated)

        if codec is not None and codec[0] == "emit":
            if f.ret_schema:
                for field, (guarded, line, col) in f.ret_schema.items():
                    ext.add_emit(codec[1], field,
                                 site(line, col, guarded))
        if codec is not None and codec[0] == "read":
            pvar = f.params[0] if f.params else None
            for var, field, node, guarded in f.reads:
                if var != pvar:
                    continue
                g = guarded or f.presence_guarded(var, field, node)
                ext.add_read(codec[1], field, site(
                    node.lineno, node.col_offset, g,
                    f.gate_covered(node.lineno)))
            continue

        for ftype, fields, expands, dnode in f.frame_dicts:
            for field, line, col, guarded in fields:
                if field == "type":
                    continue
                ext.add_emit(ftype, field, site(line, col, guarded))
            for call in expands:
                for target in callee_facts(call, f):
                    if not target.ret_schema:
                        continue
                    for field, (guarded, line, col) in \
                            target.ret_schema.items():
                        if field == "type":
                            continue
                        ext.add_emit(ftype, field, _Site(
                            target.relpath, target.module,
                            target.qual, line, col, guarded))
            ext.unknown_types.setdefault(ftype, []).append(
                site(dnode.lineno, dnode.col_offset, True))

        for var, field, node, guarded in f.reads:
            if field == "type":
                continue
            if var in f.dict_types or var in f._literal_vars:
                continue    # reading back a dict this code just built
            types, known = f.types_at(var, node)
            if not known:
                continue
            g = guarded or f.presence_guarded(var, field, node)
            gated = f.gate_covered(node.lineno)
            for ftype in types:
                ext.add_read(ftype, field, site(
                    node.lineno, node.col_offset, g, gated))
    return ext, facts


# ---------------------------------------------------------------------------
# rules


def _sorted_sites(sites: list) -> list:
    return sorted(sites, key=lambda s: (s.relpath, s.line, s.col))


def _emit_findings(rule: str, hits: list, message_of) -> list:
    """hits: (leaf, _Site) — sorted per file, keyed per file."""
    findings: list[Finding] = []
    keys_by_file: dict[str, _OrdinalKeys] = {}
    for leaf, s in sorted(
            hits, key=lambda h: (h[1].relpath, h[1].line, h[1].col,
                                 h[0])):
        keys = keys_by_file.setdefault(s.relpath, _OrdinalKeys())
        findings.append(Finding(
            rule=rule, path=s.relpath, line=s.line,
            message=message_of(leaf, s),
            key=keys.key(s.module, s.qual, leaf),
        ))
    return findings


def _check_rules(ext: Extraction, registry: dict) -> list[Finding]:
    findings: list[Finding] = []

    def spec_of(ftype, field):
        fields = registry.get(ftype)
        if fields is None or field not in fields:
            return None
        return parse_spec(fields[field])

    # rule: unversioned-frame-field
    hits = []
    for (ftype, field), sites in ext.emits.items():
        if ftype in registry and field not in registry[ftype]:
            for s in _sorted_sites(sites):
                hits.append((f"{ftype}.{field}", s))
    for ftype, sites in ext.unknown_types.items():
        if ftype not in registry:
            for s in _sorted_sites(sites):
                hits.append((ftype, s))
    findings += _emit_findings(
        "unversioned-frame-field", hits,
        lambda leaf, s: (
            f"emits wire field {leaf!r} that is absent from the "
            "reviewed WIRE_SCHEMA registry "
            "(protocol/constants.py): schema growth is a reviewed "
            "registry diff — add the field with its since-version "
            "(and '?' if its presence is optional), regenerate "
            "protocol/WIRE_SCHEMA.json, and cover it in "
            "test_wire_compat's generative matrix"
        ))

    # rule: optional-field-unconditional-emit
    hits = []
    for (ftype, field), sites in ext.emits.items():
        spec = spec_of(ftype, field)
        if spec is None or not spec[1]:
            continue
        for s in _sorted_sites(sites):
            if not s.guarded:
                hits.append((f"{ftype}.{field}", s))
    findings += _emit_findings(
        "optional-field-unconditional-emit", hits,
        lambda leaf, s: (
            f"optional-presence wire field {leaf!r} is emitted "
            "unconditionally: the registry marks it '?', meaning a "
            "frame must omit the key when there is nothing to say — "
            "an unconditional emit puts maybe-None keys on the wire, "
            "breaking byte-stability with pre-"
            "existing recorded corpora and older peers "
            "(test_wire_compat). Emit under an ``is not None`` / "
            "non-empty guard, the nack_to_json qos-attribution idiom"
        ))

    # rule: encoder-decoder-drift (both directions)
    hits = []
    for (ftype, field), sites in ext.emits.items():
        spec = spec_of(ftype, field)
        if spec is None or spec[2]:
            continue            # unknown = rule 4; '~' = tolerated
        if (ftype, field) in ext.reads:
            continue
        s = _sorted_sites(sites)[0]
        hits.append((f"{ftype}.{field}", s))
    emit_hits = list(hits)
    findings += _emit_findings(
        "encoder-decoder-drift", emit_hits,
        lambda leaf, s: (
            f"wire field {leaf!r} is emitted but no decoder in the "
            "wire modules ever consumes it: either dead freight on "
            "every frame (delete the emit) or a reader the analyzer "
            "cannot see — mark the field '~' (tolerated) in "
            "WIRE_SCHEMA with a comment naming the out-of-scope "
            "consumer"
        ))
    hits = []
    for (ftype, field), sites in ext.reads.items():
        spec = spec_of(ftype, field)
        if spec is not None and spec[2]:
            continue
        if (ftype, field) in ext.emits:
            continue
        for s in _sorted_sites(sites):
            if not s.guarded:
                hits.append((f"{ftype}.{field}", s))
    read_hits = list(hits)
    findings += _emit_findings(
        "encoder-decoder-drift", read_hits,
        lambda leaf, s: (
            f"decoder requires wire field {leaf!r} (bare subscript) "
            "but no encoder in the wire modules ever emits it: a "
            "well-formed peer frame KeyErrors this endpoint — read "
            "it with .get(), or mark the field '~' in WIRE_SCHEMA "
            "with a comment naming the out-of-scope emitter"
        ))

    # rule: ungated-wire-read
    drifted = {(leaf, s.relpath, s.line, s.col)
               for leaf, s in read_hits}
    hits = []
    for (ftype, field), sites in ext.reads.items():
        spec = spec_of(ftype, field)
        if spec is None:
            continue
        since, optional, _tolerated = spec
        if not optional and _ver(since) <= (1, 0):
            continue
        for s in _sorted_sites(sites):
            if s.guarded or s.gated:
                continue
            if (f"{ftype}.{field}", s.relpath, s.line, s.col) \
                    in drifted:
                continue
            hits.append((f"{ftype}.{field}", s))
    findings += _emit_findings(
        "ungated-wire-read", hits,
        lambda leaf, s: (
            f"bare subscript read of post-1.0 wire field {leaf!r}: "
            "a 1.0 peer's frame legitimately omits it, so this "
            "KeyErrors on exactly the cross-version pairing the "
            "compat matrix guarantees — use .get() with a default, "
            "check presence first, or put the read behind the "
            "connection's wire_version_lt gate "
            "(protocol/constants.py)"
        ))
    return findings


def stale_schema_entries(files: list[SourceFile],
                         graph: Optional[CallGraph] = None
                         ) -> list[tuple[str, str]]:
    """Registry entries (frame type, field) that the extractor finds
    NEITHER emitted NOR read anywhere in the wire modules — the
    WALL_CLOCK_SINKS non-vacuity discipline: the registry only
    describes live wire traffic (tolerated ``~`` entries are exempt;
    they exist precisely for out-of-scope traffic)."""
    registry = load_registry(files)
    if registry is None:
        return []
    ext, _facts = extract(files, graph)
    stale = []
    for ftype in sorted(registry):
        for field in sorted(registry[ftype]):
            if parse_spec(registry[ftype][field])[2]:
                continue
            if (ftype, field) not in ext.emits and \
                    (ftype, field) not in ext.reads:
                stale.append((ftype, field))
    return stale


# ---------------------------------------------------------------------------
# entry point


def check(files: list[SourceFile],
          graph: Optional[CallGraph] = None) -> list[Finding]:
    registry = load_registry(files)
    if registry is None:
        # no registry in scope, no contract to check (the live gate
        # always scans protocol/constants.py; fixture trees carry
        # their own mini registry)
        return []
    ext, _facts = extract(files, graph)
    return _check_rules(ext, registry)
