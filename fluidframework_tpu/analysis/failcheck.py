"""failcheck: exception-flow analysis for the serving planes.

Every worst bug this repo has shipped was a *silent* error path: the
PR2 dispatch thread that died quietly and blackholed acks, the PR14
resubmits swallowed by stale-csn dedupe, the silent pool-route
fallback PR8 had to make loud. The sequenced order per document is
single-sourced ("On Coordinating Collaborative Objects", arXiv
1007.5093), so an op or ack that vanishes without a signal forks
client state three hops downstream where it's unattributable. This
family statically proves the property every one of those fixes
retrofitted by hand: **error handlers in the serving paths are loud**.

Four rules:

- ``swallowed-exception`` — an ``except`` handler in a
  drivers/service/qos/runtime/loader path component whose body
  neither re-raises, returns/emits an error value (nack/error frame),
  increments a metric, flight-records, nor writes stderr. The
  reviewed per-handler ``SILENT_HANDLERS`` registry (the
  WALL_CLOCK_SINKS discipline: justified entries, gate-checked for
  staleness) is the escape hatch — NOT the allowlist.
- ``broad-except-in-dispatch-loop`` — a bare/``except Exception``
  inside a function the DISPATCH_LOOPS registry names, without loud
  teardown: the exact shape of the PR2 quietly-dead dispatch thread.
- ``exception-context-dropped`` — ``raise New(...)`` without
  ``from e`` inside an except in serving paths: severs the causal
  chain flight-recorder dumps and nack attribution rely on
  (``from None`` is an explicit, reviewed severing and passes).
- ``return-in-finally`` — ``return``/``break``/``continue`` in a
  ``finally`` block swallows the in-flight exception entirely
  (language semantics — the loudest handler upstream never runs).

Loudness resolves over the shared callgraph: a handler delegating to
a repo helper that itself re-raises or emits a signal (metric inc,
stderr write, flight record, nack/error-named call) is loud. Known FN
shape: a handler calling a recovery helper that only raises on
*failed* recovery counts as loud even when successful recovery emits
nothing — the runtime half (testing/failsan.py: fault-to-signal
accounting over the fluidchaos plane) is the backstop that catches
the actually-silent outcome.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .callgraph import CallGraph, build_callgraph
from .core import Finding, SourceFile, import_aliases
from .determinism import _OrdinalKeys, _scope_map
from .jaxhazards import DISPATCH_LOOPS

# Path components where the handler rules apply: the serving planes.
# obs/ and utils/ are telemetry (their handlers ARE the signal
# emitters); tests/ and examples/ are out of scope.
FAIL_SCOPE_COMPONENTS = ("drivers", "service", "qos", "runtime",
                         "loader")

# Reviewed silent handlers: (relpath suffix, handler key) ->
# justification. A handler key is ``<qualname>:except-<Type>`` with
# the same ordinal suffixing as the finding keys. This is a REGISTRY,
# not an allowlist: every entry is a reviewed design decision, the
# gate test fails if an entry goes stale (no statically-silent handler
# left at the site), and a new silent handler anywhere else still
# fails the gate.
SILENT_HANDLERS: dict[tuple[str, str], str] = {
    # --- EOF / peer-hangup absorbs: the disconnect itself is the
    # signal, accounted by the reconnect/teardown machinery upstream
    ("drivers/socket_driver.py",
     "SocketDocumentService._recv_exact:except-OSError"):
        "socket died mid-read: returns None, the EOF sentinel the "
        "dispatch loop maps to reconnect-or-teardown (both loud "
        "paths — dispatch-fault metric + flight dump live there)",
    ("drivers/socket_driver.py",
     "SocketDocumentService._recv_header:except-OSError+ValueError"):
        "select()/header read on a socket torn down concurrently: "
        "same None EOF sentinel as _recv_exact, same loud upstream",
    ("service/ingress.py",
     "read_frame_sized:except-IncompleteReadError"
     "+ConnectionResetError"):
        "client hung up mid-header: returns (None, 0), the EOF "
        "sentinel _handle maps to session teardown (connection "
        "gauges account the disconnect)",
    ("service/ingress.py",
     "read_frame_sized:except-IncompleteReadError"
     "+ConnectionResetError2"):
        "client hung up mid-payload: same (None, 0) EOF sentinel "
        "as the header read",
    ("service/ingress.py",
     "_ClientSession.writer_loop:except-ConnectionResetError"
     "+BrokenPipeError+OSError"):
        "peer hung up while we were flushing to it: the reader "
        "side observes the same EOF and tears the session down "
        "through the loud path; double-reporting here would count "
        "every disconnect twice",
    ("service/ingress.py",
     "AlfredServer._handle:except-ConnectionResetError"
     "+BrokenPipeError"):
        "client disconnect race during frame dispatch: falls "
        "through to the finally teardown that decrements the "
        "connection gauges — the disconnect IS accounted",
    ("service/moira.py",
     "MaterializedHistoryServer._handle:except-ConnectionResetError"
     "+BrokenPipeError+RuntimeError"):
        "history client hung up mid-response: per-request service, "
        "nothing sequenced is in flight; teardown closes the writer",
    ("service/broker.py",
     "BrokerServer._handle:except-ConnectionResetError"
     "+BrokenPipeError+RuntimeError"):
        "broker client hung up: the consumer lease reaper "
        "re-queues anything the dead consumer held (the loud, "
        "accounted path for lost work)",
    # --- idempotent close()/teardown: already-gone is the goal state
    ("drivers/socket_driver.py",
     "SocketDocumentService.close:except-OSError"):
        "shutdown() on an already-dead socket during close(): "
        "already-gone is the goal state of close()",
    ("drivers/socket_driver.py",
     "SocketDocumentService.close:except-OSError2"):
        "close() after failed shutdown(): same double-close race",
    ("drivers/socket_driver.py",
     "SocketDeltaConnection.disconnect:except-OSError"):
        "disconnect frame to a server that is already gone: the "
        "goal state (no connection) already holds",
    ("drivers/caching_driver.py",
     "_DocumentFacade.close:except-OSError"):
        "best-effort disconnect_document on facade close: the "
        "snapshot was already persisted before this; a dead inner "
        "driver at close() loses nothing cached",
    ("service/ingress.py", "_ClientSession.close:except-QueueFull"):
        "displacing one outbound frame to enqueue the goodbye on a "
        "full queue: the session is closing, undelivered frames "
        "are the documented cost, and out_dropped counts the "
        "displacement on the non-closing path",
    ("service/ingress.py",
     "_ClientSession.close:except-OSError+RuntimeError"):
        "writer.close() on a transport torn down concurrently: "
        "idempotent teardown",
    ("service/broker.py", "BrokerServer.stop:except-Exception"):
        "writer close during server-wide stop fan-in: shutdown "
        "teardown, every queue is being dropped deliberately",
    ("service/broker.py",
     "RemoteOrderingQueue._close_sock:except-OSError"):
        "closing a socket that is already dead: _close_sock exists "
        "to make teardown idempotent for the reconnect path, which "
        "counts its own retries",
    # --- operator interrupt at a CLI entry point
    ("service/broker.py", "run_broker:except-KeyboardInterrupt"):
        "operator ^C on the blocking CLI entry point: exits the "
        "serve loop into the shutdown sequence; stderr noise here "
        "would garble the operator's own terminal",
    ("service/ingress.py", "run_server:except-KeyboardInterrupt"):
        "operator ^C on the blocking CLI entry point (same shape "
        "as run_broker)",
    ("service/moira.py", "run_mh_server:except-KeyboardInterrupt"):
        "operator ^C on the blocking CLI entry point (same shape "
        "as run_broker)",
    # --- absorbs whose accounting lives in the callee/report by design
    ("service/local_orderer.py",
     "LocalOrderer.disconnect:except-FencedWriteError"):
        "deposed-primary teardown: the fence refusal was already "
        "counted by the fence check that raised; the deposed node "
        "is shutting down and must not double-report",
    ("service/local_orderer.py",
     "LocalOrderer.disconnect:except-<dynamic>"):
        "owed-leave absorb under quorum loss: the leave is parked "
        "in _owed_leaves and settled (sequenced first) at the "
        "client's next join — the op is deferred, not lost",
    ("service/local_orderer.py",
     "LocalOrderer.disconnect:except-<dynamic>2"):
        "owed-leave absorb, replicated-path twin of the above",
    ("service/local_orderer.py",
     "LocalOrderer._write_checkpoint_guarded:except-BreakerOpenError"):
        "checkpoint skipped while the storage breaker is open: the "
        "breaker counts every refusal itself; the op log still "
        "holds every op (degraded durability, not loss)",
    ("service/partitioning.py",
     "ReplicatedFileOrderingQueue.scrub.fetch:except-ValueError"):
        "scrub falling back to the next peer on a torn remote "
        "read: the scrub report carries the per-peer corruption "
        "accounting for the sweep",
    ("service/replication.py",
     "ReplicatedSequencerGroup.scrub.fetch:except-CorruptRecordError"):
        "scrub falling back to the next peer on a corrupt record: "
        "the scrub report carries the accounting (and the storage "
        "layer already bumped the torn/scrub metrics)",
    # --- crash-debris cleanup where ENOENT is the common case
    ("service/partitioning.py",
     "FileOrderingQueue.__init__:except-OSError"):
        "os.remove of a stale .tmp from a crashed predecessor: "
        "ENOENT (no debris) is the normal case; the recovery "
        "itself is what this cleanup enables",
    ("service/storage.py", "DocumentStorage.__init__:except-OSError"):
        "same stale-.tmp crash-debris cleanup as "
        "FileOrderingQueue.__init__",
    # --- in-proc fast path: non-wire-encodable envelopes skip the
    # wire transforms BY CONTRACT (they never cross a socket)
    ("runtime/op_lifecycle.py",
     "OpCompressor.maybe_compress:except-TypeError"):
        "a non-JSON-serializable envelope is in-proc-only traffic: "
        "compression is a wire optimization, skipping it for an "
        "object that never crosses the wire loses nothing",
    ("runtime/op_lifecycle.py", "OpSplitter.split:except-TypeError"):
        "same in-proc envelope contract as maybe_compress: size "
        "cannot be measured, so the op rides unsplit",
    ("runtime/op_lifecycle.py", "stage_outbound:except-TypeError"):
        "same in-proc envelope contract at the staging seam",
}


def _in_fail_scope(relpath: str) -> bool:
    parts = relpath.split("/")
    return any(p in FAIL_SCOPE_COMPONENTS for p in parts[:-1])


def silent_handler_registered(relpath: str, handler_key: str) -> bool:
    for (suffix, key), _just in SILENT_HANDLERS.items():
        if relpath.endswith(suffix) and key == handler_key:
            return True
    return False


# ---------------------------------------------------------------------------
# handler enumeration (shared with testing/failsan.py: the runtime
# half maps caught-exception line events back onto these same sites,
# so the two halves cannot drift on what a "handler site" is)


@dataclasses.dataclass
class HandlerSite:
    """One ``except`` clause, with the line-free key both halves use."""

    node: ast.ExceptHandler
    qual: str                   # enclosing scope ("<module>" at top)
    type_display: str           # "bare", "OSError", "A+B"
    handler_key: str            # "<qual>:except-<Type>[ordinal]"
    key: str                    # "<module leaf>:<handler_key>"
    lineno: int                 # the except clause's line
    body_start: int
    body_end: int
    broad: bool                 # bare / Exception / BaseException


def _type_display(type_node: Optional[ast.expr]) -> str:
    def leaf(node) -> str:
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id
        return "<dynamic>"

    if type_node is None:
        return "bare"
    if isinstance(type_node, ast.Tuple):
        return "+".join(leaf(e) for e in type_node.elts)
    return leaf(type_node)


_BROAD_NAMES = frozenset(("Exception", "BaseException"))


def _is_broad(type_node: Optional[ast.expr]) -> bool:
    if type_node is None:
        return True
    names = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    for n in names:
        if isinstance(n, ast.Attribute):
            n_id = n.attr
        elif isinstance(n, ast.Name):
            n_id = n.id
        else:
            continue
        if n_id in _BROAD_NAMES:
            return True
    return False


def module_handlers(tree: ast.AST, relpath: str) -> list[HandlerSite]:
    """Every except clause in one module, in source order, with the
    stable ordinal keys (two same-typed handlers in one scope get
    distinct keys that survive line insertions — the _OrdinalKeys
    contract every family shares)."""
    scope = _scope_map(tree)
    module = relpath.rsplit("/", 1)[-1]
    handlers = [
        n for n in ast.walk(tree) if isinstance(n, ast.ExceptHandler)
    ]
    handlers.sort(key=lambda n: (n.lineno, n.col_offset))
    keys = _OrdinalKeys()
    out: list[HandlerSite] = []
    for node in handlers:
        qual = scope.get(id(node), "<module>")
        disp = _type_display(node.type)
        full = keys.key(module, qual, f"except-{disp}")
        handler_key = full.split(":", 1)[1]
        body_end = max(
            getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
            for stmt in node.body
        )
        out.append(HandlerSite(
            node=node, qual=qual, type_display=disp,
            handler_key=handler_key, key=full, lineno=node.lineno,
            body_start=node.body[0].lineno, body_end=body_end,
            broad=_is_broad(node.type),
        ))
    return out


# ---------------------------------------------------------------------------
# the loudness predicate


# call leaves that emit an observable signal by construction: metric
# bumps, histogram observes, flight-recorder records/dumps, logging's
# error lanes, traceback printers
_LOUD_LEAVES = frozenset((
    "inc", "observe", "dump", "dump_to", "record", "exception",
    "warning", "warn", "critical", "log", "print_exc",
    "print_exception",
))

# a name containing one of these is an error-signal emitter/value by
# naming convention (send_nack, _emit_error, mark_failed, reject_op,
# report.corrupt, torn_tail)
_ERRORISH = ("nack", "error", "fail", "reject", "alert", "corrupt",
             "torn")


def _errorish(name: str) -> bool:
    low = name.lower()
    return any(s in low for s in _ERRORISH)


def _call_leaf(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _dotted(node, aliases: dict) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def _writes_stderr(call: ast.Call, aliases: dict) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in ("write",
                                                   "writelines"):
        target = _dotted(f.value, aliases)
        if target is not None and target.endswith("stderr"):
            return True
    if isinstance(f, ast.Name) and f.id == "print":
        for kw in call.keywords:
            if kw.arg == "file":
                target = _dotted(kw.value, aliases)
                if target is not None and target.endswith("stderr"):
                    return True
    return False


def _errorish_expr(expr: ast.expr) -> bool:
    """Does a returned value *name* an error? (``return nack``,
    ``return self._make_error(...)`` — the emitted-error-value arm of
    the loudness predicate; ``return default`` is the PR8 silent
    fallback and does NOT count.)"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and _errorish(node.id):
            return True
        if isinstance(node, ast.Attribute) and _errorish(node.attr):
            return True
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and _errorish(node.value):
            return True
    return False


def _walk_own_stmts(stmts):
    """ast.walk over a statement list EXCLUDING nested def subtrees
    (a nested def's raise runs when the closure runs, not when the
    handler does)."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _node_loud(node: ast.AST, aliases: dict) -> bool:
    """One statement/expression's intrinsic loudness (no callgraph)."""
    if isinstance(node, ast.Raise):
        return True
    if isinstance(node, ast.Call):
        leaf = _call_leaf(node)
        if leaf is not None and (leaf in _LOUD_LEAVES
                                 or _errorish(leaf)):
            return True
        if _writes_stderr(node, aliases):
            return True
        # an errorish name ANYWHERE in the call — the receiver chain
        # (``report.corrupt.append(i)``) or an argument
        # (``session.send({"type": "connect_document_error"})``): the
        # handler is emitting/recording an error value
        if _errorish_expr(node):
            return True
    if isinstance(node, ast.Return) and node.value is not None and \
            not (isinstance(node.value, ast.Constant)
                 and node.value.value is None) and \
            _errorish_expr(node.value):
        return True
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and _errorish(t.id):
                return True
            if isinstance(t, ast.Attribute) and _errorish(t.attr):
                return True
        # building an error value counts too: ``resp = {"type":
        # "error", ...}`` IS the emitted error frame
        value = getattr(node, "value", None)
        if value is not None and _errorish_expr(value):
            return True
    return False


class _Loudness:
    """Callgraph-propagated loudness, memoized per function node: a
    handler delegating to ``self._note_fault(e)`` is loud when the
    helper (transitively) re-raises or emits a signal."""

    def __init__(self, files: list, graph: CallGraph):
        self.graph = graph
        self._aliases: dict[str, dict] = {}
        self._by_rel = {f.relpath: f for f in files}
        self._memo: dict[int, bool] = {}

    def aliases_for(self, relpath: str) -> dict:
        cached = self._aliases.get(relpath)
        if cached is None:
            src = self._by_rel.get(relpath)
            cached = import_aliases(src.tree) \
                if src is not None and src.tree is not None else {}
            self._aliases[relpath] = cached
        return cached

    def fn_loud(self, info, _stack: Optional[set] = None) -> bool:
        cached = self._memo.get(id(info.node))
        if cached is not None:
            return cached
        _stack = _stack if _stack is not None else set()
        if id(info.node) in _stack:
            return False        # cycle: resolves on the outer frame
        _stack.add(id(info.node))
        aliases = self.aliases_for(info.relpath)
        loud = False
        for node in _walk_own_stmts(info.node.body):
            if _node_loud(node, aliases):
                loud = True
                break
        if not loud:
            for node in _walk_own_stmts(info.node.body):
                if not isinstance(node, ast.Call):
                    continue
                for target in self.graph.resolve_call(
                        node, info, info.src):
                    if self.fn_loud(target, _stack):
                        loud = True
                        break
                if loud:
                    break
        _stack.discard(id(info.node))
        self._memo[id(info.node)] = loud
        return loud

    def handler_loud(self, site: HandlerSite, src: SourceFile,
                     enclosing_def: Optional[ast.AST]) -> bool:
        aliases = self.aliases_for(src.relpath)
        for node in _walk_own_stmts(site.node.body):
            if _node_loud(node, aliases):
                return True
        caller = self.graph.info_for_node(enclosing_def) \
            if enclosing_def is not None else None
        for node in _walk_own_stmts(site.node.body):
            if not isinstance(node, ast.Call):
                continue
            for target in self.graph.resolve_call(node, caller, src):
                if self.fn_loud(target):
                    return True
        return False


def _enclosing_defs(tree: ast.AST) -> dict[int, ast.AST]:
    """ExceptHandler id -> nearest enclosing def node (for callgraph
    caller resolution); module-level handlers are absent."""
    out: dict[int, ast.AST] = {}

    def rec(node, owner):
        for sub in ast.iter_child_nodes(node):
            nxt = sub if isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                else owner
            if isinstance(sub, ast.ExceptHandler) and owner is not None:
                out[id(sub)] = owner
            rec(sub, nxt)

    rec(tree, None)
    return out


# ---------------------------------------------------------------------------
# rules 1–3: one pass over every handler


def _dispatch_loop_fns(relpath: str) -> frozenset:
    for suffix, (loop_fns, boundary_fns) in DISPATCH_LOOPS.items():
        if relpath.endswith(suffix):
            return frozenset(loop_fns) | frozenset(boundary_fns)
    return frozenset()


def _check_handlers(files: list[SourceFile],
                    graph: CallGraph) -> list[Finding]:
    loudness = _Loudness(files, graph)
    findings: list[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        in_scope = _in_fail_scope(src.relpath)
        loop_fns = _dispatch_loop_fns(src.relpath)
        if not in_scope and not loop_fns:
            continue
        owners = _enclosing_defs(src.tree)
        keys = _OrdinalKeys()
        module = src.relpath.rsplit("/", 1)[-1]
        for site in module_handlers(src.tree, src.relpath):
            owner = owners.get(id(site.node))
            in_loop = bool(loop_fns) and \
                site.qual.rsplit(".", 1)[-1] in loop_fns
            # --- exception-context-dropped (scope: serving paths) ---
            if in_scope:
                bound = site.node.name  # "e" in "except X as e"
                for node in _walk_own_stmts(site.node.body):
                    if not isinstance(node, ast.Raise) or \
                            node.exc is None or node.cause is not None:
                        continue
                    if isinstance(node.exc, ast.Name) and \
                            node.exc.id == bound:
                        continue    # ``raise e``: same exception
                    exc_leaf = _call_leaf(node.exc) if isinstance(
                        node.exc, ast.Call) else (
                        node.exc.id if isinstance(node.exc, ast.Name)
                        else getattr(node.exc, "attr", "<dynamic>"))
                    findings.append(Finding(
                        rule="exception-context-dropped",
                        path=src.relpath, line=node.lineno,
                        message=(
                            f"raise {exc_leaf}(...) inside "
                            f"``except {site.type_display}`` without "
                            "``from e``: the causal chain flight "
                            "dumps and nack attribution walk is "
                            "severed — chain it (``from e``) or "
                            "sever explicitly (``from None``)"
                        ),
                        key=keys.key(module, site.qual,
                                     f"raise-{exc_leaf}"),
                    ))
            if not (in_scope or in_loop):
                continue
            loud = loudness.handler_loud(site, src, owner)
            if loud:
                continue
            # --- broad-except-in-dispatch-loop (wins the dedup: the
            # dispatch-loop shape is the more specific diagnosis) ---
            if in_loop and site.broad:
                findings.append(Finding(
                    rule="broad-except-in-dispatch-loop",
                    path=src.relpath, line=site.lineno,
                    message=(
                        f"``except {site.type_display}`` inside "
                        f"dispatch-loop function {site.qual}() "
                        "(DISPATCH_LOOPS registry) with no loud "
                        "teardown: a swallowed error here kills the "
                        "loop quietly and blackholes every ack "
                        "behind it (the PR2 bug) — re-raise, or "
                        "emit a metric/stderr/flight signal before "
                        "recovering"
                    ),
                    key=keys.key(module, site.qual, "broad-except"),
                ))
                continue
            # --- swallowed-exception ---
            if in_scope:
                if silent_handler_registered(src.relpath,
                                             site.handler_key):
                    continue
                findings.append(Finding(
                    rule="swallowed-exception",
                    path=src.relpath, line=site.lineno,
                    message=(
                        f"``except {site.type_display}`` in "
                        f"{site.qual}() neither re-raises, returns "
                        "an error value, increments a metric, "
                        "flight-records, nor writes stderr: a "
                        "sequenced op or ack dying here vanishes "
                        "without a signal — make the handler loud, "
                        "or register it in "
                        "failcheck.SILENT_HANDLERS with a reviewed "
                        "justification"
                    ),
                    key=site.key,
                ))
    return findings


# ---------------------------------------------------------------------------
# rule 4: return-in-finally (everywhere — language semantics, not a
# serving-plane convention: the in-flight exception is DISCARDED)


def _check_return_in_finally(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        scope = _scope_map(src.tree)
        keys = _OrdinalKeys()
        module = src.relpath.rsplit("/", 1)[-1]
        hits: list[tuple] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            hits.extend(_finally_escapes(node.finalbody))
        # source order so ordinal suffixes are line-insertion stable
        hits.sort(key=lambda pair: (pair[0].lineno,
                                    pair[0].col_offset))
        for stmt, kind in hits:
            qual = scope.get(id(stmt), "<module>")
            findings.append(Finding(
                rule="return-in-finally",
                path=src.relpath, line=stmt.lineno,
                message=(
                    f"``{kind}`` inside a ``finally`` block discards "
                    "any in-flight exception (language semantics): "
                    "the error neither propagates nor signals — move "
                    f"the ``{kind}`` out of the finally, or handle "
                    "the exception explicitly first"
                ),
                key=keys.key(module, qual, f"finally-{kind}"),
            ))
    return findings


def _finally_escapes(finalbody) -> list[tuple]:
    """(stmt, kind) for every return/break/continue that escapes the
    finally block itself: a break/continue bound to a loop INSIDE the
    finalbody is that loop's business, and nested defs are their own
    scope."""
    out: list[tuple] = []

    def rec(stmts, in_loop: bool):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.Return):
                out.append((stmt, "return"))
            elif isinstance(stmt, ast.Break) and not in_loop:
                out.append((stmt, "break"))
            elif isinstance(stmt, ast.Continue) and not in_loop:
                out.append((stmt, "continue"))
            for name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, name, None)
                if sub:
                    rec(sub, in_loop or isinstance(
                        stmt, (ast.While, ast.For, ast.AsyncFor)))
            for handler in getattr(stmt, "handlers", []) or []:
                rec(handler.body, in_loop)

    rec(finalbody, False)
    return out


# ---------------------------------------------------------------------------
# registry staleness (the WALL_CLOCK_SINKS non-vacuity contract)


def stale_silent_handlers(files: list[SourceFile],
                          registry: Optional[dict] = None
                          ) -> list[tuple[str, str]]:
    """SILENT_HANDLERS entries that no longer match a statically
    SILENT handler (the site vanished, or became loud — either way
    the justification describes nothing and must be deleted).
    Intrinsic loudness only: an entry whose handler went loud via a
    helper the callgraph resolves stays conservatively live."""
    registry = SILENT_HANDLERS if registry is None else registry
    stale = []
    for (suffix, handler_key) in registry:
        live = False
        for src in files:
            if src.tree is None or not src.relpath.endswith(suffix):
                continue
            aliases = import_aliases(src.tree)
            for site in module_handlers(src.tree, src.relpath):
                if site.handler_key != handler_key:
                    continue
                if not any(_node_loud(n, aliases) for n in
                           _walk_own_stmts(site.node.body)):
                    live = True
                break
            if live:
                break
        if not live:
            stale.append((suffix, handler_key))
    return stale


# ---------------------------------------------------------------------------
# entry point


def check(files: list[SourceFile],
          graph: Optional[CallGraph] = None) -> list[Finding]:
    graph = graph or build_callgraph(files)
    findings: list[Finding] = []
    findings += _check_handlers(files, graph)
    findings += _check_return_in_finally(files)
    return findings
