"""shapecheck — abstract shape/dtype/donation analysis over the
kernel layer.

The kernel layer (``ops/``, ``parallel/seq_shard.py``, the sidecar's
dispatch loop) runs under three conventions that until this pass were
prose only (docs/PERF.md): donation safety ("never read a donated
buffer"), the bucket ladder as the ONE shape source (an unladdered
call site is a silent recompile storm — 20-40s per shape on the real
chip), and dtype stability (a silent int32->int64 widen doubles HBM).
This family turns each into a machine-checked rule, by abstract
interpretation over the AST: dataflow for donated values, a
laddered-ness lattice for shape arguments, dtype/shape propagation
through jit-reachable kernel bodies.

The runtime cross-check is ``testing/jitsan.py`` (the PR5
static<->runtime differential pattern): jitsan counts the shapes each
jit root actually compiles and traps reads of donated buffers;
``tests/test_jitsan.py`` pins (a) observed compile counts per root <=
the ladder size this module derives (:func:`ladder_bounds`) and (b)
this module's inferred output shapes/dtypes (:func:`infer_kernel_output`)
== ``jax.eval_shape`` across every ladder rung — an
abstract-interpreter gap fails by name, never silently.

Like every fluidlint pass, this module imports NOTHING it lints (no
jax, no ops): signatures and ladder arithmetic are pure Python over
``(shape-tuple, dtype-string)`` descriptors.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .callgraph import CallGraph, build_callgraph
from .core import (
    Finding,
    SourceFile,
    dotted_path as _dotted,
    import_aliases,
)

# ---------------------------------------------------------------------------
# reviewed registries (the INDIRECT_CALLS pattern: every entry is a
# deliberate, justified exception or blessing — widen with review only)

# Path components where the unladdered-jit-shape rule applies: the
# serving kernel layer. tests/ and bench.py dispatch deliberately
# exact-fit shapes (fuzz sweeps, shape-cliff measurements) — that is
# their job, and each runs a bounded number of shapes once; the storm
# the rule exists to stop is an unladdered shape source on the SERVING
# path, where windows vary per flush.
LADDER_SCOPE_COMPONENTS = ("ops", "parallel", "service", "tools")

# Functions whose RESULTS carry ladder-governed shapes (relpath
# suffix, qualname). _pack_rows buckets via BucketLadder internally;
# compile_chunks/build_chunked are shape-preserving rewrites of packed
# arrays; make_table's capacities come from the ladder's rungs at
# every serve-path call site (prewarm/regrow walk capacity_rungs) and
# fresh tables are setup-time, not per-flush.
LADDER_SOURCES = (
    # pack_rows lives in ops/host_bridge.py since the mesh-pool PR;
    # the sidecar re-exports it as _pack_rows (both names resolve)
    ("ops/host_bridge.py", "pack_rows"),
    ("service/tpu_sidecar.py", "_pack_rows"),
    ("ops/merge_chunk.py", "compile_chunks"),
    ("ops/merge_chunk.py", "build_chunked"),
    # the event-graph compiler re-buckets its prefix/suffix windows
    # through the BucketLadder internally (same contract as pack_rows)
    ("ops/event_graph.py", "build_event_graph"),
    # the wire-1.3 columnar slice entry point: its [n, 12] block is
    # consumed by pack_rows' block fast path, so its column widths
    # reach the device only through the same BucketLadder bucketing
    ("ops/host_bridge.py", "lower_columns"),
    ("ops/segment_table.py", "make_table"),
    # the tree plane's packer buckets window depth via the same
    # BucketLadder; make_tree_table is the tree slab's make_table
    # (serve-path capacities come from capacity_rungs / the pool's
    # fixed per-doc capacity)
    ("ops/tree_apply.py", "pack_tree_window"),
    ("ops/tree_apply.py", "make_tree_table"),
)

# Reviewed per-call-site exceptions: (module, caller-qualname, donated
# or shape argument display) -> justification. Keys mirror finding
# keys so an entry here is exactly one suppressed finding.
LADDERED_CALLS: dict[tuple[str, str, str], str] = {
    # K is the chunked factory's cache key — the static
    # program-selection knob, not a per-dispatch shape. These sites
    # pass the module constant CHUNK_K: exactly one program per
    # route, and prewarm dispatches through the same K, so the one
    # compile is paid before serving. A DATA-DEPENDENT K elsewhere
    # still gets flagged (one XLA program per distinct value).
    ("tpu_sidecar.py", "SeqShardedPool._apply",
     "apply_window_chunked[K]"):
        "K=CHUNK_K module constant; pool prewarm walks it",
    ("tpu_sidecar.py", "TpuMergeSidecar._apply_program",
     "apply_window_chunked[K]"):
        "K=CHUNK_K module constant; prewarm walks the chunked route",
    ("tpu_sidecar.py", "TpuMergeSidecar._apply_program",
     "apply_window_chunked_pingpong[K]"):
        "K=CHUNK_K module constant; prewarm walks the ping-pong jits",
    ("mesh_pool.py", "MeshShardedPool._apply",
     "apply_window_chunked[K]"):
        "K=CHUNK_K module constant (single-shard chunked fast path); "
        "MeshShardedPool.prewarm walks it",
    # EG_K is the egwalker factory's static program-selection
    # constant, exactly like CHUNK_K for the chunked route: one
    # program per route, prewarm dispatches through the same K.
    ("tpu_sidecar.py", "TpuMergeSidecar._apply_program",
     "apply_window_egwalker[K]"):
        "K=EG_K module constant; prewarm walks the egwalker route",
    ("tpu_sidecar.py", "TpuMergeSidecar._apply_program",
     "apply_window_egwalker_pingpong[K]"):
        "K=EG_K module constant; prewarm walks the ping-pong jits",
}

# Calls whose result is freshly allocated (never aliases argument
# buffers): names passed INTO them are not donated when the result is.
FRESH_CONSTRUCTORS = ("make_table", "make_tree_table")

# ---------------------------------------------------------------------------
# prewarm-coverage registries

# Dispatch-loop roots (relpath suffix -> qualnames): every jit compile
# site reachable from these must also be reachable from the prewarm
# roots below, or first-request latency pays a mid-serve XLA compile
# the BucketLadder prewarm never saw.
DISPATCH_ROOTS = {
    "service/tpu_sidecar.py": (
        "TpuMergeSidecar._dispatch",
        "TpuMergeSidecar._apply_program",
        "TpuMergeSidecar._settle",
        "TpuMergeSidecar._recover",
        "TpuMergeSidecar._grow",
        "TpuMergeSidecar.apply",
    ),
    "service/tree_sidecar.py": (
        "TreeSidecar._dispatch",
        "TreeSidecar._settle",
        "TreeSidecar._recover",
        "TreeSidecar._grow",
        "TreeSidecar.apply",
    ),
}

PREWARM_ROOTS = {
    "service/tpu_sidecar.py": (
        "TpuMergeSidecar.prewarm",
    ),
    "service/tree_sidecar.py": (
        "TreeSidecar.prewarm",
    ),
}

# Edges the call graph cannot resolve syntactically (attribute-held
# objects), declared like concurrency.INDIRECT_CALLS:
#   (relpath suffix, caller qualname) -> ((relpath suffix, qualname), ...)
PREWARM_INDIRECT = {
    # the pool tier dispatches at the settle boundary through the
    # attribute-held pool — EITHER tier select_pool can return
    ("service/tpu_sidecar.py", "TpuMergeSidecar._settle"): (
        ("service/tpu_sidecar.py", "SeqShardedPool.dispatch_pending"),
        ("parallel/mesh_pool.py", "MeshShardedPool.dispatch_pending"),
    ),
    ("service/tpu_sidecar.py", "TpuMergeSidecar._recover"): (
        ("service/tpu_sidecar.py", "TpuMergeSidecar._admit_to_pool"),
    ),
    ("service/tpu_sidecar.py", "TpuMergeSidecar._admit_to_pool"): (
        ("service/tpu_sidecar.py", "SeqShardedPool.admit"),
        ("parallel/mesh_pool.py", "MeshShardedPool.admit"),
    ),
    # prewarm warms the pool tier through the same attribute
    ("service/tpu_sidecar.py", "TpuMergeSidecar._warm_pool"): (
        ("service/tpu_sidecar.py", "SeqShardedPool.prewarm"),
        ("parallel/mesh_pool.py", "MeshShardedPool.prewarm"),
    ),
    # replay_chunked receives the pool's _apply as a callback value
    # (lives in ops/host_bridge.py since the mesh-pool PR; the
    # sidecar re-exports it as _replay_chunked)
    ("ops/host_bridge.py", "replay_chunked"): (
        ("service/tpu_sidecar.py", "SeqShardedPool._apply"),
        ("parallel/mesh_pool.py", "MeshShardedPool._apply"),
    ),
    # the tree plane's attribute-held pool, same edges as the merge
    # sidecar's: settle-boundary dispatch, recovery admission, and
    # the prewarm walk through _warm_pool
    ("service/tree_sidecar.py", "TreeSidecar._settle"): (
        ("service/tree_sidecar.py", "TreeSeqPool.dispatch_pending"),
    ),
    ("service/tree_sidecar.py", "TreeSidecar._recover"): (
        ("service/tree_sidecar.py", "TreeSidecar._admit_to_pool"),
    ),
    ("service/tree_sidecar.py", "TreeSidecar._admit_to_pool"): (
        ("service/tree_sidecar.py", "TreeSeqPool.admit"),
    ),
    ("service/tree_sidecar.py", "TreeSidecar._warm_pool"): (
        ("service/tree_sidecar.py", "TreeSeqPool.prewarm"),
    ),
}

# ---------------------------------------------------------------------------
# dtype-widen registry

WIDE_DTYPE_SUFFIXES = (
    "int64", "uint64", "float64", "complex128", "longlong",
)
WIDE_DTYPE_STRINGS = ("int64", "uint64", "float64", "complex128")
# astype(int)/astype(float): the Python builtins map to 64-bit under
# x64 mode — inside a kernel that is a latent 2x HBM widen
WIDE_BUILTINS = ("int", "float")


# ===========================================================================
# jit-object collection (shared by every rule in this family)


@dataclasses.dataclass
class JitObject:
    """One ``jax.jit`` compile site in a module."""

    module: str                 # file name, e.g. "merge_kernel.py"
    relpath: str
    name: str                   # bound name, or enclosing qualname
    donate_argnums: tuple       # positional indices donated
    static_argnums: tuple
    static_argnames: tuple
    wrapped: Optional[str]      # wrapped function name, if a Name
    lambda_callees: tuple       # bare names called from a jitted lambda
    scope: Optional[str]        # enclosing function qualname (factory)
    line: int


def _literal(node):
    if node is None:
        return None
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _jit_kwargs(call: ast.Call) -> tuple[tuple, tuple, tuple]:
    def tup(name):
        val = _literal(next(
            (k.value for k in call.keywords if k.arg == name), None))
        if isinstance(val, int):
            val = (val,)
        if isinstance(val, str):
            val = (val,)
        return tuple(val or ())

    return (tup("donate_argnums"), tup("static_argnums"),
            tup("static_argnames"))


def collect_jit_objects(src: SourceFile,
                        aliases: dict) -> list[JitObject]:
    """Every jit compile site in one module: module-level/assigned
    ``X = jax.jit(fn, ...)`` forms, decorated defs, and jit calls
    nested inside factory functions (``_jit_cache[K] = jax.jit(...)``
    — identity is the enclosing function)."""
    if src.tree is None:
        return []
    module = src.relpath.rsplit("/", 1)[-1]

    def is_jit(node) -> bool:
        return _dotted(node, aliases) == "jax.jit"

    # enclosing-function map for factory identity. A def does NOT
    # enclose itself: a decorated module-level jit (``@jax.jit`` on
    # ``compact``) is a plain module jit, and self-scoping it made the
    # prewarm walker treat it as factory-cached and skip its call
    # edges entirely.
    scope_of: dict[int, str] = {}

    def map_scope(fn, qual):
        for sub in ast.walk(fn):
            if sub is not fn:
                scope_of.setdefault(id(sub), qual)

    for node in src.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            map_scope(node, node.name)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    map_scope(sub, f"{node.name}.{sub.name}")

    out: list[JitObject] = []
    seen_calls: set[int] = set()

    # decorated defs
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            call = None
            if is_jit(dec):
                donate, statics, statnames = (), (), ()
            elif isinstance(dec, ast.Call):
                target = _dotted(dec.func, aliases)
                if target == "jax.jit":
                    call = dec
                elif target in ("functools.partial", "partial") and \
                        dec.args and is_jit(dec.args[0]):
                    call = dec
                else:
                    continue
                donate, statics, statnames = _jit_kwargs(call)
            else:
                continue
            if call is not None:
                seen_calls.add(id(call))
            out.append(JitObject(
                module, src.relpath, node.name, donate, statics,
                statnames, wrapped=node.name, lambda_callees=(),
                scope=scope_of.get(id(node)), line=node.lineno,
            ))

    # bound names: one pass over the module's Assigns instead of one
    # full-tree walk per jit call
    assigned_name: dict[int, str] = {}
    for stmt in ast.walk(src.tree):
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    assigned_name[id(stmt.value)] = tgt.id

    # call forms: X = jax.jit(fn, ...) / cache[K] = jax.jit(fn, ...)
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Call) and is_jit(node.func)
                and node.args) or id(node) in seen_calls:
            continue
        donate, statics, statnames = _jit_kwargs(node)
        wrapped = None
        lambda_callees: tuple = ()
        arg0 = node.args[0]
        if isinstance(arg0, ast.Name):
            wrapped = arg0.id
        elif isinstance(arg0, ast.Lambda):
            lambda_callees = tuple(sorted({
                sub.func.id for sub in ast.walk(arg0)
                if isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
            }))
        # bound name: the enclosing Assign with a Name target, else
        # the enclosing function (factory), else anonymous
        parent_scope = scope_of.get(id(node))
        name = assigned_name.get(id(node))
        if name is None:
            name = parent_scope or f"<jit@{node.lineno}>"
        out.append(JitObject(
            module, src.relpath, name, donate, statics, statnames,
            wrapped=wrapped, lambda_callees=lambda_callees,
            scope=parent_scope, line=node.lineno,
        ))
    return out


# ===========================================================================
# per-function dataflow helpers


def _functions(tree: ast.AST):
    """(qualname, node) for every def, class methods qualified."""
    out = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node))
            for sub in ast.walk(node):
                if sub is not node and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append((f"{node.name}.{sub.name}", sub))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    out.append((f"{node.name}.{sub.name}", sub))
    return out


def _param_names(fn) -> list[str]:
    args = fn.args
    return [a.arg for a in
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)]


def _names_loaded(node: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _call_target_names(call: ast.Call) -> list[str]:
    """Candidate names a call site may dispatch through: the bare
    name, a module-attr tail (``merge_kernel.apply_window`` ->
    "apply_window"), or ``self.method``."""
    func = call.func
    if isinstance(func, ast.Name):
        return [func.id]
    if isinstance(func, ast.Attribute):
        return [func.attr]
    return []


def _assigned_names(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                out.update(e.id for e in tgt.elts
                           if isinstance(e, ast.Name))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        if isinstance(stmt.target, ast.Name):
            out.add(stmt.target.id)
    elif isinstance(stmt, ast.For) and isinstance(stmt.target, ast.Name):
        out.add(stmt.target.id)
    return out


class _Index:
    """Per-run parse products, computed ONCE per file: import
    aliases, the function list, and each function's call sites. The
    fixpoint solvers re-traverse these every iteration — without the
    index each pass re-ran ``ast.walk`` over the whole tree per
    (file x iteration), which dominated the family's runtime (the
    gate-budget satellite of the shapecheck PR)."""

    def __init__(self, files: list[SourceFile], graph: CallGraph):
        self.files = files
        self.graph = graph
        self.aliases: dict[str, dict] = {}
        self.functions: dict[str, list] = {}
        self.calls: dict[int, list] = {}
        for src in files:
            if src.tree is None:
                continue
            self.aliases[src.relpath] = import_aliases(src.tree)
            fns = _functions(src.tree)
            self.functions[src.relpath] = fns
            for _, fn in fns:
                self.calls[id(fn)] = [
                    n for n in ast.walk(fn)
                    if isinstance(n, ast.Call)
                ]


# ===========================================================================
# rule: donated-buffer-reuse


class _DonationAnalysis:
    """Fixpoint over the call graph: which callables donate which
    argument positions/param names, then read-after-donation checks at
    every call site."""

    def __init__(self, idx: _Index, jits_by_file: dict):
        self.idx = idx
        self.files = idx.files
        self.graph = idx.graph
        self.jits_by_file = jits_by_file
        # jit-object donating positions per (relpath, name)
        self.jit_donors: dict[tuple, tuple] = {}
        # function donating param NAMES per (relpath, qualname)
        self.fn_donors: dict[tuple, set] = {}
        # factory functions returning a donating jit:
        # (relpath, qualname) -> donated positions
        self.factory_donors: dict[tuple, tuple] = {}
        for src in self.files:
            for jit in jits_by_file.get(src.relpath, ()):
                if not jit.donate_argnums:
                    continue
                self.jit_donors[(jit.relpath, jit.name)] = \
                    jit.donate_argnums
                if jit.scope is not None:
                    # a jit created inside a function: treat the
                    # enclosing function as a factory whose RESULT
                    # donates (the `_get_jit_pingpong(K)(dead, ...)`
                    # call-of-call shape)
                    self.factory_donors[(jit.relpath, jit.scope)] = \
                        jit.donate_argnums

    # -- donated positions of one call site ---------------------------
    def donated_positions(self, call: ast.Call, src: SourceFile,
                          caller_info) -> tuple:
        # direct jit-object call: f(...) where f is a donating jit
        # bound in this module (or `mod.f(...)`)
        for name in _call_target_names(call):
            pos = self.jit_donors.get((src.relpath, name))
            if pos:
                return pos
        # call-of-call through a donating factory:
        # `factory(K)(dead, ...)`
        if isinstance(call.func, ast.Call):
            inner = call.func
            for target in self.graph.resolve_call(
                    inner, caller_info, src):
                pos = self.factory_donors.get(target.key)
                if pos:
                    return pos
            for name in _call_target_names(inner):
                # module-local factory the graph may not resolve in
                # fixture trees
                for key, pos in self.factory_donors.items():
                    if key[0] == src.relpath and key[1] == name:
                        return pos
        # resolved call to a function with donating params
        donated: list[int] = []
        for target in self.graph.resolve_call(call, caller_info, src):
            names = self.fn_donors.get(target.key)
            if not names:
                continue
            params = _param_names(target.node)
            offset = 1 if params[:1] in (["self"], ["cls"]) else 0
            for i, p in enumerate(params):
                if p in names:
                    donated.append(i - offset)
        return tuple(sorted(set(d for d in donated if d >= 0)))

    def donated_name_args(self, call: ast.Call, positions: tuple,
                          ) -> tuple[set, int]:
        """Names feeding donated argument expressions at a call site
        (FRESH_CONSTRUCTORS excluded). Also returns the line of the
        first donated argument for reporting.

        A name that appears only as an ATTRIBUTE BASE inside the
        donated expression (``dead if self.donate else None`` loads
        ``self`` but donates ``dead``) is not itself donated — the
        donated value is the attribute, which the pass treats as
        attribute-held state (a documented conservative gap), not the
        base object."""
        names: set[str] = set()
        line = call.lineno
        exprs = []
        for i, arg in enumerate(call.args):
            if i in positions:
                exprs.append(arg)
        # keywords cannot map to donate_argnums positions statically;
        # conservatively skipped (jax donation is positional anyway)
        for expr in exprs:
            line = expr.lineno
            attr_bases = {
                id(n.value) for n in ast.walk(expr)
                if isinstance(n, ast.Attribute)
            }
            # a fresh-constructor result is unaliased: exempt THAT
            # call subtree only (its args do not alias its result),
            # not the whole expression — the other branch of
            # ``fodder if ok else make_table(n, c)`` is still donated
            fresh_ids: set[int] = set()
            for node in ast.walk(expr):
                if isinstance(node, ast.Call) and \
                        id(node) not in fresh_ids:
                    tgt = _call_target_names(node)
                    if any(t in FRESH_CONSTRUCTORS for t in tgt):
                        fresh_ids.update(
                            id(sub) for sub in ast.walk(node))
            for node in ast.walk(expr):
                if id(node) in fresh_ids:
                    continue
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        id(node) not in attr_bases:
                    names.add(node.id)
        return names, line

    # -- fixpoint: propagate donation through wrapper params ----------
    def solve(self) -> None:
        changed = True
        while changed:
            changed = False
            for src in self.files:
                if src.tree is None:
                    continue
                for qual, fn in self.idx.functions[src.relpath]:
                    params = set(_param_names(fn))
                    info = self.graph.info_for_node(fn)
                    for node in self.idx.calls[id(fn)]:
                        pos = self.donated_positions(node, src, info)
                        if not pos:
                            continue
                        names, _ = self.donated_name_args(node, pos)
                        donated_params = names & params
                        if donated_params:
                            key = (src.relpath, qual)
                            have = self.fn_donors.setdefault(key, set())
                            if not donated_params <= have:
                                have |= donated_params
                                changed = True


def _reads_after_call(fn, call: ast.Call, names: set,
                      ) -> Optional[ast.Name]:
    """First Load of a donated name on any path after ``call`` inside
    ``fn``. 'After' = sibling statements after the containing
    statement at every enclosing block level; when the call sits in a
    ``try`` body the except-handler bodies, ``else`` and ``finally``
    blocks are post-call paths too (an exception AFTER the donating
    dispatch lands in the handler with the buffer already consumed,
    and ``finally`` runs on every path — including after a
    ``return pingpong(dead, ...)``); when the call sits inside a
    loop, the loop body from the top is the wrap-around path. A
    top-level reassignment of a name kills its taint; reassignments
    inside nested branches do NOT (any-path semantics — a documented
    conservative approximation)."""

    # statement spine: enclosing block chain down to the call
    spine: list[tuple[list, int]] = []

    def find(block: list) -> bool:
        for i, stmt in enumerate(block):
            found_here = any(n is call for n in ast.walk(stmt))
            if not found_here:
                continue
            spine.append((block, i))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub and find(sub):
                    return True
            for handler in getattr(stmt, "handlers", []):
                if find(handler.body):
                    return True
            return True
        return False

    if not find(fn.body):
        return None

    def scan(stmts, live: set) -> Optional[ast.Name]:
        for stmt in stmts:
            if not live:
                return None
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in live:
                    return node
            live.difference_update(_assigned_names(stmt))
        return None

    # the statement directly containing the call may end the function
    # (``return pingpong(dead, ...)`` / ``raise``): no sibling runs
    # afterward — scanning them would walk OTHER branches' dead code
    # (the _apply_program false positive). Enclosing try blocks still
    # get their post-branch scan below: ``finally`` runs even after a
    # return, and a raise lands in the matching handler.
    inner_block, inner_i = spine[-1]
    terminal = isinstance(inner_block[inner_i], (ast.Return, ast.Raise))
    is_raise = isinstance(inner_block[inner_i], ast.Raise)

    live = set(names)

    # innermost-out: siblings after the call at each level, plus the
    # post-call branches of enclosing try statements
    loops: list = []
    child_block: Optional[list] = None
    for block, i in reversed(spine):
        # the containing statement itself may reassign (x = f(x,...))
        live.difference_update(_assigned_names(block[i]))
        stmt = block[i]
        if isinstance(stmt, ast.Try) and child_block is not None:
            in_handler = any(
                child_block is h.body for h in stmt.handlers)
            if child_block is stmt.body:
                if terminal and not is_raise:
                    # return exits through finally only
                    post = [stmt.finalbody]
                elif is_raise:
                    post = [h.body for h in stmt.handlers] + \
                        [stmt.finalbody]
                else:
                    post = [h.body for h in stmt.handlers] + \
                        [stmt.orelse, stmt.finalbody]
            elif in_handler or child_block is stmt.orelse:
                post = [stmt.finalbody]
            else:           # call inside finally: nothing follows
                post = []
            for branch in post:
                # independent live copy per branch (any-path)
                hit = scan(branch, set(live))
                if hit is not None:
                    return hit
        if not terminal:
            hit = scan(block[i + 1:], live)
            if hit is not None:
                return hit
        owner = next(
            (st for st in ast.walk(fn)
             if getattr(st, "body", None) is block
             or getattr(st, "orelse", None) is block
             or getattr(st, "finalbody", None) is block
             or any(getattr(h, "body", None) is block
                    for h in getattr(st, "handlers", []))),
            None,
        )
        if not terminal and isinstance(
                owner, (ast.For, ast.While, ast.AsyncFor)):
            # snapshot the taint surviving to the END of this loop's
            # body: the containing statement's own rebinding and the
            # sibling scan just ran have already killed their names —
            # seeding the wrap path with the ORIGINAL set would flag
            # the sanctioned rotate-in-a-loop idiom
            # (``dead = pingpong(dead, b)`` then loop around)
            loops.append((block, i, set(live)))
        child_block = block
    # wrap-around: for each enclosing loop, the body re-executes from
    # its top down to the call statement
    for block, i, survived in loops:
        hit = scan(block[:i], survived)
        if hit is not None:
            return hit
    return None


def _check_donated(idx: _Index, jits_by_file: dict) -> list[Finding]:
    ana = _DonationAnalysis(idx, jits_by_file)
    ana.solve()
    findings: list[Finding] = []
    graph = idx.graph
    for src in idx.files:
        if src.tree is None:
            continue
        module = src.relpath.rsplit("/", 1)[-1]
        for qual, fn in idx.functions[src.relpath]:
            info = graph.info_for_node(fn)
            for node in idx.calls[id(fn)]:
                pos = ana.donated_positions(node, src, info)
                if not pos:
                    continue
                names, line = ana.donated_name_args(node, pos)
                if not names:
                    continue
                # a name passed BOTH donated and live in one call is
                # an immediate aliasing bug (donating the live input);
                # live inputs count whether positional or keyword
                other_names: set[str] = set()
                for i, arg in enumerate(node.args):
                    if i not in pos:
                        other_names |= _names_loaded(arg)
                for kw in node.keywords:
                    other_names |= _names_loaded(kw.value)
                overlap = names & other_names
                if overlap:
                    nm = sorted(overlap)[0]
                    findings.append(Finding(
                        rule="donated-buffer-reuse",
                        path=src.relpath, line=line,
                        message=(
                            f"{nm!r} is passed both as a DONATED "
                            f"argument and as a live input in the "
                            "same dispatch: XLA may reuse its "
                            "buffers for the output while the "
                            "kernel still reads them"
                        ),
                        key=f"{module}:{qual}:{nm}",
                    ))
                    continue
                hit = _reads_after_call(fn, node, names)
                if hit is not None:
                    findings.append(Finding(
                        rule="donated-buffer-reuse",
                        path=src.relpath, line=hit.lineno,
                        message=(
                            f"{hit.id!r} is read after being donated "
                            f"to a jit with donate_argnums (call at "
                            f"line {line}): its buffers may already "
                            "back the dispatch output — drop every "
                            "reference after donating (docs/PERF.md "
                            "buffer-ownership rules)"
                        ),
                        key=f"{module}:{qual}:{hit.id}",
                    ))
    return findings


# ===========================================================================
# rule: unladdered-jit-shape


def _in_ladder_scope(relpath: str) -> bool:
    parts = relpath.split("/")
    return any(p in LADDER_SCOPE_COMPONENTS for p in parts[:-1])


# laddered-ness lattice verdicts
_LADDERED = "laddered"
_OK = "ok"              # attribute-held / None / unresolvable: trusted
_RAW = "raw"            # provably not ladder-derived


class _LadderAnalysis:
    def __init__(self, idx: _Index, jits_by_file: dict):
        self.idx = idx
        self.files = idx.files
        self.graph = idx.graph
        # local env per function: classify() never reads fixpoint
        # state (shape_params feeds shape_positions only), so the env
        # is iteration-invariant and memoizes per def
        self._env_cache: dict[int, dict] = {}
        # shape-determining param names per (relpath, qualname)
        self.shape_params: dict[tuple, set] = {}
        # jit objects per (relpath, name) -> static argnums / argnames
        self.jit_statics: dict[tuple, tuple] = {}
        self.jit_static_names: dict[tuple, tuple] = {}
        self.jit_names: dict[str, set] = {}     # relpath -> names
        self.factories: set[tuple] = set()      # jit factory functions
        for src in self.files:
            for jit in jits_by_file.get(src.relpath, ()):
                self.jit_statics[(jit.relpath, jit.name)] = (
                    jit.static_argnums)
                self.jit_static_names[(jit.relpath, jit.name)] = (
                    jit.static_argnames)
                self.jit_names.setdefault(jit.relpath, set()).add(
                    jit.name)
                if jit.scope is not None:
                    self.factories.add((jit.relpath, jit.scope))

    def _is_source_call(self, call: ast.Call, src: SourceFile,
                        caller_info, aliases: dict) -> bool:
        # BucketLadder itself (constructor, classmethod, or a method
        # on an imported/aliased name)
        dotted = _dotted(call.func, aliases)
        if dotted is not None and "BucketLadder" in dotted.split("."):
            return True
        for target in self.graph.resolve_call(call, caller_info, src):
            for suffix, qual in LADDER_SOURCES:
                if target.relpath.endswith(suffix) and \
                        target.qualname == qual:
                    return True
            # a registered jit entry's OUTPUT is kernel-shaped
            if target.relpath in self.jit_names and \
                    target.name in self.jit_names[target.relpath]:
                return True
        for name in _call_target_names(call):
            if any(qual == name for _, qual in LADDER_SOURCES):
                # bare-name fallback for fixture trees the graph
                # cannot resolve module paths for
                if isinstance(call.func, ast.Name):
                    return True
            if (src.relpath, name) in self.jit_statics:
                return True
        return False

    def classify(self, expr: ast.expr, src: SourceFile, fn,
                 caller_info, aliases: dict,
                 env: dict) -> tuple[str, set]:
        """-> (verdict, param-names the expr derives from)."""
        params: set = set()
        found = {"laddered": False, "raw_leaf": False}

        fn_params = set(_param_names(fn))

        def walk(node, bound: frozenset = frozenset()) -> None:
            if isinstance(node, ast.Call):
                if self._is_source_call(node, src, caller_info,
                                        aliases):
                    found["laddered"] = True
                    return
                for sub in list(node.args) + [
                        k.value for k in node.keywords]:
                    walk(sub, bound)
                if isinstance(node.func, ast.Call):
                    walk(node.func, bound)
                elif isinstance(node.func, ast.Attribute):
                    # a method call's result derives from its
                    # receiver: ``state.items()`` is as laddered as
                    # ``state`` (the pallas padding false positive).
                    # NOT so for module-attr calls (``jnp.asarray``):
                    # the base is an import alias, not a value
                    root = node.func.value
                    while isinstance(root, ast.Attribute):
                        root = root.value
                    if not (isinstance(root, ast.Name)
                            and root.id in aliases):
                        walk(node.func.value, bound)
                return
            if isinstance(node, ast.Attribute):
                return          # attribute-held state: trusted (FN)
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    if node.id in bound:
                        # comprehension variable: the generator's
                        # iterable was walked and already contributed
                        # its verdict
                        pass
                    elif node.id in fn_params:
                        params.add(node.id)
                    elif node.id in env:
                        verdict, p = env[node.id]
                        if verdict == _LADDERED:
                            found["laddered"] = True
                        elif verdict == _RAW:
                            found["raw_leaf"] = True
                        params.update(p)
                    else:
                        found["raw_leaf"] = True
                return
            if isinstance(node, ast.Constant):
                return
            if isinstance(node, (ast.ListComp, ast.SetComp,
                                 ast.GeneratorExp, ast.DictComp)):
                # bind each generator's targets to its iterable, then
                # classify the element under those bindings
                inner = set(bound)
                for gen in node.generators:
                    walk(gen.iter, frozenset(inner))
                    inner |= {
                        n.id for n in ast.walk(gen.target)
                        if isinstance(n, ast.Name)
                    }
                    for cond in gen.ifs:
                        walk(cond, frozenset(inner))
                if isinstance(node, ast.DictComp):
                    walk(node.key, frozenset(inner))
                    walk(node.value, frozenset(inner))
                else:
                    walk(node.elt, frozenset(inner))
                return
            if isinstance(node, (ast.Tuple, ast.List, ast.Dict,
                                 ast.Set, ast.IfExp, ast.BinOp,
                                 ast.Subscript, ast.Starred,
                                 ast.Compare,
                                 ast.BoolOp, ast.UnaryOp,
                                 ast.FormattedValue, ast.JoinedStr,
                                 ast.Slice)):
                for child in ast.iter_child_nodes(node):
                    walk(child, bound)
                return
            # anything else: trusted rather than misflagged
            return

        walk(expr)
        if found["laddered"]:
            return _LADDERED, set()
        if params:
            return "param", params
        if found["raw_leaf"]:
            return _RAW, set()
        return _OK, set()

    def _local_env(self, fn, src: SourceFile, caller_info,
                   aliases: dict) -> dict:
        """name -> (verdict, params) from straight-line assignments in
        statement order (last assignment wins; good enough for the
        kernel wrappers this rule audits). Memoized per def — see
        ``_env_cache``."""
        cached = self._env_cache.get(id(fn))
        if cached is not None:
            return cached
        env: dict = {}
        # textual order, NOT ast.walk's breadth-first order — BFS
        # visits every top-level assignment before any nested one, so
        # a branch-local rebinding would always override a LATER
        # top-level one (and vice versa for the laddered verdict)
        assigns = sorted(
            (node for node in ast.walk(fn)
             if isinstance(node, ast.Assign)),
            key=lambda n: (n.lineno, n.col_offset),
        )
        for node in assigns:
            verdict, params = self.classify(
                node.value, src, fn, caller_info, aliases, env)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    env[tgt.id] = (verdict, params)
        self._env_cache[id(fn)] = env
        return env

    def shape_positions(self, call: ast.Call, src: SourceFile,
                        caller_info
                        ) -> tuple[tuple, frozenset, Optional[str]]:
        """(positions, keyword-names, display-name) of
        shape-determining args at one call site; ((), frozenset(),
        None) when the target is not shape-constrained. Keyword args
        are shape-determining by NAME — a recompile-storm call site
        must not pass the gate just by switching an argument to
        keyword form."""
        all_kws = frozenset(
            kw.arg for kw in call.keywords if kw.arg is not None)
        # direct jit-object call (or via module attr): static_argnums
        # slots are positional, static_argnames exempt keywords —
        # every OTHER keyword is a traced, shape-determining argument
        for name in _call_target_names(call):
            statics = self.jit_statics.get((src.relpath, name))
            if statics is not None:
                statnames = self.jit_static_names.get(
                    (src.relpath, name), ())
                n = len(call.args)
                return (tuple(i for i in range(n) if i not in statics),
                        all_kws - frozenset(statnames), name)
        # call-of-call through a jit factory
        if isinstance(call.func, ast.Call):
            inner = call.func
            hit = False
            for target in self.graph.resolve_call(inner, caller_info,
                                                  src):
                if target.key in self.factories:
                    hit = True
            for name in _call_target_names(inner):
                if (src.relpath, name) in self.factories:
                    hit = True
            if hit:
                return tuple(range(len(call.args))), all_kws, \
                    _call_target_names(inner)[0] \
                    if _call_target_names(inner) else "<factory>"
        # resolved call to a function with shape-determining params
        out: list[int] = []
        kws: set[str] = set()
        display = None
        for target in self.graph.resolve_call(call, caller_info, src):
            names = self.shape_params.get(target.key)
            if not names:
                continue
            display = target.name
            tparams = _param_names(target.node)
            offset = 1 if tparams[:1] in (["self"], ["cls"]) else 0
            for i, p in enumerate(tparams):
                if p in names and i - offset >= 0:
                    out.append(i - offset)
            for kw in call.keywords:
                if kw.arg in names:
                    kws.add(kw.arg)
        return tuple(sorted(set(out))), frozenset(kws), display

    def solve(self) -> None:
        changed = True
        while changed:
            changed = False
            for src in self.files:
                if src.tree is None:
                    continue
                aliases = self.idx.aliases[src.relpath]
                for qual, fn in self.idx.functions[src.relpath]:
                    info = self.graph.info_for_node(fn)
                    env = self._local_env(fn, src, info, aliases)
                    for node in self.idx.calls[id(fn)]:
                        positions, kw_names, _ = self.shape_positions(
                            node, src, info)
                        if not positions and not kw_names:
                            continue
                        for i, arg in enumerate(node.args):
                            if i not in positions:
                                continue
                            verdict, params = self.classify(
                                arg, src, fn, info, aliases, env)
                            if verdict != "param":
                                continue
                            key = (src.relpath, qual)
                            have = self.shape_params.setdefault(
                                key, set())
                            if not params <= have:
                                have |= params
                                changed = True
                        for kw in node.keywords:
                            if kw.arg not in kw_names:
                                continue
                            verdict, params = self.classify(
                                kw.value, src, fn, info, aliases, env)
                            if verdict != "param":
                                continue
                            key = (src.relpath, qual)
                            have = self.shape_params.setdefault(
                                key, set())
                            if not params <= have:
                                have |= params
                                changed = True


def _check_unladdered(idx: _Index, jits_by_file: dict) -> list[Finding]:
    ana = _LadderAnalysis(idx, jits_by_file)
    ana.solve()
    findings: list[Finding] = []
    graph = idx.graph
    for src in idx.files:
        if src.tree is None or not _in_ladder_scope(src.relpath):
            continue
        aliases = idx.aliases[src.relpath]
        module = src.relpath.rsplit("/", 1)[-1]
        for qual, fn in idx.functions[src.relpath]:
            info = graph.info_for_node(fn)
            env = ana._local_env(fn, src, info, aliases)
            for node in idx.calls[id(fn)]:
                positions, kw_names, display = ana.shape_positions(
                    node, src, info)
                if not positions and not kw_names:
                    continue
                entry = display or "<jit>"
                # (arg-expression, display slot) pairs: positional
                # indices and shape-determining keywords alike — a
                # raw shape must not pass just by switching the
                # argument to keyword form
                slots = [
                    (arg, str(i)) for i, arg in enumerate(node.args)
                    if i in positions
                ] + [
                    (kw.value, kw.arg) for kw in node.keywords
                    if kw.arg in kw_names
                ]
                for arg, slot in slots:
                    verdict, _p = ana.classify(
                        arg, src, fn, info, aliases, env)
                    if verdict != _RAW:
                        continue
                    key = f"{module}:{qual}:{entry}[{slot}]"
                    if (module, qual, f"{entry}[{slot}]") in \
                            LADDERED_CALLS:
                        continue
                    findings.append(Finding(
                        rule="unladdered-jit-shape",
                        path=src.relpath, line=arg.lineno,
                        message=(
                            f"argument {slot} of jit-dispatched "
                            f"{entry}() does not flow from the "
                            "BucketLadder (or a static_argnums "
                            "slot): every distinct shape here is a "
                            "20-40s XLA compile mid-serve — pack "
                            "through _pack_rows/compile_chunks or a "
                            "BucketLadder bucket, or register a "
                            "reviewed exception in "
                            "shapecheck.LADDERED_CALLS"
                        ),
                        key=key,
                    ))
    return findings


# ===========================================================================
# rules: kernel-dtype-widen + shape-mismatch (jit-reachable bodies)


def _jit_reachable_functions(files: list[SourceFile],
                             graph: CallGraph):
    """(src, fn, aliases) for every function reachable from a jit
    root, local bare-name walk + cross-module graph edges (the
    jaxhazards recipe, shared)."""
    from .jaxhazards import _find_roots, _reachable

    seen: dict[int, tuple] = {}
    foreign: dict[int, object] = {}
    by_rel = {src.relpath: src for src in files}
    for src in files:
        if src.tree is None:
            continue
        aliases = import_aliases(src.tree, relative="skip")
        roots = _find_roots(src.tree, aliases)
        if not roots:
            continue
        local = _reachable(roots, src.tree)
        for fn in local:
            seen.setdefault(id(fn), (src, fn, aliases))
        for fn in local:
            caller = graph.info_for_node(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                for target in graph.resolve_call(node, caller, src):
                    if target.relpath != src.relpath:
                        foreign[id(target.node)] = target
    for info in graph.reachable(foreign.values()):
        src = by_rel.get(info.relpath)
        if src is None:
            continue
        aliases = import_aliases(src.tree, relative="skip")
        seen.setdefault(id(info.node), (src, info.node, aliases))
    return list(seen.values())


def _wide_dtype_of(node: ast.expr, aliases: dict,
                   builtins: bool = False) -> Optional[str]:
    """The 64-bit dtype a node denotes, if any. The bare ``int`` /
    ``float`` builtins only count in DTYPE POSITIONS (``astype(int)``,
    ``dtype=float``; ``builtins=True``) — a plain ``int(x)`` call is
    host-side scalar arithmetic, not an array widen."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in WIDE_DTYPE_STRINGS else None
    if isinstance(node, ast.Name) and node.id in WIDE_BUILTINS:
        return node.id if builtins else None
    dotted = _dotted(node, aliases)
    if dotted is not None and \
            dotted.rsplit(".", 1)[-1] in WIDE_DTYPE_SUFFIXES:
        return dotted
    return None


def _qual_index(files: list[SourceFile]) -> dict[str, dict]:
    """relpath -> {id(fn-node): qualname}: the dtype/shape rules key
    findings on qualified names (same-named methods of two classes in
    one module must not collapse onto one dedup/allowlist key)."""
    out: dict[str, dict] = {}
    for src in files:
        if src.tree is None:
            continue
        out[src.relpath] = {
            id(fn): qual for qual, fn in _functions(src.tree)
        }
    return out


def _check_dtype_widen(reachable: list, quals: dict) -> list[Finding]:
    findings: list[Finding] = []
    emitted: set = set()
    for src, fn, aliases in reachable:
        module = src.relpath.rsplit("/", 1)[-1]
        qual = quals.get(src.relpath, {}).get(id(fn), fn.name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            hits: list[str] = []
            # jnp.int64(x) / np.float64(x) cast-call forms (NOT the
            # bare int()/float() builtins — those are host scalars)
            wide = _wide_dtype_of(node.func, aliases)
            if wide is not None:
                hits.append(wide)
            # x.astype(<wide>) and dtype=<wide> keyword/positional
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype" and node.args:
                wide = _wide_dtype_of(node.args[0], aliases,
                                      builtins=True)
                if wide is not None:
                    hits.append(wide)
            for kw in node.keywords:
                if kw.arg == "dtype":
                    wide = _wide_dtype_of(kw.value, aliases,
                                          builtins=True)
                    if wide is not None:
                        hits.append(wide)
            for wide in hits:
                short = wide.rsplit(".", 1)[-1]
                key = f"{module}:{qual}:{short}"
                if key in emitted:
                    continue
                emitted.add(key)
                findings.append(Finding(
                    rule="kernel-dtype-widen",
                    path=src.relpath, line=node.lineno,
                    message=(
                        f"64-bit dtype {wide} inside jit-reachable "
                        f"{qual}(): a widened table field doubles "
                        "HBM traffic for every dispatch that touches "
                        "it (and silently upcasts whatever mixes "
                        "with it) — keep kernel state int32/float32"
                    ),
                    key=key,
                ))
    return findings


# -- shape-mismatch ---------------------------------------------------------

_SHAPE_CTORS = ("zeros", "ones", "full", "empty")


def _dim_desc(node: ast.expr):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return ("lit", node.value)
    return ("sym", ast.dump(node))


def _shape_of_call(call: ast.Call, aliases: dict) -> Optional[tuple]:
    dotted = _dotted(call.func, aliases)
    if dotted is None:
        return None
    leaf = dotted.rsplit(".", 1)[-1]
    shape_arg = None
    if leaf in _SHAPE_CTORS and call.args:
        shape_arg = call.args[0]
    elif leaf == "broadcasted_iota" and len(call.args) >= 2:
        shape_arg = call.args[1]
    elif leaf == "arange" and call.args:
        return (_dim_desc(call.args[0]),)
    if shape_arg is None:
        return None
    if isinstance(shape_arg, (ast.Tuple, ast.List)):
        return tuple(_dim_desc(e) for e in shape_arg.elts)
    return (_dim_desc(shape_arg),)


def _lit_conflict(a, b) -> bool:
    return a[0] == "lit" and b[0] == "lit" and a[1] != b[1]


def _check_shape_mismatch(reachable: list,
                          quals: dict) -> list[Finding]:
    findings: list[Finding] = []
    for src, fn, aliases in reachable:
        module = src.relpath.rsplit("/", 1)[-1]
        qual = quals.get(src.relpath, {}).get(id(fn), fn.name)
        env: dict[str, tuple] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                shape = _shape_of_call(node.value, aliases)
                if shape is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            env[tgt.id] = shape

        def known(e: ast.expr) -> Optional[tuple]:
            if isinstance(e, ast.Call):
                return _shape_of_call(e, aliases)
            if isinstance(e, ast.Name):
                return env.get(e.id)
            return None

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func, aliases)
            leaf = dotted.rsplit(".", 1)[-1] if dotted else None
            if leaf in ("concatenate", "stack") and node.args and \
                    isinstance(node.args[0], (ast.Tuple, ast.List)):
                # axis arrives by keyword OR positionally
                # (jnp.concatenate(ops, 1)); a non-literal axis means
                # we cannot know which dim the concat exempts, so the
                # per-axis comparison is skipped (rank check stands)
                axis_expr = next(
                    (k.value for k in node.keywords
                     if k.arg == "axis"),
                    node.args[1] if len(node.args) > 1 else None)
                axis = 0 if axis_expr is None else _literal(axis_expr)
                shapes = [(e, known(e)) for e in node.args[0].elts]
                shapes = [(e, s) for e, s in shapes if s is not None]
                for (e1, s1), (e2, s2) in zip(shapes, shapes[1:]):
                    if len(s1) != len(s2):
                        findings.append(Finding(
                            rule="shape-mismatch",
                            path=src.relpath, line=node.lineno,
                            message=(
                                f"{leaf}() operands have rank "
                                f"{len(s1)} vs {len(s2)}: inferred "
                                "operand shapes disagree"
                            ),
                            key=(f"{module}:{qual}:{leaf}:"
                                 f"rank{len(s1)}v{len(s2)}"),
                        ))
                        break
                    if leaf == "concatenate" and \
                            not isinstance(axis, int):
                        continue
                    norm = axis % max(len(s1), 1) \
                        if s1 and isinstance(axis, int) else 0
                    for d, (da, db) in enumerate(zip(s1, s2)):
                        if leaf == "concatenate" and d == norm:
                            continue
                        if _lit_conflict(da, db):
                            findings.append(Finding(
                                rule="shape-mismatch",
                                path=src.relpath, line=node.lineno,
                                message=(
                                    f"{leaf}() operands disagree on "
                                    f"axis {d}: {da[1]} vs {db[1]} "
                                    "(inferred from their "
                                    "constructors)"
                                ),
                                key=(f"{module}:{qual}:{leaf}:"
                                     f"ax{d}:{da[1]}v{db[1]}"),
                            ))
                            break
            elif leaf == "where" and len(node.args) == 3:
                s2, s3 = known(node.args[1]), known(node.args[2])
                if s2 is None or s3 is None:
                    continue
                # broadcast: align trailing dims; lits conflict when
                # different and neither is 1
                for off in range(1, min(len(s2), len(s3)) + 1):
                    da, db = s2[-off], s3[-off]
                    if _lit_conflict(da, db) and \
                            1 not in (da[1], db[1]):
                        findings.append(Finding(
                            rule="shape-mismatch",
                            path=src.relpath, line=node.lineno,
                            message=(
                                "where() branches do not broadcast: "
                                f"trailing axis -{off} is {da[1]} vs "
                                f"{db[1]}"
                            ),
                            key=(f"{module}:{qual}:where:"
                                 f"{da[1]}v{db[1]}"),
                        ))
                        break
    return findings


# ===========================================================================
# rule: prewarm-coverage


def _reachable_jit_entries(files: list[SourceFile], graph: CallGraph,
                           jits_by_file: dict,
                           roots_registry: dict,
                           indirect: dict) -> set[tuple]:
    """(relpath, jit-name) entries whose compile a path from the
    registry roots can trigger. Traversal: the shared call graph,
    plus calls to jit-object names (edge to the jit AND into its
    wrapped function), plus declared indirect edges."""
    by_rel = {src.relpath: src for src in files}
    # qualname index for roots/indirect targets
    fn_index: dict[tuple, object] = {}
    for info in graph.functions():
        fn_index[(info.relpath, info.qualname)] = info

    def lookup(suffix: str, qual: str):
        for (rel, q), info in fn_index.items():
            if q == qual and rel.endswith(suffix):
                yield info

    queue = []
    for suffix, quals in roots_registry.items():
        for qual in quals:
            queue.extend(lookup(suffix, qual))
    # name -> jit maps, built ONCE per traversal (not per visited
    # function — the BFS below touches these for every popped node)
    local_jits_by_rel = {
        rel: {j.name: j for j in jits}
        for rel, jits in jits_by_file.items()
    }
    imported: dict[str, list] = {}
    for jits in jits_by_file.values():
        for j in jits:
            imported.setdefault(j.name, []).append(j)
    entries: set[tuple] = set()
    seen: set[int] = set()
    while queue:
        info = queue.pop()
        if id(info.node) in seen:
            continue
        seen.add(id(info.node))
        src = by_rel.get(info.relpath)
        # resolved call-graph edges
        queue.extend(graph.callees(info))
        # declared indirect edges
        for (suffix, qual), targets in indirect.items():
            if info.relpath.endswith(suffix) and \
                    info.qualname == qual:
                for (tsuffix, tqual) in targets:
                    queue.extend(lookup(tsuffix, tqual))
        # jit-object call edges (by bare or module-attr name, local or
        # imported)
        if src is None:
            continue
        local_jits = local_jits_by_rel.get(info.relpath, {})
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            for name in _call_target_names(node):
                jit = local_jits.get(name)
                cands = [jit] if jit else imported.get(name, [])
                for j in cands:
                    if j is None:
                        continue
                    if j.scope is not None and j.scope != \
                            info.qualname:
                        # factory-cached jits reach through their
                        # factory call, handled below
                        continue
                    entries.add((j.relpath, j.name))
                    _enter_wrapped(j, by_rel, graph, queue)
            # factory call (direct or call-of-call): entering the
            # factory function marks its nested jits
        # a function that IS a jit factory contributes its entries
        for j in jits_by_file.get(info.relpath, ()):
            if j.scope == info.qualname:
                entries.add((j.relpath, j.name))
                _enter_wrapped(j, by_rel, graph, queue)
    return entries


def _enter_wrapped(jit: JitObject, by_rel: dict, graph: CallGraph,
                   queue: list) -> None:
    src = by_rel.get(jit.relpath)
    if src is None or src.tree is None:
        return
    names = ([jit.wrapped] if jit.wrapped else []) + \
        list(jit.lambda_callees)
    for qual, fn in _functions(src.tree):
        if fn.name in names:
            info = graph.info_for_node(fn)
            if info is not None:
                queue.append(info)


def _check_prewarm_coverage(files: list[SourceFile], graph: CallGraph,
                            jits_by_file: dict) -> list[Finding]:
    # only run when a registered dispatch-root module is in the scan
    has_roots = any(
        src.relpath.endswith(suffix)
        for src in files for suffix in DISPATCH_ROOTS
    )
    if not has_roots:
        return []
    dispatch = _reachable_jit_entries(
        files, graph, jits_by_file, DISPATCH_ROOTS, PREWARM_INDIRECT)
    warmed = _reachable_jit_entries(
        files, graph, jits_by_file, PREWARM_ROOTS, PREWARM_INDIRECT)
    findings: list[Finding] = []
    for relpath, name in sorted(dispatch - warmed):
        module = relpath.rsplit("/", 1)[-1]
        line = next(
            (j.line for j in jits_by_file.get(relpath, ())
             if j.name == name), 1)
        findings.append(Finding(
            rule="prewarm-coverage",
            path=relpath, line=line,
            message=(
                f"jit root {name!r} is reachable from the sidecar "
                "dispatch loop but NOT from BucketLadder prewarm: "
                "its first dispatch pays a mid-serve XLA compile "
                "(20-40s on the real chip) — walk it in prewarm or "
                "route it through an already-warmed entry"
            ),
            key=f"{module}:{name}",
        ))
    return findings


# ===========================================================================
# the pure-python derivations the jitsan differentials pin
# (NO jax imports — (shape, dtype) descriptors only)


def _pow2_span(lo: int, hi: int) -> int:
    """How many doubling steps lie in [lo, hi] (inclusive), i.e. the
    rung count of a pow2 ladder — the same arithmetic BucketLadder
    enumerates, kept import-free here and cross-checked by
    tests/test_jitsan.py against the real enumeration."""
    if lo <= 0:
        # 0 never doubles past hi: the loop below would spin forever
        raise ValueError(f"pow2 ladder needs a positive floor: {lo}")
    n = 0
    v = lo
    while v <= hi:
        n += 1
        v *= 2
    return max(n, 1)


def ladder_bounds(window_floor: int, max_bucket: int,
                  capacity: int, max_capacity: int,
                  executor: str = "scan",
                  donate: bool = False,
                  pallas: bool = False,
                  pool_capacity: Optional[int] = None,
                  pool_rows: int = 1) -> dict[str, int]:
    """Static per-root compile-count bounds for a sidecar configured
    with this ladder: the number of distinct (window-bucket,
    capacity-rung) shapes each jit root can legally see when every
    dispatch rides the ladder. jitsan's observed signature counts
    must stay <= these — more means an unladdered call site
    compiled a shape the ladder does not contain (the recompile
    storm this family exists to stop)."""
    n_buckets = _pow2_span(window_floor, max_bucket)
    n_rungs = _pow2_span(capacity, max_capacity)
    shapes = n_buckets * n_rungs
    bounds = {
        # one program per (window bucket x capacity rung)
        "apply_window": shapes,
        "apply_window_pingpong": shapes if donate else 0,
        "chunked": shapes,
        "chunked_pingpong": shapes if donate else 0,
        "egwalker": shapes,
        "egwalker_pingpong": shapes if donate else 0,
        # one per capacity rung
        "compact": n_rungs,
        # one per rung TRANSITION
        "pad_capacity": max(n_rungs - 1, 0),
        "pallas": shapes if pallas else 0,
    }
    if executor == "scan":
        bounds["chunked"] = 0
        bounds["chunked_pingpong"] = 0
        bounds["egwalker"] = 0
        bounds["egwalker_pingpong"] = 0
    elif executor == "egwalker":
        # the walker covers critical prefixes; concurrent SUFFIXES
        # dispatch the PLAIN scan jit per rung x bucket (never the
        # ping-pong form — the suffix input is the walker stage's
        # live output), and prewarm walks both programs
        bounds["chunked"] = 0
        bounds["chunked_pingpong"] = 0
        bounds["apply_window_pingpong"] = 0
    else:
        bounds["apply_window"] = 0
        bounds["apply_window_pingpong"] = 0
        bounds["egwalker"] = 0
        bounds["egwalker_pingpong"] = 0
    if pool_capacity is not None:
        # MeshShardedPool jit roots (per-shard ladder x sharding
        # signatures): ``pool_rows`` is the largest per-shard row
        # bucket the run may reach, so the doc-shape ladder is the
        # pow2 span 1..pool_rows. Window buckets are the sidecar
        # ladder's span (pool tails come from the same serving
        # windows) plus the replay chunk bucket when it lies outside
        # it. Every shape compiles at most TWICE: once with fresh
        # NamedSharding placement (a rebuild's make_table) and once
        # with the committed sharding a pool-dispatch output carries
        # — the two input-sharding signatures prewarm walks.
        chunk = max(16, min(256, pool_capacity // 4))
        rb = _pow2_span(1, max(pool_rows, 1))
        n_windows = _pow2_span(window_floor, max_bucket)
        if not (window_floor <= chunk <= max_bucket):
            n_windows += 1
        bounds["mesh_pool"] = rb * n_windows * 2
        if executor in ("chunked", "egwalker"):
            # BOTH pool tiers route these executors through the
            # CHUNKED kernel on a degenerate mesh (the seq pool's
            # n_seq==1 fast path, the mesh pool's single-shard fast
            # path; an egwalker pool deliberately routes chunked —
            # pool dispatches are full-history replays): those
            # programs ride the shared merge_chunk jit cache at the
            # pool's own (row bucket x window/replay-chunk x sharding
            # signature) shapes, ON TOP of whatever the primary route
            # compiles there — without this allowance a correctly
            # laddered pooled egwalker sidecar would read as a
            # recompile storm (bounds['chunked'] == 0)
            bounds["chunked"] += rb * n_windows * 2
        # one gather program per pool table shape (x2 sharding sigs).
        # The migration handoff ALWAYS donates on backends that
        # support it (shard_moves.migrate_rows routes on the backend,
        # NOT on the sidecar donate flag — the handoff contract is
        # unconditional), so the donating form's bound must hold
        # regardless of `donate`: on CPU it stays cold (observed 0 <=
        # bound), on TPU it is the form every migration compiles
        bounds["mesh_move"] = rb * 2
        bounds["mesh_move_pingpong"] = rb * 2
        # compact follows every pool dispatch: one extra signature
        # per pool table shape rides the shared compact root
        bounds["compact"] += rb * 2
    return bounds


def infer_kernel_output(root: str, spec: dict,
                        new_capacity: Optional[int] = None) -> dict:
    """Abstract output signature of one kernel root.

    ``spec`` maps field name -> (shape tuple, dtype string) for the
    root's table/state input; the return value is the same structure
    for its output. The merge kernels are SHAPE- AND DTYPE-PRESERVING
    maps over the table by contract — the one exception is
    ``pad_capacity``, which widens the slot axis (axis 1) to
    ``new_capacity``. tests/test_jitsan.py asserts this against
    ``jax.eval_shape`` across every ladder rung, so an executor that
    silently stops preserving a shape or widens a dtype fails there
    BY NAME."""
    identity_roots = {
        "apply_window", "apply_window_pingpong", "chunked",
        "chunked_pingpong", "egwalker", "egwalker_pingpong",
        "compact", "seq_shard", "pallas",
    }
    if root in identity_roots:
        return {f: (tuple(shape), dtype)
                for f, (shape, dtype) in spec.items()}
    if root == "pad_capacity":
        if new_capacity is None:
            raise ValueError("pad_capacity needs new_capacity")
        old = spec["length"][0][1]
        out = {}
        for f, (shape, dtype) in spec.items():
            shape = tuple(shape)
            if len(shape) >= 2 and shape[1] == old:
                shape = shape[:1] + (new_capacity,) + shape[2:]
            out[f] = (shape, dtype)
        return out
    raise ValueError(f"unknown kernel root {root!r}")


# ===========================================================================
# entry point


def check(files: list[SourceFile], graph=None) -> list[Finding]:
    graph = graph or build_callgraph(files)
    idx = _Index(files, graph)
    jits_by_file: dict[str, list[JitObject]] = {}
    for src in files:
        if src.tree is None:
            continue
        aliases = import_aliases(src.tree, relative="skip")
        jits = collect_jit_objects(src, aliases)
        if jits:
            jits_by_file[src.relpath] = jits
    # one jit-reachability sweep shared by the dtype and shape rules
    reach = _jit_reachable_functions(files, graph)
    quals = _qual_index(files)
    findings = []
    findings += _check_donated(idx, jits_by_file)
    findings += _check_unladdered(idx, jits_by_file)
    findings += _check_dtype_widen(reach, quals)
    findings += _check_shape_mismatch(reach, quals)
    findings += _check_prewarm_coverage(files, graph, jits_by_file)
    return findings
