"""concheck — interprocedural concurrency analysis.

Every serious shipped bug so far (the PR2 ingress event-loop ack
stall, the PR1 broker/moira lock races) was a cross-module concurrency
bug invisible to single-module AST scans. This family walks the shared
call graph (analysis/callgraph.py) and enforces the three obligations
a mixed asyncio+threads service plane carries:

- **``lock-order-cycle``** — a repo-wide lock-acquisition-order graph:
  acquiring lock B while holding lock A (directly nested ``with``, or
  through any resolvable call chain) adds edge A->B; a cycle means two
  threads can each hold one lock of the pair while waiting on the
  other — a potential deadlock. Lock identity is (module, scope,
  attribute), the same class-level granularity the runtime sanitizer
  (testing/sanitizer.py) aggregates to, so the two halves compare.
- **``async-blocking-call``** — a blocking primitive (socket
  recv/sendall/accept, ``time.sleep``, file I/O, a blocking
  ``queue.Queue`` get/put, an ``Event.wait``, or acquiring a SLOW lock
  — one held across blocking I/O somewhere in the program) reachable
  from an ``async def`` in a drivers/service/qos path without an
  executor hop. Blocking the event loop stalls every connection the
  loop serves, not just the caller. ``run_in_executor`` /
  ``asyncio.to_thread`` naturally break reachability: the offloaded
  function is passed as an argument, never called from the coroutine.
- **``await-holding-lock``** — an ``await`` inside a ``with <threading
  lock>:`` body parks the coroutine at the await while the OS lock
  stays held; any thread (or any other coroutine on an executor
  thread) that wants the lock now waits on scheduler whim. Threading
  locks must never span a suspension point.

Known false-positive shapes (docs/ANALYSIS.md has the guidance):
fast locks (never held across blocking work) are deliberately NOT
blocking primitives, so ``metrics.Counter.inc`` style short critical
sections stay clean; receiver-typed checks (queue/event/socket
attributes) only fire when the attribute's constructor is visible to
the scope, so duck-typed injected dependencies are unresolved rather
than misflagged.

Call edges the graph cannot resolve syntactically (callbacks stored in
attributes) are declared in ``INDIRECT_CALLS`` below — a reviewed
registry, not a silent miss. The runtime sanitizer's differential test
(tests/test_sanitizer.py) enforces exactly this: every lock-order edge
observed at run time must be a subset of this pass's static edges, so
a missing resolution surfaces as a named analyzer-resolution gap.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .callgraph import CallGraph, FunctionInfo, build_callgraph
from .core import Finding, SourceFile, dotted_path as _dotted

LOCK_FACTORIES = {"threading.Lock", "threading.RLock"}

# receiver-typed blocking surfaces: constructor dotted path -> kind
TYPED_CTORS = {
    "queue.Queue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "queue.SimpleQueue": "queue",
    "threading.Event": "event",
    "threading.Condition": "event",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
}
TYPED_BLOCKING_METHODS = {
    "queue": {"get", "put", "join"},
    "event": {"wait", "wait_for"},
    "socket": {"connect", "makefile"},
}

# unconditionally blocking calls by dotted path (prefix match when the
# entry ends with a dot, exact-or-attr match otherwise)
BLOCKING_CALLS = (
    "time.sleep",
    "open",
    "io.open",
    "socket.create_connection",
    "socket.getaddrinfo",
    "os.replace",
    "os.makedirs",
    "os.listdir",
    "os.remove",
    "os.fsync",
    "os.rename",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "shutil.",
)

# attribute-call names distinctive enough to flag on ANY receiver
# (in this tree they only ever appear on sockets)
BLOCKING_METHODS_ALWAYS = {"recv", "recv_into", "recvfrom", "sendall",
                           "accept"}

# path components whose async defs are event-loop roots for the
# async-blocking-call rule (the serving planes; matches qoscheck's
# path-component scoping so tmp-dir fixtures exercise the rule)
ASYNC_SCOPE_COMPONENTS = {"drivers", "service", "qos"}

# Call edges real control flow takes but syntax cannot resolve: the
# (module-suffix, qualname) on the left stores a callable in an
# attribute (or receives one) and invokes it; the right lists where
# that control flow can land. Reviewed registry — the sanitizer
# differential test fails on any runtime lock-order edge these plus
# the resolvable edges do not cover.
INDIRECT_CALLS = {
    # The socket driver's dispatch thread delivers broadcasts while
    # holding ``self.lock``; the container's inbound path may issue
    # blocking requests from inside the callback (gap refetch calls
    # read_ops — deltaManager.ts:883), which re-enters _request/_send
    # and takes _pending_lock/_send_lock under self.lock.
    ("drivers/socket_driver.py", "SocketDocumentService._deliver"): (
        ("drivers/socket_driver.py", "SocketDocumentService._request"),
        ("drivers/socket_driver.py", "SocketDocumentService._send"),
    ),
}


@dataclasses.dataclass(frozen=True)
class LockId:
    relpath: str
    scope: str          # class name, or "<module>"
    attr: str

    def display(self) -> str:
        base = self.relpath.rsplit("/", 1)[-1]
        return f"{base}:{self.scope}.{self.attr}"


@dataclasses.dataclass
class LockInfo:
    lock_id: LockId
    creation_line: int
    kind: str           # "Lock" | "RLock"


@dataclasses.dataclass
class _Acq:
    lock: LockId
    held: frozenset
    line: int


@dataclasses.dataclass
class _Blocking:
    desc: str
    held: frozenset
    line: int


@dataclasses.dataclass
class _Call:
    node: ast.Call
    held: frozenset
    line: int


@dataclasses.dataclass
class _Await:
    held: frozenset     # locks held at the await
    line: int


@dataclasses.dataclass
class _FnFacts:
    info: FunctionInfo
    acquisitions: list
    blocking: list
    calls: list
    awaits: list


def _blocking_call_match(dotted: str) -> bool:
    return any(
        dotted == p or (p.endswith(".") and dotted.startswith(p))
        for p in BLOCKING_CALLS
    )


class _Scopes:
    """Lock + typed-attribute registries for one file."""

    def __init__(self, src: SourceFile, aliases: dict):
        self.src = src
        self.aliases = aliases
        # (scope, attr) -> LockInfo
        self.locks: dict = {}
        # (scope, attr) -> typed kind ("queue"/"event"/"socket")
        self.typed: dict = {}
        self._collect()

    def _ctor_kind(self, value: ast.AST) -> Optional[tuple]:
        if not isinstance(value, ast.Call):
            return None
        dotted = _dotted(value.func, self.aliases)
        if dotted is None:
            return None
        if dotted in LOCK_FACTORIES:
            return ("lock", dotted.rsplit(".", 1)[-1])
        kind = TYPED_CTORS.get(dotted)
        if kind is not None:
            return ("typed", kind)
        return None

    def _register(self, scope: str, target: ast.AST,
                  value: ast.AST, line: int) -> None:
        kind = self._ctor_kind(value)
        if kind is None:
            return
        attr = None
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            attr = target.attr
        elif isinstance(target, ast.Name) and scope == "<module>":
            attr = target.id
        if attr is None:
            return
        if kind[0] == "lock":
            self.locks[(scope, attr)] = LockInfo(
                LockId(self.src.relpath, scope, attr), line, kind[1])
        else:
            self.typed[(scope, attr)] = kind[1]

    def _collect(self) -> None:
        tree = self.src.tree

        def targets_of(stmt):
            if isinstance(stmt, ast.Assign):
                return stmt.targets, stmt.value
            if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                return [stmt.target], stmt.value
            return [], None

        for stmt in tree.body:
            targets, value = targets_of(stmt)
            for t in targets:
                self._register("<module>", t, value, stmt.lineno)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                targets, value = targets_of(sub)
                for t in targets:
                    self._register(node.name, t, value,
                                   getattr(sub, "lineno", 0))


class _FnWalker(ast.NodeVisitor):
    """Walk one function body tracking held locks; record
    acquisitions, blocking primitives, calls and awaits."""

    def __init__(self, info: FunctionInfo, scopes: _Scopes):
        self.info = info
        self.scopes = scopes
        self.held: frozenset = frozenset()
        self.facts = _FnFacts(info, [], [], [], [])
        # function-local typed receivers: name -> kind
        self.local_typed: dict = {}
        # nested-def facts, merged in finalize() ONLY when the owner
        # calls the closure by name: a closure merely PASSED somewhere
        # (run_in_executor(None, work)) runs on whatever thread the
        # receiver chooses, not on this function's path — folding its
        # body in unconditionally would flag the sanctioned executor
        # offload pattern itself
        self._nested: dict = {}
        self._called_names: set = set()

    # -- resolution helpers -------------------------------------------

    def _lock_of(self, expr: ast.AST) -> Optional[LockId]:
        cls = self.info.class_name
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and cls is not None:
            li = self.scopes.locks.get((cls, expr.attr))
            return li.lock_id if li else None
        if isinstance(expr, ast.Name):
            li = self.scopes.locks.get(("<module>", expr.id))
            return li.lock_id if li else None
        return None

    def _typed_kind(self, expr: ast.AST) -> Optional[str]:
        cls = self.info.class_name
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and cls is not None:
            return self.scopes.typed.get((cls, expr.attr))
        if isinstance(expr, ast.Name):
            return self.local_typed.get(expr.id) or \
                self.scopes.typed.get(("<module>", expr.id))
        return None

    # -- visitors -----------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        prev = self.held
        # items acquire LEFT TO RIGHT: in `with self.a, self.b:` the
        # b-acquisition already holds a, so the a->b order edge must
        # be recorded exactly as the single-item nested form would
        for item in node.items:
            self.visit(item.context_expr)
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                self.facts.acquisitions.append(
                    _Acq(lock, self.held, item.context_expr.lineno))
                self.held = self.held | frozenset((lock,))
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    visit_AsyncWith = visit_With

    def visit_Await(self, node: ast.Await) -> None:
        self.facts.awaits.append(_Await(self.held, node.lineno))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # function-local typed receivers: q = queue.Queue()
        kind = None
        if isinstance(node.value, ast.Call):
            dotted = _dotted(node.value.func, self.scopes.aliases)
            if dotted is not None:
                kind = TYPED_CTORS.get(dotted)
        if kind is not None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.local_typed[t.id] = kind
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func, self.scopes.aliases)
        desc = None
        if dotted is not None and _blocking_call_match(dotted):
            desc = dotted
        elif isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            if meth in BLOCKING_METHODS_ALWAYS:
                desc = f".{meth}"
            else:
                kind = self._typed_kind(node.func.value)
                if kind is not None and \
                        meth in TYPED_BLOCKING_METHODS[kind]:
                    desc = f".{meth}"
                elif meth == "acquire":
                    lock = self._lock_of(node.func.value)
                    if lock is not None:
                        # bare acquire(): treated like a with-entry
                        # (slow-lock logic decides if it blocks)
                        self.facts.acquisitions.append(
                            _Acq(lock, self.held, node.lineno))
        if desc is not None:
            self.facts.blocking.append(
                _Blocking(desc, self.held, node.lineno))
        if isinstance(node.func, ast.Name):
            self._called_names.add(node.func.id)
        self.facts.calls.append(_Call(node, self.held, node.lineno))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # a nested def executes when CALLED, not here: walk it with a
        # FRESH walker (empty held set — the closure may run on any
        # thread later) and merge its facts only if finalize() sees a
        # local call to it
        sub = _FnWalker(self.info, self.scopes)
        sub.local_typed = dict(self.local_typed)
        for stmt in node.body:
            sub.visit(stmt)
        self._nested.setdefault(node.name, []).append(sub)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # lambdas are (almost) always passed, not called in place —
        # treat like an uncalled closure and keep their bodies out
        # (an immediately-invoked lambda's blocking call is a
        # documented false negative)
        pass

    def finalize(self) -> "_FnWalker":
        """Merge the facts of nested defs the owner demonstrably
        calls (directly, or through another merged closure)."""
        merged: set = set()
        changed = True
        while changed:
            changed = False
            for name, subs in self._nested.items():
                if name not in self._called_names:
                    continue
                for sub in subs:
                    if id(sub) in merged:
                        continue
                    merged.add(id(sub))
                    changed = True
                    sub.finalize()
                    self.facts.acquisitions.extend(
                        sub.facts.acquisitions)
                    self.facts.blocking.extend(sub.facts.blocking)
                    self.facts.calls.extend(sub.facts.calls)
                    self.facts.awaits.extend(sub.facts.awaits)
                    self._called_names |= sub._called_names
        return self


class Analysis:
    """The shared interprocedural computation behind all three rules
    (and the lock-graph surface the sanitizer differential test
    compares against)."""

    def __init__(self, files: list, graph: Optional[CallGraph] = None):
        self.files = [f for f in files if f.tree is not None]
        self.graph = graph or build_callgraph(self.files)
        self.scopes: dict[str, _Scopes] = {}
        self.facts: dict[int, _FnFacts] = {}
        self.locks: dict[LockId, LockInfo] = {}
        # (LockId, LockId) -> witness (path, line, via)
        self.edges: dict = {}
        self._indirect: dict[int, list] = {}
        self._collect()
        self._propagate()

    # -- phase 1: per-function facts ----------------------------------

    def _collect(self) -> None:
        for src in self.files:
            scopes = _Scopes(
                src, self.graph.module_aliases(src.relpath))
            self.scopes[src.relpath] = scopes
            for (scope, attr), li in scopes.locks.items():
                self.locks[li.lock_id] = li
        for info in self.graph.functions():
            scopes = self.scopes.get(info.relpath)
            if scopes is None:
                continue
            walker = _FnWalker(info, scopes)
            for stmt in info.node.body:
                walker.visit(stmt)
            self.facts[id(info.node)] = walker.finalize().facts
        # resolve the INDIRECT_CALLS registry against real functions
        by_suffix: dict = {}
        for info in self.graph.functions():
            by_suffix.setdefault(
                (info.relpath, info.qualname), []).append(info)

        def find(suffix_key):
            return [
                info for (relpath, qual), infos in by_suffix.items()
                for info in infos
                if relpath.endswith(suffix_key[0])
                and qual == suffix_key[1]
            ]

        for src_key, dst_keys in INDIRECT_CALLS.items():
            for src_info in find(src_key):
                targets = []
                for dk in dst_keys:
                    targets.extend(find(dk))
                self._indirect[id(src_info.node)] = targets

    def _callees(self, info: FunctionInfo) -> list:
        return self.graph.callees(info) + \
            self._indirect.get(id(info.node), [])

    # -- phase 2: fixpoints -------------------------------------------

    def _transitive(self, direct: dict) -> dict:
        """Generic union-over-callees fixpoint: node-id -> set."""
        trans = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for info in self.graph.functions():
                cur = trans.setdefault(id(info.node), set())
                before = len(cur)
                for callee in self._callees(info):
                    cur |= trans.get(id(callee.node), set())
                if len(cur) != before:
                    changed = True
        return trans

    def _propagate(self) -> None:
        # locks transitively acquired when a function runs
        direct_acq = {
            fid: {a.lock for a in facts.acquisitions}
            for fid, facts in self.facts.items()
        }
        self.trans_acquired = self._transitive(direct_acq)

        # lock-order edges: direct nesting + held-at-call-site x
        # transitively-acquired-by-callee
        for fid, facts in self.facts.items():
            info = facts.info
            for acq in facts.acquisitions:
                for held in acq.held:
                    if held != acq.lock:
                        self.edges.setdefault(
                            (held, acq.lock),
                            (info.relpath, acq.line,
                             f"{info.qualname} acquires "
                             f"{acq.lock.display()} while holding "
                             f"{held.display()}"))
                    elif self.locks[acq.lock].kind == "Lock":
                        # re-acquiring a NON-reentrant Lock already
                        # held on this path is a self-deadlock; a
                        # self-edge makes it a one-lock cycle
                        self.edges.setdefault(
                            (acq.lock, acq.lock),
                            (info.relpath, acq.line,
                             f"{info.qualname} re-acquires "
                             f"{acq.lock.display()} it already "
                             "holds"))
            for call in facts.calls:
                if not call.held:
                    continue
                # _callees_at includes INDIRECT_CALLS targets: a
                # callback invoked at an unresolved call site inside a
                # registered function fires within the same held
                # regions its resolvable calls do
                for callee in self._callees_at(info, call):
                    self._edge_through(info, call, callee)

        # functions whose execution can block (directly or through
        # callees); slow locks iterate with it to fixpoint
        self.slow_locks: set = set()
        trans_blocking: dict = {}
        for _ in range(len(self.locks) + 1):
            direct = {}
            for fid, facts in self.facts.items():
                hits = {b.desc for b in facts.blocking}
                hits |= {
                    f"with {a.lock.display()}"
                    for a in facts.acquisitions
                    if a.lock in self.slow_locks
                }
                direct[fid] = hits
            trans_blocking = self._transitive(direct)
            new_slow = set(self.slow_locks)
            for fid, facts in self.facts.items():
                info = facts.info
                for b in facts.blocking:
                    new_slow |= b.held
                for call in facts.calls:
                    if not call.held:
                        continue
                    blocked = False
                    for callee in self._callees_at(info, call):
                        if trans_blocking.get(id(callee.node)):
                            blocked = True
                            break
                    if blocked:
                        new_slow |= call.held
            if new_slow == self.slow_locks:
                self.trans_blocking = trans_blocking
                break
            self.slow_locks = new_slow
        else:  # pragma: no cover - bounded by lock count
            self.trans_blocking = trans_blocking

    def _callees_at(self, info: FunctionInfo, call: _Call) -> list:
        out = self.graph.resolve_call(call.node, info, info.src)
        out.extend(self._indirect.get(id(info.node), []))
        return out

    def _edge_through(self, info: FunctionInfo, call: _Call,
                      callee: FunctionInfo) -> None:
        for lock in self.trans_acquired.get(id(callee.node), ()):
            for held in call.held:
                if held != lock:
                    self.edges.setdefault(
                        (held, lock),
                        (info.relpath, call.line,
                         f"{info.qualname} -> {callee.qualname}"))
                elif self.locks[lock].kind == "Lock":
                    self.edges.setdefault(
                        (lock, lock),
                        (info.relpath, call.line,
                         f"{info.qualname} -> {callee.qualname} "
                         f"re-acquires held {lock.display()}"))

    # -- the lock-graph surface (sanitizer differential) --------------

    def lock_edges_by_site(self) -> set:
        """Static edges keyed by lock CREATION SITE (relpath, line) —
        the identity the runtime sanitizer observes."""
        out = set()
        for (a, b) in self.edges:
            ia, ib = self.locks.get(a), self.locks.get(b)
            if ia is None or ib is None:
                continue
            out.add(((a.relpath, ia.creation_line),
                     (b.relpath, ib.creation_line)))
        return out


def _cycles(edges: dict) -> list:
    """Strongly-connected components of the lock graph with more than
    one lock (or a genuine self-edge, kept upstream only for
    non-reentrant locks)."""
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph[v], key=lambda x: x.display())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append(
                        (w, iter(sorted(graph[w],
                                        key=lambda x: x.display()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w is node:
                        break
                sccs.append(scc)

    for v in sorted(graph, key=lambda x: x.display()):
        if v not in index:
            strongconnect(v)
    out = []
    for scc in sccs:
        if len(scc) > 1:
            out.append(sorted(scc, key=lambda x: x.display()))
        elif (scc[0], scc[0]) in edges:
            out.append(scc)
    return out


def check(files: list, graph: Optional[CallGraph] = None) -> list:
    ana = Analysis(files, graph)
    findings: list = []

    # -- lock-order-cycle ---------------------------------------------
    for cycle in _cycles(ana.edges):
        members = set(cycle)
        names = sorted(lock.display() for lock in cycle)
        # every REAL edge inside the SCC, each with its witness —
        # the SCC's member list has no meaningful direction, the
        # edges do
        cyc_edges = sorted(
            ((a, b, ana.edges[(a, b)]) for (a, b) in ana.edges
             if a in members and b in members),
            key=lambda e: (e[0].display(), e[1].display()),
        )
        detail = "; ".join(
            f"{a.display()} -> {b.display()} ({via})"
            for a, b, (_p, _l, via) in cyc_edges
        )
        path, line, _via = cyc_edges[0][2]
        findings.append(Finding(
            rule="lock-order-cycle",
            path=path, line=line,
            message=(
                f"lock-acquisition-order cycle among {names}: "
                f"{detail} — two threads taking these locks in "
                "opposite orders deadlock; pick one global order and "
                "restructure the offending call path"
            ),
            key="cycle:" + "<->".join(names),
        ))

    # -- await-holding-lock -------------------------------------------
    for facts in ana.facts.values():
        info = facts.info
        if not info.is_async:
            continue
        module = info.relpath.rsplit("/", 1)[-1]
        seen = set()
        for aw in facts.awaits:
            for lock in sorted(aw.held, key=lambda x: x.display()):
                if (lock, info.qualname) in seen:
                    continue
                seen.add((lock, info.qualname))
                findings.append(Finding(
                    rule="await-holding-lock",
                    path=info.relpath, line=aw.line,
                    message=(
                        f"await inside `with {lock.display()}:` in "
                        f"{info.qualname}(): the coroutine parks with "
                        "the OS lock held — every thread wanting it "
                        "now waits on the event loop's schedule; "
                        "release before awaiting (or use an "
                        "asyncio.Lock)"
                    ),
                    # qualname, not bare name: same-named methods of
                    # two classes in one module must not share one
                    # allowlist key
                    key=f"{module}:{info.qualname}:{lock.attr}",
                ))

    # -- async-blocking-call ------------------------------------------
    def in_scope(relpath: str) -> bool:
        return bool(
            set(relpath.split("/")[:-1]) & ASYNC_SCOPE_COMPONENTS
        )

    roots = [
        info for info in ana.graph.functions()
        if info.is_async and in_scope(info.relpath)
    ]
    via: dict[int, str] = {}
    queue = []
    for r in roots:
        if id(r.node) not in via:
            via[id(r.node)] = r.qualname
            queue.append(r)
    while queue:
        info = queue.pop()
        for callee in ana._callees(info):
            if id(callee.node) not in via:
                via[id(callee.node)] = via[id(info.node)]
                queue.append(callee)

    reported = set()
    for fid, root_qual in via.items():
        facts = ana.facts.get(fid)
        if facts is None:
            continue
        info = facts.info
        module = info.relpath.rsplit("/", 1)[-1]
        hits = [
            (b.desc, b.desc.lstrip("."), b.line)
            for b in facts.blocking
        ]
        hits += [
            (f"acquisition of slow lock {a.lock.display()} (held "
             "across blocking I/O elsewhere in the program)",
             f"with-{a.lock.attr}", a.line)
            for a in facts.acquisitions if a.lock in ana.slow_locks
        ]
        for desc, keydesc, line in hits:
            dedupe = (info.relpath, info.qualname, keydesc)
            if dedupe in reported:
                continue
            reported.add(dedupe)
            findings.append(Finding(
                rule="async-blocking-call",
                path=info.relpath, line=line,
                message=(
                    f"blocking {desc} in {info.qualname}() is "
                    f"reachable from async {root_qual}(): it stalls "
                    "the event loop for every connection the loop "
                    "serves — hop through "
                    "loop.run_in_executor/asyncio.to_thread (or use "
                    "the asyncio-native primitive)"
                ),
                key=f"{module}:{info.qualname}:{keydesc}",
            ))
    return findings


def build_analysis(files: list,
                   graph: Optional[CallGraph] = None) -> Analysis:
    """The lock-graph surface for tooling and the sanitizer
    differential test."""
    return Analysis(files, graph)
