"""Shared fluidlint infrastructure: findings, suppressions, file
walking, the allowlist, and the pass registry."""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable, Optional

# comma-separated rule ids, optional spaces after commas; stops before
# any justification text ("rule-a, rule-b  -- why")
_RULE_LIST = re.compile(r"[\w-]+(?:\s*,\s*[\w-]+)*")

# repo root = parent of the fluidframework_tpu package
PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_ROOT)

# what the gate scans by default, relative to the repo root. layercheck
# only constrains modules inside the package (tests/ and examples/ are
# architecturally unconstrained); jaxhazards and lockcheck apply
# everywhere — a test that mutates a lock-guarded attribute without the
# lock is exactly the race shape the pass exists to catch.
DEFAULT_ROOTS = (
    "fluidframework_tpu",
    "tests",
    "examples",
    "bench.py",
    "__graft_entry__.py",
)

ALLOWLIST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "allowlist.txt"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit.

    ``key`` is the STABLE identity used by suppressions and the
    allowlist — rule-specific and line-number-free so entries survive
    unrelated edits (e.g. ``drivers->service`` for layercheck,
    ``ClassName.attr`` for lockcheck).
    """

    rule: str          # rule id, e.g. "lock-unlocked-write"
    path: str          # repo-relative posix path
    line: int
    message: str
    key: str

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    """A parsed python file plus its per-line suppressions."""

    def __init__(self, abspath: str, repo_root: str = REPO_ROOT):
        self.abspath = abspath
        self.relpath = os.path.relpath(abspath, repo_root).replace(
            os.sep, "/"
        )
        with open(abspath, encoding="utf-8") as f:
            self.source = f.read()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.source, filename=abspath)
        except SyntaxError as e:
            self.parse_error = e
        # line -> set of disabled rule ids; line 0 = whole file
        self.suppressions: dict[int, set[str]] = {}
        for i, text in enumerate(self.source.splitlines(), start=1):
            marker = "# fluidlint:"
            idx = text.find(marker)
            if idx < 0:
                continue
            directive = text[idx + len(marker):].strip()
            if directive.startswith("disable-file="):
                rules = directive[len("disable-file="):]
                scope = 0
            elif directive.startswith("disable="):
                rules = directive[len("disable="):]
                scope = i
            else:
                continue
            # the rule list is comma-separated ids (spaces after
            # commas allowed); it ends where the justification
            # comment the policy asks for begins ("disable=rule-a,
            # rule-b  -- why") — the trailing text must neither
            # poison a rule id nor be parsed as one
            m = _RULE_LIST.match(rules.lstrip())
            self.suppressions.setdefault(scope, set()).update(
                r.strip()
                for r in (m.group(0) if m else "").split(",")
                if r.strip()
            )

    def suppressed(self, rule: str, line: int) -> bool:
        for scope in (0, line):
            rules = self.suppressions.get(scope)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


def walk_python_files(roots: Iterable[str],
                      repo_root: str = REPO_ROOT) -> list[SourceFile]:
    out = []
    for root in roots:
        top = root if os.path.isabs(root) else os.path.join(
            repo_root, root
        )
        if not os.path.exists(top):
            # a typo'd path silently scanning nothing would report a
            # clean tree with exit 0 — fail loudly instead
            raise ValueError(f"no such file or directory: {root!r}")
        if os.path.isfile(top):
            if not top.endswith(".py"):
                raise ValueError(f"not a python file: {root!r}")
            out.append(SourceFile(top, repo_root))
            continue
        for dirpath, dirs, files in os.walk(top):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(
                        SourceFile(os.path.join(dirpath, f), repo_root)
                    )
    return out


def import_aliases(tree, relative: str = "tail") -> dict:
    """local name -> dotted origin, from every import in the module
    (function-local ones included: analyzed bodies may import
    locally). ONE definition shared by the pass families.

    ``relative`` controls ``from .x import y`` forms: ``"tail"``
    keeps the module tail (``..obs.trace`` -> ``obs.trace`` — the
    suffix-matching registries in obscheck/qoscheck need it);
    ``"skip"`` drops them (jaxhazards matches ABSOLUTE stdlib
    prefixes, where a relative ``..random`` tail colliding with the
    stdlib ``random.`` prefix would be a false positive)."""
    aliases: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.level > 0 and relative == "skip":
                continue
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_path(node, aliases: dict) -> Optional[str]:
    """Resolve a Name/Attribute chain to a dotted path with import
    aliases substituted; None for anything non-static (calls,
    subscripts). ONE definition — jaxhazards, obscheck, qoscheck and
    concheck all match registries against the same resolution."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def load_allowlist(path: str = ALLOWLIST_PATH) -> list[tuple[str, str]]:
    """Grandfathered findings: one ``<rule-id> <key>`` pair per line,
    ``#`` comments. The gate test enforces the ratchet: every entry
    must still match a live finding (stale entries fail the gate — the
    list only shrinks) and the total stays under the cap."""
    entries = []
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split(None, 1)
            if len(parts) != 2:
                raise ValueError(
                    f"malformed allowlist line {raw!r} "
                    "(expected '<rule-id> <key>')"
                )
            entries.append((parts[0], parts[1]))
    return entries


FAMILIES = ("layercheck", "jaxhazards", "lockcheck", "obscheck",
            "qoscheck", "concheck", "shapecheck", "detcheck",
            "wirecheck", "failcheck")

# rule id -> owning family: tooling that groups ONE combined run's
# findings per family (bench's fluidlint_findings records) reads
# this instead of re-running the analysis once per family. The gate
# test pins it complete against FAMILIES.
FAMILY_RULES = {
    "layercheck": ("layer-undeclared", "layer-cycle"),
    "jaxhazards": ("jit-nondeterminism", "jit-host-callback",
                   "jit-tracer-branch", "jit-static-unhashable",
                   "dispatch-loop-sync"),
    "lockcheck": ("lock-unlocked-write", "lock-external-write"),
    "obscheck": ("obs-untimed-hop", "slo-unbound-objective",
                 "undocumented-metric"),
    "qoscheck": ("service-unbounded-queue", "retry-without-jitter",
                 "fence-before-fanout", "unbounded-blocking-wait"),
    "concheck": ("lock-order-cycle", "async-blocking-call",
                 "await-holding-lock"),
    "shapecheck": ("donated-buffer-reuse", "unladdered-jit-shape",
                   "kernel-dtype-widen", "shape-mismatch",
                   "prewarm-coverage"),
    "detcheck": ("wall-clock-unrouted", "unseeded-rng",
                 "iteration-order-leak", "hash-order-dependence"),
    "wirecheck": ("encoder-decoder-drift",
                  "optional-field-unconditional-emit",
                  "ungated-wire-read", "unversioned-frame-field"),
    "failcheck": ("swallowed-exception",
                  "broad-except-in-dispatch-loop",
                  "exception-context-dropped", "return-in-finally"),
}
RULE_FAMILY = {
    rule: fam for fam, rules in FAMILY_RULES.items() for rule in rules
}


def run_analysis(roots: Iterable[str] = DEFAULT_ROOTS,
                 families: Iterable[str] = FAMILIES,
                 repo_root: str = REPO_ROOT,
                 ) -> list[Finding]:
    """Run the selected pass families; returns findings with per-line
    suppressions already applied (allowlist filtering is the caller's
    choice — the CLI and gate apply it, tooling may want raw)."""
    from . import (
        concurrency,
        determinism,
        failcheck,
        jaxhazards,
        layercheck,
        lockcheck,
        obscheck,
        qoscheck,
        shapecheck,
        wirecheck,
    )

    passes = {
        "layercheck": layercheck.check,
        "jaxhazards": jaxhazards.check,
        "lockcheck": lockcheck.check,
        "obscheck": obscheck.check,
        "qoscheck": qoscheck.check,
        "concheck": concurrency.check,
        "shapecheck": shapecheck.check,
        "detcheck": determinism.check,
        "wirecheck": wirecheck.check,
        "failcheck": failcheck.check,
    }
    unknown = [f for f in families if f not in passes]
    if unknown:
        raise ValueError(
            f"unknown rule families {unknown}; pick from {FAMILIES}"
        )
    files = walk_python_files(roots, repo_root)
    findings: list[Finding] = []
    by_path = {f.relpath: f for f in files}
    # one shared call graph per run: jaxhazards, concheck, shapecheck,
    # detcheck and wirecheck resolve through the same interprocedural
    # edges (and pay for the build once)
    GRAPH_FAMILIES = ("jaxhazards", "concheck", "shapecheck",
                      "detcheck", "wirecheck", "failcheck")
    shared_graph = None
    if set(GRAPH_FAMILIES) & set(families):
        from .callgraph import build_callgraph

        shared_graph = build_callgraph(files)
    for fam in families:
        if fam in GRAPH_FAMILIES:
            findings.extend(passes[fam](files, graph=shared_graph))
        else:
            findings.extend(passes[fam](files))
    kept = []
    for fnd in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        src = by_path.get(fnd.path)
        if src is not None and src.suppressed(fnd.rule, fnd.line):
            continue
        kept.append(fnd)
    return kept


def apply_allowlist(findings: list[Finding],
                    allowlist: list[tuple[str, str]],
                    ) -> tuple[list[Finding], list[tuple[str, str]]]:
    """Split findings into (non-allowlisted, stale-allowlist-entries).
    An entry matches any finding with the same (rule, key)."""
    allowed = set(allowlist)
    live = {(f.rule, f.key) for f in findings}
    kept = [f for f in findings if (f.rule, f.key) not in allowed]
    stale = [e for e in allowlist if e not in live]
    return kept, stale
