"""Differential tests: batched merge kernel vs scalar oracle on
identical sequenced streams (SURVEY §4, pillar (d)).

Every fuzz stream is applied both by a fresh oracle client (pure remote
apply) and by the kernel; final text and per-position property
signatures must match exactly.
"""
import numpy as np
import pytest

from fluidframework_tpu.models.mergetree import MergeTreeClient
from fluidframework_tpu.ops import (
    NOT_REMOVED,
    apply_window,
    build_batch,
    compact,
    encode_stream,
    extract_signature,
    extract_text,
    fetch,
    make_table,
)
from fluidframework_tpu.testing import FuzzConfig, record_op_stream


def oracle_replay(stream):
    """Fresh observer client applying the whole sequenced stream."""
    obs = MergeTreeClient("kernel-observer")
    obs.start_collaboration("kernel-observer")
    for msg in stream:
        obs.apply_msg(msg)
    return obs


def oracle_signature(obs, enc):
    """Observer's visible content with properties interned the same way
    the encoder interned them for the kernel."""
    from fluidframework_tpu.ops.host_bridge import interned_signature

    return interned_signature(obs, enc)


def run_kernel(streams, capacity=512):
    encs = [encode_stream(s) for s in streams]
    batch = build_batch(encs)
    table = make_table(len(encs), capacity)
    table = apply_window(table, batch)
    np_table = fetch(table)
    assert not np_table["overflow"].any(), "capacity overflow"
    return encs, np_table


def test_kernel_basic_insert_remove():
    from fluidframework_tpu.testing import MockCollabSession

    stream = []
    s = MockCollabSession(["A"], stream_log=stream)
    s.do("A", "insert_text_local", 0, "hello world")
    s.do("A", "remove_range_local", 5, 11)
    s.do("A", "insert_text_local", 5, "!")
    s.process_all()
    encs, np_table = run_kernel([stream])
    assert extract_text(np_table, encs[0], 0) == "hello!"


def test_kernel_concurrent_inserts_tiebreak():
    from fluidframework_tpu.testing import MockCollabSession

    stream = []
    s = MockCollabSession(["A", "B"], stream_log=stream)
    s.do("A", "insert_text_local", 0, "aaa")
    s.do("B", "insert_text_local", 0, "bbb")
    s.process_all()
    assert s.assert_converged() == "bbbaaa"
    encs, np_table = run_kernel([stream])
    assert extract_text(np_table, encs[0], 0) == "bbbaaa"


@pytest.mark.parametrize("seed", range(25))
def test_kernel_differential_fuzz(seed):
    text, stream = record_op_stream(FuzzConfig(
        n_clients=3, n_steps=120, seed=seed * 31 + 7,
        remove_weight=0.3, annotate_weight=0.15,
    ))
    encs, np_table = run_kernel([stream])
    assert extract_text(np_table, encs[0], 0) == text
    obs = oracle_replay(stream)
    assert extract_signature(np_table, encs[0], 0) == oracle_signature(
        obs, encs[0]
    )


def test_kernel_multidoc_batch():
    """Independent docs, one dispatch, padded window."""
    cases = [
        record_op_stream(FuzzConfig(n_clients=3, n_steps=80,
                                    seed=900 + i))
        for i in range(8)
    ]
    streams = [stream for _, stream in cases]
    encs, np_table = run_kernel(streams)
    for d, (text, _) in enumerate(cases):
        assert extract_text(np_table, encs[d], d) == text, f"doc {d}"


def test_kernel_compaction_preserves_content():
    text, stream = record_op_stream(FuzzConfig(
        n_clients=3, n_steps=150, seed=77, remove_weight=0.4,
    ))
    encs = [encode_stream(stream)]
    batch = build_batch(encs)
    table = make_table(1, 512)
    table = apply_window(table, batch)
    before = fetch(table)
    table = compact(table)
    after = fetch(table)
    assert extract_text(after, encs[0], 0) == text
    assert int(after["count"][0]) <= int(before["count"][0])
    # everything below the window is gone
    cnt = int(after["count"][0])
    removed = after["removed_seq"][0, :cnt]
    assert not ((removed != NOT_REMOVED)
                & (removed <= int(after["min_seq"][0]))).any()


def test_kernel_overflow_flag():
    text, stream = record_op_stream(FuzzConfig(n_clients=2, n_steps=60,
                                               seed=5))
    encs = [encode_stream(stream)]
    batch = build_batch(encs)
    table = make_table(1, 8)  # deliberately tiny
    table = apply_window(table, batch)
    assert int(fetch(table)["overflow"][0]) == 1
