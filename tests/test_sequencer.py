"""DocumentSequencer (deli ticket) unit tests.

Mirrors the reference's deli lambda tests
(server/routerlicious/packages/lambdas/src/test)."""
from fluidframework_tpu.protocol import (
    ClientDetail,
    DocumentMessage,
    MessageType,
    NackErrorType,
)
from fluidframework_tpu.service import DocumentSequencer


def op(csn, refseq, contents=None):
    return DocumentMessage(
        client_sequence_number=csn,
        reference_sequence_number=refseq,
        type=MessageType.OPERATION,
        contents=contents,
    )


def test_join_assigns_seq_and_msn():
    seq = DocumentSequencer("doc")
    join = seq.client_join(ClientDetail("A"))
    assert join.sequence_number == 1
    assert join.type == MessageType.CLIENT_JOIN
    assert join.minimum_sequence_number <= join.sequence_number


def test_ticket_stamps_monotone_seq():
    s = DocumentSequencer("doc")
    s.client_join(ClientDetail("A"))
    r1 = s.ticket("A", op(1, 1))
    r2 = s.ticket("A", op(2, 2))
    assert r1.ok and r2.ok
    assert r1.message.sequence_number == 2
    assert r2.message.sequence_number == 3
    assert r2.message.client_sequence_number == 2


def test_msn_is_min_refseq_over_clients():
    s = DocumentSequencer("doc")
    s.client_join(ClientDetail("A"))  # seq 1, A.refSeq = 1
    s.client_join(ClientDetail("B"))  # seq 2, B.refSeq = 2
    r = s.ticket("A", op(1, 1))  # seq 3; msn = min(1, 2) = 1
    assert r.message.minimum_sequence_number == 1
    r = s.ticket("B", op(1, 2))  # B.refSeq=2; msn = min(1,2) = 1
    assert r.message.minimum_sequence_number == 1
    r = s.ticket("A", op(2, 3))  # A.refSeq=3; msn = min(3,2) = 2
    assert r.message.minimum_sequence_number == 2


def test_msn_never_regresses_on_join_leave_churn():
    s = DocumentSequencer("doc")
    s.client_join(ClientDetail("A"))
    for i in range(5):
        s.ticket("A", op(i + 1, s.sequence_number))
    msn_before = s.minimum_sequence_number
    s.client_leave("A")
    j = s.client_join(ClientDetail("B"))
    assert j.minimum_sequence_number >= msn_before


def test_redundant_join_does_not_reset_sequencing_state():
    s = DocumentSequencer("doc")
    s.client_join(ClientDetail("A"))
    for i in range(3):
        assert s.ticket("A", op(i + 1, s.sequence_number)).ok
    s.client_join(ClientDetail("A"))  # at-least-once ingress retry
    replayed = s.ticket("A", op(1, s.sequence_number))  # old op replayed
    assert replayed.message is None and replayed.nack is None  # dropped
    fresh = s.ticket("A", op(4, s.sequence_number))
    assert fresh.ok


def test_unknown_client_nacked():
    s = DocumentSequencer("doc")
    r = s.ticket("ghost", op(1, 0))
    assert not r.ok
    assert r.nack.error_type == NackErrorType.BAD_REQUEST


def test_duplicate_csn_dropped_and_gap_nacked():
    s = DocumentSequencer("doc")
    s.client_join(ClientDetail("A"))
    assert s.ticket("A", op(1, 1)).ok
    dup = s.ticket("A", op(1, 1))  # duplicate: dropped, no nack
    assert dup.message is None and dup.nack is None
    gap = s.ticket("A", op(5, 1))  # gap: nacked
    assert gap.nack is not None


def test_stale_refseq_nacked():
    s = DocumentSequencer("doc")
    s.client_join(ClientDetail("A"))
    s.client_join(ClientDetail("B"))
    for i in range(10):
        s.ticket("A", op(i + 1, s.sequence_number))
    s.ticket("B", op(1, s.sequence_number))  # advance B so msn moves
    s.ticket("A", op(11, s.sequence_number))
    stale = s.ticket("B", op(2, 0))  # refSeq 0 < msn
    assert stale.nack is not None


def test_future_refseq_nacked():
    s = DocumentSequencer("doc")
    s.client_join(ClientDetail("A"))
    r = s.ticket("A", op(1, 99))
    assert r.nack is not None


def test_checkpoint_roundtrip():
    s = DocumentSequencer("doc")
    s.client_join(ClientDetail("A"))
    s.client_join(ClientDetail("B"))
    s.ticket("A", op(1, 1))
    s.ticket("B", op(1, 2))
    state = s.checkpoint()
    restored = DocumentSequencer.restore(state)
    r1 = s.ticket("A", op(2, 3))
    r2 = restored.ticket("A", op(2, 3))
    assert r1.message.sequence_number == r2.message.sequence_number
    assert (
        r1.message.minimum_sequence_number
        == r2.message.minimum_sequence_number
    )


def test_wire_timestamps_ride_the_injected_clock():
    """The sequencer's wire-visible timestamps (ticket stamps, system
    messages, trace hops) route through the injectable clock: two
    sequencers on the same manual clock produce byte-identical
    sequenced messages, so recorded corpora are stable per seed —
    not per wall time (the detcheck wall-clock-unrouted contract)."""
    from fluidframework_tpu.protocol.serialization import (
        message_to_json,
    )

    def run():
        t = {"v": 100.0}

        def clock():
            t["v"] += 0.25
            return t["v"]

        seq = DocumentSequencer("doc", clock=clock)
        # the join payload's ClientDetail carries its own (client-
        # side) timestamp: pinned explicitly, as a recording client
        # would
        out = [seq.client_join(
            ClientDetail(client_id="alice", timestamp=101.0))]
        msg = DocumentMessage(
            type=MessageType.OPERATION, contents={"op": 1},
            client_sequence_number=1, reference_sequence_number=0,
        )
        out.append(seq.ticket("alice", msg).message)
        out.append(seq.system_message(MessageType.NO_OP, None))
        return [message_to_json(m) for m in out]

    a, b = run(), run()
    assert a == b
    # and the stamps really came from the manual clock, not the wall
    assert all(rec["timestamp"] > 100.0 and rec["timestamp"] < 200.0
               for rec in a)


def test_checkpoint_restore_keeps_the_injected_clock():
    clock = lambda: 42.0  # noqa: E731
    seq = DocumentSequencer("doc", clock=clock)
    seq.client_join(ClientDetail(client_id="alice"))
    restored = DocumentSequencer.restore(seq.checkpoint(),
                                         clock=clock)
    msg = restored.system_message(MessageType.NO_OP, None)
    assert msg.timestamp == 42.0
