"""jitsan (testing/jitsan.py) — the runtime half of shapecheck — and
THE two differentials that pin the static analyzer to reality:

(a) observed XLA compile counts per jit root must stay <= the
    per-root bounds ``shapecheck.ladder_bounds`` derives from the
    BucketLadder (one extra = an unladdered shape reached a kernel);
(b) ``shapecheck.infer_kernel_output``'s abstract output signatures
    must EQUAL ``jax.eval_shape`` for every real kernel root across
    every ladder rung — an abstract-interpreter gap fails here by
    name, never silently.

Plus the donation read-traps (the runtime form of
``donated-buffer-reuse``) and the prewarm-coverage runtime pin:
after ``prewarm()``, in-ladder serving traffic — including grow
recovery and pool admission — compiles NOTHING new.
"""
import numpy as np
import pytest

import jax

from fluidframework_tpu.analysis.shapecheck import (
    _pow2_span,
    infer_kernel_output,
    ladder_bounds,
)
from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.obs import metrics as obs_metrics
from fluidframework_tpu.ops import make_table
from fluidframework_tpu.ops.bucket_ladder import BucketLadder
from fluidframework_tpu.ops.segment_table import KIND_NOOP, OpBatch
from fluidframework_tpu.service import LocalServer, TpuMergeSidecar
from fluidframework_tpu.service.tpu_sidecar import _pack_rows
from fluidframework_tpu.testing import jitsan

NOOP = dict(
    kind=KIND_NOOP, pos1=0, pos2=0, seq=0, refseq=0, client=0,
    op_id=0, length=0, is_marker=0, prop_key=0, prop_val=0, min_seq=0,
)


@pytest.fixture()
def sanitizer():
    jitsan.install()
    jitsan.reset()
    yield jitsan
    # deliberate trips belong to the test that made them, not to the
    # session-wide conftest guard
    jitsan.reset()
    jitsan.uninstall()


@pytest.fixture()
def cold_mesh_caches(monkeypatch):
    """Fresh jit caches for the mesh-pool roots: any earlier test in
    the process that drove the same (mesh, ladder) shapes leaves the
    module caches warm, and a warm-cache run observes ZERO new
    compiles — which would make the non-vacuity asserts below fail
    (and the bound differential vacuous) depending on suite order."""
    from fluidframework_tpu.ops import shard_moves
    from fluidframework_tpu.parallel import mesh_pool as mp

    # jit caches key on FUNCTION IDENTITY: re-jitting the same impl
    # inherits the warm signatures, so each replacement jit wraps a
    # fresh function object to start genuinely cold
    def _fresh_take(table, idx):
        return shard_moves._take_rows_impl(table, idx)

    def _fresh_migrate(table, idx):
        return shard_moves._take_rows_impl(table, idx)

    monkeypatch.setattr(mp, "_compiled_cache", {})
    monkeypatch.setattr(
        shard_moves, "_take_rows_jit", jax.jit(_fresh_take))
    monkeypatch.setattr(
        shard_moves, "_migrate_rows_donating",
        jax.jit(_fresh_migrate, donate_argnums=(0,)))
    jitsan.reset()  # baseline the fresh (empty) caches


def _batch(docs: int, bucket: int) -> OpBatch:
    return OpBatch(**_pack_rows(docs, {0: [NOOP]}, bucket_floor=bucket))


def _drive(server, sidecar, doc: str, n: int = 24,
           chunk: str = "abcdefgh"):
    """Frequent-flush writer traffic: windows stay under the ladder's
    max_bucket (one flush per apply), segments churn via removes."""
    factory = LocalDocumentServiceFactory(server)
    sidecar.subscribe(server, doc, "d", "s")
    c = Container.load(factory.create_document_service(doc),
                       client_id=f"{doc}-writer")
    s = c.runtime.create_datastore("d").create_channel(
        "sharedstring", "s")
    for i in range(n):
        s.insert_text(0, chunk)
        c.flush()
        if i % 3 == 2 and s.get_length() > 6:
            s.remove_text(2, 5)
            c.flush()
        sidecar.apply()
    sidecar.sync()
    return c, s


# ======================================================================
# differential (a): compile counts <= the static ladder bounds


def test_compile_counts_within_ladder_bounds_scan_route(sanitizer):
    """A prewarmed sidecar driven through real traffic — including an
    overflow regrow up the capacity ladder — compiles at most the
    shapes shapecheck derives from the BucketLadder, per root."""
    ladder = BucketLadder(window_floor=16, max_bucket=32)
    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=16, max_capacity=64, executor="scan",
        donate=False, ladder=ladder,
    )
    sidecar.prewarm()
    server = LocalServer()
    _drive(server, sidecar, "doc")
    assert sidecar.grow_count >= 1, "traffic must exercise a regrow"
    counts = sanitizer.compile_counts()
    bounds = ladder_bounds(16, 32, 16, 64, executor="scan",
                           donate=False)
    for root, bound in bounds.items():
        assert counts[root] <= bound, (
            f"{root}: {counts[root]} compiles > static ladder bound "
            f"{bound} — an unladdered shape reached the kernel"
        )
    assert counts["apply_window"] > 0  # the bound check is not vacuous


def test_compile_counts_within_ladder_bounds_chunked_route(sanitizer):
    ladder = BucketLadder(window_floor=16, max_bucket=32)
    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=16, max_capacity=64, executor="chunked",
        donate=False, ladder=ladder,
    )
    sidecar.prewarm()
    server = LocalServer()
    _drive(server, sidecar, "doc")
    counts = sanitizer.compile_counts()
    bounds = ladder_bounds(16, 32, 16, 64, executor="chunked",
                           donate=False)
    for root, bound in bounds.items():
        assert counts[root] <= bound, (root, counts[root], bound)
    assert counts["chunked"] > 0
    assert counts["apply_window"] == 0  # the scan jit stayed cold


def test_compile_counts_within_ladder_bounds_egwalker_route(sanitizer):
    """The third executor route: a prewarmed egwalker sidecar driven
    through real traffic — including an overflow regrow — compiles at
    most the shapes shapecheck derives per root. The walker jits AND
    the plain scan jit (the concurrent-suffix program) both stay
    within bounds; the chunked roots stay cold."""
    ladder = BucketLadder(window_floor=16, max_bucket=32)
    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=16, max_capacity=64, executor="egwalker",
        donate=False, ladder=ladder,
    )
    sidecar.prewarm()
    server = LocalServer()
    _drive(server, sidecar, "doc")
    assert sidecar.grow_count >= 1, "traffic must exercise a regrow"
    counts = sanitizer.compile_counts()
    bounds = ladder_bounds(16, 32, 16, 64, executor="egwalker",
                           donate=False)
    for root, bound in bounds.items():
        assert counts[root] <= bound, (
            f"{root}: {counts[root]} compiles > static ladder bound "
            f"{bound} — an unladdered shape reached the kernel"
        )
    assert counts["egwalker"] > 0  # the bound check is not vacuous
    assert counts["chunked"] == 0  # the chunked jits stayed cold


def test_prewarm_covers_egwalker_serving_compiles(sanitizer):
    """After prewarm, in-ladder egwalker traffic (incl. grow
    recovery) pays ZERO mid-serve compiles — including the scan
    SUFFIX program a concurrent window would dispatch, which an
    all-noop prewarm window can never reach through the graph (the
    prewarm walk compiles it explicitly)."""
    ladder = BucketLadder(window_floor=16, max_bucket=32)
    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=16, max_capacity=64, executor="egwalker",
        donate=False, ladder=ladder,
    )
    sidecar.prewarm()
    jitsan.reset()
    server = LocalServer()
    _drive(server, sidecar, "doc")
    assert sidecar.grow_count >= 1
    # a genuinely CONCURRENT window (two blind writers) exercises the
    # suffix route too — prewarm must already have compiled it
    from fluidframework_tpu.models.mergetree.ops import InsertOp
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    sidecar.track("conc", "d", "s")
    for seq, refseq, cli in [(1, 0, "a"), (2, 0, "b"), (3, 0, "c")]:
        sidecar.ingest("conc", SequencedMessage(
            client_id=cli, sequence_number=seq,
            minimum_sequence_number=0, client_sequence_number=1,
            reference_sequence_number=refseq,
            type=MessageType.OPERATION,
            contents={"kind": "op", "address": "d", "channel": "s",
                      "contents": InsertOp(pos1=0, text="zz")},
        ))
    sidecar.apply()
    sidecar.sync()
    counts = sanitizer.compile_counts()
    assert all(n == 0 for n in counts.values()), (
        f"mid-serve compiles after prewarm: "
        f"{ {r: n for r, n in counts.items() if n} }"
    )


def test_egwalker_bounds_arithmetic():
    """The egwalker route's bound shape: walker roots get the full
    (window bucket x capacity rung) ladder, the suffix rides the
    PLAIN scan root (never its ping-pong form), chunked roots are
    zero — and the other routes pin the egwalker roots to zero."""
    b = ladder_bounds(16, 32, 16, 64, executor="egwalker")
    shapes = b["egwalker"]
    assert shapes > 0
    assert b["apply_window"] == shapes
    assert b["apply_window_pingpong"] == 0
    assert b["egwalker_pingpong"] == 0  # donate off
    assert b["chunked"] == b["chunked_pingpong"] == 0
    donating = ladder_bounds(16, 32, 16, 64, executor="egwalker",
                             donate=True)
    assert donating["egwalker_pingpong"] == shapes
    assert donating["apply_window_pingpong"] == 0  # suffix stays plain
    for other in ("scan", "chunked"):
        cold = ladder_bounds(16, 32, 16, 64, executor=other)
        assert cold["egwalker"] == cold["egwalker_pingpong"] == 0
    # a POOLED egwalker (or chunked) sidecar routes pool dispatches
    # through the chunked kernel on a degenerate mesh — the bound
    # must grant the pool's chunked programs instead of reading a
    # correctly laddered sidecar as a recompile storm
    pooled = ladder_bounds(16, 32, 16, 64, executor="egwalker",
                           pool_capacity=64, pool_rows=1)
    assert pooled["chunked"] > 0
    assert pooled["chunked"] == ladder_bounds(
        16, 32, 16, 64, executor="chunked",
        pool_capacity=64, pool_rows=1,
    )["chunked"] - ladder_bounds(16, 32, 16, 64,
                                 executor="chunked")["chunked"]
    scan_pooled = ladder_bounds(16, 32, 16, 64, executor="scan",
                                pool_capacity=64, pool_rows=1)
    assert scan_pooled["chunked"] == 0  # scan pools ride seq_shard


@pytest.fixture
def cold_route_caches(monkeypatch):
    """Fresh chunked/egwalker factory caches: both fill with FRESH
    lambdas on miss, so an emptied dict yields genuinely cold
    compiles — suite-order warm caches otherwise make cache-delta
    non-vacuity asserts flaky (the cold_mesh_caches precedent)."""
    from fluidframework_tpu.ops import event_graph, merge_chunk

    monkeypatch.setattr(merge_chunk, "_jit_cache", {})
    monkeypatch.setattr(merge_chunk, "_jit_pingpong_cache", {})
    monkeypatch.setattr(event_graph, "_jit_cache", {})
    monkeypatch.setattr(event_graph, "_jit_pingpong_cache", {})
    jitsan.reset()  # baseline the fresh (empty) caches


def test_pooled_egwalker_compile_counts_within_ladder_bounds(
        sanitizer, cold_route_caches):
    """The runtime half of the pooled-route bound: an egwalker
    sidecar whose documents overflow into a degenerate seq pool
    compiles chunked POOL programs (the deliberate egwalker->chunked
    pool routing) and still stays within ladder_bounds per root."""
    from fluidframework_tpu.parallel.seq_shard import make_seq_mesh

    mesh = make_seq_mesh(jax.devices()[:1], doc_shards=1)
    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=16, max_capacity=16, executor="egwalker",
        donate=False, seq_mesh=mesh, pool_capacity=64,
        ladder=BucketLadder(16, 16),
    )
    sidecar.prewarm()
    server = LocalServer()
    _, s = _drive(server, sidecar, "doc", n=24)
    assert sidecar.pooled_docs() == 1, "traffic must exercise the pool"
    assert sidecar.text("doc", "d", "s") == s.get_text()
    counts = sanitizer.compile_counts()
    bounds = ladder_bounds(16, 16, 16, 16, executor="egwalker",
                           donate=False, pool_capacity=64,
                           pool_rows=1)
    for root, bound in bounds.items():
        assert counts[root] <= bound, (root, counts[root], bound)
    # non-vacuity (cold caches): the pool's chunked programs AND the
    # primary window's walker programs both actually compiled
    assert counts["chunked"] > 0
    assert counts["egwalker"] > 0


def test_ladder_arithmetic_matches_the_real_enumeration():
    """shapecheck keeps the ladder arithmetic import-free
    (_pow2_span); this pins it to the real BucketLadder enumeration
    so the two can never drift."""
    for floor, top in ((16, 16), (16, 64), (16, 128), (8, 64)):
        assert _pow2_span(floor, top) == len(
            BucketLadder(floor, top).window_buckets())
    for base, top in ((16, 16), (16, 512), (32, 64)):
        assert _pow2_span(base, top) == len(
            BucketLadder.capacity_rungs(base, top))
    # a non-positive floor never doubles past the top: raise instead
    # of spinning forever (a misread config used to hang the caller)
    with pytest.raises(ValueError, match="positive floor"):
        _pow2_span(0, 64)
    with pytest.raises(ValueError, match="positive floor"):
        ladder_bounds(16, 64, 0, 64)


def test_mesh_pool_compile_counts_within_ladder_bounds(
        sanitizer, cold_mesh_caches):
    """The mesh-pool route under differential (a): an UN-prewarmed
    2-shard mesh-pool sidecar driven through admission, incremental
    dispatch, and a live migration compiles at most the shapes
    ladder_bounds derives for the mesh_pool/mesh_move roots (per-
    shard row-bucket ladder x window buckets x sharding signatures),
    and the pool's compact signatures stay inside the extended
    compact bound."""
    from fluidframework_tpu.parallel import make_mesh

    ladder = BucketLadder(window_floor=16, max_bucket=32)
    sidecar = TpuMergeSidecar(
        max_docs=6, capacity=16, max_capacity=16, executor="scan",
        donate=False, ladder=ladder,
        seq_mesh=make_mesh(jax.devices()[:2]), pool_capacity=256,
    )
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    docs = {}
    for i in range(3):
        doc = f"doc-{i}"
        sidecar.subscribe(server, doc, "d", "s")
        c = Container.load(factory.create_document_service(doc),
                           client_id=f"{doc}-w")
        s = c.runtime.create_datastore("d").create_channel(
            "sharedstring", "s")
        for _ in range(20):
            s.insert_text(0, "abcdefgh")
            c.flush()
        docs[doc] = (c, s)
    sidecar.apply()
    sidecar.sync()
    assert sidecar.pooled_docs() == 3
    # hot-spot traffic (windows stay under max_bucket per settle)
    for _ in range(5):
        for doc, (c, s) in docs.items():
            n = 10 if doc == "doc-0" else 1
            for _ in range(n):
                s.insert_text(0, "XY")
            c.flush()
        sidecar.apply()
        sidecar.sync()
    assert sidecar._pool.migration_count > 0, (
        "traffic must exercise a migration")
    counts = sanitizer.compile_counts()
    bounds = ladder_bounds(
        16, 32, 16, 16, executor="scan", donate=False,
        pool_capacity=256, pool_rows=sidecar._pool.rows_per_shard,
    )
    for root in ("mesh_pool", "mesh_move", "mesh_move_pingpong",
                 "compact"):
        assert counts[root] <= bounds[root], (
            f"{root}: {counts[root]} compiles > static ladder bound "
            f"{bounds[root]} — an unladdered shape reached the "
            "mesh-pool route"
        )
    assert counts["mesh_pool"] > 0    # the bound check is not vacuous
    assert counts["mesh_move"] > 0    # the migration gather ran


def test_mesh_pool_bounds_arithmetic():
    """The mesh-pool bound formula pinned: row buckets x window
    buckets (+ the replay chunk rung when outside the ladder span) x
    the two input-sharding signatures."""
    bounds = ladder_bounds(16, 32, 16, 64, executor="scan",
                           donate=False, pool_capacity=256,
                           pool_rows=2)
    rb = _pow2_span(1, 2)             # 2
    n_windows = _pow2_span(16, 32) + 1  # chunk=64 outside [16, 32]
    assert bounds["mesh_pool"] == rb * n_windows * 2
    assert bounds["mesh_move"] == rb * 2
    # the migration handoff donates by BACKEND, not by the sidecar
    # donate flag (shard_moves.migrate_rows), so the donating form's
    # bound holds even with donate=False — on TPU every migration
    # compiles it while CPU CI leaves it cold
    assert bounds["mesh_move_pingpong"] == rb * 2
    assert bounds["compact"] == _pow2_span(16, 64) + rb * 2
    donating = ladder_bounds(16, 32, 16, 64, donate=True,
                             pool_capacity=256, pool_rows=2)
    assert donating["mesh_move_pingpong"] == rb * 2
    # no pool attached -> no mesh roots in the bound map
    assert "mesh_pool" not in ladder_bounds(16, 32, 16, 64)


def test_prewarm_covers_mesh_pool_admission_compiles(
        sanitizer, cold_mesh_caches):
    """With a docs mesh attached, prewarm walks the mesh pool's
    dispatch programs (both window floors x both sharding signatures
    + the migration gather), so the FIRST pool admission and its
    incremental tails compile NOTHING mid-serve."""
    from fluidframework_tpu.parallel import make_mesh

    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=16, max_capacity=16, executor="scan",
        donate=False, seq_mesh=make_mesh(jax.devices()[:2]),
        pool_capacity=64, ladder=BucketLadder(16, 16),
    )
    sidecar.prewarm()
    jitsan.reset()
    server = LocalServer()
    _, s = _drive(server, sidecar, "doc", n=24)
    assert sidecar.pooled_docs() == 1, "traffic must exercise the pool"
    assert sidecar.text("doc", "d", "s") == s.get_text()
    counts = sanitizer.compile_counts()
    assert all(n == 0 for n in counts.values()), (
        f"mid-serve compiles after prewarm: "
        f"{ {r: n for r, n in counts.items() if n} }"
    )


# ======================================================================
# differential (b): abstract output signatures == jax.eval_shape


def _sig_of(tree) -> dict:
    if hasattr(tree, "_fields"):
        items = zip(tree._fields, tree)
    else:
        items = tree.items()
    return {f: (tuple(a.shape), str(a.dtype)) for f, a in items}


RUNGS = (32, 64, 128)
BUCKETS = (16, 32)


@pytest.mark.parametrize("rung", RUNGS)
@pytest.mark.parametrize("bucket", BUCKETS)
def test_static_signatures_match_eval_shape_scan(rung, bucket):
    from fluidframework_tpu.ops.merge_kernel import (
        apply_window_impl,
        compact,
    )

    table = make_table(4, rung)
    spec = _sig_of(table)
    batch = _batch(4, bucket)
    out = jax.eval_shape(apply_window_impl, table, batch)
    assert infer_kernel_output("apply_window", spec) == _sig_of(out)
    out = jax.eval_shape(compact, table)
    assert infer_kernel_output("compact", spec) == _sig_of(out)


@pytest.mark.parametrize("rung", RUNGS[:-1])
def test_static_signatures_match_eval_shape_pad_capacity(rung):
    from fluidframework_tpu.ops.merge_kernel import pad_capacity

    table = make_table(4, rung)
    spec = _sig_of(table)
    out = jax.eval_shape(lambda t: pad_capacity(t, rung * 2), table)
    assert infer_kernel_output(
        "pad_capacity", spec, new_capacity=rung * 2) == _sig_of(out)


@pytest.mark.parametrize("rung", RUNGS)
@pytest.mark.parametrize("bucket", BUCKETS)
def test_static_signatures_match_eval_shape_chunked(rung, bucket):
    from fluidframework_tpu.ops.merge_chunk import (
        CHUNK_FIELDS,
        _chunk_state,
        _window_loop,
        build_chunked,
    )
    import jax.numpy as jnp

    st = _chunk_state(make_table(4, rung))
    spec = {f: (tuple(a.shape), str(a.dtype)) for f, a in st.items()}
    chunked = build_chunked(_batch(4, bucket), K=8)
    ops_w = {f: jnp.asarray(chunked[f])
             for f in OpBatch._fields + CHUNK_FIELDS}
    out = jax.eval_shape(lambda s, o: _window_loop(s, o, 8), st, ops_w)
    assert infer_kernel_output("chunked", spec) == _sig_of(out)


def test_static_signatures_match_eval_shape_egwalker():
    """Differential (b) for the walker root: shapecheck's abstract
    (shape, dtype) signature == jax.eval_shape for the egwalker
    macro-step loop across a rung x bucket sample."""
    import jax.numpy as jnp

    from fluidframework_tpu.ops.event_graph import (
        EG_K,
        _walker_loop,
        build_event_graph,
    )
    from fluidframework_tpu.ops.merge_chunk import (
        CHUNK_FIELDS,
        _chunk_state,
    )
    import numpy as np

    for rung, bucket in ((16, 16), (64, 32)):
        st = _chunk_state(make_table(4, rung))
        spec = {f: (tuple(a.shape), str(a.dtype))
                for f, a in st.items()}
        arrays = {f: np.array(getattr(_batch(4, bucket), f), np.int32)
                  for f in OpBatch._fields}
        prefix = build_event_graph(arrays)["prefix"]
        ops_w = {f: jnp.asarray(prefix[f])
                 for f in OpBatch._fields + CHUNK_FIELDS}
        out = jax.eval_shape(
            lambda s, o: _walker_loop(s, o, EG_K), st, ops_w)
        assert infer_kernel_output("egwalker", spec) == _sig_of(out)


@pytest.mark.parametrize("rung", RUNGS)
def test_static_signatures_match_eval_shape_seq_shard(rung):
    from fluidframework_tpu.parallel.seq_shard import (
        apply_window_seq_sharded,
        make_seq_mesh,
    )

    mesh = make_seq_mesh(jax.devices()[:2], doc_shards=1)
    table = make_table(4, rung)
    spec = _sig_of(table)
    out = jax.eval_shape(
        lambda t, b: apply_window_seq_sharded(t, b, mesh),
        table, _batch(4, 16),
    )
    assert infer_kernel_output("seq_shard", spec) == _sig_of(out)


@pytest.mark.parametrize("rung", (128, 256))
def test_static_signatures_match_eval_shape_pallas(rung):
    from fluidframework_tpu.ops import pallas_merge
    from fluidframework_tpu.ops.merge_step import (
        OP_COLS,
        table_to_state,
    )
    import jax.numpy as jnp

    state = table_to_state(make_table(8, rung))
    spec = {f: (tuple(a.shape), str(a.dtype))
            for f, a in state.items()}
    arrays = _pack_rows(8, {0: [NOOP]}, bucket_floor=16)
    ops = {f: jnp.asarray(arrays[f]).astype(jnp.int32)
           for f in OP_COLS}
    out = jax.eval_shape(pallas_merge._pallas_call, state, ops)
    assert infer_kernel_output("pallas", spec) == _sig_of(out)


def test_infer_kernel_output_rejects_unknown_root():
    with pytest.raises(ValueError, match="unknown kernel root"):
        infer_kernel_output("warp_drive", {})
    with pytest.raises(ValueError, match="new_capacity"):
        infer_kernel_output("pad_capacity", {})


# ======================================================================
# donation read-traps


def test_donated_table_reads_trap_on_any_backend(sanitizer):
    """apply_window_pingpong consumes its ``dead`` argument; jitsan
    makes a later read raise even on CPU, where XLA would silently
    ignore the donation and the bug would only detonate on-chip."""
    from fluidframework_tpu.ops.merge_kernel import (
        apply_window_pingpong,
    )

    table = make_table(2, 32)
    dead = make_table(2, 32)
    out = apply_window_pingpong(dead, table, _batch(2, 16))
    assert [e.root for e in sanitizer.donation_events()] == [
        "apply_window_pingpong"]
    with pytest.raises(RuntimeError, match="deleted"):
        # the deliberate post-donation read the trap exists to catch
        np.asarray(dead.length)  # fluidlint: disable=donated-buffer-reuse
    # the live input and the output stay readable
    np.asarray(table.length)
    np.asarray(out.length)
    assert sanitizer.trips() == []


def test_donated_chunked_state_reads_trap(sanitizer):
    from fluidframework_tpu.ops.merge_chunk import (
        apply_window_chunked_pingpong,
        build_chunked,
    )

    table = make_table(2, 32)
    dead = make_table(2, 32)
    out = apply_window_chunked_pingpong(
        dead, table, build_chunked(_batch(2, 16), K=8), K=8)
    assert [e.root for e in sanitizer.donation_events()] == [
        "chunked_pingpong"]
    with pytest.raises(RuntimeError, match="deleted"):
        # the deliberate post-donation read the trap exists to catch
        np.asarray(dead.seq)  # fluidlint: disable=donated-buffer-reuse
    np.asarray(out.length)
    # dead=None is the explicit plain-dispatch opt-out: no trap
    jitsan.reset()
    apply_window_chunked_pingpong(
        None, table, build_chunked(_batch(2, 16), K=8), K=8)
    assert sanitizer.donation_events() == []


def test_donated_egwalker_fodder_reads_trap(sanitizer):
    """The walker route's double-buffer contract: fodder donated to
    apply_window_egwalker_pingpong becomes a read-trap on ANY
    backend (CPU ignores donation; on-chip it is consumed)."""
    import numpy as onp

    from fluidframework_tpu.ops.event_graph import (
        apply_window_egwalker_pingpong,
        build_event_graph,
    )

    arrays = {f: onp.array(getattr(_batch(2, 16), f), onp.int32)
              for f in OpBatch._fields}
    prefix = build_event_graph(arrays)["prefix"]
    table = make_table(2, 32)
    dead = make_table(2, 32)
    out = apply_window_egwalker_pingpong(dead, table, prefix)
    assert [e.root for e in sanitizer.donation_events()] == [
        "egwalker_pingpong"]
    with pytest.raises(RuntimeError, match="deleted"):
        # the deliberate post-donation read the trap exists to catch
        np.asarray(dead.seq)  # fluidlint: disable=donated-buffer-reuse
    np.asarray(out.length)
    # dead=None is the explicit plain-dispatch opt-out: no trap
    jitsan.reset()
    apply_window_egwalker_pingpong(None, table, prefix)
    assert sanitizer.donation_events() == []


def test_donating_the_live_input_records_a_trip(sanitizer):
    """The aliasing form of donated-buffer-reuse: one table passed
    both donated and live. jitsan records a trip (and refuses to
    delete the shared buffers — the live input must stay readable so
    the test can report instead of crash)."""
    from fluidframework_tpu.ops.merge_kernel import (
        apply_window_pingpong,
    )

    table = make_table(2, 32)
    # the deliberate aliasing dispatch the trip exists to catch
    apply_window_pingpong(table, table, _batch(2, 16))  # fluidlint: disable=donated-buffer-reuse
    trips = sanitizer.trips()
    assert trips and all(
        t.root == "apply_window_pingpong" for t in trips)
    assert "aliases a live input" in trips[0].describe()
    np.asarray(table.length)  # not deleted
    jitsan.reset()  # the trip was deliberate; clear it for the guard


def test_keyword_live_args_alias_check_and_survive(sanitizer):
    """Live inputs passed BY KEYWORD are part of the aliasing check:
    donating a table that also rides in as ``table=`` records a trip
    and the shared buffers are NOT deleted (deleting them would
    corrupt the live input the kernel still reads)."""
    from fluidframework_tpu.ops.merge_kernel import (
        apply_window_pingpong,
    )

    table = make_table(2, 32)
    # the deliberate keyword-aliasing dispatch the trip exists to catch
    apply_window_pingpong(table, table=table, batch=_batch(2, 16))  # fluidlint: disable=donated-buffer-reuse
    trips = sanitizer.trips()
    assert trips and trips[0].root == "apply_window_pingpong"
    np.asarray(table.length)  # still readable: not deleted
    jitsan.reset()  # the trip was deliberate; clear it for the guard


def test_migration_handoff_source_reads_trap(sanitizer):
    """The migration handoff (ops/shard_moves.migrate_rows) consumes
    its SOURCE table; jitsan makes a later read raise on any backend
    — a migration that kept reading the pre-move table would pass on
    CPU (donation ignored) and detonate on-chip."""
    from fluidframework_tpu.ops.shard_moves import (
        migrate_rows,
        take_rows,
    )

    table = make_table(4, 32)
    perm = np.arange(4, dtype=np.int32)[::-1].copy()
    out = migrate_rows(table, perm)
    assert [e.root for e in sanitizer.donation_events()] == [
        "mesh_move_pingpong"]
    with pytest.raises(RuntimeError, match="deleted"):
        # the deliberate post-handoff read the trap exists to catch
        np.asarray(table.length)  # fluidlint: disable=donated-buffer-reuse
    np.asarray(out.length)  # the permuted output stays readable
    assert sanitizer.trips() == []
    # the PLAIN gather is the non-consuming form: source stays live
    jitsan.reset()
    kept = take_rows(out, np.arange(4, dtype=np.int32))
    np.asarray(out.length)
    np.asarray(kept.length)
    assert sanitizer.donation_events() == []


def test_mesh_pool_migration_under_sanitizer_never_rereads(sanitizer):
    """The pool's own migration discipline end to end under the
    sanitizer: a driven hot-spot migration consumes the pre-move
    table (a mesh_move donation event fires), no aliasing trip
    fires, and every member's text stays bit-correct afterwards —
    the runtime half of the 'migration handoff buffers must not
    read-after-donate' contract."""
    from fluidframework_tpu.parallel import make_mesh

    sidecar = TpuMergeSidecar(
        max_docs=4, capacity=16, max_capacity=16, executor="scan",
        donate=False, seq_mesh=make_mesh(jax.devices()[:2]),
        pool_capacity=256, ladder=BucketLadder(16, 32),
    )
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    docs = {}
    for i in range(3):
        doc = f"doc-{i}"
        sidecar.subscribe(server, doc, "d", "s")
        c = Container.load(factory.create_document_service(doc),
                           client_id=f"{doc}-w")
        s = c.runtime.create_datastore("d").create_channel(
            "sharedstring", "s")
        for _ in range(20):
            s.insert_text(0, "abcdefgh")
            c.flush()
        docs[doc] = (c, s)
    sidecar.apply()
    sidecar.sync()
    for _ in range(5):
        for doc, (c, s) in docs.items():
            n = 10 if doc == "doc-0" else 1
            for _ in range(n):
                s.insert_text(0, "XY")
            c.flush()
        sidecar.apply()
        sidecar.sync()
    assert sidecar._pool.migration_count > 0
    assert sanitizer.trips() == []
    assert any(
        e.root == "mesh_move_pingpong"
        for e in sanitizer.donation_events()
    ), "the migration handoff must consume the pre-move table"
    for doc, (c, s) in docs.items():
        assert sidecar.text(doc, "d", "s") == s.get_text(), doc


def test_sidecar_donate_path_retires_fodder_loudly(sanitizer):
    """The sidecar's double-buffer discipline under the sanitizer:
    with donation forced on (CPU falls back to the plain dispatch but
    the CONTRACT is identical), every retired fodder table is
    consumed, no trip fires, and serving stays correct — the
    ping-pong invariant from PR2, machine-checked end to end."""
    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=64, max_capacity=64, donate=True,
        ladder=BucketLadder(16, 16),
    )
    server = LocalServer()
    _, s = _drive(server, sidecar, "doc", n=8)
    assert sidecar.text("doc", "d", "s") == s.get_text()
    assert sanitizer.trips() == []
    assert any(
        e.root == "apply_window_pingpong"
        for e in sanitizer.donation_events()
    )


# ======================================================================
# prewarm coverage, runtime pin + the compile metric


def test_prewarm_covers_all_serving_compiles(sanitizer):
    """After prewarm, in-ladder traffic (incl. grow recovery) pays
    ZERO mid-serve compiles — the runtime form of shapecheck's
    prewarm-coverage rule."""
    ladder = BucketLadder(window_floor=16, max_bucket=32)
    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=16, max_capacity=64, executor="scan",
        donate=False, ladder=ladder,
    )
    sidecar.prewarm()
    jitsan.reset()
    server = LocalServer()
    _drive(server, sidecar, "doc")
    assert sidecar.grow_count >= 1
    counts = sanitizer.compile_counts()
    assert all(n == 0 for n in counts.values()), (
        f"mid-serve compiles after prewarm: "
        f"{ {r: n for r, n in counts.items() if n} }"
    )


def test_prewarm_covers_pool_admission_compiles(sanitizer):
    """The pool tier (the gap the prewarm-coverage rule found live:
    SeqShardedPool dispatched through a program prewarm never
    walked): with a seq mesh attached, prewarm walks the pool's
    dispatch programs too, so the FIRST pool admission mid-serve
    compiles nothing."""
    from fluidframework_tpu.parallel.seq_shard import make_seq_mesh

    mesh = make_seq_mesh(jax.devices()[:1], doc_shards=1)
    sidecar = TpuMergeSidecar(
        max_docs=2, capacity=16, max_capacity=16, executor="scan",
        donate=False, seq_mesh=mesh, pool_capacity=64,
        ladder=BucketLadder(16, 16),
    )
    sidecar.prewarm()
    jitsan.reset()
    server = LocalServer()
    _, s = _drive(server, sidecar, "doc", n=24)
    assert sidecar.pooled_docs() == 1, "traffic must exercise the pool"
    assert sidecar.text("doc", "d", "s") == s.get_text()
    counts = sanitizer.compile_counts()
    assert all(n == 0 for n in counts.values()), (
        f"mid-serve compiles after prewarm: "
        f"{ {r: n for r, n in counts.items() if n} }"
    )


def _drive_tree(server, sidecar, doc: str, n: int = 20):
    """Frequent-flush tree writer traffic: small per-apply windows,
    node count climbing past the first capacity rung (a regrow)."""
    from fluidframework_tpu.models.tree import node

    factory = LocalDocumentServiceFactory(server)
    sidecar.subscribe(server, doc, "d", "t")
    c = Container.load(factory.create_document_service(doc),
                       client_id=f"{doc}-writer")
    t = c.runtime.create_datastore("d").create_channel(
        "sharedtree", "t")
    for i in range(n):
        t.insert_nodes(("root",), 0,
                       [node("n", value=i * 4 + j) for j in range(4)])
        c.flush()
        if i % 3 == 2:
            t.move_nodes(("root",), 0, 1, 3)
            c.flush()
        sidecar.apply()
    sidecar.sync()
    return c, t


@pytest.fixture()
def cold_tree_caches(monkeypatch):
    """Fresh jit caches for the tree serving roots (the
    ``cold_mesh_caches`` rule): a warm-cache run observes ZERO new
    compiles at prewarm, failing the non-vacuity asserts below
    depending on suite order. ``tree_sidecar`` binds
    ``pad_tree_capacity`` by value at import, so the fresh pad jit is
    patched in BOTH modules or the sidecar would keep dispatching the
    warm original while jitsan probes the cold replacement."""
    import fluidframework_tpu.ops.tree_apply as tree_apply
    import fluidframework_tpu.service.tree_sidecar as tree_sidecar_mod

    def _fresh_pad(table, new_slots):
        return tree_apply._pad_tree_impl(table, new_slots)

    fresh_pad = jax.jit(_fresh_pad, static_argnums=(1,))
    monkeypatch.setattr(tree_apply, "_jit_cache", {})
    monkeypatch.setattr(tree_apply, "pad_tree_capacity", fresh_pad)
    monkeypatch.setattr(
        tree_sidecar_mod, "pad_tree_capacity", fresh_pad)
    jitsan.reset()  # baseline the fresh (empty) caches


@pytest.mark.parametrize("route", ("atom", "macro"))
def test_tree_prewarm_covers_serving_compiles(
        sanitizer, cold_tree_caches, route):
    """The tree serving plane's prewarm-coverage pin, per route:
    after ``TreeSidecar.prewarm()`` (which walks the full
    (capacity rung x window bucket x BOTH routes) ladder plus the
    pad step), in-ladder tree traffic — including a grow recovery —
    pays ZERO mid-serve compiles on either tree root."""
    from fluidframework_tpu.service import TreeSidecar

    ladder = BucketLadder(window_floor=16, max_bucket=32)
    sidecar = TreeSidecar(max_docs=2, capacity=16, max_capacity=64,
                          executor=route, ladder=ladder)
    sidecar.prewarm()
    counts = sanitizer.compile_counts()
    # non-vacuity + ladder arithmetic: the window root holds at most
    # one signature per (rung, bucket, route, input-commitment) —
    # prewarm walks fresh AND dispatch-output tables — the pad root
    # one per rung transition
    rungs = len(BucketLadder.capacity_rungs(16, 64))
    buckets = len(ladder.window_buckets())
    assert 0 < counts["tree_window"] <= rungs * buckets * 2 * 2
    assert 0 < counts["tree_pad"] <= max(rungs - 1, 1)
    jitsan.reset()
    server = LocalServer()
    _drive_tree(server, sidecar, "doc")
    assert sidecar.grow_count >= 1, "traffic must exercise a regrow"
    counts = sanitizer.compile_counts()
    assert all(n == 0 for n in counts.values()), (
        f"mid-serve tree compiles after prewarm: "
        f"{ {r: n for r, n in counts.items() if n} }"
    )


def test_tree_prewarm_covers_pool_admission_compiles(
        sanitizer, cold_tree_caches):
    """With a pool mesh attached, TreeSidecar.prewarm walks the pool
    tier's first-admission programs too — the first mid-serve pool
    admission and its incremental dispatches compile nothing."""
    from fluidframework_tpu.parallel.seq_shard import make_seq_mesh
    from fluidframework_tpu.service import TreeSidecar

    mesh = make_seq_mesh(jax.devices()[:1], doc_shards=1)
    sidecar = TreeSidecar(max_docs=2, capacity=16, max_capacity=16,
                          executor="atom", pool_mesh=mesh,
                          pool_capacity=64,
                          ladder=BucketLadder(16, 16))
    sidecar.prewarm()
    jitsan.reset()
    server = LocalServer()
    _, t = _drive_tree(server, sidecar, "doc", n=8)
    assert sidecar.pooled_docs() == 1, "traffic must exercise the pool"
    counts = sanitizer.compile_counts()
    assert all(n == 0 for n in counts.values()), (
        f"mid-serve tree compiles after prewarm: "
        f"{ {r: n for r, n in counts.items() if n} }"
    )


def test_publish_compiles_feeds_the_registry_counter(sanitizer):
    from fluidframework_tpu.ops.merge_kernel import compact

    before = obs_metrics.REGISTRY.flat().get(
        'jax_compiles_total{root="compact"}', 0.0)
    compact(make_table(3, 32))
    sizes = jitsan.publish_compiles()
    assert sizes["compact"] >= 1
    after = obs_metrics.REGISTRY.flat()[
        'jax_compiles_total{root="compact"}']
    assert after > before
    # monotone watermark: publishing again without new compiles must
    # not double-count
    jitsan.publish_compiles()
    assert obs_metrics.REGISTRY.flat()[
        'jax_compiles_total{root="compact"}'] == after


def test_uninstall_sweeps_late_imported_wrapper_copies():
    """A module first-imported AFTER install() binds the trap wrapper
    by value (`from ..ops.merge_kernel import apply_window_pingpong`)
    and is not in the install-time patch record — uninstall() must
    sweep it back too, or that module keeps delete()ing donated
    tables with the sanitizer nominally off."""
    import sys
    import types

    from fluidframework_tpu.ops import merge_kernel

    if jitsan.installed():
        # FFTPU_SANITIZE=1 session: the conftest holds an install
        # refcount, so a nested install/uninstall pair never restores
        # anything (by design — the guard stays armed)
        pytest.skip("session-wide jitsan holds the install refcount")

    original = merge_kernel.apply_window_pingpong
    jitsan.install()
    try:
        wrapper = merge_kernel.apply_window_pingpong
        assert wrapper is not original
        late = types.ModuleType("fluidframework_tpu._jitsan_late")
        late.apply_window_pingpong = wrapper  # the by-value import
        sys.modules["fluidframework_tpu._jitsan_late"] = late
    finally:
        jitsan.uninstall()
    try:
        assert merge_kernel.apply_window_pingpong is original
        assert late.apply_window_pingpong is original, (
            "late importer kept the trap wrapper after uninstall()"
        )
    finally:
        sys.modules.pop("fluidframework_tpu._jitsan_late", None)
