"""The fluidlint gate: every pass family over the whole repo, wired
into tier-1 so the analyzer's invariants hold forever after.

Green means: zero non-allowlisted findings AND zero stale allowlist
entries (the ratchet — grandfathered findings may only disappear,
never accumulate; see docs/ANALYSIS.md for the policy).
"""
import json
import subprocess
import sys
import time

from fluidframework_tpu.analysis import core

# the ratchet cap (acceptance: <= 10 grandfathered findings). This
# number may be LOWERED as entries burn down; never raised.
MAX_ALLOWLIST_ENTRIES = 10

# wall-clock budget for ONE combined all-family run (the shared
# per-run callgraph + the memoized _gate() below are what keep this
# honest). The seven-family run measures ~10s on the dev box; the
# budget leaves CI headroom while still tripping on a superlinear
# regression (e.g. a fixpoint that stops converging, or a family
# rebuilding the callgraph per file).
GATE_BUDGET_S = 60.0

_GATE_CACHE = None
_GATE_RUNTIME_S = None


def _gate():
    # one full-tree run per pytest session: several tests read the
    # same result, and the interprocedural families are not free
    global _GATE_CACHE, _GATE_RUNTIME_S
    if _GATE_CACHE is None:
        t0 = time.perf_counter()
        findings = core.run_analysis()
        _GATE_RUNTIME_S = time.perf_counter() - t0
        allowlist = core.load_allowlist()
        kept, stale = core.apply_allowlist(findings, allowlist)
        _GATE_CACHE = (kept, stale, allowlist, findings)
    return _GATE_CACHE[:3]


def test_fluidlint_gate_is_clean():
    kept, stale, _ = _gate()
    problems = [f.format() for f in kept]
    problems += [
        f"stale allowlist entry '{rule} {key}' matches no live "
        "finding — delete it from analysis/allowlist.txt"
        for rule, key in stale
    ]
    assert not problems, (
        "fluidlint gate failed (fix the code, add a justified "
        "'# fluidlint: disable=<rule>' inline, or — for pre-existing "
        "debt only — allowlist it):\n" + "\n".join(problems)
    )


def test_allowlist_ratchet_cap():
    allowlist = core.load_allowlist()
    assert len(allowlist) <= MAX_ALLOWLIST_ENTRIES, (
        f"allowlist has {len(allowlist)} entries, cap is "
        f"{MAX_ALLOWLIST_ENTRIES}: the list only ratchets DOWN — fix "
        "findings instead of grandfathering new ones"
    )


def test_obs_untimed_hop_rule_fires_on_unregistered_hops(tmp_path):
    """The obs-untimed-hop rule (obscheck family): a module stamping
    a hop name outside the canonical table in obs/trace.py fails; a
    canonical stamp passes. Covers both the stamp() call form and a
    direct Trace(...) construction."""
    fixture = tmp_path / "bad_hops.py"
    fixture.write_text(
        "from fluidframework_tpu.obs.trace import stamp\n"
        "from fluidframework_tpu.protocol.messages import Trace\n"
        "def f(traces):\n"
        "    stamp(traces, 'client', 'submit')\n"       # canonical
        "    stamp(traces, 'warpdrive', 'engage')\n"    # not
        "    traces.append(Trace('sequencer', 'ticket'))\n"  # canonical
        "    traces.append(Trace('gremlin', 'nibble'))\n"    # not
        "    name = 'dyn'\n"
        "    stamp(traces, name, name)\n"  # dynamic: runtime's job
    )
    findings = core.run_analysis(
        roots=[str(fixture)], families=["obscheck"],
    )
    keys = sorted(f.key for f in findings)
    assert keys == [
        "bad_hops.py:gremlin:nibble",
        "bad_hops.py:warpdrive:engage",
    ]
    assert all(f.rule == "obs-untimed-hop" for f in findings)

    # a module's own unrelated stamp()/Trace() — no obs/protocol
    # import — must NOT false-positive the gate
    unrelated = tmp_path / "unrelated.py"
    unrelated.write_text(
        "def stamp(canvas, layer, mode):\n"
        "    return (canvas, layer, mode)\n"
        "class Trace:\n"
        "    def __init__(self, a, b):\n"
        "        pass\n"
        "def g(c):\n"
        "    stamp(c, 'fill', 'round')\n"
        "    Trace('not', 'a-hop')\n"
    )
    assert core.run_analysis(
        roots=[str(unrelated)], families=["obscheck"],
    ) == []


def test_canonical_hops_resolve_to_live_stamp_sites():
    """Registry non-vacuity (the WALL_CLOCK_SINKS / FANOUT_GATES
    contract, applied to the hop table): every CANONICAL_HOPS entry —
    including the PR13 replication/partition/pool hops — must be
    reachable from a real literal ``stamp()``/``Trace()`` call site
    in the package tree. A ghost hop entry fails HERE, so the table
    can only describe hops something actually emits."""
    from fluidframework_tpu.analysis.obscheck import (
        collect_stamped_hops,
        load_canonical_hops,
        stale_canonical_hops,
    )

    files = core.walk_python_files(["fluidframework_tpu"])
    stale = stale_canonical_hops(files)
    assert stale == [], (
        "CANONICAL_HOPS entries with no live stamp()/Trace() call "
        f"site (ghost vocabulary — delete or stamp them): {stale}"
    )
    # the new fleet hops specifically come from the surfaces the
    # tentpole instrumented: the replicated sequencer, the
    # partitioned transport, and the mesh pool's settle boundary
    by_file = {}
    for relpath in ("service/replication.py",
                    "service/partitioning.py",
                    "parallel/mesh_pool.py"):
        (src,) = [f for f in files if f.relpath.endswith(relpath)]
        by_file[relpath] = collect_stamped_hops([src])
    assert {("repl", "fence_check"), ("repl", "forward"),
            ("repl", "follower_append"), ("repl", "quorum_ack")} <= \
        by_file["service/replication.py"]
    assert ("partition", "route") in \
        by_file["service/partitioning.py"]
    assert ("pool", "migrate") in by_file["parallel/mesh_pool.py"]

    # the staleness detector itself is not vacuous: an injected
    # ghost entry is caught
    ghost = load_canonical_hops() | {("ghost", "hop")}
    assert stale_canonical_hops(files, hops=ghost) == \
        [("ghost", "hop")]


def test_obs_canonical_table_stays_statically_readable():
    """obscheck must keep extracting the hop table without importing
    the obs package (the linter depends on nothing it lints); this
    breaks loudly if CANONICAL_HOPS stops being a pure literal."""
    from fluidframework_tpu.analysis.obscheck import load_canonical_hops

    hops = load_canonical_hops()
    assert ("sequencer", "ticket") in hops
    assert ("client", "submit") in hops
    assert ("sidecar", "settle") in hops


def test_obscheck_family_is_in_the_gate():
    assert "obscheck" in core.FAMILIES


def test_slo_unbound_objective_rule_fires_on_unregistered_metric(
        tmp_path):
    """The slo-unbound-objective rule (obscheck family): a declared
    Objective whose metric literal names no registered family — or a
    family of the wrong kind — fails; objectives bound to families
    registered anywhere in the scanned tree pass; dynamic names are
    the runtime ValueError's job."""
    fixture = tmp_path / "objectives.py"
    fixture.write_text(
        "from fluidframework_tpu.obs.slo import Objective\n"
        "from fluidframework_tpu.obs import metrics as obs_metrics\n"
        "H = obs_metrics.REGISTRY.histogram('fix_lat_ms', 'h')\n"
        "C = obs_metrics.REGISTRY.counter('fix_good_total', 'c')\n"
        "T = obs_metrics.REGISTRY.counter('fix_total_total', 'c')\n"
        "G = obs_metrics.REGISTRY.gauge('fix_depth', 'g')\n"
        "OK1 = Objective('lat', metric='fix_lat_ms',\n"
        "                threshold_ms=5.0)\n"
        "OK2 = Objective('gp', kind='goodput',\n"
        "                good_metric='fix_good_total',\n"
        "                total_metric='fix_total_total')\n"
        "BAD1 = Objective('ghost', metric='fix_nonexistent_ms')\n"
        "BAD2 = Objective('wrongkind', metric='fix_good_total')\n"
        "BAD3 = Objective('gpbad', kind='goodput',\n"
        "                 good_metric='fix_depth',\n"
        "                 total_metric='fix_total_total')\n"
        "name = 'dyn_ms'\n"
        "DYN = Objective('dyn', metric=name)\n"  # runtime's job
    )
    findings = core.run_analysis(
        roots=[str(fixture)], families=["obscheck"],
    )
    assert sorted(f.key for f in findings) == [
        "objectives.py:ghost:fix_nonexistent_ms",
        "objectives.py:gpbad:fix_depth",
        "objectives.py:wrongkind:fix_good_total",
    ]
    assert all(f.rule == "slo-unbound-objective" for f in findings)

    # partial-path scans fall back to the real package's registered
    # families: an objective bound to a family registered OUTSIDE the
    # scanned files (here: the sidecar's settle histogram and the
    # ingress goodput counters) must stay clean
    partial = tmp_path / "partial.py"
    partial.write_text(
        "from fluidframework_tpu.obs.slo import Objective\n"
        "A = Objective('settle', metric='sidecar_settle_ms',\n"
        "              threshold_ms=100.0)\n"
        "B = Objective('gp', kind='goodput',\n"
        "              good_metric='ingress_ops_received_total',\n"
        "              total_metric='ingress_ops_offered_total')\n"
    )
    assert core.run_analysis(
        roots=[str(partial)], families=["obscheck"],
    ) == []

    # a module's own unrelated Objective class (no obs import) is
    # not the rule's business
    own = tmp_path / "own.py"
    own.write_text(
        "class Objective:\n"
        "    def __init__(self, *a, **k):\n"
        "        pass\n"
        "X = Objective('x', metric='definitely_not_registered')\n"
    )
    assert core.run_analysis(
        roots=[str(own)], families=["obscheck"],
    ) == []


def test_undocumented_metric_rule_staleness_both_ways(tmp_path):
    """The undocumented-metric rule (obscheck family): a registered
    family with no row in the fixture tree's docs/OBSERVABILITY.md
    fails; a documented ghost family nothing registers fails too; a
    documented + registered family passes. Scope: the doc is found
    by ascent, and files under tests/ are not the doc's business."""
    root = tmp_path / "repo"
    (root / "docs").mkdir(parents=True)
    (root / "docs" / "OBSERVABILITY.md").write_text(
        "# Observability\n\n"
        "| family | type | meaning |\n"
        "|---|---|---|\n"
        "| `fixture_documented_total` | counter | documented |\n"
        "| `fixture_ghost_family_total` | counter | nothing "
        "registers this |\n"
    )
    mod = root / "plane.py"
    mod.write_text(
        "def wire(reg):\n"
        "    reg.counter('fixture_documented_total', 'ok')\n"
        "    reg.counter('fixture_undocumented_total', 'missing "
        "row')\n"
        "    name = 'dyn_total'\n"
        "    reg.counter(name, 'dynamic: runtime concern')\n"
    )
    findings = core.run_analysis(
        roots=[str(mod)], families=["obscheck"],
    )
    assert sorted(f.key for f in findings) == [
        "fixture_ghost_family_total", "fixture_undocumented_total",
    ]
    assert all(f.rule == "undocumented-metric" for f in findings)
    ghost = next(f for f in findings
                 if f.key == "fixture_ghost_family_total")
    assert ghost.path.endswith("docs/OBSERVABILITY.md")
    missing = next(f for f in findings
                   if f.key == "fixture_undocumented_total")
    assert missing.path.endswith("plane.py")

    # a registry driven from under tests/ is a synthetic test rig,
    # not serving surface: out of the doc's scope
    tdir = root / "tests"
    tdir.mkdir()
    rig = tdir / "test_rig.py"
    rig.write_text(
        "def rig(reg):\n"
        "    reg.counter('rig_only_total', 'synthetic')\n"
    )
    assert core.run_analysis(
        roots=[str(rig)], families=["obscheck"],
    ) == []

    # no docs/OBSERVABILITY.md above the scan roots (plain fixture
    # trees): the rule is silent, not a false-positive storm
    bare = tmp_path / "bare.py"
    bare.write_text(
        "def wire(reg):\n"
        "    reg.counter('undocumented_anywhere_total', 'x')\n"
    )
    assert core.run_analysis(
        roots=[str(bare)], families=["obscheck"],
    ) == []


def test_undocumented_metric_live_tree_is_clean():
    """The acceptance bar for the heat PR's doc satellite: every
    family the real tree registers has a row in docs/
    OBSERVABILITY.md's metric family index, no ghost rows, NOTHING
    allowlisted — the doc can be trusted as the complete operator
    surface."""
    kept, _stale, allowlist = _gate()
    mine = [f for f in kept if f.rule == "undocumented-metric"]
    assert mine == [], "\n".join(f.format() for f in mine)
    assert not [e for e in allowlist
                if e[0] == "undocumented-metric"], (
        "undocumented-metric must not be allowlisted — document the "
        "family instead")


def test_service_unbounded_queue_rule_fires_in_service_paths(
        tmp_path):
    """The service-unbounded-queue rule (qoscheck family): an
    unbounded asyncio.Queue()/deque() in a service/qos path fails;
    bounded constructions and justified inline disables pass; the
    same code OUTSIDE a service path is not the rule's business."""
    svc_dir = tmp_path / "service"
    svc_dir.mkdir()
    bad = svc_dir / "bad.py"
    bad.write_text(
        "import asyncio\n"
        "from collections import deque\n"
        "class Session:\n"
        "    def __init__(self):\n"
        "        self.outbound = asyncio.Queue()\n"            # BAD
        "        self.infinite = asyncio.Queue(maxsize=0)\n"   # BAD
        "        self.bounded = asyncio.Queue(maxsize=100)\n"  # ok
        "        self.log = deque()\n"                         # BAD
        "        self.ring = deque((), 64)\n"                  # ok
        "        self.ok = deque(maxlen=8)\n"                  # ok
        "        self.justified = deque()  "
        "# fluidlint: disable=service-unbounded-queue -- test\n"
    )
    findings = core.run_analysis(
        roots=[str(bad)], families=["qoscheck"],
    )
    assert sorted(f.key for f in findings) == [
        "bad.py:Session.__init__.infinite",
        "bad.py:Session.__init__.log",
        "bad.py:Session.__init__.outbound",
    ]
    assert all(
        f.rule == "service-unbounded-queue" for f in findings
    )

    # a module's own class named Queue/deque (no import) must not
    # false-positive, and non-service paths are out of scope
    other = tmp_path / "elsewhere.py"
    other.write_text(
        "import asyncio\n"
        "q = asyncio.Queue()\n"
    )
    assert core.run_analysis(
        roots=[str(other)], families=["qoscheck"],
    ) == []
    own = svc_dir / "own.py"
    own.write_text(
        "class deque:\n"
        "    pass\n"
        "d = deque()\n"
    )
    assert core.run_analysis(
        roots=[str(own)], families=["qoscheck"],
    ) == []


def test_retry_without_jitter_rule(tmp_path):
    """qoscheck:retry-without-jitter — a constant time.sleep inside a
    retry/reconnect loop in drivers/service/qos paths flags
    (synchronized reconnect storms after a mass disconnect); delays
    routed through driver_utils.full_jitter_delay pass, as do sleeps
    outside loops, unknown-provenance values, suppressed lines and
    out-of-scope paths."""
    drv = tmp_path / "drivers"
    drv.mkdir()
    bad = drv / "bad.py"
    bad.write_text(
        "import time\n"
        "from .driver_utils import full_jitter_delay\n"
        "class Conn:\n"
        "    def reconnect(self):\n"
        "        attempt = 0\n"
        "        while True:\n"
        "            try:\n"
        "                return self.dial()\n"
        "            except OSError:\n"
        "                attempt += 1\n"
        "                time.sleep(0.5)\n"                     # BAD
        "    def reconnect_scaled(self):\n"
        "        delay = 0.1 * 2\n"
        "        for _ in range(5):\n"
        "            time.sleep(delay)\n"                       # BAD
        "    def reconnect_jittered(self, attempt):\n"
        "        while True:\n"
        "            time.sleep(full_jitter_delay(attempt))\n"  # ok
        "    def reconnect_jittered_var(self, attempt):\n"
        "        while True:\n"
        "            d = full_jitter_delay(attempt)\n"
        "            time.sleep(d)\n"                           # ok
        "    def settle_once(self):\n"
        "        time.sleep(0.5)\n"          # ok: not a retry loop
        "    def injected(self, delay_fn):\n"
        "        while True:\n"
        "            time.sleep(delay_fn())\n"  # ok: unknown prov
        "    def justified(self):\n"
        "        while True:\n"
        "            time.sleep(1.0)  "
        "# fluidlint: disable=retry-without-jitter -- test\n"
    )
    findings = core.run_analysis(
        roots=[str(bad)], families=["qoscheck"],
    )
    assert sorted(f.key for f in findings) == [
        "bad.py:Conn.reconnect.sleep",
        "bad.py:Conn.reconnect_scaled.sleep",
    ]
    assert all(f.rule == "retry-without-jitter" for f in findings)

    # two raw sleeps in ONE scope get distinct stable keys
    two = drv / "two.py"
    two.write_text(
        "import time\n"
        "def pump():\n"
        "    while True:\n"
        "        time.sleep(0.1)\n"
        "        time.sleep(0.2)\n"
    )
    keys = sorted(f.key for f in core.run_analysis(
        roots=[str(two)], families=["qoscheck"]))
    assert keys == ["two.py:pump.sleep", "two.py:pump.sleep2"]

    # the same code OUTSIDE a drivers/service/qos path component is
    # not the rule's business
    other = tmp_path / "elsewhere.py"
    other.write_text(
        "import time\n"
        "def pump():\n"
        "    while True:\n"
        "        time.sleep(0.1)\n"
    )
    assert core.run_analysis(
        roots=[str(other)], families=["qoscheck"],
    ) == []


def test_fence_before_fanout_rule(tmp_path):
    """qoscheck:fence-before-fanout — a call to a replication gate
    (the reviewed FANOUT_GATES registry) in a service path must be
    textually preceded, in the same function, by an epoch fence
    check; both ``<...>.fence.check(...)`` and ``check_epoch(...)``
    spellings count, suppression works, non-service paths are out of
    scope."""
    svc = tmp_path / "service"
    svc.mkdir()
    bad = svc / "bad.py"
    bad.write_text(
        "class Log:\n"
        "    def persist(self, msg):\n"
        "        self.write(msg)\n"
        "        self.group.replicate_before_fanout(msg)\n"   # BAD
        "    def persist_checked(self, msg):\n"
        "        self.group.fence.check(self.epoch)\n"
        "        self.group.replicate_before_fanout(msg)\n"   # ok
        "    def persist_epoch(self, msg):\n"
        "        check_epoch(self.epoch)\n"
        "        self._replicate_before_fanout(msg)\n"        # ok
        "    def persist_late_fence(self, msg):\n"
        "        self._replicate_before_fanout(msg)\n"        # BAD
        "        self.group.fence.check(self.epoch)\n"
        "    def persist_justified(self, msg):\n"
        "        self.group.replicate_before_fanout(msg)  "
        "# fluidlint: disable=fence-before-fanout -- test\n"
        "    def persist_nested_fence(self, msg):\n"
        "        def helper():\n"
        "            self.group.fence.check(self.epoch)\n"
        "        self.group.replicate_before_fanout(msg)\n"  # BAD
        "    def persist_nested_gate(self, msg):\n"
        "        def flush():\n"
        "            self.group.replicate_before_fanout(msg)\n"  # BAD
        "        flush()\n"
    )
    findings = [f for f in core.run_analysis(
        roots=[str(bad)], families=["qoscheck"])
        if f.rule == "fence-before-fanout"]
    assert sorted(f.key for f in findings) == [
        "bad.py:Log.persist.fanout",
        "bad.py:Log.persist_late_fence.fanout",
        # a fence check hidden inside a nested helper does NOT guard
        # the outer gate — the hoist the rule exists to catch
        "bad.py:Log.persist_nested_fence.fanout",
        # a gate inside a nested def is ONE finding against the
        # nested scope, not a duplicate against the method too
        "bad.py:flush.fanout",
    ]

    # the same code outside a service path component is not the
    # rule's business (the replicated sequencer lives in service/)
    other = tmp_path / "elsewhere.py"
    other.write_text(
        "def persist(group, msg):\n"
        "    group.replicate_before_fanout(msg)\n"
    )
    assert [f for f in core.run_analysis(
        roots=[str(other)], families=["qoscheck"])
        if f.rule == "fence-before-fanout"] == []


def test_fence_before_fanout_live_tree_is_clean():
    """The replicated sequencer's real gates (document plane +
    partitioned queue) all check the fence first — and the rule
    actually SEES them (non-vacuity: the gate callees exist in the
    scanned tree)."""
    findings = [
        f for f in core.run_analysis(families=["qoscheck"])
        if f.rule == "fence-before-fanout"
    ]
    assert findings == [], [f.key for f in findings]
    import ast as _ast

    repl = open("fluidframework_tpu/service/replication.py").read()
    gates = [n for n in _ast.walk(_ast.parse(repl))
             if isinstance(n, _ast.Call)
             and getattr(n.func, "attr", None)
             and n.func.attr.lstrip("_") == "replicate_before_fanout"]
    assert gates, "the rule's registry no longer matches the code"


def test_retry_without_jitter_live_tree_is_clean():
    findings = [
        f for f in core.run_analysis(families=["qoscheck"])
        if f.rule == "retry-without-jitter"
    ]
    assert findings == [], [f.key for f in findings]


def test_unbounded_blocking_wait_rule(tmp_path):
    """qoscheck:unbounded-blocking-wait — a while loop in a service
    path that SLEEPS while waiting for external progress must carry a
    deadline (a comparison against a clock reading or a
    deadline/timeout-named bound): the minority-side quorum barrier
    that hung every submitter forever is the bug class. Clean shapes:
    the deadline-bounded barrier, a bounded ``for`` retry, and
    non-service paths."""
    svc = tmp_path / "service"
    svc.mkdir()
    bad = svc / "bad.py"
    bad.write_text(
        "import time\n"
        "class Barrier:\n"
        "    def replicate(self, acked, quorum):\n"
        "        while acked < quorum:\n"                    # BAD
        "            time.sleep(0.05)\n"
        "            acked = self.poll()\n"
        "    def wait_injectable(self, acked, quorum):\n"
        "        while acked < quorum:\n"                    # BAD
        "            self._sleep(0.05)\n"
        "            acked = self.poll()\n"
        "    def bounded(self, acked, quorum, clock):\n"
        "        deadline = clock() + 0.5\n"
        "        while acked < quorum:\n"                    # ok
        "            if clock() >= deadline:\n"
        "                raise RuntimeError('unavailable')\n"
        "            self._sleep(0.05)\n"
        "            acked = self.poll()\n"
        "    def named_timeout(self, acked, quorum):\n"
        "        while acked < quorum and "
        "self.elapsed() < self.timeout_s:\n"                 # ok
        "            self._sleep(0.05)\n"
        "            acked = self.poll()\n"
        "    def no_sleep(self, items):\n"
        "        while items:\n"          # ok: not a wait, no sleep
        "            items.pop()\n"
        "    def justified(self, acked, quorum):\n"
        "        while acked < quorum:  "
        "# fluidlint: disable=unbounded-blocking-wait -- test\n"
        "            time.sleep(0.05)\n"
        "            acked = self.poll()\n"
    )
    findings = [f for f in core.run_analysis(
        roots=[str(bad)], families=["qoscheck"])
        if f.rule == "unbounded-blocking-wait"]
    assert sorted(f.key for f in findings) == [
        "bad.py:Barrier.replicate.blockwait",
        "bad.py:Barrier.wait_injectable.blockwait",
    ]

    # non-service paths are out of scope (drivers poll sockets with
    # their own lifecycle; the rule is about the serving plane)
    other = tmp_path / "elsewhere.py"
    other.write_text(
        "import time\n"
        "def spin(q):\n"
        "    while not q:\n"
        "        time.sleep(0.01)\n"
    )
    assert [f for f in core.run_analysis(
        roots=[str(other)], families=["qoscheck"])
        if f.rule == "unbounded-blocking-wait"] == []


def test_unbounded_blocking_wait_live_tree_is_clean():
    """The quorum barrier's wait is deadline-bounded (the netsplit
    fix) and nothing else in the service plane blocks unboundedly —
    and the rule actually SEES the barrier (non-vacuity: a sleeping
    while loop exists in replication.py)."""
    findings = [
        f for f in core.run_analysis(families=["qoscheck"])
        if f.rule == "unbounded-blocking-wait"
    ]
    assert findings == [], [f.key for f in findings]
    import ast as _ast

    repl = open("fluidframework_tpu/service/replication.py").read()
    loops = [n for n in _ast.walk(_ast.parse(repl))
             if isinstance(n, _ast.While)]
    sleeping = [
        loop for loop in loops
        if any(isinstance(n, _ast.Call)
               and getattr(n.func, "attr", "") == "_sleep"
               for stmt in loop.body for n in _ast.walk(stmt))
    ]
    assert sleeping, (
        "the quorum barrier's deadline wait vanished — the rule has "
        "nothing left to pin")


def test_qoscheck_family_is_in_the_gate():
    assert "qoscheck" in core.FAMILIES


def test_concheck_family_is_in_the_gate():
    assert "concheck" in core.FAMILIES


def test_shapecheck_family_is_in_the_gate():
    assert "shapecheck" in core.FAMILIES


def test_detcheck_family_is_in_the_gate():
    assert "detcheck" in core.FAMILIES


def test_wirecheck_family_is_in_the_gate():
    assert "wirecheck" in core.FAMILIES


def test_failcheck_family_is_in_the_gate():
    assert "failcheck" in core.FAMILIES


def test_wall_clock_unrouted_rule(tmp_path):
    """detcheck:wall-clock-unrouted — a direct time.* read reachable
    from a deterministic-contract root (here: a fixture matching the
    sequencer-root suffix) fails; reads routed through an injected
    ``clock()`` pass; a fixture matching a WALL_CLOCK_SINKS suffix
    (obs/trace.py stamp) is a reviewed sink; code NOT reachable from
    any root is out of the rule's scope."""
    svc = tmp_path / "service"
    svc.mkdir()
    bad = svc / "sequencer.py"
    bad.write_text(
        "import time\n"
        "class DocumentSequencer:\n"
        "    def __init__(self, clock=None):\n"
        "        self._clock = clock or time.time\n"
        "    def ticket(self, op):\n"
        # the first read is NESTED deeper than the second: ordinals
        # must still follow SOURCE order, not ast.walk's BFS order
        "        raw = max(1.0, time.time())\n"            # BAD
        "        raw2 = time.time()\n"                     # BAD
        "        routed = self._clock()\n"                 # ok
        "        return self._stamp(op, raw + raw2 + routed)\n"
        "    def _stamp(self, op, t):\n"
        "        return (op, t, time.monotonic())\n"       # BAD
    )
    # reads in a module no deterministic root reaches are out of the
    # rule's scope (reachability IS the scope)
    (svc / "util.py").write_text(
        "import time\n"
        "def helper_not_reachable():\n"
        "    return time.perf_counter()\n"
    )
    findings = core.run_analysis(
        roots=[str(svc)], families=["detcheck"])
    assert sorted(f.key for f in findings) == [
        "sequencer.py:DocumentSequencer._stamp:time.monotonic",
        "sequencer.py:DocumentSequencer.ticket:time.time",
        "sequencer.py:DocumentSequencer.ticket:time.time2",
    ]
    assert all(f.rule == "wall-clock-unrouted" for f in findings)
    # ordinal suffixes follow source order: the nested read on the
    # EARLIER line owns the unsuffixed key
    by_key = {f.key.rsplit(":", 1)[-1]: f.line for f in findings
              if "ticket" in f.key}
    assert by_key["time.time"] < by_key["time.time2"]

    # a registered sink suffix is the reviewed escape hatch
    obs = tmp_path / "sink" / "obs"
    obs.mkdir(parents=True)
    (tmp_path / "sink" / "service").mkdir()
    (obs / "trace.py").write_text(
        "import time\n"
        "def stamp(traces):\n"
        "    traces.append(time.time())\n"
    )
    (tmp_path / "sink" / "service" / "sequencer.py").write_text(
        "from ..obs.trace import stamp\n"
        "class DocumentSequencer:\n"
        "    def ticket(self, traces):\n"
        "        stamp(traces)\n"
    )
    assert core.run_analysis(
        roots=[str(tmp_path / "sink")], families=["detcheck"],
    ) == []


def test_unseeded_rng_rule(tmp_path):
    """detcheck:unseeded-rng — unseeded random.Random(), the
    process-global random.* stream, and seedless np.random draws fail
    in deterministic-plane components; seeded/injected RNG passes;
    the same code outside the planes is out of scope."""
    drv = tmp_path / "drivers"
    drv.mkdir()
    bad = drv / "bad.py"
    bad.write_text(
        "import random\n"
        "import numpy as np\n"
        "_RNG = random.Random()\n"                         # BAD
        "def jitter():\n"
        "    return random.uniform(0.0, 1.0)\n"            # BAD
        "def noise():\n"
        "    return np.random.rand()\n"                    # BAD
        "def seeded(seed):\n"
        "    rng = random.Random(seed)\n"                  # ok
        "    gen = np.random.default_rng(seed)\n"          # ok
        "    return rng.random() + gen.random()\n"
        "def injected(rng):\n"
        "    return rng.uniform(0.0, 1.0)\n"               # ok
    )
    findings = core.run_analysis(
        roots=[str(bad)], families=["detcheck"])
    assert sorted(f.key for f in findings) == [
        "bad.py:<module>:Random",
        "bad.py:jitter:random.uniform",
        "bad.py:noise:rand",
    ]
    assert all(f.rule == "unseeded-rng" for f in findings)

    other = tmp_path / "elsewhere.py"
    other.write_text(
        "import random\n"
        "x = random.random()\n"
    )
    assert core.run_analysis(
        roots=[str(other)], families=["detcheck"]) == []


def test_iteration_order_leak_rule(tmp_path):
    """detcheck:iteration-order-leak — sets iterated into
    order-sensitive sinks (fan-out/append loops, list()/tuple()
    materialization, join) fail; sorted(...) kills the taint;
    order-insensitive consumption (membership, len, building another
    set) passes."""
    svc = tmp_path / "service"
    svc.mkdir()
    bad = svc / "bad.py"
    bad.write_text(
        "class Fanout:\n"
        "    def __init__(self):\n"
        "        self.writers = set()\n"
        "    def broadcast(self, out, frame):\n"
        "        for w in self.writers:\n"                 # BAD
        "            out.append((w, frame))\n"
        "    def snapshot(self, ids):\n"
        "        pending = set(ids)\n"
        "        return list(pending)\n"                   # BAD
        "    def wire(self, ids):\n"
        "        return ','.join(set(ids))\n"              # BAD
        "    def stable(self, ids):\n"
        "        pending = set(ids)\n"
        "        for w in sorted(pending):\n"              # ok
        "            ids.append(w)\n"
        "        return sorted(self.writers)\n"            # ok
        "    def insensitive(self, ids):\n"
        "        pending = set(ids)\n"
        "        n = len(pending)\n"                       # ok
        "        return {x for x in pending}, n\n"         # ok
        # a defect inside a nested def is ONE finding against the
        # nested scope, not a duplicate against the method too (the
        # fence-before-fanout nested-gate contract)
        "    def wrap(self, out):\n"
        "        def inner(ids):\n"
        "            pend = set(ids)\n"
        "            for w in pend:\n"                     # BAD once
        "                out.append(w)\n"
        "        return inner\n"
    )
    findings = core.run_analysis(
        roots=[str(bad)], families=["detcheck"])
    assert sorted(f.key for f in findings) == [
        "bad.py:Fanout.broadcast:writers",
        "bad.py:Fanout.snapshot:pending",
        "bad.py:Fanout.wire:<set>",
        "bad.py:Fanout.wrap.inner:pend",
    ]
    assert all(
        f.rule == "iteration-order-leak" for f in findings)


def test_hash_order_dependence_rule(tmp_path):
    """detcheck:hash-order-dependence — builtin hash() of str/bytes,
    and hash(x) %% n partition selection, fail in deterministic
    planes; __hash__ methods and integer hashing pass."""
    svc = tmp_path / "service"
    svc.mkdir()
    bad = svc / "bad.py"
    bad.write_text(
        "class Router:\n"
        "    def partition(self, doc_id, n):\n"
        "        return hash(doc_id) % n\n"                # BAD (%)
        "    def key(self, tenant, doc):\n"
        "        return hash(f'{tenant}/{doc}')\n"         # BAD (str)
        "    def __hash__(self):\n"
        "        return hash(('Router', self.key))\n"      # ok
        "    def int_ok(self, seq):\n"
        "        return hash(seq + 1)\n"                   # ok
    )
    findings = core.run_analysis(
        roots=[str(bad)], families=["detcheck"])
    assert sorted(f.key for f in findings) == [
        "bad.py:Router.key:hash",
        "bad.py:Router.partition:hash",
    ]
    assert all(
        f.rule == "hash-order-dependence" for f in findings)


def test_detcheck_live_tree_is_clean_with_empty_allowlist():
    """The acceptance bar (the PR1/PR5/PR7 precedent): zero live
    detcheck findings over the whole repo and NOTHING grandfathered —
    the sites the family found live (driver_utils' module RNG, the
    collab-window/scheduler clocks, the sequencer wire timestamps,
    the broker writer set, the interval pending-delete resubmission)
    were FIXED in the PR that introduced it. WALL_CLOCK_SINKS is the
    reviewed escape hatch, not the allowlist."""
    kept, _stale, allowlist = _gate()
    det_rules = set(core.FAMILY_RULES["detcheck"])
    det_kept = [f for f in kept if f.rule in det_rules]
    assert det_kept == [], \
        "\n".join(f.format() for f in det_kept)
    grandfathered = [e for e in allowlist if e[0] in det_rules]
    assert grandfathered == [], (
        "detcheck findings must be fixed, never grandfathered: "
        f"{grandfathered}"
    )


def test_wirecheck_live_tree_is_clean_with_empty_allowlist():
    """The acceptance bar (the PR1/PR5/PR11 precedent): zero live
    wirecheck findings over the whole repo and NOTHING grandfathered
    — the unguarded optional emits the family found live (the nack
    retry hint in nack_to_json, the throttle error's qos attribution
    in _send_shed) were FIXED in the PR that introduced it. The
    WIRE_SCHEMA registry's '?'/'~' flags are the reviewed escape
    hatch, not the allowlist."""
    kept, _stale, allowlist = _gate()
    wire_rules = set(core.FAMILY_RULES["wirecheck"])
    wire_kept = [f for f in kept if f.rule in wire_rules]
    assert wire_kept == [], \
        "\n".join(f.format() for f in wire_kept)
    grandfathered = [e for e in allowlist if e[0] in wire_rules]
    assert grandfathered == [], (
        "wirecheck findings must be fixed, never grandfathered: "
        f"{grandfathered}"
    )


def test_wire_schema_registry_resolves_to_live_traffic():
    """Registry non-vacuity (the WALL_CLOCK_SINKS contract): every
    non-tolerated WIRE_SCHEMA entry must still name a field some
    in-scope encoder emits or decoder reads — ghost vocabulary fails
    HERE so the registry can only describe the live protocol. (The
    staleness detector's own non-vacuity is pinned by
    test_wirecheck.py's ghost-entry fixture; the registry is a pure
    literal in the scanned tree, so there is nothing to monkeypatch
    live.)"""
    from fluidframework_tpu.analysis import wirecheck

    files = core.walk_python_files(["fluidframework_tpu"])
    stale = wirecheck.stale_schema_entries(files)
    assert stale == [], (
        "stale WIRE_SCHEMA entries (no emit or read resolves to "
        f"them anymore — delete or mark '~'): {stale}"
    )
    registry = wirecheck.load_registry(files)
    assert registry, "WIRE_SCHEMA registry unexpectedly empty"


def test_failcheck_live_tree_is_clean_with_empty_allowlist():
    """The acceptance bar (the PR1/PR5/PR11/PR19 precedent): zero
    live failcheck findings over the whole repo and NOTHING
    grandfathered — every silent handler the family found live was
    either made loud or reviewed into SILENT_HANDLERS in the PR that
    introduced it. The registry is the escape hatch, never the
    allowlist."""
    kept, _stale, allowlist = _gate()
    fail_rules = set(core.FAMILY_RULES["failcheck"])
    fail_kept = [f for f in kept if f.rule in fail_rules]
    assert fail_kept == [], \
        "\n".join(f.format() for f in fail_kept)
    grandfathered = [e for e in allowlist if e[0] in fail_rules]
    assert grandfathered == [], (
        "failcheck findings must be fixed, never grandfathered: "
        f"{grandfathered}"
    )


def test_silent_handlers_registry_resolves_to_live_sites():
    """Registry non-vacuity (the WALL_CLOCK_SINKS contract): every
    SILENT_HANDLERS entry must still match a statically-silent
    handler at its site — an entry whose handler vanished or went
    loud describes nothing and fails HERE so the registry can only
    describe live code. The staleness detector itself is pinned
    non-vacuous with a planted ghost."""
    from fluidframework_tpu.analysis import failcheck

    files = core.walk_python_files(["fluidframework_tpu"])
    stale = failcheck.stale_silent_handlers(files)
    assert stale == [], (
        "stale SILENT_HANDLERS entries (no statically-silent "
        f"handler at the registered site anymore — delete): {stale}"
    )
    assert failcheck.SILENT_HANDLERS, "registry unexpectedly empty"

    # the staleness detector itself is not vacuous
    ghost = ("service/ingress.py",
             "AlfredServer._handle:except-ZeroDivisionError")
    assert ghost not in failcheck.SILENT_HANDLERS
    try:
        failcheck.SILENT_HANDLERS[ghost] = "test-only ghost entry"
        assert ghost in failcheck.stale_silent_handlers(files)
    finally:
        del failcheck.SILENT_HANDLERS[ghost]


def test_wall_clock_sinks_registry_resolves_to_live_sites():
    """Registry non-vacuity (the FANOUT_GATES contract): every
    WALL_CLOCK_SINKS entry must still name a function (or module)
    containing a real wall-clock call — a stale entry fails HERE so
    the registry can only describe live code."""
    from fluidframework_tpu.analysis import determinism

    files = core.walk_python_files(["fluidframework_tpu"])
    stale = determinism.stale_wall_clock_sinks(files)
    assert stale == [], (
        "stale WALL_CLOCK_SINKS entries (no wall-clock call at the "
        f"registered site anymore — delete them): {stale}"
    )
    assert determinism.WALL_CLOCK_SINKS, "registry unexpectedly empty"

    # the staleness detector itself is not vacuous
    ghost = ("service/sequencer.py", "DocumentSequencer.ticket")
    assert ghost not in determinism.WALL_CLOCK_SINKS
    try:
        determinism.WALL_CLOCK_SINKS[ghost] = "test-only ghost entry"
        assert ghost in determinism.stale_wall_clock_sinks(files)
    finally:
        del determinism.WALL_CLOCK_SINKS[ghost]


def test_family_rules_map_stays_complete():
    """RULE_FAMILY is how one combined run's findings group per
    family (bench records); a family missing from the map would
    silently drop its counts."""
    assert set(core.FAMILY_RULES) == set(core.FAMILIES)
    for rule in ("layer-undeclared", "jit-nondeterminism",
                 "lock-unlocked-write", "obs-untimed-hop",
                 "slo-unbound-objective", "undocumented-metric",
                 "service-unbounded-queue", "lock-order-cycle",
                 "async-blocking-call", "await-holding-lock",
                 "dispatch-loop-sync", "donated-buffer-reuse",
                 "unladdered-jit-shape", "kernel-dtype-widen",
                 "shape-mismatch", "prewarm-coverage",
                 "wall-clock-unrouted", "unseeded-rng",
                 "iteration-order-leak", "hash-order-dependence",
                 "encoder-decoder-drift",
                 "optional-field-unconditional-emit",
                 "ungated-wire-read", "unversioned-frame-field",
                 "swallowed-exception",
                 "broad-except-in-dispatch-loop",
                 "exception-context-dropped", "return-in-finally"):
        assert rule in core.RULE_FAMILY, rule


def test_concheck_live_tree_is_clean_within_the_ratchet():
    """The acceptance bar: concheck over the whole repo, at most the
    allowlist cap grandfathered (today: zero — the moira event-loop
    file I/O it found was FIXED, not grandfathered)."""
    kept, _stale, allowlist = _gate()
    concheck_rules = {"lock-order-cycle", "async-blocking-call",
                      "await-holding-lock"}
    concheck_kept = [f for f in kept if f.rule in concheck_rules]
    assert concheck_kept == [], \
        "\n".join(f.format() for f in concheck_kept)
    grandfathered = [e for e in allowlist if e[0] in concheck_rules]
    assert len(grandfathered) <= MAX_ALLOWLIST_ENTRIES


def test_shapecheck_live_tree_is_clean_within_the_ratchet():
    """The acceptance bar for the shapecheck family: zero live
    findings over the real kernel layer with an EMPTY allowlist —
    everything the new family found (the unwarmed pool-tier dispatch
    programs) was FIXED in the PR that introduced it, the PR1/PR5
    precedent. The registries (LADDERED_CALLS, PREWARM_INDIRECT) are
    the reviewed escape hatch, not the allowlist."""
    kept, _stale, allowlist = _gate()
    shape_rules = set(core.FAMILY_RULES["shapecheck"])
    shape_kept = [f for f in kept if f.rule in shape_rules]
    assert shape_kept == [], \
        "\n".join(f.format() for f in shape_kept)
    grandfathered = [e for e in allowlist if e[0] in shape_rules]
    assert grandfathered == [], (
        "shapecheck findings must be fixed, never grandfathered: "
        f"{grandfathered}"
    )


def test_combined_gate_run_stays_under_budget():
    """The CI/tooling satellite: ten families, one shared
    callgraph, one budget. A blowup here means a family stopped
    reusing the per-run graph or a fixpoint regressed superlinear."""
    _gate()  # ensures the timed run happened (memoized per session)
    assert _GATE_RUNTIME_S is not None
    assert _GATE_RUNTIME_S < GATE_BUDGET_S, (
        f"combined {len(core.FAMILIES)}-family run took "
        f"{_GATE_RUNTIME_S:.1f}s, budget is {GATE_BUDGET_S:.0f}s"
    )


def test_cli_sarif_mode_emits_valid_report(tmp_path, monkeypatch):
    """`--sarif` (diff-annotation tooling): findings carry ruleId,
    message, physical location, and the allowlist key as a
    fingerprint; a dirty tree still exits 1."""
    from fluidframework_tpu.analysis import __main__ as cli

    svc = tmp_path / "service"
    svc.mkdir()
    bad = svc / "bad.py"
    bad.write_text(
        "import time\n"
        "async def handle():\n"
        "    time.sleep(1)\n"
    )
    monkeypatch.setattr(cli, "REPO_ROOT", str(tmp_path))
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main([str(bad), "--sarif", "--rules", "concheck"])
    assert rc == 1
    report = json.loads(buf.getvalue())
    assert report["version"] == "2.1.0"
    (run,) = report["runs"]
    assert run["tool"]["driver"]["name"] == "fluidlint"
    (result,) = run["results"]
    assert result["ruleId"] == "async-blocking-call"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 3
    assert result["partialFingerprints"]["fluidlintKey"] == \
        "bad.py:handle:time.sleep"
    # SARIF semantics: findings do NOT make the run unsuccessful (the
    # tool completed); consumers discard results of "failed" runs
    assert report["runs"][0]["invocations"][0]["executionSuccessful"]

    # clean tree: empty results, executionSuccessful, exit 0
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main([str(clean), "--sarif"])
    assert rc == 0
    report = json.loads(buf.getvalue())
    assert report["runs"][0]["results"] == []
    assert report["runs"][0]["invocations"][0]["executionSuccessful"]


def test_bench_records_carry_fluidlint_counts(monkeypatch):
    """Stage records embed the per-family finding trajectory next to
    metrics_registry (machine-readable debt curve across rounds).
    FAMILIES is narrowed to the cheap non-interprocedural pair here —
    the full-tree cleanliness of every family is the gate test's
    job, this one pins the record SHAPE and memoization."""
    import bench

    monkeypatch.setattr(bench, "_FLUIDLINT_CACHE", None)
    monkeypatch.setattr(bench, "_FLUIDLINT_RAN", False)
    monkeypatch.setattr(core, "FAMILIES", ("layercheck", "qoscheck"))
    counts = bench._fluidlint_counts()
    assert counts is not None
    assert set(counts) == {"layercheck", "qoscheck"}
    for fam, c in counts.items():
        assert set(c) == {"findings", "allowlisted"}, fam
        # the gate keeps the live tree clean
        assert c["findings"] == 0, (fam, c)
    # memoized: the second call must not re-run the analyzer
    assert bench._fluidlint_counts() is counts


def test_cli_json_mode_exits_zero_on_clean_tree():
    """The `--json` surface BENCH/ADVICE tooling consumes: exit 0 and
    a well-formed empty report on a clean tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "fluidframework_tpu.analysis",
         "--json"],
        capture_output=True, text=True, cwd=core.REPO_ROOT,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    assert report["stale_allowlist"] == []
    assert sorted(report["families"]) == sorted(core.FAMILIES)
