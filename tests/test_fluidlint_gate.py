"""The fluidlint gate: every pass family over the whole repo, wired
into tier-1 so the analyzer's invariants hold forever after.

Green means: zero non-allowlisted findings AND zero stale allowlist
entries (the ratchet — grandfathered findings may only disappear,
never accumulate; see docs/ANALYSIS.md for the policy).
"""
import json
import subprocess
import sys

from fluidframework_tpu.analysis import core

# the ratchet cap (acceptance: <= 10 grandfathered findings). This
# number may be LOWERED as entries burn down; never raised.
MAX_ALLOWLIST_ENTRIES = 10


def _gate():
    findings = core.run_analysis()
    allowlist = core.load_allowlist()
    kept, stale = core.apply_allowlist(findings, allowlist)
    return kept, stale, allowlist


def test_fluidlint_gate_is_clean():
    kept, stale, _ = _gate()
    problems = [f.format() for f in kept]
    problems += [
        f"stale allowlist entry '{rule} {key}' matches no live "
        "finding — delete it from analysis/allowlist.txt"
        for rule, key in stale
    ]
    assert not problems, (
        "fluidlint gate failed (fix the code, add a justified "
        "'# fluidlint: disable=<rule>' inline, or — for pre-existing "
        "debt only — allowlist it):\n" + "\n".join(problems)
    )


def test_allowlist_ratchet_cap():
    allowlist = core.load_allowlist()
    assert len(allowlist) <= MAX_ALLOWLIST_ENTRIES, (
        f"allowlist has {len(allowlist)} entries, cap is "
        f"{MAX_ALLOWLIST_ENTRIES}: the list only ratchets DOWN — fix "
        "findings instead of grandfathering new ones"
    )


def test_obs_untimed_hop_rule_fires_on_unregistered_hops(tmp_path):
    """The obs-untimed-hop rule (obscheck family): a module stamping
    a hop name outside the canonical table in obs/trace.py fails; a
    canonical stamp passes. Covers both the stamp() call form and a
    direct Trace(...) construction."""
    fixture = tmp_path / "bad_hops.py"
    fixture.write_text(
        "from fluidframework_tpu.obs.trace import stamp\n"
        "from fluidframework_tpu.protocol.messages import Trace\n"
        "def f(traces):\n"
        "    stamp(traces, 'client', 'submit')\n"       # canonical
        "    stamp(traces, 'warpdrive', 'engage')\n"    # not
        "    traces.append(Trace('sequencer', 'ticket'))\n"  # canonical
        "    traces.append(Trace('gremlin', 'nibble'))\n"    # not
        "    name = 'dyn'\n"
        "    stamp(traces, name, name)\n"  # dynamic: runtime's job
    )
    findings = core.run_analysis(
        roots=[str(fixture)], families=["obscheck"],
    )
    keys = sorted(f.key for f in findings)
    assert keys == [
        "bad_hops.py:gremlin:nibble",
        "bad_hops.py:warpdrive:engage",
    ]
    assert all(f.rule == "obs-untimed-hop" for f in findings)

    # a module's own unrelated stamp()/Trace() — no obs/protocol
    # import — must NOT false-positive the gate
    unrelated = tmp_path / "unrelated.py"
    unrelated.write_text(
        "def stamp(canvas, layer, mode):\n"
        "    return (canvas, layer, mode)\n"
        "class Trace:\n"
        "    def __init__(self, a, b):\n"
        "        pass\n"
        "def g(c):\n"
        "    stamp(c, 'fill', 'round')\n"
        "    Trace('not', 'a-hop')\n"
    )
    assert core.run_analysis(
        roots=[str(unrelated)], families=["obscheck"],
    ) == []


def test_obs_canonical_table_stays_statically_readable():
    """obscheck must keep extracting the hop table without importing
    the obs package (the linter depends on nothing it lints); this
    breaks loudly if CANONICAL_HOPS stops being a pure literal."""
    from fluidframework_tpu.analysis.obscheck import load_canonical_hops

    hops = load_canonical_hops()
    assert ("sequencer", "ticket") in hops
    assert ("client", "submit") in hops
    assert ("sidecar", "settle") in hops


def test_obscheck_family_is_in_the_gate():
    assert "obscheck" in core.FAMILIES


def test_service_unbounded_queue_rule_fires_in_service_paths(
        tmp_path):
    """The service-unbounded-queue rule (qoscheck family): an
    unbounded asyncio.Queue()/deque() in a service/qos path fails;
    bounded constructions and justified inline disables pass; the
    same code OUTSIDE a service path is not the rule's business."""
    svc_dir = tmp_path / "service"
    svc_dir.mkdir()
    bad = svc_dir / "bad.py"
    bad.write_text(
        "import asyncio\n"
        "from collections import deque\n"
        "class Session:\n"
        "    def __init__(self):\n"
        "        self.outbound = asyncio.Queue()\n"            # BAD
        "        self.infinite = asyncio.Queue(maxsize=0)\n"   # BAD
        "        self.bounded = asyncio.Queue(maxsize=100)\n"  # ok
        "        self.log = deque()\n"                         # BAD
        "        self.ring = deque((), 64)\n"                  # ok
        "        self.ok = deque(maxlen=8)\n"                  # ok
        "        self.justified = deque()  "
        "# fluidlint: disable=service-unbounded-queue -- test\n"
    )
    findings = core.run_analysis(
        roots=[str(bad)], families=["qoscheck"],
    )
    assert sorted(f.key for f in findings) == [
        "bad.py:Session.__init__.infinite",
        "bad.py:Session.__init__.log",
        "bad.py:Session.__init__.outbound",
    ]
    assert all(
        f.rule == "service-unbounded-queue" for f in findings
    )

    # a module's own class named Queue/deque (no import) must not
    # false-positive, and non-service paths are out of scope
    other = tmp_path / "elsewhere.py"
    other.write_text(
        "import asyncio\n"
        "q = asyncio.Queue()\n"
    )
    assert core.run_analysis(
        roots=[str(other)], families=["qoscheck"],
    ) == []
    own = svc_dir / "own.py"
    own.write_text(
        "class deque:\n"
        "    pass\n"
        "d = deque()\n"
    )
    assert core.run_analysis(
        roots=[str(own)], families=["qoscheck"],
    ) == []


def test_qoscheck_family_is_in_the_gate():
    assert "qoscheck" in core.FAMILIES


def test_cli_json_mode_exits_zero_on_clean_tree():
    """The `--json` surface BENCH/ADVICE tooling consumes: exit 0 and
    a well-formed empty report on a clean tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "fluidframework_tpu.analysis",
         "--json"],
        capture_output=True, text=True, cwd=core.REPO_ROOT,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    assert report["stale_allowlist"] == []
    assert sorted(report["families"]) == sorted(core.FAMILIES)
