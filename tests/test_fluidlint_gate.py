"""The fluidlint gate: every pass family over the whole repo, wired
into tier-1 so the analyzer's invariants hold forever after.

Green means: zero non-allowlisted findings AND zero stale allowlist
entries (the ratchet — grandfathered findings may only disappear,
never accumulate; see docs/ANALYSIS.md for the policy).
"""
import json
import subprocess
import sys

from fluidframework_tpu.analysis import core

# the ratchet cap (acceptance: <= 10 grandfathered findings). This
# number may be LOWERED as entries burn down; never raised.
MAX_ALLOWLIST_ENTRIES = 10


def _gate():
    findings = core.run_analysis()
    allowlist = core.load_allowlist()
    kept, stale = core.apply_allowlist(findings, allowlist)
    return kept, stale, allowlist


def test_fluidlint_gate_is_clean():
    kept, stale, _ = _gate()
    problems = [f.format() for f in kept]
    problems += [
        f"stale allowlist entry '{rule} {key}' matches no live "
        "finding — delete it from analysis/allowlist.txt"
        for rule, key in stale
    ]
    assert not problems, (
        "fluidlint gate failed (fix the code, add a justified "
        "'# fluidlint: disable=<rule>' inline, or — for pre-existing "
        "debt only — allowlist it):\n" + "\n".join(problems)
    )


def test_allowlist_ratchet_cap():
    allowlist = core.load_allowlist()
    assert len(allowlist) <= MAX_ALLOWLIST_ENTRIES, (
        f"allowlist has {len(allowlist)} entries, cap is "
        f"{MAX_ALLOWLIST_ENTRIES}: the list only ratchets DOWN — fix "
        "findings instead of grandfathering new ones"
    )


def test_cli_json_mode_exits_zero_on_clean_tree():
    """The `--json` surface BENCH/ADVICE tooling consumes: exit 0 and
    a well-formed empty report on a clean tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "fluidframework_tpu.analysis",
         "--json"],
        capture_output=True, text=True, cwd=core.REPO_ROOT,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    assert report["stale_allowlist"] == []
    assert sorted(report["families"]) == sorted(core.FAMILIES)
