"""Summarizer subsystem: election, heuristics, ack flow, failover.

Mirrors container-runtime summarizer tests (summaryManager,
orderedClientElection, runningSummarizer w/ heuristics) over the
in-proc service.
"""
import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.runtime import (
    OrderedClientElection,
    SummarizerHeuristics,
    SummaryManager,
)
from fluidframework_tpu.service.local_server import LocalServer


def heuristics():
    return SummarizerHeuristics(max_ops=5)


def make(n=2, doc="doc"):
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    names = ["alice", "bob", "carol"][:n]
    containers = [
        Container.load(factory.create_document_service(doc), client_id=c)
        for c in names
    ]
    managers = [
        SummaryManager(c, heuristics_factory=heuristics)
        for c in containers
    ]
    return server, factory, containers, managers


# ----------------------------------------------------------------------
# election

def test_election_oldest_eligible_client():
    e = OrderedClientElection()
    e.add_client("read-1", eligible=False)
    e.add_client("w-1")
    e.add_client("w-2")
    assert e.elected == "w-1"
    e.remove_client("w-1")
    assert e.elected == "w-2"


def test_first_joined_container_becomes_summarizer():
    server, factory, (a, b), (ma, mb) = make(2)
    assert ma.is_summarizer
    assert not mb.is_summarizer


def test_summary_produced_after_op_threshold_and_acked():
    server, factory, (a, b), (ma, mb) = make(2)
    ds = a.runtime.create_datastore("d")
    m = ds.create_channel("sharedmap", "kv")
    a.flush()
    acked = []
    ma.collection.on("summaryAck", lambda ack: acked.append(ack))
    for i in range(8):
        m.set(f"k{i}", i)
        a.flush()
    assert acked, "no summary ack observed"
    assert ma.running.summaries_produced >= 1
    # ack observed by the non-summarizer too
    assert mb.collection.last_ack_seq > 0
    # service summary actually stored
    assert server.get_orderer("doc").summary_store.latest() is not None


def test_new_client_loads_from_produced_summary():
    server, factory, (a, b), (ma, mb) = make(2)
    ds = a.runtime.create_datastore("d")
    m = ds.create_channel("sharedmap", "kv")
    a.flush()
    for i in range(8):
        m.set(f"k{i}", i)
        a.flush()
    assert ma.collection.last_ack_seq > 0
    late = Container.load(factory.create_document_service("doc"),
                          client_id="dora")
    kv = late.runtime.get_datastore("d").get_channel("kv")
    assert kv.get("k7") == 7


def test_summarizer_failover_on_leave():
    server, factory, (a, b), (ma, mb) = make(2)
    ds = a.runtime.create_datastore("d")
    m = ds.create_channel("sharedmap", "kv")
    a.flush()
    assert ma.is_summarizer and not mb.is_summarizer
    a.disconnect()
    # bob observes alice's leave and takes over
    assert mb.is_summarizer
    mb_chan = b.runtime.get_datastore("d").get_channel("kv")
    acked = []
    mb.collection.on("summaryAck", lambda ack: acked.append(ack))
    for i in range(8):
        mb_chan.set(f"x{i}", i)
        b.flush()
    assert acked, "failover summarizer produced no ack"


def test_summarizer_defers_while_dirty_then_fires_on_tick():
    """The dirty guard blocks an attempt; a later tick (once
    quiescent) produces the deferred summary."""
    server, factory, (a, b), (ma, mb) = make(2)
    ds = a.runtime.create_datastore("d")
    m = ds.create_channel("sharedmap", "kv")
    a.flush()
    run = ma.running
    run.heuristics.ops_since_summary = 99  # over threshold
    m.set("unflushed", 1)  # outbox non-empty -> dirty
    assert a.runtime.is_dirty
    run.maybe_summarize()
    assert not run.attempt_pending  # deferred, not attempted
    produced = run.summaries_produced
    a.flush()  # quiescent again (sync service acks immediately)
    run.heuristics.ops_since_summary = 99
    ma.tick()
    assert run.summaries_produced > produced


def test_time_heuristic_fires_via_tick_on_quiet_document():
    clock = [0.0]
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice")
    ma = SummaryManager(a, heuristics_factory=lambda: SummarizerHeuristics(
        max_ops=1000, max_time_s=60, clock=lambda: clock[0]))
    m = a.runtime.create_datastore("d").create_channel("sharedmap", "kv")
    a.flush()
    m.set("k", 1)
    a.flush()
    assert ma.running.summaries_produced == 0
    clock[0] = 120.0  # a minute passes with zero traffic
    ma.tick()
    assert ma.running.summaries_produced == 1


def test_foreign_summary_ack_not_claimed_by_summarizer():
    """Another client's direct summarize() must not be attributed to
    the elected summarizer's attempt."""
    server, factory, (a, b), (ma, mb) = make(2)
    m = a.runtime.create_datastore("d").create_channel("sharedmap", "kv")
    a.flush()
    m.set("k", 1)
    a.flush()
    produced = ma.running.summaries_produced
    b.summarize()  # bob summarizes out-of-band
    assert ma.running.summaries_produced == produced
    # but the freshness reset applies: the heuristic saw a summary
    assert ma.running.heuristics.ops_since_summary == 0


def test_summary_manager_dispose_detaches():
    server, factory, (a, b), (ma, mb) = make(2)
    m = a.runtime.create_datastore("d").create_channel("sharedmap", "kv")
    a.flush()
    ma.dispose()
    assert ma.disposed and not ma.is_summarizer
    for i in range(10):
        m.set(f"k{i}", i)
        a.flush()
    # no summaries: the disposed manager stopped observing
    assert server.get_orderer("doc").summary_store.latest() is None


def test_auto_summarize_permission_error_sticky_not_fatal():
    """A PermissionError from the upload plane on the AUTO path (event
    pump) must not unwind into the driver's dispatch loop (it would
    kill delta processing for every doc on the connection): the
    summarizer records it, goes sticky-disabled, and the pump lives
    (code-review r5)."""
    server, factory, (a, b), (ma, mb) = make(2)

    def denied(summary):
        raise PermissionError("token lacks doc:write")

    a.service.upload_summary = denied
    events = []
    ma.running.on("authFailed", lambda e: events.append(e))
    t = a.runtime.create_datastore("ds").create_channel(
        "sharedstring", "t")
    a.flush()
    for i in range(8):  # past the op threshold (5)
        t.insert_text(0, "x")
        a.flush()  # would raise out of the pump without the fix
    assert ma.running.auth_failed
    assert len(events) == 1 and isinstance(events[0], PermissionError)
    # sticky: no further attempts, and no exception on later ops
    t.insert_text(0, "y")
    a.flush()
    assert ma.running.summaries_produced == 0
