"""Container/datastore runtime stack tests: routing, batching, pending
state, reconnect replay, summarize/load — across every channel type.

Mirrors the reference DDS tests' create-clients/interleave/processAll
pattern at the container level (mocks.ts:196 usage)."""
import random

import pytest

from fluidframework_tpu.testing.runtime_mocks import ContainerSession


def make_session(n=2, channels=("sharedstring", "sharedmap",
                                "sharedcell", "sharedcounter",
                                "shareddirectory")):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    for cid in ids:
        ds = s.runtime(cid).create_datastore("default")
        for ctype in channels:
            ds.create_channel(ctype, ctype)
    return s, ids


def chan(s, cid, name):
    return s.runtime(cid).get_datastore("default").get_channel(name)


def test_string_through_runtime_stack():
    s, _ = make_session()
    chan(s, "A", "sharedstring").insert_text(0, "hello")
    s.process_all()
    chan(s, "B", "sharedstring").insert_text(5, " world")
    s.process_all()
    s.assert_converged()
    assert chan(s, "A", "sharedstring").get_text() == "hello world"


def test_map_pending_wins_until_ack():
    s, _ = make_session()
    a, b = chan(s, "A", "sharedmap"), chan(s, "B", "sharedmap")
    b.set("k", "remote")   # sequenced first
    a.set("k", "local")    # sequenced second
    s.flush("B")
    s.process_all()        # only B's op ticketed so far? both flushed below
    s.process_all()
    s.assert_converged()
    assert a.get("k") == "local"
    assert b.get("k") == "local"


def test_map_clear_vs_concurrent_set():
    s, _ = make_session()
    a, b = chan(s, "A", "sharedmap"), chan(s, "B", "sharedmap")
    a.set("x", 1)
    s.process_all()
    a.clear()               # sequenced first
    b.set("y", 2)           # concurrent, sequenced second
    s.process_all()
    s.assert_converged()
    assert not a.has("x")
    assert a.get("y") == 2  # set sequenced after clear survives


def test_cell_and_counter():
    s, _ = make_session()
    chan(s, "A", "sharedcell").set("v1")
    chan(s, "B", "sharedcounter").increment(5)
    chan(s, "A", "sharedcounter").increment(-2)
    s.process_all()
    s.assert_converged()
    assert chan(s, "B", "sharedcell").get() == "v1"
    assert chan(s, "A", "sharedcounter").value == 3


def test_directory_subdirs():
    s, _ = make_session()
    a = chan(s, "A", "shareddirectory")
    b = chan(s, "B", "shareddirectory")
    a.create_sub_directory("users")
    a.set("alice", 1, path="/users")
    b.set("root", True)
    s.process_all()
    s.assert_converged()
    assert b.get("alice", path="/users") == 1
    assert a.get("root") is True
    a.delete_sub_directory("users")
    s.process_all()
    s.assert_converged()
    assert not b.has_sub_directory("users")


def test_batching_order_sequentially():
    s, _ = make_session()
    rt = s.runtime("A")
    ss = chan(s, "A", "sharedstring")

    def batch():
        ss.insert_text(0, "ab")
        ss.insert_text(2, "cd")

    rt.order_sequentially(batch)
    s.process_all()
    s.assert_converged()
    assert chan(s, "B", "sharedstring").get_text() == "abcd"


def test_runtime_reconnect_with_offline_edits():
    s, _ = make_session()
    chan(s, "A", "sharedstring").insert_text(0, "base")
    chan(s, "A", "sharedmap").set("k", 0)
    s.process_all()
    s.disconnect("A")
    chan(s, "A", "sharedstring").insert_text(4, "-off")
    chan(s, "A", "sharedmap").set("k", 1)
    chan(s, "A", "sharedcounter").increment(7)
    s.flush("A")
    chan(s, "B", "sharedstring").insert_text(0, "B:")
    s.process_all()
    s.reconnect("A")
    s.process_all()
    s.assert_converged()
    assert chan(s, "B", "sharedstring").get_text() == "B:base-off"
    assert chan(s, "B", "sharedmap").get("k") == 1
    assert chan(s, "B", "sharedcounter").value == 7


def test_summarize_then_load_new_client():
    s, ids = make_session()
    chan(s, "A", "sharedstring").insert_text(0, "snapshot me")
    chan(s, "A", "sharedmap").set("key", [1, 2])
    chan(s, "A", "sharedcounter").increment(9)
    s.process_all()
    s.assert_converged()
    summary = s.runtime("A").summarize()

    import json
    json.dumps(summary)  # summaries must be JSON-safe

    # a late-joining client loads from the summary and keeps editing
    from fluidframework_tpu.protocol.messages import ClientDetail
    from fluidframework_tpu.runtime import ContainerRuntime
    from fluidframework_tpu.models import default_registry
    from fluidframework_tpu.testing.runtime_mocks import _Endpoint

    rt = ContainerRuntime(default_registry())
    rt.set_submit_fn(lambda c, m: s._enqueue("C", c))
    rt.load(summary)
    rt.set_connection_state(True, "C")
    s.endpoints["C"] = _Endpoint(runtime=rt,
                                 last_seen_seq=s.sequencer.sequence_number)
    s._broadcast(s.sequencer.client_join(ClientDetail("C")))

    cstr = rt.get_datastore("default").get_channel("sharedstring")
    assert cstr.get_text() == "snapshot me"
    cstr.insert_text(0, "C>")
    s.process_all()
    s.assert_converged()
    assert chan(s, "A", "sharedstring").get_text() == "C>snapshot me"


@pytest.mark.parametrize("seed", range(8))
def test_runtime_multichannel_fuzz(seed):
    """Random ops across channel types + reconnect churn."""
    rng = random.Random(seed + 777)
    s, ids = make_session(3)
    down = set()
    for _ in range(120):
        r = rng.random()
        cid = rng.choice(ids)
        if r < 0.04 and len(down) < 2:
            target = rng.choice([c for c in ids if c not in down])
            s.disconnect(target)
            down.add(target)
        elif r < 0.10 and down:
            target = rng.choice(sorted(down))
            s.reconnect(target)
            down.remove(target)
        elif r < 0.3 and s.pending_count:
            s.process_some(rng.randint(1, s.pending_count))
        else:
            kind = rng.choice(["str", "map", "cell", "counter", "dir",
                               "flush"])
            if kind == "str":
                ss = chan(s, cid, "sharedstring")
                length = ss.get_length()
                if length > 3 and rng.random() < 0.4:
                    start = rng.randint(0, length - 2)
                    ss.remove_text(start,
                                   rng.randint(start + 1, length))
                else:
                    ss.insert_text(rng.randint(0, length), "ab")
            elif kind == "map":
                chan(s, cid, "sharedmap").set(
                    rng.choice("xyz"), rng.randint(0, 9)
                )
            elif kind == "cell":
                chan(s, cid, "sharedcell").set(rng.randint(0, 99))
            elif kind == "counter":
                chan(s, cid, "sharedcounter").increment(rng.randint(1, 5))
            elif kind == "dir":
                chan(s, cid, "shareddirectory").set(
                    rng.choice("ab"), rng.randint(0, 9)
                )
            else:
                s.flush(cid)
    for cid in sorted(down):
        s.reconnect(cid)
    s.process_all()
    s.assert_converged()
