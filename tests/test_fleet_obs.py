"""Fleet observability plane (PR13): metrics federation
(obs/federation.py), the causal fleet timeline (obs/timeline.py), the
timeline OTLP export, and the `fleet-metrics` wire/CLI surface.

The cross-node trace-propagation half (repl hops in op_breakdown,
round-trip bit-exactness through the replicated plane) lives in
tests/test_replication.py next to the mechanisms it instruments; the
chaos-federation determinism differential lives in tests/test_chaos.py.
"""
import json

import pytest

from fluidframework_tpu.obs import metrics as obs_metrics
from fluidframework_tpu.obs.federation import FederatedView, parse_labels
from fluidframework_tpu.obs.metrics import MetricsRegistry
from fluidframework_tpu.obs.slo import Objective, SloEngine
from fluidframework_tpu.obs.spans import timeline_to_otlp
from fluidframework_tpu.obs.timeline import TIMELINE_KINDS, FleetTimeline


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _two_nodes():
    a = MetricsRegistry(node="n0")
    b = MetricsRegistry(node="n1")
    return a, b


# ======================================================================
# federation: merge semantics


def test_counters_sum_across_nodes_per_label_set():
    a, b = _two_nodes()
    a.counter("f_ops_total", "ops").inc(3)
    b.counter("f_ops_total", "ops").inc(4)
    a.counter("f_lab_total", "ops", labelnames=("k",)) \
        .labels(k="x").inc(1)
    b.counter("f_lab_total", "ops", labelnames=("k",)) \
        .labels(k="x").inc(2)
    b.counter("f_lab_total", "ops", labelnames=("k",)) \
        .labels(k="y").inc(7)
    view = FederatedView(clock=_Clock())
    view.add_registry("n0", a)
    view.add_registry("n1", b)
    merged = view.refresh()
    assert merged["f_ops_total"]["values"][""] == 7.0
    assert merged["f_lab_total"]["values"]['{k="x"}'] == 3.0
    assert merged["f_lab_total"]["values"]['{k="y"}'] == 7.0
    # and the merged registry serves every existing surface
    assert "f_ops_total 7.0" in view.registry.render_prometheus()
    assert view.registry.flat()["f_ops_total"] == 7.0


def test_gauges_keep_per_node_identity_under_a_node_label():
    a, b = _two_nodes()
    a.gauge("f_head", "head").set(5)
    b.gauge("f_head", "head").set(9)
    a.gauge("f_depth", "d", labelnames=("shard",)) \
        .labels(shard="0").set(2)
    view = FederatedView(clock=_Clock())
    view.add_registry("n0", a)
    view.add_registry("n1", b)
    merged = view.refresh()
    assert merged["f_head"]["values"] == {
        '{node="n0"}': 5.0, '{node="n1"}': 9.0}
    assert merged["f_depth"]["values"] == {
        '{node="n0",shard="0"}': 2.0}


def test_histograms_merge_bucket_wise():
    a, b = _two_nodes()
    ha = a.histogram("f_lat_ms", "lat", buckets=(1.0, 10.0))
    hb = b.histogram("f_lat_ms", "lat", buckets=(1.0, 10.0))
    ha.observe(0.5)
    ha.observe(5.0)
    hb.observe(50.0)
    view = FederatedView(clock=_Clock())
    view.add_registry("n0", a)
    view.add_registry("n1", b)
    merged = view.refresh()
    value = merged["f_lat_ms"]["values"][""]
    assert value["count"] == 3
    assert value["sum"] == 55.5
    assert value["buckets"] == {"1.0": 1, "10.0": 2, "+Inf": 3}
    # a bound SLO objective over the merged histogram sees the fleet
    child = view.registry.get("f_lat_ms")._solo()
    assert child.count_le(10.0) == 2


def test_label_escaping_round_trips_through_the_merge():
    a, b = _two_nodes()
    hairy = 'q"uo\\te\nnl'
    a.counter("f_esc_total", "ops", labelnames=("k",)) \
        .labels(k=hairy).inc(1)
    b.counter("f_esc_total", "ops", labelnames=("k",)) \
        .labels(k=hairy).inc(2)
    view = FederatedView(clock=_Clock())
    view.add_registry("n0", a)
    view.add_registry("n1", b)
    view.refresh()
    child = view.registry.get("f_esc_total").labels(k=hairy)
    assert child.value == 3.0
    # the parser really is _render_labels' inverse
    rendered = list(a.snapshot()["f_esc_total"]["values"])[0]
    assert parse_labels(rendered) == [("k", hairy)]


def test_kind_mismatch_and_bucket_mismatch_fail_loudly():
    a, b = _two_nodes()
    a.counter("f_clash", "x")
    b.gauge("f_clash", "x")
    view = FederatedView(clock=_Clock())
    view.add_registry("n0", a)
    view.add_registry("n1", b)
    with pytest.raises(ValueError, match="two definitions"):
        view.refresh()
    c, d = _two_nodes()
    c.histogram("f_h_ms", "x", buckets=(1.0,)).observe(0.5)
    d.histogram("f_h_ms", "x", buckets=(2.0,)).observe(0.5)
    view2 = FederatedView(clock=_Clock())
    view2.add_registry("n0", c)
    view2.add_registry("n1", d)
    with pytest.raises(ValueError, match="bucket bounds"):
        view2.refresh()


def test_view_refuses_to_federate_its_own_registry():
    view = FederatedView(clock=_Clock())
    with pytest.raises(ValueError):
        view.add_registry("fleet", view.registry)


def test_wire_snapshots_age_and_node_identity():
    a, _ = _two_nodes()
    a.counter("f_remote_total", "ops").inc(2)
    clock = _Clock(t=100.0)
    view = FederatedView(clock=clock)
    shipped = a.node_snapshot()
    assert shipped["node"] == "n0"
    view.add_snapshot(shipped["node"], shipped["metrics"],
                      captured_at=90.0)
    merged = view.refresh()
    assert merged["f_remote_total"]["values"][""] == 2.0
    assert merged["fleet_nodes"]["values"][""] == 1.0
    assert merged["fleet_snapshot_age_s"]["values"][""] == 10.0
    # a live registry under the same node id replaces the snapshot
    view.add_registry("n0", a)
    merged = view.refresh()
    assert merged["fleet_snapshot_age_s"]["values"][""] == 0.0


def test_refresh_rewrites_children_in_place():
    """Child identity survives refresh — the SLO binding contract."""
    a, _ = _two_nodes()
    counter = a.counter("f_grow_total", "ops")
    counter.inc(1)
    view = FederatedView(clock=_Clock())
    view.add_registry("n0", a)
    view.refresh()
    child = view.registry.get("f_grow_total")._solo()
    assert child.value == 1.0
    counter.inc(4)
    view.refresh()
    assert view.registry.get("f_grow_total")._solo() is child
    assert child.value == 5.0


def test_refresh_prunes_series_a_replaced_node_stopped_exporting():
    """Ghost-metric regression: replacing a node's source (the
    documented add_snapshot/add_registry replacement semantics) must
    not leave the old node state being served forever."""
    a, _ = _two_nodes()
    a.counter("f_old_total", "ops").inc(7)
    a.gauge("f_old_head", "head").set(3)
    view = FederatedView(clock=_Clock())
    view.add_registry("n1", a)
    merged = view.refresh()
    assert merged["f_old_total"]["values"][""] == 7.0
    # the replacement snapshot no longer carries f_old_*
    fresh = MetricsRegistry(node="n1")
    fresh.counter("f_new_total", "ops").inc(1)
    view.add_snapshot("n1", fresh.snapshot())
    merged = view.refresh()
    assert "f_old_total" not in merged
    assert "f_old_head" not in merged
    assert "f_old_total" not in view.counter_totals()
    assert merged["f_new_total"]["values"][""] == 1.0
    # per-series pruning too: a vanished label set goes, the rest stay
    b = MetricsRegistry(node="n2")
    fam = b.counter("f_lab2_total", "ops", labelnames=("k",))
    fam.labels(k="x").inc(1)
    fam.labels(k="y").inc(2)
    view.add_registry("n2", b)
    view.refresh()
    b2 = MetricsRegistry(node="n2")
    b2.counter("f_lab2_total", "ops", labelnames=("k",)) \
        .labels(k="y").inc(5)
    view.add_snapshot("n2", b2.snapshot())
    merged = view.refresh()
    assert merged["f_lab2_total"]["values"] == {'{k="y"}': 5.0}
    # the view's own gauges survive pruning
    assert merged["fleet_nodes"]["values"][""] == 2.0


# ======================================================================
# federated SLO grading


def test_slo_objective_grades_the_whole_plane_through_federation():
    """A per-partition goodput objective bound to MERGED counters:
    one healthy partition cannot mask a failing one's share of the
    fleet's error budget (the federated good/total ratio is the
    plane's, not any node's)."""
    a, b = _two_nodes()
    ga = a.counter("f_good_total", "good")
    ta = a.counter("f_off_total", "offered")
    gb = b.counter("f_good_total", "good")
    tb = b.counter("f_off_total", "offered")
    clock = _Clock()
    view = FederatedView(clock=clock)
    view.add_registry("n0", a)
    view.add_registry("n1", b)
    view.refresh()  # families must exist before binding
    engine = SloEngine(
        [Objective("fleet-goodput", kind="goodput",
                   good_metric="f_good_total",
                   total_metric="f_off_total", target=0.9)],
        registry=view.registry, refresh=view.refresh,
        fast_window_s=1.0, slow_window_s=12.0, clock=clock,
    )
    # node n0 serves perfectly; n1 drops half its ops
    for _ in range(20):
        ga.inc()
        ta.inc()
        gb.inc(0.5)
        tb.inc()
        clock.t += 0.1
        engine.tick()
    report = engine.evaluate()
    (obj,) = report["objectives"]
    assert obj["verdict"] == "breach", obj
    assert obj["fast"]["burn"] > 1.0


# ======================================================================
# the fleet timeline


def test_timeline_kinds_are_validated_and_counted():
    reg = MetricsRegistry(node="t")
    tl = FleetTimeline(clock=_Clock(), registry=reg)
    tl.record("lease_grant", node="node-0", ttl=0.3)
    tl.record("promotion", node="node-1", epoch=2)
    with pytest.raises(ValueError, match="unknown timeline event"):
        tl.record("warp_drive", node="node-0")
    flat = reg.flat()
    assert flat['timeline_events_total{kind="lease_grant"}'] == 1
    assert flat['timeline_events_total{kind="promotion"}'] == 1
    assert len(tl) == 2
    assert [e.kind for e in tl.events("promotion")] == ["promotion"]


def test_timeline_seq_is_causal_and_capacity_bounded():
    clock = _Clock()
    tl = FleetTimeline(clock=clock, registry=MetricsRegistry(),
                       capacity=8)
    for i in range(20):
        clock.t = i * 0.05
        tl.record("lease_renew", node="node-0")
    assert len(tl) == 8
    assert tl.dropped == 12
    seqs = [e.seq for e in tl.events()]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert seqs[-1] == 20  # causal position survives the ring


def test_failover_phases_decompose_and_sum_exactly():
    clock = _Clock()
    tl = FleetTimeline(clock=clock, registry=MetricsRegistry())
    assert tl.failover_phases() is None
    tl.record("leader_kill", node="node-0", mode="clean")
    clock.t = 0.31
    tl.record("lease_expire", node="node-0", origin="observed")
    clock.t = 0.32
    tl.record("anti_entropy", node="node-1", source="node-2", ops=3)
    clock.t = 0.34
    tl.record("lease_grant", node="node-1", ttl=0.3)
    tl.record("epoch_advance", epoch=2)
    clock.t = 0.35
    tl.record("promotion", node="node-1", epoch=2)
    assert tl.failover_phases() is None  # no first_ack yet
    clock.t = 0.50
    tl.record("first_ack", node="node-1")
    phases = tl.failover_phases()
    assert phases == {
        "detection_s": 0.31,
        "anti_entropy_s": pytest.approx(0.03),
        "promotion_s": pytest.approx(0.01),
        "first_ack_s": pytest.approx(0.15),
        "total_s": 0.5,
    }
    total = (phases["detection_s"] + phases["anti_entropy_s"]
             + phases["promotion_s"] + phases["first_ack_s"])
    assert total == pytest.approx(phases["total_s"])
    assert "leader_kill" in tl.format()


def test_timeline_otlp_export_is_deterministic_and_causal():
    clock = _Clock()
    tl = FleetTimeline(clock=clock, registry=MetricsRegistry())
    tl.record("leader_kill", node="node-0", mode="clean")
    clock.t = 0.31
    tl.record("lease_expire", node="node-0", origin="observed")
    clock.t = 0.35
    tl.record("promotion", node="node-1", epoch=2)
    doc = timeline_to_otlp(tl.events())
    assert doc == timeline_to_otlp(tl.events()), "export not stable"
    (rs,) = doc["resourceSpans"]
    spans = rs["scopeSpans"][0]["spans"]
    assert [s["name"] for s in spans] == [
        "fleet_timeline", "leader_kill", "lease_expire", "promotion"]
    root, children = spans[0], spans[1:]
    assert all(s["parentSpanId"] == root["spanId"] for s in children)
    assert all(s["traceId"] == root["traceId"] for s in children)
    # child windows tile the incident ([prev, t] — the hop-span shape)
    assert children[1]["startTimeUnixNano"] == \
        children[0]["endTimeUnixNano"]
    attrs = {a["key"]: a["value"] for a in children[2]["attributes"]}
    assert attrs["fleet.node"]["stringValue"] == "node-1"
    assert attrs["fleet.epoch"]["intValue"] == "2"
    # the exact-float contract carries over from the op spans
    assert attrs["fluid.timestamp"]["stringValue"] == repr(0.35)


def test_timeline_kind_table_is_a_pure_literal():
    """The CANONICAL_HOPS discipline: the metric label vocabulary is
    bounded by a literal table."""
    import ast

    with open("fluidframework_tpu/obs/timeline.py") as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "TIMELINE_KINDS"
            for t in node.targets
        ):
            assert ast.literal_eval(node.value) == TIMELINE_KINDS
            break
    else:
        raise AssertionError("TIMELINE_KINDS literal not found")


# ======================================================================
# the wire + CLI surface


def test_ingress_fleet_metrics_frame_and_dump_cli(alfred):
    import socket as socket_mod

    from fluidframework_tpu.service.__main__ import dump_fleet
    from fluidframework_tpu.service.ingress import (
        pack_frame,
        recv_frame_blocking,
    )

    server = alfred()
    with socket_mod.create_connection(
            ("127.0.0.1", server.port), timeout=10) as sock:
        sock.sendall(pack_frame({"type": "fleet-metrics", "rid": 9}))
        frame = recv_frame_blocking(sock)
    assert frame["type"] == "fleet-metrics" and frame["rid"] == 9
    # no view attached -> the process registry as a one-node fleet
    assert frame["nodes"] == [obs_metrics.REGISTRY.node]
    assert "fleet_nodes 1.0" in frame["text"]
    assert "sequencer_tickets_total" in frame["metrics"]
    assert frame["metrics"]["fleet_nodes"]["values"][""] == 1.0
    # the CLI command against the same server, both expositions
    assert dump_fleet(f"127.0.0.1:{server.port}", False) == 0
    assert dump_fleet(f"127.0.0.1:{server.port}", True) == 0


def test_ingress_serves_an_attached_multi_node_view():
    from fluidframework_tpu.service.ingress import (
        AlfredServer,
        _ClientSession,
    )

    a, b = _two_nodes()
    a.counter("f_wire_total", "ops").inc(1)
    b.counter("f_wire_total", "ops").inc(2)
    view = FederatedView(clock=_Clock())
    view.add_registry("n0", a)
    view.add_registry("n1", b)
    server = AlfredServer(fleet=view)
    s = _ClientSession(server, None)
    server._sessions.add(s)
    server._dispatch(s, {"type": "fleet-metrics", "rid": 1})
    raw = s.outbound.get_nowait()
    frame = json.loads(raw[4:])
    assert frame["type"] == "fleet-metrics"
    assert frame["nodes"] == ["n0", "n1"]
    assert frame["metrics"]["f_wire_total"]["values"][""] == 3.0


# ======================================================================
# serve_bench rides the fleet surface


def test_serve_bench_report_carries_the_fleet_nodes():
    from fluidframework_tpu.tools.serve_bench import (
        ServeBenchConfig,
        run_serve_bench,
    )

    report = run_serve_bench(ServeBenchConfig(
        duration_s=0.5, n_docs=1, readers_per_doc=0,
        sidecar_docs=0, qos=False))
    assert report.fleet_nodes == [obs_metrics.REGISTRY.node]
