"""failcheck unit tests: per rule, a true-positive fixture (the
analyzer catches the planted silent error path) and a clean-pass
fixture (the loud idiom sails through), plus the machinery the live
gate depends on — callgraph-propagated loudness, the SILENT_HANDLERS
registry escape hatch and its staleness detector, and the
line-insertion-stable ordinal keys. Fixtures are PARSED, never
imported.
"""
import textwrap

from fluidframework_tpu.analysis import failcheck
from fluidframework_tpu.analysis.core import (
    run_analysis,
    walk_python_files,
)


def _lint(tmp_path, files):
    for relpath, source in files.items():
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    return run_analysis(
        roots=sorted({p.split("/")[0] for p in files}),
        families=["failcheck"],
        repo_root=str(tmp_path),
    )


# ------------------------------------------------- swallowed-exception


def test_swallowed_exception_rule(tmp_path):
    """A serving-path handler that absorbs the exception with no
    signal fails; every loudness arm (re-raise, metric inc, stderr,
    errorish return value, flight record) passes; a justified inline
    disable suppresses."""
    findings = _lint(tmp_path, {
        "service/handler.py": """
            import sys

            class Svc:
                def recv(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError:
                        return None                         # BAD

                def loud_metric(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError:
                        self.metrics["faults"].inc()
                        return None

                def loud_stderr(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError as e:
                        print(f"recv: {e}", file=sys.stderr)
                        return None

                def loud_reraise(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError as e:
                        raise RuntimeError("apply") from e

                def loud_error_value(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError as e:
                        return self._nack(frame, e)

                def loud_flight(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError as e:
                        self.flight.record("fault", err=str(e))
                        return None

                def reviewed(self, frame):
                    try:
                        return self._apply(frame)
                    except KeyError:  # fluidlint: disable=swallowed-exception -- test fixture
                        return None
        """,
    })
    assert [f.key for f in findings] == [
        "handler.py:Svc.recv:except-ValueError"]
    assert findings[0].rule == "swallowed-exception"


def test_swallowed_exception_out_of_scope_components_pass(tmp_path):
    """obs/ and utils/ handlers ARE the signal emitters — the rule
    only patrols the serving-plane path components."""
    findings = _lint(tmp_path, {
        "obs/quiet.py": """
            def sample():
                try:
                    return read()
                except OSError:
                    return None
        """,
        "utils/quiet.py": """
            def load(path):
                try:
                    return open(path).read()
                except OSError:
                    return ""
        """,
    })
    assert findings == []


def test_silent_handler_registry_escape(tmp_path, monkeypatch):
    """A reviewed SILENT_HANDLERS entry exempts exactly its site —
    an unregistered silent handler in the same module still fails
    (registry, not allowlist)."""
    monkeypatch.setitem(
        failcheck.SILENT_HANDLERS,
        ("service/reg.py", "Svc.absorb:except-OSError"),
        "test fixture: reviewed absorb")
    findings = _lint(tmp_path, {
        "service/reg.py": """
            class Svc:
                def absorb(self, path):
                    try:
                        return open(path).read()
                    except OSError:
                        return None                     # registered

                def other(self, path):
                    try:
                        return open(path).read()
                    except OSError:
                        return None                     # BAD
        """,
    })
    assert [f.key for f in findings] == [
        "reg.py:Svc.other:except-OSError"]


def test_loudness_resolves_through_callgraph(tmp_path):
    """A handler delegating to a repo helper that itself re-raises
    or emits a signal is loud — including through a two-hop chain;
    delegating to a silent helper is not."""
    findings = _lint(tmp_path, {
        "service/deleg.py": """
            class Svc:
                def via_reraise(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError as e:
                        self._note(e)
                        return None

                def _note(self, e):
                    self._escalate(e)

                def _escalate(self, e):
                    raise RuntimeError("fault") from e

                def via_silence(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError as e:
                        self._shrug(e)
                        return None                     # BAD

                def _shrug(self, e):
                    self.last = e
        """,
    })
    assert [f.key for f in findings] == [
        "deleg.py:Svc.via_silence:except-ValueError"]


# ------------------------------------------ broad-except-in-dispatch-loop


def test_broad_except_in_dispatch_loop_rule(tmp_path):
    """A bare/``except Exception`` in a DISPATCH_LOOPS-registered
    function without loud teardown is the PR2 quietly-dead-thread
    shape — and wins the dedup over plain swallowed-exception (the
    more specific diagnosis). The same broad except with a loud
    teardown passes; a NARROW silent except in the loop falls back
    to swallowed-exception."""
    findings = _lint(tmp_path, {
        "service/tpu_sidecar.py": """
            import sys

            class Sidecar:
                def _dispatch(self, ops):
                    try:
                        self._run(ops)
                    except Exception:
                        self.dead = True                # BAD (broad)
                    try:
                        self._settle_rows(ops)
                    except KeyError:
                        self.skipped += 1               # BAD (narrow)

                def apply(self, ops):
                    try:
                        self._run(ops)
                    except Exception as e:
                        print(f"apply died: {e}", file=sys.stderr)
                        raise
        """,
    })
    by_rule = {f.rule: f.key for f in findings}
    assert by_rule == {
        "broad-except-in-dispatch-loop":
            "tpu_sidecar.py:Sidecar._dispatch:broad-except",
        "swallowed-exception":
            "tpu_sidecar.py:Sidecar._dispatch:except-KeyError",
    }


# ---------------------------------------------- exception-context-dropped


def test_exception_context_dropped_rule(tmp_path):
    """``raise New(...)`` without ``from`` inside an except severs
    the causal chain; ``from e`` chains, ``from None`` is an explicit
    reviewed severing, and ``raise e`` re-raises the same exception —
    all three pass."""
    findings = _lint(tmp_path, {
        "service/chain.py": """
            class Svc:
                def recv(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError:
                        raise RuntimeError("apply")     # BAD

                def chained(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError as e:
                        raise RuntimeError("apply") from e

                def severed(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError:
                        raise RuntimeError("apply") from None

                def same(self, frame):
                    try:
                        return self._apply(frame)
                    except ValueError as e:
                        raise e
        """,
    })
    assert [(f.rule, f.key) for f in findings] == [
        ("exception-context-dropped",
         "chain.py:Svc.recv:raise-RuntimeError")]


# ------------------------------------------------------ return-in-finally


def test_return_in_finally_rule(tmp_path):
    """return/break/continue in a finally discards the in-flight
    exception (language semantics — applies everywhere, not just the
    serving planes); a break bound to a loop INSIDE the finalbody and
    a return inside a nested def are that scope's business."""
    findings = _lint(tmp_path, {
        "ops/cleanup.py": """
            def leak(path):
                try:
                    return parse(path)
                finally:
                    return None                         # BAD

            def sweep(paths):
                for p in paths:
                    try:
                        consume(p)
                    finally:
                        continue                        # BAD

            def fine(paths):
                try:
                    consume(paths)
                finally:
                    for p in paths:
                        if stale(p):
                            break                       # inner loop's

            def fine_nested(path):
                try:
                    return parse(path)
                finally:
                    def report():
                        return "done"
                    note(report)
        """,
    })
    assert [(f.rule, f.key) for f in findings] == [
        ("return-in-finally", "cleanup.py:leak:finally-return"),
        ("return-in-finally", "cleanup.py:sweep:finally-continue"),
    ]


# ------------------------------------------------- keys + registry hygiene


def test_handler_ordinal_keys_are_line_insertion_stable(tmp_path):
    """Two same-typed handlers in one scope get distinct ordinal
    keys, and inserting lines above both changes neither (the
    allowlist-key contract every family shares)."""
    src = """
        class Svc:
            def recv(self, frame):
                try:
                    a = self._head(frame)
                except OSError:
                    a = None                            # BAD
                try:
                    b = self._body(frame)
                except OSError:
                    b = None                            # BAD
                return a, b
    """
    baseline = _lint(tmp_path, {"service/two.py": src})
    assert sorted(f.key for f in baseline) == [
        "two.py:Svc.recv:except-OSError",
        "two.py:Svc.recv:except-OSError2",
    ]
    shifted = _lint(tmp_path / "shifted", {
        # indentation matches the fixture body so dedent still
        # normalizes it; only the line NUMBERS move
        "service/two.py": "\n        # shifted\n        # shifted"
                          + src})
    assert sorted(f.key for f in baseline) == \
        sorted(f.key for f in shifted)
    assert sorted(f.line for f in baseline) != \
        sorted(f.line for f in shifted)


def test_stale_silent_handlers_detects_ghost_entries(tmp_path):
    """A registry entry whose site vanished — or went intrinsically
    loud — describes nothing and must be reported stale; the entry
    matching a still-silent handler stays live."""
    (tmp_path / "service").mkdir(parents=True)
    (tmp_path / "service" / "reg.py").write_text(textwrap.dedent("""
        class Svc:
            def absorb(self, path):
                try:
                    return open(path).read()
                except OSError:
                    return None

            def loud(self, path):
                try:
                    return open(path).read()
                except OSError as e:
                    raise RuntimeError(str(e)) from e
    """))
    files = walk_python_files(["service"], repo_root=str(tmp_path))
    registry = {
        ("service/reg.py", "Svc.absorb:except-OSError"): "live",
        ("service/reg.py", "Svc.loud:except-OSError"): "went loud",
        ("service/reg.py", "Svc.gone:except-ValueError"): "vanished",
    }
    stale = failcheck.stale_silent_handlers(files, registry)
    assert sorted(stale) == [
        ("service/reg.py", "Svc.gone:except-ValueError"),
        ("service/reg.py", "Svc.loud:except-OSError"),
    ]
