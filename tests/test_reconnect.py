"""Reconnect / pending-op resubmission (§3.5: replayPendingStates ->
regeneratePendingOp, client.ts:972). Mirrors the reference's
mocksForReconnection-based DDS tests."""
import random

import pytest

from fluidframework_tpu.testing import FuzzConfig, MockCollabSession
from fluidframework_tpu.testing.fuzz import random_op


def make(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    return MockCollabSession(ids), ids


def test_offline_edit_resubmitted_on_reconnect():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "base")
    s.process_all()
    s.disconnect("A")
    s.do("A", "insert_text_local", 4, "-offline")  # stays pending
    s.do("B", "insert_text_local", 0, "B:")
    s.process_all()
    s.reconnect("A")
    s.process_all()
    assert s.assert_converged() == "B:base-offline"


def test_inflight_op_lost_on_disconnect_is_regenerated():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "hello")
    s.process_all()
    s.do("A", "insert_text_local", 5, " world")  # queued, not ticketed
    s.disconnect("A")  # raw op dropped
    s.do("B", "remove_range_local", 0, 1)
    s.process_all()
    s.reconnect("A")
    s.process_all()
    assert s.assert_converged() == "ello world"


def test_pending_insert_then_remove_of_it_survives_reconnect():
    """Code-review repro: a pending insert fully removed by a later
    pending local remove must resubmit both ops (or neither's effects),
    and the ack queue must stay aligned."""
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "keep")
    s.process_all()
    s.disconnect("A")
    s.do("A", "insert_text_local", 4, "abc")
    s.do("A", "remove_range_local", 4, 7)  # removes own pending insert
    s.do("B", "insert_text_local", 4, "-B")
    s.process_all()
    s.reconnect("A")
    s.process_all()
    assert s.assert_converged() == "keep-B"


def test_remove_superseded_by_remote_remove_is_dropped():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "abcdef")
    s.process_all()
    s.disconnect("A")
    s.do("A", "remove_range_local", 0, 3)   # pending remove, offline
    s.do("B", "remove_range_local", 0, 3)   # remote remove, sequenced
    s.process_all()
    s.reconnect("A")
    s.process_all()
    assert s.assert_converged() == "def"


def test_multiple_pending_removes_regenerate_in_order():
    """Out-of-document-order pending removes must resolve via the
    rebase view (localSeq-aware), not the plain local view."""
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "ABCD")
    s.process_all()
    s.disconnect("A")
    s.do("A", "remove_range_local", 2, 3)  # remove 'C' first
    s.do("A", "remove_range_local", 0, 1)  # then remove 'A'
    s.process_all()
    s.reconnect("A")
    s.process_all()
    assert s.assert_converged() == "BD"


def test_annotate_resubmitted_after_reconnect():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "abcd")
    s.process_all()
    s.disconnect("A")
    s.do("A", "annotate_range_local", 0, 2, {"bold": True})
    s.do("B", "insert_text_local", 0, "xx")
    s.process_all()
    s.reconnect("A")
    s.process_all()
    s.assert_converged()
    for cid in ("A", "B"):
        tree = s.client(cid).mergetree
        annotated = [
            seg.text for seg in tree.segments
            if not seg.removed and (seg.props or {}).get("bold")
        ]
        assert "".join(annotated) == "ab", cid


def test_double_reconnect():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "base")
    s.process_all()
    s.disconnect("A")
    s.do("A", "insert_text_local", 0, "x")
    s.reconnect("A")
    s.disconnect("A")  # drops the just-resubmitted raw op again
    s.do("A", "insert_text_local", 0, "y")
    s.do("B", "insert_text_local", 4, "!")
    s.process_all()
    s.reconnect("A")
    s.process_all()
    text = s.assert_converged()
    # y (resubmitted last, highest seq) lands left of x, both left of
    # base; B's '!' was appended at the tip of "base".
    assert text == "yxbase!"


@pytest.mark.parametrize("seed", range(15))
def test_reconnect_fuzz(seed):
    """Random ops + random disconnect/reconnect churn, must converge."""
    rng = random.Random(seed + 4242)
    ids = ["A", "B", "C"]
    s = MockCollabSession(ids)
    cfg = FuzzConfig()
    down: set[str] = set()
    for step in range(150):
        r = rng.random()
        if r < 0.05 and len(down) < len(ids) - 1:
            cid = rng.choice([c for c in ids if c not in down])
            s.disconnect(cid)
            down.add(cid)
        elif r < 0.12 and down:
            cid = rng.choice(sorted(down))
            s.reconnect(cid)
            down.remove(cid)
        elif r < 0.30 and s.pending_count:
            s.process_some(rng.randint(1, s.pending_count))
        else:
            random_op(rng, s, rng.choice(ids), cfg)
    for cid in sorted(down):
        s.reconnect(cid)
    s.process_all()
    s.assert_converged()
