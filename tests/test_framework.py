"""Framework layer: DataObject, FluidContainer, LocalServiceClient,
undo-redo.

Mirrors aqueduct/fluid-static/undo-redo tests and the tinylicious
client e2e pattern (create container -> second client gets it).
"""
import pytest

from fluidframework_tpu.framework import (
    DataObject,
    DataObjectFactory,
    FluidContainer,
    LocalServiceClient,
    SharedMapUndoRedoHandler,
    SharedStringUndoRedoHandler,
    UndoRedoStackManager,
)


# ----------------------------------------------------------------------
# client + FluidContainer

SCHEMA = {"kv": "sharedmap", "text": "sharedstring"}


def test_create_and_get_container_roundtrip():
    client = LocalServiceClient()
    created, services, doc_id = client.create_container(SCHEMA)
    created.initial_objects["kv"].set("hello", "world")
    created.initial_objects["text"].insert_text(0, "shared text")
    created.container.flush()

    got, services2 = client.get_container(doc_id, SCHEMA)
    assert got.initial_objects["kv"].get("hello") == "world"
    assert got.initial_objects["text"].get_text() == "shared text"
    # audience sees both clients
    assert services2.audience.size == 2


def test_two_clients_collaborate_via_fluid_container():
    client = LocalServiceClient()
    c1, _, doc_id = client.create_container(SCHEMA)
    c2, _ = client.get_container(doc_id, SCHEMA)
    c1.initial_objects["text"].insert_text(0, "alpha")
    c1.container.flush()
    c2.initial_objects["text"].insert_text(5, "-beta")
    c2.container.flush()
    assert c1.initial_objects["text"].get_text() == "alpha-beta"


def test_dynamic_dds_creation():
    client = LocalServiceClient()
    c1, _, doc_id = client.create_container(SCHEMA)
    extra = c1.create_dds("sharedcounter", "clicks")
    extra.increment(5)
    c1.container.flush()
    c2, _ = client.get_container(doc_id, SCHEMA)
    got = c2.container.runtime.get_datastore(
        "initial-objects").get_channel("clicks")
    assert got.value == 5


# ----------------------------------------------------------------------
# DataObject

class Counter(DataObject):
    def initializing_first_time(self):
        self.root.set("count", 0)
        self.created_fresh = True

    def initializing_from_existing(self):
        self.created_fresh = False

    def increment(self):
        self.root.set("count", self.root.get("count") + 1)

    @property
    def count(self):
        return self.root.get("count")


def test_data_object_lifecycle():
    client = LocalServiceClient()
    c1, _, doc_id = client.create_container({})
    factory = DataObjectFactory("counter", Counter)
    obj = factory.create(c1.container.runtime)
    assert obj.created_fresh and obj.count == 0
    obj.increment()
    obj.increment()
    c1.container.flush()

    c2, _ = client.get_container(doc_id, {})
    obj2 = factory.load(c2.container.runtime)
    assert obj2.created_fresh is False
    assert obj2.count == 2


# ----------------------------------------------------------------------
# undo-redo

def make_collab():
    client = LocalServiceClient()
    c1, _, doc_id = client.create_container(SCHEMA)
    c2, _ = client.get_container(doc_id, SCHEMA)
    return c1, c2


def test_map_undo_redo():
    c1, c2 = make_collab()
    kv = c1.initial_objects["kv"]
    stack = UndoRedoStackManager()
    SharedMapUndoRedoHandler(stack, kv)
    kv.set("a", 1)
    stack.close_current_operation()
    kv.set("a", 2)
    stack.close_current_operation()
    c1.container.flush()
    assert stack.undo_operation()
    assert kv.get("a") == 1
    assert stack.undo_operation()
    assert kv.get("a") is None
    assert stack.redo_operation()
    assert kv.get("a") == 1
    assert stack.redo_operation()
    assert kv.get("a") == 2
    c1.container.flush()
    assert c2.initial_objects["kv"].get("a") == 2


def test_map_clear_undo():
    c1, c2 = make_collab()
    kv = c1.initial_objects["kv"]
    stack = UndoRedoStackManager()
    SharedMapUndoRedoHandler(stack, kv)
    kv.set("x", 1)
    kv.set("y", 2)
    stack.close_current_operation()
    kv.clear()
    stack.close_current_operation()
    assert stack.undo_operation()
    assert kv.get("x") == 1 and kv.get("y") == 2


def test_string_undo_redo():
    c1, c2 = make_collab()
    text = c1.initial_objects["text"]
    stack = UndoRedoStackManager()
    SharedStringUndoRedoHandler(stack, text)
    text.insert_text(0, "hello")
    stack.close_current_operation()
    text.insert_text(5, " world")
    stack.close_current_operation()
    text.remove_text(0, 5)
    stack.close_current_operation()
    c1.container.flush()
    assert text.get_text() == " world"
    stack.undo_operation()
    assert text.get_text() == "hello world"
    stack.undo_operation()
    assert text.get_text() == "hello"
    stack.redo_operation()
    assert text.get_text() == "hello world"
    stack.redo_operation()
    c1.container.flush()
    assert text.get_text() == " world"
    assert c2.initial_objects["text"].get_text() == " world"


def test_string_undo_with_concurrent_remote_edit():
    """The undo target slides under a concurrent remote insert."""
    c1, c2 = make_collab()
    t1 = c1.initial_objects["text"]
    t2 = c2.initial_objects["text"]
    stack = UndoRedoStackManager()
    SharedStringUndoRedoHandler(stack, t1)
    t1.insert_text(0, "base ")
    c1.container.flush()
    stack.close_current_operation()
    t1.insert_text(5, "MISTAKE ")
    stack.close_current_operation()
    c1.container.flush()
    t2.insert_text(0, ">> ")  # remote edit shifts everything
    c2.container.flush()
    assert t1.get_text() == ">> base MISTAKE "
    stack.undo_operation()
    c1.container.flush()
    assert t1.get_text() == ">> base "
    assert t2.get_text() == ">> base "


def test_string_remove_undo_restores_markers():
    """A removed span containing a marker restores text AND marker."""
    c1, _ = make_collab()
    text = c1.initial_objects["text"]
    stack = UndoRedoStackManager()
    SharedStringUndoRedoHandler(stack, text)
    text.insert_text(0, "ab")
    text.insert_marker(2, 7, {"tag": "hr"})
    text.insert_text(3, "cd")
    stack.close_current_operation()
    sig_before = text.signature()
    text.remove_text(1, 4)  # removes 'b', the marker, 'c'
    stack.close_current_operation()
    c1.container.flush()
    assert text.get_text() == "ad"
    stack.undo_operation()
    c1.container.flush()
    assert text.signature() == sig_before
    assert text.get_text() == "abcd"


def test_string_annotate_undo_restores_prior_props():
    c1, _ = make_collab()
    text = c1.initial_objects["text"]
    stack = UndoRedoStackManager()
    SharedStringUndoRedoHandler(stack, text)
    text.insert_text(0, "hello world")
    text.annotate_range(0, 5, {"bold": True})
    stack.close_current_operation()
    sig_before = text.signature()
    text.annotate_range(3, 8, {"bold": False, "em": True})
    stack.close_current_operation()
    c1.container.flush()
    stack.undo_operation()
    c1.container.flush()
    assert text.signature() == sig_before


def test_string_insert_undo_spares_remote_text_inside_range():
    """Undoing an insert removes only the inserted segments — a
    remote insert INSIDE the range survives (tracking groups)."""
    c1, c2 = make_collab()
    t1 = c1.initial_objects["text"]
    t2 = c2.initial_objects["text"]
    stack = UndoRedoStackManager()
    SharedStringUndoRedoHandler(stack, t1)
    t1.insert_text(0, "ABCDE")
    stack.close_current_operation()
    c1.container.flush()
    t2.insert_text(2, "xx")  # remote text inside the undone range
    c2.container.flush()
    assert t1.get_text() == "ABxxCDE"
    stack.undo_operation()
    c1.container.flush()
    assert t1.get_text() == "xx"
    assert t2.get_text() == "xx"


def test_map_delete_absent_key_is_not_undoable():
    c1, _ = make_collab()
    kv = c1.initial_objects["kv"]
    stack = UndoRedoStackManager()
    SharedMapUndoRedoHandler(stack, kv)
    kv.set("real", 1)
    stack.close_current_operation()
    stack.undo_operation()
    assert stack.redo_count == 1
    kv.delete("ghost")  # no-op: must not destroy redo history
    assert stack.redo_count == 1
    assert stack.undo_count == 0


def test_new_edit_clears_redo():
    c1, _ = make_collab()
    kv = c1.initial_objects["kv"]
    stack = UndoRedoStackManager()
    SharedMapUndoRedoHandler(stack, kv)
    kv.set("a", 1)
    stack.close_current_operation()
    stack.undo_operation()
    assert stack.redo_count == 1
    kv.set("b", 9)  # a new edit invalidates redo history
    assert stack.redo_count == 0


# ----------------------------------------------------------------------
# framework helpers (oldest-client-observer, dds-interceptions,
# request-handler — packages/framework/*)

def test_oldest_client_observer_tracks_join_order():
    from fluidframework_tpu.framework import OldestClientObserver
    from fluidframework_tpu.protocol.messages import ClientDetail
    from fluidframework_tpu.protocol.quorum import QuorumClients

    q = QuorumClients()
    q.add_member("a", ClientDetail("a"))
    q.add_member("b", ClientDetail("b"))
    obs_b = OldestClientObserver(q, "b")
    assert not obs_b.is_oldest()
    events = []
    obs_b.on("becameOldest", lambda: events.append("became"))
    obs_b.on("lostOldest", lambda: events.append("lost"))
    q.remove_member("a")  # oldest leaves -> b inherits
    assert obs_b.is_oldest()
    assert events == ["became"]
    q.add_member("c", ClientDetail("c"))
    assert obs_b.is_oldest()  # later joins never preempt


def test_intercepted_string_stamps_props():
    from fluidframework_tpu.framework import (
        create_shared_string_with_interception,
    )
    from fluidframework_tpu.testing.runtime_mocks import ContainerSession

    s = ContainerSession(["A", "B"])
    for c in ("A", "B"):
        s.runtime(c).create_datastore("ds").create_channel(
            "sharedstring", "t")
    s.process_all()
    raw_a = s.runtime("A").get_datastore("ds").get_channel("t")
    raw_b = s.runtime("B").get_datastore("ds").get_channel("t")

    def stamp(pos, props):
        return dict(props or {}, author="alice")

    wrapped = create_shared_string_with_interception(raw_a, stamp)
    wrapped.insert_text(0, "hi", {"bold": 1})
    s.process_all()
    # the interception stamped the LOCAL edit; remote replica sees it
    sig_b = raw_b.signature()
    assert raw_a.signature() == sig_b
    assert wrapped.get_text() == "hi"  # reads pass through


def test_intercepted_map_can_rewrite_and_veto():
    from fluidframework_tpu.framework import (
        create_shared_map_with_interception,
    )
    from fluidframework_tpu.testing.runtime_mocks import ContainerSession

    s = ContainerSession(["A"])
    s.runtime("A").create_datastore("ds").create_channel(
        "sharedmap", "m")
    raw = s.runtime("A").get_datastore("ds").get_channel("m")

    def interceptor(key, value):
        if key.startswith("_"):
            raise PermissionError("reserved key")
        return {"v": value, "by": "alice"}

    wrapped = create_shared_map_with_interception(raw, interceptor)
    wrapped.set("k", 42)
    s.process_all()
    assert raw.get("k") == {"v": 42, "by": "alice"}
    import pytest as _pytest

    with _pytest.raises(PermissionError):
        wrapped.set("_internal", 1)


def test_request_handler_routes_paths():
    from fluidframework_tpu.framework import (
        RequestHandlerError,
        build_request_handler,
        datastore_channel_handler,
    )
    from fluidframework_tpu.testing.runtime_mocks import ContainerSession
    import pytest as _pytest

    s = ContainerSession(["A"])
    ds = s.runtime("A").create_datastore("ds")
    chan = ds.create_channel("sharedmap", "m")
    route = build_request_handler(datastore_channel_handler)
    rt = s.runtime("A")
    assert route("/ds", rt) is ds
    assert route("/ds/m", rt) is chan
    with _pytest.raises(RequestHandlerError) as e:
        route("/nope", rt)
    assert e.value.status == 404


def test_agent_scheduler_single_runner_and_failover():
    from fluidframework_tpu.framework import AgentScheduler
    from fluidframework_tpu.testing.runtime_mocks import ContainerSession

    s = ContainerSession(["A", "B"])
    for c in ("A", "B"):
        s.runtime(c).create_datastore("ds").create_channel(
            "taskmanager", "tm")
    s.process_all()
    tm_a = s.runtime("A").get_datastore("ds").get_channel("tm")
    tm_b = s.runtime("B").get_datastore("ds").get_channel("tm")
    runs = []
    sched_a = AgentScheduler(tm_a)
    sched_b = AgentScheduler(tm_b)
    sched_a.register("indexer", lambda: runs.append("A"))
    sched_b.register("indexer", lambda: runs.append("B"))
    s.process_all()
    # exactly one client runs the task (first volunteer sequenced)
    assert runs == ["A"]
    assert sched_a.picked_tasks() == ["indexer"]
    assert sched_b.picked_tasks() == []
    # failover: A leaves -> B picks it up
    released = []
    sched_a.on("released", released.append)
    sched_a.unregister("indexer")
    assert released == ["indexer"]  # fires on local abandon too
    s.process_all()
    assert runs == ["A", "B"]
    assert sched_b.picked_tasks() == ["indexer"]
