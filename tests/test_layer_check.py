"""Dependency layering enforcement — the build-tools layer-check
analogue (reference: build-tools/packages/build-tools/src/layerCheck,
cited in README.md:79-81: layering is machine-enforced, not aspirational).

Module-level imports between subpackages must stay within the declared
architecture; TYPE_CHECKING-only and function-local imports are
exempt (they cannot create import cycles). A NEW upward edge fails
this test and must either be redesigned or explicitly added here with
justification.
"""
import ast
import os

import fluidframework_tpu

ROOT = os.path.dirname(fluidframework_tpu.__file__)

# subpackage -> subpackages it may import at module level
ALLOWED = {
    "utils": set(),
    "protocol": {"utils"},
    "models": {"protocol", "utils", "runtime"},  # runtime: the
    # SharedObject contract lives in runtime/shared_object (layer 6
    # sits on the datastore runtime, sharedObject.ts:42)
    "ops": {"models", "protocol", "utils"},
    "runtime": {"protocol", "utils"},
    "drivers": {"protocol", "service", "utils"},  # local/socket
    # drivers bind to the in-proc/networked service (local-driver ->
    # local-server in the reference)
    "loader": {"drivers", "models", "protocol", "runtime", "utils"},
    "framework": {"drivers", "loader", "models", "runtime",
                  "service", "utils"},
    "service": {"models", "native", "ops", "protocol", "utils"},
    "native": {"ops", "protocol", "service", "utils"},
    "parallel": {"ops", "utils"},
    "testing": {"models", "ops", "protocol", "runtime", "service",
                "utils"},
    "tools": {"drivers", "loader", "models", "ops", "protocol",
              "runtime", "service", "testing", "utils"},
}


def _module_level_imports(path):
    """(package-relative) import edges, skipping TYPE_CHECKING blocks
    and anything nested inside functions/methods."""
    tree = ast.parse(open(path).read())
    out = []

    def visit_body(body):
        for stmt in body:
            if isinstance(stmt, ast.If):
                test = ast.unparse(stmt.test)
                if "TYPE_CHECKING" in test:
                    continue
                visit_body(stmt.body)
                visit_body(stmt.orelse)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            elif isinstance(stmt, ast.ClassDef):
                visit_body(stmt.body)
            elif isinstance(stmt, ast.ImportFrom):
                out.append(stmt)
            elif isinstance(stmt, ast.Try):
                visit_body(stmt.body)
                visit_body(stmt.orelse)
                for h in stmt.handlers:
                    visit_body(h.body)

    visit_body(tree.body)
    return out


def _edges():
    edges = set()
    for dirpath, _dirs, files in os.walk(ROOT):
        if "__pycache__" in dirpath:
            continue
        rel = os.path.relpath(dirpath, ROOT)
        pkg = rel.split(os.sep)[0] if rel != "." else "<root>"
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            for node in _module_level_imports(path):
                target = None
                if node.level > 0:
                    parts = [] if rel == "." else rel.split(os.sep)
                    up = node.level - 1
                    base = parts[: len(parts) - up] if up else parts
                    mod = (node.module or "").split(".")
                    full = [p for p in base + mod if p]
                    target = full[0] if full else "<root>"
                elif node.module and node.module.startswith(
                    "fluidframework_tpu"
                ):
                    parts = node.module.split(".")
                    target = parts[1] if len(parts) > 1 else "<root>"
                if target and target != pkg:
                    edges.add((pkg, target, path))
    return edges


def test_no_undeclared_cross_package_imports():
    violations = []
    for pkg, target, path in sorted(_edges()):
        if pkg == "<root>" or target == "<root>":
            continue  # package facade re-exports
        if target not in ALLOWED.get(pkg, set()):
            violations.append(f"{pkg} -> {target}  ({path})")
    assert not violations, (
        "undeclared layer dependencies:\n" + "\n".join(violations)
    )


def test_declared_layers_are_acyclic():
    graph = {k: set(v) for k, v in ALLOWED.items()}
    seen, stack = set(), set()

    def dfs(n):
        if n in stack:
            raise AssertionError(f"layer cycle through {n!r}")
        if n in seen:
            return
        stack.add(n)
        for m in graph.get(n, ()):  # noqa: B007
            dfs(m)
        stack.remove(n)
        seen.add(n)

    # drivers<->service and service<->native are the two sanctioned
    # mutual pairs in the reference too (local-driver <-> local-server
    # live in one release group); exclude them from the strict check
    graph["drivers"].discard("service")
    graph["native"].discard("service")
    for pkg in graph:
        dfs(pkg)