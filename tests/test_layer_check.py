"""Dependency layering enforcement — the build-tools layer-check
analogue (reference: build-tools/packages/build-tools/src/layerCheck,
cited in README.md:79-81: layering is machine-enforced, not
aspirational).

The declared map lives in fluidframework_tpu/analysis/layercheck.py —
ONE source of truth shared with the fluidlint CLI (`python -m
fluidframework_tpu.analysis`), so this tier-1 test and the linter
cannot drift apart. Module-level imports between subpackages must stay
within the declared architecture; TYPE_CHECKING-only and
function-local imports are exempt (they cannot create import cycles).
A NEW upward edge fails this test and must either be redesigned or
explicitly added to the shared ALLOWED map with justification.
"""
from fluidframework_tpu.analysis import layercheck
from fluidframework_tpu.analysis.core import walk_python_files


def test_no_undeclared_cross_package_imports():
    files = walk_python_files(["fluidframework_tpu"])
    findings = [
        f for f in layercheck.check(files)
        if f.rule == "layer-undeclared"
    ]
    assert not findings, (
        "undeclared layer dependencies:\n"
        + "\n".join(f.format() for f in findings)
    )


def test_declared_layers_are_acyclic():
    # drivers<->service and service<->native are the two sanctioned
    # mutual pairs in the reference too (local-driver <-> local-server
    # live in one release group); layercheck excludes exactly those
    # from the strict check
    assert layercheck.declared_cycle() == []


def test_every_subpackage_is_declared():
    import os

    import fluidframework_tpu

    root = os.path.dirname(fluidframework_tpu.__file__)
    subpackages = {
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)) and d != "__pycache__"
    }
    undeclared = subpackages - set(layercheck.ALLOWED)
    assert not undeclared, (
        f"subpackages missing from the declared layer map: "
        f"{sorted(undeclared)} — add them to analysis/layercheck.py"
    )
