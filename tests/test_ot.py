"""OT bridge (SharedOT / SharedJson): transform-based convergence under
concurrency — list index shifts, deleted-subtree drops, numeric-add
commutation, collab-window pruning.

Reference behavior: experimental/dds/ot/ot/src/ot.ts processCore.
"""
import pytest

from fluidframework_tpu.testing.runtime_mocks import ContainerSession


def make_session(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    for c in ids:
        s.runtime(c).create_datastore("ds").create_channel(
            "sharedjson", "j")
    chans = [
        s.runtime(c).get_datastore("ds").get_channel("j") for c in ids
    ]
    return s, chans


def converged(s, chans):
    s.process_all()
    sig = chans[0].signature()
    for c in chans[1:]:
        assert c.signature() == sig, (sig, c.signature())
    return sig


def test_basic_set_get():
    s, (a, b) = make_session()
    a.set(["title"], "hello")
    sig = converged(s, [a, b])
    assert sig == {"title": "hello"}
    assert b.get(["title"]) == "hello"


def test_concurrent_sets_different_keys_merge():
    s, (a, b) = make_session()
    a.set(["x"], 1)
    b.set(["y"], 2)
    sig = converged(s, [a, b])
    assert sig == {"x": 1, "y": 2}


def test_concurrent_set_same_key_lww():
    s, (a, b) = make_session()
    a.set(["k"], "from-a")
    b.set(["k"], "from-b")
    sig = converged(s, [a, b])
    # later-sequenced wins (B flushes after A in session order)
    assert sig == {"k": "from-b"}


def test_concurrent_list_inserts_shift():
    s, (a, b) = make_session()
    a.set(["items"], [])
    s.process_all()
    a.list_insert(["items"], 0, "a0")
    b.list_insert(["items"], 0, "b0")
    sig = converged(s, [a, b])
    # earlier-sequenced keeps the left slot
    assert sig == {"items": ["a0", "b0"]}


def test_concurrent_delete_and_edit_inside():
    s, (a, b) = make_session()
    a.set(["items"], [{"v": 1}, {"v": 2}])
    s.process_all()
    a.list_delete(["items"], 0)
    b.set(["items", 0, "v"], 99)  # edits the element A deletes
    sig = converged(s, [a, b])
    # B's edit inside the deleted element drops
    assert sig == {"items": [{"v": 2}]}


def test_concurrent_deletes_same_element():
    s, (a, b) = make_session()
    a.set(["items"], ["x", "y"])
    s.process_all()
    a.list_delete(["items"], 0)
    b.list_delete(["items"], 0)
    sig = converged(s, [a, b])
    # one element deleted once, not twice
    assert sig == {"items": ["y"]}


def test_pending_local_op_transformed_over_remote_delete():
    # ot.ts:125-127 — the pending queue must be transformed over each
    # incoming remote op; otherwise the optimistic view replays the
    # pending op at a stale index (IndexError / wrong element here)
    s, (a, b) = make_session()
    a.set(["items"], ["x", "y", "z"])
    s.process_all()
    a.list_delete(["items"], 0)
    s.flush("A")
    b.set(["items", 2], "Z")           # still pending on B
    s.process_some(1)                  # deliver A's delete to B
    assert b.state == {"items": ["y", "Z"]}
    sig = converged(s, [a, b])
    assert sig == {"items": ["y", "Z"]}


def test_pending_local_op_dropped_when_remote_removes_subtree():
    # a pending edit under a subtree a remote od removed must not
    # poison the optimistic view (KeyError in _descend pre-fix)
    s, (a, b) = make_session()
    a.set(["cfg"], {"x": 1})
    s.process_all()
    a.remove(["cfg"])
    s.flush("A")
    b.set(["cfg", "x"], 2)             # pending, targets dead subtree
    s.process_some(1)
    assert b.state == {}               # no crash, edit dropped
    sig = converged(s, [a, b])
    assert sig == {}


def test_delete_shifts_later_indices():
    s, (a, b) = make_session()
    a.set(["items"], ["x", "y", "z"])
    s.process_all()
    a.list_delete(["items"], 0)
    b.set(["items", 2], "Z")  # addresses 'z' pre-delete
    sig = converged(s, [a, b])
    assert sig == {"items": ["y", "Z"]}


def test_numeric_add_commutes():
    s, (a, b) = make_session()
    a.set(["count"], 0)
    s.process_all()
    a.add(["count"], 5)
    b.add(["count"], 7)
    sig = converged(s, [a, b])
    assert sig == {"count": 12}


def test_object_delete_drops_nested_edit():
    s, (a, b) = make_session()
    a.set(["cfg"], {"depth": 1})
    s.process_all()
    a.remove(["cfg"])
    b.set(["cfg", "depth"], 2)
    sig = converged(s, [a, b])
    assert sig == {}


def test_delete_then_concurrent_recreate_survives():
    s, (a, b) = make_session()
    a.set(["cfg"], {"old": True})
    s.process_all()
    a.remove(["cfg"])
    b.set(["cfg"], {"new": True})  # full re-set of the key survives
    sig = converged(s, [a, b])
    assert sig == {"cfg": {"new": True}}


def test_sequenced_window_prunes_below_msn():
    s, (a, b) = make_session()
    for i in range(10):
        # both clients submit so both refSeqs (and hence the msn)
        # advance — an idle client correctly pins the window open
        a.set([f"ka{i}"], i)
        s.process_all()
        b.set([f"kb{i}"], i)
        s.process_all()
    assert len(a._sequenced) <= 4
    assert len(b._sequenced) <= 4


def test_summarize_load_roundtrip():
    s, (a, b) = make_session()
    a.set(["x"], {"nested": [1, 2, 3]})
    s.process_all()
    from fluidframework_tpu.models.ot import SharedJson

    fresh = SharedJson("j2")
    fresh.load_core(a.summarize_core())
    assert fresh.signature() == a.signature()
    assert fresh.get(["x", "nested", 1]) == 2


@pytest.mark.parametrize("seed", range(10))
def test_ot_convergence_fuzz(seed):
    import random

    rng = random.Random(seed * 101 + 5)
    s, chans = make_session(3)
    chans[0].set(["lst"], [])
    chans[0].set(["num"], 0)
    s.process_all()
    for round_ in range(12):
        for c in chans:
            action = rng.random()
            lst = c.get(["lst"], [])
            if action < 0.4:
                c.list_insert(["lst"], rng.randrange(len(lst) + 1),
                              f"{round_}")
            elif action < 0.6 and lst:
                c.list_delete(["lst"], rng.randrange(len(lst)))
            elif action < 0.8:
                c.add(["num"], rng.randrange(10))
            else:
                c.set([f"k{rng.randrange(4)}"], round_)
        if rng.random() < 0.6:
            s.process_all()
    converged(s, chans)


def test_na_over_concurrent_replace_drops():
    """Regression: a numeric add racing a same-path replace with a
    non-number must drop (it used to TypeError on every replica)."""
    s, (a, b) = make_session()
    a.set(["k"], 0)
    s.process_all()
    a.set(["k"], "now-a-string")
    b.add(["k"], 1)
    sig = converged(s, [a, b])
    assert sig == {"k": "now-a-string"}
