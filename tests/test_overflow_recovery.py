"""Sidecar overflow recovery + insert-props kernel fidelity
(VERDICT r1 weak #4/#5).

A document that outgrows its device slab or exceeds the interned
property channels must never be silently wrong: the sidecar regrows
the slab (capacity ladder) or evicts the doc to a full-fidelity host
replica.
"""
import random

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.models.mergetree import MergeTreeClient
from fluidframework_tpu.ops import (
    apply_window,
    build_batch,
    encode_stream,
    extract_signature,
    extract_text,
    fetch,
    make_table,
)
from fluidframework_tpu.ops.host_replay import replay_encoded
from fluidframework_tpu.service import LocalServer, TpuMergeSidecar
from fluidframework_tpu.testing import FuzzConfig, record_op_stream


def _session(server, sidecar, doc, n_chunks=40, chunk="abcdefgh",
             props=None):
    factory = LocalDocumentServiceFactory(server)
    sidecar.subscribe(server, doc, "d", "s")
    c = Container.load(factory.create_document_service(doc),
                       client_id=f"{doc}-writer")
    s = c.runtime.create_datastore("d").create_channel("sharedstring", "s")
    for i in range(n_chunks):
        if props is not None:
            s.insert_text(0, chunk, dict(props))
        else:
            s.insert_text(0, chunk)
        c.flush()
        # segment churn: removes create splits/tombstones
        if i % 3 == 2 and s.get_length() > 6:
            s.remove_text(2, 5)
            c.flush()
    return c, s


def test_overflow_grows_capacity_ladder():
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=2, capacity=16, max_capacity=512)
    c, s = _session(server, sidecar, "doc")
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: recovery runs at settle
    assert sidecar.grow_count >= 1, "expected slab growth"
    assert sidecar.host_mode_docs() == 0
    assert not sidecar.overflowed()
    assert sidecar.text("doc", "d", "s") == s.get_text()


def test_overflow_evicts_to_host_at_max_capacity():
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=2, capacity=16, max_capacity=16)
    c, s = _session(server, sidecar, "doc")
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: recovery runs at settle
    assert sidecar.evict_count >= 1
    assert sidecar.host_mode_docs() == 1
    assert not sidecar.overflowed()
    assert sidecar.text("doc", "d", "s") == s.get_text()
    # later traffic keeps flowing to the host replica
    s.insert_text(0, "MORE")
    c.flush()
    sidecar.apply()
    assert sidecar.text("doc", "d", "s") == s.get_text()


def test_excess_prop_channels_evicts_to_host():
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=2, capacity=256)
    factory = LocalDocumentServiceFactory(server)
    sidecar.subscribe(server, "doc", "d", "s")
    c = Container.load(factory.create_document_service("doc"),
                       client_id="w")
    s = c.runtime.create_datastore("d").create_channel("sharedstring", "s")
    s.insert_text(0, "hello world")
    c.flush()
    for i, key in enumerate(["k1", "k2", "k3", "k4", "k5", "k6"]):
        s.annotate_range(0, 5, {key: i + 1})
        c.flush()
    sidecar.apply()
    assert sidecar.host_mode_docs() == 1
    assert sidecar.text("doc", "d", "s") == s.get_text()
    assert s.client.mergetree.segments[0].props == {
        "k1": 1, "k2": 2, "k3": 3, "k4": 4, "k5": 5, "k6": 6,
    }


def test_healthy_docs_unaffected_by_neighbor_eviction():
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=2, capacity=16, max_capacity=16)
    c1, s1 = _session(server, sidecar, "big")        # overflows
    factory = LocalDocumentServiceFactory(server)
    sidecar.subscribe(server, "small", "d", "s")
    c2 = Container.load(factory.create_document_service("small"),
                        client_id="w2")
    s2 = c2.runtime.create_datastore("d").create_channel(
        "sharedstring", "s")
    s2.insert_text(0, "tiny")
    c2.flush()
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: recovery runs at settle
    assert sidecar.host_mode_docs() == 1
    assert sidecar.text("big", "d", "s") == s1.get_text()
    assert sidecar.text("small", "d", "s") == s2.get_text()


# ----------------------------------------------------------------------
# insert-with-props kernel fidelity

@pytest.mark.parametrize("seed", range(8))
def test_kernel_insert_props_differential(seed):
    text, stream = record_op_stream(FuzzConfig(
        n_clients=3, n_steps=100, seed=seed * 13 + 5,
        remove_weight=0.25, annotate_weight=0.1,
        insert_props_weight=0.5,
    ))
    enc = encode_stream(stream)
    batch = build_batch([enc])
    table = apply_window(make_table(1, 1024), batch)
    np_table = fetch(table)
    assert not np_table["overflow"].any()
    assert extract_text(np_table, enc, 0) == text

    from fluidframework_tpu.ops.host_bridge import interned_signature

    obs = MergeTreeClient("observer")
    obs.start_collaboration("observer")
    for msg in stream:
        obs.apply_msg(msg)
    assert extract_signature(np_table, enc, 0) == interned_signature(
        obs, enc)


# ----------------------------------------------------------------------
# host replay twin: python-encoded vs kernel (and implicitly vs C++,
# which test_native_replay pins to the kernel)

@pytest.mark.parametrize("seed", range(8))
def test_host_replay_matches_kernel(seed):
    text, stream = record_op_stream(FuzzConfig(
        n_clients=3, n_steps=90, seed=seed * 7 + 1,
        remove_weight=0.3, annotate_weight=0.15,
        insert_props_weight=0.3,
    ))
    enc = encode_stream(stream)
    batch = build_batch([enc])
    table = apply_window(make_table(1, 1024), batch)
    np_table = fetch(table)
    assert not np_table["overflow"].any()
    host = replay_encoded(enc.ops).as_table()
    assert extract_text(host, enc, 0) == extract_text(np_table, enc, 0)
    assert extract_signature(host, enc, 0) == extract_signature(
        np_table, enc, 0)


def test_post_eviction_new_prop_value_signature():
    """code-review r2: ops after eviction bypass the encoder, so the
    signature path must intern unseen values at read time instead of
    crashing."""
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=2, capacity=16, max_capacity=16)
    c, s = _session(server, sidecar, "doc")
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: recovery runs at settle
    assert sidecar.host_mode_docs() == 1
    s.annotate_range(0, 4, {"bold": 777})  # value the encoder never saw
    c.flush()
    sidecar.apply()
    sig = sidecar.signature("doc", "d", "s")  # must not raise
    assert len(sig) == s.get_length()
    assert sidecar.text("doc", "d", "s") == s.get_text()
