"""bench.py run-status contract (VERDICT r4 next #8): a correctness-
stage failure must poison the run — top-level flag + nonzero exit —
never hide in `failures` under rc 0."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke",
         "--stages", "probe,fuzz"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=300,
    )
    line = proc.stdout.strip().splitlines()[-1]
    return proc.returncode, json.loads(line)


@pytest.mark.slow
def test_green_fuzz_reports_clean_status():
    rc, out = _run()
    assert rc == 0
    assert out["correctness_failed"] is False
    assert out["detail"]["stages"]["fuzz"]["result"] == \
        "all-signatures-match"


@pytest.mark.slow
def test_seeded_fuzz_failure_flips_run_status():
    rc, out = _run({"FFTPU_FUZZ_SABOTAGE": "1"})
    assert rc != 0
    assert out["correctness_failed"] is True
    assert "fuzz" in out["correctness_failures"]
