"""TPU merge sidecar end-to-end: real service pipeline -> device
tables, validated against the live containers (the north-star
integration)."""
import random

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service import LocalServer, TpuMergeSidecar


def test_sidecar_tracks_service_stream():
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    sidecar = TpuMergeSidecar(max_docs=4, capacity=256)
    sidecar.subscribe(server, "doc", "default", "text")

    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice")
    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    sa = a.runtime.create_datastore("default").create_channel(
        "sharedstring", "text"
    )
    b.runtime.create_datastore("default").create_channel(
        "sharedstring", "text"
    )
    sa.insert_text(0, "hello sidecar")
    a.flush()
    sb = b.runtime.get_datastore("default").get_channel("text")
    sb.remove_text(0, 6)
    sb.annotate_range(0, 7, {"bold": 1})
    b.flush()

    applied = sidecar.apply()
    assert applied > 0
    assert not sidecar.overflowed()
    assert sidecar.text("doc", "default", "text") == sa.get_text() == \
        "sidecar"


def test_sidecar_multidoc_batch():
    rng = random.Random(42)
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    sidecar = TpuMergeSidecar(max_docs=8, capacity=256)
    docs = [f"doc-{i}" for i in range(5)]
    strings = {}
    containers = {}
    for doc in docs:
        sidecar.subscribe(server, doc, "d", "s")
        c1 = Container.load(factory.create_document_service(doc),
                            client_id=f"{doc}-a")
        c2 = Container.load(factory.create_document_service(doc),
                            client_id=f"{doc}-b")
        s1 = c1.runtime.create_datastore("d").create_channel(
            "sharedstring", "s")
        c2.runtime.create_datastore("d").create_channel("sharedstring", "s")
        containers[doc] = (c1, c2)
        strings[doc] = (
            s1, c2.runtime.get_datastore("d").get_channel("s")
        )
    for _ in range(60):
        doc = rng.choice(docs)
        idx = rng.randint(0, 1)
        s = strings[doc][idx]
        length = s.get_length()
        if length > 4 and rng.random() < 0.4:
            start = rng.randint(0, length - 2)
            s.remove_text(start, rng.randint(start + 1, length))
        else:
            s.insert_text(rng.randint(0, length), rng.choice(
                ["ab", "xyz", "q"]))
        containers[doc][idx].flush()
        if rng.random() < 0.3:
            sidecar.apply()
    sidecar.apply()
    assert not sidecar.overflowed()
    for doc in docs:
        assert sidecar.text(doc, "d", "s") == strings[doc][0].get_text(), doc
