"""RichTextEditor binding (the prosemirror-class example layer,
VERDICT r3 next-round #10): paragraphs/marks/comments/cursors over
SharedString, concurrent editing convergence, cursor stability through
remote edits, and the fuzz workload."""
import random

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.framework.richtext import (
    MARK_KEYS,
    RichTextEditor,
    editor_workload,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer


def make_pair(doc="rt"):
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service(doc),
                       client_id="alice")
    sa = a.runtime.create_datastore("app").create_channel(
        "sharedstring", "body")
    a.flush()
    b = Container.load(factory.create_document_service(doc),
                       client_id="bob")
    sb = b.runtime.get_datastore("app").get_channel("body")
    return server, (a, RichTextEditor(sa, "alice")), \
        (b, RichTextEditor(sb, "bob"))


def test_typing_and_rendering():
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("Hello world")
    ea.split_paragraph(heading=2)
    ea.type_text("Section body")
    ca.flush()
    paras = eb.render()
    assert [p.text for p in paras] == ["Hello world", "Section body"]
    assert paras[1].style == {"heading": 2}
    assert eb.plain_text() == ea.plain_text()


def test_marks_apply_and_toggle_off():
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("make this bold")
    ea.set_cursor(5)
    ea.set_cursor(9, extend=True)
    ea.toggle_mark("bold")
    ca.flush()
    runs = eb.render()[0].runs
    assert ("this", frozenset({"bold"})) in runs
    # toggling again over the same span clears it
    ea.set_cursor(5)
    ea.set_cursor(9, extend=True)
    ea.toggle_mark("bold")
    ca.flush()
    assert all("bold" not in m for _, m in eb.render()[0].runs)


def test_stored_marks_caret_typing():
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("ab")
    ea.toggle_mark("italic")  # caret: stored mark
    ea.type_text("cd")
    ca.flush()
    runs = eb.render()[0].runs
    assert runs == [("ab", frozenset()),
                    ("cd", frozenset({"italic"}))]


def test_cursor_survives_remote_edits():
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("abcdef")
    ca.flush()
    eb.set_cursor(3)  # bob's caret between c and d
    # alice inserts at the front; bob's caret must slide right
    ea.set_cursor(0)
    ea.type_text("XY")
    ca.flush()
    cb.flush()
    assert eb.plain_text() == "XYabcdef"
    assert eb.cursor == 5  # still between c and d
    # alice deletes the region containing the caret: slides
    ea.set_cursor(0)
    ea.string.remove_text(2, 6)  # removes abcd
    ca.flush()
    assert eb.plain_text() == "XYef"
    assert 0 <= eb.cursor <= eb.length


def test_comment_slides_with_edits():
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("review this passage carefully")
    ca.flush()
    ea.add_comment(7, 19, "check wording")  # "this passage"
    ca.flush()
    # bob types at the front concurrently
    eb.set_cursor(0)
    eb.type_text(">> ")
    cb.flush()
    ca.flush()
    got = ea.comments()
    assert len(got) == 1
    c = got[0]
    assert ea.plain_text()[c["start"]:c["end"]] == "this passage"
    assert c["author"] == "alice" and c["text"] == "check wording"
    assert eb.comments() == got


def test_concurrent_editing_converges():
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("shared document")
    ca.flush()
    cb.flush()
    # concurrent: alice bolds while bob types in the middle
    ea.set_cursor(0)
    ea.set_cursor(6, extend=True)
    ea.toggle_mark("bold")
    eb.set_cursor(7)
    eb.type_text("rich ")
    ca.flush()
    cb.flush()
    assert ea.plain_text() == eb.plain_text()
    assert [p.runs for p in ea.render()] == \
        [p.runs for p in eb.render()]


@pytest.mark.parametrize("seed", range(6))
def test_workload_fuzz_converges(seed):
    """The editor workload generator: two users hammer the same doc
    with bursty typing/formatting/comments; everything converges at
    the binding level (render + comments identical)."""
    _, (ca, ea), (cb, eb) = make_pair()
    rng = random.Random(seed)
    for round_ in range(8):
        editor_workload(ea, rng, 6)
        editor_workload(eb, rng, 6)
        if rng.random() < 0.7:
            ca.flush()
        if rng.random() < 0.7:
            cb.flush()
    ca.flush()
    cb.flush()
    ca.flush()
    assert ea.plain_text() == eb.plain_text(), seed
    assert [(p.style, p.runs) for p in ea.render()] == \
        [(p.style, p.runs) for p in eb.render()], seed
    assert ea.comments() == eb.comments(), seed


def test_reconnect_offline_edits_replay():
    """Offline typing + formatting replays on reconnect — the editor
    session survives a connection blip (faultInjection-style)."""
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("stable base. ")
    ca.flush()
    cb.flush()
    ca.disconnect()
    ea.set_cursor(ea.length)
    ea.type_text("offline words")
    ea.set_cursor(0)
    ea.set_cursor(6, extend=True)
    ea.toggle_mark("code")
    # bob keeps editing while alice is away
    eb.set_cursor(eb.length)
    eb.type_text("(bob was here) ")
    cb.flush()
    ca.connect()
    ca.flush()
    cb.flush()
    ca.flush()
    assert ea.plain_text() == eb.plain_text()
    assert "offline words" in ea.plain_text()
    assert "(bob was here)" in ea.plain_text()
    assert [p.runs for p in ea.render()] == \
        [p.runs for p in eb.render()]


def test_workload_feeds_merge_kernel():
    """The binding's sequenced stream replays bit-faithfully through
    the batched device executors — the editor doubles as the kernel
    workload generator it was asked to be."""
    import dataclasses

    import numpy as np

    from fluidframework_tpu.ops import (
        build_batch, encode_stream, extract_text, fetch, make_table,
    )
    from fluidframework_tpu.ops.merge_chunk import (
        apply_window_chunked, build_chunked,
    )
    from fluidframework_tpu.ops.merge_kernel import apply_window_impl
    from fluidframework_tpu.protocol.messages import MessageType

    server, (ca, ea), (cb, eb) = make_pair()
    rng = random.Random(42)
    for _ in range(5):
        editor_workload(ea, rng, 5)
        editor_workload(eb, rng, 5)
        ca.flush()
        cb.flush()
    ca.flush()
    msgs = []
    for msg in server.read_ops("rt", 0):
        env = msg.contents if isinstance(msg.contents, dict) else {}
        if (msg.type == MessageType.OPERATION
                and env.get("kind", "op") == "op"
                and env.get("address") == "app"
                and env.get("channel") == "body"):
            inner = env["contents"]
            if not hasattr(inner, "type"):
                # interval-collection op: rides the channel stream but
                # isn't a merge-tree op — the device path sees a noop
                msgs.append(dataclasses.replace(
                    msg, type=MessageType.NO_OP, contents=None,
                    client_id=None))
                continue
            msgs.append(dataclasses.replace(msg, contents=inner))
        else:
            msgs.append(dataclasses.replace(
                msg, type=MessageType.NO_OP, contents=None,
                client_id=None))
    enc = encode_stream(msgs)
    batch = build_batch([enc])
    seq_tab = fetch(apply_window_impl(make_table(1, 1024), batch))
    chunk_tab = fetch(apply_window_chunked(
        make_table(1, 1024), build_chunked(batch, K=8), K=8))
    want = ea.plain_text()
    assert extract_text(seq_tab, enc, 0) == want
    assert extract_text(chunk_tab, enc, 0) == want
    n = int(seq_tab["count"][0])
    for f in ("length", "seq", "client", "removed_seq"):
        assert np.array_equal(seq_tab[f][0, :n], chunk_tab[f][0, :n])


def test_toggle_mark_across_paragraph_boundary_clears():
    """A fully-marked selection spanning a paragraph marker must
    CLEAR on toggle (the marker itself never carries the mark —
    code-review r4 reproduced the double-toggle bug)."""
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("aaa")
    ea.split_paragraph()
    ea.type_text("bbb")
    # bold both paragraphs' text separately
    ea.set_cursor(0)
    ea.set_cursor(3, extend=True)
    ea.toggle_mark("bold")
    ea.set_cursor(4)
    ea.set_cursor(7, extend=True)
    ea.toggle_mark("bold")
    ca.flush()
    assert all(
        m == frozenset({"bold"})
        for p in eb.render() for _, m in p.runs
    )
    # select ALL (spans the marker) and toggle: must clear
    ea.set_cursor(0)
    ea.set_cursor(ea.length, extend=True)
    ea.toggle_mark("bold")
    ca.flush()
    assert all(
        "bold" not in m for p in eb.render() for _, m in p.runs
    )


def test_comment_to_document_end_keeps_last_char():
    """A comment ending at the document end must cover the final
    character (end anchors on the last char with +1 bias — the clamp
    used to silently shorten the range)."""
    _, (ca, ea), (cb, eb) = make_pair()
    ea.type_text("note the last word")
    i = ea.plain_text().index("word")
    ea.add_comment(i, ea.length, "on the last word")
    ca.flush()
    c = eb.comments()[0]
    assert eb.text_span(c["start"], c["end"]) == "word"
