"""Consensus DDS family: register collection, ordered collection,
task manager, quorum, ink, summary block.

Mirrors the reference DDS test approach (packages/dds/*/src/test):
multi-client sessions over the mock sequencer, interleaved ops,
convergence + semantics asserts.
"""
import pytest

from fluidframework_tpu.testing.runtime_mocks import ContainerSession


def make_session(n, ctype, cid="chan"):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    for c in ids:
        s.runtime(c).create_datastore("ds").create_channel(ctype, cid)
    chans = [
        s.runtime(c).get_datastore("ds").get_channel(cid) for c in ids
    ]
    return s, chans


# ----------------------------------------------------------------------
# ConsensusRegisterCollection

def test_register_write_sequences_before_visible():
    s, (ra, rb) = make_session(2, "consensusregistercollection")
    ra.write("k", 1)
    assert ra.read("k") is None  # consensus: nothing until sequenced
    s.process_all()
    assert ra.read("k") == 1
    assert rb.read("k") == 1


def test_register_concurrent_writes_keep_versions():
    s, (ra, rb) = make_session(2, "consensusregistercollection")
    ra.write("k", "a")
    rb.write("k", "b")
    s.process_all()
    # neither writer had seen the other: both versions survive,
    # atomic read = earliest sequenced
    for r in (ra, rb):
        assert r.read("k") == "a"
        assert r.read_versions("k") == ["a", "b"]
    assert ra.signature() == rb.signature()


def test_register_sequential_write_supersedes():
    s, (ra, rb) = make_session(2, "consensusregistercollection")
    ra.write("k", "a")
    s.process_all()
    rb.write("k", "b")  # b's refSeq covers a's write
    s.process_all()
    for r in (ra, rb):
        assert r.read_versions("k") == ["b"]


def test_register_completion_callback_reports_winner():
    s, (ra, rb) = make_session(2, "consensusregistercollection")
    results = {}
    ra.write("k", "a", on_complete=lambda won: results.__setitem__("a", won))
    rb.write("k", "b", on_complete=lambda won: results.__setitem__("b", won))
    s.process_all()
    assert results == {"a": True, "b": False}


# ----------------------------------------------------------------------
# ConsensusOrderedCollection

def test_ordered_collection_acquire_complete():
    s, (ca, cb) = make_session(2, "consensusorderedcollection")
    ca.add("job1")
    ca.add("job2")
    s.process_all()
    assert ca.size == 2 and cb.size == 2
    aid = cb.acquire()
    s.process_all()
    assert cb.result_of(aid) == "job1"
    assert ca.size == 1  # leased, no longer queued
    assert ca.leases() and list(ca.leases().values())[0]["client"] == "B"
    cb.complete(aid)
    s.process_all()
    assert not ca.leases() and not cb.leases()
    assert ca.signature() == cb.signature()


def test_ordered_collection_concurrent_acquire_one_winner():
    s, (ca, cb) = make_session(2, "consensusorderedcollection")
    ca.add("only")
    s.process_all()
    aid_a = ca.acquire()
    aid_b = cb.acquire()
    s.process_all()
    assert ca.result_of(aid_a) == "only"  # sequenced first
    assert cb.result_of(aid_b) is None    # queue was empty
    assert ca.signature() == cb.signature()


def test_ordered_collection_release_returns_to_head():
    s, (ca, cb) = make_session(2, "consensusorderedcollection")
    ca.add("j1")
    ca.add("j2")
    s.process_all()
    aid = ca.acquire()
    s.process_all()
    ca.release(aid)
    s.process_all()
    aid2 = cb.acquire()
    s.process_all()
    assert cb.result_of(aid2) == "j1"  # released work reclaims its slot


# ----------------------------------------------------------------------
# TaskManager

def test_taskmanager_first_volunteer_wins():
    s, (ta, tb) = make_session(2, "taskmanager")
    ta.volunteer("summarizer")
    tb.volunteer("summarizer")
    s.process_all()
    assert ta.have_task("summarizer")
    assert not tb.have_task("summarizer")
    assert tb.queued("summarizer")
    assert ta.signature() == tb.signature()


def test_taskmanager_abandon_passes_assignment():
    s, (ta, tb) = make_session(2, "taskmanager")
    ta.volunteer("t")
    tb.volunteer("t")
    s.process_all()
    events = []
    tb.on("assigned", lambda tid, who: events.append((tid, who)))
    ta.abandon("t")
    s.process_all()
    assert tb.have_task("t")
    assert ("t", "B") in events


def test_taskmanager_client_left_reassigns():
    s, (ta, tb) = make_session(2, "taskmanager")
    ta.volunteer("t")
    tb.volunteer("t")
    s.process_all()
    tb.client_left("A")
    assert tb.assigned("t") == "B"


def test_ordered_collection_client_left_releases_leases():
    s, (ca, cb) = make_session(2, "consensusorderedcollection")
    ca.add("j1")
    s.process_all()
    aid = cb.acquire()
    s.process_all()
    assert ca.size == 0 and ca.leases()
    for c in (ca, cb):
        c.client_left("B")
    assert ca.size == 1 and not ca.leases()
    assert ca.signature() == cb.signature()


def test_taskmanager_abandon_then_revolunteer():
    """A pending abandon must not swallow a re-volunteer (the queue
    still lists us while the abandon is in flight)."""
    s, (ta, tb) = make_session(2, "taskmanager")
    ta.volunteer("job")
    s.process_all()
    ta.abandon("job")
    ta.volunteer("job")
    s.process_all()
    assert ta.queued("job")
    assert ta.have_task("job")


def test_ink_remote_clear_interleaves_with_pending_ops():
    """A clear sequencing between a peer's optimistic ops and their
    acks must still converge: peers apply those ops post-clear."""
    s, (ia, ib) = make_session(2, "ink")
    ib.clear()  # sequences first
    sid = ia.create_stroke({"c": "red"})
    ia.append_point(sid, {"x": 1})
    s.flush("B")
    s.flush("A")
    s.process_all()
    assert ia.get_stroke(sid)["points"] == [{"x": 1}]
    assert ia.signature() == ib.signature()


def test_ink_append_to_cleared_stroke_is_noop():
    s, (ia, ib) = make_session(2, "ink")
    sid = ia.create_stroke()
    s.process_all()
    ib.clear()
    s.process_all()
    ia.append_point(sid, {"x": 9})  # stroke gone: silent no-op
    s.process_all()
    assert ia.get_stroke(sid) is None
    assert ia.signature() == ib.signature()


def test_quorum_accepts_via_attach_traffic():
    """Window advances carried by attach ops must reach msn-keyed
    DDSes (regression: attach early-return skipped _advance_all)."""
    s, (qa, qb) = make_session(2, "sharedquorum")
    qa.set("k", "v")
    s.process_all()
    assert qa.get("k") is None
    # only attach traffic from both clients from here on
    s.runtime("A").get_datastore("ds").create_channel("sharedcell", "c1")
    s.runtime("B").get_datastore("ds").create_channel("sharedcell", "c2")
    s.process_all()
    s.runtime("A").get_datastore("ds").create_channel("sharedcell", "c3")
    s.runtime("B").get_datastore("ds").create_channel("sharedcell", "c4")
    s.process_all()
    for q in (qa, qb):
        assert q.get("k") == "v"


# ----------------------------------------------------------------------
# SharedQuorum

def test_quorum_accepts_after_all_clients_caught_up():
    s, (qa, qb) = make_session(2, "sharedquorum")
    qa.set("k", "v")
    s.process_all()
    # sequenced but msn hasn't caught up: still pending
    assert qa.get("k") is None and qa.get_pending("k") == "v"
    # traffic from BOTH clients advances everyone's refSeq past the set
    qa.set("other", 1)
    qb.set("other2", 2)
    s.process_all()
    qa.set("other3", 3)
    qb.set("other4", 4)
    s.process_all()
    for q in (qa, qb):
        assert q.get("k") == "v", (q.get_pending("k"), q._accepted)
    assert qa.signature() == qb.signature()


def test_quorum_later_set_supersedes_pending():
    s, (qa, qb) = make_session(2, "sharedquorum")
    qa.set("k", "first")
    qb.set("k", "second")
    s.process_all()
    for q in (qa, qb):
        assert q.get_pending("k") == "second"


# ----------------------------------------------------------------------
# Ink

def test_ink_strokes_converge():
    s, (ia, ib) = make_session(2, "ink")
    sid = ia.create_stroke({"color": "red"})
    ia.append_point(sid, {"x": 1, "y": 2})
    ia.append_point(sid, {"x": 3, "y": 4})
    s.process_all()
    stroke = ib.get_stroke(sid)
    assert stroke["pen"] == {"color": "red"}
    assert stroke["points"] == [{"x": 1, "y": 2}, {"x": 3, "y": 4}]
    assert ia.signature() == ib.signature()


def test_ink_clear_drops_concurrent_appends():
    s, (ia, ib) = make_session(2, "ink")
    sid = ia.create_stroke()
    s.process_all()
    ib.clear()
    ia.append_point(sid, {"x": 9, "y": 9})  # concurrent with clear
    s.process_all()
    # clear sequenced first; the append to a dropped stroke is a no-op
    assert ia.get_strokes() == [] or ia.get_stroke(sid) is None
    assert ia.signature() == ib.signature()


# ----------------------------------------------------------------------
# SharedSummaryBlock

def test_summary_block_roundtrip():
    from fluidframework_tpu.models.summaryblock import SharedSummaryBlock
    blk = SharedSummaryBlock("b")
    blk.set("schema", {"v": 1})
    summary = blk.summarize_core()
    fresh = SharedSummaryBlock("b")
    fresh.load_core(summary)
    assert fresh.get("schema") == {"v": 1}


def test_summary_block_rejects_live_writes():
    s, (ba,) = make_session(1, "sharedsummaryblock")
    with pytest.raises(RuntimeError):
        ba.set("k", 1)
