"""Batched tree rebase kernel vs the scalar mark-list algebra
(VERDICT r1 missing #1: the second kernel target).

Parity target: the APPLIED effect. For fuzzed changesets C and trunks
[O1..OK] over a shared base, applying the kernel-rebased atoms must
produce the same node sequence as applying the scalar-rebased marks —
the same end state the EditManager would hand the forest.
"""
import random

import numpy as np
import pytest

from fluidframework_tpu.models.tree import changeset as cs
from fluidframework_tpu.ops.tree_atoms import (
    DEFAULT_ATOMS,
    TreeAtoms,
    apply_atoms,
    atoms_to_marks,
    encode_changeset,
    stack_changesets,
)
from fluidframework_tpu.ops.tree_kernel import (
    rebase_atoms,
    rebase_over_trunk,
)

from fluidframework_tpu.testing.tree_fuzz import random_changeset, random_trunk

FIELD = "root"


def rand_marks(rng: random.Random, base_len: int, n_edits: int = 3):
    return random_changeset(rng, base_len, n_edits)


def base_seq(rng: random.Random, n: int):
    return [{"type": "n", "value": i} for i in range(n)]


def scalar_rebase_chain(c_marks, overs):
    change = {FIELD: c_marks}
    for o in overs:
        change = cs.rebase(change, {FIELD: o})
    return change.get(FIELD, [])


def apply_chain(seq, overs):
    for o in overs:
        seq = cs.walk_apply(seq, o)
    return seq


@pytest.mark.parametrize("seed", range(40))
def test_single_over_parity(seed):
    rng = random.Random(seed * 101 + 13)
    n = rng.randint(4, 16)
    base = base_seq(rng, n)
    c_marks = rand_marks(rng, n)
    o_marks = rand_marks(rng, n)

    after_o = cs.walk_apply(base, o_marks)
    scalar_marks = scalar_rebase_chain(c_marks, [o_marks])
    expect = cs.walk_apply(after_o, scalar_marks)

    enc_c, content = encode_changeset(c_marks)
    enc_o, _ = encode_changeset(o_marks)
    out = rebase_atoms(
        stack_changesets([enc_c]), stack_changesets([enc_o])
    )
    out_np = {f: np.asarray(getattr(out, f))[0] for f in out._fields}
    got = apply_atoms(after_o, out_np, content)
    assert got == expect, (
        f"seed {seed}: base={n}\nC={c_marks}\nO={o_marks}\n"
        f"scalar={scalar_marks}\nkernel={atoms_to_marks(out_np, content)}"
    )


@pytest.mark.parametrize("seed", range(25))
def test_trunk_scan_parity(seed):
    """Rebase over a K-deep trunk suffix: the scan must equal the
    scalar sequential rebase (the compose law)."""
    rng = random.Random(seed * 7 + 3)
    n = rng.randint(6, 14)
    k_trunk = rng.randint(2, 4)
    base = base_seq(rng, n)

    c_marks = rand_marks(rng, n)
    overs = []
    cur = list(base)
    for _ in range(k_trunk):
        o = rand_marks(rng, len(cur))
        overs.append(o)
        cur = cs.walk_apply(cur, o)

    scalar_marks = scalar_rebase_chain(c_marks, overs)
    expect = cs.walk_apply(cur, scalar_marks)

    enc_c, content = encode_changeset(c_marks)
    trunk_atoms = [encode_changeset(o, allow_moves=False)[0]
                   for o in overs]
    trunk = TreeAtoms(*[
        np.stack([np.stack([t[f] for t in trunk_atoms])])
        for f in ("kind", "pos", "n", "muted", "pos2")
    ])
    out = rebase_over_trunk(stack_changesets([enc_c]), trunk)
    out_np = {f: np.asarray(getattr(out, f))[0] for f in out._fields}
    got = apply_atoms(cur, out_np, content)
    assert got == expect, (
        f"seed {seed}: C={c_marks}\novers={overs}\n"
        f"scalar={scalar_marks}\nkernel={atoms_to_marks(out_np, content)}"
    )


def test_batched_docs_independent():
    """Docs rebase independently in one dispatch."""
    rng = random.Random(99)
    docs = 16
    cases = []
    for _ in range(docs):
        n = rng.randint(4, 12)
        base = base_seq(rng, n)
        c, o = rand_marks(rng, n), rand_marks(rng, n)
        cases.append((base, c, o))
    c_stack = stack_changesets(
        [encode_changeset(c)[0] for _, c, _ in cases]
    )
    o_stack = stack_changesets(
        [encode_changeset(o)[0] for _, _, o in cases]
    )
    out = rebase_atoms(c_stack, o_stack)
    for d, (base, c_marks, o_marks) in enumerate(cases):
        after_o = cs.walk_apply(base, o_marks)
        expect = cs.walk_apply(
            after_o, scalar_rebase_chain(c_marks, [o_marks])
        )
        out_np = {f: np.asarray(getattr(out, f))[d] for f in out._fields}
        content = encode_changeset(c_marks)[1]
        assert apply_atoms(after_o, out_np, content) == expect, d


def test_device_inexpressible_marks_raise():
    with pytest.raises(ValueError):
        encode_changeset([cs.rev(1, "uid", 0)])
    with pytest.raises(ValueError):
        encode_changeset(
            [cs.mod(fields={"x": [cs.dele(1)]})]
        )
    with pytest.raises(ValueError):
        encode_changeset([cs.dele(1)] * (DEFAULT_ATOMS + 1))


def test_valueless_mod_encodes_as_skip():
    """code-review r2: a valueless, fieldless mod is skip(1) after
    normalize; encoding must not emit a SET atom that decodes into a
    crash inside walk_apply."""
    enc, content = encode_changeset(
        [cs.skip(1), {"t": "mod"}, cs.dele(1)]
    )
    assert list(enc["kind"][:2]) == [2, 0]  # just the unit del
    assert enc["pos"][0] == 2
    got = apply_atoms(
        [{"v": 0}, {"v": 1}, {"v": 2}], enc, content
    )
    assert got == [{"v": 0}, {"v": 1}]


@pytest.mark.parametrize("seed", range(30))
def test_kernel_move_parity_fuzz(seed):
    """MOV atoms: a changeset containing a move rebased over a random
    ins/del/mod trunk must match the scalar algebra exactly —
    including delete-wins muting of both halves (VERDICT r2 #6)."""
    rng = random.Random(seed * 41 + 5)
    base = [{"type": "n", "value": i} for i in range(8)]
    src = rng.randint(0, len(base) - 1)
    choices = [d for d in range(len(base) + 1)
               if d <= src or d >= src + 1]
    dst = rng.choice(choices)
    c_marks = cs.stamp(
        {"root": cs.move(src, 1, dst)}, f"M{seed}"
    )["root"]
    overs, cur = random_trunk(rng, base, rng.randint(1, 4), 3)

    scalar_marks = scalar_rebase_chain(c_marks, overs)
    from fluidframework_tpu.models.tree.forest import Forest

    f = Forest({"root": [dict(x) for x in base]})
    for i, o in enumerate(overs):
        f.apply({"root": o}, f"o{i}")
    fs = f.clone()
    fs.apply({"root": scalar_marks}, "scalar")
    expect = fs.content()["root"]

    enc_c, content = encode_changeset(c_marks)
    trunk_atoms = [encode_changeset(o, allow_moves=False)[0]
                   for o in overs]
    trunk = TreeAtoms(*[
        np.stack([np.stack([t[f] for t in trunk_atoms])])
        for f in ("kind", "pos", "n", "muted", "pos2")
    ])
    out = rebase_over_trunk(stack_changesets([enc_c]), trunk)
    out_np = {f: np.asarray(getattr(out, f))[0] for f in out._fields}
    got = apply_atoms(cur, out_np, content)
    assert got == expect, (
        f"seed {seed}: C={c_marks}\novers={overs}\n"
        f"scalar={scalar_marks}\n"
        f"kernel={atoms_to_marks(out_np, content)}"
    )
