"""Generate the golden snapshot+oplog fixtures (run ONCE per format
version; the committed outputs are historical artifacts that CI loads
— regenerating them silently would defeat the back-compat check, so
only run this when intentionally minting fixtures for a NEW version).

Reference: packages/test/snapshots (stored-format replay validation).
"""
import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers import (  # noqa: E402
    LocalDocumentServiceFactory,
    save_document,
)
from fluidframework_tpu.loader import Container  # noqa: E402
from fluidframework_tpu.models.tree import node  # noqa: E402
from fluidframework_tpu.service.local_server import (  # noqa: E402
    LocalServer,
)

HERE = os.path.dirname(os.path.abspath(__file__))


def build_session():
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    c = Container.load(factory.create_document_service("golden"),
                       client_id="author")
    ds = c.runtime.create_datastore("app")
    text = ds.create_channel("sharedstring", "text")
    kv = ds.create_channel("sharedmap", "kv")
    tree = ds.create_channel("sharedtree", "tree")
    grid = ds.create_channel("sharedmatrix", "grid")
    c.flush()

    text.insert_text(0, "golden snapshot fixture")
    text.annotate_range(0, 6, {"style": "bold"})
    kv.set("version", 3)
    kv.set("author", "round-3")
    tree.insert_nodes(("root",), 0, [
        node("doc", value="fixture", ),
    ])
    tree.insert_nodes(("root", 0, "children"), 0, [
        node("leaf", value=i) for i in range(3)
    ])
    grid.insert_rows(0, 2)
    grid.insert_cols(0, 2)
    for r in range(2):
        for co in range(2):
            grid.set_cell(r, co, r * 2 + co)
    c.flush()
    c.summarize()

    # trailing ops AFTER the summary (load = snapshot + replay)
    text.insert_text(0, ">> ")
    kv.set("version", 4)
    c.flush()
    return server, c, {"text": text, "kv": kv, "tree": tree,
                       "grid": grid}


def main() -> None:
    server, c, channels = build_session()
    summary = server.latest_summary("golden")
    ops = server.read_ops("golden", 0)
    out = os.path.join(HERE, "golden_v1.json")
    save_document(out, "golden", ops,
                  (summary.sequence_number, summary.summary))
    expectations = {
        "text": channels["text"].get_text(),
        "kv_version": channels["kv"].get("version"),
        "tree_signature_sha": hashlib.sha256(
            str(channels["tree"].signature()).encode()
        ).hexdigest(),
        "grid_cells": [
            [channels["grid"].get_cell(r, co) for co in range(2)]
            for r in range(2)
        ],
        "final_seq": c.last_processed_seq,
    }
    with open(os.path.join(HERE, "golden_v1.expect.json"), "w") as f:
        json.dump(expectations, f, indent=2, sort_keys=True)
    print("wrote", out)
    print(json.dumps(expectations, indent=2)[:400])


if __name__ == "__main__":
    main()
