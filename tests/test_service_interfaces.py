"""services-core conformance: the concrete service classes satisfy the
interface layer (structural Protocols), and the riddler-analogue
token path gates the networked ingress.
"""
import time

import pytest

from fluidframework_tpu.service.core_interfaces import (
    IConsumer,
    IContentStore,
    IOpLog,
    IOrderer,
    IOrdererManager,
    IProducer,
    ITelemetrySink,
    ITenantManager,
)
from fluidframework_tpu.service.lambdas import OpLog
from fluidframework_tpu.service.local_orderer import LocalOrderer
from fluidframework_tpu.service.local_server import LocalServer
from fluidframework_tpu.service.partitioning import (
    FileOrderingQueue,
    InMemoryOrderingQueue,
)
from fluidframework_tpu.service.storage import ContentStore
from fluidframework_tpu.service.telemetry import Lumberjack
from fluidframework_tpu.service.tenancy import (
    SCOPE_READ,
    SCOPE_WRITE,
    AuthError,
    TenantManager,
    sign_token,
)


def test_concrete_classes_conform():
    assert isinstance(LocalOrderer("d"), IOrderer)
    assert isinstance(LocalServer(), IOrdererManager)
    assert isinstance(OpLog(), IOpLog)
    q = InMemoryOrderingQueue(1)
    assert isinstance(q, IProducer)
    assert isinstance(q, IConsumer)
    assert isinstance(ContentStore(), IContentStore)
    assert isinstance(TenantManager(), ITenantManager)
    assert isinstance(Lumberjack(), ITelemetrySink)


def test_file_queue_conforms(tmp_path):
    q = FileOrderingQueue(str(tmp_path), 1)
    assert isinstance(q, IProducer)
    assert isinstance(q, IConsumer)


# ---- tenancy / tokens -------------------------------------------------

def test_token_roundtrip():
    tm = TenantManager()
    t = tm.create_tenant("acme", "Acme Inc")
    tok = sign_token(t.key, "acme", "doc1", "alice")
    claims = tm.validate_token(tok, "acme", "doc1", SCOPE_WRITE)
    assert claims["user"]["id"] == "alice"


def test_token_rejections():
    tm = TenantManager()
    t = tm.create_tenant("acme")
    tok = sign_token(t.key, "acme", "doc1", "alice")
    with pytest.raises(AuthError, match="document mismatch"):
        tm.validate_token(tok, "acme", "other-doc")
    with pytest.raises(AuthError, match="unknown or disabled"):
        tm.validate_token(tok, "ghost", "doc1")
    with pytest.raises(AuthError, match="bad signature"):
        tm.validate_token(tok[:-4] + "AAAA", "acme", "doc1")
    expired = sign_token(t.key, "acme", "doc1", "alice",
                         lifetime_s=-5)
    with pytest.raises(AuthError, match="expired"):
        tm.validate_token(expired, "acme", "doc1")
    ro = sign_token(t.key, "acme", "doc1", "alice",
                    scopes=[SCOPE_READ])
    with pytest.raises(AuthError, match="missing scope"):
        tm.validate_token(ro, "acme", "doc1", SCOPE_WRITE)


def test_signed_non_object_claims_is_auth_error():
    # a valid-signature token whose claims JSON is a list/scalar must
    # map to AuthError (not AttributeError → generic server error)
    import base64
    import hashlib as _hl
    import hmac as _hm
    import json as _json

    tm = TenantManager()
    t = tm.create_tenant("acme")
    for bad_claims in ([1, 2, 3], "just-a-string", 42):
        payload = base64.urlsafe_b64encode(
            _json.dumps(bad_claims).encode()).rstrip(b"=").decode()
        sig = _hm.new(t.key.encode(), payload.encode(),
                      _hl.sha256).digest()
        sig_s = base64.urlsafe_b64encode(sig).rstrip(b"=").decode()
        with pytest.raises(AuthError, match="malformed"):
            tm.validate_token(f"{payload}.{sig_s}", "acme", "doc1")


def test_disabled_tenant_rejected():
    tm = TenantManager()
    t = tm.create_tenant("acme")
    tok = sign_token(t.key, "acme", "doc1", "alice")
    tm.disable_tenant("acme")
    with pytest.raises(AuthError):
        tm.validate_token(tok, "acme", "doc1")


def test_key_refresh_invalidates_old_tokens():
    tm = TenantManager()
    t = tm.create_tenant("acme")
    tok = sign_token(t.key, "acme", "doc1", "alice")
    tm.refresh_key("acme")
    with pytest.raises(AuthError, match="bad signature"):
        tm.validate_token(tok, "acme", "doc1")


# ---- authenticated ingress -------------------------------------------

@pytest.fixture()
def alfred_on_thread():
    """Start an AlfredServer on a background event loop; yields a
    factory taking (tenants) and returning the started server; tears
    the server down on the loop before stopping it (abandoned handler
    coroutines otherwise raise 'Event loop is closed' at GC)."""
    import asyncio
    import threading

    state = {}

    def start(tenants=None, local=None):
        from fluidframework_tpu.service.ingress import AlfredServer

        server = AlfredServer(local, tenants=tenants)
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            loop.run_until_complete(server.start())
            started.set()
            loop.run_forever()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert started.wait(10)
        state.update(server=server, loop=loop, thread=t)
        return server

    yield start
    if state:
        fut = asyncio.run_coroutine_threadsafe(
            state["server"].stop(), state["loop"])
        try:
            fut.result(timeout=10)
        except Exception:
            pass
        state["loop"].call_soon_threadsafe(state["loop"].stop)
        state["thread"].join(timeout=10)

def test_alfred_rejects_bad_token_and_accepts_good():
    import asyncio

    from fluidframework_tpu.service.ingress import (
        AlfredServer,
        pack_frame,
        read_frame,
    )

    tm = TenantManager()
    tenant = tm.create_tenant("acme")

    async def scenario():
        server = AlfredServer(tenants=tm)
        await server.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port)

        # bad token -> connect_document_error
        writer.write(pack_frame({
            "type": "connect_document", "document_id": "d",
            "client_id": "alice", "tenant_id": "acme",
            "token": "bogus.token",
        }))
        await writer.drain()
        resp = await read_frame(reader)
        assert resp["type"] == "connect_document_error"
        assert "malformed token" in resp["message"]

        # good token -> connected
        tok = sign_token(tenant.key, "acme", "d", "alice")
        writer.write(pack_frame({
            "type": "connect_document", "document_id": "d",
            "client_id": "alice", "tenant_id": "acme", "token": tok,
        }))
        await writer.drain()
        while True:
            resp = await read_frame(reader)
            if resp["type"] in ("connected", "connect_document_error"):
                break
        assert resp["type"] == "connected"
        writer.close()
        await server.stop()

    asyncio.run(scenario())


def test_read_mode_connection_cannot_write_and_does_not_pin_msn():
    """Read-scoped connections subscribe without joining the quorum."""
    from fluidframework_tpu.service.local_server import LocalServer

    server = LocalServer()
    seen = []
    ro = server.connect("d", "reader", on_message=seen.append,
                        read_only=True)
    # reader is not in the quorum
    assert "reader" not in server.get_orderer("d").sequencer.clients
    # a writer's ops still reach the reader
    rw = server.connect("d", "writer", on_message=lambda m: None)
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )

    rw.submit(DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={"x": 1}))
    assert any(getattr(m, "type", None) == MessageType.OPERATION
               for m in seen)
    with pytest.raises(PermissionError, match="read-mode"):
        ro.submit(DocumentMessage(
            client_sequence_number=1, reference_sequence_number=0,
            type=MessageType.OPERATION, contents={}))


def test_connect_rejection_prompt_while_holding_service_lock(
        alfred_on_thread):
    """Regression: the documented usage holds svc.lock around
    Container.load; connect_document_error used to route through the
    dispatcher (which needs that lock), so an auth rejection surfaced
    as a full-timeout TimeoutError instead of a prompt
    PermissionError."""
    import time as _time

    from fluidframework_tpu.drivers.socket_driver import (
        SocketDocumentService,
    )

    tm = TenantManager()
    tm.create_tenant("acme")
    server = alfred_on_thread(tenants=tm)
    svc = SocketDocumentService(
        "127.0.0.1", server.port, "d", timeout=10.0,
        tenant_id="acme", token="bogus.token")
    try:
        with svc.lock:        # what Container.load does
            t0 = _time.monotonic()
            with pytest.raises(PermissionError, match="rejected"):
                svc.connect_to_delta_stream("alice", lambda m: None)
            assert _time.monotonic() - t0 < 5.0  # prompt, not timeout
    finally:
        svc.close()


def test_storage_planes_require_auth():
    """Regression: read_ops/fetch_summary must not bypass the token
    gate — an unauthenticated socket could read any document."""
    import asyncio

    from fluidframework_tpu.service.ingress import (
        AlfredServer,
        pack_frame,
        read_frame,
    )

    tm = TenantManager()
    tenant = tm.create_tenant("acme")

    async def scenario():
        server = AlfredServer(tenants=tm)
        await server.start()
        # seed the document through an authed connection
        r1, w1 = await asyncio.open_connection("127.0.0.1", server.port)
        tok = sign_token(tenant.key, "acme", "d", "alice")
        w1.write(pack_frame({
            "type": "connect_document", "document_id": "d",
            "client_id": "alice", "tenant_id": "acme", "token": tok,
        }))
        await w1.drain()

        # unauthenticated socket tries to read the op log
        r2, w2 = await asyncio.open_connection("127.0.0.1", server.port)
        w2.write(pack_frame({
            "type": "read_ops", "rid": 1, "document_id": "d",
            "from_seq": 0,
        }))
        await w2.drain()
        resp = await read_frame(r2)
        assert resp["type"] == "error"
        assert "not authorized" in resp["message"]
        w2.write(pack_frame({
            "type": "fetch_summary", "rid": 2, "document_id": "d",
        }))
        await w2.drain()
        resp = await read_frame(r2)
        assert resp["type"] == "error"
        w1.close()
        w2.close()
        await server.stop()

    asyncio.run(scenario())


def test_read_mode_submit_nacked_over_socket():
    """Regression: a submit on a read-mode SOCKET connection must fire
    on_nack (not vanish into a stderr log)."""
    import asyncio

    from fluidframework_tpu.service.ingress import (
        AlfredServer,
        pack_frame,
        read_frame,
    )
    from fluidframework_tpu.protocol.messages import NackErrorType

    async def scenario():
        server = AlfredServer()
        await server.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port)
        writer.write(pack_frame({
            "type": "connect_document", "document_id": "d",
            "client_id": "viewer", "mode": "read",
        }))
        await writer.drain()
        resp = await read_frame(reader)
        assert resp["type"] == "connected"
        writer.write(pack_frame({
            "type": "submitOp", "document_id": "d",
            "op": {"client_sequence_number": 1,
                   "reference_sequence_number": 0,
                   "type": 2, "contents": None, "metadata": None,
                   "traces": []},
        }))
        await writer.drain()
        while True:
            resp = await read_frame(reader)
            if resp["type"] == "nack":
                break
        assert resp["error_type"] == int(NackErrorType.INVALID_SCOPE)
        assert "read-mode" in resp["message"]
        writer.close()
        await server.stop()

    asyncio.run(scenario())


def test_multiplexed_token_refresh_not_sticky(alfred_on_thread):
    """Regression: a rejected facade must accept a new token on retry
    (cached facade used to keep the old token + sticky auth_error)."""
    from fluidframework_tpu.drivers.caching_driver import (
        MultiplexedSocketClient,
    )

    tm = TenantManager()
    tenant = tm.create_tenant("acme")
    server = alfred_on_thread(tenants=tm)
    client = MultiplexedSocketClient("127.0.0.1", server.port,
                                     timeout=5)
    bad = client.document_service("d", tenant_id="acme",
                                  token="junk.tok")
    with pytest.raises(PermissionError):
        bad.connect_to_delta_stream("alice", lambda m: None)
    good_tok = sign_token(tenant.key, "acme", "d", "alice")
    good = client.document_service("d", tenant_id="acme",
                                   token=good_tok)
    conn = good.connect_to_delta_stream("alice", lambda m: None)
    assert conn.open
    client.close()


def test_loader_reads_storage_with_token_before_connect(
        alfred_on_thread):
    """Regression: Container.load fetches summary + ops BEFORE the
    delta-stream connect; storage-plane requests must honor the token
    themselves (found by examples/secure_multitenant.py)."""
    from fluidframework_tpu.drivers.socket_driver import (
        SocketDocumentService,
    )
    from fluidframework_tpu.loader import Container

    tm = TenantManager()
    tenant = tm.create_tenant("acme")
    server = alfred_on_thread(tenants=tm)
    tok = sign_token(tenant.key, "acme", "d", "alice")
    svc = SocketDocumentService(
        "127.0.0.1", server.port, "d",
        tenant_id="acme", token=tok, timeout=10)
    with svc.lock:
        c = Container.load(svc, client_id="alice")  # reads first
        ch = (c.runtime.create_datastore("ds")
              .create_channel("sharedstring", "t"))
        c.flush()
        ch.insert_text(0, "authed")
        c.flush()
    # a second authed client loads the doc purely via storage
    tok2 = sign_token(tenant.key, "acme", "d", "bob")
    svc2 = SocketDocumentService(
        "127.0.0.1", server.port, "d",
        tenant_id="acme", token=tok2, timeout=10)
    with svc2.lock:
        c2 = Container.load(svc2, client_id="bob")
        got = c2.runtime.get_datastore("ds").get_channel("t")
        assert got.get_text() == "authed"
    svc.close()
    svc2.close()


# ---- foreman: task routing to agent workers ---------------------------

def _help_msg(seq, tasks):
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )
    from fluidframework_tpu.service.foreman import help_envelope

    return SequencedMessage(
        client_id="runtime", sequence_number=seq,
        minimum_sequence_number=0, client_sequence_number=seq,
        reference_sequence_number=0, type=MessageType.OPERATION,
        contents=help_envelope(tasks),
    )


def test_foreman_routes_least_loaded_and_reroutes_on_leave():
    from fluidframework_tpu.service.foreman import ForemanLambda

    ran = []
    fm = ForemanLambda()
    fm.register_agent("spell-1", {"spell"},
                      run=lambda t, m: ran.append(("spell-1", t)))
    fm.register_agent("spell-2", {"spell"},
                      run=lambda t, m: ran.append(("spell-2", t)))
    fm.register_agent("intel", {"translate", "*"},
                      run=lambda t, m: ran.append(("intel", t)))
    fm.handler(_help_msg(1, ["spell:doc1", "translate:doc1"]))
    # no capability match for 'spell:doc1' string: capabilities match
    # by task name
    fm2 = ForemanLambda()
    fm2.register_agent("a", {"spell"},
                       run=lambda t, m: ran.append(("a", t)))
    fm2.register_agent("b", {"spell"},
                       run=lambda t, m: ran.append(("b", t)))
    fm2.handler(_help_msg(1, ["spell"]))
    assert fm2.assignments["spell"] == "a"       # tiebreak by name
    fm2.handler(_help_msg(2, ["spell"]))         # duplicate: no-op
    assert fm2.agent_load("a") == 1 and fm2.agent_load("b") == 0
    # agent leaves: its task reroutes to the survivor
    fm2.unregister_agent("a")
    assert fm2.assignments["spell"] == "b"
    # completion frees the slot
    fm2.complete("spell")
    assert fm2.agent_load("b") == 0
    assert "spell" not in fm2.assignments


def test_foreman_queues_until_capable_agent_registers():
    from fluidframework_tpu.service.foreman import ForemanLambda

    fm = ForemanLambda()
    fm.handler(_help_msg(1, ["snapshot"]))
    assert fm.unassigned and not fm.assignments
    ran = []
    fm.register_agent("snapper", {"snapshot"},
                      run=lambda t, m: ran.append(t))
    assert fm.assignments["snapshot"] == "snapper"
    assert ran == ["snapshot"]
    assert not fm.unassigned


def test_wire_version_negotiation(alfred_on_thread):
    """connect_document negotiates the newest shared wire version;
    disjoint offers are a connect error, not a silent mismatch."""
    import asyncio

    from fluidframework_tpu.service.ingress import (
        AlfredServer,
        pack_frame,
        read_frame,
    )

    async def scenario():
        server = AlfredServer()
        await server.start()
        r, w = await asyncio.open_connection("127.0.0.1", server.port)
        # current client
        w.write(pack_frame({
            "type": "connect_document", "document_id": "d",
            "client_id": "a", "versions": ["2.0", "1.0"],
        }))
        await w.drain()
        while True:
            resp = await read_frame(r)
            if resp["type"] in ("connected", "connect_document_error"):
                break
        assert resp["type"] == "connected"
        assert resp["version"] == "1.0"
        # future-only client: refused loudly
        w.write(pack_frame({
            "type": "connect_document", "document_id": "d2",
            "client_id": "a", "versions": ["9.9"],
        }))
        await w.drain()
        while True:
            resp = await read_frame(r)
            if resp["type"] in ("connected", "connect_document_error"):
                break
        assert resp["type"] == "connect_document_error"
        assert "no common wire version" in resp["message"]
        # legacy client with no field: implicit 1.0
        w.write(pack_frame({
            "type": "connect_document", "document_id": "d3",
            "client_id": "a",
        }))
        await w.drain()
        while True:
            resp = await read_frame(r)
            if resp["type"] in ("connected", "connect_document_error"):
                break
        assert resp["type"] == "connected"
        w.close()
        await server.stop()

    asyncio.run(scenario())
