"""Compat matrix (describeCompat analogue): every scenario runs for
each writer configuration — current format and the oldest supported
(legacy) format — asserting load, collaboration, and forward
re-summarize. Guards the persisted-format axis the way
packages/test/test-version-utils guards version pairings.
"""
import pytest

from fluidframework_tpu.models import SharedString
from fluidframework_tpu.testing.compat import (
    CompatConfig,
    compat_matrix,
    downgrade_sharedstring_summary,
    import_as_fresh_document,
)
from fluidframework_tpu.testing.runtime_mocks import ContainerSession

MATRIX = list(compat_matrix())


def _build_document():
    """A session whose summary exercises text, markers, removes,
    props, and attribution."""
    s = ContainerSession(["A", "B"])
    for c in ("A", "B"):
        s.runtime(c).create_datastore("ds").create_channel(
            "sharedstring", "t")
    s.process_all()
    a = s.runtime("A").get_datastore("ds").get_channel("t")
    b = s.runtime("B").get_datastore("ds").get_channel("t")
    a.insert_text(0, "hello brave world")
    s.process_all()
    b.remove_text(6, 12)  # drop "brave "
    s.process_all()
    a.annotate_range(0, 5, {"bold": 1})
    s.process_all()
    return s, a, b


@pytest.mark.parametrize("config", MATRIX, ids=lambda c: c.name)
def test_summary_loads_across_formats(config: CompatConfig):
    s, a, b = _build_document()
    summary = config.channel_summary("sharedstring",
                                     a.summarize_core())
    if config.summary_format == "legacy":
        assert "segments" in summary and "chunks" not in summary
    fresh = SharedString("t2")
    fresh.load_core(summary)
    assert fresh.get_text() == a.get_text() == "hello world"
    # forward re-summarize: ALWAYS the current format, whatever loaded
    again = fresh.summarize_core()
    assert again.get("format") == 2 and "chunks" in again


@pytest.mark.parametrize("config", MATRIX, ids=lambda c: c.name)
def test_legacy_loaded_replica_collaborates(config: CompatConfig):
    """A replica booted from an old-format summary must converge with
    current-format replicas in live collaboration (the new-runtime +
    old-snapshot pairing)."""
    s, a, b = _build_document()
    summary = config.channel_summary("sharedstring",
                                     a.summarize_core())
    # booting a NEW document from stored content: rebase into the new
    # document's sequence space (same-document loads keep the original
    # seq space via the op log — tests/test_local_server.py)
    imported = import_as_fresh_document(summary)

    s2 = ContainerSession(["X", "Y"])
    for c in ("X", "Y"):
        ds = s2.runtime(c).create_datastore("ds")
        chan = ds.create_channel("sharedstring", "t")
        chan.client.mergetree.segments.clear()
        chan.load_core(imported)
    s2.process_all()
    x = s2.runtime("X").get_datastore("ds").get_channel("t")
    y = s2.runtime("Y").get_datastore("ds").get_channel("t")
    x.insert_text(0, ">> ")
    y.insert_text(len(y.get_text()), " <<")
    s2.process_all()
    assert x.get_text() == y.get_text()
    assert x.get_text() == ">> hello world <<"


def test_downgrade_preserves_content_exactly():
    s, a, b = _build_document()
    current = a.summarize_core()
    legacy = downgrade_sharedstring_summary(current)
    flat_current = [e for chunk in current["chunks"] for e in chunk]
    assert legacy["segments"] == flat_current
    assert legacy["minSeq"] == current["minSeq"]


def test_downgraded_summary_shape_matches_golden_fixture():
    """The committed golden fixture (written by the round-3 format-1
    era writer) and downgrade_sharedstring_summary must agree on the
    legacy shape: the downgrade of a current summary must load through
    the same code path the fixture does."""
    s, a, b = _build_document()
    legacy = downgrade_sharedstring_summary(a.summarize_core())
    # the legacy shape: flat segments list, no format/chunks keys
    assert set(legacy) >= {"segments", "minSeq", "currentSeq"}
    assert "chunks" not in legacy and "format" not in legacy
    fresh = SharedString("from-legacy")
    fresh.load_core(legacy)
    assert fresh.get_text() == a.get_text()
