"""Op lifecycle: compression, chunking, batch marks, scheduling.

Mirrors test-end-to-end-tests/src/test/messageSize.spec.ts (chunked
>1MB ops), opCompressor/opSplitter unit tests, and ScheduleManager
batch-integrity tests.
"""
import pytest

from fluidframework_tpu.protocol.messages import (
    MessageType,
    SequencedMessage,
)
from fluidframework_tpu.runtime.op_lifecycle import (
    ChunkReassembler,
    OpCompressor,
    OpDecompressor,
    OpSplitter,
    RemoteMessageProcessor,
    batch_flag,
    mark_batch,
)
from fluidframework_tpu.loader.scheduler import (
    DeltaScheduler,
    ScheduleManager,
)
from fluidframework_tpu.testing.runtime_mocks import ContainerSession


# ----------------------------------------------------------------------
# unit: compressor / splitter / reassembler

def envelope(payload):
    return {"kind": "op", "address": "ds", "channel": "ch",
            "contents": payload}


def test_compressor_small_ops_pass_through():
    env = envelope({"v": 1})
    assert OpCompressor().maybe_compress(env) is env


def test_compressor_roundtrip():
    env = envelope({"text": "na" * 8000})
    comp = OpCompressor(min_size=128).maybe_compress(env)
    assert comp["kind"] == "compressed"
    assert len(str(comp)) < len(str(env))  # actually smaller
    assert OpDecompressor.decompress(comp) == env


def test_splitter_chunks_and_reassembles():
    env = envelope({"blob": "x" * 1000})
    chunks = OpSplitter(chunk_size=256).split(env)
    assert len(chunks) > 1
    assert all(c["kind"] == "chunk" for c in chunks)
    ra = ChunkReassembler()
    done = None
    for c in chunks:
        assert done is None
        done = ra.add("client", c)
    assert done == env


def test_remote_processor_interleaved_clients():
    """Chunk streams from different clients must not mix."""
    env_a = envelope({"blob": "a" * 600})
    env_b = envelope({"blob": "b" * 600})
    ca = OpSplitter(chunk_size=256).split(env_a)
    cb = OpSplitter(chunk_size=256).split(env_b)
    proc = RemoteMessageProcessor()
    results = []
    for pair in zip(ca, cb):
        results.append(proc.process("A", pair[0]))
        results.append(proc.process("B", pair[1]))
    finished = [r for r in results if r is not None]
    assert finished == [env_a, env_b]


def test_compress_then_chunk_roundtrip():
    env = envelope({"blob": "qz" * 4000})
    comp = OpCompressor(min_size=64).maybe_compress(env)
    chunks = OpSplitter(chunk_size=128).split(comp)
    proc = RemoteMessageProcessor()
    out = None
    for c in chunks:
        out = proc.process("A", c)
    assert out == env


# ----------------------------------------------------------------------
# integration: huge op end-to-end through the runtime stack

def make_session(n=2, ctype="sharedmap", cid="m"):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    for c in ids:
        s.runtime(c).create_datastore("ds").create_channel(ctype, cid)
    chans = [
        s.runtime(c).get_datastore("ds").get_channel(cid) for c in ids
    ]
    return s, chans


def test_megabyte_op_roundtrips_chunked():
    """messageSize.spec.ts: >chunk-threshold ops split and converge."""
    s, (ma, mb) = make_session()
    for rt in (s.runtime("A"), s.runtime("B")):
        rt.splitter.chunk_size = 2048  # force chunking at small size
    big = "payload-" * 4096  # ~32KB
    ma.set("big", big)
    sent_before = s.pending_count
    s.flush("A")
    assert s.pending_count > 1  # really chunked into several messages
    s.process_all()
    assert mb.get("big") == big
    assert ma.signature() == mb.signature()


def test_chunked_own_op_acks_once():
    s, (ma, mb) = make_session()
    s.runtime("A").splitter.chunk_size = 1024
    ma.set("k", "v" * 5000)
    ma.set("k2", "small")
    s.process_all()
    assert s.runtime("A").pending.count == 0
    assert mb.get("k2") == "small"
    assert ma.signature() == mb.signature()


def test_compressed_op_roundtrips():
    s, (ma, mb) = make_session()
    s.runtime("A").compressor.min_size = 64
    ma.set("k", "abcabc" * 400)
    s.process_all()
    assert mb.get("k") == "abcabc" * 400


# ----------------------------------------------------------------------
# batch marks + schedule manager

def seqmsg(n, client="A", metadata=None, mtype=MessageType.OPERATION):
    return SequencedMessage(
        client_id=client, sequence_number=n, minimum_sequence_number=0,
        client_sequence_number=n, reference_sequence_number=0,
        type=mtype, contents={"n": n}, metadata=metadata,
    )


def test_flush_marks_batch_boundaries():
    s, (ma, mb) = make_session()
    ma.set("a", 1)
    ma.set("b", 2)
    ma.set("c", 3)
    s.flush("A")
    metas = [raw.metadata for _, raw in s._raw_queue]
    assert batch_flag(metas[0]) is True
    assert batch_flag(metas[-1]) is False
    assert all(batch_flag(m) is None for m in metas[1:-1])
    s.process_all()
    assert ma.signature() == mb.signature()


def test_schedule_manager_releases_complete_batch():
    sm = ScheduleManager()
    assert sm.feed(seqmsg(1)) == [seqmsg(1)]
    assert sm.feed(seqmsg(2, metadata=mark_batch(None, True))) == []
    assert sm.feed(seqmsg(3)) == []
    out = sm.feed(seqmsg(4, metadata=mark_batch(None, False)))
    assert [m.sequence_number for m in out] == [2, 3, 4]


def test_schedule_manager_holds_system_messages_in_seq_order_mid_batch():
    """A service-interleaved system message must NOT be released ahead
    of the still-buffered batch: Container._process asserts strict seq
    continuity, so reordering would crash (ADVICE r1 #1). The reference
    scheduleManager.ts pauses the queue until the whole batch is in."""
    sm = ScheduleManager()
    sm.feed(seqmsg(1, metadata=mark_batch(None, True)))
    join = seqmsg(2, client=None, mtype=MessageType.CLIENT_JOIN)
    assert sm.feed(join) == []  # held — not reordered ahead of seq 1
    out = sm.feed(seqmsg(3, metadata=mark_batch(None, False)))
    assert [m.sequence_number for m in out] == [1, 2, 3]
    assert out[1].type == MessageType.CLIENT_JOIN


def test_schedule_manager_asserts_foreign_op_mid_batch():
    sm = ScheduleManager()
    sm.feed(seqmsg(1, metadata=mark_batch(None, True)))
    with pytest.raises(AssertionError):
        sm.feed(seqmsg(2, client="B"))


def test_delta_scheduler_batch_is_atomic_across_slices():
    processed = []
    ds = DeltaScheduler(lambda m: processed.append(m.sequence_number))
    ds.enqueue([seqmsg(1), seqmsg(2)])  # one batch
    ds.enqueue([seqmsg(3)])
    # zero budget: first unit still processes whole, then yields
    ds.drain(slice_s=0.0)
    assert processed == [1, 2]
    assert ds.pending_units == 1
    ds.drain()
    assert processed == [1, 2, 3]


def test_delta_scheduler_slice_deadline_on_a_manual_clock():
    """The slice budget runs on the injected clock (the detcheck
    wall-clock-unrouted contract): a deadline mid-queue yields
    between units deterministically, with no wall-clock read."""
    t = {"v": 0.0}
    processed = []

    def tick_process(m):
        processed.append(m.sequence_number)
        t["v"] += 0.03            # each message costs 30 simulated ms

    ds = DeltaScheduler(tick_process, clock=lambda: t["v"])
    ds.enqueue([seqmsg(1), seqmsg(2)])
    ds.enqueue([seqmsg(3)])
    ds.enqueue([seqmsg(4)])
    # 50ms budget: unit one (60ms, atomic) overruns the deadline ->
    # yield; units two and three wait for the next slice
    assert ds.drain(slice_s=0.05) == 2
    assert processed == [1, 2]
    assert ds.pending_units == 2
    assert ds.drain(slice_s=0.05) == 2
    assert processed == [1, 2, 3, 4]
