"""End-to-end integration over the in-proc service (SURVEY §4 pillar
(c)): real sequencing, msn, nacks, summaries, op-log truncation,
failover — zero deployment.

Mirrors packages/test/local-server-tests/src/test."""
import pytest

from fluidframework_tpu.drivers import (
    LocalDocumentServiceFactory,
    load_document,
    save_document,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
)
from fluidframework_tpu.service.local_server import LocalServer


def make_pair(doc="doc"):
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service(doc),
                       client_id="alice")
    b = Container.load(factory.create_document_service(doc),
                       client_id="bob")
    return server, factory, a, b


def bootstrap(container):
    ds = container.runtime.create_datastore("default")
    return ds.create_channel("sharedstring", "text")


def text_of(container):
    return (container.runtime.get_datastore("default")
            .get_channel("text").get_text())


def test_two_containers_collaborate_through_service():
    server, factory, a, b = make_pair()
    sa = bootstrap(a)
    sb = bootstrap(b)
    sa.insert_text(0, "hello")
    a.flush()
    sb.insert_text(0, "world-")
    b.flush()
    assert text_of(a) == text_of(b)
    assert "hello" in text_of(a) and "world-" in text_of(a)
    # service state: ops durably logged, msn advanced
    orderer = server.get_orderer("doc")
    assert len(orderer.op_log) > 0
    assert orderer.sequencer.minimum_sequence_number >= 1


def test_quorum_visible_to_clients():
    server, factory, a, b = make_pair()
    assert set(a.protocol.quorum.members) == {"alice", "bob"}
    b.close()
    assert "bob" not in a.protocol.quorum.members


def test_summarize_ack_and_late_join_from_summary():
    server, factory, a, b = make_pair()
    sa = bootstrap(a)
    bootstrap(b)
    sa.insert_text(0, "summarized content")
    a.flush()
    acks = []
    a.on("summaryAck", acks.append)
    a.summarize()
    assert acks and "handle" in acks[0]
    # service summary exists; op log truncated at the summary refseq
    latest = server.latest_summary("doc")
    assert latest is not None
    assert "runtime" in latest.summary and "protocol" in latest.summary
    remaining = server.read_ops("doc", 0)
    summarized_refseq = latest.sequence_number - 1  # submitted at tip
    assert all(m.sequence_number > summarized_refseq for m in remaining)

    # new client loads from the service summary + trailing ops
    sa.insert_text(0, ">")
    a.flush()
    c = Container.load(factory.create_document_service("doc"),
                       client_id="carol")
    assert text_of(c) == ">summarized content"
    # and can edit
    c.runtime.get_datastore("default").get_channel("text").insert_text(
        0, "c:"
    )
    c.flush()
    assert text_of(a) == "c:>summarized content"
    assert text_of(b) == text_of(a)


def test_stale_client_nacked_by_service():
    server, factory, a, b = make_pair()
    orderer = server.get_orderer("doc")
    nack = orderer.submit("alice", DocumentMessage(
        client_sequence_number=99,  # csn gap
        reference_sequence_number=0,
        type=MessageType.OPERATION,
        contents=None,
    ))
    assert nack is not None and "gap" in nack.message


def test_container_reconnect_with_offline_edits():
    server, factory, a, b = make_pair()
    sa = bootstrap(a)
    bootstrap(b)
    sa.insert_text(0, "base")
    a.flush()
    a.disconnect()
    sa.insert_text(4, "-offline")
    a.flush()  # goes to pending, not the wire
    sb = b.runtime.get_datastore("default").get_channel("text")
    sb.insert_text(0, "b:")
    b.flush()
    assert text_of(b) == "b:base"
    a.connect()  # catch-up + pending replay
    a.flush()
    assert text_of(a) == text_of(b) == "b:base-offline"


def test_gap_refetch_from_delta_storage():
    """A connection that drops messages recovers via delta storage."""
    server, factory, a, b = make_pair()
    sa = bootstrap(a)
    bootstrap(b)
    # sabotage: swallow the next broadcast to bob
    orig = b._on_message
    dropped = []

    def lossy(msg):
        if not dropped:
            dropped.append(msg)
            return  # lost in the network
        orig(msg)

    b._connection.on_message = lossy
    sa.insert_text(0, "one")   # this broadcast is dropped for bob
    a.flush()
    sa.insert_text(3, "two")   # arrival triggers bob's gap refetch
    a.flush()
    assert text_of(b) == "onetwo"


def test_orderer_checkpoint_failover():
    """Service failover: restore the orderer from its checkpoint and
    continue the same session (Kafka partition reassignment, §5.3)."""
    server, factory, a, b = make_pair()
    sa = bootstrap(a)
    bootstrap(b)
    sa.insert_text(0, "before")
    a.flush()
    orderer = server.get_orderer("doc")
    state = orderer.checkpoint()
    orderer.restore(state)
    sa.insert_text(6, "-after")
    a.flush()
    assert text_of(a) == text_of(b) == "before-after"


def test_record_and_replay_roundtrip(tmp_path):
    server, factory, a, b = make_pair()
    sa = bootstrap(a)
    bootstrap(b)
    sa.insert_text(0, "persist me")
    a.flush()
    b.runtime.get_datastore("default").get_channel("text").remove_text(0, 8)
    b.flush()
    expected = text_of(a)

    orderer = server.get_orderer("doc")
    path = tmp_path / "doc.json"
    save_document(path, "doc", orderer.op_log.read(0))
    replay_service = load_document(path)
    replayed = Container.load(replay_service, client_id="replayer",
                              connect=False)
    # replay catch-up happens via read_ops during load
    assert text_of(replayed) == expected


def test_server_minted_corpus_is_byte_stable_on_a_manual_clock():
    """The whole server pipeline routes its wire timestamps through
    the injected clock (sequencer ticket/system stamps, join
    ClientDetail, scriptorium/scribe/broadcaster hop stamps): two
    identical sessions on the same manual clock record byte-identical
    op logs, modulo the client/driver hop stamps minted by the OTHER
    process's clock (the detcheck wall-clock-unrouted contract)."""
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.protocol.serialization import (
        message_to_json,
    )

    def strip_client_hops(rec):
        rec = dict(rec)
        rec["traces"] = [
            t for t in rec.get("traces") or []
            if t["service"] not in ("client", "driver")
        ]
        return rec

    def session():
        t = {"v": 1000.0}

        def clock():
            t["v"] += 0.5
            return t["v"]

        server = LocalServer(clock=clock)
        factory = LocalDocumentServiceFactory(server)
        a = Container.load(factory.create_document_service("doc"),
                           client_id="alice")
        b = Container.load(factory.create_document_service("doc"),
                           client_id="bob")
        sa = a.runtime.create_datastore("d").create_channel(
            "sharedstring", "t")
        a.flush()
        sb = b.runtime.get_datastore("d").get_channel("t")
        sa.insert_text(0, "hello ")
        a.flush()
        sb.insert_text(6, "world")
        b.flush()
        assert sa.get_text() == sb.get_text() == "hello world"
        return [message_to_json(m)
                for m in server.get_orderer("doc").op_log.read(0)]

    s1, s2 = session(), session()
    assert [strip_client_hops(m) for m in s1] == \
        [strip_client_hops(m) for m in s2]
    for rec in s1:
        assert 1000.0 < rec["timestamp"] < 2000.0
        for t in strip_client_hops(rec)["traces"]:
            assert 1000.0 < t["timestamp"] < 2000.0, t
