"""Multi-device mesh: sharding placement, cross-device collectives,
and scaling plumbing on the 8-device virtual CPU mesh (conftest).

SURVEY §2.9 axis 1 (document parallelism over the mesh) and §5.8 (the
collective plane): doc shards must actually land one-per-device, the
global collab-window floor must ride a real collective (lax.pmin under
shard_map), and the sharded executor must agree bit-for-bit with the
single-device one.
"""
import jax
import numpy as np
import pytest

from fluidframework_tpu.ops import (
    build_batch,
    encode_stream,
    fetch,
    make_table,
)
from fluidframework_tpu.ops.merge_kernel import apply_window_impl
from fluidframework_tpu.parallel import (
    DOC_AXIS,
    doc_sharding,
    global_window_floor,
    make_mesh,
    shard_pytree,
)
from fluidframework_tpu.testing import FuzzConfig, record_op_stream

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)


def _workload(docs, window=40):
    streams = []
    for d in range(docs):
        _, s = record_op_stream(FuzzConfig(
            n_clients=3, n_steps=30, seed=7000 + d,
        ))
        streams.append(encode_stream(s))
    return build_batch(streams, window=window)


def test_doc_shards_place_one_per_device():
    mesh = make_mesh(jax.devices()[:8])
    table = shard_pytree(make_table(16, 128), mesh)
    # every array's shards split dim 0 across all 8 devices
    sharding = table.length.sharding
    assert sharding.is_equivalent_to(doc_sharding(mesh), ndim=2)
    devices = {
        s.device for s in table.length.addressable_shards
    }
    assert len(devices) == 8
    for shard in table.length.addressable_shards:
        assert shard.data.shape == (2, 128)  # 16 docs / 8 devices


def test_sharded_apply_matches_single_device():
    docs = 16
    batch = _workload(docs)
    ref = fetch(apply_window_impl(make_table(docs, 128), batch))

    mesh = make_mesh(jax.devices()[:8])
    table = shard_pytree(make_table(docs, 128), mesh)
    sbatch = shard_pytree(batch, mesh)
    step = jax.jit(apply_window_impl, out_shardings=doc_sharding(mesh))
    got = fetch(step(table, sbatch))
    for f in ref:
        np.testing.assert_array_equal(got[f], ref[f], err_msg=f)


def test_global_window_floor_collective():
    mesh = make_mesh(jax.devices()[:8])
    min_seq = jax.device_put(
        np.array([9, 5, 7, 3, 8, 6, 4, 11, 2, 9, 5, 7, 3, 8, 6, 4],
                 np.int32),
        doc_sharding(mesh),
    )
    floor = global_window_floor(min_seq, mesh)
    assert int(floor) == 2
    # the reduction result is replicated (usable on every shard)
    assert floor.sharding.is_fully_replicated


def test_uneven_docs_pad_to_mesh():
    """Doc counts that don't divide the mesh must still be shardable
    via padding at the caller (the sidecar always allocates max_docs
    as a device multiple; this pins the constraint)."""
    mesh = make_mesh(jax.devices()[:8])
    with pytest.raises(ValueError):
        shard_pytree(make_table(10, 128), mesh)  # 10 % 8 != 0