"""Native (C++) sequencer core: differential tests vs the Python
DocumentSequencer oracle, checkpoint parity, end-to-end service use.

SURVEY §4's TPU-kernel pillar applies to native host code too: the
scalar Python implementation is the spec; the native core must match
it op-for-op on fuzzed streams, including every nack path.
"""
import random

import pytest

from fluidframework_tpu.protocol.messages import (
    ClientDetail,
    DocumentMessage,
    MessageType,
)
from fluidframework_tpu.service.sequencer import DocumentSequencer

native = pytest.importorskip("fluidframework_tpu.native")
try:
    native.NativeSequencerCore("probe")
    HAVE_NATIVE = True
except (RuntimeError, OSError):  # no toolchain in this environment
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native toolchain unavailable"
)


def op(csn, refseq):
    return DocumentMessage(
        client_sequence_number=csn,
        reference_sequence_number=refseq,
        type=MessageType.OPERATION,
        contents={"csn": csn},
    )


def make_pair():
    return (DocumentSequencer("doc"),
            native.NativeSequencerCore("doc"))


def assert_same_result(py_result, nat_result):
    assert (py_result.message is None) == (nat_result.message is None)
    assert (py_result.nack is None) == (nat_result.nack is None)
    if py_result.message is not None:
        pm, nm = py_result.message, nat_result.message
        assert pm.sequence_number == nm.sequence_number
        assert pm.minimum_sequence_number == nm.minimum_sequence_number
        assert pm.client_sequence_number == nm.client_sequence_number


def test_join_ticket_leave_parity():
    py, nat = make_pair()
    for cid in ("A", "B", "C"):
        pj = py.client_join(ClientDetail(cid))
        nj = nat.client_join(ClientDetail(cid))
        assert pj.sequence_number == nj.sequence_number
        assert pj.minimum_sequence_number == nj.minimum_sequence_number
    assert_same_result(py.ticket("A", op(1, 2)), nat.ticket("A", op(1, 2)))
    assert_same_result(py.ticket("B", op(1, 3)), nat.ticket("B", op(1, 3)))
    pl, nl = py.client_leave("C"), nat.client_leave("C")
    assert pl.sequence_number == nl.sequence_number
    assert py.minimum_sequence_number == nat.minimum_sequence_number
    assert set(py.clients) == set(nat.clients)


def test_nack_paths_parity():
    py, nat = make_pair()
    for s in (py, nat):
        s.client_join(ClientDetail("A"))
    # unknown client
    assert_same_result(py.ticket("X", op(1, 0)), nat.ticket("X", op(1, 0)))
    # csn gap
    assert_same_result(py.ticket("A", op(5, 1)), nat.ticket("A", op(5, 1)))
    # duplicate (dropped)
    for s in (py, nat):
        s.ticket("A", op(1, 1))
    assert_same_result(py.ticket("A", op(1, 1)), nat.ticket("A", op(1, 1)))
    # refSeq ahead
    assert_same_result(py.ticket("A", op(2, 99)), nat.ticket("A", op(2, 99)))


def test_fuzzed_stream_parity():
    """Long random stream with joins/leaves/valid/invalid ops: the
    sequenced (seq, msn) streams must match exactly."""
    rng = random.Random(42)
    py, nat = make_pair()
    csn = {}
    alive = []
    for step in range(3000):
        action = rng.random()
        if action < 0.05 or not alive:
            cid = f"c{rng.randrange(8)}"
            pj = py.client_join(ClientDetail(cid))
            nj = nat.client_join(ClientDetail(cid))
            assert pj.sequence_number == nj.sequence_number
            assert (pj.minimum_sequence_number
                    == nj.minimum_sequence_number)
            if cid not in alive:
                alive.append(cid)
                csn.setdefault(cid, 0)
        elif action < 0.08 and len(alive) > 1:
            cid = rng.choice(alive)
            alive.remove(cid)
            pl, nl = py.client_leave(cid), nat.client_leave(cid)
            assert pl.sequence_number == nl.sequence_number
        else:
            cid = rng.choice(alive)
            if rng.random() < 0.1:  # invalid op variants
                bad_csn = csn[cid] + rng.choice([0, 2, 5])
                refseq = rng.randrange(py.sequence_number + 3)
                o = op(bad_csn, refseq)
            else:
                csn[cid] += 1
                refseq = rng.randrange(
                    py.minimum_sequence_number,
                    py.sequence_number + 1,
                )
                o = op(csn[cid], refseq)
            pr, nr = py.ticket(cid, o), nat.ticket(cid, o)
            assert_same_result(pr, nr)
            if pr.nack is not None and "gap" in pr.nack.message:
                # both rejected; keep oracle csn consistent
                pass
            if pr.message is None and pr.nack is None:
                pass  # duplicate dropped in both
    assert py.sequence_number == nat.sequence_number
    assert py.minimum_sequence_number == nat.minimum_sequence_number


def test_checkpoint_restore_parity():
    py, nat = make_pair()
    for s in (py, nat):
        s.client_join(ClientDetail("A"))
        s.client_join(ClientDetail("B"))
        s.ticket("A", op(1, 1))
        s.ticket("B", op(1, 2))
    py2 = DocumentSequencer.restore(py.checkpoint())
    nat2 = native.NativeSequencerCore.restore(nat.checkpoint())
    assert_same_result(py2.ticket("A", op(2, 3)), nat2.ticket("A", op(2, 3)))
    assert py2.minimum_sequence_number == nat2.minimum_sequence_number


def test_batch_ticketing_matches_sequential():
    nat_seq = native.NativeSequencerCore("doc")
    nat_batch = native.NativeSequencerCore("doc")
    for s in (nat_seq, nat_batch):
        s.client_join(ClientDetail("A"))
        s.client_join(ClientDetail("B"))
    ops = [("A", op(1, 1)), ("B", op(1, 2)), ("A", op(2, 2)),
           ("B", op(5, 2)), ("A", op(3, 4))]
    sequential = [nat_seq.ticket(cid, o) for cid, o in ops]
    batched = nat_batch.ticket_batch(ops)
    for s, b in zip(sequential, batched):
        assert (s.message is None) == (b.message is None)
        if s.message:
            assert s.message.sequence_number == b.message.sequence_number
            assert (s.message.minimum_sequence_number
                    == b.message.minimum_sequence_number)


def test_batch_nack_seq_matches_sequential_oracle():
    py, nat = make_pair()
    for s in (py, nat):
        s.client_join(ClientDetail("A"))
        s.client_join(ClientDetail("B"))
    ops = [("A", op(5, 2)), ("B", op(1, 2)), ("B", op(2, 2))]
    seq_results = [py.ticket(cid, o) for cid, o in ops]
    batch_results = nat.ticket_batch(ops)
    for s, b in zip(seq_results, batch_results):
        if s.nack is not None:
            assert b.nack.sequence_number == s.nack.sequence_number


def test_native_summarize_flow(monkeypatch):
    """summaryAck system ops must sequence through the native core."""
    monkeypatch.setenv("FFTPU_NATIVE_SEQUENCER", "1")
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.service.local_server import LocalServer

    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice")
    m = a.runtime.create_datastore("d").create_channel("sharedmap", "m")
    a.flush()
    m.set("k", 1)
    a.flush()
    acks = []
    a.on("summaryAck", lambda ack: acks.append(ack))
    a.summarize()
    assert acks, "summary ack did not round-trip via native sequencer"
    late = Container.load(factory.create_document_service("doc"),
                          client_id="late")
    assert late.runtime.get_datastore("d").get_channel("m").get("k") == 1


def test_native_sequencer_serves_local_orderer(monkeypatch):
    monkeypatch.setenv("FFTPU_NATIVE_SEQUENCER", "1")
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.native import NativeSequencerCore
    from fluidframework_tpu.service.local_server import LocalServer

    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice")
    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    assert isinstance(
        server.get_orderer("doc").sequencer, NativeSequencerCore
    )
    sa = a.runtime.create_datastore("d").create_channel("sharedstring", "t")
    a.flush()
    sa.insert_text(0, "native")
    a.flush()
    sb = b.runtime.get_datastore("d").get_channel("t")
    sb.insert_text(6, " path")
    b.flush()
    assert sa.get_text() == sb.get_text() == "native path"


def test_native_throughput_exceeds_python():
    """The array lane (one FFI call, numeric in/out, zero per-op Python
    objects — what the TPU sidecar consumes) must beat the Python
    per-op loop by >=5x at realistic quorum sizes. The object-building
    ``ticket_batch`` wrapper can't win — SequencedMessage construction
    dominates both sides — so the service plane feeds tensors, not
    dataclasses (VERDICT r2 #7)."""
    import time

    import numpy as np

    n_clients, n = 200, 20000
    py = DocumentSequencer("doc")
    nat = native.NativeSequencerCore("doc")
    names = [f"c{i}" for i in range(n_clients)]
    for s in (py, nat):
        for cid in names:
            s.client_join(ClientDetail(cid))
    base = py.sequence_number
    ops = [
        (names[i % n_clients],
         op(i // n_clients + 1, base))
        for i in range(n)
    ]

    t0 = time.perf_counter()
    for cid, o in ops:
        py.ticket(cid, o)
    t_py = time.perf_counter() - t0

    cids = np.array([nat.intern_id(cid) for cid, _ in ops], np.int64)
    csns = np.array([o.client_sequence_number for _, o in ops],
                    np.int64)
    refs = np.array([o.reference_sequence_number for _, o in ops],
                    np.int64)
    t0 = time.perf_counter()
    out_seq, out_msn, out_status = nat.ticket_batch_arrays(
        cids, csns, refs
    )
    t_nat = time.perf_counter() - t0
    print(f"python={n / t_py:.0f} ops/s native={n / t_nat:.0f} ops/s "
          f"speedup={t_py / t_nat:.1f}x")
    assert (out_status == 0).all()
    assert py.sequence_number == nat.sequence_number
    assert py.minimum_sequence_number == nat.minimum_sequence_number
    assert int(out_seq[-1]) == py.sequence_number
    assert int(out_msn[-1]) == py.minimum_sequence_number
    assert t_nat * 5 < t_py, (
        f"array lane only {t_py / t_nat:.1f}x vs Python"
    )


def test_ticket_batch_arrays_matches_scalar_oracle():
    """Differential: the array lane's (seq, msn, status) stream equals
    the Python oracle's op-for-op, including nack/duplicate statuses."""
    import numpy as np

    rng = random.Random(7)
    py = DocumentSequencer("doc")
    nat = native.NativeSequencerCore("doc")
    names = [f"c{i}" for i in range(6)]
    for s in (py, nat):
        for cid in names:
            s.client_join(ClientDetail(cid))
    csn_state = {cid: 0 for cid in names}
    ops = []
    for _ in range(400):
        cid = rng.choice(names)
        if rng.random() < 0.1:
            csn = csn_state[cid] + rng.choice([0, 2])  # dup or gap
        else:
            csn_state[cid] += 1
            csn = csn_state[cid]
        refseq = py.sequence_number - rng.choice([0, 0, 1])
        ops.append((cid, op(csn, max(0, refseq))))
        # tick the oracle as we go so refseq choices stay plausible
        py.ticket(cid, ops[-1][1])

    # replay the identical stream through both implementations fresh
    py2 = DocumentSequencer("doc")
    for cid in names:
        py2.client_join(ClientDetail(cid))
    expected = []
    for cid, o in ops:
        res = py2.ticket(cid, o)
        if res.message is not None:
            expected.append(
                (0, res.message.sequence_number,
                 res.message.minimum_sequence_number)
            )
        elif res.nack is None:
            expected.append((2, -1, -1))
        else:
            expected.append((-1, -1, -1))

    cids = np.array([nat.intern_id(cid) for cid, _ in ops], np.int64)
    csns = np.array([o.client_sequence_number for _, o in ops],
                    np.int64)
    refs = np.array([o.reference_sequence_number for _, o in ops],
                    np.int64)
    out_seq, out_msn, out_status = nat.ticket_batch_arrays(
        cids, csns, refs
    )
    for i, (status, seq, msn) in enumerate(expected):
        if status == 0:
            assert out_status[i] == 0
            assert out_seq[i] == seq
            assert out_msn[i] == msn
        elif status == 2:
            assert out_status[i] == 2
        else:
            assert out_status[i] not in (0, 2)
