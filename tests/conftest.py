"""Test environment: force an 8-device virtual CPU mesh so every
multi-chip sharding path is exercised without TPU hardware.

Must run before the first `import jax` anywhere in the test session.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize registers the axon TPU plugin and pins
# JAX_PLATFORMS=axon before this file runs; the config update below is
# what actually wins.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavyweight multi-process tests"
    )
