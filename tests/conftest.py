"""Test environment: force an 8-device virtual CPU mesh so every
multi-chip sharding path is exercised without TPU hardware.

Must run before the first `import jax` anywhere in the test session.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize registers the axon TPU plugin and pins
# JAX_PLATFORMS=axon before this file runs; the config update below is
# what actually wins.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavyweight multi-process tests"
    )


import pytest  # noqa: E402

# fluidsan (testing/sanitizer.py): FFTPU_SANITIZE=1 instruments every
# threading.Lock/RLock created during the session with the lockset
# sanitizer. Installed at conftest import — BEFORE test modules
# import — so locks created at test-module import time are wrapped
# too. jitsan (testing/jitsan.py) rides the same guard: it baselines
# the kernel jit caches and arms the donation read-traps. detsan
# (testing/detsan.py) rides it too: patched time/random entry points
# trip on un-routed clock reads / unseeded RNG draws inside
# deterministic-plane components. wiresan (testing/wiresan.py)
# completes the set: the patched pack/dispatch wire seams trip on any
# registered frame type carrying a field absent from the WIRE_SCHEMA
# registry. failsan (testing/failsan.py) is the fifth: it hooks the
# chaos plane's arm/disarm and trips when an injected fault maps to
# no observable signal (fault-to-signal accounting,
# docs/ROBUSTNESS.md). The autouse guard below fails any test that
# trips any of the five.
_SANITIZE = os.environ.get("FFTPU_SANITIZE") == "1"
if _SANITIZE:
    from fluidframework_tpu.testing import detsan as _detsan
    from fluidframework_tpu.testing import failsan as _failsan
    from fluidframework_tpu.testing import jitsan as _jitsan
    from fluidframework_tpu.testing import sanitizer as _fluidsan
    from fluidframework_tpu.testing import wiresan as _wiresan

    _fluidsan.install()
    _jitsan.install()
    _detsan.install()
    _wiresan.install()
    _failsan.install()


@pytest.fixture(autouse=True)
def _fluidsan_trip_guard():
    if not _SANITIZE:
        yield
        return
    from fluidframework_tpu.testing import (
        detsan, failsan, jitsan, sanitizer, wiresan,
    )

    before = len(sanitizer.trips())
    before_jit = len(jitsan.trips())
    before_det = len(detsan.trips())
    before_wire = len(wiresan.trips())
    before_fail = len(failsan.trips())
    yield
    fresh = sanitizer.trips()[before:]
    if fresh:
        pytest.fail(
            "fluidsan tripped during this test:\n"
            + "\n".join(t.describe() for t in fresh)
            + "\n" + fresh[0].flight_dump
        )
    fresh_jit = jitsan.trips()[before_jit:]
    if fresh_jit:
        pytest.fail(
            "jitsan tripped during this test:\n"
            + "\n".join(t.describe() for t in fresh_jit)
        )
    fresh_det = detsan.trips()[before_det:]
    if fresh_det:
        pytest.fail(
            "detsan tripped during this test:\n"
            + "\n".join(t.describe() for t in fresh_det)
            + "\n" + fresh_det[0].flight_dump
        )
    fresh_wire = wiresan.trips()[before_wire:]
    if fresh_wire:
        pytest.fail(
            "wiresan tripped during this test:\n"
            + "\n".join(t.describe() for t in fresh_wire)
        )
    # trips() evaluates any window closed during this test — the
    # chaos harnesses disarm before quiesce, so teardown is the first
    # point where every recovery signal has landed
    fresh_fail = failsan.trips()[before_fail:]
    if fresh_fail:
        pytest.fail(
            "failsan tripped during this test:\n"
            + "\n".join(t.describe() for t in fresh_fail)
        )


@pytest.fixture()
def mesh_cpu_subprocess():
    """Run a python snippet in a subprocess pinned to a 4-device
    virtual CPU mesh (JAX_PLATFORMS=cpu +
    XLA_FLAGS=--xla_force_host_platform_device_count=4) — the
    mesh-pool suite's multi-shard paths execute on CPU-only CI
    regardless of how the PARENT session configured its devices
    (bench config10 emulates shards the same way). The env is
    subprocess-scoped: nothing leaks into this process, whose jax is
    already initialized."""
    import subprocess
    import sys

    def run(code: str, timeout: float = 300.0) -> str:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        # the child asserts its own invariants; the session sanitizer
        # belongs to THIS process's conftest guard, not the child
        env.pop("FFTPU_SANITIZE", None)
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            env=env)
        assert proc.returncode == 0, (
            f"mesh subprocess failed rc={proc.returncode}:\n"
            f"{proc.stderr[-2000:]}"
        )
        return proc.stdout

    return run


@pytest.fixture()
def alfred(monkeypatch):
    """AlfredServer on a background event loop — ONE definition for
    every wire-level test file. ``start(tenants=..., 
    server_versions=...)`` returns the running server; teardown stops
    it and joins the thread."""
    import asyncio
    import threading

    state = {}

    def start(tenants=None, server_versions=None, qos=None,
              slo=None):
        from fluidframework_tpu.service import ingress as ingress_mod
        from fluidframework_tpu.service.ingress import AlfredServer

        if server_versions is not None:
            monkeypatch.setattr(
                ingress_mod, "WIRE_VERSIONS", tuple(server_versions))
        server = AlfredServer(tenants=tenants, qos=qos, slo=slo)
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            loop.run_until_complete(server.start())
            started.set()
            loop.run_forever()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert started.wait(10)
        state.update(server=server, loop=loop, thread=t)
        return server

    yield start
    if state:
        import asyncio

        fut = asyncio.run_coroutine_threadsafe(
            state["server"].stop(), state["loop"])
        try:
            fut.result(timeout=10)
        except Exception:
            pass
        state["loop"].call_soon_threadsafe(state["loop"].stop)
        state["thread"].join(timeout=10)
