"""URL resolvers + debugger driver (the §2.6 aux-drivers row).

Mirrors packages/drivers/routerlicious-urlResolver (urlResolver.ts:25),
local-driver/localResolver.ts:32, and debugger/
fluidDebuggerController.ts:34.
"""
import asyncio
import threading
import time

import pytest

from fluidframework_tpu.drivers import (
    DebugDocumentService,
    LocalDocumentServiceFactory,
    LocalUrlResolver,
    SocketUrlResolver,
    load_container_from_url,
    resolve_request,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.ingress import AlfredServer
from fluidframework_tpu.service.local_server import LocalServer




def test_socket_resolver_parses_fftpu_urls():
    r = SocketUrlResolver()
    res = r.resolve("fftpu://10.0.0.5:7071/acme/doc%201")
    assert res.tenant_id == "acme"
    assert res.document_id == "doc 1"
    assert res.endpoints["ordering"] == {
        "host": "10.0.0.5", "port": 7071}
    assert res.url == "fftpu://10.0.0.5:7071/acme/doc%201"
    assert r.get_absolute_url(res, "/dds/map1") == \
        "fftpu://10.0.0.5:7071/acme/doc%201/dds/map1"


def test_socket_resolver_tenantless_and_http_localhost():
    r = SocketUrlResolver()
    res = r.resolve("fftpu://127.0.0.1:7070/solo-doc")
    assert res.tenant_id is None and res.document_id == "solo-doc"
    res2 = r.resolve("http://localhost:7070/t/d")
    assert (res2.tenant_id, res2.document_id) == ("t", "d")
    # foreign hosts are not ours (resolver chains)
    assert r.resolve("http://example.com/t/d") is None
    assert r.resolve("odsp://whatever") is None


def test_resolver_chain_and_token_provider():
    minted = []

    def mint(tenant, doc):
        minted.append((tenant, doc))
        return f"jwt-{tenant}-{doc}"

    local = LocalUrlResolver(LocalServer())
    sock = SocketUrlResolver(token_provider=mint)
    res = resolve_request([local, sock],
                          "fftpu://127.0.0.1:7070/acme/d")
    assert res.tokens["jwt"] == "jwt-acme-d"
    assert minted == [("acme", "d")]
    res2 = resolve_request([local, sock], "fftpu-local:///dev-doc")
    assert "local_server" in res2.endpoints
    with pytest.raises(ValueError, match="no resolver"):
        resolve_request([local, sock], "odsp://foo/bar")


def test_load_container_via_local_resolver():
    server = LocalServer()
    resolvers = [LocalUrlResolver(server)]
    c, svc = load_container_from_url(
        resolvers, "fftpu-local:///resolved-doc", client_id="alice")
    t = c.runtime.create_datastore("ds").create_channel(
        "sharedstring", "t")
    t.insert_text(0, "via resolver")
    c.flush()
    c2, _ = load_container_from_url(
        resolvers, "fftpu-local:///resolved-doc", client_id="bob")
    t2 = c2.runtime.get_datastore("ds").get_channel("t")
    assert t2.get_text() == "via resolver"
    c.close()
    c2.close()


def test_load_container_via_socket_resolver(alfred):
    server = alfred()
    url = f"fftpu://127.0.0.1:{server.port}/wire-doc"
    c, svc = load_container_from_url(
        [SocketUrlResolver()], url, client_id="alice")
    try:
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "over tcp")
            c.flush()
        deadline = time.time() + 10
        while time.time() < deadline:
            with svc.lock:
                if c.runtime.pending.count == 0:
                    break
            time.sleep(0.02)
        with svc.lock:
            assert t.get_text() == "over tcp"
            c.close()
    finally:
        svc.close()


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_debug_driver_steps_through_live_stream():
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    # writer fills the doc live
    w = Container.load(factory.create_document_service("dbg"),
                       client_id="writer")
    tw = w.runtime.create_datastore("ds").create_channel(
        "sharedstring", "t")
    w.flush()
    # debugger-wrapped reader joins paused
    dbg = DebugDocumentService(
        factory.create_document_service("dbg"), start_paused=True)
    r = Container.load(dbg, client_id="reader")
    tr = r.runtime.get_datastore("ds").get_channel("t")
    for ch in "abcde":
        tw.insert_text(tw.get_length(), ch)
        w.flush()
    assert dbg.pending_count >= 5  # gated, nothing delivered
    assert tr.get_text() == ""
    n = dbg.step(3)  # releases 3 MESSAGES (joins/attach ops count)
    assert n == 3
    mid = tr.get_text()
    assert mid != tw.get_text()  # still behind the writer
    assert tw.get_text().startswith(mid)  # replayed a true prefix
    # play_to a specific sequence number
    dbg.play_to(dbg.delivered_seq + 1)
    assert tw.get_text().startswith(tr.get_text())
    # breakpoint far ahead doesn't block resume (set via the locked
    # setter — raw break_at writes race the network thread's drain)
    dbg.set_breakpoint(10 ** 9)
    dbg.resume_live()
    assert _wait(lambda: tr.get_text() == tw.get_text())
    # live now: new writer ops flow straight through
    tw.insert_text(0, "z")
    w.flush()
    assert _wait(lambda: tr.get_text() == tw.get_text())
    w.close()
    r.close()
