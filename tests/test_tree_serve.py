"""Tree serving plane: service-level differential parity across BOTH
tree executor routes (atom / macro), the test_sidecar_routes pattern
instantiated for the second kernelized DDS.

Two sidecars on the same sequenced stream — one per route — must
serve identical ``signature()`` through every policy transition:
steady windows, the 2x regrow ladder, overflow PARKING within one
window (both routes park conservatively at the shared predicate; the
snapshot re-apply at doubled capacity must erase any difference),
host eviction (capacity, ring-straggler, device-inexpressible), the
pooled tier, and the ChannelKindRouter ingress boundary.

The centerpiece is the THREE-WRITER concurrent fuzz: moves racing
removes (and annotates racing both) across three blind writers,
flushed in shuffled order, must converge bit-identical across both
device routes AND against the scalar SharedTree/EditManager oracle —
through the real LocalServer -> Container -> sidecar dispatch loop,
not a synthetic commit feed.
"""
import json
import random

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.models.tree import changeset as cs
from fluidframework_tpu.models.tree import node
from fluidframework_tpu.protocol.messages import (
    MessageType,
    SequencedMessage,
)
from fluidframework_tpu.service import (
    LocalServer,
    TpuMergeSidecar,
    TreeSidecar,
)
from fluidframework_tpu.service.tree_sidecar import ChannelKindRouter
from fluidframework_tpu.testing.tree_fuzz import random_change_with_moves
from test_merge_chunk import smoke_seeds

ROUTES = ("atom", "macro")


def _pair(**kw):
    """One tree sidecar per route, identical otherwise."""
    return {r: TreeSidecar(executor=r, **kw) for r in ROUTES}


def _open_doc(server, sidecars, doc, client_id=None):
    factory = LocalDocumentServiceFactory(server)
    for sc in sidecars.values():
        sc.subscribe(server, doc, "d", "t")
    c = Container.load(factory.create_document_service(doc),
                       client_id=client_id or f"{doc}-w")
    t = c.runtime.create_datastore("d").create_channel(
        "sharedtree", "t")
    return c, t


def _join(server, doc, client_id):
    """Second/third writer on an already-created document."""
    factory = LocalDocumentServiceFactory(server)
    c = Container.load(factory.create_document_service(doc),
                       client_id=client_id)
    t = c.runtime.get_datastore("d").get_channel("t")
    return c, t


def _sig_of(tree):
    """The scalar oracle in the sidecar's signature convention."""
    return json.dumps({"root": tree.root().get("root", [])},
                      sort_keys=True, default=str)


def _assert_parity(sidecars, docs, oracle=None):
    atom = sidecars["atom"]
    for doc in docs:
        sig = atom.signature(doc, "d", "t")
        for route in ROUTES[1:]:
            assert sig == sidecars[route].signature(doc, "d", "t"), (
                f"signature route divergence ({route}) on {doc}")
        if oracle is not None and doc in oracle:
            assert sig == _sig_of(oracle[doc]), (
                f"both routes diverged from the oracle on {doc}")


def mk_nodes(n, base=0):
    return [node("n", value=base + i) for i in range(n)]


# ======================================================================
# the tentpole differential: three blind writers, moves racing removes


@pytest.mark.parametrize("seed", smoke_seeds(10, {0, 4, 7}))
def test_three_writer_concurrent_move_fuzz(seed):
    """Three writers author concurrently (moves, removes, inserts and
    annotates all racing), flush in shuffled order, for several
    rounds. All scalar replicas converge (EditManager), and both
    device routes serve that exact state through the real dispatch
    loop."""
    rng = random.Random(seed * 101 + 13)
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=64, max_capacity=1024)
    c1, t1 = _open_doc(server, sidecars, "doc", client_id="alice")
    t1.insert_nodes(("root",), 0, mk_nodes(6))
    c1.flush()
    c2, t2 = _join(server, "doc", "bob")
    c3, t3 = _join(server, "doc", "carol")
    writers = [(c1, t1, "A"), (c2, t2, "B"), (c3, t3, "C")]

    for rnd in range(5):
        # author concurrently: every writer edits its CURRENT view
        # before anyone flushes
        for _c, t, uid in writers:
            base_nodes = t.get_field(("root",))
            t.apply_changeset(random_change_with_moves(
                rng, base_nodes, f"{uid}{rnd}"))
        order = list(writers)
        rng.shuffle(order)
        for c, _t, _uid in order:
            c.flush()
        if rng.random() < 0.5:
            for sc in sidecars.values():
                sc.apply()

    # scalar convergence first (the oracle is meaningful) ...
    sig1 = _sig_of(t1)
    assert sig1 == _sig_of(t2) == _sig_of(t3), "scalar replicas split"
    # ... then both device routes serve exactly that state
    for sc in sidecars.values():
        sc.apply()
        sc.sync()
    _assert_parity(sidecars, ["doc"], {"doc": t1})
    for route in ROUTES:
        assert not sidecars[route].overflowed(), route


# ======================================================================
# policy transitions, the test_sidecar_routes ladder


@pytest.mark.slow
def test_routes_agree_on_steady_multidoc_traffic():
    rng = random.Random(11)
    server = LocalServer()
    sidecars = _pair(max_docs=8, capacity=256)
    docs = [f"doc-{i}" for i in range(4)]
    trees, containers = {}, {}
    for doc in docs:
        c, t = _open_doc(server, sidecars, doc)
        t.insert_nodes(("root",), 0, mk_nodes(4))
        c.flush()
        containers[doc], trees[doc] = c, t
    for i in range(40):
        doc = rng.choice(docs)
        t = trees[doc]
        n = len(t.get_field(("root",)))
        roll = rng.random()
        if n > 2 and roll < 0.25:
            start = rng.randint(0, n - 2)
            t.delete_nodes(("root",), start,
                           rng.randint(1, n - start))
        elif n >= 2 and roll < 0.5:
            src = rng.randint(0, n - 2)
            t.move_nodes(("root",), src, 1,
                         rng.choice([0, n]))
        elif n > 0 and roll < 0.7:
            t.set_value(("root",), rng.randint(0, n - 1),
                        rng.randint(100, 199))
        else:
            t.insert_nodes(("root",), rng.randint(0, n),
                           mk_nodes(rng.randint(1, 2), 500))
        containers[doc].flush()
        if rng.random() < 0.3:
            for sc in sidecars.values():
                sc.apply()
    for sc in sidecars.values():
        sc.apply()
    _assert_parity(sidecars, docs, trees)
    for route in ROUTES:
        assert not sidecars[route].overflowed(), route


@pytest.mark.slow
def test_routes_agree_through_grow_ladder():
    """Windows big enough to overflow a 16-slot slab force the regrow
    path: both routes PARK the doc at the shared predicate and the
    snapshot re-apply at doubled capacity must reconverge them."""
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=16, max_capacity=512)
    c, t = _open_doc(server, sidecars, "doc")
    for i in range(30):
        t.insert_nodes(("root",), 0, mk_nodes(4, i * 10))
        c.flush()
        if i % 4 == 3 and len(t.get_field(("root",))) > 6:
            t.delete_nodes(("root",), 2, 5)
            c.flush()
    for sc in sidecars.values():
        sc.apply()
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].grow_count >= 1, route
        assert sidecars[route].host_mode_docs() == 0, route
    _assert_parity(sidecars, ["doc"], {"doc": t})


def test_routes_agree_on_overflow_parking_within_one_window():
    """ONE window whose attaches keep coming past the capacity point:
    the kernel parks the doc (state, ring and overflow all predate
    the window — the park contract) and the sidecar re-applies the
    whole window from the snapshot at the doubled capacity. The blind
    burst stays UNDER the trunk ring depth (a deeper one is a ring
    eviction by design — see the straggler test)."""
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=16, max_capacity=256)
    c, t = _open_doc(server, sidecars, "doc")
    for i in range(7):
        t.insert_nodes(("root",), 0, mk_nodes(4, i * 10))
    c.flush()
    for sc in sidecars.values():
        sc.apply()   # one dispatch: overflow mid-window on both
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].grow_count >= 1, route
        assert not sidecars[route].overflowed(), route
    _assert_parity(sidecars, ["doc"], {"doc": t})


def test_routes_agree_through_eviction_and_recovery():
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=16, max_capacity=16)
    c, t = _open_doc(server, sidecars, "big")
    c2, t2 = _open_doc(server, sidecars, "small")
    for i in range(20):
        t.insert_nodes(("root",), 0, mk_nodes(2, i * 10))
        c.flush()
    t2.insert_nodes(("root",), 0, mk_nodes(3))
    c2.flush()
    for sc in sidecars.values():
        sc.apply()
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].host_mode_docs() == 1, route
    # post-eviction traffic keeps flowing on both routes (host
    # replica ingest path), small doc stays on device
    t.move_nodes(("root",), 0, 1, 4)
    t2.set_value(("root",), 0, 42)
    c.flush()
    c2.flush()
    for sc in sidecars.values():
        sc.apply()
    _assert_parity(sidecars, ["big", "small"],
                   {"big": t, "small": t2})


def test_ring_straggler_evicts_to_host():
    """A commit whose ref predates the device trunk ring takes the
    host path by design: the ring holds the last TRUNK_RING rebased
    trunk commits, so a straggler needing more is evicted BEFORE its
    encode (both routes, same trigger, same served state).
    Local containers capture refs at flush, so the straggler arrives
    as a synthetic sequenced message through the real ingest path."""
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=256)
    c, t = _open_doc(server, sidecars, "doc", client_id="w")
    t.insert_nodes(("root",), 0, mk_nodes(4))
    c.flush()
    for i in range(20):
        t.set_value(("root",), 0, i)
        c.flush()
    last = max(sc._last_ingested["doc"] for sc in sidecars.values())
    change = cs.stamp({"root": [cs.skip(1), cs.mod(value={
        "new": 999, "old": None})]}, "straggler")
    for sc in sidecars.values():
        sc.ingest("doc", SequencedMessage(
            client_id="straggler", sequence_number=last + 1,
            minimum_sequence_number=0, client_sequence_number=1,
            reference_sequence_number=1,
            type=MessageType.OPERATION,
            contents={"kind": "op", "address": "d", "channel": "t",
                      "contents": {"type": "tree",
                                   "changes": change}},
        ))
        sc.apply()
        sc.sync()
    sig = sidecars["atom"].signature("doc", "d", "t")
    for route in ROUTES:
        assert sidecars[route].ring_evict_count == 1, route
        assert sidecars[route].host_mode_docs() == 1, route
        assert sidecars[route].signature("doc", "d", "t") == sig, route
    assert '"value": 999' in sig  # the straggler's edit was served


def test_inexpressible_changeset_evicts_to_host():
    """A changeset touching a non-root field is device-inexpressible
    (the slab holds the root sequence only): the full-fidelity host
    replica takes over, and reads keep serving the ROOT field
    identically on both routes and vs the scalar oracle."""
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=64)
    c, t = _open_doc(server, sidecars, "doc")
    t.insert_nodes(("root",), 0, mk_nodes(3))
    c.flush()
    t.apply_changeset(cs.stamp(
        {"side": [cs.ins(mk_nodes(2, 900))]}, "u-side"))
    c.flush()
    t.set_value(("root",), 0, 42)  # post-eviction traffic
    c.flush()
    for sc in sidecars.values():
        sc.apply()
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].evict_count == 1, route
        assert sidecars[route].host_mode_docs() == 1, route
    _assert_parity(sidecars, ["doc"], {"doc": t})


def test_routes_agree_with_pool_tier():
    """Grow ladder -> pooled-tier admission -> continued pooled
    collaboration on both routes (the pool's capacity unlock is a
    bigger chip-local slab; single-device mesh for select_pool API
    parity)."""
    import jax

    from fluidframework_tpu.parallel import make_seq_mesh

    mesh = make_seq_mesh(jax.devices()[:1])
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=16, max_capacity=32,
                     pool_mesh=mesh, pool_capacity=256)
    c, t = _open_doc(server, sidecars, "big")
    for i in range(25):
        t.insert_nodes(("root",), 0, mk_nodes(2, i * 10))
        c.flush()
    for sc in sidecars.values():
        sc.apply()
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].pooled_docs() == 1, route
        assert sidecars[route].host_mode_docs() == 0, route
    # pooled docs keep collaborating through the pool dispatch path
    for i in range(3):
        t.move_nodes(("root",), 0, 1, 5)
        c.flush()
    for sc in sidecars.values():
        sc.apply()
    _assert_parity(sidecars, ["big"], {"big": t})
    for route in ROUTES:
        assert sidecars[route]._pool.dispatch_count >= 1, route


def test_duplicate_delivery_dropped():
    """At-least-once upstream: re-ingesting an already-sequenced
    message must not extend the canonical histories (the merge
    sidecar's dedupe discipline)."""
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=64)
    c, t = _open_doc(server, sidecars, "doc")
    t.insert_nodes(("root",), 0, mk_nodes(3))
    c.flush()
    replay = SequencedMessage(
        client_id="doc-w", sequence_number=1,
        minimum_sequence_number=0, client_sequence_number=1,
        reference_sequence_number=0, type=MessageType.OPERATION,
        contents={"kind": "op", "address": "d", "channel": "t",
                  "contents": {"type": "tree", "changes": cs.stamp(
                      {"root": [cs.ins(mk_nodes(1))]}, "dup")}},
    )
    for sc in sidecars.values():
        slot = sc._slot("doc", "d", "t")
        depth = len(sc._raw[slot])
        assert depth >= 1
        sc.ingest("doc", replay)
        assert len(sc._raw[slot]) == depth, (
            "duplicate extended history")
        sc.apply()
    _assert_parity(sidecars, ["doc"], {"doc": t})


# ======================================================================
# ingress routing + pool selection


def test_channel_kind_router_routes_by_channel_type():
    """One document carrying BOTH channel kinds: the router feeds the
    string channel to the merge sidecar and the tree channel to the
    tree sidecar off the attach op's channelType — neither plane's
    state traverses the other's code."""
    server = LocalServer()
    merge_sc = TpuMergeSidecar(max_docs=4, capacity=64)
    tree_sc = TreeSidecar(max_docs=4, capacity=64)
    router = ChannelKindRouter(merge=merge_sc, tree=tree_sc)
    router.subscribe(server, "doc")
    factory = LocalDocumentServiceFactory(server)
    c = Container.load(factory.create_document_service("doc"),
                       client_id="w")
    ds = c.runtime.create_datastore("d")
    s = ds.create_channel("sharedstring", "s")
    t = ds.create_channel("sharedtree", "t")
    s.insert_text(0, "hello")
    t.insert_nodes(("root",), 0, mk_nodes(2))
    c.flush()
    s.insert_text(5, "!")
    t.move_nodes(("root",), 0, 1, 2)
    c.flush()
    merge_sc.apply()
    merge_sc.sync()
    tree_sc.apply()
    tree_sc.sync()
    assert merge_sc.text("doc", "d", "s") == s.get_text()
    assert tree_sc.signature("doc", "d", "t") == _sig_of(t)
    # cross-plane isolation: the tree sidecar never tracked the
    # string channel, the merge sidecar never tracked the tree one
    assert ("doc", "d", "s") not in tree_sc._slots
    assert ("doc", "d", "t") not in merge_sc._slots


def test_select_pool_tree_plane():
    import jax

    from fluidframework_tpu.parallel import make_seq_mesh
    from fluidframework_tpu.service.tpu_sidecar import select_pool
    from fluidframework_tpu.service.tree_sidecar import TreeSeqPool

    mesh = make_seq_mesh(jax.devices()[:1])
    pool = select_pool(mesh, None, executor="atom",
                       max_capacity=64, plane="tree")
    assert isinstance(pool, TreeSeqPool)
    assert pool.capacity == 256  # min(max_capacity * 4, 16384)
    with pytest.raises(ValueError, match="plane"):
        select_pool(mesh, None, plane="bogus")
    with pytest.raises(ValueError, match="executor"):
        select_pool(mesh, None, executor="scan", plane="tree")
