"""odsp-class driver: snapshot caching (fresh hit / refresh / stale
offline fallback, on-disk persistence) and socket multiplexing (many
documents, one TCP connection).
"""
import asyncio
import threading
import time

import pytest

from fluidframework_tpu.drivers.caching_driver import (
    CachingDocumentService,
    CachingMultiplexFactory,
    FileSnapshotCache,
    MultiplexedSocketClient,
    SnapshotCache,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.ingress import AlfredServer


@pytest.fixture()
def server():
    srv = AlfredServer()
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def _run():
        await srv.start()
        started.set()
        try:
            await srv.serve_forever()
        except asyncio.CancelledError:
            pass

    holder = {}

    def runner():
        task = loop.create_task(_run())
        holder["task"] = task
        try:
            loop.run_until_complete(task)
        except Exception:
            pass

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    assert started.wait(10)
    yield srv
    loop.call_soon_threadsafe(holder["task"].cancel)
    thread.join(timeout=10)
    loop.call_soon_threadsafe(loop.stop)


# ---- snapshot cache ---------------------------------------------------

class _FakeService:
    document_id = "doc"

    def __init__(self):
        self.calls = 0
        self.fail = False
        self.summary = (7, {"tree": "v1"})

    def get_latest_summary(self):
        if self.fail:
            raise ConnectionError("offline")
        self.calls += 1
        return self.summary


def test_cache_fresh_hit_skips_network():
    inner = _FakeService()
    svc = CachingDocumentService(inner, SnapshotCache(), max_age_s=60)
    assert svc.get_latest_summary() == (7, {"tree": "v1"})
    assert svc.last_load_source == "network"
    assert svc.get_latest_summary() == (7, {"tree": "v1"})
    assert svc.last_load_source == "cache"
    assert inner.calls == 1


def test_cache_age_policy_refreshes():
    inner = _FakeService()
    svc = CachingDocumentService(inner, SnapshotCache(), max_age_s=0.0)
    svc.get_latest_summary()
    inner.summary = (9, {"tree": "v2"})
    time.sleep(0.01)
    assert svc.get_latest_summary() == (9, {"tree": "v2"})
    assert svc.last_load_source == "network"
    assert inner.calls == 2


def test_stale_cache_serves_offline_load():
    inner = _FakeService()
    svc = CachingDocumentService(inner, SnapshotCache(), max_age_s=0.0)
    svc.get_latest_summary()
    inner.fail = True
    time.sleep(0.01)
    assert svc.get_latest_summary() == (7, {"tree": "v1"})
    assert svc.last_load_source == "stale-cache"


def test_offline_without_cache_raises():
    inner = _FakeService()
    inner.fail = True
    svc = CachingDocumentService(inner, SnapshotCache())
    with pytest.raises(ConnectionError):
        svc.get_latest_summary()


def test_file_cache_survives_restart(tmp_path):
    c1 = FileSnapshotCache(str(tmp_path))
    c1.put("doc", 5, {"blob": [1, 2, 3]})
    c2 = FileSnapshotCache(str(tmp_path))
    entry = c2.get("doc")
    assert entry["sequence_number"] == 5
    assert entry["summary"] == {"blob": [1, 2, 3]}


def test_file_cache_hostile_document_id_stays_in_root(tmp_path):
    # ids with path separators / '..' must hash to a filename inside
    # the cache root and still reload after restart
    import os
    root = tmp_path / "cache"
    evil = "../../escape/../doc/with/slashes"
    c1 = FileSnapshotCache(str(root))
    c1.put(evil, 7, {"v": 1})
    # nothing written outside the cache root
    names = os.listdir(root)
    assert len(names) == 1 and names[0].endswith(".json")
    assert not (tmp_path / "escape").exists()
    c2 = FileSnapshotCache(str(root))
    entry = c2.get(evil)
    assert entry is not None and entry["sequence_number"] == 7


# ---- multiplexing -----------------------------------------------------

def test_two_documents_one_socket(server):
    factory = CachingMultiplexFactory("127.0.0.1", server.port)
    sa = factory.create_document_service("doc-x")
    sb = factory.create_document_service("doc-y")
    # both facades share one physical client
    assert factory._client is not None
    client = factory._client

    with sa.lock:
        a = Container.load(sa, client_id="alice")
        ta = (a.runtime.create_datastore("d")
              .create_channel("sharedstring", "t"))
        a.flush()
        ta.insert_text(0, "doc-x-text")
        a.flush()
    with sb.lock:
        b = Container.load(sb, client_id="bob")
        tb = (b.runtime.create_datastore("d")
              .create_channel("sharedstring", "t"))
        b.flush()
        tb.insert_text(0, "doc-y-text")
        b.flush()

    deadline = time.time() + 5
    while time.time() < deadline:
        with client.lock:
            if ta.get_text() == "doc-x-text" \
                    and tb.get_text() == "doc-y-text":
                break
        time.sleep(0.05)
    with client.lock:
        # no cross-document bleed through the shared socket
        assert ta.get_text() == "doc-x-text"
        assert tb.get_text() == "doc-y-text"
    a.close()
    b.close()
    factory.close()


def test_multiplexed_second_client_catches_up(server):
    factory = CachingMultiplexFactory("127.0.0.1", server.port,
                                      max_age_s=0.0)
    s1 = factory.create_document_service("doc-m")
    with s1.lock:
        c1 = Container.load(s1, client_id="alice")
        t1 = (c1.runtime.create_datastore("d")
              .create_channel("sharedstring", "t"))
        c1.flush()
        t1.insert_text(0, "shared state")
        c1.flush()

    # a second process-worth of client over ITS OWN factory/socket
    factory2 = CachingMultiplexFactory("127.0.0.1", server.port,
                                       max_age_s=0.0)
    s2 = factory2.create_document_service("doc-m")
    with s2.lock:
        c2 = Container.load(s2, client_id="bob")
        t2 = c2.runtime.get_datastore("d").get_channel("t")
        assert t2.get_text() == "shared state"
    c1.close()
    c2.close()
    factory.close()
    factory2.close()


def test_auth_rejection_is_not_served_from_stale_cache():
    """Regression: a PermissionError from the storage plane must NOT
    fall back to the cached snapshot (PermissionError subclasses
    OSError, which the offline clause catches)."""
    inner = _FakeService()
    svc = CachingDocumentService(inner, SnapshotCache(), max_age_s=0.0)
    svc.get_latest_summary()  # populate cache
    time.sleep(0.01)

    def revoked():
        raise PermissionError("token expired")

    inner.get_latest_summary = revoked
    with pytest.raises(PermissionError):
        svc.get_latest_summary()
