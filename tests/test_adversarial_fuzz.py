"""Adversarial convergence scale-up (VERDICT r2 #9 / weak #7):

1. 100+ seeded differential-fuzz runs of the batched kernel against
   the scalar oracle at larger scale (8 clients, 200+ steps,
   overlap-remove / annotate / marker storms, deep concurrency so msn
   boundary crossings happen constantly).
2. Directed regression scenarios transcribed from the behaviors the
   reference's merge-tree suites pin (packages/dds/merge-tree/src/
   test: tie-break insert storms, overlapping removes, annotate over
   concurrent remove, zamboni-boundary edits) — hand-written, not
   ported code.

Marked to run in CI; seeds are deterministic so failures repro.
"""
import pytest

from fluidframework_tpu.ops import (
    build_batch,
    encode_stream,
    extract_signature,
    extract_text,
    fetch,
    make_table,
)
from fluidframework_tpu.ops.merge_kernel import apply_window
from fluidframework_tpu.testing import (
    FuzzConfig,
    MockCollabSession,
    record_op_stream,
)
from fluidframework_tpu.models.mergetree import MergeTreeClient
from fluidframework_tpu.protocol.messages import MessageType


def run_kernel(streams, capacity=1024):
    encs = [encode_stream(s) for s in streams]
    batch = build_batch(encs)
    table = apply_window(make_table(len(encs), capacity), batch)
    return encs, fetch(table)


def oracle_replay(stream):
    obs = MergeTreeClient("oracle")
    obs.start_collaboration("oracle")
    for msg in stream:
        if msg.type == MessageType.OPERATION:
            obs.apply_msg(msg)
    return obs


def oracle_signature(obs, enc):
    from fluidframework_tpu.ops.host_bridge import interned_signature

    return interned_signature(obs, enc)


def check_stream(stream):
    encs, np_table = run_kernel([stream])
    obs = oracle_replay(stream)
    assert extract_text(np_table, encs[0], 0) == obs.get_text()
    assert extract_signature(np_table, encs[0], 0) == \
        oracle_signature(obs, encs[0])


# ----------------------------------------------------------------------
# 1. scale-up fuzz: 120 seeds across four adversarial mixes


def _smoke(n, keep):
    """range(n) with every seed outside ``keep`` slow-marked — tier-1
    runs a smoke subset of the sweep, the full sweep is slow-lane."""
    return [
        s if s in keep else pytest.param(s, marks=pytest.mark.slow)
        for s in range(n)
    ]

@pytest.mark.parametrize("seed", _smoke(40, {0, 1, 2, 3, 4}))
def test_fuzz_eight_clients_deep_concurrency(seed):
    _, stream = record_op_stream(FuzzConfig(
        n_clients=8, n_steps=220, seed=10_000 + seed * 13,
        insert_weight=0.45, remove_weight=0.3, annotate_weight=0.1,
        process_weight=0.15,
    ))
    check_stream(stream)


@pytest.mark.parametrize("seed", _smoke(30, {0, 1, 2, 3, 4}))
def test_fuzz_overlap_remove_storm(seed):
    """Remove-heavy with rare processing: most removes overlap
    concurrently (the overlapRemove bookkeeping,
    partialLengths.ts:125-135)."""
    _, stream = record_op_stream(FuzzConfig(
        n_clients=6, n_steps=200, seed=20_000 + seed * 7,
        insert_weight=0.3, remove_weight=0.55, annotate_weight=0.05,
        process_weight=0.1,
    ))
    check_stream(stream)


@pytest.mark.parametrize("seed", _smoke(30, {0, 1, 2, 3, 4}))
def test_fuzz_annotate_storm_with_insert_props(seed):
    _, stream = record_op_stream(FuzzConfig(
        n_clients=5, n_steps=200, seed=30_000 + seed * 11,
        insert_weight=0.35, remove_weight=0.15, annotate_weight=0.35,
        process_weight=0.15, insert_props_weight=0.5,
    ))
    check_stream(stream)


@pytest.mark.parametrize("seed", _smoke(20, {0, 1, 2, 3, 4}))
def test_fuzz_msn_boundary_churn(seed):
    """Heavy processing keeps the msn advancing through the op storm,
    so zamboni-eligible tombstones cross the window constantly."""
    _, stream = record_op_stream(FuzzConfig(
        n_clients=4, n_steps=250, seed=40_000 + seed * 3,
        insert_weight=0.4, remove_weight=0.25, annotate_weight=0.05,
        process_weight=0.3,
    ))
    check_stream(stream)


# ----------------------------------------------------------------------
# 2. directed regression scenarios (reference-suite behaviors)

def _session():
    log = []
    s = MockCollabSession(["A", "B", "C"], stream_log=log)
    return s, log


def test_directed_same_position_insert_storm():
    """Three clients insert at position 0 concurrently, twice over:
    later-sequenced wins the left slot at every tie (breakTie,
    mergeTree.ts:1705)."""
    s, log = _session()
    s.do("A", "insert_text_local", 0, "a1")
    s.do("B", "insert_text_local", 0, "b1")
    s.do("C", "insert_text_local", 0, "c1")
    s.process_all()
    s.do("A", "insert_text_local", 0, "a2")
    s.do("B", "insert_text_local", 0, "b2")
    s.do("C", "insert_text_local", 0, "c2")
    s.process_all()
    expected = s.assert_converged()
    check_stream(log)
    encs, np_table = run_kernel([log])
    assert extract_text(np_table, encs[0], 0) == expected


def test_directed_overlapping_removes_with_interleaved_insert():
    """A and B remove overlapping ranges while C inserts inside the
    doomed region (markRangeRemoved overlap tracking +
    insert-into-removed placement)."""
    s, log = _session()
    s.do("A", "insert_text_local", 0, "0123456789")
    s.process_all()
    s.do("A", "remove_range_local", 2, 8)
    s.do("B", "remove_range_local", 4, 10)
    s.do("C", "insert_text_local", 5, "XYZ")
    s.process_all()
    s.assert_converged()
    check_stream(log)


def test_directed_annotate_vs_concurrent_remove():
    """Annotate over a range another client concurrently removes: the
    annotation lands on tombstones and must not resurrect them."""
    s, log = _session()
    s.do("A", "insert_text_local", 0, "hello world")
    s.process_all()
    s.do("A", "annotate_range_local", 0, 11, {"bold": 1})
    s.do("B", "remove_range_local", 5, 11)
    s.process_all()
    s.assert_converged()
    check_stream(log)


def test_directed_insert_at_zamboni_boundary():
    """Edits target positions adjacent to below-msn tombstones: the
    insert walk's stop-eligibility must exclude them
    (mergeTree.ts:1003-1025 new length calculations)."""
    s, log = _session()
    s.do("A", "insert_text_local", 0, "abcdef")
    s.process_all()
    s.do("A", "remove_range_local", 0, 3)
    s.process_all()  # removal fully acked; msn advances past it
    s.do("B", "insert_text_local", 0, "B")  # before the tombstone run
    s.do("C", "insert_text_local", 3, "C")  # at the end
    s.process_all()
    s.assert_converged()
    check_stream(log)


def test_directed_remove_then_same_spot_insert_race():
    """B inserts into the middle of a range A removed concurrently;
    the insert survives inside the tombstone gap."""
    s, log = _session()
    s.do("A", "insert_text_local", 0, "0123456789")
    s.process_all()
    s.do("A", "remove_range_local", 3, 7)
    s.do("B", "insert_text_local", 5, "!!")
    s.do("C", "remove_range_local", 6, 9)
    s.process_all()
    s.assert_converged()
    check_stream(log)