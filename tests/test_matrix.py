"""SharedMatrix tests: concurrent permutations + cell LWW.

Mirrors packages/dds/matrix/src/test patterns over the container
session."""
import random

import pytest

from fluidframework_tpu.testing.runtime_mocks import ContainerSession


def make(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    for cid in ids:
        s.runtime(cid).create_datastore("d").create_channel(
            "sharedmatrix", "m"
        )
    return s, ids


def mat(s, cid):
    return s.runtime(cid).get_datastore("d").get_channel("m")


def test_basic_grid():
    s, _ = make()
    a = mat(s, "A")
    a.insert_rows(0, 2)
    a.insert_cols(0, 3)
    a.set_cell(0, 0, "x")
    a.set_cell(1, 2, 42)
    s.process_all()
    s.assert_converged()
    b = mat(s, "B")
    assert b.row_count == 2 and b.col_count == 3
    assert b.get_cell(0, 0) == "x"
    assert b.get_cell(1, 2) == 42


def test_cell_survives_concurrent_row_insert():
    """setCell targets handles, so concurrent permutations cannot
    displace it."""
    s, _ = make()
    a, b = mat(s, "A"), mat(s, "B")
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    s.process_all()
    a.insert_rows(0, 1)        # shifts row indices (sequenced first)
    b.set_cell(1, 1, "keep")   # concurrent: targets old row 1
    s.process_all()
    s.assert_converged()
    # the cell followed its row (now at index 2)
    assert a.get_cell(2, 1) == "keep"
    assert b.get_cell(2, 1) == "keep"


def test_concurrent_cell_set_lww():
    s, _ = make()
    a, b = mat(s, "A"), mat(s, "B")
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    s.process_all()
    b.set_cell(0, 0, "first")
    s.flush("B")                # sequenced first
    a.set_cell(0, 0, "second")
    s.flush("A")                # sequenced second -> wins
    s.process_all()
    s.assert_converged()
    assert a.get_cell(0, 0) == "second"
    assert b.get_cell(0, 0) == "second"


def test_remove_rows_hides_cells():
    s, _ = make()
    a = mat(s, "A")
    a.insert_rows(0, 3)
    a.insert_cols(0, 1)
    a.set_cell(1, 0, "doomed")
    a.set_cell(2, 0, "stays")
    s.process_all()
    a.remove_rows(1, 1)
    s.process_all()
    s.assert_converged()
    b = mat(s, "B")
    assert b.row_count == 2
    assert b.get_cell(1, 0) == "stays"


def test_matrix_summary_roundtrip():
    s, _ = make()
    a = mat(s, "A")
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    a.set_cell(0, 1, "v")
    s.process_all()
    s.assert_converged()
    import json
    summary = a.summarize_core()
    json.dumps(summary)
    from fluidframework_tpu.models import SharedMatrix
    loaded = SharedMatrix("m2")
    loaded.load_core(summary)
    assert loaded.row_count == 2 and loaded.col_count == 2
    assert loaded.get_cell(0, 1) == "v"


@pytest.mark.parametrize("seed", range(6))
def test_matrix_fuzz(seed):
    rng = random.Random(seed + 31)
    s, ids = make(3)
    for cid in ids:
        pass
    # seed a base grid
    mat(s, "A").insert_rows(0, 2)
    mat(s, "A").insert_cols(0, 2)
    s.process_all()
    for _ in range(120):
        cid = rng.choice(ids)
        m = mat(s, cid)
        r = rng.random()
        if r < 0.25 and s.pending_count:
            s.process_some(rng.randint(1, s.pending_count))
        elif r < 0.4:
            m.insert_rows(rng.randint(0, m.row_count), rng.randint(1, 2))
        elif r < 0.5:
            m.insert_cols(rng.randint(0, m.col_count), rng.randint(1, 2))
        elif r < 0.6 and m.row_count > 1:
            pos = rng.randint(0, m.row_count - 1)
            m.remove_rows(pos, 1)
        elif r < 0.65 and m.col_count > 1:
            pos = rng.randint(0, m.col_count - 1)
            m.remove_cols(pos, 1)
        elif m.row_count and m.col_count:
            m.set_cell(rng.randint(0, m.row_count - 1),
                       rng.randint(0, m.col_count - 1),
                       rng.randint(0, 99))
    s.process_all()
    s.assert_converged()
