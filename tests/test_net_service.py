"""Networked service plane: alfred-equivalent ingress + socket driver.

Reference parity targets: the connect_document/submitOp socket protocol
(lambdas/src/alfred/index.ts:465,500; driver-base/src/
documentDeltaConnection.ts:41) and the multi-process load runner
(test-service-load). In-proc tests run the asyncio server on a thread
and real TCP clients through the synchronous socket driver; the
heavyweight test spawns the dev service and workers as separate OS
processes via tools/net_stress.
"""
import asyncio
import threading

import pytest

from fluidframework_tpu.drivers.socket_driver import (
    SocketDocumentService,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
)
from fluidframework_tpu.service.ingress import AlfredServer


@pytest.fixture()
def server():
    srv = AlfredServer()
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def _run():
        await srv.start()
        started.set()
        try:
            await srv.serve_forever()
        except asyncio.CancelledError:
            pass

    task_holder = {}

    def runner():
        task = loop.create_task(_run())
        task_holder["task"] = task
        try:
            loop.run_until_complete(task)
        except Exception:
            pass

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    assert started.wait(10)
    yield srv
    loop.call_soon_threadsafe(task_holder["task"].cancel)
    thread.join(timeout=10)
    loop.call_soon_threadsafe(loop.stop)


def test_two_clients_converge_over_tcp(server):
    sa = SocketDocumentService("127.0.0.1", server.port, "doc")
    sb = SocketDocumentService("127.0.0.1", server.port, "doc")
    with sa.lock:
        a = Container.load(sa, client_id="alice")
    with sa.lock:
        ta = (a.runtime.create_datastore("d")
              .create_channel("sharedstring", "t"))
        a.flush()
        ta.insert_text(0, "hello")
        a.flush()

    with sb.lock:
        b = Container.load(sb, client_id="bob")
        tb = b.runtime.get_datastore("d").get_channel("t")
        assert tb.get_text() == "hello"
        tb.insert_text(5, " world")
        b.flush()

    deadline = 50
    import time

    for _ in range(deadline):
        with sa.lock:
            if ta.get_text() == "hello world":
                break
        time.sleep(0.05)
    with sa.lock, sb.lock:
        assert ta.get_text() == tb.get_text() == "hello world"
    a.close()
    b.close()
    sa.close()
    sb.close()


def test_read_ops_and_nack_over_tcp(server):
    svc = SocketDocumentService("127.0.0.1", server.port, "doc2")
    nacks = []
    got = []
    conn = svc.connect_to_delta_stream(
        "carol", on_message=got.append, on_nack=nacks.append
    )
    conn.submit(DocumentMessage(
        client_sequence_number=1, reference_sequence_number=1,
        type=MessageType.OPERATION, contents={"x": 1},
    ))
    import time

    for _ in range(100):
        if len(got) >= 2:  # join + the op
            break
        time.sleep(0.02)
    assert any(m.type == MessageType.OPERATION for m in got)

    # storage plane over the wire
    ops = svc.read_ops(0)
    assert [m.sequence_number for m in ops] == list(
        range(1, len(ops) + 1)
    )
    assert svc.get_latest_summary() is None

    # deterministic nack: client_sequence_number gap
    conn.submit(DocumentMessage(
        client_sequence_number=99, reference_sequence_number=2,
        type=MessageType.OPERATION, contents={"x": 2},
    ))
    for _ in range(100):
        if nacks:
            break
        time.sleep(0.02)
    assert nacks and "clientSequenceNumber" in nacks[0].message
    svc.close()


def test_multi_process_stress_converges():
    """VERDICT r3 done-criterion: multiple OS processes over real
    sockets converge through the runnable dev service."""
    from fluidframework_tpu.tools.net_stress import run_net_stress

    report = run_net_stress(n_workers=3, n_ops=12, seed=77)
    assert len({w["text_sha"] for w in report["workers"]}) == 1
    assert report["replay_length"] == report["workers"][0]["length"]


def test_multi_process_stress_converges_partitioned():
    """Same multi-process convergence bar, through the PARTITIONED
    queue pipeline (produce -> broker -> partition consumer -> deli)."""
    from fluidframework_tpu.tools.net_stress import run_net_stress

    report = run_net_stress(n_workers=3, n_ops=12, seed=78,
                            partitions=2)
    assert len({w["text_sha"] for w in report["workers"]}) == 1
    assert report["replay_length"] == report["workers"][0]["length"]
