"""failsan (testing/failsan.py) unit tests plus THE static/runtime
differentials that close the failcheck loop both ways:

- fault-to-signal: drive the REAL 20-seed chaos + failover + netsplit
  sweeps under the sanitizer and assert every injected fault mapped
  to an observable signal (``signal_coverage() == 1.0``, zero trips)
  — a silent absorb fails BY SITE, never silently.
- handler containment: every runtime-silent ``except`` clause an
  ``observe()`` window sees executing during a real chaos run must be
  a failcheck ``swallowed-exception`` static finding or a reviewed
  ``SILENT_HANDLERS`` registry entry (the detsan<->detcheck /
  wiresan<->wirecheck contract).
"""
import importlib.util
import os
import sys
import textwrap

import pytest

from fluidframework_tpu.obs import metrics as obs_metrics
from fluidframework_tpu.obs.flight_recorder import FlightRecorder
from fluidframework_tpu.qos.faults import PLANE, FaultSchedule
from fluidframework_tpu.testing import failsan

N_SEEDS = 20


def _smoke(n, keep):
    """range(n) with every seed outside ``keep`` slow-marked (the
    test_chaos.py sweep discipline): tier-1 runs a smoke subset, the
    full 20-seed differential is slow-lane."""
    return [
        s if s in keep else pytest.param(s, marks=pytest.mark.slow)
        for s in range(n)
    ]


@pytest.fixture()
def sanitized():
    """Install with a clean slate; always restore (refcounted, so an
    FFTPU_SANITIZE=1 session stays installed) — and reset BEFORE the
    conftest trip guard's teardown runs, so intentionally-planted
    trips never leak into the session accounting."""
    failsan.install()
    failsan.reset()
    yield failsan
    failsan.reset()
    failsan.uninstall()


def _fake_site(name, kinds=("error",)):
    """Register a throwaway site on the global plane (the plane the
    sanitizer hooks); the caller must drop it via _drop_site."""
    return PLANE.site(name, kinds)


def _drop_site(name):
    PLANE._sites.pop(name, None)


def _trips_metric(site):
    flat = obs_metrics.REGISTRY.flat()
    return sum(v for k, v in flat.items()
               if k.startswith("failsan_trips_total") and site in k)


# ------------------------------------------------------- window shapes


def test_unregistered_fired_site_trips(sanitized):
    """A fired site with no SITE_SIGNALS entry is an unregistered
    seam — always a trip, with the register-the-pairing diagnosis and
    the by-site metric increment."""
    site = _fake_site("zzz.unpaired_seam")
    metric_before = _trips_metric("zzz.unpaired_seam")
    try:
        PLANE.arm(FaultSchedule(seed=11, rates={}))
        site.force("error")
        PLANE.disarm()
        trips = failsan.trips()
        assert len(trips) == 1
        trip = trips[0]
        assert trip.site == "zzz.unpaired_seam"
        assert trip.reason == "unregistered-site"
        assert trip.kinds == ("error",)
        assert trip.events == 1
        assert trip.seed == 11
        assert trip.expected == ()
        assert "NO SITE_SIGNALS entry" in trip.describe()
        assert failsan.signal_coverage() == 0.0
        assert _trips_metric("zzz.unpaired_seam") == metric_before + 1
    finally:
        PLANE.disarm()
        _drop_site("zzz.unpaired_seam")


def test_registered_site_with_silent_absorb_trips(sanitized):
    """A registered site whose paired families did NOT move (and no
    stderr line / flight record named it) is a silent absorb: the
    trip carries the families that were consulted."""
    from fluidframework_tpu.service import partitioning  # noqa: F401

    try:
        PLANE.arm(FaultSchedule(seed=7, rates={}))
        PLANE._sites["broker.queue_append"].force("error")
        PLANE.disarm()
        trips = failsan.trips()
        assert len(trips) == 1
        assert trips[0].reason == "silent"
        assert trips[0].expected == ("broker_append_retries_total",)
        assert "broker_append_retries_total" in trips[0].describe()
        assert failsan.signal_coverage() == 0.0
    finally:
        PLANE.disarm()


def test_paired_metric_delta_covers_even_after_disarm(sanitized):
    """The lazy-evaluation contract: the chaos harnesses disarm
    BEFORE quiesce, so a handling metric that moves after disarm (but
    before the next evaluation point) still credits the injection."""
    from fluidframework_tpu.service import partitioning

    try:
        PLANE.arm(FaultSchedule(seed=3, rates={}))
        PLANE._sites["broker.queue_append"].force("error")
        PLANE.disarm()
        # the recovery signal lands during quiesce, post-disarm
        partitioning._M_APPEND_RETRIES.inc()
        assert failsan.trips() == []
        assert failsan.signal_coverage() == 1.0
    finally:
        PLANE.disarm()


def test_loud_stderr_line_credits(sanitized):
    """The ``chaos[site]`` transient-message shape on stderr is a
    signal; arbitrary run chatter naming the site is NOT (that credit
    would be vacuous — every armed run prints rate tables). Lines are
    fed through the tee's own write path: pytest rebinds sys.stderr
    per test phase around the installed tee (a tolerated swap — the
    metric pairing is the primary channel), so the global binding is
    not what this test is about."""
    from fluidframework_tpu.service import partitioning  # noqa: F401

    try:
        PLANE.arm(FaultSchedule(seed=5, rates={}))
        PLANE._sites["broker.queue_append"].force("error")
        PLANE.disarm()
        # bare-name chatter: NOT a signal
        _feed_stderr("note: broker.queue_append rates armed\n")
        trips = failsan.trips()
        assert len(trips) == 1 and trips[0].reason == "silent"
        failsan.reset()
        PLANE.arm(FaultSchedule(seed=5, rates={}))
        PLANE._sites["broker.queue_append"].force("error")
        PLANE.disarm()
        # the transient-message shape: credits
        _feed_stderr(
            "chaos[broker.queue_append]: injected error (event 1)\n")
        assert failsan.trips() == []
        assert failsan.signal_coverage() == 1.0
    finally:
        PLANE.disarm()


def _feed_stderr(text):
    """Write through the installed tee when the call-phase binding
    still IS the tee; otherwise feed the line buffer the tee fills —
    the two are the same code path (_StderrTee.write)."""
    if isinstance(sys.stderr, failsan._StderrTee):
        sys.stderr.write(text)
    else:
        import io

        # any tee instance fills the one shared line buffer — same
        # write path, minus the swapped-out global binding
        failsan._StderrTee(io.StringIO()).write(text)


def test_stderr_tee_plumbing_captures_lines():
    """The installed tee itself: write-through plus line capture.
    Skipped when a session-level sanitizer owns stderr (pytest's
    capture then sits ABOVE the tee and test writes bypass it)."""
    if failsan.installed():
        pytest.skip("session sanitizer owns the stderr tee")
    failsan.install()
    try:
        failsan.reset()
        assert isinstance(sys.stderr, failsan._StderrTee)
        print("chaos[test.plumbing]: injected error (event 1)",
              file=sys.stderr)
        assert ("chaos[test.plumbing]: injected error (event 1)"
                in failsan._STATE.stderr_lines)
    finally:
        failsan.reset()
        failsan.uninstall()


def test_flight_record_naming_the_site_credits(sanitized):
    """A flight-recorder record from the SYSTEM naming the seam is a
    signal — but the chaos plane's own recorder (the injection log)
    never counts, or coverage would be vacuous by construction."""
    from fluidframework_tpu.service import partitioning  # noqa: F401

    recorder = FlightRecorder(name="fstest")
    try:
        PLANE.arm(FaultSchedule(seed=9, rates={}))
        PLANE._sites["broker.queue_append"].force("error")
        PLANE.disarm()
        recorder.record("recovered", seam="broker.queue_append")
        assert failsan.trips() == []
        assert failsan.signal_coverage() == 1.0
    finally:
        PLANE.disarm()


def test_plane_own_flight_records_never_credit(sanitized):
    """The plane's inject/arm/disarm records name every site — if
    they counted, nothing could ever trip. They must not."""
    site = _fake_site("zzz.vacuity_probe")
    try:
        PLANE.arm(FaultSchedule(seed=13, rates={}))
        # force() writes an "inject" record naming the site to
        # PLANE.flight; that record is the injector observing itself
        site.force("error")
        PLANE.disarm()
        trips = failsan.trips()
        assert len(trips) == 1
        assert trips[0].site == "zzz.vacuity_probe"
    finally:
        PLANE.disarm()
        _drop_site("zzz.vacuity_probe")


def test_test_prefix_sites_are_exempt(sanitized):
    """test.* sites are harness fixtures (scripted-frame servers and
    unit seams), outside the system's fault-to-signal contract."""
    site = _fake_site("test.failsan_fixture_seam")
    try:
        PLANE.arm(FaultSchedule(seed=2, rates={}))
        site.force("error")
        PLANE.disarm()
        assert failsan.trips() == []
        assert failsan.signal_coverage() == 1.0  # nothing accountable
    finally:
        PLANE.disarm()
        _drop_site("test.failsan_fixture_seam")


def test_chaos_families_are_forbidden_as_signals():
    """The registry can never pair the injector with itself — pinned
    here in addition to the import-time assert, so a refactor moving
    the assert cannot silently drop the property."""
    for site, kinds in failsan.SITE_SIGNALS.items():
        for fams in kinds.values():
            assert not any(f.startswith("chaos_") for f in fams), site


def test_install_uninstall_restores_the_surface():
    before = (obs_metrics.MetricsRegistry.__init__,
              FlightRecorder.record, obs_metrics.Counter.inc,
              sys.stderr)
    failsan.install()
    try:
        assert isinstance(sys.stderr, failsan._StderrTee)
        assert failsan._on_arm in PLANE.on_arm
        assert failsan._on_disarm in PLANE.on_disarm
    finally:
        failsan.uninstall()
    after = (obs_metrics.MetricsRegistry.__init__,
             FlightRecorder.record, obs_metrics.Counter.inc,
             sys.stderr)
    assert before == after


# ------------------------------------------------- observe() (unit)


def _plant_module(tmp_path, relpath, source):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    name = relpath.replace("/", "_").removesuffix(".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_observe_classifies_silent_and_loud_handlers(
        sanitized, tmp_path, monkeypatch):
    """The settrace window: a handler completing with no credit is
    runtime-silent; metric bumps, stderr writes, and re-raises all
    credit — keyed by the SAME handler keys the static pass emits."""
    monkeypatch.setattr(failsan, "_REPO_ROOT",
                        str(tmp_path) + os.sep)
    mod = _plant_module(
        tmp_path, "fluidframework_tpu/service/fakefail.py", """
        def absorb():
            try:
                raise ValueError("boom")
            except ValueError:
                return None

        def loud_stderr(err_stream):
            try:
                raise ValueError("boom")
            except ValueError as e:
                print(f"fakefail: {e}", file=err_stream)
                return None

        def loud_metric(counter):
            try:
                raise ValueError("boom")
            except ValueError:
                counter.inc()
                return None

        def loud_reraise():
            try:
                raise ValueError("boom")
            except ValueError as e:
                raise RuntimeError("wrapped") from e
    """)
    import io

    counter = obs_metrics.MetricsRegistry("fstest").counter(
        "fstest_handled_total", "test counter")
    # a tee-backed stream: the stderr-write credit path, independent
    # of pytest's per-phase sys.stderr swaps around the installed tee
    err_stream = failsan._StderrTee(io.StringIO())
    with failsan.observe() as rep:
        mod.absorb()
        mod.absorb()
        mod.loud_stderr(err_stream)
        mod.loud_metric(counter)
        with pytest.raises(RuntimeError):
            mod.loud_reraise()
    by_key = {h.handler_key: h for h in rep.observed()}
    assert by_key["absorb:except-ValueError"].silent_runs == 2
    assert by_key["absorb:except-ValueError"].count == 2
    assert by_key["loud_stderr:except-ValueError"].silent_runs == 0
    assert by_key["loud_metric:except-ValueError"].silent_runs == 0
    assert by_key["loud_reraise:except-ValueError"].silent_runs == 0
    silent = rep.runtime_silent()
    assert [h.handler_key for h in silent] == \
        ["absorb:except-ValueError"]
    assert silent[0].relpath == \
        "fluidframework_tpu/service/fakefail.py"


def test_observe_windows_do_not_nest(sanitized):
    with failsan.observe():
        with pytest.raises(RuntimeError):
            with failsan.observe():
                pass


# ------------------------------------------------------ differentials


@pytest.mark.parametrize("seed", _smoke(N_SEEDS, {0, 1, 2}))
def test_sweep_full_fault_to_signal_coverage(seed):
    """THE fault-to-signal differential: the real chaos, failover and
    netsplit harnesses under one seed, every injected event mapped to
    a signal. A trip names the site and the families consulted — fix
    the seam's handling accounting (or the SITE_SIGNALS pairing),
    never this test."""
    from fluidframework_tpu.testing.chaos import (
        run_chaos,
        run_chaos_failover,
        run_chaos_netsplit,
    )

    failsan.install()
    try:
        failsan.reset()
        assert run_chaos(seed=seed).converged
        run_chaos_failover(seed=seed)
        run_chaos_netsplit(seed=seed)
        failsan.flush()
        trips = failsan.trips()
        assert trips == [], "\n".join(t.describe() for t in trips)
        assert failsan.signal_coverage() == 1.0
        assert failsan._STATE.total_events > 0  # non-vacuous window
    finally:
        failsan.reset()
        failsan.uninstall()


def test_runtime_silent_handlers_are_subset_of_static_and_registry(
        tmp_path):
    """THE handler-containment differential: every except clause that
    completed silently while the real chaos run (crash seed: torn
    states + restart recovery) executed must be a failcheck static
    ``swallowed-exception`` finding or a reviewed SILENT_HANDLERS
    entry. A gap fails BY NAME as an analyzer-resolution gap — fix
    failcheck's loudness resolution or review the handler into the
    registry; do NOT weaken this test."""
    from fluidframework_tpu.analysis.core import run_analysis
    from fluidframework_tpu.analysis.failcheck import (
        silent_handler_registered,
    )
    from fluidframework_tpu.service.partitioning import (
        FileOrderingQueue,
    )
    from fluidframework_tpu.testing.chaos import run_chaos

    failsan.install()
    try:
        failsan.reset()
        with failsan.observe() as rep:
            report = run_chaos(seed=3, faults=True, n_steps=12)
            # deterministic driver for the registry's non-vacuity
            # arm below: the crash-debris cleanup handler always
            # runs on a fresh root (ENOENT is the common case)
            FileOrderingQueue(str(tmp_path / "fsq"), n_partitions=2)
        assert report.converged, report.failures
    finally:
        failsan.reset()
        failsan.uninstall()

    findings = run_analysis(
        roots=["fluidframework_tpu"], families=["failcheck"])
    static_silent = {
        (f.path, f.key.split(":", 1)[1]) for f in findings
        if f.rule == "swallowed-exception"
    }
    silent = rep.runtime_silent()
    gaps = [
        h for h in silent
        if (h.relpath, h.handler_key) not in static_silent
        and not silent_handler_registered(h.relpath, h.handler_key)
    ]
    assert not gaps, (
        "ANALYZER-RESOLUTION GAP: failsan observed runtime-silent "
        "handlers that failcheck neither finds nor has registered:\n"
        + "\n".join(
            f"  {h.relpath}:{h.lineno} {h.handler_key} "
            f"({h.silent_runs}/{h.count} silent runs)" for h in gaps
        )
    )
    # non-vacuity, both arms: the window actually observed handling
    # (a no-op tracer must not pass), and at least one REGISTERED
    # silent handler was seen silently absorbing — the registry
    # describes live behavior, not folklore
    assert rep.observed(), "no handler observed: the window drove nothing"
    assert any(
        silent_handler_registered(h.relpath, h.handler_key)
        for h in silent
    ), "no registered silent handler observed: the differential is vacuous"
