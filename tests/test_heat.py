"""Cost attribution plane (obs/heat.py): the deterministic HeatLedger
and the sidecar device-time attribution built on it.

The pins, in order of load-bearing-ness:

- CONSERVATION: attribute_round splits a round's wall-ms across its
  documents proportional to ops — the per-doc charges must sum back
  to the round total (up to float rounding), every round, and the
  aggregate heat_doc_ms_total counter must agree with the ledger.
- DETERMINISM: the ledger is pure host math over SoA float64 — two
  identical charge/tick sequences produce bit-identical snapshots
  and top-k cuts (ties break ascending by key, no dict-order leak).
- CARDINALITY: the ledger is LRU-capped (least recently WRITTEN
  evicted first) so a tenant-id flood cannot grow host memory.
- SHARED-LEDGER PARITY: MeshShardedPool's migration heuristic reads
  its heat off the same HeatLedger type since PR18 — co-owning one
  ledger with the attribution plane (int slot keys next to doc-name
  strings) must leave the migration differential bit-exact.
"""
import random

import jax

from fluidframework_tpu.obs import metrics as obs_metrics
from fluidframework_tpu.obs.heat import (
    HeatLedger,
    attribute_round,
    usage_ledger,
)


class StepClock:
    def __init__(self, step_s: float = 0.001):
        self.t = 0.0
        self.step_s = step_s

    def __call__(self) -> float:
        self.t += self.step_s
        return self.t


# ======================================================================
# conservation


def test_attribute_round_conserves_device_time():
    """sum(per-doc charges) == round_ms for every round, and the
    aggregate counter tracks the ledger total."""
    rng = random.Random(7)
    ledger = HeatLedger(clock=StepClock())
    usage = usage_ledger(clock=StepClock())
    counter = obs_metrics.REGISTRY.get("heat_doc_ms_total")
    before = counter.value if counter is not None else 0.0
    total_charged = 0.0
    for _ in range(50):
        counts = {
            f"doc-{rng.randrange(12)}": rng.randrange(0, 9)
            for _ in range(rng.randrange(1, 8))
        }
        round_ms = rng.random() * 20.0
        pre = {d: ledger.get(d) for d in counts}
        charged = attribute_round(
            ledger, counts, round_ms,
            usage=usage, tenant_of=lambda d: "t-" + d[-1])
        real = sum(n for n in counts.values() if n > 0)
        if real == 0:
            assert charged == 0.0
            continue
        # the round total is conserved across its documents
        deltas = [ledger.get(d) - pre[d] for d in counts]
        assert abs(sum(deltas) - round_ms) <= 1e-9 * max(1.0, round_ms)
        assert abs(charged - round_ms) <= 1e-9 * max(1.0, round_ms)
        # proportionality: a doc's share is n/real of the round
        for d, n in counts.items():
            want = round_ms * n / real if n > 0 else 0.0
            assert abs((ledger.get(d) - pre[d]) - want) <= 1e-9 * 20.0
        total_charged += charged
    # the aggregate counter is the same sum, counted as it happened
    assert counter is not None
    assert abs((counter.value - before) - total_charged) <= 1e-6
    # and the tenant rollup conserves the same total
    tenant_ms = sum(usage.column(t, "device_ms")
                    for t in usage.keys())
    assert abs(tenant_ms - total_charged) <= 1e-6


def test_attribute_round_degenerate_rounds_charge_nothing():
    ledger = HeatLedger(clock=StepClock())
    assert attribute_round(None, {"d": 3}, 5.0) == 0.0
    assert attribute_round(ledger, {"d": 3}, 0.0) == 0.0
    assert attribute_round(ledger, {}, 5.0) == 0.0
    assert attribute_round(ledger, {"d": 0}, 5.0) == 0.0
    assert len(ledger) == 0


# ======================================================================
# determinism


def _scripted_run(seed: int) -> HeatLedger:
    rng = random.Random(seed)
    ledger = usage_ledger(max_keys=64, clock=StepClock())
    keys = [f"tenant-{i}" for i in range(20)]
    for step in range(200):
        k = rng.choice(keys)
        ledger.charge(k, rng.random() * 4.0,
                      ops_offered=rng.randrange(1, 5),
                      bytes_in=float(rng.randrange(0, 512)))
        if step % 17 == 0:
            # EWMA tick over a random sub-population
            pop = rng.sample(keys, 5)
            ledger.ewma_tick(
                {k: 0 for k in pop if k in ledger},
                {k: rng.random() * 8.0 for k in pop},
                decay=0.8)
    return ledger


def test_heat_ledger_is_bit_deterministic_x2():
    """Same scripted sequence twice: bit-identical snapshot, top-k,
    and key order (the LRU order is part of the contract)."""
    a, b = _scripted_run(3), _scripted_run(3)
    assert a.snapshot() == b.snapshot()
    assert a.keys() == b.keys()
    for by in (None, "ops_offered", "bytes_in"):
        assert a.top_k(10, by=by) == b.top_k(10, by=by)


def test_top_k_tie_break_is_ascending_by_key():
    ledger = HeatLedger(clock=StepClock())
    # insert in an order that would expose dict/insertion leaks
    for k in ("z", "a", "m", "b"):
        ledger.charge(k, 2.0)
    ledger.charge("m", 1.0)
    assert ledger.top_k(4) == [
        ("m", 3.0), ("a", 2.0), ("b", 2.0), ("z", 2.0)]
    assert ledger.top_k(2) == [("m", 3.0), ("a", 2.0)]


# ======================================================================
# cardinality


def test_ledger_lru_cap_evicts_least_recently_written():
    counter = obs_metrics.REGISTRY.get("heat_ledger_evictions_total")
    before = counter.value if counter is not None else 0.0
    ledger = HeatLedger(max_keys=4, clock=StepClock())
    for i in range(4):
        ledger.charge(f"k{i}", 1.0)
    ledger.charge("k0", 1.0)          # k0 becomes most recent
    ledger.charge("flood-1", 1.0)     # evicts k1 (oldest write)
    ledger.charge("flood-2", 1.0)     # evicts k2
    assert len(ledger) == 4
    assert "k1" not in ledger and "k2" not in ledger
    assert "k0" in ledger and "k3" in ledger
    assert ledger.evictions == 2
    assert counter is not None
    assert counter.value - before == 2.0


def test_usage_ledger_survives_tenant_flood_bounded():
    ledger = usage_ledger(max_keys=32, clock=StepClock())
    for i in range(10_000):
        ledger.charge(f"tenant-{i}", 0.001, ops_offered=1)
    assert len(ledger) == 32
    assert ledger.evictions == 10_000 - 32


# ======================================================================
# shared-ledger mesh-pool parity (the PR8 migration differential,
# re-pinned with the pool's heat co-owned by the attribution plane)


def _hotspot_sidecars():
    from fluidframework_tpu.parallel import MeshShardedPool, make_mesh
    from fluidframework_tpu.service import TpuMergeSidecar

    # the co-owned ledger: the mesh pool's migration heat (int slot
    # keys) and the sidecar attribution plane (doc-name string keys)
    # live on ONE ledger, like a serving deployment sharing the
    # federation surface
    shared = HeatLedger(max_keys=1 << 16, decay=0.5,
                        clock=StepClock())
    shared_sc = TpuMergeSidecar(
        max_docs=6, capacity=16, max_capacity=16,
        seq_mesh=make_mesh(jax.devices()[:2]), pool_capacity=256,
        heat=shared, attr_clock=StepClock(),
    )
    assert isinstance(shared_sc._pool, MeshShardedPool)
    shared_sc._pool.heat = shared    # co-own (pool is still empty)
    plain_sc = TpuMergeSidecar(
        max_docs=6, capacity=16, max_capacity=16,
        seq_mesh=make_mesh(jax.devices()[:2]), pool_capacity=256,
    )
    return shared, shared_sc, plain_sc


def test_mesh_pool_parity_on_shared_attribution_ledger():
    """The hot-spot migration run with the pool's heat tracker on a
    ledger CO-OWNED with the attribution plane must stay bit-exact
    against the private-ledger pool: same migrations, same text,
    same signatures — and the attribution keys must not perturb the
    migration heuristic (nor vice versa)."""
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.service import LocalServer

    server = LocalServer()
    shared, shared_sc, plain_sc = _hotspot_sidecars()
    sidecars = [shared_sc, plain_sc]
    factory = LocalDocumentServiceFactory(server)
    docs, containers, strings = [], {}, {}
    for i in range(3):
        doc = f"doc-{i}"
        for sc in sidecars:
            sc.subscribe(server, doc, "d", "s")
        c = Container.load(factory.create_document_service(doc),
                           client_id=f"{doc}-w")
        s = c.runtime.create_datastore("d").create_channel(
            "sharedstring", "s")
        docs.append(doc)
        containers[doc], strings[doc] = c, s

    def grow(c, s, n_chunks=20):
        for i in range(n_chunks):
            s.insert_text(0, "abcdefgh")
            c.flush()
            if i % 3 == 2 and s.get_length() > 6:
                s.remove_text(2, 5)
                c.flush()

    for doc in docs:
        grow(containers[doc], strings[doc])
    for sc in sidecars:
        sc.apply()
        sc.sync()
    # hot-spot doc-0 until the mesh pools migrate
    for _ in range(6):
        for doc in docs:
            n = 12 if doc == "doc-0" else 1
            for _ in range(n):
                strings[doc].insert_text(0, "XY")
            containers[doc].flush()
        for sc in sidecars:
            sc.apply()
            sc.sync()

    assert shared_sc._pool.migration_count > 0, (
        "the hot-spot run must actually migrate")
    assert shared_sc._pool.migration_count == \
        plain_sc._pool.migration_count
    for doc in docs:
        want = strings[doc].get_text()
        assert shared_sc.text(doc, "d", "s") == want
        assert plain_sc.text(doc, "d", "s") == want
        assert shared_sc.signature(doc, "d", "s") == \
            plain_sc.signature(doc, "d", "s")
    # both planes actually wrote the shared ledger: int slot keys
    # (pool heat) next to doc-name strings (attribution), and the
    # attribution side conserved the doc plane's charges
    keys = shared.keys()
    assert any(isinstance(k, int) for k in keys)
    assert any(isinstance(k, str) for k in keys)
    attributed = sum(shared.get(d) for d in docs)
    assert attributed > 0.0
    # mixed key population still serves a deterministic top-k
    assert shared.top_k(5) == shared.top_k(5)
