"""Networked ordering broker (VERDICT r3 missing #3): the rdkafka-tier
seam over framed TCP — at-least-once, committed-offset resume, durable
across broker restarts, partitions spanning processes.

Reference semantics: services-ordering-rdkafka/src/rdkafkaConsumer.ts
:37 (committed-offset consume) / rdkafkaProducer.ts:52.
"""
import asyncio
import os
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType
from fluidframework_tpu.service.broker import (
    BrokerServer,
    RemoteOrderingQueue,
)
from fluidframework_tpu.service.partitioning import (
    PartitionedOrderingService,
    partition_for,
)


@pytest.fixture()
def broker(tmp_path):
    """BrokerServer on a background loop; yields a factory so tests
    can restart it over the same data dir."""
    state = {}

    def start(n_partitions=2, durable=True):
        b = BrokerServer(
            n_partitions,
            str(tmp_path / "qdata") if durable else None,
        )
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            loop.run_until_complete(b.start())
            started.set()
            loop.run_forever()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert started.wait(10)
        state.update(server=b, loop=loop, thread=t)
        return b

    def stop():
        if not state:
            return
        fut = asyncio.run_coroutine_threadsafe(
            state["server"].stop(), state["loop"])
        try:
            fut.result(timeout=10)
        except Exception:
            pass
        state["loop"].call_soon_threadsafe(state["loop"].stop)
        state["thread"].join(timeout=10)
        state.clear()

    start.stop = stop
    yield start
    stop()


def test_produce_read_commit_roundtrip(broker):
    b = broker()
    q = RemoteOrderingQueue("127.0.0.1", b.port)
    assert q.n_partitions == 2
    o0 = q.produce(0, "doc-a", {"x": 1})
    o1 = q.produce(0, "doc-b", {"x": 2})
    q.produce(1, "doc-c", {"x": 3})
    assert (o0, o1) == (0, 1)
    recs = list(q.read(0, 0))
    assert [(r.offset, r.document_id) for r in recs] == [
        (0, "doc-a"), (1, "doc-b")]
    assert q.committed(0) == -1
    q.commit(0, 1)
    assert q.committed(0) == 1
    # re-read from committed+1: nothing left (at-least-once resume)
    assert list(q.read(0, q.committed(0) + 1)) == []
    q.close()


def test_read_batches_past_server_limit(broker):
    b = broker()
    q = RemoteOrderingQueue("127.0.0.1", b.port)
    for i in range(1203):
        q.produce(1, "d", {"i": i})
    got = [r.payload["i"] for r in q.read(1, 0)]
    assert got == list(range(1203))  # spans 3 server batches
    q.close()


def test_partition_out_of_range_errors(broker):
    b = broker()
    q = RemoteOrderingQueue("127.0.0.1", b.port)
    with pytest.raises(RuntimeError, match="out of range"):
        q.produce(9, "d", {})
    q.close()


def test_broker_restart_preserves_offsets_and_client_reconnects(
        broker):
    b = broker()
    q = RemoteOrderingQueue("127.0.0.1", b.port)
    q.produce(0, "d", {"n": 1})
    q.commit(0, 0)
    port = b.port
    broker.stop()
    # restart over the same data dir on the same port
    b2 = BrokerServer(2, str(b.queue.root), port=port)

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(b2.start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(10)
    try:
        # the client's dead socket retries transparently
        assert q.committed(0) == 0
        q.produce(0, "d", {"n": 2})
        assert [r.payload["n"] for r in q.read(0, 0)] == [1, 2]
    finally:
        fut = asyncio.run_coroutine_threadsafe(b2.stop(), loop)
        fut.result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)
    q.close()


def _op(csn, ref=0):
    return DocumentMessage(
        client_sequence_number=csn, reference_sequence_number=ref,
        type=MessageType.OPERATION, contents={"n": csn},
    )


def test_partitioned_service_over_remote_queue(broker):
    """The full pipeline shape with the queue on the wire: produce ->
    pump -> sequenced; commits land on the broker so a replacement
    consumer starts past them."""
    from fluidframework_tpu.protocol.messages import ClientDetail

    b = broker()
    q = RemoteOrderingQueue("127.0.0.1", b.port)
    svc = PartitionedOrderingService(n_partitions=2, queue=q)
    doc = "doc-x"
    svc.produce_join(doc, ClientDetail("alice"))
    for i in range(1, 6):
        svc.produce_op(doc, "alice", _op(i))
    svc.pump()
    ord1 = svc.orderer(doc)
    assert ord1.sequencer.sequence_number == 6  # join + 5 ops
    seen1 = [m.contents["n"] for m in ord1.op_log.read(0)
             if m.type == MessageType.OPERATION]
    assert seen1 == [1, 2, 3, 4, 5]
    p = partition_for(doc, 2)
    assert q.committed(p) == 5  # all six records (offsets 0..5)
    # a replacement consumer (fresh service, same broker) reads
    # nothing below the committed offset: no duplicate sequencing
    q2 = RemoteOrderingQueue("127.0.0.1", b.port)
    svc2 = PartitionedOrderingService(n_partitions=2, queue=q2)
    assert svc2.pump() == 0
    q.close()
    q2.close()


@pytest.mark.slow
def test_partitions_span_processes_against_one_broker(tmp_path):
    """The scale-out deployment shape the VERDICT asked for: a broker
    process + TWO consumer processes each pumping ONE partition of the
    same queue, with producers on a third process; every partition's
    records sequence exactly once per consumer."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    broker_proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.broker",
         "--port", "0", "--partitions", "2",
         "--data-dir", str(tmp_path / "q")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo, env=env,
    )
    line = broker_proc.stdout.readline()
    m = re.search(r"listening on [\w.]+:(\d+)", line)
    assert m, line
    bport = int(m.group(1))

    consumer_code = """
import sys; sys.path.insert(0, '.')
from fluidframework_tpu.service.broker import RemoteOrderingQueue
from fluidframework_tpu.service.partitioning import (
    PartitionedOrderingService)
from fluidframework_tpu.protocol.messages import MessageType
import time
q = RemoteOrderingQueue('127.0.0.1', PORT)
svc = PartitionedOrderingService(n_partitions=2, queue=q)
part = svc.partitions[WHICH]
deadline = time.time() + 30
total = 0
while time.time() < deadline:
    total += part.pump()
    done = True
    for doc, dp in part.documents.items():
        ops = [m for m in dp.orderer.op_log.read(0)
               if m.type == MessageType.OPERATION]
        if len(ops) < 40:
            done = False
    if part.documents and done:
        break
    time.sleep(0.05)
for doc in sorted(part.documents):
    ops = [m.contents['n'] for m in
           part.documents[doc].orderer.op_log.read(0)
           if m.type == MessageType.OPERATION]
    print(f'DOC {doc} ' + ','.join(map(str, ops)))
"""
    producer_code = """
import sys; sys.path.insert(0, '.')
from fluidframework_tpu.service.broker import RemoteOrderingQueue
from fluidframework_tpu.service.partitioning import partition_for
from fluidframework_tpu.protocol.messages import MessageType
q = RemoteOrderingQueue('127.0.0.1', PORT)
docs = ['alpha', 'beta', 'gamma', 'delta']
for d in docs:
    p = partition_for(d, 2)
    q.produce(p, d, {'kind': 'join',
                     'detail': {'client_id': 'w'}})
for i in range(1, 41):
    for d in docs:
        p = partition_for(d, 2)
        q.produce(p, d, {'kind': 'op', 'client_id': 'w', 'op': {
            'client_sequence_number': i,
            'reference_sequence_number': 0,
            'type': int(MessageType.OPERATION),
            'contents': {'n': i}, 'metadata': None,
            'traces': []}})
print('PRODUCED')
"""
    try:
        prod = subprocess.run(
            [sys.executable, "-c",
             producer_code.replace("PORT", str(bport))],
            capture_output=True, text=True, cwd=repo, env=env,
            timeout=120,
        )
        assert prod.returncode == 0, prod.stderr[-1500:]
        consumers = [
            subprocess.Popen(
                [sys.executable, "-c",
                 consumer_code.replace("PORT", str(bport))
                 .replace("WHICH", str(w))],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=repo, env=env,
            )
            for w in (0, 1)
        ]
        outs = [c.communicate(timeout=120)[0] for c in consumers]
        assert all(c.returncode == 0 for c in consumers), outs
        docs_seen = {}
        for out in outs:
            for line in out.splitlines():
                if line.startswith("DOC "):
                    _, doc, ops = line.split(" ", 2)
                    docs_seen[doc] = ops
        want = ",".join(str(i) for i in range(1, 41))
        assert set(docs_seen) == {"alpha", "beta", "gamma", "delta"}
        for doc, ops in docs_seen.items():
            assert ops == want, (doc, ops)
    finally:
        os.kill(broker_proc.pid, signal.SIGKILL)
        broker_proc.wait()


def test_corrupt_frame_poisons_socket_and_reconnects_fresh():
    """A desynced/corrupt length prefix raises ValueError out of the
    frame reader; the client must DROP the cached socket (reusing it
    would parse mid-stream garbage as fresh frames) and the next
    request must reconnect from scratch."""
    from fluidframework_tpu.testing.fault_injection import (
        ScriptedFrameServer,
    )

    meta = {"type": "meta", "n_partitions": 2}
    with ScriptedFrameServer(
        [meta, ScriptedFrameServer.CORRUPT, meta]
    ) as srv:
        q = RemoteOrderingQueue("127.0.0.1", srv.port, timeout=5.0)
        with pytest.raises(ValueError, match="exceeds"):
            q._request({"type": "meta"})
        assert q._sock is None  # poisoned socket dropped, not cached
        # next request reconnects and succeeds on the fresh stream
        assert q._request({"type": "meta"})["n_partitions"] == 2
        q.close()
