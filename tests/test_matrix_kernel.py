"""SharedMatrix batched path vs the scalar model (VERDICT r1 missing
#5 / BASELINE config #3): two merge-kernel axes in one dispatch +
vectorized cell scatter must reproduce the converged to_lists() of the
live SharedMatrix replicas."""
import dataclasses
import random

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.ops import fetch
from fluidframework_tpu.ops.matrix_bridge import (
    MatrixStream,
    apply_matrix_batch,
    extract_matrix,
)
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.service import LocalServer


def channel_stream(server, document_id, ds_id, ch_id):
    """Extract one channel's inner sequenced stream from the op log
    (the sidecar's envelope rule)."""
    out = []
    for msg in server.read_ops(document_id, 0):
        envelope = msg.contents if isinstance(msg.contents, dict) else {}
        if (
            msg.type == MessageType.OPERATION
            and envelope.get("kind", "op") == "op"
            and envelope.get("address") == ds_id
            and envelope.get("channel") == ch_id
        ):
            out.append(
                dataclasses.replace(msg, contents=envelope["contents"])
            )
        else:
            out.append(dataclasses.replace(
                msg, type=MessageType.NO_OP, contents=None,
                client_id=None,
            ))
    return out


def make_matrix_session(doc="m"):
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service(doc),
                       client_id="alice")
    b = Container.load(factory.create_document_service(doc),
                       client_id="bob")
    ma = a.runtime.create_datastore("d").create_channel("sharedmatrix", "m")
    a.flush()
    mb = b.runtime.get_datastore("d").get_channel("m")
    return server, a, b, ma, mb


def replay_kernel(server, doc="m"):
    ms = MatrixStream()
    for msg in channel_stream(server, doc, "d", "m"):
        ms.add_message(msg)
    table = apply_matrix_batch([ms], capacity=512)
    np_table = fetch(table)
    assert not np_table["overflow"].any()
    return extract_matrix(np_table, ms, 0)


def test_matrix_kernel_basic():
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 3)
    ma.insert_cols(0, 2)
    a.flush()
    ma.set_cell(0, 0, "tl")
    ma.set_cell(2, 1, "br")
    a.flush()
    mb.set_cell(1, 1, "mid")
    b.flush()
    assert ma.to_lists() == mb.to_lists()
    assert replay_kernel(server) == ma.to_lists()


def test_matrix_kernel_concurrent_permutation_vs_cells():
    """Cells commute with concurrent permutation (handle stability)."""
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 4)
    ma.insert_cols(0, 3)
    a.flush()
    for r in range(4):
        for c in range(3):
            ma.set_cell(r, c, f"{r}.{c}")
    a.flush()
    # concurrent: A removes row 1 while B writes into rows 1 and 2
    ma.remove_rows(1, 1)
    mb.set_cell(1, 0, "doomed")
    mb.set_cell(2, 0, "survives")
    a.flush()
    b.flush()
    assert ma.to_lists() == mb.to_lists()
    assert replay_kernel(server) == ma.to_lists()


def test_matrix_kernel_concurrent_row_inserts_tiebreak():
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 2)
    ma.insert_cols(0, 1)
    a.flush()
    ma.insert_rows(0, 1)
    mb.insert_rows(0, 1)
    ma.set_cell(0, 0, "a-row")
    mb.set_cell(0, 0, "b-row")
    a.flush()
    b.flush()
    assert ma.to_lists() == mb.to_lists()
    assert replay_kernel(server) == ma.to_lists()


@pytest.mark.parametrize("seed", range(10))
def test_matrix_kernel_fuzz(seed):
    rng = random.Random(seed * 37 + 11)
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 2)
    ma.insert_cols(0, 2)
    a.flush()
    clients = [(a, ma), (b, mb)]
    for step in range(60):
        c, m = clients[rng.randint(0, 1)]
        roll = rng.random()
        try:
            if roll < 0.2:
                m.insert_rows(rng.randint(0, m.row_count), rng.randint(1, 2))
            elif roll < 0.35:
                m.insert_cols(rng.randint(0, m.col_count), 1)
            elif roll < 0.45 and m.row_count > 1:
                m.remove_rows(rng.randint(0, m.row_count - 1), 1)
            elif roll < 0.5 and m.col_count > 1:
                m.remove_cols(rng.randint(0, m.col_count - 1), 1)
            elif m.row_count and m.col_count:
                m.set_cell(rng.randint(0, m.row_count - 1),
                           rng.randint(0, m.col_count - 1),
                           rng.randint(0, 999))
        except AssertionError:
            continue  # cell outside local view mid-churn
        if rng.random() < 0.5:
            c.flush()
    a.flush()
    b.flush()
    assert ma.to_lists() == mb.to_lists(), f"seed {seed} diverged"
    assert replay_kernel(server) == ma.to_lists(), f"seed {seed}"


def test_matrix_kernel_reconnect_resubmit_handles():
    """code-review r2: reconnect resubmission emits GroupOps and split
    inserts with handle=[alloc, base>0]; the device handle derivation
    must track both or cells miss after replay."""
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 2)
    ma.insert_cols(0, 2)
    a.flush()
    a.disconnect()
    # offline: a run insert that will be split by b's concurrent edit
    ma.insert_rows(1, 3)
    ma.set_cell(2, 0, "offline")
    mb.insert_rows(0, 1)
    b.flush()
    a.connect()
    a.flush()
    b.flush()
    assert ma.to_lists() == mb.to_lists()
    assert replay_kernel(server) == ma.to_lists()


# ---- device cell path: sort + last-wins (matrix.ts:79 LWW) -----------

def _host_lww(streams):
    """Scalar LWW oracle: dict keyed by (row, col), window order."""
    out = []
    for s in streams:
        d = {}
        for rh, ch, v in zip(s.cell_rows, s.cell_cols, s.cell_vals):
            d[(rh, ch)] = v
        out.append(d)
    return out


@pytest.mark.parametrize("seed", range(6))
def test_cell_kernel_matches_host_lww(seed):
    import numpy as np

    from fluidframework_tpu.ops.matrix_cells import CellPack

    rng = random.Random(seed)
    streams = []
    for m in range(3):
        s = MatrixStream()
        n = rng.randint(0, 120)
        for _ in range(n):
            s.cell_rows.append(f"r{rng.randint(0, 15)}")
            s.cell_cols.append(f"c{rng.randint(0, 5)}")
            s.cell_vals.append(rng.randint(0, 10**6))
        streams.append(s)
    pack = CellPack(n_rows=16, n_cols=6)
    pack.pack(streams)
    grid = np.asarray(pack.apply())
    oracle = _host_lww(streams)
    for m, s in enumerate(streams):
        for (rh, ch), want in oracle[m].items():
            assert pack.lookup(grid, m, rh, ch) == want, (seed, m, rh, ch)
        # unwritten cells read None
        assert pack.lookup(grid, m, "r-none", "c0") is None
    # every grid entry that holds an index must be a winner
    for m in range(len(streams)):
        for r_h, r in pack.row_ids[m].items():
            for c_h, c in pack.col_ids[m].items():
                got = pack.lookup(grid, m, r_h, c_h)
                assert got == oracle[m].get((r_h, c_h))


def test_cell_kernel_empty_and_single():
    import numpy as np

    from fluidframework_tpu.ops.matrix_cells import CellPack

    empty = MatrixStream()
    one = MatrixStream()
    one.cell_rows.append("a:0")
    one.cell_cols.append("b:0")
    one.cell_vals.append("v")
    pack = CellPack(n_rows=4, n_cols=4)
    pack.pack([empty, one])
    grid = np.asarray(pack.apply())
    assert pack.lookup(grid, 0, "a:0", "b:0") is None
    assert pack.lookup(grid, 1, "a:0", "b:0") == "v"


def test_cell_kernel_window_segmentation():
    """Composite-key overflow splits the window into LWW-combined
    segments — exercised through the PRODUCTION CellPack.apply branch
    by shrinking the int32 budget — and must equal the single-kernel
    result."""
    import numpy as np

    from fluidframework_tpu.ops.matrix_cells import CellPack

    rng = random.Random(7)
    s = MatrixStream()
    for _ in range(50):
        s.cell_rows.append(f"r{rng.randint(0, 3)}")
        s.cell_cols.append(f"c{rng.randint(0, 3)}")
        s.cell_vals.append(rng.randint(0, 999))
    pack = CellPack(n_rows=4, n_cols=4)
    pack.pack([s])
    full = np.asarray(pack.apply())            # single-kernel path
    # budget 16*6 => max_n = 5 => ten ~5-op segments, real branch
    seg_grid = np.asarray(pack.apply(budget=4 * 4 * 6))
    assert np.array_equal(full, seg_grid)
    oracle = _host_lww([s])[0]
    for (rh, ch), want in oracle.items():
        assert pack.lookup(full, 0, rh, ch) == want
        assert pack.lookup(seg_grid, 0, rh, ch) == want
