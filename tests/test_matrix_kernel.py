"""SharedMatrix batched path vs the scalar model (VERDICT r1 missing
#5 / BASELINE config #3): two merge-kernel axes in one dispatch +
vectorized cell scatter must reproduce the converged to_lists() of the
live SharedMatrix replicas."""
import dataclasses
import random

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.ops import fetch
from fluidframework_tpu.ops.matrix_bridge import (
    MatrixStream,
    apply_matrix_batch,
    extract_matrix,
)
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.service import LocalServer


def channel_stream(server, document_id, ds_id, ch_id):
    """Extract one channel's inner sequenced stream from the op log
    (the sidecar's envelope rule)."""
    out = []
    for msg in server.read_ops(document_id, 0):
        envelope = msg.contents if isinstance(msg.contents, dict) else {}
        if (
            msg.type == MessageType.OPERATION
            and envelope.get("kind", "op") == "op"
            and envelope.get("address") == ds_id
            and envelope.get("channel") == ch_id
        ):
            out.append(
                dataclasses.replace(msg, contents=envelope["contents"])
            )
        else:
            out.append(dataclasses.replace(
                msg, type=MessageType.NO_OP, contents=None,
                client_id=None,
            ))
    return out


def make_matrix_session(doc="m"):
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service(doc),
                       client_id="alice")
    b = Container.load(factory.create_document_service(doc),
                       client_id="bob")
    ma = a.runtime.create_datastore("d").create_channel("sharedmatrix", "m")
    a.flush()
    mb = b.runtime.get_datastore("d").get_channel("m")
    return server, a, b, ma, mb


def replay_kernel(server, doc="m"):
    ms = MatrixStream()
    for msg in channel_stream(server, doc, "d", "m"):
        ms.add_message(msg)
    table = apply_matrix_batch([ms], capacity=512)
    np_table = fetch(table)
    assert not np_table["overflow"].any()
    return extract_matrix(np_table, ms, 0)


def test_matrix_kernel_basic():
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 3)
    ma.insert_cols(0, 2)
    a.flush()
    ma.set_cell(0, 0, "tl")
    ma.set_cell(2, 1, "br")
    a.flush()
    mb.set_cell(1, 1, "mid")
    b.flush()
    assert ma.to_lists() == mb.to_lists()
    assert replay_kernel(server) == ma.to_lists()


def test_matrix_kernel_concurrent_permutation_vs_cells():
    """Cells commute with concurrent permutation (handle stability)."""
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 4)
    ma.insert_cols(0, 3)
    a.flush()
    for r in range(4):
        for c in range(3):
            ma.set_cell(r, c, f"{r}.{c}")
    a.flush()
    # concurrent: A removes row 1 while B writes into rows 1 and 2
    ma.remove_rows(1, 1)
    mb.set_cell(1, 0, "doomed")
    mb.set_cell(2, 0, "survives")
    a.flush()
    b.flush()
    assert ma.to_lists() == mb.to_lists()
    assert replay_kernel(server) == ma.to_lists()


def test_matrix_kernel_concurrent_row_inserts_tiebreak():
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 2)
    ma.insert_cols(0, 1)
    a.flush()
    ma.insert_rows(0, 1)
    mb.insert_rows(0, 1)
    ma.set_cell(0, 0, "a-row")
    mb.set_cell(0, 0, "b-row")
    a.flush()
    b.flush()
    assert ma.to_lists() == mb.to_lists()
    assert replay_kernel(server) == ma.to_lists()


@pytest.mark.parametrize("seed", range(10))
def test_matrix_kernel_fuzz(seed):
    rng = random.Random(seed * 37 + 11)
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 2)
    ma.insert_cols(0, 2)
    a.flush()
    clients = [(a, ma), (b, mb)]
    for step in range(60):
        c, m = clients[rng.randint(0, 1)]
        roll = rng.random()
        try:
            if roll < 0.2:
                m.insert_rows(rng.randint(0, m.row_count), rng.randint(1, 2))
            elif roll < 0.35:
                m.insert_cols(rng.randint(0, m.col_count), 1)
            elif roll < 0.45 and m.row_count > 1:
                m.remove_rows(rng.randint(0, m.row_count - 1), 1)
            elif roll < 0.5 and m.col_count > 1:
                m.remove_cols(rng.randint(0, m.col_count - 1), 1)
            elif m.row_count and m.col_count:
                m.set_cell(rng.randint(0, m.row_count - 1),
                           rng.randint(0, m.col_count - 1),
                           rng.randint(0, 999))
        except AssertionError:
            continue  # cell outside local view mid-churn
        if rng.random() < 0.5:
            c.flush()
    a.flush()
    b.flush()
    assert ma.to_lists() == mb.to_lists(), f"seed {seed} diverged"
    assert replay_kernel(server) == ma.to_lists(), f"seed {seed}"


def test_matrix_kernel_reconnect_resubmit_handles():
    """code-review r2: reconnect resubmission emits GroupOps and split
    inserts with handle=[alloc, base>0]; the device handle derivation
    must track both or cells miss after replay."""
    server, a, b, ma, mb = make_matrix_session()
    ma.insert_rows(0, 2)
    ma.insert_cols(0, 2)
    a.flush()
    a.disconnect()
    # offline: a run insert that will be split by b's concurrent edit
    ma.insert_rows(1, 3)
    ma.set_cell(2, 0, "offline")
    mb.insert_rows(0, 1)
    b.flush()
    a.connect()
    a.flush()
    b.flush()
    assert ma.to_lists() == mb.to_lists()
    assert replay_kernel(server) == ma.to_lists()
