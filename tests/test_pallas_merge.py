"""Pallas TPU merge kernel: bit-equality vs the XLA scan executor.

Both executors run the identical ``merge_step.fused_step``; this suite
pins the Pallas grid/blocking/aliasing plumbing (interpret mode on CPU;
the same comparison runs against the real Mosaic lowering on TPU via
tools/tpu_evidence.py). The XLA executor itself is differential-tested
against the scalar oracle in test_merge_kernel.py, so transitively the
Pallas path inherits the reference semantics (mergeTree.ts:1705,1723).
"""
import numpy as np
import pytest

from fluidframework_tpu.ops import (
    build_batch,
    encode_stream,
    fetch,
    make_table,
)
from fluidframework_tpu.ops.merge_kernel import apply_window_impl
from fluidframework_tpu.testing import FuzzConfig, record_op_stream


def _fuzz_batch(docs, seed0, steps=40, clients=3):
    streams = []
    for d in range(docs):
        _, stream = record_op_stream(FuzzConfig(
            n_clients=clients, n_steps=steps, seed=seed0 + d,
            insert_weight=0.5, remove_weight=0.25, annotate_weight=0.1,
            process_weight=0.15,
        ))
        streams.append(encode_stream(stream))
    return build_batch(streams)


def _pallas_interpret(table, batch):
    from fluidframework_tpu.ops import pallas_merge as pm
    from fluidframework_tpu.ops.merge_step import (
        STATE_FIELDS,
        state_to_table,
        table_to_state,
    )
    from fluidframework_tpu.ops.segment_table import SegmentTable

    from fluidframework_tpu.ops.merge_step import OP_COLS

    ops = {f: getattr(batch, f) for f in OP_COLS}
    out = pm._pallas_call(
        table_to_state(table), ops, interpret=True
    )
    return state_to_table(out, SegmentTable)


@pytest.mark.parametrize("seed", [
    pytest.param(0, marks=pytest.mark.slow), 7,
    pytest.param(99, marks=pytest.mark.slow),
])
def test_pallas_interpret_matches_xla(seed):
    docs, cap = 4, 128
    batch = _fuzz_batch(docs, seed0=1000 + seed * 10, steps=30)
    ref = apply_window_impl(make_table(docs, cap), batch)
    got = _pallas_interpret(make_table(docs, cap), batch)
    ref_np, got_np = fetch(ref), fetch(got)
    for f in ref_np:
        np.testing.assert_array_equal(
            got_np[f], ref_np[f], err_msg=f"field {f} seed {seed}"
        )


def test_pallas_interpret_doc_padding_path():
    """The wrapper pads the doc axis to a block multiple; padded docs
    must be inert (NOOP ops only) and real docs identical after
    unpadding. Runs the same pad/unpad code as the TPU path, with the
    kernel itself in interpret mode."""
    import jax.numpy as jnp

    from fluidframework_tpu.ops import pallas_merge as pm
    from fluidframework_tpu.ops.merge_step import (
        OP_COLS,
        state_to_table,
        table_to_state,
    )
    from fluidframework_tpu.ops.segment_table import (
        KIND_NOOP,
        NOT_REMOVED,
        SegmentTable,
    )

    docs, cap = 5, 128  # not a multiple of any block size
    batch = _fuzz_batch(docs, seed0=4321, steps=25)
    ref = apply_window_impl(make_table(docs, cap), batch)

    # replicate apply_window_pallas's padding, run interpret, unpad
    table = make_table(docs, cap)
    block = pm._doc_block(cap, docs)
    padded = max(block, -(-docs // block) * block)
    assert padded > docs  # the padding path is actually exercised
    pad = padded - docs
    state = {
        f: jnp.pad(
            a, [(0, pad)] + [(0, 0)] * (a.ndim - 1),
            constant_values=NOT_REMOVED if f == "removed_seq" else 0,
        )
        for f, a in table_to_state(table).items()
    }
    ops = {
        f: jnp.pad(
            getattr(batch, f), [(0, pad), (0, 0)],
            constant_values=KIND_NOOP if f == "kind" else 0,
        )
        for f in OP_COLS
    }
    out = pm._pallas_call(state, ops, interpret=True)
    # padded docs stayed empty
    for d in range(docs, padded):
        assert int(out["count"][d, 0]) == 0
    got = state_to_table(
        {f: a[:docs] for f, a in out.items()}, SegmentTable
    )
    ref_np, got_np = fetch(ref), fetch(got)
    for f in ref_np:
        np.testing.assert_array_equal(
            got_np[f], ref_np[f], err_msg=f"field {f}"
        )
