"""Replicated sequencer (service/replication.py): the ack barrier
(fsync-and-replicate-before-fanout), the lease/epoch-fence seam, and
follower promotion at exactly the replicated head — plus the
partitioned plane's replicated queue/checkpoint counterparts.

The end-to-end proof lives in tests/test_chaos.py (the 20-seed
kill-the-leader differential); this file pins each mechanism in
isolation so a failover bug names its broken piece.
"""
import json
import os

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.obs import metrics as obs_metrics
from fluidframework_tpu.qos.faults import (
    KIND_DEFER,
    KIND_DROP,
    PLANE,
)
from fluidframework_tpu.service.replication import (
    EpochFence,
    FencedWriteError,
    FollowerReplica,
    LeaseHeldError,
    LeaseUnreachableError,
    NetworkTopology,
    QuorumUnavailableError,
    ReplicatedSequencerGroup,
    SequencerLease,
)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _group(tmp_path, **kw):
    clock = _Clock()
    kw.setdefault("n_followers", 2)
    g = ReplicatedSequencerGroup(str(tmp_path), clock=clock, **kw)
    return g, clock


def _load_writer(group, doc="doc", client="w"):
    factory = LocalDocumentServiceFactory(group.server)
    c = Container.load(factory.create_document_service(doc),
                       client_id=client)
    return c


def _text_channel(c):
    return c.runtime.get_datastore("app").get_channel("t")


def _drive(c, n=5, tag="x"):
    ds = c.runtime.datastores.get("app") or \
        c.runtime.create_datastore("app")
    if "t" not in ds.channels:
        ds.create_channel("sharedstring", "t")
    t = _text_channel(c)
    for i in range(n):
        t.insert_text(0, f"{tag}{i}.")
        c.flush()
    return t.get_text()


# ----------------------------------------------------------------------
# lease + fence


def test_lease_acquire_bumps_epoch_and_refuses_live_contender():
    clock = _Clock()
    fence = EpochFence()
    lease = SequencerLease(fence, ttl=1.0, clock=clock)
    assert lease.acquire("a") == 1
    with pytest.raises(LeaseHeldError):
        lease.acquire("b")
    clock.t += 1.1  # TTL lapses, nobody renewed
    assert lease.expired()
    assert lease.acquire("b") == 2
    assert fence.epoch == 2


def test_lease_renew_extends_and_refuses_deposed_caller():
    clock = _Clock()
    fence = EpochFence()
    lease = SequencerLease(fence, ttl=1.0, clock=clock)
    epoch_a = lease.acquire("a")
    clock.t += 0.9
    assert lease.renew("a", epoch_a) is True
    clock.t += 0.9  # inside the renewed window
    assert not lease.expired()
    clock.t += 0.2
    epoch_b = lease.acquire("b")
    # the deposed holder's renewal is refused without consulting the
    # chaos site (it is not a fault — the grant simply moved on)
    assert lease.renew("a", epoch_a) is False
    assert lease.renew("b", epoch_b) is True


def test_lease_renewal_drop_and_spurious_expiry_faults():
    clock = _Clock()
    lease = SequencerLease(EpochFence(), ttl=1.0, clock=clock)
    epoch = lease.acquire("a")
    site = PLANE.site("repl.lease_expire")
    site.push(KIND_DROP, 1)
    deadline = lease.expires_at
    assert lease.renew("a", epoch) is False
    assert lease.expires_at == deadline, (
        "a dropped renewal must leave the TTL running, not reset it")
    from fluidframework_tpu.qos.faults import KIND_ERROR

    site.push(KIND_ERROR, 1)
    assert lease.renew("a", epoch) is False
    assert lease.expired(), (
        "the error fault models the lease service lapsing the grant "
        "NOW — the split-brain trigger")


def test_fence_counts_and_raises_on_stale_epoch():
    fence = EpochFence()
    fence.advance()
    before = obs_metrics.REGISTRY.flat().get(
        "sequencer_fenced_writes_total", 0)
    fence.check(1)  # current epoch: fine
    fence.advance()
    with pytest.raises(FencedWriteError):
        fence.check(1)
    assert obs_metrics.REGISTRY.flat()[
        "sequencer_fenced_writes_total"] == before + 1


# ----------------------------------------------------------------------
# follower replica


def _msg(seq, v=0):
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    return SequencedMessage(
        client_id="w", sequence_number=seq,
        minimum_sequence_number=0, client_sequence_number=seq,
        reference_sequence_number=0, type=MessageType.OPERATION,
        contents={"v": v}, timestamp=0.0)


def test_follower_append_is_contiguous_and_durable(tmp_path):
    f = FollowerReplica(str(tmp_path / "n1"), "n1")
    f.append_durable("d", 1, _msg(1))
    f.append_durable("d", 1, _msg(2))
    assert f.head("d") == 2
    with pytest.raises(AssertionError):
        f.append_durable("d", 1, _msg(4))  # gap refused
    # durable: a fresh replica over the same dir resumes the head
    f.close()
    f2 = FollowerReplica(str(tmp_path / "n1"), "n1")
    assert f2.head("d") == 2
    assert [m.sequence_number for m in f2.read_log("d")] == [1, 2]


def test_follower_lag_buffer_flushes_contiguous_prefix_only(tmp_path):
    f = FollowerReplica(str(tmp_path / "n1"), "n1")
    f.append_durable("d", 1, _msg(1))
    f.buffer_lag("d", 1, _msg(3))  # op 2 never arrived (dropped)
    f.buffer_lag("d", 1, _msg(4))
    assert f.flush_lag("d") == 0
    assert f.head("d") == 1 and f.lag_depth() == 2, (
        "a gapped buffer must stay buffered, not tear a hole in the "
        "contiguous log")
    f.sync_from("d", [_msg(2)])  # catch-up supplies the middle
    assert f.flush_lag("d") == 2
    assert f.head("d") == 4 and f.lag_depth() == 0


def test_follower_refuses_stale_epoch(tmp_path):
    f = FollowerReplica(str(tmp_path / "n1"), "n1")
    f.append_durable("d", 2, _msg(1))
    with pytest.raises(FencedWriteError):
        f.append_durable("d", 1, _msg(2))
    with pytest.raises(FencedWriteError):
        f.buffer_lag("d", 1, _msg(2))


def test_follower_torn_tail_discarded_on_restart(tmp_path):
    f = FollowerReplica(str(tmp_path / "n1"), "n1")
    for s in (1, 2, 3):
        f.append_durable("d", 1, _msg(s))
    f.close()
    path = tmp_path / "n1" / "d" / "ops.jsonl"
    lines = path.read_bytes().splitlines(keepends=True)
    path.write_bytes(b"".join(lines[:-1])
                     + lines[-1][: len(lines[-1]) // 2])
    f2 = FollowerReplica(str(tmp_path / "n1"), "n1")
    assert f2.head("d") == 2, (
        "the torn tail op never acked, so discarding it is exact")
    # and the log was rewritten whole: appending works again
    f2.append_durable("d", 1, _msg(3, v=9))
    f2.close()
    rows = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [r["sequenceNumber"] for r in rows] == [1, 2, 3]


# ----------------------------------------------------------------------
# the group: barrier, committed watermark, failover


def test_every_append_is_quorum_durable_before_return(tmp_path):
    g, _ = _group(tmp_path)
    c = _load_writer(g)
    _drive(c, 3)
    doc_head = g.server.get_orderer("doc").op_log.last_seq
    assert g.committed("doc") == doc_head
    # quorum=2 of 3: at least one follower must hold EVERY op
    assert max(f.head("doc") for f in g.followers) == doc_head
    c.close()


def test_lag_deferred_follower_trails_but_quorum_holds(tmp_path):
    g, _ = _group(tmp_path)
    c = _load_writer(g)
    _drive(c, 2)
    # defer the next TWO offers: one per follower for one append —
    # the barrier must then BLOCK and force-sync one of them
    PLANE.site("repl.lag").push(KIND_DEFER, 2)
    _text_channel(c).insert_text(0, "L.")
    c.flush()
    head = g.server.get_orderer("doc").op_log.last_seq
    assert g.committed("doc") == head
    heads = sorted(f.head("doc") for f in g.followers)
    assert heads[-1] == head, "quorum requires one durable follower"
    assert g.max_lag_observed > 0
    c.close()


def test_dropped_ack_catches_up_on_next_append(tmp_path):
    g, _ = _group(tmp_path)
    c = _load_writer(g)
    _drive(c, 2)
    # drop both attempts (first + retry) for ONE follower's next offer
    PLANE.site("repl.append_ack").push(KIND_DROP, 2)
    _text_channel(c).insert_text(0, "D.")
    c.flush()
    _text_channel(c).insert_text(0, "E.")
    c.flush()
    head = g.server.get_orderer("doc").op_log.last_seq
    # the clean second append triggered catch-up: both followers whole
    assert [f.head("doc") for f in g.followers] == [head, head]
    c.close()


def test_failover_resumes_ticketing_at_replicated_head(tmp_path):
    g, clock = _group(tmp_path)
    c = _load_writer(g)
    final = _drive(c, 5)
    before = obs_metrics.REGISTRY.flat().get(
        "sequencer_failovers_total", 0)
    head = g.server.get_orderer("doc").op_log.last_seq
    g.kill_leader()
    clock.t += 1.0
    g.failover()
    assert g.epoch == 2 and g.leader_id in ("node-1", "node-2")
    assert obs_metrics.REGISTRY.flat()[
        "sequencer_failovers_total"] == before + 1
    # the promoted orderer resumes at EXACTLY the replicated head
    orderer = g.server.get_orderer("doc")
    assert orderer.sequencer.sequence_number == orderer.op_log.last_seq
    assert orderer.op_log.last_seq >= head
    r = _load_writer(g, client="r")
    assert _text_channel(r).get_text() == final
    # and new writes sequence contiguously on the new leader
    _text_channel(r).insert_text(0, "post.")
    r.flush()
    assert _text_channel(r).get_text() == "post." + final
    r.close()


def test_promotion_under_lag_lands_on_exact_head(tmp_path):
    g, clock = _group(tmp_path)
    c = _load_writer(g)
    _drive(c, 3)
    PLANE.site("repl.lag").push(KIND_DEFER, 4)
    final = _drive(c, 2, tag="z")
    laggard = g.laggiest_follower()
    head = g.server.get_orderer("doc").op_log.last_seq
    assert laggard.head("doc") < head, "the kill must catch real lag"
    g.kill_leader()
    clock.t += 1.0
    g.failover(candidate=laggard)  # promote the LAGGIEST on purpose
    orderer = g.server.get_orderer("doc")
    assert orderer.op_log.last_seq == head, (
        "flush + anti-entropy must land the laggard on the exact "
        "replicated head before it serves")
    r = _load_writer(g, client="r")
    assert _text_channel(r).get_text() == final
    r.close()


def test_deposed_leader_is_fenced_on_write_and_read(tmp_path):
    g, clock = _group(tmp_path)
    c = _load_writer(g)
    _drive(c, 3)
    c.close()  # a close after deposition would itself be fenced
    old_server = g.server
    g.lease.force_expire(reason="test")
    g.failover()
    # writes through the old leader refuse BEFORE consuming seqs
    orderer = old_server.documents["doc"]
    seq_before = orderer.sequencer.sequence_number
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )

    with pytest.raises(FencedWriteError):
        orderer.submit("w", DocumentMessage(
            client_sequence_number=99, reference_sequence_number=0,
            type=MessageType.OPERATION, contents={}))
    assert orderer.sequencer.sequence_number == seq_before
    with pytest.raises(FencedWriteError):
        old_server.connect("doc", "z", on_message=lambda m: None)
    # the deposed read path refuses too: its view may disagree with
    # the order the new leader is minting
    with pytest.raises(FencedWriteError):
        old_server.read_ops("doc", 0)


def test_deposed_teardown_does_not_detonate(tmp_path):
    """Session teardown on a DEPOSED node (a transport death during
    the deposed-race window runs close() -> conn.disconnect() ->
    orderer.disconnect) must NOT raise through the cleanup path: the
    leave a fenced node sequences could never reach a client anyway.
    Joins/submits still refuse loudly — only teardown is absorbed."""
    g, clock = _group(tmp_path)
    msgs = []
    conn = g.server.connect("doc", "w", on_message=msgs.append)
    g.lease.force_expire(reason="test")
    g.failover()
    conn.disconnect()  # must not raise
    # joins still refuse loudly, and the fence context names the
    # refused operation truthfully (was mislabeled "submit")
    from fluidframework_tpu.protocol.messages import ClientDetail

    with pytest.raises(FencedWriteError, match="'op': 'connect'"):
        conn._orderer.connect(ClientDetail("z"))


def test_second_failover_shrinks_quorum_and_still_serves(tmp_path):
    g, clock = _group(tmp_path)
    c = _load_writer(g)
    final = _drive(c, 3)
    g.kill_leader()
    clock.t += 1.0
    g.failover()
    r = _load_writer(g, client="r1")
    final = "a." + final
    _text_channel(r).insert_text(0, "a.")
    r.flush()
    r.close()
    g.kill_leader()
    clock.t += 1.0
    g.failover()
    assert g.quorum == 1 + len(g.followers) <= 2
    r2 = _load_writer(g, client="r2")
    assert _text_channel(r2).get_text() == final
    r2.close()


def test_summary_truncation_clamped_to_replication_floor(tmp_path):
    g, _ = _group(tmp_path)
    c = _load_writer(g)
    _drive(c, 3)
    PLANE.site("repl.lag").push(KIND_DEFER, 4)
    _drive(c, 2, tag="q")
    floor = g.replication_floor("doc")
    head = g.server.get_orderer("doc").op_log.last_seq
    assert floor < head
    log = g.server.get_orderer("doc").op_log
    log.truncate_below(head)  # a summary ack would ask for this
    remaining = [m.sequence_number for m in log.read(0)]
    assert remaining and remaining[0] == floor + 1, (
        "truncation must never outrun the laggiest follower — the "
        "leader log is its catch-up source")
    c.close()


def test_group_metrics_registered_and_move(tmp_path):
    g, clock = _group(tmp_path)
    flat = obs_metrics.REGISTRY.flat()
    assert flat.get('repl_followers{partition="docs"}') == 2
    assert flat.get("repl_epoch", 0) >= 1
    c = _load_writer(g)
    _drive(c, 2)
    c.close()
    g.kill_leader()
    clock.t += 1.0
    g.failover()
    flat = obs_metrics.REGISTRY.flat()
    assert flat['repl_followers{partition="docs"}'] == 1


def test_group_refuses_followerless_and_unsatisfiable_quorum(tmp_path):
    with pytest.raises(ValueError):
        ReplicatedSequencerGroup(str(tmp_path / "a"), n_followers=0)
    with pytest.raises(ValueError):
        ReplicatedSequencerGroup(str(tmp_path / "b"), n_followers=1,
                                 quorum=3)


def test_default_quorum_is_a_strict_majority(tmp_path):
    """For EVEN group sizes too: 4 nodes need 3 acks — at quorum 2,
    losing leader + the one acked follower (a minority) would lose a
    client-acked op that anti-entropy can never recover."""
    for n_followers, want in ((1, 2), (2, 2), (3, 3), (4, 3), (5, 4)):
        g = ReplicatedSequencerGroup(
            str(tmp_path / f"g{n_followers}"),
            n_followers=n_followers)
        assert g.quorum == want, (n_followers, g.quorum)
        assert 2 * g.quorum > 1 + n_followers, "strict majority"


def test_failover_refused_while_lease_live(tmp_path):
    g, clock = _group(tmp_path)
    c = _load_writer(g)
    _drive(c, 1)  # renews on the replication heartbeat
    with pytest.raises(LeaseHeldError):
        g.failover()
    c.close()


# ----------------------------------------------------------------------
# partition tolerance: the deadline-bounded quorum barrier, degraded
# mode, membership lifecycle, rejoin, scrubbing


def _net_group(tmp_path, **kw):
    """Group on a manual clock with a NetworkTopology and a sleep
    that ADVANCES the clock — the barrier's deadline wait terminates
    deterministically instead of spinning forever."""
    clock = _Clock()
    net = NetworkTopology()
    kw.setdefault("n_followers", 2)
    kw.setdefault("quorum_timeout_s", 0.2)
    kw.setdefault("retry_interval_s", 0.05)
    g = ReplicatedSequencerGroup(
        str(tmp_path), clock=clock, network=net,
        sleep=lambda dt: setattr(clock, "t", clock.t + dt), **kw)
    return g, clock, net


def test_vanished_follower_set_cannot_hang_a_submitter(tmp_path):
    """THE regression the deadline exists for: with every follower
    across a partition, a submit must come back as a RETRIABLE
    unavailable nack within the configured deadline on the manual
    clock — never hang in the quorum wait — and the refused op must
    be fully unwound (log, durable file, sequencer)."""
    from fluidframework_tpu.qos.policy import REASON_UNAVAILABLE

    g, clock, net = _net_group(tmp_path)
    c = _load_writer(g)
    c._backoff_clock = clock  # throttle backoff on the manual clock
    final = _drive(c, 2)
    orderer = g.server.get_orderer("doc")
    head = orderer.op_log.last_seq
    seq_before = orderer.sequencer.sequence_number
    net.partition([["node-0"], ["node-1", "node-2"]])
    t0 = clock.t
    nacks = []
    c.on("nack", nacks.append)
    _text_channel(c).insert_text(0, "LOST.")
    c.flush()  # must RETURN (nack), not hang
    assert nacks, "the refused write must surface as a nack"
    nack = nacks[0]
    assert nack.retry_after_seconds and nack.retry_after_seconds > 0
    assert nack.shed_class == REASON_UNAVAILABLE
    # the discovery cost exactly one deadline on the injected clock
    assert clock.t - t0 <= g.quorum_timeout_s + 0.01 + 0.3
    assert g.degraded and g.metrics["degraded"].value == 1
    # full unwind: nothing leaked into the log, the durable file or
    # the sequencer — the op stays with its submitter
    assert orderer.op_log.last_seq == head
    assert orderer.sequencer.sequence_number == seq_before
    rows = [json.loads(ln) for ln in open(os.path.join(
        str(tmp_path), "node-0", "doc", "ops.jsonl"))]
    assert rows[-1]["sequenceNumber"] == head
    # later submits fast-nack off the CACHED verdict: no more
    # deadline waits (probed at the orderer — the client itself is
    # down and backing off, exactly as the nack told it to)
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )

    t1 = clock.t
    nack2 = orderer.submit("probe", DocumentMessage(
        client_sequence_number=1,
        reference_sequence_number=orderer.op_log.last_seq,
        type=MessageType.OPERATION, contents={}))
    assert nack2 is not None and \
        nack2.shed_class == REASON_UNAVAILABLE
    assert clock.t == t1, "a latched verdict must not pay the wait"
    # a refused reconnect surfaces the retriable error to the driver
    with pytest.raises(QuorumUnavailableError):
        g.server.connect("doc", "z", on_message=lambda m: None)
    # reads stay served, clamped at the committed watermark
    assert [m.sequence_number for m in g.server.read_ops("doc", 0)][-1] \
        == g.committed("doc")
    # ALSO-LOST lands while the client is down: pending local state
    _text_channel(c).insert_text(0, "ALSO-LOST.")
    # heal: the next join probes, exits degraded, and the pending
    # ops converge through the normal reconnect/resubmit path
    net.heal()
    clock.t += 2.0  # the nack backoff window passes
    c.flush()  # reconnect-on-nack replays the pending edits
    assert not g.degraded
    r = _load_writer(g, client="r")
    assert "LOST." in _text_channel(r).get_text()
    assert "ALSO-LOST." in _text_channel(r).get_text()
    assert _text_channel(r).get_text().endswith(final)
    assert g.metrics["unavailable"].value >= 2
    assert g.metrics["degraded_s"].value > 0
    c.close()
    r.close()


def test_lease_isolation_browns_out_until_heal(tmp_path):
    """The lease service in its own island: replication works but
    leadership cannot be proven past the TTL — writes refuse with
    the retriable nack (read-only brownout), and the first renewal
    after the heal resumes acks with no election."""
    g, clock, net = _net_group(tmp_path, lease_ttl=0.3)
    c = _load_writer(g)
    c._backoff_clock = clock
    _drive(c, 2)
    epoch = g.epoch
    net.partition([["node-0", "node-1", "node-2"], []],
                  lease_island=1)
    clock.t += 0.4  # TTL lapses; renewals are lost across the split
    assert g.lease.expired()
    nacks = []
    c.on("nack", nacks.append)
    _text_channel(c).insert_text(0, "B.")
    c.flush()
    assert nacks and g.degraded
    assert g.degraded_reason == "lease_unreachable"
    # elections are impossible from an isolated island
    with pytest.raises(LeaseUnreachableError):
        g.lease.acquire("node-1")
    net.heal()
    clock.t += 1.0  # the nack backoff window passes
    c.flush()
    assert not g.degraded
    assert g.epoch == epoch, "no election: same leader, same epoch"
    r = _load_writer(g, client="r")
    assert "B." in _text_channel(r).get_text()
    c.close()
    r.close()


def test_membership_shrinks_on_grace_and_grows_on_rejoin(tmp_path):
    """A follower unseen past the grace TTL detaches (quorum
    recomputes over the remaining set); rejoin() re-admits it behind
    the epoch fence with a bit-equal replicated head."""
    g, clock, net = _net_group(tmp_path, membership_grace_s=0.3)
    c = _load_writer(g)
    _drive(c, 2)
    net.partition([["node-0", "node-1"], ["node-2"]])
    for i in range(8):
        clock.t += 0.1
        _text_channel(c).insert_text(0, f"g{i}.")
        c.flush()
    assert [f.node_id for f in g.followers] == ["node-1"]
    assert "node-2" in g.detached
    assert g.quorum == 2
    head = g.server.get_orderer("doc").op_log.last_seq
    net.heal()
    f = g.rejoin("node-2")
    assert [x.node_id for x in g.followers] == ["node-1", "node-2"]
    assert g.quorum == 2
    assert f.head("doc") == g.committed("doc"), (
        "rejoin must land on the committed replicated head")
    assert f.max_epoch_seen == g.fence.epoch
    assert g.metrics["rejoins"].value == 1
    # and the rejoined follower partakes in the next quorum
    _text_channel(c).insert_text(0, "post.")
    c.flush()
    assert f.head("doc") == head + 1 or f.head("doc") == \
        g.server.get_orderer("doc").op_log.last_seq
    c.close()


def test_wiped_follower_rejoins_bit_equal_from_peer(tmp_path):
    """A crashed-AND-wiped follower (dir deleted) resyncs its whole
    history from a surviving full-history peer — byte-equal records,
    fresh crcs, exact head."""
    import shutil

    g, clock, net = _net_group(tmp_path)
    c = _load_writer(g)
    _drive(c, 4)
    victim = g.followers[1]
    victim._heads.clear()
    victim._lag.clear()
    root = g.detach(victim.node_id, origin="wipe")
    shutil.rmtree(root)
    assert g.quorum == 2
    f = g.rejoin("node-2")
    peer = g.followers[0]
    assert f.head("doc") == peer.head("doc") > 0
    assert [m.sequence_number for m in f.read_log("doc")] == \
        [m.sequence_number for m in peer.read_log("doc")]
    # bit-equal replicated head: same records, verified crcs
    rows_f = [json.loads(ln) for ln in open(
        os.path.join(f.root, "doc", "ops.jsonl"))]
    rows_p = [json.loads(ln) for ln in open(
        os.path.join(peer.root, "doc", "ops.jsonl"))]
    strip = lambda r: {k: v for k, v in r.items()  # noqa: E731
                       if k not in ("_crc", "traces")}
    assert [strip(r) for r in rows_f] == [strip(r) for r in rows_p]
    c.close()


def test_scrub_read_repairs_bit_flip_from_peer(tmp_path):
    """A mid-file bit flip on one follower's log (parseable JSON,
    wrong crc) is detected and read-repaired from a quorum peer,
    loudly counted; with NO surviving intact copy it raises."""
    from fluidframework_tpu.obs import metrics as om
    from fluidframework_tpu.service.storage import CorruptRecordError

    g, clock, net = _net_group(tmp_path)
    c = _load_writer(g)
    final = _drive(c, 4)
    c.close()
    target = g.followers[0]
    path = os.path.join(target.root, "doc", "ops.jsonl")
    lines = open(path).readlines()
    row = json.loads(lines[1])
    row["contents"] = {"rot": True}  # stale _crc kept: crc mismatch
    lines[1] = json.dumps(row) + "\n"
    fh = target._fhs.pop("doc", None)
    if fh is not None:
        fh.close()
    open(path, "w").writelines(lines)
    before = om.REGISTRY.flat().get(
        'storage_scrub_repairs_total{file="repl"}', 0)
    assert g.scrub() == 1
    assert om.REGISTRY.flat()[
        'storage_scrub_repairs_total{file="repl"}'] == before + 1
    # the repaired replica is whole again: a fresh load serves it
    target.close()
    f2 = FollowerReplica(target.root, target.node_id)
    assert [m.sequence_number for m in f2.read_log("doc")] == \
        list(range(1, f2.head("doc") + 1))
    f2.close()
    # no surviving peer: corrupt the SAME record everywhere
    for node in [g.followers[1]]:
        p2 = os.path.join(node.node_id and node.root, "doc",
                          "ops.jsonl")
        lns = open(p2).readlines()
        r2 = json.loads(lns[1])
        r2["contents"] = {"rot": 2}
        lns[1] = json.dumps(r2) + "\n"
        fh = node._fhs.pop("doc", None)
        if fh is not None:
            fh.close()
        open(p2, "w").writelines(lns)
    # and truncate the leader's log above the record so it cannot
    # supply the copy either
    g.server.get_orderer("doc").op_log.truncate_below(99)
    # re-corrupt the first follower too
    lines = open(path).readlines()
    row = json.loads(lines[1])
    row["contents"] = {"rot": 3}
    lines[1] = json.dumps(row) + "\n"
    g.followers[0].close()
    open(path, "w").writelines(lines)
    with pytest.raises(CorruptRecordError, match="no surviving peer"):
        g.scrub()
    assert final  # silence the unused warning


def test_degraded_reprobe_is_paced_without_a_topology(tmp_path):
    """Production has NO NetworkTopology (reachability is only
    discoverable by trying): after a quorum timeout, later writes
    must fast-nack off the cached verdict, with exactly ONE paced
    probe write per timeout window allowed through to the barrier —
    whose quorum success is what exits degraded."""
    clock = _Clock()
    g = ReplicatedSequencerGroup(
        str(tmp_path), clock=clock, n_followers=2,
        quorum_timeout_s=0.2, retry_interval_s=0.05,
        sleep=lambda dt: setattr(clock, "t", clock.t + dt))
    c = _load_writer(g)
    _drive(c, 2)
    # the barrier timed out somewhere (simulated entry: in-process
    # followers cannot actually vanish without a topology)
    g._enter_degraded("quorum_timeout")
    with pytest.raises(QuorumUnavailableError):
        g.ensure_available("doc")  # inside the window: fast-nack
    clock.t += 0.25  # the probe window opens
    g.ensure_available("doc")  # the ONE paced probe passes the gate
    with pytest.raises(QuorumUnavailableError):
        g.ensure_available("doc")  # next window not open yet
    # the probe write runs the barrier; quorum success exits degraded
    clock.t += 0.25
    _text_channel(c).insert_text(0, "probe.")
    c.flush()
    assert not g.degraded
    assert g.metrics["degraded_s"].value > 0
    c.close()


def test_owed_leave_resets_csn_watermark_on_rejoin(tmp_path):
    """A leave absorbed during the degraded window is OWED: the
    client's next join sequences it first, so the fresh-csn resubmit
    stream is never swallowed by the duplicate dedupe (the netsplit
    differential's silent-divergence bug, pinned in isolation)."""
    g, clock, net = _net_group(tmp_path)
    msgs = []
    conn = g.server.connect("doc", "w", on_message=msgs.append)
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )

    orderer = g.server.get_orderer("doc")

    def op(csn):
        return DocumentMessage(
            client_sequence_number=csn,
            reference_sequence_number=orderer.op_log.last_seq,
            type=MessageType.OPERATION, contents={"v": csn})

    assert conn._orderer.submit("w", op(1)) is None
    assert conn._orderer.submit("w", op(2)) is None
    net.partition([["node-0"], ["node-1", "node-2"]])
    # the leave cannot replicate: absorbed + owed
    conn.disconnect()
    assert "w" in orderer._owed_leaves
    net.heal()
    # rejoin settles the owed leave FIRST (watermark reset), so the
    # fresh stream's csn 1 sequences instead of deduping silently
    conn2 = g.server.connect("doc", "w", on_message=msgs.append)
    assert "w" not in orderer._owed_leaves
    assert conn2._orderer.submit("w", op(1)) is None
    ops = [m for m in orderer.op_log.read(0)
           if m.type == MessageType.OPERATION]
    assert [m.client_sequence_number for m in ops] == [1, 2, 1], (
        "the post-rejoin csn-1 op must SEQUENCE, not silently dedupe")
    kinds = [m.type for m in orderer.op_log.read(0)]
    assert kinds.count(MessageType.CLIENT_LEAVE) == 1


# ----------------------------------------------------------------------
# fleet observability (PR13): cross-node trace propagation + the
# injectable-registry separation fix


def test_replicated_op_shows_repl_hops_in_breakdown_and_otlp(tmp_path):
    """The acceptance criterion: an op acked through the replicated
    plane shows the repl hops — fence_check, forward, one
    follower_append per appending follower, quorum_ack — as its own
    breakdown rows between the sequencer/scriptorium hops and the
    fanout, and the OTLP export round-trips bit-exact."""
    g = ReplicatedSequencerGroup(str(tmp_path))  # wall clock: real
    c = _load_writer(g)
    _drive(c, 3)
    entry = c.op_trace()
    names = [h["hop"] for h in entry["hops"]]
    for hop in ("repl:fence_check", "repl:forward",
                "repl:follower_append", "repl:quorum_ack"):
        assert hop in names, names
    assert names.count("repl:follower_append") == 2, (
        "both followers appended on the clean path")
    order = [names.index(h) for h in (
        "sequencer:ticket", "scriptorium:write", "repl:fence_check",
        "repl:forward", "repl:quorum_ack", "broadcaster:fanout",
        "client:ack")]
    assert order == sorted(order), names
    # quorum wait is its own hop AND its own histogram (the ledger
    # bridge feeds repl_quorum_wait_ms from the forward->quorum_ack
    # pair), no longer silently inflating the sequencer-ticket hop
    flat = obs_metrics.REGISTRY.flat()
    assert flat["repl_quorum_wait_ms_count"] >= 3
    # OTLP: repl hops become child spans; the round trip stays exact
    from fluidframework_tpu.obs.spans import op_to_otlp, otlp_to_hops

    doc = op_to_otlp(entry["traces"], document_id="doc",
                     client_id="w", csn=entry["clientSequenceNumber"])
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    span_names = [s["name"] for s in spans]
    assert "repl:forward" in span_names
    assert "repl:quorum_ack" in span_names
    assert otlp_to_hops(doc) == sorted(
        entry["traces"], key=lambda t: t.timestamp)
    assert doc == op_to_otlp(
        otlp_to_hops(doc), document_id="doc", client_id="w",
        csn=entry["clientSequenceNumber"]), "re-export not byte-equal"
    c.close()


def test_anti_entropy_counter_moves_on_catch_up(tmp_path):
    from fluidframework_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry(node="lead")
    clock = _Clock()
    g = ReplicatedSequencerGroup(str(tmp_path), clock=clock,
                                 registry=reg)
    c = _load_writer(g)
    _drive(c, 2)
    assert reg.flat()["repl_anti_entropy_ops_total"] == 0
    # drop one follower's acks twice (first + retry): the next clean
    # append catches it up from the leader's log — anti-entropy
    PLANE.site("repl.append_ack").push(KIND_DROP, 2)
    _text_channel(c).insert_text(0, "D.")
    c.flush()
    _text_channel(c).insert_text(0, "E.")
    c.flush()
    assert reg.flat()["repl_anti_entropy_ops_total"] >= 1
    c.close()


def test_follower_registries_do_not_double_count_into_process(
        tmp_path):
    """The satellite fix, pinned: leader and follower fence series
    land on their OWN injected registries; the process-wide registry
    sees none of it (in-process multi-node tests used to double-count
    every node into one aggregate). Default construction (no
    registry) keeps the process-wide behaviour — production is one
    node per process."""
    from fluidframework_tpu.obs.metrics import MetricsRegistry

    lead = MetricsRegistry(node="node-0")
    f1 = MetricsRegistry(node="node-1")
    f2 = MetricsRegistry(node="node-2")
    clock = _Clock()
    before = obs_metrics.REGISTRY.flat().get(
        "sequencer_fenced_writes_total", 0)
    g = ReplicatedSequencerGroup(
        str(tmp_path), clock=clock, registry=lead,
        follower_registries=[f1, f2])
    c = _load_writer(g)
    _drive(c, 2)
    c.close()
    # a follower-side fencing-token refusal counts on the FOLLOWER's
    # registry only
    follower = g.followers[0]
    follower.note_epoch(99)
    with pytest.raises(FencedWriteError):
        follower.append_durable("doc", 1, _msg(
            follower.head("doc") + 1))
    assert f1.flat()["sequencer_fenced_writes_total"] == 1
    assert f2.flat()["sequencer_fenced_writes_total"] == 0
    # a deposed-leader refusal counts on the GROUP's registry only
    g.lease.force_expire(reason="test")
    g.failover()
    with pytest.raises(FencedWriteError):
        g.fence.check(1)
    assert lead.flat()["sequencer_fenced_writes_total"] == 1
    # and the process-wide registry never moved
    assert obs_metrics.REGISTRY.flat().get(
        "sequencer_fenced_writes_total", 0) == before
    # federation puts the fleet total back together
    from fluidframework_tpu.obs.federation import FederatedView

    view = FederatedView(clock=clock)
    for node, reg in (("node-0", lead), ("node-1", f1),
                      ("node-2", f2)):
        view.add_registry(node, reg)
    totals = view.counter_totals()
    assert totals["sequencer_fenced_writes_total"] == 2
    assert totals["sequencer_failovers_total"] == 1
    # gauges stay per-node under the node label
    merged = view.refresh()
    assert '{node="node-0"}' in merged["repl_epoch"]["values"]


def test_group_timeline_records_the_failover_chain(tmp_path):
    from fluidframework_tpu.obs.metrics import MetricsRegistry
    from fluidframework_tpu.obs.timeline import FleetTimeline

    clock = _Clock()
    tl = FleetTimeline(clock=clock, registry=MetricsRegistry())
    g = ReplicatedSequencerGroup(str(tmp_path), clock=clock,
                                 timeline=tl)
    c = _load_writer(g)
    _drive(c, 3)
    c.close()
    kinds = [e.kind for e in tl.events()]
    assert kinds[0] == "lease_grant" and kinds[1] == "epoch_advance"
    assert "lease_renew" in kinds  # the replication heartbeat
    g.kill_leader()
    tl.record("leader_kill", node="node-0", mode="clean")
    clock.t += 1.0
    g.failover()
    clock.t += 0.05
    tl.record("first_ack", node=g.leader_id)
    phases = tl.failover_phases()
    assert phases is not None
    assert phases["detection_s"] == pytest.approx(1.0)
    assert phases["first_ack_s"] == pytest.approx(0.05)
    assert phases["total_s"] == pytest.approx(1.05)
    # the causal chain is ordered: expire -> epoch -> promotion
    tail = [e.kind for e in tl.events()
            if e.kind in ("lease_expire", "epoch_advance",
                          "promotion", "leader_kill")]
    assert tail[-4:] == ["leader_kill", "lease_expire",
                         "epoch_advance", "promotion"]


# ----------------------------------------------------------------------
# O(1) sequencer fast-forward (promotion used to pay O(log))


def test_sequencer_fast_forward_equals_noop_walk():
    from fluidframework_tpu.protocol.messages import MessageType
    from fluidframework_tpu.service.sequencer import DocumentSequencer

    a = DocumentSequencer("d")
    b = DocumentSequencer("d")
    for _ in range(7):
        b.system_message(MessageType.NO_OP, None)
    a.fast_forward(7)
    assert a.sequence_number == b.sequence_number == 7
    assert a.minimum_sequence_number == b.minimum_sequence_number
    a.fast_forward(3)  # never regresses
    assert a.sequence_number == 7


# ----------------------------------------------------------------------
# partitioned-plane counterparts


def test_replicated_queue_promotes_follower_root(tmp_path):
    from fluidframework_tpu.service.partitioning import (
        FileOrderingQueue,
        ReplicatedFileOrderingQueue,
    )

    roots = [str(tmp_path / n) for n in ("lead", "f1", "f2")]
    q = ReplicatedFileOrderingQueue(roots[0], 2, roots[1:])
    assert q.fsync and all(f.fsync for f in q.followers), (
        "the quorum claim is only as strong as each node's own "
        "write barrier")
    for i in range(6):
        q.produce(i % 2, f"doc{i % 2}", {"v": i})
    q.commit(0, 1)
    q.commit(1, 2)
    # promotion anti-entropies the best follower root against every
    # peer, then resumes at the replicated head + mirrored commit
    promoted = ReplicatedFileOrderingQueue.promote(roots[1:], 2)
    assert isinstance(promoted, FileOrderingQueue)
    assert promoted.committed(0) == 1
    assert promoted.committed(1) == 2
    assert [r.payload["v"] for r in promoted.read(0, 0)] == [0, 2, 4]
    tail = [r.payload["v"] for r in promoted.read(
        0, promoted.committed(0) + 1)]
    assert tail == [4], "resume exactly past the replicated commit"


def test_replicated_queue_survives_dropped_acks(tmp_path):
    from fluidframework_tpu.service.partitioning import (
        FileOrderingQueue,
        ReplicatedFileOrderingQueue,
    )

    roots = [str(tmp_path / n) for n in ("lead", "f1", "f2")]
    q = ReplicatedFileOrderingQueue(roots[0], 1, roots[1:])
    PLANE.site("repl.append_ack").push(KIND_DROP, 4)  # both, twice
    q.produce(0, "d", {"v": 0})  # quorum must BLOCK and force-sync
    q.produce(0, "d", {"v": 1})
    heads = [FileOrderingQueue(r, 1)._counts[0] for r in roots[1:]]
    assert max(heads) == 2, "quorum needs one whole follower"
    # and promotion must land on the TRUE replicated head even when
    # the drop left one follower root lagging — anti-entropy, not
    # "serve whichever root you grabbed"
    promoted = ReplicatedFileOrderingQueue.promote(roots[1:], 1)
    assert promoted._counts[0] == 2
    assert [r.payload["v"] for r in promoted.read(0, 0)] == [0, 1]


def test_replicated_queue_and_checkpoint_fence(tmp_path):
    from fluidframework_tpu.service.partitioning import (
        ReplicatedCheckpointManager,
        ReplicatedFileOrderingQueue,
    )

    fence = EpochFence(1)
    roots = [str(tmp_path / n) for n in ("lead", "f1")]
    q = ReplicatedFileOrderingQueue(roots[0], 1, roots[1:],
                                    fence=fence, epoch=1)
    q.produce(0, "d", {"v": 0})
    ckpt = ReplicatedCheckpointManager(q, 0, fence, 1)
    ckpt.starting(0)
    ckpt.completed(0)
    assert q.committed(0) == 0
    # promotion THROUGH the shared fence IS the deposition — no
    # separate advance() for callers to forget
    ReplicatedFileOrderingQueue.promote(roots[1:], 1, fence=fence)
    with pytest.raises(FencedWriteError):
        q.produce(0, "d", {"v": 1})
    with pytest.raises(FencedWriteError):
        q.commit(0, 5)
    ckpt.starting(1)
    with pytest.raises(FencedWriteError):
        ckpt.completed(1)
    assert q.committed(0) == 0, (
        "a deposed consumer must not move the committed offset")
    # without a shared fence, fencing is explicitly OFF (a private
    # default fence would READ as protection while providing none)
    q2 = ReplicatedFileOrderingQueue(
        str(tmp_path / "lead2"), 1, [str(tmp_path / "f2")])
    assert q2.fence is None
    q2.produce(0, "d", {"v": 0})
