"""Sequence-axis sharding (SURVEY §5.7): a long document's slot slab
split across devices must produce BIT-IDENTICAL state to the
single-device executor on the same sequenced streams.

The collective path reuses fused_step through its AxisPrims seam, so
equality here pins the prefix-sum offsets, the pmin/psum point lookups,
and the ppermute boundary exchange all at once.
"""
import jax
import numpy as np
import pytest

from fluidframework_tpu.models.mergetree import MergeTreeClient
from fluidframework_tpu.ops import (
    apply_window,
    build_batch,
    encode_stream,
    extract_signature,
    extract_text,
    fetch,
    make_table,
)
from fluidframework_tpu.parallel import (
    apply_window_seq_sharded,
    make_seq_mesh,
)
from fluidframework_tpu.testing import FuzzConfig, record_op_stream


def _smoke(n, keep):
    """range(n) with every seed outside ``keep`` slow-marked — tier-1
    runs a smoke subset of the sweep, the full sweep is slow-lane."""
    return [
        s if s in keep else pytest.param(s, marks=pytest.mark.slow)
        for s in range(n)
    ]


def _streams(n_docs, base_seed, steps=120):
    cases = [
        record_op_stream(FuzzConfig(
            n_clients=3, n_steps=steps, seed=base_seed + 13 * i,
            remove_weight=0.3, annotate_weight=0.15,
        ))
        for i in range(n_docs)
    ]
    return [t for t, _ in cases], [s for _, s in cases]


def _run_both(streams, capacity, mesh):
    encs = [encode_stream(s) for s in streams]
    batch = build_batch(encs)
    table = make_table(len(encs), capacity)
    ref = fetch(apply_window(table, batch))
    shd = fetch(apply_window_seq_sharded(table, batch, mesh))
    return encs, ref, shd


def _assert_tables_equal(ref, shd):
    for key in ref:
        np.testing.assert_array_equal(
            ref[key], shd[key], err_msg=f"field {key} diverged"
        )


def test_seq_sharded_bit_identical_8way():
    mesh = make_seq_mesh(jax.devices())  # 1 doc lane x 8 seq shards
    texts, streams = _streams(2, base_seed=4001)
    encs, ref, shd = _run_both(streams, capacity=512, mesh=mesh)
    _assert_tables_equal(ref, shd)
    for d, text in enumerate(texts):
        assert extract_text(shd, encs[d], d) == text


def test_seq_sharded_2d_mesh_docs_by_seq():
    """docs x seq 2-D mesh: collectives stay inside each doc lane."""
    mesh = make_seq_mesh(jax.devices(), doc_shards=2)
    texts, streams = _streams(4, base_seed=5501, steps=100)
    encs, ref, shd = _run_both(streams, capacity=256, mesh=mesh)
    _assert_tables_equal(ref, shd)
    for d, text in enumerate(texts):
        assert extract_text(shd, encs[d], d) == text


@pytest.mark.parametrize("seed", [
    pytest.param(77, marks=pytest.mark.slow), 177,
])
def test_seq_sharded_signature_matches_oracle(seed):
    mesh = make_seq_mesh(jax.devices())
    text, stream = record_op_stream(FuzzConfig(
        n_clients=4, n_steps=160, seed=seed,
        remove_weight=0.35, annotate_weight=0.2,
    ))
    encs, ref, shd = _run_both([stream], capacity=512, mesh=mesh)
    assert extract_text(shd, encs[0], 0) == text
    obs = MergeTreeClient("observer")
    obs.start_collaboration("observer")
    for msg in stream:
        obs.apply_msg(msg)
    from fluidframework_tpu.ops.host_bridge import interned_signature

    assert extract_signature(shd, encs[0], 0) == interned_signature(
        obs, encs[0]
    )


def test_seq_sharded_overflow_flag_consistent():
    """Global capacity = sum of shard capacities: a stream that fits in
    512 total slots must not overflow even though each shard holds only
    64, and the overflow decision must match the unsharded table."""
    mesh = make_seq_mesh(jax.devices())
    _, streams = _streams(1, base_seed=9100, steps=200)
    encs, ref, shd = _run_both(streams, capacity=512, mesh=mesh)
    assert not shd["overflow"].any()
    np.testing.assert_array_equal(ref["overflow"], shd["overflow"])


def test_seq_sharded_rejects_indivisible_capacity():
    mesh = make_seq_mesh(jax.devices())
    _, streams = _streams(1, base_seed=1)
    encs = [encode_stream(s) for s in streams]
    batch = build_batch(encs)
    table = make_table(1, 500)
    with pytest.raises(ValueError, match="not divisible"):
        apply_window_seq_sharded(table, batch, mesh)


def test_seq_sharded_rejects_single_slot_shards():
    """Shard width 1 would let the two-slot restructure shift cross
    more than one boundary (data loss) — must refuse loudly."""
    mesh = make_seq_mesh(jax.devices())
    _, streams = _streams(1, base_seed=1)
    encs = [encode_stream(s) for s in streams]
    batch = build_batch(encs)
    table = make_table(1, 8)  # 1 slot per shard on the 8-way mesh
    with pytest.raises(ValueError, match="shard width"):
        apply_window_seq_sharded(table, batch, mesh)


@pytest.mark.parametrize("seed", _smoke(20, {5, 7, 9}))
def test_seq_sharded_adversarial_fuzz(seed):
    """Heavier differential load on the collective path: more clients,
    remove/annotate storms, longer streams — every field bit-identical
    to the single-device executor (the collective prefix sums, point
    lookups, and boundary exchanges all on the hot path)."""
    mesh = make_seq_mesh(jax.devices())
    text, stream = record_op_stream(FuzzConfig(
        n_clients=6, n_steps=220, seed=seed * 73 + 11,
        remove_weight=0.35, annotate_weight=0.2,
    ))
    encs, ref, shd = _run_both([stream], capacity=1024, mesh=mesh)
    _assert_tables_equal(ref, shd)
    assert extract_text(shd, encs[0], 0) == text


def test_seq_sharded_ops_spanning_shard_boundaries():
    """Directed: removes and annotates whose ranges cross shard
    boundaries (the two-split restructure with both boundary slots in
    different shards, exercising the ppermute exchange)."""
    from fluidframework_tpu.testing import MockCollabSession

    stream = []
    s = MockCollabSession(["A", "B"], stream_log=stream)
    # build a doc whose segments straddle the 8 x 64-slot shards
    for i in range(100):
        s.do("A", "insert_text_local", 0, f"seg{i:03d}-")
    s.process_all()
    # cross-boundary range operations
    s.do("B", "remove_range_local", 50, 450)
    s.do("A", "annotate_range_local", 10, 700, {"bold": 1})
    s.do("B", "insert_text_local", 200, "XBOUNDARYX")
    s.process_all()
    expected = s.assert_converged()
    mesh = make_seq_mesh(jax.devices())
    encs, ref, shd = _run_both([stream], capacity=512, mesh=mesh)
    _assert_tables_equal(ref, shd)
    assert extract_text(shd, encs[0], 0) == expected
