"""Mesh-sharded document pool (ROADMAP item 1): pooled documents
spread across the mesh's DOC shards (parallel/mesh_pool.py), with
live hot-document migration at the settle boundary.

THE correctness pin is the route-parity differential: a scripted
hot-spot run on a multi-shard mesh — with migrations actually firing
— must serve text() and signature() bit-identical to the
never-migrated single-shard pool AND the per-client container oracle,
through grow/evict/overflow/migration interleavings, including a
migration racing an overflow-recovery rebuild (the PR2 double-apply
shape, re-pinned for cross-shard moves).
"""
import jax
import numpy as np
import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.parallel import (
    MeshShardedPool,
    make_mesh,
    make_seq_mesh,
)
from fluidframework_tpu.service import LocalServer, TpuMergeSidecar
from fluidframework_tpu.service.tpu_sidecar import (
    SeqShardedPool,
    select_pool,
)


def _open_doc(server, sidecars, doc):
    factory = LocalDocumentServiceFactory(server)
    for sc in sidecars:
        sc.subscribe(server, doc, "d", "s")
    c = Container.load(factory.create_document_service(doc),
                       client_id=f"{doc}-w")
    s = c.runtime.create_datastore("d").create_channel(
        "sharedstring", "s")
    return c, s


def _grow_into_pool(c, s, n_chunks=20):
    for i in range(n_chunks):
        s.insert_text(0, "abcdefgh")
        c.flush()
        if i % 3 == 2 and s.get_length() > 6:
            s.remove_text(2, 5)
            c.flush()


def _assert_parity(sidecars, docs, strings):
    ref = sidecars[0]
    for doc in docs:
        want = strings[doc].get_text()
        for sc in sidecars:
            assert sc.text(doc, "d", "s") == want, (
                f"text divergence on {doc}")
            assert sc.signature(doc, "d", "s") == \
                ref.signature(doc, "d", "s"), (
                    f"signature divergence on {doc}")


# ======================================================================
# route selection (ONE place: select_pool)


def test_select_pool_routes_by_mesh_axes():
    docs4 = make_mesh(jax.devices()[:4])
    assert isinstance(select_pool(docs4, 128), MeshShardedPool)
    seq = make_seq_mesh(jax.devices()[:4])  # 1 doc lane x 4 seq
    assert isinstance(select_pool(seq, 128), SeqShardedPool)
    # single-shard: a degenerate seq mesh keeps the existing seq-pool
    # path, a docs mesh gets a 1-shard mesh pool
    seq1 = make_seq_mesh(jax.devices()[:1])
    assert isinstance(select_pool(seq1, 128), SeqShardedPool)
    docs1 = make_mesh(jax.devices()[:1])
    assert isinstance(select_pool(docs1, 128), MeshShardedPool)


def test_select_pool_env_and_arg_override(monkeypatch):
    docs1 = make_mesh(jax.devices()[:1])
    seq1 = make_seq_mesh(jax.devices()[:1])
    # constructor arg wins outright
    assert isinstance(
        select_pool(seq1, 128, route="seq"), SeqShardedPool)
    # env override routes — and an override that cannot fit the mesh
    # fails in the chosen pool's own validation, never silently
    monkeypatch.setenv("FFTPU_SIDECAR_POOL", "mesh")
    assert isinstance(select_pool(docs1, 128), MeshShardedPool)
    monkeypatch.setenv("FFTPU_SIDECAR_POOL", "seq")
    with pytest.raises(ValueError, match="seq pool needs"):
        select_pool(docs1, 128)
    monkeypatch.setenv("FFTPU_SIDECAR_POOL", "warp")
    with pytest.raises(ValueError, match="FFTPU_SIDECAR_POOL"):
        select_pool(docs1, 128)
    # the CONSTRUCTOR-ARG spelling of a typo must be just as loud —
    # a route='msh' silently building the other pool is exactly the
    # silent-route-change failure select_pool exists to close
    monkeypatch.delenv("FFTPU_SIDECAR_POOL")
    with pytest.raises(ValueError, match="pool_route='msh'"):
        select_pool(seq1, 128, route="msh")


def test_select_pool_resolves_backend_default_executor(monkeypatch):
    """A single-shard docs mesh follows the executor route like the
    degenerate seq pool: select_pool resolves default_executor() (the
    mesh pool lives below service and cannot read it itself), so a
    chunked-default backend gets the chunked fast path without the
    caller passing executor."""
    monkeypatch.setenv("FFTPU_SIDECAR_EXECUTOR", "chunked")
    pool = select_pool(make_mesh(jax.devices()[:1]), 128)
    assert isinstance(pool, MeshShardedPool)
    assert pool.executor == "chunked"
    monkeypatch.setenv("FFTPU_SIDECAR_EXECUTOR", "scan")
    assert select_pool(
        make_mesh(jax.devices()[:1]), 128).executor == "scan"


def test_mesh_pool_rejects_bad_meshes():
    with pytest.raises(ValueError, match="mesh axis"):
        MeshShardedPool(make_seq_mesh(jax.devices()[:2]), 128,
                        doc_axis="absent")
    # a real seq axis is the seq pool's job
    mesh2d = make_seq_mesh(jax.devices()[:4], doc_shards=2)
    with pytest.raises(ValueError, match="documents only"):
        MeshShardedPool(mesh2d, 128)
    with pytest.raises(ValueError, match="capacity"):
        MeshShardedPool(make_mesh(jax.devices()[:2]), 8)


# ======================================================================
# the pool tier end to end (sidecar-driven, multi-shard)


def test_overgrown_docs_spread_across_shards():
    mesh = make_mesh(jax.devices()[:4])
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=8, capacity=16, max_capacity=32,
                              seq_mesh=mesh, pool_capacity=256)
    assert isinstance(sidecar._pool, MeshShardedPool)
    docs, strings = [], {}
    for i in range(4):
        doc = f"doc-{i}"
        c, s = _open_doc(server, [sidecar], doc)
        _grow_into_pool(c, s, n_chunks=60)
        docs.append(doc)
        strings[doc] = s
    sidecar.apply()
    sidecar.sync()
    assert sidecar.pooled_docs() == 4
    assert sidecar.host_mode_docs() == 0
    # placement spread: no shard hoards the pool
    assert [len(m) for m in sidecar._pool.shard_members] == [1, 1, 1, 1]
    for doc in docs:
        assert sidecar.text(doc, "d", "s") == strings[doc].get_text()


def test_mesh_pool_eviction_keeps_survivors_correct():
    """Beyond pooled capacity -> host eviction; the mesh pool's
    remaining members must keep reading/applying correctly (the
    mesh-pool variant of the seq pool's eviction regression)."""
    mesh = make_mesh(jax.devices()[:2])
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=4, capacity=16, max_capacity=32,
                              seq_mesh=mesh, pool_capacity=128)
    a_c, a_s = _open_doc(server, [sidecar], "doc-a")
    b_c, b_s = _open_doc(server, [sidecar], "doc-b")
    _grow_into_pool(a_c, a_s, n_chunks=60)
    _grow_into_pool(b_c, b_s, n_chunks=60)
    sidecar.apply()
    sidecar.sync()
    assert sidecar.pooled_docs() == 2
    for _ in range(120):
        a_s.insert_text(0, "zzzzzzzz")
        a_c.flush()
    sidecar.apply()
    sidecar.sync()
    assert sidecar.host_mode_docs() == 1       # doc-a evicted
    assert sidecar.pooled_docs() == 1          # doc-b survives
    assert sidecar.text("doc-b", "d", "s") == b_s.get_text()
    b_s.insert_text(0, "still-alive-")
    b_c.flush()
    sidecar.apply()
    sidecar.sync()
    assert sidecar.pooled_docs() == 1, "no spurious eviction"
    assert sidecar.text("doc-b", "d", "s") == b_s.get_text()
    assert sidecar.text("doc-a", "d", "s") == a_s.get_text()


# ======================================================================
# THE migration route-parity differential


def _hotspot_pair(server, n_docs=3):
    """One sidecar on a 2-shard docs mesh (migrations expected), one
    on the degenerate single-shard seq mesh (the never-migrated
    oracle), identical otherwise, same sequenced streams.
    max_capacity == capacity: every overgrown doc pools at its first
    overflow (the ladder cannot grow), like the PR2 deferred tests."""
    mesh_sc = TpuMergeSidecar(
        max_docs=6, capacity=16, max_capacity=16,
        seq_mesh=make_mesh(jax.devices()[:2]), pool_capacity=256,
    )
    seq_sc = TpuMergeSidecar(
        max_docs=6, capacity=16, max_capacity=16,
        seq_mesh=make_seq_mesh(jax.devices()[:1]), pool_capacity=256,
    )
    assert isinstance(mesh_sc._pool, MeshShardedPool)
    assert isinstance(seq_sc._pool, SeqShardedPool)
    sidecars = [mesh_sc, seq_sc]
    docs, containers, strings = [], {}, {}
    for i in range(n_docs):
        doc = f"doc-{i}"
        c, s = _open_doc(server, sidecars, doc)
        docs.append(doc)
        containers[doc], strings[doc] = c, s
    return mesh_sc, seq_sc, docs, containers, strings


def test_hotspot_migration_is_bit_exact_vs_single_shard_pool():
    """The acceptance differential: a hot-spot run that MIGRATES
    (migrations_total > 0) serves bit-identical text/signature to the
    never-migrated single-shard pool and the container oracle."""
    server = LocalServer()
    mesh_sc, seq_sc, docs, containers, strings = _hotspot_pair(server)
    # fleet observability (PR13): attach a timeline so each migration
    # lands as a causal event next to its pool:migrate hop stamp
    from fluidframework_tpu.obs.metrics import MetricsRegistry
    from fluidframework_tpu.obs.timeline import FleetTimeline

    timeline = FleetTimeline(registry=MetricsRegistry(node="pool"))
    mesh_sc._pool.timeline = timeline
    # all three docs overflow into the pool in one settle: placement
    # [doc-0, doc-2] / [doc-1] on the 2-shard mesh
    for doc in docs:
        _grow_into_pool(containers[doc], strings[doc], n_chunks=20)
    for sc in (mesh_sc, seq_sc):
        sc.apply()
        sc.sync()
    assert mesh_sc.pooled_docs() == 3
    assert seq_sc.pooled_docs() == 3
    _assert_parity([mesh_sc, seq_sc], docs, strings)

    # hot-spot doc-0; its co-resident doc-2 should migrate off the
    # hot shard within a few settles
    for _ in range(6):
        for doc in docs:
            n = 12 if doc == "doc-0" else 1
            for _ in range(n):
                strings[doc].insert_text(0, "XY")
            containers[doc].flush()
        for sc in (mesh_sc, seq_sc):
            sc.apply()
            sc.sync()
    assert mesh_sc._pool.migration_count > 0, (
        "the hot-spot run must actually migrate")
    # every migration stamped the canonical pool:migrate hop and
    # recorded a timeline event carrying the move's src/dst shards
    pool = mesh_sc._pool
    assert len(pool.migration_traces) == pool.migration_count
    assert all(t.service == "pool" and t.action == "migrate"
               for t in pool.migration_traces)
    moves = timeline.events("migration")
    assert len(moves) == pool.migration_count
    assert all(e.fields["src"] != e.fields["dst"] for e in moves)
    assert seq_sc._pool.dispatch_count > 0
    assert mesh_sc.host_mode_docs() == 0
    assert seq_sc.host_mode_docs() == 0
    _assert_parity([mesh_sc, seq_sc], docs, strings)


def test_migration_racing_overflow_recovery_rebuild():
    """The PR2 double-apply shape re-pinned for cross-shard moves:
    after a migration has moved a doc, ONE apply carries (a) deferred
    window ops for the migrated doc and (b) a fourth doc overflowing
    into the pool — the recovery rebuild replays full canonical
    streams (which already contain the deferred ops) and must subsume
    them exactly once, with the migrated placement intact."""
    server = LocalServer()
    mesh_sc, seq_sc, docs, containers, strings = _hotspot_pair(server)
    for doc in docs:
        _grow_into_pool(containers[doc], strings[doc], n_chunks=20)
    for sc in (mesh_sc, seq_sc):
        sc.apply()
        sc.sync()
    for _ in range(4):
        for doc in docs:
            n = 12 if doc == "doc-0" else 1
            for _ in range(n):
                strings[doc].insert_text(0, "XY")
            containers[doc].flush()
        for sc in (mesh_sc, seq_sc):
            sc.apply()
            sc.sync()
    assert mesh_sc._pool.migration_count > 0
    members_after_migration = [
        list(m) for m in mesh_sc._pool.shard_members
    ]

    # ONE apply: deferred traffic for the MIGRATED pool members plus
    # a new doc overflowing into the pool (admission rebuild) in the
    # same settle
    late_c, late_s = _open_doc(server, [mesh_sc, seq_sc], "doc-late")
    docs.append("doc-late")
    containers["doc-late"], strings["doc-late"] = late_c, late_s
    for doc in docs[:3]:
        for _ in range(3):
            strings[doc].insert_text(0, "AB")
        containers[doc].flush()
    for _ in range(20):
        late_s.insert_text(0, "qrstuvwx")
    late_c.flush()
    for sc in (mesh_sc, seq_sc):
        sc.apply()
        sc.sync()
    assert mesh_sc.pooled_docs() == 4
    assert seq_sc.pooled_docs() == 4
    # the rebuild must respect the migrated placement, not undo it
    for shard, before in enumerate(members_after_migration):
        now = mesh_sc._pool.shard_members[shard]
        assert now[:len(before)] == before
    _assert_parity([mesh_sc, seq_sc], docs, strings)

    # second interleaving: round N overflows a FRESH primary doc with
    # the flag unsettled (pipelined default is on); round N+1 packs
    # fresh ops for a migrated pool member, and its LEADING settle
    # runs round N's recovery rebuild mid-flight — pre-watermark code
    # would apply those ops twice
    x_c, x_s = _open_doc(server, [mesh_sc, seq_sc], "doc-x")
    docs.append("doc-x")
    containers["doc-x"], strings["doc-x"] = x_c, x_s
    for _ in range(20):
        x_s.insert_text(0, "qrstuvwx")
    x_c.flush()
    for sc in (mesh_sc, seq_sc):
        sc.apply()          # NO sync: recovery defers to next settle
    for _ in range(3):
        strings["doc-0"].insert_text(0, "Z")
    containers["doc-0"].flush()
    for sc in (mesh_sc, seq_sc):
        sc.apply()
        sc.sync()
    assert mesh_sc.pooled_docs() == 5
    _assert_parity([mesh_sc, seq_sc], docs, strings)


# ======================================================================
# loud route fallback (the silent-fallback bugfix)


def test_seq_pool_off_route_fallback_is_loud(capsys):
    from fluidframework_tpu.obs import metrics as obs_metrics

    pool = SeqShardedPool(make_seq_mesh(jax.devices()[:4]), 256,
                          executor="chunked")
    before = obs_metrics.REGISTRY.flat().get(
        "pool_route_fallback_total", 0.0)
    from fluidframework_tpu.ops import DocStream

    streams = [DocStream()]
    streams[0].add_noop(0)
    pool.admit([0], streams)
    err = capsys.readouterr().err
    assert "scan-collective route" in err
    assert obs_metrics.REGISTRY.flat()[
        "pool_route_fallback_total"] == before + 1
    # once per instance, not per dispatch
    streams[0].add_noop(1)
    pool.dispatch_pending(streams)
    assert "scan-collective" not in capsys.readouterr().err


def test_mesh_pool_chunked_request_is_loud_on_multishard(capsys):
    from fluidframework_tpu.obs import metrics as obs_metrics
    from fluidframework_tpu.ops import DocStream

    pool = MeshShardedPool(make_mesh(jax.devices()[:2]), 128,
                           executor="chunked")
    before = obs_metrics.REGISTRY.flat().get(
        "mesh_pool_route_fallback_total", 0.0)
    streams = [DocStream()]
    streams[0].add_noop(0)
    pool.admit([0], streams)
    assert "scan window body" in capsys.readouterr().err
    assert obs_metrics.REGISTRY.flat()[
        "mesh_pool_route_fallback_total"] == before + 1


def test_mesh_pool_single_shard_follows_chunked_route():
    """A 1-shard mesh pool follows the executor route exactly like
    the degenerate seq pool — no fallback, no warning."""
    from fluidframework_tpu.ops import DocStream

    pool = MeshShardedPool(make_mesh(jax.devices()[:1]), 128,
                           executor="chunked")
    streams = [DocStream()]
    streams[0].add_noop(0)
    assert pool.admit([0], streams) == []
    assert pool._route_warned is False


# ======================================================================
# metrics + multi-shard CI subprocess (the tier-1 fixture satellite)


def test_mesh_pool_metrics_registered():
    from fluidframework_tpu.obs import metrics as obs_metrics

    server = LocalServer()
    mesh_sc, _seq, docs, containers, strings = _hotspot_pair(server)
    for doc in docs:
        _grow_into_pool(containers[doc], strings[doc], n_chunks=20)
    mesh_sc.apply()
    mesh_sc.sync()
    for _ in range(4):
        for doc in docs:
            n = 12 if doc == "doc-0" else 1
            for _ in range(n):
                strings[doc].insert_text(0, "XY")
            containers[doc].flush()
        mesh_sc.apply()
        mesh_sc.sync()
    flat = obs_metrics.REGISTRY.flat()
    assert flat.get('mesh_pool_members{shard="0"}', 0) >= 1
    assert flat.get("mesh_pool_dispatches_total", 0) >= 1
    assert flat.get("mesh_pool_watermark_ops", 0) > 0
    assert flat.get("mesh_pool_migrations_total", 0) >= 1
    assert "mesh_pool_shard_imbalance" in flat


def test_mesh_pool_on_4_device_cpu_subprocess(mesh_cpu_subprocess):
    """Multi-shard paths must run on CPU-only CI regardless of the
    parent session's device flags: the conftest fixture spawns a
    subprocess pinned to XLA_FLAGS=--xla_force_host_platform_
    device_count=4 and the mini hot-spot parity script must pass
    there on a real 4-shard mesh."""
    out = mesh_cpu_subprocess(
        """
import jax
jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 4, jax.devices()
from fluidframework_tpu.parallel import make_mesh
from fluidframework_tpu.service.tpu_sidecar import select_pool
from fluidframework_tpu.testing import FuzzConfig, record_op_stream
from fluidframework_tpu.ops import encode_stream, extract_text
from fluidframework_tpu.protocol.messages import MessageType

pool = select_pool(make_mesh(jax.devices()), 128)
oracle = select_pool(make_mesh(jax.devices()[:1]), 128, route="mesh")
texts, streams, o_streams = [], [], []
for i in range(6):
    text, msgs = record_op_stream(
        FuzzConfig(n_clients=2, n_steps=12, seed=300 + i))
    ops = [m for m in msgs if m.type == MessageType.OPERATION]
    streams.append(encode_stream(ops))
    o_streams.append(encode_stream(ops))
    texts.append(text)
assert pool.admit(list(range(6)), streams) == []
assert oracle.admit(list(range(6)), o_streams) == []
assert pool.n_shards == 4
for src in (streams, o_streams):
    fetched = (pool if src is streams else oracle).fetch()
    row_of = (pool if src is streams else oracle).row_of
    for slot in range(6):
        assert extract_text(fetched, src[slot], row_of[slot]) == \\
            texts[slot], slot
print("MESH4-OK")
""")
    assert "MESH4-OK" in out
