"""Long-document tier (SURVEY §5.7 in the product path): documents
that outgrow the primary slab ladder move to the SEQUENCE-SHARDED pool
(slot axis split across the 8-device mesh) and stay on the device
path; host eviction only past even the pooled capacity.
"""
import jax
import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.parallel import make_seq_mesh
from fluidframework_tpu.service import LocalServer, TpuMergeSidecar


def make_pool_sidecar(max_docs=3, capacity=16, max_capacity=32,
                      pool_capacity=256):
    mesh = make_seq_mesh(jax.devices())  # 1 doc lane x 8 seq shards
    return TpuMergeSidecar(
        max_docs=max_docs, capacity=capacity,
        max_capacity=max_capacity, seq_mesh=mesh,
        pool_capacity=pool_capacity,
    )


def write_doc(server, sidecar, doc, n_chunks, chunk="abcdefgh"):
    factory = LocalDocumentServiceFactory(server)
    sidecar.subscribe(server, doc, "d", "s")
    c = Container.load(factory.create_document_service(doc),
                       client_id=f"{doc}-w")
    s = c.runtime.create_datastore("d").create_channel(
        "sharedstring", "s")
    for i in range(n_chunks):
        s.insert_text(0, chunk)
        c.flush()
        if i % 3 == 2 and s.get_length() > 6:
            s.remove_text(2, 5)
            c.flush()
    return c, s


def test_overgrown_doc_lands_in_pool_not_host():
    server = LocalServer()
    sidecar = make_pool_sidecar()
    c, s = write_doc(server, sidecar, "big", n_chunks=60)
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.pool_admit_count >= 1
    assert sidecar.pooled_docs() == 1
    assert sidecar.host_mode_docs() == 0, \
        "pool must catch the doc before host eviction"
    assert sidecar.text("big", "d", "s") == s.get_text()


def test_pooled_doc_keeps_collaborating():
    server = LocalServer()
    sidecar = make_pool_sidecar()
    c, s = write_doc(server, sidecar, "big", n_chunks=60)
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.pooled_docs() == 1
    # continued edits dispatch through the seq-sharded window path
    for _ in range(10):
        s.insert_text(3, "XYZ")
        c.flush()
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.pooled_docs() == 1
    assert sidecar.host_mode_docs() == 0
    assert sidecar.text("big", "d", "s") == s.get_text()


def test_mixed_primary_and_pooled_docs_converge():
    server = LocalServer()
    sidecar = make_pool_sidecar(max_docs=3)
    big_c, big_s = write_doc(server, sidecar, "big", n_chunks=60)
    small_c, small_s = write_doc(server, sidecar, "small", n_chunks=4)
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.pooled_docs() == 1
    # both tiers keep taking edits in the same apply cycle
    big_s.insert_text(0, "B")
    big_c.flush()
    small_s.insert_text(0, "S")
    small_c.flush()
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.text("big", "d", "s") == big_s.get_text()
    assert sidecar.text("small", "d", "s") == small_s.get_text()
    assert sidecar.host_mode_docs() == 0


def test_beyond_pool_capacity_falls_back_to_host():
    server = LocalServer()
    # pool holds only 64 slots/doc: a doc that beats the ladder AND
    # the pool must still end up correct (host replica)
    sidecar = make_pool_sidecar(max_capacity=32, pool_capacity=64)
    c, s = write_doc(server, sidecar, "huge", n_chunks=120)
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.host_mode_docs() == 1
    assert sidecar.pooled_docs() == 0
    assert sidecar.text("huge", "d", "s") == s.get_text()


def test_pool_rejects_sharded_doc_axis():
    from fluidframework_tpu.service.tpu_sidecar import SeqShardedPool

    mesh = make_seq_mesh(jax.devices(), doc_shards=2)
    with pytest.raises(ValueError, match="unsharded doc axis"):
        SeqShardedPool(mesh, 256)


def test_pool_eviction_does_not_corrupt_remaining_members():
    """Regression: evicting one pooled doc (dispatch overflow) used to
    leave the other members' rows unshifted — wrong text reads and
    spurious evictions from stale overflow flags."""
    server = LocalServer()
    sidecar = make_pool_sidecar(max_docs=3, max_capacity=32,
                                pool_capacity=128)
    a_c, a_s = write_doc(server, sidecar, "doc-a", n_chunks=60)
    b_c, b_s = write_doc(server, sidecar, "doc-b", n_chunks=60)
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.pooled_docs() == 2
    # grow doc-a past the pool capacity through the dispatch path
    for _ in range(120):
        a_s.insert_text(0, "zzzzzzzz")
        a_c.flush()
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.host_mode_docs() == 1       # doc-a evicted
    assert sidecar.pooled_docs() == 1          # doc-b survives
    # doc-b's reads stay correct, and further edits keep applying
    assert sidecar.text("doc-b", "d", "s") == b_s.get_text()
    b_s.insert_text(0, "still-alive-")
    b_c.flush()
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.pooled_docs() == 1, "no spurious eviction"
    assert sidecar.text("doc-b", "d", "s") == b_s.get_text()
    assert sidecar.text("doc-a", "d", "s") == a_s.get_text()


def test_ingest_eviction_of_pooled_doc_rebuilds_pool():
    """Regression: a pooled doc leaving via ingest's tensor-
    inexpressible path (too many interned props) must rebuild the
    pool for the survivors."""
    server = LocalServer()
    sidecar = make_pool_sidecar(max_docs=3, pool_capacity=256)
    a_c, a_s = write_doc(server, sidecar, "doc-a", n_chunks=60)
    b_c, b_s = write_doc(server, sidecar, "doc-b", n_chunks=60)
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.pooled_docs() == 2
    # doc-a submits an op with more prop keys than PROP_CHANNELS:
    # encode fails -> ingest evicts doc-a mid-pool
    a_s.insert_text(0, "X", {f"k{i}": i for i in range(9)})
    a_c.flush()
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.host_mode_docs() == 1
    assert sidecar.pooled_docs() == 1
    assert sidecar.text("doc-a", "d", "s") == a_s.get_text()
    # survivor reads/edits stay correct
    assert sidecar.text("doc-b", "d", "s") == b_s.get_text()
    b_s.insert_text(0, "ok-")
    b_c.flush()
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.pooled_docs() == 1
    assert sidecar.text("doc-b", "d", "s") == b_s.get_text()


def test_remove_heavy_doc_fits_pool_after_compaction():
    """Regression: pool replay/dispatch compact — a doc whose HISTORY
    exceeds pooled capacity but whose live text fits must stay pooled,
    not fall through to host eviction."""
    server = LocalServer()
    sidecar = make_pool_sidecar(max_docs=2, max_capacity=32,
                                pool_capacity=64)
    factory = LocalDocumentServiceFactory(server)
    sidecar.subscribe(server, "churn", "d", "s")
    c = Container.load(factory.create_document_service("churn"),
                       client_id="w")
    s = c.runtime.create_datastore("d").create_channel(
        "sharedstring", "s")
    # insert/remove churn: ~160 historical segments, few live ones
    for i in range(80):
        s.insert_text(0, "abcd")
        c.flush()
        if s.get_length() > 8:
            s.remove_text(0, 4)
            c.flush()
    sidecar.apply()
    sidecar.sync()  # pipelined dispatch: pool policy runs at settle
    assert sidecar.host_mode_docs() == 0, \
        "compaction should keep the live set inside the pool"
    assert sidecar.text("churn", "d", "s") == s.get_text()


def test_deferred_pool_ops_not_double_applied_by_recovery_rebuild():
    """Pipelined settle ordering (review repro): round N defers window
    ops for an already-pooled doc while ANOTHER doc overflows into the
    pool in the same round. Recovery's admission rebuilds the pool
    from the FULL canonical streams — which already contain the
    deferred ops — so an incremental dispatch of the deferred batch
    onto the rebuilt table applied those ops twice (served text
    diverged). The stream watermarks make the rebuild subsume them."""
    import jax

    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.parallel import make_seq_mesh
    from fluidframework_tpu.service import LocalServer, TpuMergeSidecar

    mesh = make_seq_mesh(jax.devices()[:1])
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=3, capacity=16, max_capacity=16,
                              seq_mesh=mesh, pool_capacity=256)
    factory = LocalDocumentServiceFactory(server)

    def open_doc(doc):
        sidecar.subscribe(server, doc, "d", "s")
        c = Container.load(factory.create_document_service(doc),
                           client_id=f"{doc}-w")
        s = c.runtime.create_datastore("d").create_channel(
            "sharedstring", "s")
        return c, s

    big_c, big_s = open_doc("big")
    other_c, other_s = open_doc("other")
    # phase 1: "big" outgrows the ladder into the pool
    for _ in range(20):
        big_s.insert_text(0, "abcdefgh")
        big_c.flush()
    sidecar.apply()
    sidecar.sync()
    assert sidecar.pooled_docs() == 1

    # phase 2, ONE apply: deferred traffic for the pooled doc plus a
    # second doc overflowing into the pool (recovery rebuild) in the
    # same settle
    for _ in range(20):
        big_s.insert_text(0, "abcdefgh")
    big_c.flush()
    for _ in range(20):
        other_s.insert_text(0, "qrstuvwx")
    other_c.flush()
    sidecar.apply()
    sidecar.sync()
    assert sidecar.pooled_docs() == 2
    assert sidecar.text("big", "d", "s") == big_s.get_text()
    assert sidecar.text("other", "d", "s") == other_s.get_text()


def test_pool_ops_packed_across_recovery_rebuild_apply_once():
    """Second interleaving of the same bug: doc 'other' overflows in
    round N with the flag UNSETTLED (pipelined default); round N+1
    packs new ops for the already-pooled 'big', and its LEADING settle
    recovers round N (pool rebuild from full streams, subsuming big's
    just-packed ops). Pre-watermark code then queued those ops for the
    next pool dispatch anyway — applied twice."""
    import jax

    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.parallel import make_seq_mesh
    from fluidframework_tpu.service import LocalServer, TpuMergeSidecar

    mesh = make_seq_mesh(jax.devices()[:1])
    server = LocalServer()
    sidecar = TpuMergeSidecar(max_docs=3, capacity=16, max_capacity=16,
                              seq_mesh=mesh, pool_capacity=256)
    factory = LocalDocumentServiceFactory(server)

    def open_doc(doc):
        sidecar.subscribe(server, doc, "d", "s")
        c = Container.load(factory.create_document_service(doc),
                           client_id=f"{doc}-w")
        s = c.runtime.create_datastore("d").create_channel(
            "sharedstring", "s")
        return c, s

    big_c, big_s = open_doc("big")
    other_c, other_s = open_doc("other")
    for _ in range(20):
        big_s.insert_text(0, "abcdefgh")
        big_c.flush()
    sidecar.apply()
    sidecar.sync()
    assert sidecar.pooled_docs() == 1

    # round N: 'other' overflows — do NOT settle (pipelined)
    for _ in range(20):
        other_s.insert_text(0, "qrstuvwx")
    other_c.flush()
    sidecar.apply()

    # round N+1: new ops for pooled 'big'; the leading settle of this
    # apply runs round N's recovery (pool rebuild) mid-flight
    for _ in range(3):
        big_s.insert_text(0, "XY")
    big_c.flush()
    sidecar.apply()
    sidecar.sync()
    assert sidecar.pooled_docs() == 2
    assert sidecar.text("big", "d", "s") == big_s.get_text()
    assert sidecar.text("other", "d", "s") == other_s.get_text()
