"""PropertyDDS: typed schemas, squash-on-commit changesets, per-path
merge (LWW modify, remove-wins), summarize/load.

Reference behavior: experimental/PropertyDDS/packages/{property-dds,
property-changeset,property-properties}.
"""
import pytest

from fluidframework_tpu.models.property_dds import (
    PropertySchemaRegistry,
    SharedPropertyTree,
    empty_changeset,
    is_empty,
    squash,
)
from fluidframework_tpu.testing.runtime_mocks import ContainerSession

POINT = {
    "typeid": "test:point-1.0.0",
    "properties": [
        {"id": "x", "typeid": "Float64"},
        {"id": "y", "typeid": "Float64"},
        {"id": "label", "typeid": "String"},
    ],
}


def make_session(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    trees = []
    for c in ids:
        s.runtime(c).create_datastore("ds").create_channel(
            "sharedpropertytree", "pt")
        t = s.runtime(c).get_datastore("ds").get_channel("pt")
        t.schemas.register(POINT)
        trees.append(t)
    s.process_all()  # drain the channel-attach ops
    return s, trees


def converged(s, trees):
    s.process_all()
    sig = trees[0].signature()
    for t in trees[1:]:
        assert t.signature() == sig
    return sig


# ---- schemas ---------------------------------------------------------

def test_schema_instantiate_defaults():
    reg = PropertySchemaRegistry()
    reg.register(POINT)
    node = reg.instantiate("test:point-1.0.0")
    assert node["children"]["x"] == {"typeid": "Float64", "value": 0.0}
    assert node["children"]["label"]["value"] == ""


def test_schema_rejects_unknown_typeid():
    reg = PropertySchemaRegistry()
    with pytest.raises(ValueError, match="unregistered"):
        reg.instantiate("test:nope-1.0.0")


def test_primitive_type_enforcement():
    s, (a, b) = make_session()
    a.insert_property("p", "test:point-1.0.0")
    a.commit()
    s.process_all()
    with pytest.raises(TypeError):
        a.set_value("p.x", "not-a-number")
    with pytest.raises(KeyError):
        a.set_value("p.ghost", 1)


# ---- commit model ----------------------------------------------------

def test_edits_buffer_until_commit():
    s, (a, b) = make_session()
    a.insert_property("n", "Int32", 5)
    assert a.dirty
    s.process_all()
    assert b.get_value("n") is None  # nothing shipped yet
    a.commit()
    assert not a.dirty
    s.process_all()
    assert b.get_value("n") == 5


def test_squash_insert_modify_remove():
    cs = empty_changeset()
    cs = squash(cs, {"insert": {"a": {"typeid": "Int32", "value": 1}},
                     "modify": {}, "remove": []})
    cs = squash(cs, {"insert": {}, "modify": {"a": 9}, "remove": []})
    # insert∘modify folds into the insert
    assert cs["insert"]["a"]["value"] == 9
    assert cs["modify"] == {}
    cs = squash(cs, {"insert": {}, "modify": {}, "remove": ["a"]})
    # insert∘remove annihilates
    assert is_empty(cs)


def test_squash_modify_modify_last_wins():
    cs = squash(
        {"insert": {}, "modify": {"p.x": 1.0}, "remove": []},
        {"insert": {}, "modify": {"p.x": 2.0}, "remove": []})
    assert cs["modify"] == {"p.x": 2.0}


def test_commit_ships_one_op_per_commit():
    s, (a, b) = make_session()
    a.insert_property("p", "test:point-1.0.0")
    a.set_value("p.x", 1.5)
    a.set_value("p.x", 2.5)
    a.set_value("p.label", "pt")
    a.commit()
    s.flush("A")
    assert s.pending_count == 1  # squashed into a single changeset op
    s.process_all()
    assert b.get_value("p.x") == 2.5
    assert b.get_value("p.label") == "pt"


# ---- merge semantics -------------------------------------------------

def test_concurrent_modify_lww():
    s, (a, b) = make_session()
    a.insert_property("p", "test:point-1.0.0")
    a.commit()
    s.process_all()
    a.set_value("p.x", 1.0)
    a.commit()
    b.set_value("p.x", 2.0)
    b.commit()
    converged(s, [a, b])
    assert a.get_value("p.x") == 2.0  # later-sequenced commit wins


def test_remove_wins_over_nested_modify():
    s, (a, b) = make_session()
    a.insert_property("p", "test:point-1.0.0")
    a.commit()
    s.process_all()
    a.remove_property("p")
    a.commit()
    b.set_value("p.x", 9.0)
    b.commit()
    sig = converged(s, [a, b])
    assert sig["children"] == {}


def test_concurrent_inserts_different_paths():
    s, (a, b) = make_session()
    a.insert_property("pa", "test:point-1.0.0")
    a.commit()
    b.insert_property("pb", "Int32", 7)
    b.commit()
    converged(s, [a, b])
    assert a.get_value("pb") == 7
    assert b.resolve("pa") is not None


def test_pending_commit_is_optimistic_locally():
    s, (a, b) = make_session()
    a.insert_property("n", "Int32", 3)
    a.commit()
    assert a.get_value("n") == 3   # pending, optimistic
    assert b.get_value("n") is None
    s.process_all()
    assert b.get_value("n") == 3


def test_summarize_load_roundtrip():
    s, (a, b) = make_session()
    a.insert_property("p", "test:point-1.0.0",
                      {"x": 4.0, "label": "origin"})
    a.commit()
    s.process_all()
    fresh = SharedPropertyTree("pt2")
    fresh.load_core(a.summarize_core())
    assert fresh.signature() == a.signature()
    assert fresh.get_value("p.x") == 4.0


def test_remove_under_pending_insert_squashes_into_it():
    """Regression: removing a child of a not-yet-committed insert must
    edit the insert spec (a global remove would no-op because removes
    apply before inserts)."""
    s, (a, b) = make_session()
    a.insert_property("p", "test:point-1.0.0")
    a.remove_property("p.label")
    a.commit()
    s.process_all()
    assert a.resolve("p.label") is None
    assert b.resolve("p.label") is None
    assert b.resolve("p.x") is not None
    assert a.signature() == b.signature()
