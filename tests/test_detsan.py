"""detsan (testing/detsan.py) unit tests plus THE static/runtime
differential: every un-routed clock read and every global-stream RNG
draw detsan observes inside the deterministic planes — while driving
the REAL chaos sweep and a serve_bench slice — must be a detcheck
static finding or a reviewed WALL_CLOCK_SINKS registry entry. A gap
fails here BY NAME as an analyzer-resolution gap (the
fluidsan<->concheck / jitsan<->shapecheck contract), never silently.
"""
import importlib.util
import os
import textwrap

import pytest

from fluidframework_tpu.testing import detsan


@pytest.fixture()
def sanitized():
    """Install with a clean slate; always restore (refcounted, so an
    FFTPU_SANITIZE=1 session stays installed)."""
    detsan.install()
    detsan.reset()
    yield detsan
    detsan.reset()
    detsan.uninstall()


def _plant_module(tmp_path, relpath: str, source: str):
    """Write a module under a fake repo root and import it by path —
    the call sites then carry in-scope repo-relative paths once
    detsan._REPO_ROOT points at tmp_path."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    name = relpath.replace("/", "_").removesuffix(".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def fake_repo(tmp_path, monkeypatch):
    monkeypatch.setattr(
        detsan, "_REPO_ROOT", str(tmp_path) + os.sep)
    return tmp_path


def test_unrouted_wall_read_in_scope_trips(sanitized, fake_repo):
    """A direct time.monotonic() inside a deterministic-plane
    component trips: site, component attribution, flight dump, and
    the detsan_trips_total metric all ride the payload."""
    mod = _plant_module(fake_repo, "fluidframework_tpu/service/fake.py", """
        import time

        def raw_read():
            return time.monotonic()
    """)
    metric_before = detsan._TRIPS_TOTAL.value
    mod.raw_read()
    trips = detsan.trips()
    assert len(trips) == 1
    trip = trips[0]
    assert trip.kind == "wall"
    assert trip.what == "time.monotonic"
    assert trip.relpath == "fluidframework_tpu/service/fake.py"
    assert trip.func == "raw_read"
    assert trip.component == "main"       # MainThread attribution
    assert "fake.py" in trip.flight_dump  # recent-read history rides
    assert detsan._TRIPS_TOTAL.value == metric_before + 1
    # one trip per site, not one per call
    mod.raw_read()
    assert len(detsan.trips()) == 1


def test_routed_clock_read_does_not_trip(sanitized, fake_repo):
    """A read arriving through an injected clock() is ROUTED — the
    provenance the static rule credits — even though the same patched
    time.monotonic runs underneath."""
    mod = _plant_module(fake_repo, "fluidframework_tpu/qos/fakeq.py", """
        import time

        class Breaker:
            def __init__(self, clock=time.monotonic):
                self._clock = clock

            def probe(self):
                return self._clock()
    """)
    assert mod.Breaker().probe() > 0
    assert detsan.trips() == []
    # the read WAS observed (non-vacuous): recorded, just routed
    sites = detsan.observed_sites("wall")
    assert any(r.relpath.endswith("fakeq.py") for r in sites)
    assert detsan.unrouted_wall_sites() == []


def test_out_of_scope_reads_do_not_trip(sanitized, fake_repo):
    """obs/ is a telemetry plane (wall-clock by design), and files
    outside the package are nobody's contract."""
    mod = _plant_module(fake_repo, "fluidframework_tpu/obs/fakeo.py", """
        import time

        def sample():
            return time.time()
    """)
    mod.sample()
    other = _plant_module(fake_repo, "scripts/fake_tool.py", """
        import time

        def now():
            return time.time()
    """)
    other.now()
    assert detsan.trips() == []


def test_registered_sink_does_not_trip(sanitized, fake_repo):
    """A function matching a WALL_CLOCK_SINKS entry is a reviewed
    telemetry sink — recorded, never tripped (registry, not
    allowlist: the gate test pins every entry to live code)."""
    mod = _plant_module(
        fake_repo, "fluidframework_tpu/service/tenancy.py", """
        import time

        def sign_token():
            return time.time() + 60.0
    """)
    mod.sign_token()
    assert detsan.trips() == []
    # ...but it IS an un-routed site: the differential counts it
    # against the registry, which is exactly where it is registered
    sites = detsan.unrouted_wall_sites()
    assert any(r.func == "sign_token" for r in sites)


def test_global_rng_draw_and_unseeded_random_trip(
        sanitized, fake_repo):
    """Module-level random.* rides the process-global unseeded
    stream; random.Random() without a seed is unreplayable at its
    creation site. Seeded construction and injected instances pass."""
    mod = _plant_module(
        fake_repo, "fluidframework_tpu/drivers/faked.py", """
        import random

        def jitter():
            return random.uniform(0.0, 1.0)

        def fresh_unseeded():
            return random.Random()

        def fresh_seeded(seed):
            return random.Random(seed)
    """)
    mod.fresh_seeded(42).random()
    assert detsan.trips() == []
    mod.jitter()
    mod.fresh_unseeded()
    kinds = sorted(t.kind for t in detsan.trips())
    assert kinds == ["rng", "rng-unseeded"]
    whats = sorted(t.what for t in detsan.trips())
    assert whats == ["random.Random()", "random.uniform"]


def test_seeded_random_instances_are_untouched(sanitized):
    """random.Random(seed) still produces the exact stdlib stream —
    the sanitizer must never perturb seeded determinism."""
    import random

    a = random.Random(1234)
    b = random.Random(1234)
    assert [a.random() for _ in range(5)] == \
        [b.random() for _ in range(5)]
    assert isinstance(a, random.Random)
    assert detsan.trips() == []


def test_install_uninstall_restores_the_module_surface():
    import random
    import time

    before = (time.time, time.monotonic, time.perf_counter,
              random.random, random.Random)
    detsan.install()
    try:
        assert hasattr(time.monotonic, "__detsan_wrapped__")
        assert hasattr(random.Random, "__detsan_wrapped__")
    finally:
        detsan.uninstall()
    after = (time.time, time.monotonic, time.perf_counter,
             random.random, random.Random)
    assert before == after


# ---------------------------------------------------------------- differential


def _static_detcheck():
    from fluidframework_tpu.analysis import determinism
    from fluidframework_tpu.analysis.core import run_analysis

    findings = run_analysis(
        roots=["fluidframework_tpu"], families=["detcheck"])
    return determinism, {(f.path, f.line) for f in findings}


def test_runtime_sites_are_subset_of_static_findings_and_registry():
    """THE closing of the loop: drive the real chaos sweep (faults
    armed, crash-restart mid-run) and a serve_bench slice under the
    sanitizer, then pin every runtime-observed un-routed wall-clock
    site — and every scoped RNG draw — to detcheck's static findings
    plus the WALL_CLOCK_SINKS registry. A missing site means the
    static analyzer can no longer see a read the runtime performs —
    fix resolution (DETERMINISTIC_ROOTS/INDIRECT) or register a
    reviewed sink in analysis/determinism.py; do NOT weaken this
    test."""
    from fluidframework_tpu.testing.chaos import run_chaos
    from fluidframework_tpu.tools.serve_bench import (
        ServeBenchConfig,
        run_serve_bench,
    )

    detsan.install()
    try:
        detsan.reset()
        # seed 3 is an odd seed: crash + torn-state restart mid-run,
        # so the recovery paths run under the sanitizer too
        report = run_chaos(seed=3, faults=True, n_steps=12)
        assert report.converged, report.failures
        bench = run_serve_bench(ServeBenchConfig(
            n_docs=8, readers_per_doc=2, duration_s=1.0,
            tick_s=0.05, capacity_ops_per_s=100.0,
            offered_multiple=0.8, seed=7, sidecar_docs=0,
        ))
        assert bench.acked_ops > 0
        unrouted = detsan.unrouted_wall_sites()
        rng_sites = detsan.scoped_rng_sites()
        all_wall = detsan.observed_sites("wall")
    finally:
        detsan.reset()
        detsan.uninstall()

    determinism, static_sites = _static_detcheck()
    gaps = [
        rec for rec in unrouted
        if (rec.relpath, rec.line) not in static_sites
        and not determinism.sink_registered(
            rec.relpath, rec.func, by_code_name=True)
    ]
    assert not gaps, (
        "ANALYZER-RESOLUTION GAP: detsan observed un-routed "
        "wall-clock reads that detcheck neither finds nor has "
        "registered:\n" + "\n".join(
            f"  {r.relpath}:{r.line} in {r.func}() "
            f"(components {sorted(r.components)})" for r in gaps
        )
    )
    # the live tree is clean, so every scoped RNG draw would be a gap
    rng_gaps = [
        r for r in rng_sites
        if (r.relpath, r.line) not in static_sites
    ]
    assert not rng_gaps, (
        "unseeded/global RNG observed on a deterministic plane with "
        "no static finding:\n" + "\n".join(
            f"  {r.relpath}:{r.line} in {r.func}()" for r in rng_gaps
        )
    )

    # non-vacuity: the run actually exercised the planes — routed
    # sequencer reads and at least one registered telemetry sink were
    # OBSERVED (a silent no-op sanitizer must not pass this test)
    observed_paths = {r.relpath for r in all_wall}
    assert "fluidframework_tpu/tools/serve_bench.py" in observed_paths
    assert any(
        determinism.sink_registered(r.relpath, r.func,
                                    by_code_name=True)
        for r in unrouted
    ), "no registered sink observed: the differential drove nothing"


def test_registry_and_static_scope_agree_with_runtime_scope():
    """The two halves must share one scope definition: detsan's
    runtime component scope is imported from detcheck, so a component
    added to one side cannot silently diverge from the other."""
    from fluidframework_tpu.analysis.determinism import (
        DET_SCOPE_COMPONENTS,
    )

    assert detsan._in_runtime_scope(
        "fluidframework_tpu/service/sequencer.py")
    assert not detsan._in_runtime_scope(
        "fluidframework_tpu/obs/profiler.py")
    assert not detsan._in_runtime_scope("tests/test_detsan.py")
    assert "service" in DET_SCOPE_COMPONENTS
    assert "obs" not in DET_SCOPE_COMPONENTS
