"""Seeded convergence fuzzing over the mock sequencer — the framework's
race detector (SURVEY §5.2). Mirrors the reference's stochastic tests
(packages/dds/merge-tree/src/test/*fuzz*)."""
import pytest

from fluidframework_tpu.testing import FuzzConfig, run_convergence_fuzz


@pytest.mark.parametrize("seed", range(20))
def test_three_client_convergence(seed):
    run_convergence_fuzz(FuzzConfig(n_clients=3, n_steps=150, seed=seed))


@pytest.mark.parametrize("seed", range(5))
def test_many_client_convergence(seed):
    run_convergence_fuzz(
        FuzzConfig(n_clients=6, n_steps=250, seed=1000 + seed)
    )


@pytest.mark.parametrize("seed", range(5))
def test_insert_heavy_convergence(seed):
    run_convergence_fuzz(FuzzConfig(
        n_clients=4, n_steps=200, insert_weight=0.8, remove_weight=0.05,
        annotate_weight=0.05, process_weight=0.1, seed=2000 + seed,
    ))


@pytest.mark.parametrize("seed", range(5))
def test_remove_heavy_convergence(seed):
    run_convergence_fuzz(FuzzConfig(
        n_clients=3, n_steps=200, insert_weight=0.35, remove_weight=0.45,
        annotate_weight=0.05, process_weight=0.15, seed=3000 + seed,
    ))
