"""Local references + interval collections.

Mirrors packages/dds/sequence/src/test/intervalCollection.spec.ts and
merge-tree localReference tests: endpoints slide under concurrent edits,
delete-wins, pending-local-wins, reconnect rebase, convergence.
"""
import pytest

from fluidframework_tpu.models.mergetree import MergeTreeClient
from fluidframework_tpu.models.mergetree.localref import DETACHED_POSITION
from fluidframework_tpu.models.mergetree.ops import ReferenceType
from fluidframework_tpu.testing import MockCollabSession
from fluidframework_tpu.testing.runtime_mocks import ContainerSession


# ----------------------------------------------------------------------
# local references on a single client

def make_client(text="hello world"):
    c = MergeTreeClient("A")
    c.start_collaboration("A")
    c.insert_text_local(0, text)
    return c


def test_reference_tracks_position_under_inserts():
    c = make_client("abcdef")
    ref = c.create_reference(3, ReferenceType.SLIDE_ON_REMOVE)  # at 'd'
    assert c.reference_position(ref) == 3
    c.insert_text_local(0, "XY")  # shift right by 2
    assert c.reference_position(ref) == 5
    c.insert_text_local(8, "tail")  # after the ref: no move
    assert c.reference_position(ref) == 5


def test_reference_survives_segment_split():
    c = make_client("abcdef")
    ref = c.create_reference(4, ReferenceType.SLIDE_ON_REMOVE)  # at 'e'
    c.insert_text_local(2, "--")  # splits the abcdef segment
    assert c.reference_position(ref) == 6
    assert c.get_text() == "ab--cdef"


def test_reference_slides_forward_on_remove():
    """SlideOnRemove: anchor removed -> resolve to next surviving
    position (localReference.ts slide semantics)."""
    s, _ = make(2)
    a = s.client("A")
    s.do("A", "insert_text_local", 0, "abcdef")
    s.process_all()
    ref = a.create_reference(2, ReferenceType.SLIDE_ON_REMOVE)  # 'c'
    s.do("B", "remove_range_local", 1, 4)  # removes bcd
    s.process_all()
    assert a.get_text() == "aef"
    assert a.reference_position(ref) == 1  # slid to 'e'


def test_reference_slides_backward_at_document_end():
    s, _ = make(2)
    a = s.client("A")
    s.do("A", "insert_text_local", 0, "abc")
    s.process_all()
    ref = a.create_reference(2, ReferenceType.SLIDE_ON_REMOVE)  # 'c'
    s.do("B", "remove_range_local", 1, 3)  # removes bc, nothing after
    s.process_all()
    assert a.get_text() == "a"
    assert a.reference_position(ref) == 0  # slid backward to 'a'


def test_simple_reference_detaches_on_remove():
    s, _ = make(2)
    a = s.client("A")
    s.do("A", "insert_text_local", 0, "abc")
    s.process_all()
    ref = a.create_reference(1, ReferenceType.SIMPLE)
    s.do("B", "remove_range_local", 0, 3)
    s.process_all()
    assert a.reference_position(ref) == DETACHED_POSITION


def test_reference_survives_zamboni_compaction():
    """When the tombstone is compacted below the collab window, the
    reference transfers to its slide target and keeps resolving."""
    s, _ = make(2)
    a = s.client("A")
    s.do("A", "insert_text_local", 0, "abcdef")
    s.process_all()
    ref = a.create_reference(2, ReferenceType.SLIDE_ON_REMOVE)
    s.do("B", "remove_range_local", 1, 4)
    s.process_all()
    # advance the window far enough for zamboni with noop-ish traffic
    for _ in range(3):
        s.do("A", "insert_text_local", a.get_length(), "x")
        s.process_all()
        s.do("B", "insert_text_local", 0, "y")
        s.process_all()
    assert a.reference_position(ref) is not None
    pos = a.reference_position(ref)
    assert a.get_text()[pos] == "e"


def make(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    return MockCollabSession(ids), ids


# ----------------------------------------------------------------------
# interval collections over container runtimes

def make_session(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    for cid in ids:
        ds = s.runtime(cid).create_datastore("ds")
        ds.create_channel("sharedstring", "text")
    return s, ids


def strings(s, ids):
    return [
        s.runtime(cid).get_datastore("ds").get_channel("text")
        for cid in ids
    ]


def test_interval_add_converges():
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "hello world")
    s.process_all()
    sa.get_interval_collection("comments").add(0, 4, {"author": "A"})
    s.process_all()
    cb = sb.get_interval_collection("comments")
    assert len(cb) == 1
    iv = next(iter(cb))
    assert cb.endpoints(iv) == (0, 4)
    assert iv.props == {"author": "A"}
    assert sa.signature() == sb.signature()


def test_interval_slides_under_concurrent_text_edit():
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "hello world")
    s.process_all()
    # A intervals "world" while B inserts at the front concurrently
    ca = sa.get_interval_collection("c")
    ca.add(6, 10)
    sb.insert_text(0, ">> ")
    s.process_all()
    for ss in (sa, sb):
        coll = ss.get_interval_collection("c")
        iv = next(iter(coll))
        assert coll.endpoints(iv) == (9, 13)
        start, end = coll.endpoints(iv)
        assert ss.get_text()[start:end + 1] == "world"


def test_interval_endpoint_slides_when_text_removed():
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "abcdefgh")
    s.process_all()
    ca = sa.get_interval_collection("c")
    ca.add(2, 5)  # c..f
    s.process_all()
    sb.remove_text(0, 4)  # removes abcd; start anchor 'c' gone
    s.process_all()
    for ss in (sa, sb):
        coll = ss.get_interval_collection("c")
        iv = next(iter(coll))
        assert coll.endpoints(iv) == (0, 1)  # slid to 'e', end 'f'
    assert sa.signature() == sb.signature()


def test_interval_delete_wins_over_concurrent_change():
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "0123456789")
    s.process_all()
    ca = sa.get_interval_collection("c")
    iv = ca.add(1, 3)
    s.process_all()
    # A deletes while B concurrently changes
    ca.delete(iv.interval_id)
    sb.get_interval_collection("c").change(iv.interval_id, start=5, end=7)
    s.process_all()
    assert len(sa.get_interval_collection("c")) == 0
    assert len(sb.get_interval_collection("c")) == 0
    assert sa.signature() == sb.signature()


def test_interval_concurrent_change_lww():
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "0123456789")
    s.process_all()
    iv = sa.get_interval_collection("c").add(0, 1)
    s.process_all()
    # both change concurrently; B's op sequences second -> B wins
    sa.get_interval_collection("c").change(iv.interval_id, start=2, end=3)
    sb.get_interval_collection("c").change(iv.interval_id, start=6, end=7)
    s.flush("A")
    s.flush("B")
    s.process_all()
    for ss in (sa, sb):
        coll = ss.get_interval_collection("c")
        got = coll.endpoints(next(iter(coll)))
        assert got == (6, 7), got
    assert sa.signature() == sb.signature()


def test_interval_pending_local_change_wins_until_ack():
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "0123456789")
    s.process_all()
    iv = sa.get_interval_collection("c").add(0, 1)
    s.process_all()
    # B's change sequences first; A has a pending local change and must
    # keep its own value until the ack (then A's own op, sequenced
    # later, wins everywhere)
    sb.get_interval_collection("c").change(iv.interval_id, start=6, end=7)
    s.flush("B")
    sa.get_interval_collection("c").change(iv.interval_id, start=2, end=3)
    s.flush("A")
    ca = sa.get_interval_collection("c")
    assert ca.endpoints(next(iter(ca))) == (2, 3)
    s.process_all()
    for ss in (sa, sb):
        coll = ss.get_interval_collection("c")
        assert coll.endpoints(next(iter(coll))) == (2, 3)
    assert sa.signature() == sb.signature()


def test_interval_concurrent_prop_changes_merge_per_key():
    """Pending-wins is per aspect: A's pending prop 'a' must not drop
    B's concurrent change to prop 'b' (or B's endpoint change)."""
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "0123456789")
    s.process_all()
    iv = sa.get_interval_collection("c").add(0, 1)
    s.process_all()
    # B changes prop b and endpoints; sequences first
    sb.get_interval_collection("c").change(
        iv.interval_id, start=4, end=5, props={"b": 2}
    )
    s.flush("B")
    # A concurrently changes only prop a (no endpoints)
    sa.get_interval_collection("c").change(iv.interval_id, props={"a": 1})
    s.flush("A")
    s.process_all()
    for ss in (sa, sb):
        coll = ss.get_interval_collection("c")
        got = next(iter(coll))
        assert got.props == {"a": 1, "b": 2}, (ss, got.props)
        assert coll.endpoints(got) == (4, 5)
    assert sa.signature() == sb.signature()


def test_find_overlapping():
    s, ids = make_session(1)
    (sa,) = strings(s, ids)
    sa.insert_text(0, "0123456789")
    s.process_all()
    coll = sa.get_interval_collection("c")
    coll.add(0, 2)
    coll.add(4, 6)
    coll.add(8, 9)
    s.process_all()
    hits = coll.find_overlapping(1, 5)
    spans = sorted(coll.endpoints(iv) for iv in hits)
    assert spans == [(0, 2), (4, 6)]


def test_interval_summary_roundtrip():
    s, ids = make_session(1)
    (sa,) = strings(s, ids)
    sa.insert_text(0, "hello world")
    coll = sa.get_interval_collection("c")
    coll.add(6, 10, {"k": "v"})
    s.process_all()
    summary = sa.summarize_core()

    from fluidframework_tpu.models.sharedstring import SharedString
    fresh = SharedString("text")
    fresh.load_core(summary)
    assert fresh.get_text() == "hello world"
    lc = fresh.get_interval_collection("c")
    assert len(lc) == 1
    iv = next(iter(lc))
    assert lc.endpoints(iv) == (6, 10)
    assert iv.props == {"k": "v"}


def test_interval_reconnect_resubmits_pending_adds():
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "hello world")
    s.process_all()
    s.disconnect("A")
    # A adds an interval while offline; B edits text meanwhile
    sa.get_interval_collection("c").add(6, 10)
    sb.insert_text(0, ">> ")
    s.process_all()
    s.reconnect("A")
    s.process_all()
    for ss in (sa, sb):
        coll = ss.get_interval_collection("c")
        assert len(coll) == 1, ss
        iv = next(iter(coll))
        start, end = coll.endpoints(iv)
        assert ss.get_text()[start:end + 1] == "world"
    assert sa.signature() == sb.signature()


def test_interval_reconnect_resubmits_pending_prop_deletion():
    """ADVICE r1 #2: a pending property deletion ({key: None}) must
    survive reconnect as an explicit None entry, or peers keep the
    deleted key forever (signature divergence)."""
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "hello world")
    iv = sa.get_interval_collection("c").add(0, 4, props={"k": 1, "j": 2})
    s.process_all()
    s.disconnect("A")
    sa.get_interval_collection("c").change(
        iv.interval_id, props={"k": None})  # delete k while offline
    s.reconnect("A")
    s.process_all()
    for ss in (sa, sb):
        got = ss.get_interval_collection("c").get(iv.interval_id)
        assert "k" not in got.props, ss
        assert got.props["j"] == 2
    assert sa.signature() == sb.signature()


def test_interval_reconnect_resubmit_preserves_concurrent_remote_props():
    """Resubmission must cover ONLY locally-pending keys: a concurrent
    remote update to an untouched key must not be stomped by the
    reconnect replay."""
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "hello world")
    iv = sa.get_interval_collection("c").add(0, 4, props={"x": 1, "y": 1})
    s.process_all()
    s.disconnect("A")
    sa.get_interval_collection("c").change(iv.interval_id, props={"x": 9})
    sb.get_interval_collection("c").change(iv.interval_id, props={"y": 7})
    s.process_all()
    s.reconnect("A")
    s.process_all()
    for ss in (sa, sb):
        got = ss.get_interval_collection("c").get(iv.interval_id)
        assert got.props == {"x": 9, "y": 7}, ss
    assert sa.signature() == sb.signature()


def test_interval_reconnect_props_only_change_keeps_remote_endpoints():
    """A pending props-only change must not resubmit endpoints: a
    concurrent remote endpoint move would otherwise be overwritten."""
    s, ids = make_session(2)
    sa, sb = strings(s, ids)
    sa.insert_text(0, "hello world")
    iv = sa.get_interval_collection("c").add(0, 4, props={"x": 1})
    s.process_all()
    s.disconnect("A")
    sa.get_interval_collection("c").change(iv.interval_id, props={"x": 2})
    sb.get_interval_collection("c").change(iv.interval_id, start=6, end=10)
    s.process_all()
    s.reconnect("A")
    s.process_all()
    for ss in (sa, sb):
        coll = ss.get_interval_collection("c")
        got = coll.get(iv.interval_id)
        assert got.props == {"x": 2}, ss
        assert coll.endpoints(got) == (6, 10), ss
    assert sa.signature() == sb.signature()


# ---- endpoint stickiness (intervalCollection.ts IntervalStickiness) --

def _sticky_coll(stickiness, text="abcdef"):
    from fluidframework_tpu.models.intervals import IntervalCollection

    c = make_client(text)
    coll = IntervalCollection("x", c, lambda op: None)
    iv = coll.add(2, 4, stickiness=stickiness)  # "cd"
    return c, coll, iv


def test_stickiness_end_default_boundary_inserts():
    """Default (end-sticky): text at the END boundary joins, text at
    the START boundary stays out."""
    c, coll, iv = _sticky_coll("end")
    c.insert_text_local(4, "XY")          # end boundary
    lo, hi = coll.endpoints(iv)
    assert c.get_text()[lo:hi] == "cdXY"
    c.insert_text_local(2, "Z")           # start boundary
    lo, hi = coll.endpoints(iv)
    assert c.get_text()[lo:hi] == "cdXY"  # Z stayed outside


def test_stickiness_none_boundary_inserts_stay_out():
    c, coll, iv = _sticky_coll("none")
    c.insert_text_local(4, "XY")
    lo, hi = coll.endpoints(iv)
    assert c.get_text()[lo:hi] == "cd"
    c.insert_text_local(2, "Z")
    lo, hi = coll.endpoints(iv)
    assert c.get_text()[lo:hi] == "cd"


def test_stickiness_full_absorbs_both_boundaries():
    c, coll, iv = _sticky_coll("full")
    c.insert_text_local(4, "XY")
    c.insert_text_local(2, "Z")
    lo, hi = coll.endpoints(iv)
    assert c.get_text()[lo:hi] == "ZcdXY"


def test_stickiness_full_at_document_edges():
    """Sticky start at 0 stays 0; sticky end at the document end
    tracks appends."""
    from fluidframework_tpu.models.intervals import IntervalCollection

    c = make_client("abcdef")
    coll = IntervalCollection("x", c, lambda op: None)
    iv = coll.add(0, c.get_length(), stickiness="full")
    c.insert_text_local(0, ">>")
    c.insert_text_local(c.get_length(), "<<")
    lo, hi = coll.endpoints(iv)
    assert (lo, hi) == (0, c.get_length())


def test_stickiness_replicates_to_remote():
    """The add op carries stickiness; a remote replica anchors the
    same way and boundary inserts converge."""
    cs = ContainerSession(["A", "B"])
    for cid in ("A", "B"):
        cs.runtime(cid).create_datastore("d").create_channel(
            "sharedstring", "t")
    ta = cs.runtime("A").get_datastore("d").get_channel("t")
    tb = cs.runtime("B").get_datastore("d").get_channel("t")
    ta.insert_text(0, "abcdef")
    cs.process_all()
    ia = ta.get_interval_collection("c")
    ia.add(2, 4, stickiness="full")
    cs.process_all()
    tb.insert_text(4, "XY")
    tb.insert_text(2, "Z")
    cs.process_all()
    ib = tb.get_interval_collection("c")
    assert ia.signature() == ib.signature()
    iv_b = next(iter(ib))
    lo, hi = ib.endpoints(iv_b)
    assert tb.get_text()[lo:hi] == "ZcdXY"


def test_stickiness_survives_summary_roundtrip():
    from fluidframework_tpu.models.intervals import IntervalCollection

    c, coll, iv = _sticky_coll("full")
    entries = coll.summarize()
    assert entries[0]["stickiness"] == "full"
    c2 = make_client("abcdef")
    coll2 = IntervalCollection("x", c2, lambda op: None)
    coll2.load(entries)
    c2.insert_text_local(4, "XY")
    iv2 = next(iter(coll2))
    lo, hi = coll2.endpoints(iv2)
    assert c2.get_text()[lo:hi] == "cdXY"


def test_stickiness_anchor_removal_collapses_not_slides():
    """Removing an endpoint's anchor character must collapse the
    boundary backward, not slide it forward (code-review r4: the
    +1-bias representation absorbed/dropped a character here; the
    side-aware AFTER reference fixes it)."""
    from fluidframework_tpu.models.intervals import IntervalCollection

    c = make_client("abcdef")
    coll = IntervalCollection("x", c, lambda op: None)
    iv = coll.add(2, 4, stickiness="full")   # "cd"
    c.remove_range_local(1, 2)               # remove the start anchor
    lo, hi = coll.endpoints(iv)
    assert c.get_text()[lo:hi] == "cd"

    c2 = make_client("abcdef")
    coll2 = IntervalCollection("x", c2, lambda op: None)
    iv2 = coll2.add(2, 4, stickiness="none")  # "cd"
    c2.remove_range_local(3, 4)               # remove the end anchor
    lo, hi = coll2.endpoints(iv2)
    assert c2.get_text()[lo:hi] == "c"        # no absorb of 'e'


def test_empty_interval_with_nonsticky_end_stays_empty():
    from fluidframework_tpu.models.intervals import IntervalCollection

    c = make_client("abcdef")
    coll = IntervalCollection("x", c, lambda op: None)
    iv = coll.add(2, 2, stickiness="none")
    assert coll.endpoints(iv) == (2, 2)


def test_sticky_change_local_and_remote_partial():
    """change() on sticky intervals is sentinel-safe and exact; a
    remote PARTIAL change leaves the untouched endpoint's anchor alone
    (re-deriving it through the sender's older view diverged
    replicas — code-review r4)."""
    cs = ContainerSession(["A", "B"])
    for cid in ("A", "B"):
        cs.runtime(cid).create_datastore("d").create_channel(
            "sharedstring", "t")
    ta = cs.runtime("A").get_datastore("d").get_channel("t")
    tb = cs.runtime("B").get_datastore("d").get_channel("t")
    ta.insert_text(0, "abcdef")
    cs.process_all()
    ia = ta.get_interval_collection("c")
    iv = ia.add(2, 4, stickiness="full")
    cs.process_all()
    # concurrent: A inserts at the front while B changes ONLY start
    ib = tb.get_interval_collection("c")
    iv_b = next(iter(ib))
    ta.insert_text(0, "XX")
    ib.change(iv_b.interval_id, start=3)
    cs.process_all()
    assert ia.signature() == ib.signature()
    # local sticky change on sentinel endpoints doesn't crash
    iv0 = ia.add(0, 3, stickiness="full")
    ia.change(iv0.interval_id, start=1)
    cs.process_all()
    assert ia.signature() == ib.signature()


def test_stickiness_survives_zamboni_compaction():
    """Compaction transfers AFTER refs BACKWARD (code-review r4: the
    forward-first transfer made a collapsed endpoint jump forward one
    character once min_seq passed the removal)."""
    from fluidframework_tpu.models.intervals import IntervalCollection

    s, clients = _mock_session(2)
    a, b = clients
    s.do("c0", "insert_text_local", 0, "abcdef")
    s.process_all()
    coll = IntervalCollection("x", a, lambda op: None)
    iv = coll.add(2, 4, stickiness="none")    # 'cd', end AFTER 'd'
    ivf = coll.add(2, 4, stickiness="full")   # start AFTER 'b'
    s.do("c0", "remove_range_local", 3, 4)    # remove 'd'
    s.do("c0", "remove_range_local", 1, 2)    # remove 'b'
    s.process_all()
    lo, hi = coll.endpoints(iv)
    assert a.get_text()[lo:hi] == "c"
    lo_f, hi_f = coll.endpoints(ivf)
    assert a.get_text()[lo_f:hi_f] == "c"
    # advance min_seq well past the removals: BOTH clients must keep
    # submitting, or the silent client floors the msn at 0 and the
    # zamboni path under test never executes (code-review r4 caught
    # the first version of this test passing against the broken code)
    for i in range(20):
        s.do("c0", "insert_text_local", a.get_length(), "z")
        s.do("c1", "insert_text_local", b.get_length(), "y")
        s.process_all()
    assert a.mergetree.collab.min_seq > 4, "msn never advanced"
    a.zamboni() if hasattr(a, "zamboni") else a.mergetree.zamboni()
    lo, hi = coll.endpoints(iv)
    assert a.get_text()[lo:hi] == "c", (a.get_text(), lo, hi)
    lo_f, hi_f = coll.endpoints(ivf)
    assert a.get_text()[lo_f:hi_f].startswith("c"), (lo_f, hi_f)


def _mock_session(n):
    ids = [f"c{i}" for i in range(n)]
    s = MockCollabSession(ids)
    return s, [s.client(i) for i in ids]


@pytest.mark.parametrize("stickiness", ["none", "start", "end", "full"])
@pytest.mark.parametrize("removal", ["start", "end", "both"])
def test_zamboni_preserves_endpoints_matrix(stickiness, removal):
    """Stickiness x anchor-removal x compaction: once an endpoint's
    anchor char is removed and the interval has settled, running
    zamboni (which drops the tombstone the ref sits on) must not move
    either endpoint (VERDICT r4 next #2: full matrix, sequenced)."""
    from fluidframework_tpu.models.intervals import IntervalCollection

    s, clients = _mock_session(2)
    a, b = clients
    s.do("c0", "insert_text_local", 0, "abcdef")
    s.process_all()
    coll = IntervalCollection("x", a, lambda op: None)
    iv = coll.add(2, 4, stickiness=stickiness)  # 'cd'
    if removal in ("start", "both"):
        s.do("c0", "remove_range_local", 2, 3)  # start anchor 'c'
    if removal in ("end", "both"):
        # end anchor region 'd' (shifted left if 'c' already removed)
        off = 1 if removal == "both" else 0
        s.do("c0", "remove_range_local", 3 - off, 4 - off)
    s.process_all()
    before = coll.endpoints(iv)
    # advance msn past the removals (both clients must submit)
    for _ in range(20):
        s.do("c0", "insert_text_local", a.get_length(), "z")
        s.do("c1", "insert_text_local", b.get_length(), "y")
        s.process_all()
    assert a.mergetree.collab.min_seq > 4, "msn never advanced"
    a.mergetree.zamboni()
    after = coll.endpoints(iv)
    assert before == after, (
        f"zamboni moved endpoints: {before} -> {after} "
        f"(stickiness={stickiness}, removal={removal})"
    )
    assert coll.signature()  # resolvable, no crash


def test_empty_interval_end_zero_resolves():
    """end==0 with start/none stickiness stores the DOC_START sentinel
    as the END ref; endpoints()/signature() must resolve it, not crash
    (code-review r4)."""
    from fluidframework_tpu.models.intervals import IntervalCollection

    c = make_client("abc")
    coll = IntervalCollection("x", c, lambda op: None)
    iv = coll.add(0, 0, stickiness="none")
    assert coll.endpoints(iv) == (0, 0)
    assert coll.signature()  # no AttributeError
    assert coll.summarize() is not None
