"""Tools: benchmark harness, replay tool, headless exporter, fault
injection, stress runner.

Mirrors tools/benchmark tests, replay-tool validation runs, and
test-service-load's fault-injection stress pattern.
"""
import json

import pytest

from fluidframework_tpu.drivers import (
    LocalDocumentServiceFactory,
    save_document,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer
from fluidframework_tpu.testing.fault_injection import (
    FaultInjectionDocumentService,
)
from fluidframework_tpu.tools import (
    BenchmarkType,
    BenchmarkReporter,
    StressConfig,
    benchmark,
    export_file,
    replay_file,
    run_stress,
)


# ----------------------------------------------------------------------
# benchmark harness

def test_benchmark_runs_and_reports():
    counter = [0]

    def work():
        counter[0] += 1

    result = benchmark("noop", work, min_iterations=10,
                       min_time_s=0.0, warmup=2)
    assert result.iterations == 10
    assert counter[0] == 12  # warmup included
    assert result.mean_s >= 0 and result.p95_s >= result.p50_s >= 0
    assert result.ops_per_sec > 0


def test_benchmark_reporter_renders():
    reporter = BenchmarkReporter()
    reporter.add(benchmark(
        "a", lambda: None, min_iterations=3, min_time_s=0.0,
        benchmark_type=BenchmarkType.DIAGNOSTIC,
    ))
    table = reporter.render_table()
    assert "a" in table and "ops/s" in table
    parsed = json.loads(reporter.render_json())
    assert parsed[0]["type"] == "Diagnostic"


def test_benchmark_setup_argument():
    seen = []
    result = benchmark(
        "with-setup", seen.append, setup=lambda: len(seen),
        min_iterations=3, min_time_s=0.0, warmup=0,
    )
    assert result.iterations == 3
    assert seen == [0, 1, 2]


# ----------------------------------------------------------------------
# record a session then replay/export it

def record_session(tmp_path):
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice")
    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    sa = a.runtime.create_datastore("app").create_channel(
        "sharedstring", "text")
    a.flush()
    sa.insert_text(0, "hello")
    a.flush()
    sb = b.runtime.get_datastore("app").get_channel("text")
    sb.insert_text(5, " world")
    b.flush()
    kv = a.runtime.get_datastore("app").create_channel("sharedmap", "kv")
    a.flush()
    kv.set("done", True)
    a.flush()
    orderer = server.get_orderer("doc")
    path = tmp_path / "doc.json"
    save_document(path, "doc", orderer.op_log.read(0))
    return path, sa.get_text()


def test_replay_tool_reproduces_session(tmp_path):
    path, expected_text = record_session(tmp_path)
    container, report = replay_file(path)
    assert report.ok and report.ops_replayed > 0
    text = container.runtime.get_datastore("app").get_channel("text")
    assert text.get_text() == expected_text


def test_replay_tool_checkpoints_and_validation(tmp_path):
    path, _ = record_session(tmp_path)
    _, report = replay_file(path, checkpoint_every=3)
    assert report.checkpoints
    # replaying again against recorded checkpoints validates clean
    _, report2 = replay_file(
        path, checkpoint_every=3,
        expected_checkpoints=report.checkpoints,
    )
    assert report2.ok
    # a corrupted expectation is caught
    bad = [dict(c, summary={"tampered": 1})
           for c in report.checkpoints]
    _, report3 = replay_file(
        path, checkpoint_every=3, expected_checkpoints=bad,
    )
    assert not report3.ok


def test_fluid_runner_exports_content(tmp_path):
    path, expected_text = record_session(tmp_path)
    out_path = tmp_path / "export.json"
    result = export_file(path, str(out_path))
    assert result["content"]["app"]["text"]["text"] == expected_text
    assert result["content"]["app"]["kv"]["content"]["data"]["done"] is True
    assert json.loads(out_path.read_text()) == result


# ----------------------------------------------------------------------
# fault injection

def test_fault_injection_disconnect_and_recovery():
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    svc = FaultInjectionDocumentService(
        factory.create_document_service("doc"))
    a = Container.load(svc, client_id="alice")
    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    m = a.runtime.create_datastore("d").create_channel("sharedmap", "m")
    a.flush()
    # kill the socket under alice, edit while down, reconnect
    svc.inject_disconnect_all()
    m.set("offline", 1)
    a.flush()  # goes to pending, connection is dead
    bm = b.runtime.get_datastore("d").get_channel("m")
    assert bm.get("offline") is None
    a.disconnect()  # container notices; clears connection state
    a.connect()
    a.flush()
    assert bm.get("offline") == 1


def test_fault_injection_nack():
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    svc = FaultInjectionDocumentService(
        factory.create_document_service("doc"))
    nacks = []
    a = Container.load(svc, client_id="alice")
    a.on("nack", lambda n: nacks.append(n))
    m = a.runtime.create_datastore("d").create_channel("sharedmap", "m")
    a.flush()
    svc.live_connections[-1].inject_nacks(1)
    m.set("k", 1)
    a.flush()
    assert nacks and nacks[0].message == "injected nack"


# ----------------------------------------------------------------------
# stress

@pytest.mark.parametrize("seed", [0, 7])
def test_stress_run_converges_with_faults(seed):
    report = run_stress(StressConfig(
        n_clients=3, n_steps=250, seed=seed,
        p_disconnect=0.03, p_nack=0.02,
    ))
    assert report.ok, report.errors
    assert report.ops_submitted > 50
    assert report.disconnects_injected > 0 or seed != 0
