"""Persisted-format back-compat: committed golden fixtures must load
in every future round.

Reference: packages/test/snapshots (README.md:1-16) — stored old-format
snapshots + op logs are replayed and validated on every build, so a
format change that breaks loading fails LOUDLY here instead of
corrupting real documents. The fixtures are historical artifacts:
regenerate ONLY when minting a new format version (add a new
golden_vN, never overwrite old ones).
"""
import hashlib
import json
import os

from fluidframework_tpu.drivers import load_document
from fluidframework_tpu.loader import Container

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "fixtures")


def _load(name):
    service = load_document(os.path.join(HERE, f"{name}.json"))
    with open(os.path.join(HERE, f"{name}.expect.json")) as f:
        return service, json.load(f)


def test_golden_v1_loads_and_matches():
    service, expect = _load("golden_v1")
    c = Container.load(service, client_id="reader", connect=False)
    ds = c.runtime.get_datastore("app")
    assert ds.get_channel("text").get_text() == expect["text"]
    assert ds.get_channel("kv").get("version") == expect["kv_version"]
    sig = hashlib.sha256(
        str(ds.get_channel("tree").signature()).encode()
    ).hexdigest()
    assert sig == expect["tree_signature_sha"]
    grid = ds.get_channel("grid")
    cells = [[grid.get_cell(r, co) for co in range(2)]
             for r in range(2)]
    assert cells == expect["grid_cells"]
    assert c.last_processed_seq == expect["final_seq"]


def test_golden_v1_resummarizes_and_reloads():
    """Round-trip: a summary produced by TODAY's code from the golden
    state must load back identically (forward path of the compat
    matrix)."""
    service, expect = _load("golden_v1")
    c = Container.load(service, client_id="reader", connect=False)
    summary = {
        "protocol": c.protocol.snapshot(),
        "runtime": c.runtime.summarize(),
    }
    from fluidframework_tpu.models import default_registry
    from fluidframework_tpu.runtime import ContainerRuntime

    fresh = ContainerRuntime(default_registry())
    fresh.load(summary["runtime"])
    ds = fresh.get_datastore("app")
    assert ds.get_channel("text").get_text() == expect["text"]
    assert ds.get_channel("kv").get("version") == expect["kv_version"]