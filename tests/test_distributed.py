"""Multi-host backend helpers (§5.8): single-process no-op gating,
global (docs, seq) mesh layout policy, host<->doc-lane bridging — and
the seq-sharded kernel running over the global mesh."""
import jax
import numpy as np
import pytest

from fluidframework_tpu.ops import (
    apply_window,
    build_batch,
    encode_stream,
    fetch,
    make_table,
)
from fluidframework_tpu.parallel import (
    DistributedConfig,
    apply_window_seq_sharded,
    ensure_initialized,
    local_doc_slice,
    make_global_mesh,
)
from fluidframework_tpu.testing import FuzzConfig, record_op_stream


def test_single_process_is_noop():
    assert ensure_initialized(DistributedConfig()) is False
    assert ensure_initialized(
        DistributedConfig(coordinator=None, num_processes=4)
    ) is False
    # coordinator set but single process: still local mode
    assert ensure_initialized(
        DistributedConfig(coordinator="host:1234", num_processes=1)
    ) is False


def test_global_mesh_layout():
    mesh = make_global_mesh()  # 1 process -> 1 doc lane x 8 seq
    assert mesh.shape == {"docs": 1, "seq": 8}
    mesh2 = make_global_mesh(doc_shards=4)
    assert mesh2.shape == {"docs": 4, "seq": 2}
    with pytest.raises(ValueError, match="not divisible"):
        make_global_mesh(doc_shards=3)


def test_local_doc_slice_single_process():
    assert local_doc_slice(10) == slice(0, 10)


def test_seq_sharded_window_on_global_mesh():
    mesh = make_global_mesh(doc_shards=2)
    cases = [
        record_op_stream(FuzzConfig(n_clients=3, n_steps=90,
                                    seed=7000 + i))
        for i in range(4)
    ]
    streams = [s for _, s in cases]
    encs = [encode_stream(s) for s in streams]
    batch = build_batch(encs)
    table = make_table(4, 256)
    ref = fetch(apply_window(table, batch))
    shd = fetch(apply_window_seq_sharded(table, batch, mesh))
    for key in ref:
        np.testing.assert_array_equal(ref[key], shd[key], err_msg=key)
