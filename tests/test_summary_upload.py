"""Client-driven summary upload over the wire (VERDICT r3 missing #1):
the elected summarizer uploads the summary tree to service storage
(chunked, token-gated) and proposes only the HANDLE on the op stream;
scribe validates the handle and commits the version.

Reference flow: containerRuntime.ts:2477 (summarize -> upload ->
submit handle), driver-definitions/src/storage.ts:119
(uploadSummaryWithContext), historian summary routes; scribe ack in
server/routerlicious/packages/lambdas/src/scribe/lambda.ts.
"""
import asyncio
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from fluidframework_tpu.drivers.socket_driver import (
    SocketDocumentService,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.service.ingress import AlfredServer
from fluidframework_tpu.service.lambdas import SummaryStore
from fluidframework_tpu.service.tenancy import (
    SCOPE_READ,
    TenantManager,
    sign_token,
)




def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _negotiate(svc, client_id="alice"):
    """The upload plane requires a prior connect_document (the wire
    version agreed there authorizes 1.1 frames); tests driving raw
    upload frames must negotiate like any real client."""
    return svc.connect_to_delta_stream(client_id, lambda m: None)


def test_summary_store_stage_commit_roundtrip():
    store = SummaryStore()
    root = store.stage({"a": {"x": 1}, "b": [1, 2]})
    assert store.has_tree(root)
    assert store.latest() is None  # staged, not committed
    store.commit(7, root)
    latest = store.latest()
    assert latest.sequence_number == 7
    assert latest.summary == {"a": {"x": 1}, "b": [1, 2]}
    assert not store.has_tree("not-a-sha")


def test_upload_then_summarize_handle_over_tcp(alfred):
    """Full wire loop: ops -> upload_summary (chunked) -> SUMMARIZE
    with handle -> scribe ack -> fetch_summary serves the
    client-uploaded tree; a second client loads from it."""
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "d",
                                timeout=15.0)
    try:
        with svc.lock:
            c = Container.load(svc, client_id="alice")
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            c.flush()
            t.insert_text(0, "uploaded state")
            c.flush()
        assert _wait(lambda: c.runtime.pending.count == 0)
        with svc.lock:
            c.summarize()
        # scribe commits asynchronously via the sequenced ack
        assert _wait(lambda: svc.get_latest_summary() is not None)
        seq, summary = svc.get_latest_summary()
        assert "protocol" in summary and "runtime" in summary
        # the orderer's store holds exactly one committed version and
        # the op log truncated below the summarized refseq
        orderer = server.local.get_orderer("d")
        assert orderer.summary_store.version_count == 1
        with svc.lock:
            c.close()
    finally:
        svc.close()

    # a fresh client loads from the client-uploaded summary
    svc2 = SocketDocumentService("127.0.0.1", server.port, "d",
                                 timeout=15.0)
    try:
        with svc2.lock:
            c2 = Container.load(svc2, client_id="bob")
            t2 = c2.runtime.get_datastore("ds").get_channel("t")
            assert t2.get_text() == "uploaded state"
            c2.close()
    finally:
        svc2.close()


def test_upload_chunking_small_chunks(alfred):
    """Multi-chunk uploads reassemble exactly (chunk size forced tiny
    so even a small summary splits)."""
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "d",
                                timeout=15.0)
    try:
        with svc.lock:
            c = Container.load(svc, client_id="alice")
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            c.flush()
            t.insert_text(0, "x" * 500)
            c.flush()
        assert _wait(lambda: c.runtime.pending.count == 0)
        svc._UPLOAD_CHUNK = 64  # force many chunks
        with svc.lock:
            c.summarize()
        assert _wait(lambda: svc.get_latest_summary() is not None)
        _, summary = svc.get_latest_summary()
        assert "runtime" in summary
        c.close()
    finally:
        svc.close()


def test_summarize_unknown_handle_nacked(alfred):
    """A summarize proposing a handle storage never saw must NACK,
    not commit garbage."""
    from fluidframework_tpu.protocol.messages import DocumentMessage

    server = alfred()
    acks = []
    svc = SocketDocumentService("127.0.0.1", server.port, "d",
                                timeout=15.0)
    try:
        conn = svc.connect_to_delta_stream(
            "alice", lambda m: acks.append(m))
        conn.submit(DocumentMessage(
            client_sequence_number=1, reference_sequence_number=0,
            type=MessageType.SUMMARIZE,
            contents={"handle": "deadbeef",
                      "referenceSequenceNumber": 0},
        ))
        assert _wait(lambda: any(
            m.type == MessageType.SUMMARY_NACK for m in acks))
        assert svc.get_latest_summary() is None
    finally:
        svc.close()


def test_upload_requires_write_scope(alfred):
    """Token-gated: a doc:read token can fetch but not upload."""
    tm = TenantManager()
    tenant = tm.create_tenant("acme")
    server = alfred(tenants=tm)
    ro = sign_token(tenant.key, "acme", "d", "alice",
                    scopes=[SCOPE_READ])
    # read-mode connect: the doc:read token passes the handshake (and
    # negotiates the wire version the upload plane now requires), then
    # the upload itself must still be rejected for missing doc:write
    svc = SocketDocumentService("127.0.0.1", server.port, "d",
                                timeout=15.0, tenant_id="acme",
                                token=ro, mode="read")
    try:
        _negotiate(svc)
        with pytest.raises(PermissionError, match="write"):
            svc.upload_summary({"runtime": {}})
    finally:
        svc.close()


def test_upload_out_of_order_chunk_rejected(alfred):
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "d",
                                timeout=15.0)
    try:
        _negotiate(svc)
        svc._request({
            "type": "upload_summary_chunk", "document_id": "d",
            "upload_id": "u1", "chunk": 0, "total": 3,
            "data": "xx",
        })
        with pytest.raises(RuntimeError, match="out of order"):
            svc._request({
                "type": "upload_summary_chunk", "document_id": "d",
                "upload_id": "u1", "chunk": 2, "total": 3,
                "data": "xx",
            })
    finally:
        svc.close()


@pytest.mark.slow
def test_sigkill_restart_resumes_from_client_uploaded_summary(
        tmp_path):
    """VERDICT r3 #6 done-criterion: SIGKILL the service after a
    CLIENT-UPLOADED summary committed; the restarted service loads
    documents from that summary (op log truncated below it, so the
    summary — not the log — must carry the state)."""
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data_dir = str(tmp_path / "data")

    def start_server():
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.service",
             "--port", "0", "--data-dir", data_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=repo,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        line = proc.stdout.readline()
        m = re.search(r"listening on [\w.]+:(\d+)", line)
        assert m, line
        return proc, int(m.group(1))

    code = (
        "import sys, time; sys.path.insert(0, '.')\n"
        "from fluidframework_tpu.drivers.socket_driver import "
        "SocketDocumentService\n"
        "from fluidframework_tpu.loader import Container\n"
        "svc = SocketDocumentService('127.0.0.1', PORT, 'sum-doc')\n"
        "with svc.lock:\n"
        "    c = Container.load(svc, client_id='alice')\n"
        "    t = c.runtime.create_datastore('d')"
        ".create_channel('sharedstring', 't')\n"
        "    c.flush()\n"
        "    t.insert_text(0, 'summarized state')\n"
        "    c.flush()\n"
        "deadline = time.time() + 30\n"
        "while time.time() < deadline:\n"
        "    with svc.lock:\n"
        "        if c.runtime.pending.count == 0: break\n"
        "    time.sleep(0.02)\n"
        "with svc.lock:\n"
        "    c.summarize()\n"
        "deadline = time.time() + 30\n"
        "while time.time() < deadline:\n"
        "    if svc.get_latest_summary() is not None: break\n"
        "    time.sleep(0.05)\n"
        "else:\n"
        "    raise TimeoutError('summary never committed')\n"
        "print('UPLOADED')\n"
        "c.close(); svc.close()\n"
    )
    server, port = start_server()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code.replace("PORT", str(port))],
            capture_output=True, text=True, cwd=repo,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "UPLOADED" in proc.stdout
    finally:
        os.kill(server.pid, signal.SIGKILL)
        server.wait()

    server, port = start_server()
    try:
        check = (
            "import sys; sys.path.insert(0, '.')\n"
            "from fluidframework_tpu.drivers.socket_driver import "
            "SocketDocumentService\n"
            "from fluidframework_tpu.loader import Container\n"
            "svc = SocketDocumentService('127.0.0.1', PORT, "
            "'sum-doc')\n"
            "seq, summary = svc.get_latest_summary()\n"
            "print('SUMMARY_AT=' + str(seq))\n"
            "with svc.lock:\n"
            "    c = Container.load(svc, client_id='bob')\n"
            "    t = c.runtime.get_datastore('d').get_channel('t')\n"
            "    print('TEXT=' + t.get_text())\n"
            "c.close(); svc.close()\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", check.replace("PORT", str(port))],
            capture_output=True, text=True, cwd=repo,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "TEXT=summarized state" in proc.stdout
    finally:
        server.kill()
        server.wait()


def test_upload_concurrency_limit_rejects_new_not_evicts_old(alfred):
    """Hitting MAX_UPLOADS_IN_FLIGHT must reject the NEW upload with
    an explicit error; in-flight uploads keep working (ADVICE r4: the
    old eviction killed a live upload on a multiplexed connection and
    its next chunk then failed with a misleading out-of-order error)."""
    import json as _json

    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "d",
                                timeout=15.0)
    try:
        _negotiate(svc)
        payload = _json.dumps({"runtime": {}})
        for i in range(4):  # MAX_UPLOADS_IN_FLIGHT
            svc._request({
                "type": "upload_summary_chunk", "document_id": "d",
                "upload_id": f"u{i}", "chunk": 0, "total": 2,
                "data": payload[:1],
            })
        with pytest.raises(RuntimeError,
                           match="too many concurrent uploads"):
            svc._request({
                "type": "upload_summary_chunk", "document_id": "d",
                "upload_id": "u-over", "chunk": 0, "total": 2,
                "data": payload[:1],
            })
        # the in-flight upload u0 is untouched: its final chunk lands
        resp = svc._request({
            "type": "upload_summary_chunk", "document_id": "d",
            "upload_id": "u0", "chunk": 1, "total": 2,
            "data": payload[1:],
        })
        assert resp.get("handle")
        # abandoned uploads are reclaimed once idle past the TTL:
        # u1-u3 are still staged; after the TTL, FOUR brand-new
        # uploads must all be accepted — impossible unless the three
        # abandoned ones were swept (non-vacuous: without the sweep
        # the second new id below hits the cap)
        server.UPLOAD_IDLE_TTL = 0.05
        time.sleep(0.2)
        for i in range(4):
            resp = svc._request({
                "type": "upload_summary_chunk", "document_id": "d",
                "upload_id": f"u-new{i}", "chunk": 0, "total": 2,
                "data": payload[:1],
            })
            assert resp.get("type") != "error", resp
    finally:
        svc.close()


def test_container_summarize_surfaces_permission_error():
    """An upload plane that rejects for auth must raise out of
    summarize(), not silently degrade to inline summaries forever
    (ADVICE r4: PermissionError is an OSError subclass and was
    swallowed by the transient-failure fallback)."""
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.service import LocalServer

    server = LocalServer()
    svc = LocalDocumentServiceFactory(server).create_document_service(
        "doc")

    def denied(summary):
        raise PermissionError("token lacks doc:write")

    svc.upload_summary = denied
    c = Container.load(svc, client_id="alice")
    c.runtime.create_datastore("ds").create_channel("sharedstring", "t")
    c.flush()
    with pytest.raises(PermissionError):
        c.summarize()
    c.close()


def test_upload_continuation_of_unknown_id_distinct_error(alfred):
    """chunk>0 for an id the server doesn't know (rejected at the cap,
    TTL-reclaimed, or never started) gets an accurate error, not the
    misleading 'out of order' from a freshly-created state
    (code-review r5)."""
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "d",
                                timeout=15.0)
    try:
        _negotiate(svc)
        with pytest.raises(RuntimeError,
                           match="rejected, expired, or never started"):
            svc._request({
                "type": "upload_summary_chunk", "document_id": "d",
                "upload_id": "ghost", "chunk": 1, "total": 3,
                "data": "xx",
            })
    finally:
        svc.close()
