"""fluidsan (testing/sanitizer.py) unit tests plus the static/dynamic
differential: every lock-order edge the sanitizer observes at runtime
must be a subset of the concheck static lock graph
(analysis/concurrency.py) — a runtime edge the static pass cannot
derive is an analyzer-resolution gap and fails HERE, by name, instead
of silently narrowing the deadlock gate's coverage.
"""
import threading
import time

import pytest

from fluidframework_tpu.testing import sanitizer as san


@pytest.fixture()
def sanitized():
    """Install the sanitizer with a clean registry; always restore
    (refcounted, so an FFTPU_SANITIZE=1 session stays installed)."""
    san.install()
    san.reset()
    yield san
    san.reset()
    san.uninstall()


def test_scripted_two_thread_inversion_trips(sanitized):
    """A deterministic AB/BA inversion: thread one takes A then B and
    finishes; thread two then takes B then A (sequenced by events, so
    no real deadlock) — the order HISTORY alone must trip, with the
    edge pair, both thread names and a flight dump in the payload."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    t1_done = threading.Event()
    trips_before = san.trips()
    metric_before = san._TRIPS_TOTAL.value

    def forward():
        with lock_a:
            with lock_b:
                pass
        t1_done.set()

    def backward():
        assert t1_done.wait(10)
        with lock_b:
            with lock_a:
                pass

    t1 = threading.Thread(target=forward, name="san-forward")
    t2 = threading.Thread(target=backward, name="san-backward")
    t1.start()
    t2.start()
    t1.join(10)
    t2.join(10)

    fresh = san.trips()[len(trips_before):]
    assert len(fresh) == 1
    trip = fresh[0]
    # the edge pair: forward order was A (first) -> B (second), both
    # created in THIS file a couple of lines apart
    assert trip.first_site.relpath.endswith("test_sanitizer.py")
    assert trip.second_site.relpath.endswith("test_sanitizer.py")
    assert trip.second_site.line == trip.first_site.line + 1
    assert trip.first_site.name == "lock_a"
    assert trip.second_site.name == "lock_b"
    # both thread names, attributed to the right roles
    assert trip.thread_name == "san-backward"
    assert trip.other_thread_name == "san-forward"
    # the flight dump rides the payload and shows the history
    assert "acquire" in trip.flight_dump
    assert "san-forward" in trip.flight_dump
    # the obs metric counted it
    assert san._TRIPS_TOTAL.value == metric_before + 1


def test_consistent_order_and_reentrant_rlock_do_not_trip(sanitized):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    rl = threading.RLock()
    done = threading.Event()

    def worker():
        with lock_a:
            with lock_b:
                pass
        with rl:
            with rl:  # reentrant: no self-edge, no trip
                pass
        done.set()

    t = threading.Thread(target=worker, name="san-worker")
    t.start()
    assert done.wait(10)
    t.join(10)
    with lock_a:
        with lock_b:  # same order again, other thread: still fine
            pass
    assert san.trips() == []


def test_condition_and_queue_interop_keeps_locksets_truthful(
        sanitized):
    """Condition.wait fully releases an RLock (via _release_save) and
    re-acquires it; the per-thread lockset must follow, or every lock
    taken while waiting would record phantom edges."""
    import queue

    cond = threading.Condition()
    q = queue.Queue(maxsize=4)
    got = []

    def consumer():
        with cond:
            while not got:
                cond.wait(5)

    def producer():
        q.put("x")
        got.append(q.get())
        with cond:
            cond.notify_all()

    t1 = threading.Thread(target=consumer, name="san-consumer")
    t2 = threading.Thread(target=producer, name="san-producer")
    t1.start()
    time.sleep(0.05)
    t2.start()
    t1.join(10)
    t2.join(10)
    assert got == ["x"]
    assert san.trips() == []


def test_edges_aggregate_to_creation_sites(sanitized):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    with lock_a:
        with lock_b:
            pass
    sites = san.edges_by_site(repo_only=False)
    ours = {
        (a, b) for (a, b) in sites
        if a[0].endswith("test_sanitizer.py")
        and b[0].endswith("test_sanitizer.py")
    }
    assert len(ours) == 1
    ((a, b),) = ours
    assert b[1] == a[1] + 1  # created on adjacent lines, in order


# ---------------------------------------------------------------- differential


def _static_lock_edges():
    from fluidframework_tpu.analysis import concurrency
    from fluidframework_tpu.analysis.core import walk_python_files

    files = walk_python_files(["fluidframework_tpu"])
    ana = concurrency.build_analysis(files)
    return ana, ana.lock_edges_by_site()


def test_runtime_lock_edges_are_subset_of_static_graph(alfred):
    """THE closing of the loop: drive the real socket driver through
    the dispatch-thread re-entry path (a delivery callback issuing a
    blocking read_ops — the gap-refetch shape), collect the runtime
    lock-order edges, and assert each one exists in concheck's static
    lock graph. A missing edge means the static analyzer can no
    longer see a path the runtime takes — fix resolution or register
    it in concurrency.INDIRECT_CALLS; do NOT weaken this test."""
    from fluidframework_tpu.drivers.socket_driver import (
        SocketDocumentService,
    )

    ana, static_edges = _static_lock_edges()

    san.install()
    try:
        san.reset()
        server = alfred()
        svc = SocketDocumentService("127.0.0.1", server.port,
                                    "san-doc")
        refetched = []

        def on_message(msg):
            # the dispatch thread holds svc.lock here; a blocking
            # request from inside the callback nests
            # _pending_lock/_send_lock under it
            if not refetched:
                refetched.append(svc.read_ops(0))

        svc.connect_to_delta_stream("sanity", on_message=on_message)
        deadline = time.monotonic() + 10
        while not refetched and time.monotonic() < deadline:
            time.sleep(0.02)
        assert refetched, "delivery callback never ran"
        svc.close()
        runtime_edges = san.edges_by_site()
    finally:
        san.reset()
        san.uninstall()

    missing = runtime_edges - static_edges
    assert not missing, (
        "ANALYZER-RESOLUTION GAP: the sanitizer observed lock-order "
        "edges the concheck static graph does not contain:\n"
        + "\n".join(
            f"  {a[0]}:{a[1]} -> {b[0]}:{b[1]}" for a, b in
            sorted(missing)
        )
        + "\nadd call-graph resolution (or an INDIRECT_CALLS entry "
        "with justification) in analysis/concurrency.py"
    )

    # the scenario is not vacuous: the dispatch-thread nesting was
    # actually observed (svc.lock -> _pending_lock and -> _send_lock)
    sd = "fluidframework_tpu/drivers/socket_driver.py"
    creation = {
        lock_id.attr: (lock_id.relpath, info.creation_line)
        for lock_id, info in ana.locks.items()
        if lock_id.relpath == sd
        and lock_id.scope == "SocketDocumentService"
    }
    assert (creation["lock"], creation["_pending_lock"]) \
        in runtime_edges
    assert (creation["lock"], creation["_send_lock"]) in runtime_edges


def test_static_graph_contains_the_declared_indirect_edges():
    """The INDIRECT_CALLS registry is load-bearing for the
    differential: deleting it must fail loudly here, not only when
    the (heavier) runtime test runs."""
    ana, static_edges = _static_lock_edges()
    sd = "fluidframework_tpu/drivers/socket_driver.py"
    by_attr = {
        lock_id.attr: (lock_id.relpath, info.creation_line)
        for lock_id, info in ana.locks.items()
        if lock_id.relpath == sd
    }
    assert (by_attr["lock"], by_attr["_pending_lock"]) in static_edges
    assert (by_attr["lock"], by_attr["_send_lock"]) in static_edges
