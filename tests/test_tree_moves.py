"""SharedTree moves: the detach+revive pairing (changeset.move).

Reference parity target: sequence-field MoveOut/MoveIn
(feature-libraries/sequence-field/format.ts) under the ChangeRebaser
laws (core/rebase/rebaser.ts:138-170). Semantics choice (documented on
changeset.move): DELETE WINS on a concurrent source delete — both
halves mute, and undoing that delete unmutes the whole move.

Covers: algebra laws fuzzed WITH moves, EditManager convergence with
concurrent moves, directed move-vs-delete / move-vs-move scenarios,
and the end-to-end SharedTree surface (incl. transactions/anchors).
"""
import random

import pytest

from fluidframework_tpu.models.tree import changeset as cs
from fluidframework_tpu.models.tree import node
from fluidframework_tpu.models.tree.forest import Forest
from fluidframework_tpu.testing.runtime_mocks import ContainerSession
from fluidframework_tpu.testing.tree_fuzz import random_change_with_moves


def mk_nodes(n, base=0):
    return [node("n", value=base + i) for i in range(n)]


def applied(base, *changes_revs):
    f = Forest({"root": [dict(x) for x in base]})
    for change, rev in changes_revs:
        f.apply(change, rev)
    return f.content()["root"]


@pytest.mark.parametrize("seed", range(40))
def test_move_rebase_laws(seed):
    """rebase(a, compose(b, c)) == rebase(rebase(a, b), c) and the
    identity laws, with moves in all three changesets."""
    rng = random.Random(seed * 17 + 3)
    base = mk_nodes(6)
    a = random_change_with_moves(rng, base, f"A{seed}")
    b = random_change_with_moves(rng, base, f"B{seed}")
    fb = Forest({"root": [dict(x) for x in base]})
    fb.apply(b, "b")
    c = random_change_with_moves(
        rng, fb.content()["root"], f"C{seed}"
    )
    fb.apply(c, "c")  # fb now holds base+b+c WITH their repair data

    lhs = cs.rebase(a, cs.compose([b, c]))
    rhs = cs.rebase(cs.rebase(a, b), c)
    fl, fr = fb.clone(), fb.clone()
    fl.apply(lhs, "L")
    fr.apply(rhs, "R")
    assert fl.content()["root"] == fr.content()["root"]

    assert cs.rebase(a, cs.compose([])) == a
    assert cs.rebase(cs.compose([]), a) == {}


@pytest.mark.parametrize("seed", range(30))
def test_move_invert_roundtrip(seed):
    """compose([a, invert(a)]) applies as a no-op — a move's inverse
    is the move back."""
    rng = random.Random(seed * 29 + 11)
    base = mk_nodes(6)
    a = random_change_with_moves(rng, base, f"A{seed}")
    inv = cs.invert(a, f"inv{seed}")
    out = applied(base, (a, "a"), (inv, "inv"))
    assert out == base


def _session():
    s = ContainerSession(["A", "B"])
    for cid in ("A", "B"):
        s.runtime(cid).create_datastore("d").create_channel(
            "sharedtree", "t")
    s.process_all()
    return (s, s.runtime("A").get_datastore("d").get_channel("t"),
            s.runtime("B").get_datastore("d").get_channel("t"))


def test_move_basic_and_converges():
    s, a, b = _session()
    a.insert_nodes(("root",), 0, mk_nodes(4))
    s.process_all()
    a.move_nodes(("root",), 0, 2, 4)  # [0,1,2,3] -> [2,3,0,1]
    s.process_all()
    s.assert_converged()
    assert [n["value"] for n in b.get_field(("root",))] == [2, 3, 0, 1]


def test_move_vs_concurrent_delete_delete_wins():
    s, a, b = _session()
    a.insert_nodes(("root",), 0, mk_nodes(4))
    s.process_all()
    b.delete_nodes(("root",), 0, 2)     # sequences first
    a.move_nodes(("root",), 0, 2, 4)    # concurrent move of the same
    s.flush("B")
    s.flush("A")
    s.process_all()
    s.assert_converged()
    assert [n["value"] for n in b.get_field(("root",))] == [2, 3]


def test_move_vs_concurrent_delete_then_undo():
    """Undoing the winning delete restores the nodes at their SOURCE:
    the muted move is sequenced history by then, and unmute-through-
    tombstones applies only to changes still being rebased (pending /
    branch changes), never retroactively to the trunk. All replicas
    agree."""
    s, a, b = _session()
    a.insert_nodes(("root",), 0, mk_nodes(4))
    s.process_all()
    b.delete_nodes(("root",), 0, 2)
    a.move_nodes(("root",), 0, 2, 4)
    s.flush("B")
    s.flush("A")
    s.process_all()
    s.assert_converged()
    # b undoes its delete (inverse changeset via the DDS escape hatch)
    em = b._em
    del_commit = [c for c in em.trunk
                  if c.session_id == "B"][-1]
    b.apply_changeset(cs.invert(del_commit.changes, "undo"))
    s.process_all()
    s.assert_converged()
    assert [n["value"] for n in a.get_field(("root",))] == [0, 1, 2, 3]


def test_concurrent_moves_of_same_nodes():
    """Two clients move the same node to different places: the
    earlier-sequenced move detaches it; the later move's halves mute
    (its source is gone — same delete-wins rule) and the node lands at
    the first mover's destination."""
    s, a, b = _session()
    a.insert_nodes(("root",), 0, mk_nodes(4))
    s.process_all()
    a.move_nodes(("root",), 0, 1, 4)
    b.move_nodes(("root",), 0, 1, 2)
    s.flush("A")
    s.flush("B")
    s.process_all()
    s.assert_converged()
    assert sorted(n["value"] for n in a.get_field(("root",))) == \
        [0, 1, 2, 3]


def test_move_inside_transaction_with_anchor():
    s, a, b = _session()
    a.insert_nodes(("root",), 0, mk_nodes(5))
    s.process_all()
    anchor = a.track_anchor(("root",), 3)
    with a.transaction():
        a.move_nodes(("root",), 0, 2, 5)  # [2,3,4,0,1]
        a.set_value(("root",), 4, 99)
    s.process_all()
    s.assert_converged()
    # post-move view [2,3,4,0,1]; set_value(4) targets the node "1"
    assert [n["value"] for n in b.get_field(("root",))] == \
        [2, 3, 4, 0, 99]
    loc = a.locate_anchor(anchor)
    assert loc is not None
    assert a.get_field(("root",))[loc[-1]]["value"] == 3


def test_editable_move():
    s, a, b = _session()
    items = a.editable().field("root")
    items.insert(0, mk_nodes(3))
    s.process_all()
    items.move(0, 3)
    s.process_all()
    s.assert_converged()
    assert [n["value"] for n in b.get_field(("root",))] == [1, 2, 0]

def test_transaction_insert_then_move_squashes_correctly():
    """Composing [insert, move-of-the-inserted] (transaction squash)
    must not orphan the move's rev half into repair-missing nodes
    (code-review r3, reproduced): the net effect is an insert at the
    destination."""
    s, a, b = _session()
    a.insert_nodes(("root",), 0, mk_nodes(2, 10))
    s.process_all()
    with a.transaction():
        a.insert_nodes(("root",), 0, mk_nodes(2, 50))  # [50,51,10,11]
        a.move_nodes(("root",), 0, 2, 4)               # [10,11,50,51]
    s.process_all()
    s.assert_converged()
    assert [n["value"] for n in b.get_field(("root",))] == \
        [10, 11, 50, 51]


def test_two_moves_same_geometry_different_fields():
    """Default pair tokens must be unique: two moves with identical
    (src, count, dst) in different fields of one changeset must not
    cross-wire their pairings (code-review r3, reproduced)."""
    change = {
        "a": cs.move(0, 1, 2),
        "b": cs.move(0, 1, 2),
    }
    cs.stamp(change, "u1")
    f = Forest({
        "a": mk_nodes(2, 0),     # values [0, 1]
        "b": mk_nodes(2, 100),   # values [100, 101]
    })
    f.apply(change, "r1")
    out = f.content()
    assert [n["value"] for n in out["a"]] == [1, 0]
    assert [n["value"] for n in out["b"]] == [101, 100]


def test_anchor_follows_move():
    """An anchor on a moved node follows it to the destination instead
    of dying (anchorSet.ts move semantics; code-review r3,
    reproduced)."""
    s, a, _b = _session()
    a.insert_nodes(("root",), 0, mk_nodes(4))
    s.process_all()
    anchor = a.track_anchor(("root",), 0)
    a.move_nodes(("root",), 0, 1, 4)  # [1,2,3,0]
    loc = a.locate_anchor(anchor)
    assert loc is not None
    assert a.get_field(("root",))[loc[-1]]["value"] == 0


def test_trunk_move_rejected_by_kernel_encoder():
    """A move in the rebased-OVER role must take the host path: the
    kernel's rebase math does not model follow-the-move shifts
    (code-review r3)."""
    import pytest as _pytest

    from fluidframework_tpu.ops.tree_atoms import encode_changeset

    marks = cs.stamp({"root": cs.move(0, 1, 3)}, "u")["root"]
    encode_changeset(marks)  # fine in the rebased role
    with _pytest.raises(ValueError, match="host path"):
        encode_changeset(marks, allow_moves=False)
