"""Legacy (anchor-based) SharedTree: atomic edits, anchor re-resolution
under concurrency, edit drop semantics, constraints, undo from repair
data, summarize/load.

Reference behavior: experimental/dds/tree/src/{TransactionInternal.ts,
ChangeTypes.ts, HistoryEditFactory.ts}.
"""
import pytest

from fluidframework_tpu.models.legacy_tree import (
    APPLIED,
    INVALID,
    MALFORMED,
    build,
    constraint,
    delete_,
    detach,
    insert,
    insert_tree,
    move,
    place_after,
    place_at_end,
    place_at_start,
    place_before,
    range_all,
    range_of,
    set_value,
)
from fluidframework_tpu.testing.runtime_mocks import ContainerSession


def make_session(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    for c in ids:
        s.runtime(c).create_datastore("ds").create_channel(
            "legacysharedtree", "tree")
    trees = [
        s.runtime(c).get_datastore("ds").get_channel("tree")
        for c in ids
    ]
    return s, trees


def leaf(ident, definition="item", payload=None):
    return {"definition": definition, "identifier": ident,
            "payload": payload}


def kids_of(tree, parent="root", label="items"):
    return tree.view.trait(parent, label)


def test_build_insert_roundtrip():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("n1", payload=1), leaf("n2", payload=2)],
                        place_at_start("root", "items")))
    s.process_all()
    assert kids_of(a) == ["n1", "n2"]
    assert kids_of(b) == ["n1", "n2"]
    assert a.signature() == b.signature()
    assert a.edit_log[-1]["status"] == APPLIED


def test_concurrent_sibling_anchored_inserts():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("base")],
                        place_at_start("root", "items")))
    s.process_all()
    # both insert after the same sibling concurrently; both anchors
    # re-resolve -> both land, sequenced order decides adjacency
    a.apply(insert_tree([leaf("a1")], place_after("base")))
    b.apply(insert_tree([leaf("b1")], place_after("base")))
    s.process_all()
    assert a.signature() == b.signature()
    assert set(kids_of(a)) == {"base", "a1", "b1"}
    assert kids_of(a)[0] == "base"


def test_edit_on_concurrently_deleted_sibling_drops():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("x"), leaf("y")],
                        place_at_start("root", "items")))
    s.process_all()
    # A deletes x; B concurrently anchors an insert after x
    a.apply(delete_(range_of(place_before("x"), place_after("x"))))
    b.apply(insert_tree([leaf("z")], place_after("x")))
    s.process_all()
    assert a.signature() == b.signature()
    # B's edit dropped: its anchor no longer resolves
    assert kids_of(a) == ["y"]
    statuses = [e["status"] for e in a.edit_log]
    assert statuses[-1] == INVALID


def test_atomicity_partial_failure_rolls_back():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("x", payload=0)],
                        place_at_start("root", "items")))
    s.process_all()
    # one edit: a valid set_value AND an invalid insert -> whole edit
    # drops, payload untouched
    a.apply(set_value("x", 99), insert(7, place_after("ghost")))
    s.process_all()
    assert a.view.nodes["x"]["payload"] == 0
    assert b.view.nodes["x"]["payload"] == 0
    assert a.edit_log[-1]["status"] == MALFORMED


def test_constraint_guards_edit():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("x"), leaf("y")],
                        place_at_start("root", "items")))
    s.process_all()
    # A's edit requires the trait to still have exactly 2 items
    a.apply([constraint(range_all("root", "items"), length=2),
             set_value("x", "guarded")])
    # B concurrently deletes y -> A's constraint must fail on every
    # replica IF B sequences first; here A sequenced first so it lands
    s.process_all()
    assert a.view.nodes["x"]["payload"] == "guarded"
    b.apply(delete_(range_of(place_before("y"), place_after("y"))))
    a.apply([constraint(range_all("root", "items"), length=2),
             set_value("x", "second")])
    s.flush("B")  # B's delete sequences before A's guarded edit
    s.process_all()
    # constraint (length==2) fails after the delete
    assert a.view.nodes["x"]["payload"] == "guarded"
    assert a.edit_log[-1]["status"] == INVALID
    assert a.signature() == b.signature()


def test_move_between_traits():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("box", "container")],
                        place_at_start("root", "items")))
    a.apply(insert_tree([leaf("ball")], place_at_start("root", "loose")))
    s.process_all()
    a.apply(move(range_of(place_before("ball"), place_after("ball")),
                 place_at_start("box", "contents")))
    s.process_all()
    assert kids_of(a, "box", "contents") == ["ball"]
    assert kids_of(a, "root", "loose") == []
    assert a.signature() == b.signature()


def test_set_value_lww_by_sequencing():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("x")], place_at_start("root", "items")))
    s.process_all()
    a.apply(set_value("x", "from-a"))
    b.apply(set_value("x", "from-b"))
    s.process_all()
    assert a.signature() == b.signature()
    # later-sequenced write wins
    assert a.view.nodes["x"]["payload"] == "from-b"


def test_undo_delete_restores_subtree():
    s, (a, b) = make_session()
    a.apply(insert_tree(
        [leaf("p", "parent"), leaf("q")],
        place_at_start("root", "items")))
    eid = a.apply(
        insert_tree([leaf("kid", payload=5)],
                    place_at_start("p", "children")))
    s.process_all()
    del_id = a.apply(delete_(range_of(place_before("p"),
                                      place_after("p"))))
    s.process_all()
    assert "p" not in a.view.nodes
    a.revert(del_id)
    s.process_all()
    assert a.signature() == b.signature()
    assert kids_of(a) == ["p", "q"]
    assert kids_of(a, "p", "children") == ["kid"]
    assert a.view.nodes["kid"]["payload"] == 5


def test_undo_insert_detaches_it():
    s, (a, b) = make_session()
    eid = a.apply(insert_tree([leaf("x")],
                              place_at_start("root", "items")))
    s.process_all()
    a.revert(eid)
    s.process_all()
    assert kids_of(a) == []
    assert a.signature() == b.signature()


def test_pending_local_view_is_optimistic():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("x")], place_at_start("root", "items")))
    # before sequencing: A sees it, B does not
    assert kids_of(a) == ["x"]
    assert kids_of(b) == []
    s.process_all()
    assert kids_of(b) == ["x"]


def test_summarize_load_roundtrip():
    s, (a, b) = make_session()
    a.apply(insert_tree(
        [leaf("p", "parent", payload="v")],
        place_at_start("root", "items")))
    a.apply(insert_tree([leaf("c", payload=3)],
                        place_at_start("p", "sub")))
    s.process_all()
    summary = a.summarize_core()
    from fluidframework_tpu.models.legacy_tree import LegacySharedTree

    fresh = LegacySharedTree("tree2")
    fresh.load_core(summary)
    assert fresh.signature() == a.signature()
    assert fresh.view.nodes["c"]["payload"] == 3


def test_duplicate_node_id_is_malformed():
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("x")], place_at_start("root", "items")))
    s.process_all()
    a.apply(insert_tree([leaf("x")], place_at_end("root", "items")))
    s.process_all()
    assert a.edit_log[-1]["status"] == MALFORMED
    assert kids_of(a) == ["x"]
    assert a.signature() == b.signature()


def test_revert_move_moves_back():
    """Regression: reverting a move must move the subtree BACK, not
    delete it (the insert half's inverse used to be a plain delete)."""
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("n1", payload="keep")],
                        place_at_start("root", "items")))
    s.process_all()
    mid = a.apply(move(range_of(place_before("n1"), place_after("n1")),
                       place_at_end("root", "archive")))
    s.process_all()
    assert kids_of(a, "root", "archive") == ["n1"]
    a.revert(mid)
    s.process_all()
    assert a.signature() == b.signature()
    assert kids_of(a) == ["n1"]
    assert kids_of(a, "root", "archive") == []
    assert a.view.nodes["n1"]["payload"] == "keep"


def test_revert_move_of_empty_range_is_noop():
    """Regression: an APPLIED move of an EMPTY range produced an
    insert repair entry with ids=[]; revert used to IndexError on
    inserted[0] instead of emitting a no-op inverse."""
    s, (a, b) = make_session()
    a.apply(insert_tree([leaf("n1")], place_at_start("root", "items")))
    s.process_all()
    # empty range: before-n1 .. before-n1 selects zero nodes
    mid = a.apply(move(range_of(place_before("n1"), place_before("n1")),
                       place_at_end("root", "archive")))
    s.process_all()
    assert a.edit_log[-1]["status"] == APPLIED
    a.revert(mid)          # must not raise
    s.process_all()
    assert a.signature() == b.signature()
    assert kids_of(a) == ["n1"]


def test_revert_ids_do_not_collide_across_clients():
    """Regression: repair data is keyed by global seq; two clients'
    edit #N must not collide (revert used to invert the wrong edit)."""
    s, (a, b) = make_session()
    # both clients' FIRST edit (local edit_id 0 on each side)
    a_id = a.apply(insert_tree([leaf("from-a", payload="A")],
                               place_at_start("root", "items")))
    b_id = b.apply(insert_tree([leaf("from-b", payload="B")],
                               place_at_end("root", "items")))
    s.process_all()
    assert a_id == b_id == 0  # the collision-prone ids
    # A reverts ITS edit: only from-a disappears
    a.revert(a_id)
    s.process_all()
    assert a.signature() == b.signature()
    assert "from-a" not in a.view.nodes
    assert "from-b" in a.view.nodes
    # history undo by sequence number still reaches any edit
    seq_of_b = next(e["seq"] for e in a.edit_log
                    if e["status"] == APPLIED
                    and "from-b" in str(e["changes"]))
    a.revert_seq(seq_of_b)
    s.process_all()
    assert "from-b" not in a.view.nodes
    assert a.signature() == b.signature()
