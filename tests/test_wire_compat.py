"""Live wire-version compat matrix (describeCompat analogue for the
FRAME axis — packages/test/test-version-utils pairs old clients with
new services and vice versa; here the pairings are real TCP sessions
against a real server, not format shims).

Wire 1.0 = base frames; wire 1.1 adds the chunked summary-upload
plane. The matrix drives: negotiation outcome, live collaboration
across mixed-version clients, and the summarizer's degrade-to-inline
path whenever either side lacks 1.1.
"""
import asyncio
import threading
import time

import pytest

from fluidframework_tpu.drivers.socket_driver import (
    WIRE_VERSIONS,
    SocketDocumentService,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service import ingress as ingress_mod
from fluidframework_tpu.service.ingress import AlfredServer




def _pump(svc, container, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with svc.lock:
            if container.runtime.pending.count == 0:
                return True
        time.sleep(0.02)
    return False


def _load(port, doc, client_id, versions=None):
    svc = SocketDocumentService("127.0.0.1", port, doc,
                                timeout=15.0,
                                wire_versions=versions)
    with svc.lock:
        c = Container.load(svc, client_id=client_id)
    return svc, c


@pytest.mark.parametrize("client_versions,server_versions,agreed", [
    (("1.1", "1.0"), ("1.1", "1.0"), "1.1"),  # new / new
    (("1.0",), ("1.1", "1.0"), "1.0"),        # old client / new srv
    (("1.1", "1.0"), ("1.0",), "1.0"),        # new client / old srv
])
def test_negotiation_matrix(alfred, client_versions,
                            server_versions, agreed):
    server = alfred(server_versions=server_versions)
    svc, c = _load(server.port, "neg", "alice",
                   versions=client_versions)
    try:
        assert svc.agreed_version == agreed
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "negotiated")
            c.flush()
        assert _pump(svc, c)
        with svc.lock:
            assert t.get_text() == "negotiated"
            c.close()
    finally:
        svc.close()


def test_no_common_version_is_connect_error(alfred):
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "nc",
                                timeout=15.0,
                                wire_versions=("0.9",))
    try:
        with pytest.raises(Exception, match="no common wire version"):
            with svc.lock:
                Container.load(svc, client_id="alice")
    finally:
        svc.close()


@pytest.mark.parametrize("pairing,client_versions,server_versions", [
    ("old-client-new-server", ("1.0",), ("1.1", "1.0")),
    ("new-client-old-server", ("1.1", "1.0"), ("1.0",)),
])
def test_summarize_degrades_to_inline_on_10_pairings(
        alfred, pairing, client_versions, server_versions):
    """Either 1.0 pairing: the upload plane is unavailable, the
    summarizer must degrade to an INLINE summary that still lands and
    is loadable — never a wedge, never a server-side frame error."""
    server = alfred(server_versions=server_versions)
    svc, c = _load(server.port, "deg", "alice",
                   versions=client_versions)
    try:
        assert svc.agreed_version == "1.0"
        with pytest.raises(RuntimeError, match="wire"):
            svc.upload_summary({"runtime": {}})
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "inline fallback")
            c.flush()
        assert _pump(svc, c)
        with svc.lock:
            c.summarize()
        deadline = time.time() + 10
        latest = None
        while time.time() < deadline and latest is None:
            with svc.lock:
                latest = svc.get_latest_summary()
            time.sleep(0.05)
        assert latest is not None, f"{pairing}: summary never landed"
        _, summary = latest
        assert "runtime" in summary  # inline tree, not a handle stub
        # a fresh (new) client loads from it
        svc2, c2 = _load(server.port, "deg", "bob")
        with svc2.lock:
            t2 = c2.runtime.get_datastore("ds").get_channel("t")
            assert t2.get_text() == "inline fallback"
            c2.close()
        svc2.close()
        with svc.lock:
            c.close()
    finally:
        svc.close()


def test_mixed_version_clients_collaborate(alfred):
    """An old (1.0) and a new (1.1) client on the SAME document
    converge over live ops — frame compat is per-connection, not
    per-document."""
    server = alfred()
    svc_old, c_old = _load(server.port, "mix", "old",
                           versions=("1.0",))
    svc_new, c_new = _load(server.port, "mix", "new")
    try:
        assert svc_old.agreed_version == "1.0"
        assert svc_new.agreed_version == WIRE_VERSIONS[0]
        with svc_old.lock:
            t_old = c_old.runtime.create_datastore(
                "ds").create_channel("sharedstring", "t")
            t_old.insert_text(0, "from old ")
            c_old.flush()
        assert _pump(svc_old, c_old)
        time.sleep(0.3)
        with svc_new.lock:
            t_new = c_new.runtime.get_datastore(
                "ds").get_channel("t")
            t_new.insert_text(t_new.get_length(), "from new")
            c_new.flush()
        assert _pump(svc_new, c_new)
        time.sleep(0.3)
        with svc_old.lock, svc_new.lock:
            assert t_old.get_text() == t_new.get_text() == \
                "from old from new"
            c_old.close()
            c_new.close()
    finally:
        svc_old.close()
        svc_new.close()


def test_unnegotiated_connection_cannot_use_upload_frames(alfred):
    """A client that never ran connect_document gets a loud rejection
    for upload frames. Raw frames used to be waved through as
    "self-evidently 1.1", which made the version gate advisory: a
    client could skip negotiation and dodge the compat matrix
    entirely."""
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "raw",
                                timeout=15.0)
    try:
        with pytest.raises(RuntimeError,
                           match="before connect_document"):
            svc._request({
                "type": "upload_summary_chunk", "document_id": "raw",
                "upload_id": "u", "chunk": 0, "total": 1,
                "data": "{}",
            })
    finally:
        svc.close()


def test_boxcar_carries_traces_intact_roundtrip(alfred):
    """A wire-1.2 boxcar frame carries each member op's traces; the
    sequenced broadcasts and the op-log reads both return them
    decoded intact, with the service hops appended in order."""
    server = alfred()
    svc, c = _load(server.port, "tr", "alice")
    try:
        # container ops are traced (client:submit), so even on a 1.3
        # connection the batch is outside the columnar subset and the
        # driver falls back to the row boxcar — the traces survive
        assert svc.agreed_version == WIRE_VERSIONS[0]
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            for i in range(3):
                t.insert_text(0, f"x{i}")
            c.flush()  # one 3-op boxcar
        assert _pump(svc, c)
        with svc.lock:
            msgs = [m for m in svc.read_ops(0)
                    if m.client_id == "alice"]
        ops = [m for m in msgs if m.traces]
        assert ops, "no traced ops came back from delta storage"
        for m in ops[-3:]:
            hops = [(tr.service, tr.action) for tr in m.traces]
            # client-side stamps survived the wire, service stamps
            # appended after them
            assert hops[0] == ("client", "submit")
            assert ("driver", "send") in hops
            assert ("ingress", "receive") in hops
            assert ("sequencer", "ticket") in hops
            assert hops.index(("client", "submit")) < hops.index(
                ("sequencer", "ticket"))
            # timestamps are real floats, monotone within one process
            stamps = [tr.timestamp for tr in m.traces]
            assert stamps == sorted(stamps)
        # the ledgered ack-side view agrees (per-op breakdown)
        with svc.lock:
            entry = c.op_trace()
        assert entry is not None
        assert [h["hop"] for h in entry["hops"]][0] == "client:submit"
        assert "client:ack" in [h["hop"] for h in entry["hops"]]
        with svc.lock:
            c.close()
    finally:
        svc.close()


def _columnar_batch(texts, csn0=1, refseq=0):
    """An untraced insert batch inside the columnar subset, carrying
    the canonical batchManager.ts marks (first {batch: true}, last
    {batch: false})."""
    from fluidframework_tpu.models.mergetree.ops import InsertOp
    from fluidframework_tpu.protocol.constants import mark_batch
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )

    n = len(texts)
    pos = 0
    ops = []
    for i, text in enumerate(texts):
        metadata = None
        if n > 1 and i == 0:
            metadata = mark_batch(None, True)
        elif n > 1 and i == n - 1:
            metadata = mark_batch(None, False)
        ops.append(DocumentMessage(
            client_sequence_number=csn0 + i,
            reference_sequence_number=refseq,
            type=MessageType.OPERATION,
            contents=InsertOp(pos1=pos, text=text),
            metadata=metadata,
        ))
        pos += len(text)
    return ops


def _capture_sends(svc):
    sent = []
    orig = svc._send

    def send(data):
        sent.append(data)
        orig(data)

    svc._send = send
    return sent


def test_columnar_batch_roundtrips_live(alfred):
    """On a 1.3 connection, an untraced batch inside the columnar
    subset goes out as ONE submitOp frame whose payload IS the column
    layout — no "ops" array — and the service sequences the whole
    batch atomically: the sequenced broadcasts and the op log both
    return the ops decoded intact."""
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "cols",
                                timeout=15.0)
    got = []
    try:
        conn = svc.connect_to_delta_stream("colclient", got.append)
        assert svc.agreed_version == "1.5"
        sent = _capture_sends(svc)
        for op in _columnar_batch(["col", "umn", "ar"]):
            conn.submit(op)
        frames = [f for f in sent if f.get("type") == "submitOp"]
        assert len(frames) == 1 and "ops" not in frames[0]
        cols = frames[0]["cols"]
        assert cols["n"] == 3 and cols["text"] == "columnar"
        assert cols["text_off"] == [0, 3, 6, 8]
        deadline = time.time() + 10.0
        while time.time() < deadline and len(
                [m for m in got if m.client_id == "colclient"]) < 3:
            time.sleep(0.02)
        mine = [m for m in got if m.client_id == "colclient"]
        assert [m.client_sequence_number for m in mine] == [1, 2, 3]
        assert [m.contents.text for m in mine] == ["col", "umn", "ar"]
        # the batch marks arrive re-derived, positionally
        assert [m.metadata for m in mine] == [
            {"batch": True}, None, {"batch": False}]
        # the op log agrees (columns decoded ONCE, at the sequencer)
        with svc.lock:
            logged = [m for m in svc.read_ops(0)
                      if m.client_id == "colclient"]
        assert [m.contents.text for m in logged] == \
            ["col", "umn", "ar"]
        conn.disconnect()
    finally:
        svc.close()


def test_columnar_falls_back_to_rows_for_12_peer(alfred):
    """The same batch against a 1.2-agreed connection rides the
    wire-1.2 row boxcar unchanged — the columnar form is never sent
    to a peer that did not negotiate it."""
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "cols12",
                                timeout=15.0,
                                wire_versions=("1.2", "1.1", "1.0"))
    got = []
    try:
        conn = svc.connect_to_delta_stream("oldclient", got.append)
        assert svc.agreed_version == "1.2"
        sent = _capture_sends(svc)
        for op in _columnar_batch(["row", "s"]):
            conn.submit(op)
        frames = [f for f in sent if f.get("type") == "submitOp"]
        assert len(frames) == 1 and "cols" not in frames[0]
        assert [o["client_sequence_number"]
                for o in frames[0]["ops"]] == [1, 2]
        deadline = time.time() + 10.0
        while time.time() < deadline and len(
                [m for m in got if m.client_id == "oldclient"]) < 2:
            time.sleep(0.02)
        assert [m.client_sequence_number for m in got
                if m.client_id == "oldclient"] == [1, 2]
        conn.disconnect()
    finally:
        svc.close()


def _columnar_session(doc, versions):
    from fluidframework_tpu.service.ingress import _ClientSession

    server = AlfredServer()
    session = _ClientSession(server, None)
    server._sessions.add(session)
    server._dispatch(session, {
        "type": "connect_document", "document_id": doc,
        "client_id": "m", "mode": "write", "versions": versions,
    }, 0)
    _session_frames(session)  # drain the handshake
    return server, session


def test_malformed_columns_nacked_before_slicing():
    """A length-mismatched column refuses the batch AS A UNIT with a
    BAD_REQUEST nack naming the column — the whole layout is
    validated before anything slices it, so nothing sequences."""
    from fluidframework_tpu.protocol.columnar import encode_columns
    from fluidframework_tpu.protocol.messages import NackErrorType

    server, session = _columnar_session("mal", ["1.3"])
    cols = encode_columns(_columnar_batch(["ok", "ops"]))
    assert cols is not None
    cols["pos1"] = cols["pos1"] + [7]  # length mismatch
    server._dispatch(session, {
        "type": "submitOp", "document_id": "mal", "cols": cols,
    }, 0)
    nacks = [f for f in _session_frames(session)
             if f["type"] == "nack"]
    assert len(nacks) == 1
    assert nacks[0]["error_type"] == int(NackErrorType.BAD_REQUEST)
    assert "pos1" in nacks[0]["message"]
    # nothing sequenced: the op log holds no OPERATION messages
    server._dispatch(session, {
        "type": "read_ops", "document_id": "mal", "rid": 1,
        "from_seq": 0, "to_seq": None,
    }, 0)
    ops_frames = [f for f in _session_frames(session)
                  if f["type"] == "ops"]
    assert not [m for m in ops_frames[0]["msgs"] if m["type"] == 2]


def test_columnar_requires_wire_13():
    """Server-side enforcement: a 1.2-agreed connection sending a
    cols frame gets the loud version error, not a silent accept."""
    from fluidframework_tpu.protocol.columnar import encode_columns

    server, session = _columnar_session("enf13", ["1.2"])
    cols = encode_columns(_columnar_batch(["nope"]))
    with pytest.raises(ValueError, match="wire version >= 1.3"):
        server._dispatch(session, {
            "type": "submitOp", "document_id": "enf13", "cols": cols,
        }, 0)


def test_heat_requires_wire_14():
    """Same discipline for the cost-attribution scrape: a 1.3-agreed
    connection sending a heat frame gets the loud version error, not
    a silent accept (the 1.1 upload gate, re-pinned for 1.4)."""
    server, session = _columnar_session("enf14", ["1.3"])
    with pytest.raises(ValueError, match="wire version >= 1.4"):
        server._dispatch(session, {"type": "heat", "rid": 1}, 0)


def test_heat_unnegotiated_dump_connection_interops():
    """A bare dump connection (no connect_document — what
    ``--dump-heat`` opens) serves the heat frame like ``metrics``:
    no negotiated session, no gate, empty cuts when no ledger is
    attached — never a nack or error."""
    from fluidframework_tpu.service.ingress import _ClientSession

    server = AlfredServer()
    session = _ClientSession(server, None)
    server._sessions.add(session)
    server._dispatch(session, {"type": "heat", "rid": 7, "k": 3}, 0)
    frames = _session_frames(session)
    assert [f["type"] for f in frames] == ["heat"]
    assert frames[0]["rid"] == 7
    assert frames[0]["docs"] == [] and frames[0]["tenants"] == []


def test_pre_14_peer_never_sees_heat_vocabulary(alfred):
    """Interop pin: a 1.3-and-below peer collaborates normally and is
    never sent a heat frame (the vocabulary is request/response only
    and version-gated) — no nack, no error, ops flow."""
    server = alfred()
    svc, c = _load(server.port, "pre14", "old13",
                   versions=("1.3", "1.2", "1.1", "1.0"))
    try:
        assert svc.agreed_version == "1.3"
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "still 1.3")
            c.flush()
        assert _pump(svc, c)
        with svc.lock:
            assert t.get_text() == "still 1.3"
            c.close()
        # the flight recorder logs every received frame's type: no
        # heat vocabulary, no nack, no error reached the 1.3 peer
        seen = {f.get("type") for _, _, kind, f in svc.flight.events()
                if kind == "recv"}
        assert "heat" not in seen
        assert "nack" not in seen and "error" not in seen
        assert "op" in seen  # the pin is non-vacuous: traffic flowed
    finally:
        svc.close()


def test_traced_batch_falls_back_to_rows_on_13():
    """A batch whose ops carry traces is outside the columnar subset
    (the column layout has no traces column): the encoder refuses it
    and the driver's flush keeps the row boxcar, traces intact."""
    from fluidframework_tpu.obs.trace import stamp as trace_stamp
    from fluidframework_tpu.protocol.columnar import encode_columns

    ops = _columnar_batch(["tr", "aced"])
    assert encode_columns(ops) is not None
    for op in ops:
        trace_stamp(op.traces, "client", "submit")
    assert encode_columns(ops) is None


def test_traces_optional_on_wire_10_peer_interops(alfred):
    """Traces are optional on the wire: a 1.0 peer (per-op frames, no
    boxcar) still interoperates, and frames WITHOUT a traces key
    decode to an empty list — the pre-tracing format stays valid."""
    from fluidframework_tpu.protocol.serialization import (
        message_from_json,
        message_to_json,
    )
    from fluidframework_tpu.service.ingress import (
        document_message_from_json,
    )

    # decoder side: omitted traces = empty, never a KeyError
    legacy_op = {
        "client_sequence_number": 1,
        "reference_sequence_number": 0,
        "type": 2, "contents": None, "metadata": None,
    }
    assert document_message_from_json(legacy_op).traces == []
    legacy_seq = {
        "clientId": "a", "sequenceNumber": 1,
        "minimumSequenceNumber": 0, "clientSequenceNumber": 1,
        "referenceSequenceNumber": 0, "type": 2, "contents": None,
    }
    decoded = message_from_json(legacy_seq)
    assert decoded.traces == []
    # and an untraced message serializes WITHOUT the key (recorded
    # corpora stay byte-stable)
    assert "traces" not in message_to_json(decoded)

    # live 1.0 pairing over TCP: per-op frames, traces still flow
    # (they are plain op-frame fields, present since wire 1.0)
    server = alfred()
    svc, c = _load(server.port, "old", "alice", versions=("1.0",))
    try:
        assert svc.agreed_version == "1.0"
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "legacy")
            c.flush()
        assert _pump(svc, c)
        with svc.lock:
            assert t.get_text() == "legacy"
            entry = c.op_trace()
        assert entry is not None  # ack-side breakdown works on 1.0 too
        with svc.lock:
            c.close()
    finally:
        svc.close()


def test_throttle_nack_qos_fields_optional_on_wire():
    """Throttle nacks' qos fields (pressure_tier, shed_class) are
    OPTIONAL on the wire: pre-qos nack frames stay byte-identical
    (keys absent when unset) and frames from old servers that omit
    them parse to None — 1.0/1.1 peers interop unchanged."""
    from fluidframework_tpu.drivers.socket_driver import (
        SocketDocumentService,
    )
    from fluidframework_tpu.protocol.messages import (
        Nack,
        NackErrorType,
    )
    from fluidframework_tpu.service.ingress import nack_to_json

    # emission: unset fields never serialize (legacy byte-stability)
    legacy = Nack(operation=None, sequence_number=0,
                  error_type=NackErrorType.THROTTLING,
                  message="m", retry_after_seconds=1.5)
    j = nack_to_json(legacy)
    assert "pressure_tier" not in j and "shed_class" not in j
    shed = Nack(operation=None, sequence_number=0,
                error_type=NackErrorType.THROTTLING, message="m",
                retry_after_seconds=1.5, pressure_tier=2,
                shed_class="summary")
    j2 = nack_to_json(shed)
    assert j2["pressure_tier"] == 2
    assert j2["shed_class"] == "summary"
    # everything else in the frame is unchanged by the new fields
    assert {k: v for k, v in j2.items()
            if k not in ("pressure_tier", "shed_class")} == j

    # decode: an OLD server's nack frame (no qos keys) parses clean
    nacks = []
    svc = SocketDocumentService.__new__(SocketDocumentService)
    svc._on_message = None
    svc._on_nack = nacks.append
    svc._deliver({
        "type": "nack", "document_id": "d",
        "sequence_number": 0,
        "error_type": int(NackErrorType.THROTTLING),
        "message": "old-server throttle",
        "retry_after_seconds": 0.5,
    })
    svc._deliver({
        "type": "nack", "document_id": "d",
        "sequence_number": 0,
        "error_type": int(NackErrorType.THROTTLING),
        "message": "qos shed", "retry_after_seconds": 0.5,
        "pressure_tier": 1, "shed_class": "write",
    })
    assert nacks[0].pressure_tier is None
    assert nacks[0].shed_class is None
    assert nacks[0].retry_after_seconds == 0.5
    assert nacks[1].pressure_tier == 1
    assert nacks[1].shed_class == "write"


def test_throttle_nack_over_wire_10_peer_interops(alfred):
    """A 1.0-pinned client against a qos-enabled server: the shed
    nack (carrying the new fields) still round-trips as a valid 1.0
    nack frame — extra keys ride along, nothing breaks, and the
    retry hint arrives."""
    from fluidframework_tpu.protocol.messages import NackErrorType
    from fluidframework_tpu.qos import (
        AdmissionController,
        Budget,
        RateLimits,
    )

    qos = AdmissionController(RateLimits(
        connection_ops=Budget(5.0, burst=2.0),
    ))
    server = alfred(qos=qos)
    svc, c = _load(server.port, "old-qos", "alice",
                   versions=("1.0",))
    nacks = []
    c.on("nack", nacks.append)
    try:
        assert svc.agreed_version == "1.0"
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "a")
            c.flush()
        # 1.0 = per-op frames: burn the burst until a shed lands
        deadline = time.time() + 10.0
        while not nacks and time.time() < deadline:
            with svc.lock:
                if c.connected:
                    t.insert_text(0, "b")
                    c.flush()
            time.sleep(0.01)
        assert nacks, "no throttle nack reached the 1.0 client"
        nack = nacks[0]
        assert nack.error_type == NackErrorType.THROTTLING
        assert (nack.retry_after_seconds or 0) > 0
        assert nack.shed_class == "write"
        with svc.lock:
            c.close()
    finally:
        svc.close()


def test_negotiated_10_connection_cannot_use_upload_frames(alfred):
    """Server-side enforcement: a connection that AGREED 1.0 gets a
    loud error for 1.1 frames (not a silent accept)."""
    server = alfred()
    svc, c = _load(server.port, "enf", "alice", versions=("1.0",))
    try:
        with pytest.raises(RuntimeError,
                           match="requires wire version >= 1.1"):
            svc._request({
                "type": "upload_summary_chunk", "document_id": "enf",
                "upload_id": "u", "chunk": 0, "total": 1,
                "data": "{}",
            })
        with svc.lock:
            c.close()
    finally:
        svc.close()

# ----------------------------------------------------------------------
# optional-presence regressions for the live wirecheck findings
# (optional-field-unconditional-emit in service/ingress.py)


def test_nack_retry_hint_optional_on_wire():
    """wirecheck live finding: a nack with no retry hint must
    serialize WITHOUT the retry_after_seconds key — non-throttle nack
    frames stay byte-identical to the 1.0 shape — and a frame
    omitting it parses to None on the driver side."""
    from fluidframework_tpu.protocol.messages import (
        Nack,
        NackErrorType,
    )
    from fluidframework_tpu.service.ingress import nack_to_json

    plain = Nack(operation=None, sequence_number=3,
                 error_type=NackErrorType.BAD_REQUEST, message="bad")
    j = nack_to_json(plain)
    assert "retry_after_seconds" not in j
    assert "pressure_tier" not in j and "shed_class" not in j
    nacks = []
    svc = SocketDocumentService.__new__(SocketDocumentService)
    svc._on_message = None
    svc._on_nack = nacks.append
    svc._deliver(dict(j, type="nack", document_id="d"))
    assert nacks[0].retry_after_seconds is None
    assert nacks[0].error_type == NackErrorType.BAD_REQUEST


def _session_frames(session):
    import json as json_mod

    out = []
    q = session.outbound
    while not q.empty():
        raw = q.get_nowait()
        if raw is not None:
            out.append(json_mod.loads(raw[4:]))
    return out


class _Adm:
    """AdmissionController decision stub: shed, with optional qos
    attribution."""

    def __init__(self, tier=None, shed_class=None):
        self.admitted = False
        self.reason = "connection_ops"
        self.retry_after_seconds = 0.25
        self.tier = tier
        self.shed_class = shed_class


def test_throttle_error_omits_unset_qos_fields():
    """wirecheck live finding: the request-plane throttle error emits
    retry_after_seconds / pressure_tier / shed_class only when set —
    an old peer never sees keys its decoder doesn't know, and the
    frame is otherwise identical either way."""
    from fluidframework_tpu.service.ingress import (
        AlfredServer,
        _ClientSession,
    )

    server = AlfredServer()
    session = _ClientSession(server, None)
    server._send_shed(session, "d", {"type": "read_ops", "rid": 7},
                      _Adm(), as_nack=False)
    server._send_shed(session, "d", {"type": "read_ops", "rid": 8},
                      _Adm(tier=2, shed_class="read"), as_nack=False)
    bare, full = _session_frames(session)
    assert bare["type"] == "error"
    assert bare["error_kind"] == "throttle"
    assert bare["retry_after_seconds"] == 0.25
    assert "pressure_tier" not in bare and "shed_class" not in bare
    assert full["pressure_tier"] == 2
    assert full["shed_class"] == "read"
    drop = ("pressure_tier", "shed_class", "rid")
    assert {k: v for k, v in full.items() if k not in drop} == \
        {k: v for k, v in bare.items() if k not in drop}


# ----------------------------------------------------------------------
# golden wire-schema snapshot


def test_wire_schema_snapshot_matches_registry():
    """protocol/WIRE_SCHEMA.json is the REVIEWED golden snapshot of
    the registry: any frame-vocabulary change must regenerate it (a
    reviewed diff), never drift silently. Regenerate with:

        python - <<'PY'
        import json
        from fluidframework_tpu.protocol import constants
        with open("fluidframework_tpu/protocol/WIRE_SCHEMA.json",
                  "w") as f:
            json.dump({"hash": constants.wire_schema_hash(),
                       "schema": constants.WIRE_SCHEMA},
                      f, indent=2, sort_keys=True)
            f.write("\\n")
        PY
    """
    import json
    import os

    from fluidframework_tpu.protocol import constants

    path = os.path.join(os.path.dirname(constants.__file__),
                        "WIRE_SCHEMA.json")
    with open(path) as f:
        snap = json.load(f)
    assert snap["schema"] == constants.WIRE_SCHEMA, (
        "WIRE_SCHEMA.json drifted from protocol/constants.py — "
        "regenerate it (see docstring) and review the diff")
    assert snap["hash"] == constants.wire_schema_hash()


# ----------------------------------------------------------------------
# schema-driven generative leg: for EVERY registry frame type, build
# the MINIMAL frame — required fields at the type's floor version
# only; every optional ("?"), tolerated ("~"), and later-version
# field omitted — and assert the current decoder accepts it. This is
# the registry-derived successor to hand-enumerated interop cases:
# new vocabulary gets a failing test here until it has a route.


def _ver(s):
    return tuple(int(p) for p in s.split("."))


def _minimal_frame(ftype):
    """(frame, floor): the oldest-peer shape of ``ftype``."""
    from fluidframework_tpu.protocol.constants import (
        wire_schema_fields,
    )

    spec = wire_schema_fields(ftype)
    required = {f: since for f, (since, opt, tol) in spec.items()
                if not opt and not tol}
    pool = required or {f: s[0] for f, s in spec.items()}
    floor = min(pool.values(), key=_ver)
    # payload pseudo-types ("msg:*", "cols:columnar") are not frames:
    # no discriminator key
    frame = {} if ":" in ftype else {"type": ftype}
    for fld, since in required.items():
        if since == floor:
            frame[fld] = _sample_value(ftype, fld)
    return frame, floor


def _minimal_sequenced():
    frame, _ = _minimal_frame("msg:sequenced")
    return frame


def _minimal_document():
    frame, _ = _minimal_frame("msg:document")
    return frame


# field -> sample value (callables are built per frame, so routes
# never share mutable payloads); (ftype, field) overrides win
_SAMPLES = {
    "document_id": "gen", "client_id": "gen-client", "mode": "write",
    "versions": lambda: ["1.0"], "message": "gen message",
    "sequence_number": 1, "error_type": 2,  # BAD_REQUEST
    "operation": _minimal_document, "op": _minimal_document,
    "msg": _minimal_sequenced, "msgs": lambda: [_minimal_sequenced()],
    "from_seq": 0, "to_seq": None, "upload_id": "gen-upload",
    "chunk": 0, "total": 1, "handle": "h1", "version": "1.0",
    "text": "# gen\n", "metrics": lambda: {},
    "nodes": lambda: ["node0"], "report": lambda: {},
    "docs": lambda: [], "tenants": lambda: [],
    # sequenced-message payload fields
    "clientId": "gen", "sequenceNumber": 1,
    "minimumSequenceNumber": 0, "clientSequenceNumber": 1,
    "referenceSequenceNumber": 0, "type": 2, "contents": None,
    "metadata": None, "timestamp": 0.0,
    # document-message payload fields
    "client_sequence_number": 1, "reference_sequence_number": 0,
    "traces": lambda: [],
}
_SAMPLE_OVERRIDES = {
    # a mutually consistent single-insert columnar payload (the
    # columns are parallel arrays, so the per-field samples must
    # agree: one insert of "gen" at position 0)
    ("cols:columnar", "n"): 1,
    ("cols:columnar", "csn"): lambda: [1],
    ("cols:columnar", "refseq"): lambda: [0],
    ("cols:columnar", "kind"): lambda: [0],
    ("cols:columnar", "pos1"): lambda: [0],
    ("cols:columnar", "pos2"): lambda: [0],
    ("cols:columnar", "text_off"): lambda: [0, 3],
    ("cols:columnar", "text"): "gen",
    # the sharedtree payload: "type" is the payload discriminator
    # (generic _SAMPLES["type"] is the sequenced MessageType int) and
    # "changes" a minimal one-insert FieldChanges changeset in the
    # models/tree/changeset.py mark grammar
    ("msg:tree", "type"): "tree",
    ("msg:tree", "changes"): lambda: {
        "root": [{"t": "ins", "content": [{"type": "n", "value": 1}]}],
    },
    ("summary", "summary"): lambda: __import__(
        "fluidframework_tpu.protocol.serialization",
        fromlist=["encode_contents"]).encode_contents(
            {"runtime": {}}),
    ("upload_summary_chunk", "data"): lambda: __import__(
        "json").dumps(__import__(
            "fluidframework_tpu.protocol.serialization",
            fromlist=["encode_contents"]).encode_contents(
                {"runtime": {}})),
}


def _sample_value(ftype, fld):
    if (ftype, fld) in _SAMPLE_OVERRIDES:
        val = _SAMPLE_OVERRIDES[(ftype, fld)]
    else:
        val = _SAMPLES[fld]
    return val() if callable(val) else val


def _gen_dispatch(frame, floor, monkeypatch, connect=True,
                  expect_reply=None):
    """Route a server-bound minimal frame through a real in-proc
    AlfredServer._dispatch (the chaos transport plane) and assert the
    server neither errors nor rejects it."""
    from fluidframework_tpu.service.ingress import _ClientSession

    server = AlfredServer()
    session = _ClientSession(server, None)
    server._sessions.add(session)
    if connect:
        server._dispatch(session, {
            "type": "connect_document",
            "document_id": frame.get("document_id", "gen"),
            "client_id": "gen-client", "mode": "write",
            "versions": [floor],
        }, 0)
        handshake = [f["type"] for f in _session_frames(session)]
        # the join-op broadcast rides along with the handshake ack
        assert "connected" in handshake, handshake
        assert "error" not in handshake, handshake
        assert "connect_document_error" not in handshake, handshake
    server._dispatch(session, frame, 0)
    replies = _session_frames(session)
    bad = [f for f in replies
           if f["type"] in ("error", "connect_document_error",
                            "nack")]
    assert not bad, f"server rejected minimal {frame['type']}: {bad}"
    if expect_reply is not None:
        assert expect_reply in [f["type"] for f in replies], replies
    return replies


def _fresh_driver():
    svc = SocketDocumentService.__new__(SocketDocumentService)
    svc.agreed_version = None
    svc.auth_error = None
    svc._connected = threading.Event()
    svc._on_message = None
    svc._on_nack = None
    svc.document_id = "gen"
    svc.tenant_id = None
    svc.token = None
    return svc


def _responding_driver(reply):
    """A driver whose transport synchronously answers every request
    with ``reply`` — the decode side of the request planes with a
    constructed frame instead of a live server's."""
    import itertools

    svc = _fresh_driver()
    svc._rid = itertools.count(1)
    svc._pending = {}
    svc._pending_lock = threading.Lock()
    svc._timeout = 5.0

    def send(data):
        rid = data["rid"]
        with svc._pending_lock:
            event, slot = svc._pending.pop(rid)
        slot.append(dict(reply, rid=rid))
        event.set()

    svc._send = send
    return svc


def _route_connect_document(frame, floor, monkeypatch):
    _gen_dispatch(frame, floor, monkeypatch, connect=False,
                  expect_reply="connected")


def _route_disconnect(frame, floor, monkeypatch):
    _gen_dispatch(frame, floor, monkeypatch)


def _route_submit(frame, floor, monkeypatch):
    _gen_dispatch(frame, floor, monkeypatch)


def _route_read_ops(frame, floor, monkeypatch):
    _gen_dispatch(frame, floor, monkeypatch, expect_reply="ops")


def _route_fetch_summary(frame, floor, monkeypatch):
    _gen_dispatch(frame, floor, monkeypatch, expect_reply="summary")


def _route_upload_chunk(frame, floor, monkeypatch):
    _gen_dispatch(frame, floor, monkeypatch,
                  expect_reply="summary_uploaded")


def _route_connected(frame, floor, monkeypatch):
    svc = _fresh_driver()
    svc._on_connected(frame)
    assert svc.agreed_version == "1.0"
    assert svc._connected.is_set()


def _route_connect_error(frame, floor, monkeypatch):
    svc = _fresh_driver()
    svc._on_connect_error(frame)
    assert svc.auth_error == "gen message"
    assert svc._connected.is_set()


def _route_op(frame, floor, monkeypatch):
    got = []
    svc = _fresh_driver()
    svc._on_message = got.append
    svc._deliver(frame)
    assert len(got) == 1
    assert got[0].sequence_number == 1


def _route_nack(frame, floor, monkeypatch):
    from fluidframework_tpu.protocol.messages import NackErrorType

    got = []
    svc = _fresh_driver()
    svc._on_nack = got.append
    svc._deliver(frame)
    assert len(got) == 1
    assert got[0].error_type == NackErrorType.BAD_REQUEST
    # every post-1.0 / optional field defaults, never KeyErrors
    assert got[0].retry_after_seconds is None
    assert got[0].pressure_tier is None
    assert got[0].shed_class is None


def _route_ops_response(frame, floor, monkeypatch):
    svc = _responding_driver(frame)
    msgs = svc.read_ops(0)
    assert len(msgs) == 1 and msgs[0].traces == []


def _route_summary_response(frame, floor, monkeypatch):
    svc = _responding_driver(frame)
    latest = svc.get_latest_summary()
    assert latest == (1, {"runtime": {}})


def _route_upload_ack(frame, floor, monkeypatch):
    # no in-scope decoder reads upload_ack fields (both are "~"
    # tolerated); acceptance = the request plumbing returns it intact
    svc = _responding_driver(frame)
    assert svc._request({"type": "probe"})["type"] == "upload_ack"


def _route_summary_uploaded(frame, floor, monkeypatch):
    svc = _responding_driver(frame)
    svc.agreed_version = "1.1"
    assert svc.upload_summary({"runtime": {}}) == "h1"


def _route_error(frame, floor, monkeypatch):
    # the decoder is _request's error branch: a 1.0 error frame (no
    # error_kind, no retry hint) must raise the generic shape — never
    # KeyError on a post-1.0 key
    svc = _responding_driver(frame)
    with pytest.raises(RuntimeError, match="gen message"):
        svc._request({"type": "probe"})


class _GenSock:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def sendall(self, data):
        pass


def _patch_dump_transport(frame, monkeypatch):
    import socket as socket_mod

    monkeypatch.setattr(socket_mod, "create_connection",
                        lambda *a, **k: _GenSock())
    monkeypatch.setattr(ingress_mod, "recv_frame_blocking",
                        lambda sock: frame)


def _route_metrics(frame, floor, monkeypatch):
    from fluidframework_tpu.service.__main__ import dump_metrics

    _patch_dump_transport(frame, monkeypatch)
    assert dump_metrics("127.0.0.1:1", as_json=True) == 0


def _route_fleet(frame, floor, monkeypatch):
    from fluidframework_tpu.service.__main__ import dump_fleet

    _patch_dump_transport(frame, monkeypatch)
    assert dump_fleet("127.0.0.1:1", as_json=True) == 0


def _route_slo(frame, floor, monkeypatch):
    from fluidframework_tpu.service.__main__ import dump_slo

    _patch_dump_transport(frame, monkeypatch)
    assert dump_slo("127.0.0.1:1") == 0


def _route_heat(frame, floor, monkeypatch):
    from fluidframework_tpu.service.__main__ import dump_heat

    _patch_dump_transport(frame, monkeypatch)
    assert dump_heat("127.0.0.1:1") == 0


def _route_sequenced_payload(frame, floor, monkeypatch):
    from fluidframework_tpu.protocol.serialization import (
        message_from_json,
    )

    decoded = message_from_json(frame)
    assert decoded.sequence_number == 1
    assert decoded.traces == []  # 1.1? field defaults, no KeyError


def _route_document_payload(frame, floor, monkeypatch):
    from fluidframework_tpu.service.ingress import (
        document_message_from_json,
    )

    decoded = document_message_from_json(frame)
    assert decoded.client_sequence_number == 1


def _route_columnar_payload(frame, floor, monkeypatch):
    from fluidframework_tpu.protocol.columnar import (
        decode_columns,
        encode_columns,
        validate_columns,
    )

    assert validate_columns(frame) == 1
    decoded = decode_columns(frame)
    assert decoded[0].client_sequence_number == 1
    assert decoded[0].contents.text == "gen"
    # the codec pair is a faithful round trip on its whole subset
    assert encode_columns(decoded) == frame


def _route_tree_payload(frame, floor, monkeypatch):
    from fluidframework_tpu.models.tree import changeset as cs
    from fluidframework_tpu.protocol.tree_payload import (
        tree_change_from_json,
        tree_change_to_json,
    )

    changes = tree_change_from_json(frame)
    assert changes is not None
    # the sample changeset is well-formed model vocabulary, not just
    # schema-shaped JSON: the scalar walk applies it
    assert cs.walk_apply([], changes["root"]) == \
        [{"type": "n", "value": 1}]
    # the codec pair is a faithful round trip, and non-tree payloads
    # (the stored-schema plane shares the channel) route to None
    assert tree_change_to_json(changes) == frame
    assert tree_change_from_json(
        {"type": "tree-schema", "schema": {}}) is None


_GEN_ROUTES = {
    "connect_document": _route_connect_document,
    "connected": _route_connected,
    "connect_document_error": _route_connect_error,
    "disconnect_document": _route_disconnect,
    "submitOp": _route_submit,
    "op": _route_op,
    "nack": _route_nack,
    "read_ops": _route_read_ops,
    "ops": _route_ops_response,
    "fetch_summary": _route_fetch_summary,
    "summary": _route_summary_response,
    "upload_summary_chunk": _route_upload_chunk,
    "upload_ack": _route_upload_ack,
    "summary_uploaded": _route_summary_uploaded,
    "error": _route_error,
    "metrics": _route_metrics,
    "fleet-metrics": _route_fleet,
    "slo": _route_slo,
    "heat": _route_heat,
    "msg:sequenced": _route_sequenced_payload,
    "msg:document": _route_document_payload,
    "cols:columnar": _route_columnar_payload,
    "msg:tree": _route_tree_payload,
}


def _registry_types():
    from fluidframework_tpu.protocol.constants import WIRE_SCHEMA

    return sorted(WIRE_SCHEMA)


@pytest.mark.parametrize("ftype", _registry_types())
def test_registry_minimal_frame_is_accepted(ftype, monkeypatch):
    route = _GEN_ROUTES.get(ftype)
    assert route is not None, (
        f"no generative route for registry frame type {ftype!r} — "
        "new vocabulary needs a decode route here so the registry "
        "keeps driving interop coverage")
    frame, floor = _minimal_frame(ftype)
    route(frame, floor, monkeypatch)


def test_generative_routes_track_the_registry():
    """A route for a frame type the registry no longer knows is dead
    coverage — retire it with the vocabulary."""
    assert set(_GEN_ROUTES) == set(_registry_types())
