"""Live wire-version compat matrix (describeCompat analogue for the
FRAME axis — packages/test/test-version-utils pairs old clients with
new services and vice versa; here the pairings are real TCP sessions
against a real server, not format shims).

Wire 1.0 = base frames; wire 1.1 adds the chunked summary-upload
plane. The matrix drives: negotiation outcome, live collaboration
across mixed-version clients, and the summarizer's degrade-to-inline
path whenever either side lacks 1.1.
"""
import asyncio
import threading
import time

import pytest

from fluidframework_tpu.drivers.socket_driver import (
    WIRE_VERSIONS,
    SocketDocumentService,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service import ingress as ingress_mod
from fluidframework_tpu.service.ingress import AlfredServer




def _pump(svc, container, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with svc.lock:
            if container.runtime.pending.count == 0:
                return True
        time.sleep(0.02)
    return False


def _load(port, doc, client_id, versions=None):
    svc = SocketDocumentService("127.0.0.1", port, doc,
                                timeout=15.0,
                                wire_versions=versions)
    with svc.lock:
        c = Container.load(svc, client_id=client_id)
    return svc, c


@pytest.mark.parametrize("client_versions,server_versions,agreed", [
    (("1.1", "1.0"), ("1.1", "1.0"), "1.1"),  # new / new
    (("1.0",), ("1.1", "1.0"), "1.0"),        # old client / new srv
    (("1.1", "1.0"), ("1.0",), "1.0"),        # new client / old srv
])
def test_negotiation_matrix(alfred, client_versions,
                            server_versions, agreed):
    server = alfred(server_versions=server_versions)
    svc, c = _load(server.port, "neg", "alice",
                   versions=client_versions)
    try:
        assert svc.agreed_version == agreed
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "negotiated")
            c.flush()
        assert _pump(svc, c)
        with svc.lock:
            assert t.get_text() == "negotiated"
            c.close()
    finally:
        svc.close()


def test_no_common_version_is_connect_error(alfred):
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "nc",
                                timeout=15.0,
                                wire_versions=("0.9",))
    try:
        with pytest.raises(Exception, match="no common wire version"):
            with svc.lock:
                Container.load(svc, client_id="alice")
    finally:
        svc.close()


@pytest.mark.parametrize("pairing,client_versions,server_versions", [
    ("old-client-new-server", ("1.0",), ("1.1", "1.0")),
    ("new-client-old-server", ("1.1", "1.0"), ("1.0",)),
])
def test_summarize_degrades_to_inline_on_10_pairings(
        alfred, pairing, client_versions, server_versions):
    """Either 1.0 pairing: the upload plane is unavailable, the
    summarizer must degrade to an INLINE summary that still lands and
    is loadable — never a wedge, never a server-side frame error."""
    server = alfred(server_versions=server_versions)
    svc, c = _load(server.port, "deg", "alice",
                   versions=client_versions)
    try:
        assert svc.agreed_version == "1.0"
        with pytest.raises(RuntimeError, match="wire"):
            svc.upload_summary({"runtime": {}})
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "inline fallback")
            c.flush()
        assert _pump(svc, c)
        with svc.lock:
            c.summarize()
        deadline = time.time() + 10
        latest = None
        while time.time() < deadline and latest is None:
            with svc.lock:
                latest = svc.get_latest_summary()
            time.sleep(0.05)
        assert latest is not None, f"{pairing}: summary never landed"
        _, summary = latest
        assert "runtime" in summary  # inline tree, not a handle stub
        # a fresh (new) client loads from it
        svc2, c2 = _load(server.port, "deg", "bob")
        with svc2.lock:
            t2 = c2.runtime.get_datastore("ds").get_channel("t")
            assert t2.get_text() == "inline fallback"
            c2.close()
        svc2.close()
        with svc.lock:
            c.close()
    finally:
        svc.close()


def test_mixed_version_clients_collaborate(alfred):
    """An old (1.0) and a new (1.1) client on the SAME document
    converge over live ops — frame compat is per-connection, not
    per-document."""
    server = alfred()
    svc_old, c_old = _load(server.port, "mix", "old",
                           versions=("1.0",))
    svc_new, c_new = _load(server.port, "mix", "new")
    try:
        assert svc_old.agreed_version == "1.0"
        assert svc_new.agreed_version == WIRE_VERSIONS[0]
        with svc_old.lock:
            t_old = c_old.runtime.create_datastore(
                "ds").create_channel("sharedstring", "t")
            t_old.insert_text(0, "from old ")
            c_old.flush()
        assert _pump(svc_old, c_old)
        time.sleep(0.3)
        with svc_new.lock:
            t_new = c_new.runtime.get_datastore(
                "ds").get_channel("t")
            t_new.insert_text(t_new.get_length(), "from new")
            c_new.flush()
        assert _pump(svc_new, c_new)
        time.sleep(0.3)
        with svc_old.lock, svc_new.lock:
            assert t_old.get_text() == t_new.get_text() == \
                "from old from new"
            c_old.close()
            c_new.close()
    finally:
        svc_old.close()
        svc_new.close()


def test_unnegotiated_connection_cannot_use_upload_frames(alfred):
    """A client that never ran connect_document gets a loud rejection
    for upload frames. Raw frames used to be waved through as
    "self-evidently 1.1", which made the version gate advisory: a
    client could skip negotiation and dodge the compat matrix
    entirely."""
    server = alfred()
    svc = SocketDocumentService("127.0.0.1", server.port, "raw",
                                timeout=15.0)
    try:
        with pytest.raises(RuntimeError,
                           match="before connect_document"):
            svc._request({
                "type": "upload_summary_chunk", "document_id": "raw",
                "upload_id": "u", "chunk": 0, "total": 1,
                "data": "{}",
            })
    finally:
        svc.close()


def test_boxcar_carries_traces_intact_roundtrip(alfred):
    """A wire-1.2 boxcar frame carries each member op's traces; the
    sequenced broadcasts and the op-log reads both return them
    decoded intact, with the service hops appended in order."""
    server = alfred()
    svc, c = _load(server.port, "tr", "alice")
    try:
        assert svc.agreed_version == "1.2"
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            for i in range(3):
                t.insert_text(0, f"x{i}")
            c.flush()  # one 3-op boxcar
        assert _pump(svc, c)
        with svc.lock:
            msgs = [m for m in svc.read_ops(0)
                    if m.client_id == "alice"]
        ops = [m for m in msgs if m.traces]
        assert ops, "no traced ops came back from delta storage"
        for m in ops[-3:]:
            hops = [(tr.service, tr.action) for tr in m.traces]
            # client-side stamps survived the wire, service stamps
            # appended after them
            assert hops[0] == ("client", "submit")
            assert ("driver", "send") in hops
            assert ("ingress", "receive") in hops
            assert ("sequencer", "ticket") in hops
            assert hops.index(("client", "submit")) < hops.index(
                ("sequencer", "ticket"))
            # timestamps are real floats, monotone within one process
            stamps = [tr.timestamp for tr in m.traces]
            assert stamps == sorted(stamps)
        # the ledgered ack-side view agrees (per-op breakdown)
        with svc.lock:
            entry = c.op_trace()
        assert entry is not None
        assert [h["hop"] for h in entry["hops"]][0] == "client:submit"
        assert "client:ack" in [h["hop"] for h in entry["hops"]]
        with svc.lock:
            c.close()
    finally:
        svc.close()


def test_traces_optional_on_wire_10_peer_interops(alfred):
    """Traces are optional on the wire: a 1.0 peer (per-op frames, no
    boxcar) still interoperates, and frames WITHOUT a traces key
    decode to an empty list — the pre-tracing format stays valid."""
    from fluidframework_tpu.protocol.serialization import (
        message_from_json,
        message_to_json,
    )
    from fluidframework_tpu.service.ingress import (
        document_message_from_json,
    )

    # decoder side: omitted traces = empty, never a KeyError
    legacy_op = {
        "client_sequence_number": 1,
        "reference_sequence_number": 0,
        "type": 2, "contents": None, "metadata": None,
    }
    assert document_message_from_json(legacy_op).traces == []
    legacy_seq = {
        "clientId": "a", "sequenceNumber": 1,
        "minimumSequenceNumber": 0, "clientSequenceNumber": 1,
        "referenceSequenceNumber": 0, "type": 2, "contents": None,
    }
    decoded = message_from_json(legacy_seq)
    assert decoded.traces == []
    # and an untraced message serializes WITHOUT the key (recorded
    # corpora stay byte-stable)
    assert "traces" not in message_to_json(decoded)

    # live 1.0 pairing over TCP: per-op frames, traces still flow
    # (they are plain op-frame fields, present since wire 1.0)
    server = alfred()
    svc, c = _load(server.port, "old", "alice", versions=("1.0",))
    try:
        assert svc.agreed_version == "1.0"
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "legacy")
            c.flush()
        assert _pump(svc, c)
        with svc.lock:
            assert t.get_text() == "legacy"
            entry = c.op_trace()
        assert entry is not None  # ack-side breakdown works on 1.0 too
        with svc.lock:
            c.close()
    finally:
        svc.close()


def test_throttle_nack_qos_fields_optional_on_wire():
    """Throttle nacks' qos fields (pressure_tier, shed_class) are
    OPTIONAL on the wire: pre-qos nack frames stay byte-identical
    (keys absent when unset) and frames from old servers that omit
    them parse to None — 1.0/1.1 peers interop unchanged."""
    from fluidframework_tpu.drivers.socket_driver import (
        SocketDocumentService,
    )
    from fluidframework_tpu.protocol.messages import (
        Nack,
        NackErrorType,
    )
    from fluidframework_tpu.service.ingress import nack_to_json

    # emission: unset fields never serialize (legacy byte-stability)
    legacy = Nack(operation=None, sequence_number=0,
                  error_type=NackErrorType.THROTTLING,
                  message="m", retry_after_seconds=1.5)
    j = nack_to_json(legacy)
    assert "pressure_tier" not in j and "shed_class" not in j
    shed = Nack(operation=None, sequence_number=0,
                error_type=NackErrorType.THROTTLING, message="m",
                retry_after_seconds=1.5, pressure_tier=2,
                shed_class="summary")
    j2 = nack_to_json(shed)
    assert j2["pressure_tier"] == 2
    assert j2["shed_class"] == "summary"
    # everything else in the frame is unchanged by the new fields
    assert {k: v for k, v in j2.items()
            if k not in ("pressure_tier", "shed_class")} == j

    # decode: an OLD server's nack frame (no qos keys) parses clean
    nacks = []
    svc = SocketDocumentService.__new__(SocketDocumentService)
    svc._on_message = None
    svc._on_nack = nacks.append
    svc._deliver({
        "type": "nack", "document_id": "d",
        "sequence_number": 0,
        "error_type": int(NackErrorType.THROTTLING),
        "message": "old-server throttle",
        "retry_after_seconds": 0.5,
    })
    svc._deliver({
        "type": "nack", "document_id": "d",
        "sequence_number": 0,
        "error_type": int(NackErrorType.THROTTLING),
        "message": "qos shed", "retry_after_seconds": 0.5,
        "pressure_tier": 1, "shed_class": "write",
    })
    assert nacks[0].pressure_tier is None
    assert nacks[0].shed_class is None
    assert nacks[0].retry_after_seconds == 0.5
    assert nacks[1].pressure_tier == 1
    assert nacks[1].shed_class == "write"


def test_throttle_nack_over_wire_10_peer_interops(alfred):
    """A 1.0-pinned client against a qos-enabled server: the shed
    nack (carrying the new fields) still round-trips as a valid 1.0
    nack frame — extra keys ride along, nothing breaks, and the
    retry hint arrives."""
    from fluidframework_tpu.protocol.messages import NackErrorType
    from fluidframework_tpu.qos import (
        AdmissionController,
        Budget,
        RateLimits,
    )

    qos = AdmissionController(RateLimits(
        connection_ops=Budget(5.0, burst=2.0),
    ))
    server = alfred(qos=qos)
    svc, c = _load(server.port, "old-qos", "alice",
                   versions=("1.0",))
    nacks = []
    c.on("nack", nacks.append)
    try:
        assert svc.agreed_version == "1.0"
        with svc.lock:
            t = c.runtime.create_datastore("ds").create_channel(
                "sharedstring", "t")
            t.insert_text(0, "a")
            c.flush()
        # 1.0 = per-op frames: burn the burst until a shed lands
        deadline = time.time() + 10.0
        while not nacks and time.time() < deadline:
            with svc.lock:
                if c.connected:
                    t.insert_text(0, "b")
                    c.flush()
            time.sleep(0.01)
        assert nacks, "no throttle nack reached the 1.0 client"
        nack = nacks[0]
        assert nack.error_type == NackErrorType.THROTTLING
        assert (nack.retry_after_seconds or 0) > 0
        assert nack.shed_class == "write"
        with svc.lock:
            c.close()
    finally:
        svc.close()


def test_negotiated_10_connection_cannot_use_upload_frames(alfred):
    """Server-side enforcement: a connection that AGREED 1.0 gets a
    loud error for 1.1 frames (not a silent accept)."""
    server = alfred()
    svc, c = _load(server.port, "enf", "alice", versions=("1.0",))
    try:
        with pytest.raises(RuntimeError,
                           match="requires wire version >= 1.1"):
            svc._request({
                "type": "upload_summary_chunk", "document_id": "enf",
                "upload_id": "u", "chunk": 0, "total": 1,
                "data": "{}",
            })
        with svc.lock:
            c.close()
    finally:
        svc.close()
