"""Generic DDS fuzz: every channel type through the same engine.

Mirrors packages/dds/test-dds-utils ddsFuzzHarness: seeded action
mixes, partial sequencing, reconnect churn, convergence asserts —
parametrized over the whole channel catalogue.
"""
import pytest

from fluidframework_tpu.testing.dds_fuzz import (
    ACTIONS,
    DdsFuzzConfig,
    run_dds_fuzz,
)

CHANNELS = sorted(ACTIONS)


@pytest.mark.parametrize("channel_type", CHANNELS)
@pytest.mark.parametrize("seed", [0, 1])
def test_dds_fuzz_converges(channel_type, seed):
    report = run_dds_fuzz(DdsFuzzConfig(
        channel_type=channel_type, seed=seed, n_steps=220,
    ))
    assert report.actions > 30, (
        f"{channel_type} generator produced too few actions"
    )


@pytest.mark.parametrize("channel_type", ["sharedstring", "sharedmap",
                                          "sharedmatrix"])
def test_dds_fuzz_heavy_churn(channel_type):
    """Higher fault pressure on the structurally hardest DDSes."""
    report = run_dds_fuzz(DdsFuzzConfig(
        channel_type=channel_type, seed=99, n_steps=350,
        p_reconnect_churn=0.06, reconnect_after=8,
    ))
    assert report.reconnects > 0
