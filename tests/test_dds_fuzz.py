"""Generic DDS fuzz: every channel type through the same engine.

Mirrors packages/dds/test-dds-utils ddsFuzzHarness: seeded action
mixes, partial sequencing, reconnect churn, convergence asserts —
parametrized over the whole channel catalogue.
"""
import pytest

from fluidframework_tpu.testing.dds_fuzz import (
    ACTIONS,
    DdsFuzzConfig,
    run_dds_fuzz,
)

CHANNELS = sorted(ACTIONS)


@pytest.mark.parametrize("channel_type", CHANNELS)
@pytest.mark.parametrize("seed", [0, 1])
def test_dds_fuzz_converges(channel_type, seed):
    report = run_dds_fuzz(DdsFuzzConfig(
        channel_type=channel_type, seed=seed, n_steps=220,
    ))
    assert report.actions > 30, (
        f"{channel_type} generator produced too few actions"
    )


@pytest.mark.parametrize("channel_type", ["sharedstring", "sharedmap",
                                          "sharedmatrix"])
def test_dds_fuzz_heavy_churn(channel_type):
    """Higher fault pressure on the structurally hardest DDSes."""
    report = run_dds_fuzz(DdsFuzzConfig(
        channel_type=channel_type, seed=99, n_steps=350,
        p_reconnect_churn=0.06, reconnect_after=8,
    ))
    assert report.reconnects > 0


@pytest.mark.parametrize("seed", range(8))
def test_sticky_intervals_fuzz_with_zamboni(seed):
    """Stickiness x compaction x churn (VERDICT r4 next #7, mirrors
    intervalCollection.fuzz.spec.ts): randomized endpoint stickiness
    rides the string mix, with in-run zamboni interleavings; the
    convergence signature includes resolved sticky endpoints, so any
    endpoint drift across clients or across compaction fails here."""
    report = run_dds_fuzz(DdsFuzzConfig(
        channel_type="sharedstring", seed=7000 + seed, n_steps=300,
    ))
    sticky_adds = [t for t in report.trace if "iv add" in t]
    zambonis = [t for t in report.trace if "zamboni" in t]
    assert sticky_adds, "mix never added an interval"
    assert zambonis, "mix never ran zamboni"
    modes = {t.rsplit(" ", 1)[1] for t in sticky_adds}
    assert len(modes) >= 3, f"stickiness modes too narrow: {modes}"
