"""FlowDocument binding (the webflow-class example layer, VERDICT r4
next #9): nested tag-pair markers, pair-consistent removal, css
token-list annotates, line breaks, comments — and the heavy
marker/annotate workload that doubles as a kernel stress source.

Mirrors examples/data-objects/webflow/src/document (index.ts:248
remove walk, :309 insertTags) and test/document.spec.ts.
"""
import random

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.framework.flowdoc import (
    MARKER_TAG_BEGIN,
    MARKER_TAG_END,
    FlowDocument,
    flow_workload,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer


def make_pair(doc="fw"):
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service(doc),
                       client_id="alice")
    sa = a.runtime.create_datastore("app").create_channel(
        "sharedstring", "body")
    a.flush()
    b = Container.load(factory.create_document_service(doc),
                       client_id="bob")
    sb = b.runtime.get_datastore("app").get_channel("body")
    return server, (a, FlowDocument(sa, "alice")), \
        (b, FlowDocument(sb, "bob"))


def _pair_balance(doc):
    """begin/end marker multisets by pairId."""
    begins, ends = [], []
    for item in doc._items():
        if item[0] != "marker":
            continue
        _, rt, props = item
        if rt == MARKER_TAG_BEGIN:
            begins.append((props or {}).get("pairId"))
        elif rt == MARKER_TAG_END:
            ends.append((props or {}).get("pairId"))
    return sorted(begins), sorted(ends)


def test_tags_render_nested():
    _, (ca, da), (cb, db) = make_pair()
    da.insert_text(0, "alpha beta gamma")
    da.insert_tags(6, 10, "strong")   # 'beta'
    da.insert_tags(0, 18, "em")       # everything (incl. markers)
    ca.flush()
    runs = [(t, tags) for t, tags, _ in
            (r for b in db.render() for r in b.runs)]
    assert ("beta", ("em", "strong")) in runs
    assert ("alpha ", ("em",)) in runs


def test_remove_crossing_pair_removes_partner():
    _, (ca, da), (cb, db) = make_pair()
    da.insert_text(0, "abcdefgh")
    da.insert_tags(2, 6, "em")        # begin@2, end@7 (begin shifted)
    ca.flush()
    assert _pair_balance(db)[0] == _pair_balance(db)[1] != []
    # remove a range containing ONLY the begin marker
    da.remove(1, 4)
    ca.flush()
    b, e = _pair_balance(da)
    assert b == e == [], (b, e)       # orphan end removed too
    assert da.plain_text() == db.plain_text()


def test_remove_crossing_end_removes_begin():
    _, (ca, da), (cb, db) = make_pair()
    da.insert_text(0, "abcdefgh")
    da.insert_tags(1, 5, "code")
    ca.flush()
    # remove a range containing only the END marker
    da.remove(5, 8)
    ca.flush()
    b, e = _pair_balance(db)
    assert b == e == [], (b, e)


def test_line_breaks_and_headings_make_blocks():
    _, (ca, da), (cb, db) = make_pair()
    da.insert_text(0, "onetwo")
    da.insert_line_break(3)
    da.insert_paragraph(0, heading=2)
    ca.flush()
    blocks = db.render()
    kinds = [(b.kind, b.heading) for b in blocks]
    assert ("p", 2) in kinds and ("br", None) in kinds
    assert db.plain_text() == "onetwo"


def test_css_classes_split_runs_and_remove():
    _, (ca, da), (cb, db) = make_pair()
    da.insert_text(0, "styled text here")
    da.add_css_class(0, 6, "hot")
    da.add_css_class(3, 10, "cold")
    ca.flush()
    runs = [r for b in db.render() for r in b.runs]
    assert ("sty", (), frozenset({"hot"})) in runs
    assert ("led", (), frozenset({"hot", "cold"})) in runs
    da.remove_css_class(0, 16, "hot")
    ca.flush()
    assert all("hot" not in cls for _, _, cls in
               (r for b in db.render() for r in b.runs))


def test_comments_slide_with_edits():
    _, (ca, da), (cb, db) = make_pair()
    da.insert_text(0, "comment target")
    da.add_comment(8, 14, "look")
    ca.flush()
    db.insert_text(0, "XXX ")
    cb.flush()
    c = da.comments()[0]
    # endpoints anchor characters (end inclusive): still 'target'
    # after the remote prefix insert shifted everything right
    assert da.plain_text()[c["start"]:c["end"] + 1] == "target"
    assert c["author"] == "alice" and c["text"] == "look"


def test_concurrent_tag_inserts_converge():
    _, (ca, da), (cb, db) = make_pair()
    da.insert_text(0, "shared flowing text")
    ca.flush()
    da.insert_tags(0, 6, "em")
    db.insert_tags(7, 14, "strong")
    ca.flush()
    cb.flush()
    ca.flush()
    assert da.signature() == db.signature()
    assert [(b.kind, b.runs) for b in da.render()] == \
        [(b.kind, b.runs) for b in db.render()]


@pytest.mark.parametrize("seed", range(6))
def test_flow_workload_fuzz_converges(seed):
    """Two users hammer the flowed doc with the marker/annotate-heavy
    mix; content, tags, classes and comments all converge."""
    _, (ca, da), (cb, db) = make_pair()
    rng = random.Random(seed)
    for _ in range(8):
        flow_workload(da, rng, 5)
        flow_workload(db, rng, 5)
        if rng.random() < 0.7:
            ca.flush()
        if rng.random() < 0.7:
            cb.flush()
    ca.flush()
    cb.flush()
    ca.flush()
    assert da.plain_text() == db.plain_text(), seed
    assert da.signature() == db.signature(), seed
    assert [(b.kind, b.heading, b.runs) for b in da.render()] == \
        [(b.kind, b.heading, b.runs) for b in db.render()], seed
    assert da.comments() == db.comments(), seed


def test_recorded_flow_stream_is_kernel_exact():
    """The webflow-mix recorded stream (bench corpus member) is
    kernel-encodable within the 4 device property channels and BOTH
    executors reproduce the scalar oracle on it."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from fluidframework_tpu.models.mergetree import MergeTreeClient
    from fluidframework_tpu.ops import (
        build_batch,
        encode_stream,
        make_table,
    )
    from fluidframework_tpu.ops.host_bridge import (
        extract_signature,
        fetch,
        interned_signature,
    )
    from fluidframework_tpu.ops.merge_chunk import (
        apply_window_chunked,
        build_chunked,
    )
    from fluidframework_tpu.ops.merge_kernel import apply_window_impl
    from fluidframework_tpu.protocol.messages import MessageType
    from fluidframework_tpu.testing import record_flow_stream

    _, stream = record_flow_stream(seed=3, n_clients=3, n_steps=110)
    enc = encode_stream(stream)
    assert len(enc.prop_keys) <= 4
    batch = build_batch([enc])
    seq = fetch(apply_window_impl(make_table(1, 1024), batch))
    chk = fetch(apply_window_chunked(
        make_table(1, 1024), build_chunked(batch, K=8), K=8))
    obs = MergeTreeClient("o")
    obs.start_collaboration("o")
    for m in stream:
        if m.type == MessageType.OPERATION:
            obs.apply_msg(m)
    want = interned_signature(obs, enc)
    assert extract_signature(seq, enc, 0) == want
    assert extract_signature(chk, enc, 0) == want
