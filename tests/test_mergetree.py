"""Merge-tree concurrency semantics tests.

Mirrors the reference's merge-tree test approach
(packages/dds/merge-tree/src/test): multi-client sessions over a mock
sequencer, interleaved ops, convergence asserts. Each concurrency case
encodes a behavior pinned by mergeTree.ts (breakTie :1705,
markRangeRemoved :1908, nodeLength :984).
"""
import pytest

from fluidframework_tpu.testing import MockCollabSession


def make(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    return MockCollabSession(ids), ids


def test_single_client_insert_remove():
    s, _ = make(1)
    s.do("A", "insert_text_local", 0, "hello world")
    s.do("A", "remove_range_local", 5, 11)
    s.process_all()
    assert s.assert_converged() == "hello"


def test_sequential_inserts_converge():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "abc")
    s.process_all()
    s.do("B", "insert_text_local", 3, "def")
    s.process_all()
    assert s.assert_converged() == "abcdef"


def test_concurrent_same_position_inserts_later_seq_leftmost():
    """breakTie (mergeTree.ts:1705): among concurrent same-position
    inserts, the later-sequenced one lands leftmost."""
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "aaa")  # sequenced first
    s.do("B", "insert_text_local", 0, "bbb")  # sequenced second
    s.process_all()
    assert s.assert_converged() == "bbbaaa"


def test_concurrent_insert_ordering_is_not_submission_order_dependent():
    """Three-way concurrent inserts at 0: final order is strictly by
    descending seq regardless of client identity."""
    s, _ = make(3)
    s.do("A", "insert_text_local", 0, "1")   # seq n
    s.do("B", "insert_text_local", 0, "2")   # seq n+1
    s.do("C", "insert_text_local", 0, "3")   # seq n+2
    s.process_all()
    assert s.assert_converged() == "321"


def test_local_pending_stays_left_of_concurrent_remote():
    """While A's op is unacked, a concurrent remote insert at the same
    position must land to its right on A (and on everyone once
    sequenced): A's op sequences later => leftmost."""
    s, _ = make(2)
    s.do("B", "insert_text_local", 0, "remote")  # sequenced first
    s.do("A", "insert_text_local", 0, "local")   # sequenced second
    # Deliver B's op to A while A's own op is still pending.
    s.process_some(1)
    assert s.client("A").get_text() == "localremote"
    s.process_all()
    assert s.assert_converged() == "localremote"


def test_insert_into_concurrently_removed_range_survives():
    """A remove does not affect inserts it could not see
    (nodeMap skips len-0; nodeLength :984)."""
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "abcdef")
    s.process_all()
    s.do("A", "remove_range_local", 0, 6)     # sequenced first
    s.do("B", "insert_text_local", 3, "XYZ")  # concurrent, lands mid-range
    s.process_all()
    assert s.assert_converged() == "XYZ"


def test_concurrent_insert_at_remove_boundary():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "abcdef")
    s.process_all()
    s.do("A", "remove_range_local", 2, 4)
    s.do("B", "insert_text_local", 2, "XX")
    s.process_all()
    assert s.assert_converged() == "abXXef"


def test_overlapping_removes_are_idempotent():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "abcdef")
    s.process_all()
    s.do("A", "remove_range_local", 1, 5)
    s.do("B", "remove_range_local", 2, 6)  # overlaps [2,5)
    s.process_all()
    assert s.assert_converged() == "a"


def test_remove_of_own_pending_insert():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "abc")
    s.do("A", "remove_range_local", 1, 2)  # removes own pending 'b'
    s.process_all()
    assert s.assert_converged() == "ac"


def test_concurrent_remove_and_annotate():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "abcd")
    s.process_all()
    s.do("A", "remove_range_local", 0, 2)
    s.do("B", "annotate_range_local", 0, 4, {"bold": True})
    s.process_all()
    assert s.assert_converged() == "cd"
    # surviving segments carry the annotation
    for seg in s.client("A").mergetree.segments:
        if not seg.removed:
            assert seg.props == {"bold": True}


def test_annotate_lww_by_sequence_order():
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "ab")
    s.process_all()
    s.do("A", "annotate_range_local", 0, 2, {"c": 1})  # sequenced first
    s.do("B", "annotate_range_local", 0, 2, {"c": 2})  # sequenced second
    s.process_all()
    s.assert_converged()
    for cid in ("A", "B"):
        for seg in s.client(cid).mergetree.segments:
            if not seg.removed:
                assert seg.props["c"] == 2, f"client {cid}"


def test_annotate_pending_local_wins_until_ack():
    """segmentPropertiesManager.ts:29 — a pending local annotate shields
    the key from remote values; consistent because the local op
    sequences later and wins LWW anyway."""
    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "ab")
    s.process_all()
    s.do("B", "annotate_range_local", 0, 2, {"c": "remote"})  # seq first
    s.do("A", "annotate_range_local", 0, 2, {"c": "local"})   # seq second
    s.process_some(1)  # B's remote annotate arrives while A's pending
    seg = s.client("A").mergetree.segments[0]
    assert seg.props["c"] == "local"
    s.process_all()
    s.assert_converged()
    for cid in ("A", "B"):
        seg = s.client(cid).mergetree.segments[0]
        assert seg.props["c"] == "local"


def test_zamboni_compacts_below_window():
    s, ids = make(2)
    for i in range(6):
        s.do("A", "insert_text_local", 0, "ab")
        s.do("B", "insert_text_local", 0, "cd")
        s.process_all()
    s.do("A", "remove_range_local", 0, 4)
    s.process_all()
    text = s.assert_converged()
    # noop-style traffic to advance msn to the tip
    s.do("A", "insert_text_local", 0, "x")
    s.process_all()
    s.do("B", "insert_text_local", 0, "y")
    s.process_all()
    final = s.assert_converged()
    for cid in ids:
        tree = s.client(cid).mergetree
        assert all(
            not (seg.removal_acked
                 and seg.removed_seq <= tree.collab.min_seq)
            for seg in tree.segments
        ), "tombstones below min_seq must be zambonied"
    assert final == "y" + "x" + text


def test_marker_insert_and_text_skips_marker():
    from fluidframework_tpu.models.mergetree import ReferenceType

    s, _ = make(2)
    s.do("A", "insert_text_local", 0, "ab")
    s.do("A", "insert_marker_local", 1, ReferenceType.TILE)
    s.process_all()
    assert s.assert_converged() == "ab"  # marker occupies a position
    assert s.client("B").get_length() == 3


def test_insert_beyond_length_raises():
    s, _ = make(1)
    with pytest.raises(ValueError):
        s.do("A", "insert_text_local", 5, "late")
