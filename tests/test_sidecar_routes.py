"""Differential sidecar-level parity across ALL THREE executor routes.

The chunked and egwalker executors' bit-identical contracts are pinned
at the kernel level (tests/test_merge_chunk.py, tests/
test_event_graph.py); this suite pins them at the SERVICE level —
three sidecars on the same sequenced stream, one per route (scan /
chunked / egwalker), must serve identical ``text()`` and
``signature()`` through every policy transition: steady windows, the
2x regrow ladder, host eviction at the ladder top, the seq-sharded
pool, and the one semantic divergence the macro-step executors have —
post-overflow PARKING (chunked and egwalker stop applying a doc's
window at the failing chunk/span while the scan keeps going; the
sidecar's recovery re-applies the whole window from the pre-dispatch
snapshot, which must erase the difference; the egwalker route
additionally scans its concurrent SUFFIX onto a parked prefix, which
the same recovery absorbs).
"""
import random

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service import LocalServer, TpuMergeSidecar


ROUTES = ("scan", "chunked", "egwalker")


def _pair(**kw):
    """One sidecar per route, identical otherwise."""
    return {r: TpuMergeSidecar(executor=r, **kw) for r in ROUTES}


def _open_doc(server, sidecars, doc, client_id=None):
    factory = LocalDocumentServiceFactory(server)
    for sc in sidecars.values():
        sc.subscribe(server, doc, "d", "s")
    c = Container.load(factory.create_document_service(doc),
                       client_id=client_id or f"{doc}-w")
    s = c.runtime.create_datastore("d").create_channel(
        "sharedstring", "s")
    return c, s


def _assert_parity(sidecars, docs, oracle=None):
    scan = sidecars["scan"]
    for doc in docs:
        t_scan = scan.text(doc, "d", "s")
        sig_scan = scan.signature(doc, "d", "s")
        for route in ROUTES[1:]:
            assert t_scan == sidecars[route].text(doc, "d", "s"), (
                f"text route divergence ({route}) on {doc}")
            assert sig_scan == sidecars[route].signature(
                doc, "d", "s"), (
                f"signature route divergence ({route}) on {doc}")
        if oracle is not None and doc in oracle:
            assert t_scan == oracle[doc].get_text(), (
                f"all routes diverged from the oracle on {doc}")


@pytest.mark.slow
def test_routes_agree_on_steady_multidoc_traffic():
    rng = random.Random(7)
    server = LocalServer()
    sidecars = _pair(max_docs=8, capacity=256)
    docs = [f"doc-{i}" for i in range(4)]
    strings = {}
    containers = {}
    for doc in docs:
        c, s = _open_doc(server, sidecars, doc)
        containers[doc], strings[doc] = c, s
    for i in range(50):
        doc = rng.choice(docs)
        s = strings[doc]
        length = s.get_length()
        roll = rng.random()
        if length > 4 and roll < 0.3:
            start = rng.randint(0, length - 2)
            s.remove_text(start, rng.randint(start + 1, length))
        elif length > 2 and roll < 0.45:
            s.annotate_range(0, rng.randint(1, length),
                             {"k": rng.randint(1, 3)})
        else:
            s.insert_text(rng.randint(0, length),
                          rng.choice(["ab", "xyz", "q"]))
        containers[doc].flush()
        if rng.random() < 0.3:
            for sc in sidecars.values():
                sc.apply()
    for sc in sidecars.values():
        sc.apply()
    _assert_parity(sidecars, docs, strings)
    for route in ROUTES:
        assert not sidecars[route].overflowed(), route


@pytest.mark.slow
def test_routes_agree_through_grow_ladder():
    """Windows big enough to overflow a 16-slot slab force the regrow
    path — where the chunked route's overflow PARKING differs from the
    scan mid-window, and recovery must reconverge them."""
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=16, max_capacity=512)
    c, s = _open_doc(server, sidecars, "doc")
    for i in range(40):
        s.insert_text(0, "abcdefgh")
        c.flush()
        if i % 3 == 2 and s.get_length() > 6:
            s.remove_text(2, 5)
            c.flush()
    for sc in sidecars.values():
        sc.apply()
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].grow_count >= 1, route
        assert sidecars[route].host_mode_docs() == 0, route
    _assert_parity(sidecars, ["doc"], {"doc": s})


def test_routes_agree_on_overflow_parking_within_one_window():
    """The overflow-parking case proper: ONE window whose ops keep
    coming after the capacity overflow point. The scan executor keeps
    applying post-overflow ops (garbage-tolerant: the doc is flagged),
    the chunked executor parks the doc at its pre-chunk state — the
    sidecar policy layer re-applies the window from the snapshot at
    the doubled capacity, so the served state must be identical."""
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=16, max_capacity=256)
    c, s = _open_doc(server, sidecars, "doc")
    # a single flush cycle delivering far more segments than capacity:
    # everything lands in ONE apply window on both routes
    for i in range(30):
        s.insert_text(0, "wxyz")
    c.flush()
    for sc in sidecars.values():
        sc.apply()   # one dispatch: overflow mid-window on both
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].grow_count >= 1, route
    _assert_parity(sidecars, ["doc"], {"doc": s})
    for route in ROUTES:
        assert not sidecars[route].overflowed(), route


def test_routes_agree_through_eviction_and_recovery():
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=16, max_capacity=16)
    c, s = _open_doc(server, sidecars, "big")
    c2, s2 = _open_doc(server, sidecars, "small")
    for i in range(40):
        s.insert_text(0, "abcdefgh")
        c.flush()
    s2.insert_text(0, "tiny")
    c2.flush()
    for sc in sidecars.values():
        sc.apply()
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].host_mode_docs() == 1, route
    # post-eviction traffic keeps flowing on both routes
    s.insert_text(0, "MORE")
    s2.insert_text(4, "!")
    c.flush()
    c2.flush()
    for sc in sidecars.values():
        sc.apply()
    _assert_parity(sidecars, ["big", "small"],
                   {"big": s, "small": s2})


def test_routes_agree_with_pool_tier():
    """Grow ladder -> seq-sharded pool admission -> continued pooled
    collaboration, on both routes (single-shard mesh: the chunked
    route applies to the pool table directly there)."""
    import jax

    from fluidframework_tpu.parallel import make_seq_mesh

    mesh = make_seq_mesh(jax.devices()[:1])
    server = LocalServer()
    sidecars = _pair(max_docs=2, capacity=16, max_capacity=32,
                     seq_mesh=mesh, pool_capacity=256)
    c, s = _open_doc(server, sidecars, "big")
    for i in range(40):
        s.insert_text(0, "abcdefgh")
        c.flush()
    for sc in sidecars.values():
        sc.apply()
        sc.sync()
    for route in ROUTES:
        assert sidecars[route].pooled_docs() == 1, route
    # pooled docs keep collaborating through the pool dispatch path
    for i in range(4):
        s.insert_text(0, "Q")
        c.flush()
    for sc in sidecars.values():
        sc.apply()
    _assert_parity(sidecars, ["big"], {"big": s})
