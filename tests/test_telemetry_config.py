"""Telemetry + config systems.

Mirrors telemetry-utils tests (logger hierarchy, perf events,
sampling, config typed getters) and services-telemetry Lumberjack
tests, plus live wiring through Container and LocalOrderer.
"""
import pytest

from fluidframework_tpu.service.telemetry import (
    InMemoryLumberjackEngine,
    Lumberjack,
)
from fluidframework_tpu.utils.config import (
    CachedConfigProvider,
    ConfigProvider,
    MonitoringContext,
    mixin_monitoring_context,
)
from fluidframework_tpu.utils.telemetry import (
    ChildLogger,
    MockLogger,
    MultiSinkLogger,
    PerformanceEvent,
    SampledTelemetryHelper,
    TaggedTelemetryLogger,
)


# ----------------------------------------------------------------------
# logger hierarchy

def test_child_logger_namespaces():
    mock = MockLogger()
    child = ChildLogger(mock, "loader")
    grandchild = ChildLogger(child, "container")
    grandchild.send_telemetry_event("connected", clientId="a")
    assert mock.events[0]["eventName"] == "loader:container:connected"
    assert mock.events[0]["clientId"] == "a"


def test_multi_sink_fans_out():
    a, b = MockLogger(), MockLogger()
    multi = MultiSinkLogger([a])
    multi.add_sink(b)
    multi.send_telemetry_event("x")
    assert a.events and b.events


def test_tagged_logger_redacts():
    mock = MockLogger()
    tagged = TaggedTelemetryLogger(mock, {"userText"})
    tagged.send({"eventName": "op", "userText": "secret", "size": 3})
    assert mock.events[0]["userText"] == "REDACTED"
    assert mock.events[0]["size"] == 3


def test_mock_logger_ordered_subset_match():
    mock = MockLogger()
    mock.send_telemetry_event("a", v=1)
    mock.send_telemetry_event("b", v=2)
    mock.send_telemetry_event("c")
    assert mock.matches([{"eventName": "a"}, {"eventName": "c"}])
    assert not mock.matches([{"eventName": "c"}, {"eventName": "a"}])


def test_performance_event_success_and_cancel():
    mock = MockLogger()
    with PerformanceEvent(mock, "load", docId="d"):
        pass
    assert mock.events[0]["eventName"] == "load_end"
    assert mock.events[0]["category"] == "performance"
    assert mock.events[0]["duration"] >= 0
    with pytest.raises(ValueError):
        with PerformanceEvent(mock, "load"):
            raise ValueError("boom")
    assert mock.events[1]["eventName"] == "load_cancel"
    assert mock.events[1]["category"] == "error"


def test_sampled_helper_aggregates():
    mock = MockLogger()
    helper = SampledTelemetryHelper(mock, "opLatency", sample_every=3)
    for ms in (1.0, 2.0, 3.0):
        helper.record(ms)
    assert len(mock.events) == 1
    event = mock.events[0]
    assert event["count"] == 3 and event["mean"] == 2.0
    helper.record(5.0)
    assert len(mock.events) == 1  # not yet at sample boundary
    helper.flush()
    assert mock.events[1]["count"] == 1


# ----------------------------------------------------------------------
# config

def test_cached_config_typed_getters():
    cfg = CachedConfigProvider(ConfigProvider({
        "flagTrue": "true", "flagBool": False, "num": "42",
        "realNum": 7, "name": "prod", "junk": object(),
    }))
    assert cfg.get_boolean("flagTrue") is True
    assert cfg.get_boolean("flagBool") is False
    assert cfg.get_boolean("num") is None
    assert cfg.get_number("num") == 42.0
    assert cfg.get_number("realNum") == 7
    assert cfg.get_number("name") is None
    assert cfg.get_string("name") == "prod"
    assert cfg.get_string("junk") is None
    assert cfg.get_boolean("missing") is None


def test_config_provider_precedence_and_cache():
    calls = []

    def source(key):
        calls.append(key)
        return {"a": 1}.get(key)

    cfg = CachedConfigProvider(
        ConfigProvider({"a": 99}), ConfigProvider(source)
    )
    assert cfg.get_number("a") == 99  # first provider wins
    assert cfg.get_number("a") == 99
    assert calls == []  # never consulted, cached
    assert cfg.get_number("b") is None
    assert cfg.get_number("b") is None
    assert calls == ["b"]  # cached miss too


def test_monitoring_context_mixin():
    mock = MockLogger()
    mc = mixin_monitoring_context(mock, ConfigProvider({"gate": True}))
    assert isinstance(mc, MonitoringContext)
    assert mc.config.get_boolean("gate") is True


# ----------------------------------------------------------------------
# lumberjack

def test_lumberjack_metric_lifecycle():
    engine = InMemoryLumberjackEngine()
    lj = Lumberjack([engine], {"service": "deli"})
    metric = lj.new_metric("ticket", {"documentId": "doc"})
    metric.set_property("clientId", "a")
    metric.success("sequenced")
    (lumber,) = engine.events_named("ticket")
    assert lumber.successful and lumber.duration_ms >= 0
    assert lumber.properties["service"] == "deli"
    assert lumber.properties["clientId"] == "a"


def test_lumberjack_error_with_exception():
    engine = InMemoryLumberjackEngine()
    lj = Lumberjack([engine])
    metric = lj.new_metric("write")
    metric.error("failed", exception=RuntimeError("disk"))
    (lumber,) = engine.emitted
    assert lumber.successful is False
    assert "disk" in lumber.properties["exception"]


def test_lumber_double_emit_is_recorded_not_a_crash():
    # the old `assert not self._emitted` guard vanished under
    # `python -O` (silent double emit) and crashed the service path
    # otherwise; a double-completion is now a LOUD recorded error
    # event — the first emission stands, the duplicate is evidence
    engine = InMemoryLumberjackEngine()
    metric = Lumberjack([engine]).new_metric("m")
    metric.success()
    metric.success()  # no raise
    assert len(engine.events_named("m")) == 1
    (dup,) = engine.events_named("m:doubleEmit")
    assert dup.successful is False
    assert "completed twice" in dup.message


# ----------------------------------------------------------------------
# live wiring

def test_container_emits_connection_and_latency_telemetry():
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.service.local_server import LocalServer

    mock = MockLogger()
    mc = mixin_monitoring_context(mock, ConfigProvider({}))
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    c = Container.load(factory.create_document_service("doc"),
                       client_id="alice", mc=mc)
    m = c.runtime.create_datastore("d").create_channel("sharedmap", "m")
    c.flush()
    for i in range(25):
        m.set(f"k{i}", i)
        c.flush()
    assert mock.matches([{"eventName": "connected"}])
    perf = [e for e in mock.events
            if e["eventName"] == "opRoundtripTime"]
    assert perf and perf[0]["count"] == 20  # sampled aggregation
    c.disconnect()
    assert mock.matches([{"eventName": "disconnected"}])


def test_container_config_gates_compression():
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.service.local_server import LocalServer

    mc = mixin_monitoring_context(
        MockLogger(),
        ConfigProvider({"compressionMinSize": 128, "chunkSize": 4096}),
    )
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    c = Container.load(factory.create_document_service("doc"),
                       client_id="a", mc=mc)
    assert c.runtime.compressor.min_size == 128
    assert c.runtime.splitter.chunk_size == 4096


def test_orderer_logs_nacks_via_lumberjack():
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )
    from fluidframework_tpu.service.local_orderer import LocalOrderer

    engine = InMemoryLumberjackEngine()
    orderer = LocalOrderer("doc", lumberjack=Lumberjack([engine]))
    nack = orderer.submit("ghost", DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION,
    ))
    assert nack is not None
    (lumber,) = engine.events_named("nack")
    assert lumber.properties["clientId"] == "ghost"
