"""Durable storage plane: content-addressed summary trees, incremental
handle summaries, chunked snapshots, persisted op log + checkpoints,
and kill-and-restart resume across a real process boundary.

Reference parity: historian/gitrest (content-addressed summary
storage), SummaryType.Handle incremental summaries (summary.ts:55-59),
chunked merge-tree snapshots (snapshotV1.ts:36, snapshotChunks.ts),
scriptorium's durable op log, deli checkpoint/restore
(deli/checkpointContext.ts).
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer
from fluidframework_tpu.service.storage import (
    ContentStore,
    DocumentStorage,
    FileContentStore,
    SummaryTreeStore,
)


# ----------------------------------------------------------------------
# content-addressed tree store

def test_tree_store_roundtrip_and_dedup():
    store = SummaryTreeStore(ContentStore())
    summary = {
        "protocol": {"members": ["a", "b"]},
        "runtime": {
            "datastores": {
                "d": {"root": True, "channels": {
                    "t": {"type": "sharedstring",
                          "content": {"chunks": [[1, 2], [3]]}},
                }},
            },
            "blobs": {},
        },
    }
    root1 = store.write(summary)
    assert store.read(root1) == summary
    n1 = store.store.object_count()
    # identical summary: zero new objects
    root2 = store.write(summary)
    assert root2 == root1
    assert store.store.object_count() == n1
    # change one channel chunk: only the changed path writes objects
    summary2 = json.loads(json.dumps(summary))
    summary2["runtime"]["datastores"]["d"]["channels"]["t"][
        "content"]["chunks"][1] = [3, 4]
    root3 = store.write(summary2, previous_root=root1)
    delta = store.store.object_count() - n1
    assert root3 != root1
    assert delta <= 8  # changed chunk + spine rewrite, not O(tree)
    # the chunk split must actually engage (a regression at depth 5
    # stored the whole multi-chunk snapshot as one blob)
    assert any(
        b"__chunklist__" in store.store._load(sha)
        for sha in store.store._objects
    )
    # unchanged chunk [1, 2] was reused: exactly one object holds it
    chunk_sha = store.store.put([1, 2])  # idempotent: already there
    assert store.store.has(chunk_sha)


def test_tree_store_handle_resolution():
    store = SummaryTreeStore(ContentStore())
    v1 = {"runtime": {"datastores": {"d": {"channels": {
        "t": {"type": "x", "content": {"v": 1}},
    }}}}}
    root1 = store.write(v1)
    v2 = {"runtime": {"datastores": {"d": {"channels": {
        "t": {"__summary_handle__": "runtime/datastores/d/channels/t"},
    }}}}}
    root2 = store.write(v2, previous_root=root1)
    assert store.read(root2) == v1
    with pytest.raises(ValueError):
        store.write(v2, previous_root=None)


def test_file_content_store_persists(tmp_path):
    root = str(tmp_path / "store")
    s1 = FileContentStore(root)
    sha = s1.put({"hello": [1, 2, 3]})
    s2 = FileContentStore(root)  # fresh instance, same dir
    assert s2.has(sha)
    assert s2.get(sha) == {"hello": [1, 2, 3]}


# ----------------------------------------------------------------------
# incremental summaries end to end (client handles -> store expansion)

def _mk_pair(server):
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice")
    text = (a.runtime.create_datastore("d")
            .create_channel("sharedstring", "t"))
    other = a.runtime.get_datastore("d").create_channel(
        "sharedmap", "m"
    )
    a.flush()
    return a, text, other


def test_incremental_summary_unchanged_channel_is_handle():
    server = LocalServer()
    a, text, other = _mk_pair(server)
    text.insert_text(0, "hello world")
    other.set("k", 1)
    a.flush()
    a.summarize()  # full; ack arrives synchronously via local orderer

    # edit ONLY the map; the string must summarize as a handle
    other.set("k", 2)
    a.flush()
    summary = a.summarize(incremental=True)
    channels = summary["runtime"]["datastores"]["d"]["channels"]
    assert "__summary_handle__" in channels["t"]
    assert "content" in channels["m"]

    # the stored (expanded) version still loads with full content
    latest = server.get_orderer("doc").summary_store.latest()
    stored = latest.summary["summary"] if "summary" in latest.summary \
        else latest.summary
    chans = stored["runtime"]["datastores"]["d"]["channels"]
    assert chans["t"]["type"] == "sharedstring"
    b = Container.load(
        LocalDocumentServiceFactory(server)
        .create_document_service("doc"),
        client_id="bob",
    )
    tb = b.runtime.get_datastore("d").get_channel("t")
    assert tb.get_text() == "hello world"
    assert b.runtime.get_datastore("d").get_channel("m").get("k") == 2


def test_second_summary_of_unchanged_container_is_cheap():
    server = LocalServer()
    a, text, other = _mk_pair(server)
    text.insert_text(0, "stable content " * 50)
    a.flush()
    a.summarize()
    store = server.get_orderer("doc").summary_store
    n1 = store.object_count()
    # nothing changed except the collab window advancing via the
    # summarize op itself; the second incremental summary should write
    # O(1) new objects, not re-store every channel
    a.summarize(incremental=True)
    assert store.version_count == 2
    assert store.object_count() - n1 <= 10


def test_chunked_snapshot_roundtrip():
    server = LocalServer()
    a, text, _ = _mk_pair(server)
    from fluidframework_tpu.models import sharedstring as ss_mod

    # force multiple chunks with a small chunk size
    orig = ss_mod.SNAPSHOT_CHUNK_SEGMENTS
    ss_mod.SNAPSHOT_CHUNK_SEGMENTS = 4
    try:
        for i in range(30):
            text.insert_text(0, f"w{i} ")
        a.flush()
        summary = text.summarize_core()
        assert summary["format"] == 2
        assert len(summary["chunks"]) > 1
        clone = type(text)("t2")
        clone.load_core(summary)
        assert clone.get_text() == text.get_text()
        # format-1 (flat) snapshots must still load
        flat = {
            "segments": [e for c in summary["chunks"] for e in c],
            "minSeq": summary["minSeq"],
            "currentSeq": summary["currentSeq"],
            "intervals": {},
        }
        clone2 = type(text)("t3")
        clone2.load_core(flat)
        assert clone2.get_text() == text.get_text()
    finally:
        ss_mod.SNAPSHOT_CHUNK_SEGMENTS = orig


# ----------------------------------------------------------------------
# durable op log + checkpoint across a REAL process restart

def _run_worker(port, client_id, action):
    code = (
        "import sys; sys.path.insert(0, '.')\n"
        "from fluidframework_tpu.drivers.socket_driver import "
        "SocketDocumentService\n"
        "from fluidframework_tpu.loader import Container\n"
        f"svc = SocketDocumentService('127.0.0.1', {port}, 'dur-doc')\n"
        "with svc.lock:\n"
        f"    c = Container.load(svc, client_id={client_id!r})\n"
        "with svc.lock:\n"
        + action +
        "\nimport time\n"
        "deadline = time.time() + 30\n"
        "while time.time() < deadline:\n"
        "    with svc.lock:\n"
        "        if c.runtime.pending.count == 0: break\n"
        "    time.sleep(0.02)\n"
        "else:\n"
        "    raise TimeoutError('ops never acked')\n"
        "c.close(); svc.close()\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


@pytest.mark.slow
def test_service_survives_kill_and_restart(tmp_path):
    """VERDICT r3 #5 done-criterion: the service resumes from durable
    state across a process restart (SIGKILL, no graceful shutdown)."""
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data_dir = str(tmp_path / "data")

    def start_server():
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.service",
             "--port", "0", "--data-dir", data_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=repo,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        line = proc.stdout.readline()
        m = re.search(r"listening on [\w.]+:(\d+)", line)
        assert m, line
        return proc, int(m.group(1))

    server, port = start_server()
    try:
        _run_worker(port, "alice", (
            "    t = c.runtime.create_datastore('d')"
            ".create_channel('sharedstring', 't')\n"
            "    c.flush()\n"
            "    t.insert_text(0, 'before the crash')\n"
            "    c.flush()\n"
        ))
    finally:
        os.kill(server.pid, signal.SIGKILL)
        server.wait()

    # restart over the same data dir: op log + checkpoint reload
    server, port = start_server()
    try:
        out = _run_worker(port, "bob", (
            "    t = c.runtime.get_datastore('d').get_channel('t')\n"
            "    print('TEXT=' + t.get_text())\n"
            "    t.insert_text(0, 'after: ')\n"
            "    c.flush()\n"
            "    print('FINAL=' + t.get_text())\n"
        ))
        assert "TEXT=before the crash" in out
        assert "FINAL=after: before the crash" in out
    finally:
        server.kill()
        server.wait()

# ----------------------------------------------------------------------
# crash-atomicity: the enumerated torn states (chaos PR; the write
# barriers are write-temp+fsync+rename for the checkpoint and
# fsync-before-fanout for the op log — docs/ROBUSTNESS.md)

def _drive_some_ops(durable_dir, n=5):
    """Crash-shaped teardown: the container is ABANDONED, not closed
    — a crash sequences no client-leave, so the log's tail is the
    last real op (what the tear tests truncate)."""
    server = LocalServer(durable_dir=str(durable_dir))
    factory = LocalDocumentServiceFactory(server)
    c = Container.load(factory.create_document_service("torn-doc"),
                       client_id="w")
    ds = c.runtime.create_datastore("app")
    ds.create_channel("sharedstring", "t")
    text = c.runtime.get_datastore("app").get_channel("t")
    for i in range(n):
        text.insert_text(0, f"x{i}.")
        c.flush()
    final = text.get_text()
    return server, final


def _reload_text(durable_dir):
    server = LocalServer(durable_dir=str(durable_dir))
    factory = LocalDocumentServiceFactory(server)
    c = Container.load(factory.create_document_service("torn-doc"),
                       client_id="r")
    out = c.runtime.get_datastore("app").get_channel("t").get_text()
    c.close()
    return server, out


def test_torn_checkpoint_final_recovers_from_op_log(tmp_path):
    """The reordered-write crash state (rename durable before data —
    what the missing fsync used to permit): a prefix-truncated
    checkpoint.json parses as garbage. read_checkpoint must degrade
    LOUDLY to None and the restart fast-forwards the full op log."""
    _, final = _drive_some_ops(tmp_path)
    ckpt = tmp_path / "torn-doc" / "checkpoint.json"
    data = ckpt.read_bytes()
    ckpt.write_bytes(data[: len(data) // 2])
    server, text = _reload_text(tmp_path)
    assert text == final
    # and sequencing continues contiguously after the recovery
    orderer = server.get_orderer("torn-doc")
    last = orderer.op_log.last_seq
    orderer.connect(__import__(
        "fluidframework_tpu.protocol.messages",
        fromlist=["ClientDetail"]).ClientDetail("w2"))
    assert orderer.op_log.last_seq == last + 1


def test_crash_between_checkpoint_write_and_rename(tmp_path):
    """A torn .tmp beside the intact checkpoint (crash inside the
    write-temp+fsync+rename window): the committed checkpoint is the
    truth; the debris is cleared on reload."""
    _, final = _drive_some_ops(tmp_path)
    tmp = tmp_path / "torn-doc" / "checkpoint.json.tmp"
    tmp.write_bytes(b'{"sequencer": {"torn')
    _, text = _reload_text(tmp_path)
    assert text == final
    assert not tmp.exists(), "stale checkpoint tmp must be cleared"


def test_torn_oplog_tail_is_discarded_and_rewritten(tmp_path):
    """Crash mid-append: a partial final JSONL line. The loader
    discards exactly that op (never fanned out, so no client has it
    — the fsync-before-fanout barrier) and rewrites the log so a
    second crash cannot stack onto the half record."""
    _, final = _drive_some_ops(tmp_path)
    oplog = tmp_path / "torn-doc" / "ops.jsonl"
    lines = oplog.read_bytes().splitlines(keepends=True)
    torn_away = json.loads(lines[-1])
    oplog.write_bytes(b"".join(lines[:-1]) + lines[-1][: len(lines[-1]) // 2])
    server, text = _reload_text(tmp_path)
    # the torn op's insert is gone (x4.), everything before it intact
    assert text == final.replace("x4.", "", 1)
    # the log was re-truncated to whole records and new sequencing
    # continues contiguously from the surviving head: the torn op's
    # seq slot is REUSED (here by the reader's join) — never left as
    # a gap, never still holding the torn OPERATION
    reread = [json.loads(ln) for ln in
              oplog.read_bytes().splitlines() if ln.strip()]
    seqs = [r["sequenceNumber"] for r in reread]
    assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
    reused = [r for r in reread
              if r["sequenceNumber"] == torn_away["sequenceNumber"]]
    assert all(r["type"] != torn_away["type"] for r in reused)
    orderer = server.get_orderer("torn-doc")
    assert orderer.sequencer.sequence_number == \
        orderer.op_log.last_seq


def test_torn_middle_oplog_line_is_corruption_not_crash(tmp_path):
    """A malformed line ANYWHERE but the tail is not a legal crash
    state (appends are sequential + fsynced): refuse loudly."""
    _, _ = _drive_some_ops(tmp_path)
    oplog = tmp_path / "torn-doc" / "ops.jsonl"
    lines = oplog.read_bytes().splitlines(keepends=True)
    lines[1] = lines[1][: len(lines[1]) // 2].rstrip() + b"\n"
    oplog.write_bytes(b"".join(lines))
    with pytest.raises(ValueError, match="corrupt at line 2"):
        _reload_text(tmp_path)


def test_torn_versions_tail_is_discarded_and_rewritten(tmp_path):
    """A torn versions.jsonl tail must be REWRITTEN on load, not just
    skipped: the next commit_summary appends, and stacking a fresh
    record onto the half line would turn a recoverable crash state
    into mid-file corruption at the load after that."""
    from fluidframework_tpu.service.storage import DocumentStorage

    st = DocumentStorage(str(tmp_path / "doc"))
    st.write_summary(1, {"runtime": {"a": 1}})
    st.write_summary(2, {"runtime": {"a": 2}})
    vpath = tmp_path / "doc" / "versions.jsonl"
    lines = vpath.read_bytes().splitlines(keepends=True)
    vpath.write_bytes(b"".join(lines[:-1]) + lines[-1][:10])
    st2 = DocumentStorage(str(tmp_path / "doc"))
    assert [v.sequence_number for v in st2.versions] == [1]
    # the append after recovery lands on a CLEAN file...
    st2.write_summary(3, {"runtime": {"a": 3}})
    # ...so the next load parses every line (no mid-file corruption)
    st3 = DocumentStorage(str(tmp_path / "doc"))
    assert [v.sequence_number for v in st3.versions] == [1, 3]


def test_queue_commit_offset_is_crash_atomic(tmp_path):
    """FileOrderingQueue.commit used to plain-overwrite the offset
    file — a crash mid-write could leave a TORN offset. It now routes
    through storage.atomic_write (asserted structurally), leaves no
    tmp debris, and tolerates pre-barrier debris on load."""
    from fluidframework_tpu.service import partitioning as part
    from fluidframework_tpu.service.partitioning import (
        FileOrderingQueue,
    )

    q = FileOrderingQueue(str(tmp_path / "q"), 1)
    q.produce(0, "d", {"v": 1})
    calls = []
    real = part.atomic_write

    def spy(path, data):
        calls.append(path)
        real(path, data)

    part.atomic_write = spy
    try:
        q.commit(0, 0)
    finally:
        part.atomic_write = real
    assert calls and calls[0].endswith("partition-0.offset"), (
        "commit must route through the shared crash-atomic barrier")
    assert not os.path.exists(
        str(tmp_path / "q" / "partition-0.offset.tmp"))
    # stale .tmp debris (crash between write and rename) is cleared
    # on load and the committed file stays the truth
    debris = tmp_path / "q" / "partition-0.offset.tmp"
    debris.write_text("99")
    q2 = FileOrderingQueue(str(tmp_path / "q"), 1)
    assert q2.committed(0) == 0
    assert not debris.exists()


def test_torn_queue_offset_states_are_pinned(tmp_path):
    """The enumerated torn-offset states the old plain overwrite
    permitted: (a) a numeric PREFIX ("1" torn from "15") silently
    rewinds the checkpoint — absorbed, because consumers re-read from
    the committed offset and the at-least-once dedupe drops replays;
    (b) garbage degrades LOUDLY to 'no commit' instead of crashing
    the partition load."""
    from fluidframework_tpu.service.partitioning import (
        FileOrderingQueue,
    )

    root = str(tmp_path / "q")
    q = FileOrderingQueue(root, 1)
    for i in range(16):
        q.produce(0, "d", {"v": i})
    q.commit(0, 14)
    offset = tmp_path / "q" / "partition-0.offset"
    # (a) torn numeric prefix: "1" of "14" — a legal rewind
    offset.write_text("1")
    q2 = FileOrderingQueue(root, 1)
    assert q2.committed(0) == 1
    assert [r.offset for r in q2.read(0, q2.committed(0) + 1)] == \
        list(range(2, 16)), "re-consume from the rewound offset"
    # monotone guard: a late commit below the head is still honored
    # forward, never backward
    q2.commit(0, 14)
    assert q2.committed(0) == 14
    # (b) garbage: degrade loudly to -1, never crash the load
    before = __import__(
        "fluidframework_tpu.obs.metrics",
        fromlist=["REGISTRY"]).REGISTRY.flat().get(
        'storage_torn_recoveries_total{file="queue-offset"}', 0)
    offset.write_text("not-a-number")
    q3 = FileOrderingQueue(root, 1)
    assert q3.committed(0) == -1
    assert [r.offset for r in q3.read(0, 0)][:2] == [0, 1]
    after = __import__(
        "fluidframework_tpu.obs.metrics",
        fromlist=["REGISTRY"]).REGISTRY.flat().get(
        'storage_torn_recoveries_total{file="queue-offset"}', 0)
    assert after == before + 1, "the degrade must be LOUD"


# ----------------------------------------------------------------------
# bit rot: per-record CRCs + the scrubber (docs/ROBUSTNESS.md
# "Partition tolerance & degraded mode")


def test_midfile_bit_flip_detected_and_read_repaired(tmp_path):
    """A parseable record whose bytes changed at rest (crc mismatch)
    is CORRUPTION, not a crash state: the load refuses loudly, and
    the scrubber read-repairs it from a peer-supplied copy."""
    from fluidframework_tpu.service.storage import (
        CorruptRecordError,
        scrub_jsonl,
        scrub_repair_jsonl,
    )

    _, final = _drive_some_ops(tmp_path)
    oplog = tmp_path / "torn-doc" / "ops.jsonl"
    lines = oplog.read_text().splitlines(keepends=True)
    pristine = json.loads(lines[1])
    row = json.loads(lines[1])
    row["contents"] = {"bitrot": True}  # stale _crc: mismatch
    lines[1] = json.dumps(row) + "\n"
    oplog.write_text("".join(lines))
    # the load refuses: rot must never be silently served
    with pytest.raises(CorruptRecordError, match="crc mismatch"):
        _reload_text(tmp_path)
    # detect-only scrub classifies it (and nothing else)
    report = scrub_jsonl(str(oplog), "oplog")
    assert report.corrupt == [1] and not report.torn_tail
    # read-repair from a "peer" copy makes the log whole again
    repaired = scrub_repair_jsonl(
        str(oplog), "oplog",
        lambda i, rows: dict(pristine) if i == 1 else None)
    assert repaired.repaired == 1
    _, text = _reload_text(tmp_path)
    assert text == final


def test_torn_tail_still_recovers_locally_not_via_scrub(tmp_path):
    """The scrubber DISTINGUISHES: a torn tail is the PR9-recoverable
    crash state — left byte-for-byte for the loader's local discard,
    never treated as rot needing a peer."""
    from fluidframework_tpu.service.storage import (
        scrub_jsonl,
        scrub_repair_jsonl,
    )

    _, final = _drive_some_ops(tmp_path)
    oplog = tmp_path / "torn-doc" / "ops.jsonl"
    lines = oplog.read_bytes().splitlines(keepends=True)
    oplog.write_bytes(b"".join(lines[:-1])
                      + lines[-1][: len(lines[-1]) // 2])
    report = scrub_jsonl(str(oplog), "oplog")
    assert report.torn_tail and report.corrupt == []
    # a repair pass with NO peer must succeed: nothing to repair
    repaired = scrub_repair_jsonl(str(oplog), "oplog",
                                  lambda i, rows: None)
    assert repaired.repaired == 0
    # the loader's torn-tail discard still applies (PR9 path)
    _, text = _reload_text(tmp_path)
    assert text == final.replace("x4.", "", 1)


def test_garbage_crc_with_no_surviving_peer_raises_loudly(tmp_path):
    """Unrepairable rot (every copy gone) must detonate, not degrade:
    serving a record whose bytes are provably wrong would be silent
    corruption."""
    from fluidframework_tpu.service.storage import (
        CorruptRecordError,
        scrub_repair_jsonl,
    )

    _drive_some_ops(tmp_path)
    oplog = tmp_path / "torn-doc" / "ops.jsonl"
    lines = oplog.read_text().splitlines(keepends=True)
    row = json.loads(lines[2])
    row["_crc"] = (row.get("_crc") or 0) + 1  # garbage checksum
    lines[2] = json.dumps(row) + "\n"
    oplog.write_text("".join(lines))
    with pytest.raises(CorruptRecordError, match="no surviving peer"):
        scrub_repair_jsonl(str(oplog), "oplog",
                           lambda i, rows: None)


def test_queue_record_crc_detected_and_scrubbed(tmp_path):
    """The partitioned plane's half: a bit-flipped queue record is
    refused on consume and read-repaired from a replica root by
    ReplicatedFileOrderingQueue.scrub()."""
    from fluidframework_tpu.service.partitioning import (
        ReplicatedFileOrderingQueue,
    )
    from fluidframework_tpu.service.storage import CorruptRecordError

    roots = [str(tmp_path / n) for n in ("lead", "f1", "f2")]
    q = ReplicatedFileOrderingQueue(roots[0], 1, roots[1:])
    for i in range(4):
        q.produce(0, "d", {"v": i})
    # flip a byte in one FOLLOWER root's record 1
    log = tmp_path / "f1" / "partition-0.jsonl"
    lines = log.read_text().splitlines(keepends=True)
    row = json.loads(lines[1])
    row["payload"] = {"v": 99}  # stale crc
    lines[1] = json.dumps(row) + "\n"
    log.write_text("".join(lines))
    from fluidframework_tpu.service.partitioning import (
        FileOrderingQueue,
    )

    broken = FileOrderingQueue(str(tmp_path / "f1"), 1)
    with pytest.raises(CorruptRecordError, match="crc"):
        list(broken.read(0, 0))
    assert q.scrub() == 1
    fixed = FileOrderingQueue(str(tmp_path / "f1"), 1)
    assert [r.payload["v"] for r in fixed.read(0, 0)] == [0, 1, 2, 3]


def test_legacy_rows_without_crc_still_load(tmp_path):
    """The PR4/PR6 interop discipline: pre-existing logs whose rows
    carry no _crc keep loading (nothing to verify), and the next
    rewrite stamps them."""
    from fluidframework_tpu.service.storage import (
        read_jsonl_tolerant,
    )

    _, final = _drive_some_ops(tmp_path)
    oplog = tmp_path / "torn-doc" / "ops.jsonl"
    rows = [json.loads(ln) for ln in
            oplog.read_text().splitlines()]
    for r in rows:
        r.pop("_crc", None)
    oplog.write_text("".join(json.dumps(r) + "\n" for r in rows))
    loaded, torn = read_jsonl_tolerant(str(oplog), "oplog")
    assert len(loaded) == len(rows) and not torn
    _, text = _reload_text(tmp_path)
    assert text == final


def test_gap_over_truncated_log_raises_actionably(tmp_path):
    """A replica behind a summary-truncated log whose reconnect-time
    catch-up was EMPTY (no trailing ops yet) must fail with the loud
    truncation error when the next fanout exposes the unfillable gap
    — not the bare inbound-contiguity assert."""
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("trunc-doc"),
                       client_id="a")
    ds = a.runtime.create_datastore("app")
    ds.create_channel("sharedstring", "t")
    for i in range(4):
        ds.get_channel("t").insert_text(0, f"x{i}")
        a.flush()
    b = Container.load(factory.create_document_service("trunc-doc"),
                       client_id="b")
    b.disconnect()
    # while b is offline: more ops, then a summary truncates the log
    # above b's position, then NO trailing ops before b reconnects
    for i in range(3):
        ds.get_channel("t").insert_text(0, f"y{i}")
        a.flush()
    orderer = server.get_orderer("trunc-doc")
    orderer.op_log.truncate_below(orderer.sequencer.sequence_number)
    # reconnect: the direct catch-up read is empty (nothing trails
    # the truncation), but the join broadcast immediately exposes the
    # unfillable gap — loud and actionable, not the bare contiguity
    # assert three frames later
    with pytest.raises(RuntimeError, match="not in delta storage"):
        b.connect()
    a.close()
