"""Register field kind (modular-schema value/optional — VERDICT r3
missing #2): per-kind compose/invert/rebase, cross-kind changesets,
algebra laws fuzzed per kind and mixed, and DDS-level LWW convergence
(two clients filling one optional field merge to ONE winner)."""
import copy
import random

import pytest

from fluidframework_tpu.models.tree import changeset as cs
from fluidframework_tpu.models.tree.forest import Forest, node
from fluidframework_tpu.models.tree.schema import (
    OPTIONAL,
    SEQUENCE,
    VALUE,
    FieldSchema,
    NodeSchema,
    SchemaViolation,
    StoredSchema,
)
from fluidframework_tpu.testing.runtime_mocks import ContainerSession


def apply_to(fields: dict, changes) -> dict:
    f = Forest()
    f.fields = copy.deepcopy(fields)
    f.apply(changes, revision=("t", 0))
    return f.fields


def n(v):
    return node("item", value=v)


# ---- unit: compose / invert / rebase per kind ------------------------

def test_reg_set_apply_and_invert():
    base = {"opt": [n(1)]}
    change = {"opt": cs.reg_set(n(2), n(1))}
    cs.stamp(change, "u1")
    after = apply_to(base, change)
    assert after["opt"][0]["value"] == 2
    inv = cs.invert(change, "inv1")
    restored = apply_to(after, inv)
    assert restored["opt"][0]["value"] == 1


def test_reg_clear_and_fill_optional():
    base = {"opt": [n(1)]}
    clear = {"opt": cs.reg_set(None, n(1))}
    cs.stamp(clear, "u1")
    after = apply_to(base, clear)
    assert after["opt"] == []
    fill = {"opt": cs.reg_set(n(9), None)}
    cs.stamp(fill, "u2")
    assert apply_to(after, fill)["opt"][0]["value"] == 9
    # inverse of clear restores the node
    assert apply_to(after, cs.invert(clear, "i"))["opt"][0]["value"] == 1


def test_reg_compose_set_set_keeps_oldest_old():
    a = {"opt": cs.reg_set(n(2), n(1))}
    b = {"opt": cs.reg_set(n(3), n(2))}
    cs.stamp(a, "ua")
    cs.stamp(b, "ub")
    comp = cs.compose([a, b])
    assert comp["opt"]["set"]["new"]["value"] == 3
    assert comp["opt"]["set"]["old"]["value"] == 1
    # inverse of the composite restores the original
    assert apply_to({"opt": [n(1)]}, comp)["opt"][0]["value"] == 3
    restored = apply_to(
        {"opt": [n(3)]}, cs.invert(comp, "i"))
    assert restored["opt"][0]["value"] == 1


def test_reg_nested_mods_compose_and_invert():
    child = node("obj")
    child["fields"] = {"kids": [n(5)]}
    base = {"opt": [child]}
    # modify the register node's nested sequence field
    mods = {"opt": cs.reg_mods(
        [cs.mod(fields={"kids": [cs.ins([n(6)])]})])}
    cs.stamp(mods, "u1")
    after = apply_to(base, mods)
    assert [x["value"] for x in after["opt"][0]["fields"]["kids"]] == \
        [6, 5]
    restored = apply_to(after, cs.invert(mods, "i1"))
    assert [x["value"] for x in restored["opt"][0]["fields"]["kids"]] \
        == [5]


def test_reg_rebase_concurrent_sets_lww():
    base = {"opt": [n(0)]}
    a = {"opt": cs.reg_set(n(1), n(0))}
    b = {"opt": cs.reg_set(n(2), n(0))}
    cs.stamp(a, "ua")
    cs.stamp(b, "ub")
    # a sequences first; b rebases over a and still applies (LWW)
    b2 = cs.rebase(copy.deepcopy(b), a)
    final = apply_to(apply_to(base, a), b2)
    assert final["opt"][0]["value"] == 2
    # the mirror order converges to the later-SEQUENCED writer
    a2 = cs.rebase(copy.deepcopy(a), b)
    final2 = apply_to(apply_to(base, b), a2)
    assert final2["opt"][0]["value"] == 1


def test_reg_rebase_mods_over_set_mute_and_unmute():
    """Nested mods whose node a concurrent set replaced mute; the
    set's inverse unmutes them (the sandwich property)."""
    child = node("obj")
    child["fields"] = {"kids": [n(5)]}
    base = {"opt": [child]}
    setter = {"opt": cs.reg_set(n(9), copy.deepcopy(child))}
    modder = {"opt": cs.reg_mods(
        [cs.mod(fields={"kids": [cs.ins([n(6)])]})])}
    cs.stamp(setter, "us")
    cs.stamp(modder, "um")
    # setter sequences first: modder's nested edit mutes
    m2 = cs.rebase(copy.deepcopy(modder), setter)
    assert "mods" not in m2["opt"]
    assert m2["opt"]["muted"][0]["by"] == setter["opt"]["set"]["sid"]
    after = apply_to(apply_to(base, setter), m2)
    assert after["opt"][0]["value"] == 9  # mods did not corrupt
    # the set's inverse restores the child; rebasing the muted change
    # over it unmutes
    inv = cs.invert(setter, "inv")
    m3 = cs.rebase(m2, inv)
    assert m3["opt"].get("mods")
    restored = apply_to(after, inv)
    final = apply_to(restored, m3)
    assert [x["value"] for x in final["opt"][0]["fields"]["kids"]] == \
        [6, 5]


def test_mixed_kind_changeset():
    """Sequence and register fields compose/rebase side by side in one
    changeset."""
    base = {"seq": [n(1), n(2)], "opt": [n(0)]}
    a = {"seq": [cs.ins([n(9)])], "opt": cs.reg_set(n(7), n(0))}
    b = {"seq": [cs.skip(2), cs.ins([n(8)])]}
    cs.stamp(a, "ua")
    cs.stamp(b, "ub")
    b2 = cs.rebase(copy.deepcopy(b), a)
    final = apply_to(apply_to(base, a), b2)
    assert [x["value"] for x in final["seq"]] == [9, 1, 2, 8]
    assert final["opt"][0]["value"] == 7
    comp = cs.compose([a, b2])
    assert apply_to(base, comp) == final


def test_mixed_kind_concurrent_edits_converge_not_crash():
    """One client edits a field through the sequence surface while
    another uses the register surface (an app modeling error): the
    register change lowers to delete+insert and the document CONVERGES
    instead of wedging every replica with a rebase exception
    (code-review r4 reproduced exactly this crash)."""
    s, (ta, tb) = make_session()
    ta.insert_nodes(("cfg",), 0, [n(0)])
    s.process_all()
    # concurrent: A sequence-inserts, B register-sets
    ta.insert_nodes(("cfg",), 0, [n(1)])
    tb.set_register(("cfg",), n(2))
    s.process_all()          # must not raise
    assert ta.signature() == tb.signature()
    # and the reverse order on a fresh doc
    s2, (tc, td) = make_session()
    tc.insert_nodes(("cfg",), 0, [n(0)])
    s2.process_all()
    td.set_register(("cfg",), n(5))
    tc.insert_nodes(("cfg",), 1, [n(6)])
    s2.process_all()
    assert tc.signature() == td.signature()


def test_mixed_kind_compose_lowers():
    a = {"f": cs.reg_set(n(1), None)}
    b = {"f": [cs.skip(1), cs.ins([n(2)])]}
    cs.stamp(a, "ua")
    cs.stamp(b, "ub")
    comp = cs.compose([a, b])
    assert isinstance(comp["f"], list)  # lowered to sequence marks
    out = apply_to({"f": []}, comp)
    assert [x["value"] for x in out["f"]] == [1, 2]


# ---- algebra laws fuzz -----------------------------------------------

def _rand_reg(rng, uid):
    """Random register change authored against base {"opt": [n(-1)]}
    (old values reflect the author's view, as real authoring does)."""
    roll = rng.random()
    if roll < 0.5:
        ch = {"opt": cs.reg_set(
            n(rng.randint(0, 99)) if rng.random() < 0.8 else None,
            n(-1))}
    else:
        ch = {"opt": cs.reg_mods([cs.mod(
            value={"new": rng.randint(0, 99), "old": -1})])}
    return cs.stamp(ch, uid)


@pytest.mark.parametrize("seed", range(15))
def test_reg_laws_fuzz(seed):
    """rebaser.ts:138 laws on register changes:
    rebase(a, compose([b, c])) == rebase(rebase(a, b), c);
    compose([a, invert(a)]) applies as identity."""
    rng = random.Random(seed)
    base = {"opt": [n(-1)]}
    a = _rand_reg(rng, "a")
    b = _rand_reg(rng, "b")
    c = _rand_reg(rng, "c")
    lhs = cs.rebase(copy.deepcopy(a), cs.compose(
        [copy.deepcopy(b), cs.rebase(copy.deepcopy(c), b)]))
    rhs = cs.rebase(
        cs.rebase(copy.deepcopy(a), b),
        cs.rebase(copy.deepcopy(c), b))
    state = apply_to(apply_to(base, b),
                     cs.rebase(copy.deepcopy(c), b))
    assert apply_to(state, lhs) == apply_to(state, rhs), seed

    inv = cs.invert(copy.deepcopy(a), "inv")
    after = apply_to(base, a)
    assert apply_to(after, inv) == base, seed


@pytest.mark.parametrize("seed", range(10))
def test_mixed_laws_fuzz(seed):
    """Convergence across mixed-kind changesets: both rebase orders of
    two concurrent edits produce the same final tree."""
    rng = random.Random(100 + seed)

    def rand_change(uid):
        ch = {}
        if rng.random() < 0.7:
            marks = []
            if rng.random() < 0.5:
                marks.append(cs.skip(rng.randint(0, 1)))
            marks.append(
                cs.ins([n(rng.randint(0, 9))])
                if rng.random() < 0.6 else cs.dele(1))
            ch["seq"] = marks
        if rng.random() < 0.7:
            ch["opt"] = cs.reg_set(
                n(rng.randint(10, 19)) if rng.random() < 0.8
                else None, None)
        if not ch:
            ch["seq"] = [cs.ins([n(0)])]
        return cs.stamp(ch, uid)

    base = {"seq": [n(1), n(2), n(3)], "opt": [n(0)]}
    a = rand_change("a")
    b = rand_change("b")
    # order 1: a then rebase(b, a); order 2 must converge at the state
    # level when sequencing picks the same total order — emulate the
    # sequenced order [a, b]
    fin = apply_to(apply_to(base, a),
                   cs.rebase(copy.deepcopy(b), a))
    comp = cs.compose([copy.deepcopy(a),
                       cs.rebase(copy.deepcopy(b), a)])
    assert apply_to(base, comp) == fin, seed


# ---- DDS-level: concurrent optional fill converges LWW ---------------

def make_session():
    s = ContainerSession(["A", "B"])
    for cid in ("A", "B"):
        s.runtime(cid).create_datastore("ds").create_channel(
            "sharedtree", "t")
    return s, [
        s.runtime(cid).get_datastore("ds").get_channel("t")
        for cid in ("A", "B")
    ]


def test_concurrent_optional_fill_single_winner():
    s, (ta, tb) = make_session()
    schema = StoredSchema(
        nodes={"item": NodeSchema("item", value="any")},
        root_fields={"cfg": FieldSchema(kind=OPTIONAL,
                                        allowed_types=("item",))},
    )
    ta.set_stored_schema(schema)
    s.process_all()
    ta.set_register(("cfg",), n(1))
    tb.set_register(("cfg",), n(2))
    s.process_all()
    assert ta.signature() == tb.signature()
    # ONE winner (the later-sequenced set), not two nodes
    assert len(ta.get_field(("cfg",))) == 1
    assert ta.get_field(("cfg",))[0]["value"] == 2


def test_register_undo_restores_previous_value():
    s, (ta, tb) = make_session()
    ta.set_register(("cfg",), n(1))
    s.process_all()
    tb.set_register(("cfg",), n(2))
    s.process_all()
    assert ta.get_field(("cfg",))[0]["value"] == 2
    # schema-free editable surface
    root = ta.editable()
    root.field("cfg").set(n(3))
    s.process_all()
    assert tb.get_field(("cfg",))[0]["value"] == 3
    root.field("cfg").clear()
    s.process_all()
    assert ta.get_field(("cfg",)) == []
    assert ta.signature() == tb.signature()


def test_value_field_cannot_clear():
    s, (ta, _) = make_session()
    schema = StoredSchema(
        nodes={"item": NodeSchema("item", value="any")},
        root_fields={"v": FieldSchema(kind=VALUE, allowed_types=("item",))},
    )
    # a value field must hold a node for the tree to conform; fill it
    # via register first (schema validates on set)
    ta.set_register(("v",), n(1))
    s.process_all()
    ta.set_stored_schema(schema)
    s.process_all()
    with pytest.raises(SchemaViolation, match="cleared"):
        ta.set_register(("v",), None)


def test_set_register_rejected_on_sequence_field():
    s, (ta, _) = make_session()
    schema = StoredSchema(
        nodes={"item": NodeSchema("item", value="any")},
        root_fields={"items": FieldSchema(kind=SEQUENCE,
                                          allowed_types=("item",))},
    )
    ta.set_stored_schema(schema)
    s.process_all()
    with pytest.raises(SchemaViolation, match="sequence"):
        ta.set_register(("items",), n(1))
