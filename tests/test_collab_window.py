"""NoOp heartbeat / CollabWindowTracker (collabWindowTracker.ts).

Without heartbeats an idle write client pins the service msn at its
last submitted refSeq forever: zamboni never collects, tombstones grow
without bound (VERDICT r1 missing #3).
"""
from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.loader.collab_window import CollabWindowTracker
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.service import LocalServer


def make_pair(server=None, noop_every=None):
    from fluidframework_tpu.utils.config import (
        CachedConfigProvider,
        ConfigProvider,
        MonitoringContext,
    )
    from fluidframework_tpu.utils.telemetry import TelemetryLogger

    server = server or LocalServer()
    factory = LocalDocumentServiceFactory(server)
    mc = None
    if noop_every is not None:
        mc = MonitoringContext(
            TelemetryLogger(),
            CachedConfigProvider(ConfigProvider(
                {"noopCountFrequency": noop_every})),
        )
    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice", mc=mc)
    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob", mc=mc)
    sa = a.runtime.create_datastore("d").create_channel("sharedstring", "t")
    a.flush()
    sb = b.runtime.get_datastore("d").get_channel("t")
    return server, a, b, sa, sb


def test_idle_client_emits_noop_and_msn_advances():
    server, a, b, sa, sb = make_pair(noop_every=10)
    orderer = server.get_orderer("doc")
    for i in range(25):
        sa.insert_text(0, "x")
        a.flush()
    # bob never typed, but his tracker must have heartbeated: the msn
    # advances past bob's join refSeq
    msn = orderer.sequencer.minimum_sequence_number
    assert msn > 10, f"msn pinned at {msn} by idle client"
    assert sa.get_text() == sb.get_text()


def test_msn_pinned_without_heartbeat():
    """Control: with an enormous threshold and no ticks, the idle
    client pins the msn — proving the heartbeat is what moves it."""
    server, a, b, sa, sb = make_pair(noop_every=10_000)
    orderer = server.get_orderer("doc")
    base_msn = orderer.sequencer.minimum_sequence_number
    for _ in range(30):
        sa.insert_text(0, "x")
        a.flush()
    assert orderer.sequencer.minimum_sequence_number <= base_msn + 1


def test_idle_tick_heartbeat():
    server, a, b, sa, sb = make_pair(noop_every=10_000)
    orderer = server.get_orderer("doc")
    for _ in range(10):
        sa.insert_text(0, "y")
        a.flush()
    b.collab_window.idle_s = 0.0  # fire on the next tick
    assert b.collab_window.tick(b.last_processed_seq)
    assert orderer.sequencer.minimum_sequence_number >= 10


def test_noop_heartbeat_unpins_zamboni():
    """The device-table-boundedness story: after heartbeats advance the
    msn, removed segments below the window actually get collected."""
    server, a, b, sa, sb = make_pair(noop_every=5)
    sa.insert_text(0, "hello world, this is a long line")
    a.flush()
    sa.remove_text(0, 6)
    a.flush()
    for _ in range(20):  # stream traffic so heartbeats fire
        sa.annotate_range(0, 4, {"bold": 1})
        a.flush()
    tree = sa.client.mergetree
    tree.zamboni()
    tombs = sum(1 for s in tree.segments if s.removed)
    assert tombs == 0, "tombstones survived despite heartbeat msn"


def test_tracker_no_noop_without_advance():
    sent = []
    t = CollabWindowTracker(lambda: sent.append(1), max_unacked_ops=5,
                            idle_s=0.0)
    t.on_op_sent(7)
    assert not t.tick(7)  # nothing unacknowledged
    t.on_op_processed(9)  # below threshold
    assert sent == []
    assert t.tick(9)  # idle with advance -> heartbeat
    assert sent == [1]


def test_own_ops_count_as_heartbeat():
    """A client actively typing must never emit noops: its real ops
    carry the refSeq."""
    server, a, b, sa, sb = make_pair(noop_every=8)
    submitted = []
    orig = a.collab_window._submit_noop
    a.collab_window._submit_noop = (
        lambda: submitted.append(1) or orig()
    )
    for _ in range(30):
        sa.insert_text(0, "z")
        a.flush()
        sb.insert_text(0, "w")
        b.flush()
    assert submitted == [], "active client emitted needless noops"


def test_idle_expiry_on_a_manual_clock():
    """The clock is injectable (the detcheck wall-clock-unrouted
    contract): idle-expiry heartbeats are driven entirely by the
    injected clock, so a test pins the schedule exactly — no real
    waiting, no wall-clock read."""
    sent = []
    t = 0.0
    tracker = CollabWindowTracker(
        lambda: sent.append(1), max_unacked_ops=0, idle_s=2.0,
        clock=lambda: t,
    )
    tracker.on_op_sent(3)
    t = 1.9
    assert not tracker.tick(9)      # advanced, but not idle enough
    t = 2.0
    assert tracker.tick(9)          # exactly idle_s since activity
    assert sent == [1]
    # the heartbeat itself counts as activity on the same clock
    t = 3.9
    assert not tracker.tick(12)
    t = 4.0
    assert tracker.tick(12)
    assert sent == [1, 1]
