"""SharedTree round-3 parity: stored schema, transactions + repair
rollback, AnchorSet, editable-tree surface.

Reference parity targets: feature-libraries/modular-schema (field
kinds), core/schema-stored (replicated schema), core/transaction +
forestRepairDataStore (atomic commit/abort with exact rollback),
core/tree/anchorSet.ts (anchors slide with edits, die on delete),
feature-libraries/editable-tree (typed surface).
"""
import pytest

from fluidframework_tpu.models.tree import (
    FieldSchema,
    NodeSchema,
    SchemaViolation,
    StoredSchema,
    node,
)
from fluidframework_tpu.testing.runtime_mocks import ContainerSession


def make(n=2):
    ids = [chr(ord("A") + i) for i in range(n)]
    s = ContainerSession(ids)
    for cid in ids:
        s.runtime(cid).create_datastore("d").create_channel(
            "sharedtree", "t")
    s.process_all()
    return s, ids


def tree(s, cid):
    return s.runtime(cid).get_datastore("d").get_channel("t")


def _schema():
    return StoredSchema(
        nodes={
            "list": NodeSchema("list", value="none", fields={
                "items": FieldSchema("sequence",
                                     allowed_types=("item",)),
            }),
            "item": NodeSchema("item", value="number"),
        },
        root_fields={"root": FieldSchema("sequence",
                                         allowed_types=("list",))},
    )


# ----------------------------------------------------------------------
# stored schema

def test_schema_validates_and_replicates():
    s, _ = make()
    a, b = tree(s, "A"), tree(s, "B")
    a.insert_nodes(("root",), 0, [node("list")])
    s.process_all()
    a.set_stored_schema(_schema())
    s.process_all()
    assert b.stored_schema is not None

    # both sides now reject violations locally
    with pytest.raises(SchemaViolation):
        a.insert_nodes(("root",), 0, [node("item", value=1)])
    with pytest.raises(SchemaViolation):
        b.insert_nodes(("root", 0, "items"), 0,
                       [node("list")])  # wrong child type
    with pytest.raises(SchemaViolation):
        b.insert_nodes(("root", 0, "items"), 0,
                       [node("item", value="not-a-number")])

    # conforming edits flow
    b.insert_nodes(("root", 0, "items"), 0, [node("item", value=7)])
    s.process_all()
    s.assert_converged()
    assert a.get_field(("root", 0, "items"))[0]["value"] == 7


def test_schema_rejects_nonconforming_adoption():
    s, _ = make()
    a = tree(s, "A")
    a.insert_nodes(("root",), 0, [node("rogue")])
    s.process_all()
    with pytest.raises(SchemaViolation):
        a.set_stored_schema(_schema())


def test_schema_value_and_optional_cardinality():
    schema = StoredSchema(
        nodes={"box": NodeSchema("box", fields={
            "lid": FieldSchema("optional"),
            "label": FieldSchema("value"),
        }, extra_fields=True)},
    )
    schema.validate_node(node("box", fields={"label": [node("box",
        fields={"label": [node("box", fields={"label": [node("box")]}
                               )]})]}))
    with pytest.raises(SchemaViolation):
        schema.validate_node(node("box", fields={
            "label": [node("box"), node("box")],
        }))
    with pytest.raises(SchemaViolation):
        schema.validate_node(node("box", fields={
            "lid": [node("box"), node("box")], "label": [node("box")],
        }))


def test_schema_survives_summary_roundtrip():
    s, _ = make()
    a = tree(s, "A")
    a.insert_nodes(("root",), 0, [node("list")])
    s.process_all()
    a.set_stored_schema(_schema())
    s.process_all()
    summary = a.summarize_core()
    fresh = type(a)("t2")
    fresh.load_core(summary)
    assert fresh.stored_schema is not None
    with pytest.raises(SchemaViolation):
        fresh.insert_nodes(("root",), 0, [node("item", value=1)])


# ----------------------------------------------------------------------
# transactions

def test_transaction_commits_as_one_op():
    s, _ = make()
    a, b = tree(s, "A"), tree(s, "B")
    a.insert_nodes(("root",), 0, [node("n", value=0)])
    s.process_all()

    with a.transaction():
        a.insert_nodes(("root",), 1, [node("n", value=1)])
        a.insert_nodes(("root",), 2, [node("n", value=2)])
        a.set_value(("root",), 0, 99)
        # local view reflects buffered edits immediately
        assert [n["value"] for n in a.get_field(("root",))] == \
            [99, 1, 2]
    seq_before = s.sequencer.sequence_number
    s.process_all()
    # exactly ONE sequenced op carries the squashed transaction
    assert s.sequencer.sequence_number - seq_before == 1
    s.assert_converged()
    assert [n["value"] for n in b.get_field(("root",))] == [99, 1, 2]


def test_transaction_abort_rolls_back_exactly():
    s, _ = make()
    a, b = tree(s, "A"), tree(s, "B")
    a.insert_nodes(("root",), 0,
                   [node("n", value=i) for i in range(3)])
    s.process_all()
    before = a.signature()

    with pytest.raises(RuntimeError):
        with a.transaction():
            a.delete_nodes(("root",), 0, 2)  # repair data captured
            a.insert_nodes(("root",), 0, [node("x")])
            raise RuntimeError("boom")
    assert a.signature() == before
    s.process_all()  # nothing was submitted
    s.assert_converged()
    assert b.signature() == before


def test_transaction_with_concurrent_peer_commit():
    """A peer commit sequencing mid-transaction rebases the buffered
    edits; the squashed commit still converges."""
    s, _ = make()
    a, b = tree(s, "A"), tree(s, "B")
    a.insert_nodes(("root",), 0,
                   [node("n", value=i) for i in range(3)])
    s.process_all()

    a.begin_transaction()
    a.set_value(("root",), 2, 22)
    b.insert_nodes(("root",), 0, [node("n", value=-1)])
    s.process_all()  # b's edit lands mid-transaction
    a.commit_transaction()
    s.process_all()
    s.assert_converged()
    assert [n["value"] for n in b.get_field(("root",))] == \
        [-1, 0, 1, 22]


# ----------------------------------------------------------------------
# anchors

def test_anchor_slides_with_edits_and_dies_on_delete():
    s, _ = make()
    a, b = tree(s, "A"), tree(s, "B")
    a.insert_nodes(("root",), 0,
                   [node("n", value=i) for i in range(4)])
    s.process_all()

    anchor = a.track_anchor(("root",), 2)
    assert a.locate_anchor(anchor) == ("root", 2)

    # local insert before: slides right
    a.insert_nodes(("root",), 0, [node("x")])
    assert a.locate_anchor(anchor) == ("root", 3)

    # remote delete before: slides left (after rebase of the local op)
    b.delete_nodes(("root",), 0, 1)
    s.process_all()
    loc = a.locate_anchor(anchor)
    field = a.get_field(("root",))
    assert field[loc[1]]["value"] == 2  # still the same node

    # deleting the anchored node kills the anchor
    a.delete_nodes(("root",), loc[1], 1)
    assert a.locate_anchor(anchor) is None


def test_anchor_in_nested_field():
    s, _ = make()
    a = tree(s, "A")
    a.insert_nodes(("root",), 0, [node("list")])
    a.insert_nodes(("root", 0, "items"), 0,
                   [node("item", value=i) for i in range(3)])
    s.process_all()
    anchor = a.track_anchor(("root", 0, "items"), 1)
    a.insert_nodes(("root", 0, "items"), 0, [node("item", value=9)])
    loc = a.locate_anchor(anchor)
    assert loc == ("root", 0, "items", 2)
    assert a.get_field(loc[:-1])[loc[-1]]["value"] == 1


# ----------------------------------------------------------------------
# editable-tree surface

def test_editable_tree_reads_and_writes():
    s, _ = make()
    a, b = tree(s, "A"), tree(s, "B")
    root = a.editable()
    root.field("root").insert(0, [node("list")])
    items = root.field("root")[0].field("items")
    items.append([node("item", value=1), node("item", value=2)])
    items[0].value = 10
    s.process_all()
    s.assert_converged()

    bitems = b.editable().field("root")[0].field("items")
    assert [n.value for n in bitems] == [10, 2]
    assert bitems[-1].type == "item"
    del bitems[0:1]
    s.process_all()
    s.assert_converged()
    assert [n.value
            for n in a.editable().field("root")[0].field("items")] == [2]
    anchor = bitems[0].anchor()
    bitems.insert(0, [node("item", value=0)])
    assert b.locate_anchor(anchor)[-1] == 1

def test_schema_race_with_concurrent_edit_rejects_deterministically():
    """A concurrent edit that sequences BEFORE the schema op and
    violates it must cause every replica to drop the schema op (same
    state -> same outcome), never to hold a schema the tree violates
    (code-review r3)."""
    s, _ = make()
    a, b = tree(s, "A"), tree(s, "B")
    a.insert_nodes(("root",), 0, [node("list")])
    s.process_all()

    rejected = []
    b.on("schemaRejected", lambda **kw: rejected.append(1))
    b.insert_nodes(("root",), 0, [node("rogue")])
    a.set_stored_schema(_schema())  # authored before seeing rogue
    s.flush("B")  # rogue sequences FIRST
    s.flush("A")
    s.process_all()
    s.assert_converged()
    assert a.stored_schema is None
    assert b.stored_schema is None
    assert rejected
