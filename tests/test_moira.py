"""Moira lambda: PropertyDDS changeset ops -> Materialized History
branch/commit graph over the framed-TCP MH service.

Mirrors server/routerlicious/packages/lambdas/src/moira/lambda.ts
(handler/sendPending/processMoiraCore/createBranch/createCommit) and
closes the last §2.7 service-inventory row (VERDICT r4 next #6).
"""
import asyncio
import os
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

from fluidframework_tpu.service.moira import (
    MaterializedHistoryClient,
    MaterializedHistoryServer,
    MoiraLambda,
    derived_guid,
)
from fluidframework_tpu.testing.runtime_mocks import ContainerSession

POINT = {
    "typeid": "test:point-1.0.0",
    "properties": [
        {"id": "x", "typeid": "Float64"},
        {"id": "label", "typeid": "String"},
    ],
}


@pytest.fixture()
def mh_server():
    state = {}

    def start(data_dir=None):
        server = MaterializedHistoryServer(data_dir=data_dir)
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            loop.run_until_complete(server.start())
            started.set()
            loop.run_forever()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert started.wait(10)
        state.update(server=server, loop=loop, thread=t)
        return server

    yield start
    if state:
        fut = asyncio.run_coroutine_threadsafe(
            state["server"].stop(), state["loop"])
        try:
            fut.result(timeout=10)
        except Exception:
            pass
        state["loop"].call_soon_threadsafe(state["loop"].stop)
        state["thread"].join(timeout=10)


def _session_with_commits():
    """Two clients editing one SharedPropertyTree; returns the
    sequenced log and the number of changeset commits in it."""
    s = ContainerSession(["A", "B"])
    log = []
    orig = s._broadcast
    s._broadcast = lambda m: (log.append(m), orig(m))[1]
    for cid in ("A", "B"):
        s.runtime(cid).create_datastore("ds").create_channel(
            "sharedpropertytree", "pt")
        t = s.runtime(cid).get_datastore("ds").get_channel("pt")
        t.schemas.register(POINT)
    s.process_all()
    ta = s.runtime("A").get_datastore("ds").get_channel("pt")
    tb = s.runtime("B").get_datastore("ds").get_channel("pt")
    # also a non-PropertyDDS channel: its ops must NOT publish
    s.runtime("A").get_datastore("ds").create_channel(
        "sharedmap", "m")
    s.process_all()
    m = s.runtime("A").get_datastore("ds").get_channel("m")
    n_commits = 0
    for i in range(3):
        ta.insert_property(f"p{i}", "test:point-1.0.0")
        ta.commit()
        n_commits += 1
        m.set(f"k{i}", i)
        s.process_all()
    tb.set_value("p0.x", 4.5)
    tb.commit()
    n_commits += 1
    s.process_all()
    assert ta.signature() == tb.signature()
    return log, n_commits


def test_derived_guid_deterministic_uuid_shape():
    g1 = derived_guid("branch-a", "root")
    g2 = derived_guid("branch-a", "root")
    assert g1 == g2
    assert re.fullmatch(
        r"[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}"
        r"-[0-9a-f]{12}", g1)
    assert derived_guid("branch-a", "other") != g1


def test_lambda_publishes_commit_chain(mh_server):
    server = mh_server()
    log, n_commits = _session_with_commits()
    client = MaterializedHistoryClient("127.0.0.1", server.port)
    ckpts = []
    lam = MoiraLambda(client, "doc", checkpoint=ckpts.append)
    for i, msg in enumerate(log):
        lam.handler(msg, offset=i)
    assert lam.flush() == n_commits
    assert ckpts == [len(log) - 1]
    branch = derived_guid("doc", "ds/pt")
    state = client.get_branch(branch)
    assert state is not None
    commits = state["commits"]
    assert len(commits) == n_commits
    # parent chain: root -> c0 -> c1 -> ...
    parents = [c["parentGuid"] for c in commits]
    assert parents[0] == state["rootCommitGuid"]
    assert parents[1:] == [c["guid"] for c in commits[:-1]]
    # meta carries seq/msn; seqs strictly increase
    seqs = [c["meta"]["sequenceNumber"] for c in commits]
    assert seqs == sorted(seqs)
    assert all(c["rebase"] for c in commits)
    assert all("changeSet" in c for c in commits)
    # the sharedmap channel produced no branch
    assert client.get_branch(derived_guid("doc", "ds/m")) is None
    # nothing pending after a clean flush; repeat flush is a no-op
    assert lam.flush() == 0
    client.close()


def test_flush_failure_restores_pending_then_replays(mh_server):
    server = mh_server()
    log, n_commits = _session_with_commits()
    client = MaterializedHistoryClient("127.0.0.1", server.port)
    ckpts = []
    lam = MoiraLambda(client, "doc", checkpoint=ckpts.append)
    for i, msg in enumerate(log):
        lam.handler(msg, offset=i)

    real = client.create_commit
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise ConnectionError("mid-publish crash")
        return real(*a, **kw)

    client.create_commit = flaky
    with pytest.raises(ConnectionError):
        lam.flush()
    assert ckpts == []  # no checkpoint on failure
    assert lam.pending  # batch restored for replay
    client.create_commit = real
    # at-least-once replay: idempotent MH verbs dedupe the commit
    # that landed before the crash
    assert lam.flush() == n_commits - 1 + 1  # republishes all pending
    state = client.get_branch(derived_guid("doc", "ds/pt"))
    assert len(state["commits"]) == n_commits
    assert ckpts == [len(log) - 1]
    client.close()


@pytest.mark.slow
def test_moira_two_process_durable(tmp_path):
    """MH service in another OS process with a durable data dir: the
    lambda publishes over TCP; a SIGKILL + restart serves the same
    branch state back (the deployment shape of the reference's
    Materialized History endpoint)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn():
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "fluidframework_tpu.service.moira",
             "--port", "0", "--data-dir", str(tmp_path / "mh")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=repo, env=env,
        )
        line = proc.stdout.readline()
        m = re.search(r"listening on [\w.]+:(\d+)", line)
        assert m, line
        return proc, int(m.group(1))

    proc, port = spawn()
    try:
        log, n_commits = _session_with_commits()
        client = MaterializedHistoryClient("127.0.0.1", port)
        lam = MoiraLambda(client, "doc")
        for i, msg in enumerate(log):
            lam.handler(msg, offset=i)
        assert lam.flush() == n_commits
        client.close()
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        proc, port = spawn()
        client = MaterializedHistoryClient("127.0.0.1", port)
        state = client.get_branch(derived_guid("doc", "ds/pt"))
        assert state is not None and len(state["commits"]) == n_commits
        client.close()
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_mh_client_corrupt_frame_drops_socket():
    """Protocol faults (not just connection faults) must drop the MH
    client's cached socket: after a corrupt length prefix the stream
    position is garbage and reuse would return mis-parsed frames."""
    from fluidframework_tpu.testing.fault_injection import (
        ScriptedFrameServer,
    )

    with ScriptedFrameServer([ScriptedFrameServer.CORRUPT]) as srv:
        client = MaterializedHistoryClient("127.0.0.1", srv.port,
                                           timeout=5.0)
        with pytest.raises(ValueError, match="exceeds"):
            client.get_branch("b")
        assert client._sock is None  # not cached for reuse
        client.close()
