"""Container-level offline stash: closeAndGetPendingLocalState +
rehydrate (container.ts getPendingLocalState; sharedObject.ts:510
applyStashedOp) — edits made offline survive a full process-style
close/reload cycle and resubmit rebased.
"""
import json

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer


def _setup():
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice")
    ds = a.runtime.create_datastore("d")
    text = ds.create_channel("sharedstring", "t")
    kv = ds.create_channel("sharedmap", "m")
    a.flush()
    text.insert_text(0, "base")
    kv.set("k", 1)
    a.flush()
    return server, factory, a


def test_stash_rehydrate_resubmits_offline_edits():
    server, factory, a = _setup()
    # go offline, keep editing
    a.disconnect()
    text = a.runtime.get_datastore("d").get_channel("t")
    kv = a.runtime.get_datastore("d").get_channel("m")
    text.insert_text(4, " + offline edit")
    kv.set("k", 2)
    kv.set("offline", True)
    a.flush()
    stash = a.close_and_get_pending_state()
    # the stash is JSON-safe (it would be written to disk)
    stash = json.loads(json.dumps(stash))
    assert len(stash["pending"]) >= 3

    # meanwhile another client edits the same document
    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    tb = b.runtime.get_datastore("d").get_channel("t")
    tb.insert_text(0, ">> ")
    b.flush()

    # rehydrate: stashed edits apply as pending, then resubmit on
    # connect, rebased over bob's interleaved edit
    a2 = Container.load(factory.create_document_service("doc"),
                        client_id="alice-2", pending_state=stash)
    t2 = a2.runtime.get_datastore("d").get_channel("t")
    k2 = a2.runtime.get_datastore("d").get_channel("m")
    a2.flush()
    b.flush()
    assert t2.get_text() == ">> base + offline edit"
    assert tb.get_text() == t2.get_text()
    assert k2.get("k") == 2
    assert k2.get("offline") is True
    assert b.runtime.get_datastore("d").get_channel("m").get("k") == 2


def test_stash_includes_unattached_channels():
    """A channel created offline rides the stash as a pending attach
    and materializes on rehydrate."""
    server, factory, a = _setup()
    a.disconnect()
    ds = a.runtime.get_datastore("d")
    fresh = ds.create_channel("sharedmap", "made-offline")
    fresh.set("born", "offline")
    a.flush()
    stash = json.loads(json.dumps(a.close_and_get_pending_state()))

    a2 = Container.load(factory.create_document_service("doc"),
                        client_id="alice-2", pending_state=stash)
    got = a2.runtime.get_datastore("d").get_channel("made-offline")
    assert got.get("born") == "offline"
    a2.flush()

    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    assert (b.runtime.get_datastore("d")
            .get_channel("made-offline").get("born")) == "offline"

def test_stash_refuses_with_inflight_ops():
    """Stashing with sent-but-unacked ops would double-apply them
    (they sequence AND resubmit); the container refuses unless forced
    (code-review r3)."""
    import pytest

    server, factory, a = _setup()
    a.pause_inbound()  # acks stop arriving
    text = a.runtime.get_datastore("d").get_channel("t")
    text.insert_text(4, "X")
    a.flush()  # sent while connected; ack is queued but unprocessed
    with pytest.raises(ValueError, match="in flight"):
        a.close_and_get_pending_state()


def test_stash_against_newer_summary_fails_clearly():
    """A service summary newer than the stash truncates the op log
    (scribe ack -> truncate_below), so the stash positions can no
    longer be rebased exactly; rehydrate must fail with a CLEAR error,
    not corrupt or KeyError (code-review r3)."""
    import pytest

    server, factory, a = _setup()
    a.disconnect()
    text = a.runtime.get_datastore("d").get_channel("t")
    text.insert_text(4, "!")
    a.flush()
    stash = json.loads(json.dumps(a.close_and_get_pending_state()))

    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    tb = b.runtime.get_datastore("d").get_channel("t")
    tb.insert_text(0, "# ")
    b.flush()
    b.summarize()  # service summary PAST the stash point

    with pytest.raises(ValueError, match="op retention"):
        Container.load(factory.create_document_service("doc"),
                       client_id="alice-2", pending_state=stash)


def test_every_channel_type_survives_stash_cycle():
    """VERDICT r3 weak #10: every shipped channel must rehydrate from
    an offline stash (apply_stashed_op), or offline sessions die on
    that channel. Drives each type through edit-offline -> stash ->
    rehydrate -> resubmit -> converge with a second client.

    (sharedsummaryblock is excluded: it is write-once pre-attach and
    receives no ops by contract.)"""
    from fluidframework_tpu.models.tree.forest import node

    edits = {
        "sharedstring": lambda ch: ch.insert_text(0, "x"),
        "sharedmap": lambda ch: ch.set("k", 2),
        "shareddirectory": lambda ch: (
            ch.create_sub_directory("sub"),
            ch.set("dk", 1, path="/sub"),
        ),
        "sharedcell": lambda ch: ch.set("v2"),
        "sharedcounter": lambda ch: ch.increment(5),
        "sharedmatrix": lambda ch: (
            ch.insert_rows(0, 1), ch.insert_cols(0, 1),
            ch.set_cell(0, 0, 7),
        ),
        "sharedtree": lambda ch: ch.insert_nodes(
            ("items",), 0, [node("item", value=1)]),
        "legacysharedtree": lambda ch: ch.apply(
            __import__(
                "fluidframework_tpu.models.legacy_tree",
                fromlist=["insert_tree"],
            ).insert_tree(
                [{"definition": "n", "identifier": "s1",
                  "payload": None}],
                __import__(
                    "fluidframework_tpu.models.legacy_tree",
                    fromlist=["place_at_start"],
                ).place_at_start("root", "items"),
            )),
        "sharedjson": lambda ch: ch.set(["k"], 1),
        "sharedpropertytree": lambda ch: (
            ch.insert_property("p", "Int32", 1), ch.commit()),
        "ink": lambda ch: ch.create_stroke(),
        "sharedquorum": lambda ch: ch.set("q", "v"),
        "taskmanager": lambda ch: ch.volunteer("job"),
        "consensusregistercollection": lambda ch: ch.write("r", 1),
        "consensusorderedcollection": lambda ch: ch.add("item"),
    }
    for type_name, edit in edits.items():
        server = LocalServer()
        factory = LocalDocumentServiceFactory(server)
        a = Container.load(factory.create_document_service("doc"),
                           client_id="alice")
        ch = a.runtime.create_datastore("d").create_channel(
            type_name, "c")
        a.flush()
        a.disconnect()
        edit(ch)
        a.flush()
        stash = json.loads(json.dumps(a.close_and_get_pending_state()))
        assert stash["pending"], type_name

        b = Container.load(factory.create_document_service("doc"),
                           client_id="bob")
        a2 = Container.load(factory.create_document_service("doc"),
                            client_id="alice-2", pending_state=stash)
        a2.flush()
        b.flush()
        a2.flush()
        cb = b.runtime.get_datastore("d").get_channel("c")
        c2 = a2.runtime.get_datastore("d").get_channel("c")
        if hasattr(c2, "signature"):
            assert c2.signature() == cb.signature(), type_name
