"""Container-level offline stash: closeAndGetPendingLocalState +
rehydrate (container.ts getPendingLocalState; sharedObject.ts:510
applyStashedOp) — edits made offline survive a full process-style
close/reload cycle and resubmit rebased.
"""
import json

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer


def _setup():
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("doc"),
                       client_id="alice")
    ds = a.runtime.create_datastore("d")
    text = ds.create_channel("sharedstring", "t")
    kv = ds.create_channel("sharedmap", "m")
    a.flush()
    text.insert_text(0, "base")
    kv.set("k", 1)
    a.flush()
    return server, factory, a


def test_stash_rehydrate_resubmits_offline_edits():
    server, factory, a = _setup()
    # go offline, keep editing
    a.disconnect()
    text = a.runtime.get_datastore("d").get_channel("t")
    kv = a.runtime.get_datastore("d").get_channel("m")
    text.insert_text(4, " + offline edit")
    kv.set("k", 2)
    kv.set("offline", True)
    a.flush()
    stash = a.close_and_get_pending_state()
    # the stash is JSON-safe (it would be written to disk)
    stash = json.loads(json.dumps(stash))
    assert len(stash["pending"]) >= 3

    # meanwhile another client edits the same document
    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    tb = b.runtime.get_datastore("d").get_channel("t")
    tb.insert_text(0, ">> ")
    b.flush()

    # rehydrate: stashed edits apply as pending, then resubmit on
    # connect, rebased over bob's interleaved edit
    a2 = Container.load(factory.create_document_service("doc"),
                        client_id="alice-2", pending_state=stash)
    t2 = a2.runtime.get_datastore("d").get_channel("t")
    k2 = a2.runtime.get_datastore("d").get_channel("m")
    a2.flush()
    b.flush()
    assert t2.get_text() == ">> base + offline edit"
    assert tb.get_text() == t2.get_text()
    assert k2.get("k") == 2
    assert k2.get("offline") is True
    assert b.runtime.get_datastore("d").get_channel("m").get("k") == 2


def test_stash_includes_unattached_channels():
    """A channel created offline rides the stash as a pending attach
    and materializes on rehydrate."""
    server, factory, a = _setup()
    a.disconnect()
    ds = a.runtime.get_datastore("d")
    fresh = ds.create_channel("sharedmap", "made-offline")
    fresh.set("born", "offline")
    a.flush()
    stash = json.loads(json.dumps(a.close_and_get_pending_state()))

    a2 = Container.load(factory.create_document_service("doc"),
                        client_id="alice-2", pending_state=stash)
    got = a2.runtime.get_datastore("d").get_channel("made-offline")
    assert got.get("born") == "offline"
    a2.flush()

    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    assert (b.runtime.get_datastore("d")
            .get_channel("made-offline").get("born")) == "offline"

def test_stash_refuses_with_inflight_ops():
    """Stashing with sent-but-unacked ops would double-apply them
    (they sequence AND resubmit); the container refuses unless forced
    (code-review r3)."""
    import pytest

    server, factory, a = _setup()
    a.pause_inbound()  # acks stop arriving
    text = a.runtime.get_datastore("d").get_channel("t")
    text.insert_text(4, "X")
    a.flush()  # sent while connected; ack is queued but unprocessed
    with pytest.raises(ValueError, match="in flight"):
        a.close_and_get_pending_state()


def test_stash_against_newer_summary_fails_clearly():
    """A service summary newer than the stash truncates the op log
    (scribe ack -> truncate_below), so the stash positions can no
    longer be rebased exactly; rehydrate must fail with a CLEAR error,
    not corrupt or KeyError (code-review r3)."""
    import pytest

    server, factory, a = _setup()
    a.disconnect()
    text = a.runtime.get_datastore("d").get_channel("t")
    text.insert_text(4, "!")
    a.flush()
    stash = json.loads(json.dumps(a.close_and_get_pending_state()))

    b = Container.load(factory.create_document_service("doc"),
                       client_id="bob")
    tb = b.runtime.get_datastore("d").get_channel("t")
    tb.insert_text(0, "# ")
    b.flush()
    b.summarize()  # service summary PAST the stash point

    with pytest.raises(ValueError, match="op retention"):
        Container.load(factory.create_document_service("doc"),
                       client_id="alice-2", pending_state=stash)
